# netobserv_tpu build/test entry points (reference analog: the Go Makefile's
# compile / gen-bpf / gen-protobuf / test / bench targets).

PY ?= python
CPU_ENV = JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8

.PHONY: all test test-cpu bench gen-protobuf native bpf verify-maps lint perftest bytecode-image \
        dryrun smoke clean

all: native gen-protobuf

test:
	$(PY) -m pytest tests/ -x -q

# explicit CPU-mesh run (tests force this themselves; here for symmetry)
test-cpu:
	$(CPU_ENV) $(PY) -m pytest tests/ -x -q

bench:
	$(PY) bench.py

bench-cpu:
	JAX_PLATFORMS=cpu $(PY) bench.py

# host path only (~15s): pack/transfer/fold rates, pack-thread scaling,
# roll-stall — the per-PR CI artifact (no device ingest loop, no oracle)
bench-host:
	JAX_PLATFORMS=cpu $(PY) bench.py --host-only

# same run at 1% trace sampling: the flight-recorder overhead A/B
# (docs/observability.md "Overhead budget"; compare host_fold_ms_p50 /
# host_path_sustained against the bench-host artifact)
bench-host-traced:
	TRACE_SAMPLE=0.01 JAX_PLATFORMS=cpu $(PY) bench.py --host-only

# per-stage device breakdown (~60s): ingest ablations (signals/asym/fanout
# on/off), pallas-vs-scatter A/B (TPU), superbatch ladder 1x/2x/4x — the
# per-PR CI artifact tracking the fusion win (docs/tpu_sketch.md)
bench-device:
	JAX_PLATFORMS=cpu $(PY) bench.py --device-only

# eviction-plane decode rates (~10s, jax-free path): columnar
# decode/merge/align vs the per-key idiom on synthetic multi-CPU drains —
# the per-PR CI artifact for the userspace eviction half
bench-evict:
	JAX_PLATFORMS=cpu $(PY) bench.py --evict-only

# fused one-call host pipeline (~10s, jax-free path): fp_drain_to_resident
# vs the python island chain on identical injected drains — per-stage
# drain/merge/join/pack split + GIL-interference probe — the non-gating
# CI artifact for the native eviction pipeline (docs/architecture.md
# "Eviction plane")
bench-native:
	JAX_PLATFORMS=cpu $(PY) bench.py --native-only

# persistent-slot top-K ablation (~60s, CPU-friendly): slot-table vs the
# legacy concat+re-score update — cost (CM-only arm attributes the
# table's share) and top-N recall vs exact truth at 10k/100k distinct
# keys — the non-gating CI artifact for the device-resident heavy-hitter
# plane (docs/tpu_sketch.md "Persistent-slot heavy-hitter plane")
bench-topk:
	JAX_PLATFORMS=cpu $(PY) bench.py --topk-only

# tiered counter planes (~60s, CPU-friendly): tiered-vs-wide resident
# sketch memory — batch-walk rate, per-table bytes (the sketch_memory
# block), tier occupancy/promotion counts, heavy-hitter recall@100 vs the
# exact oracle — the non-gating CI artifact for the self-adjusting sketch
# memory plane (docs/tpu_sketch.md "Tiered counter planes")
bench-tiered:
	JAX_PLATFORMS=cpu $(PY) bench.py --tiered-only

# multi-tenant stacked sketch plane (~2-4 min, CPU-friendly): the
# one-dispatch-folds-every-tenant amortization ladder (N=1/8/64 tenants,
# small per-tenant batches) vs N sequential single-tenant dispatches of
# the same rows, plus per-tenant top-K recall through the production
# router — the non-gating CI artifact for SKETCH_TENANTS
# (docs/architecture.md "Multi-tenant sketch planes")
bench-tenants:
	JAX_PLATFORMS=cpu $(PY) bench.py --tenants-only

# sketch warehouse (~60s, CPU-friendly): per-window write amplification,
# raw-vs-compacted segment bytes, range-merge rate per ladder k, range
# top-K recall vs the union oracle — the non-gating CI artifact for the
# archive plane (docs/architecture.md "Sketch warehouse")
bench-archive:
	JAX_PLATFORMS=cpu $(PY) bench.py --archive-only

# overload control plane (~15s): overdriven synthetic feed against a
# fault-slowed fold — sustained admitted rate, AIMD shed-factor
# trajectory, heavy-hitter recall under shed vs unshed — the per-PR CI
# artifact for the shedding seam (docs/architecture.md
# "Overload & backpressure")
bench-overload:
	JAX_PLATFORMS=cpu $(PY) bench.py --overload-only

# adversarial scenario zoo (~90s): every netobserv_tpu/scenarios pcap
# replayed through a full in-process agent and graded END TO END through
# the live /query/* routes — top-K recall, alarm fire/quiet directions,
# victim naming, HLL cardinality error, CM error-bar honesty — the
# per-PR CI artifact for detection QUALITY (docs/architecture.md
# "Query plane")
bench-scenarios:
	JAX_PLATFORMS=cpu $(PY) bench.py --scenarios

gen-protobuf:
	protoc --python_out=netobserv_tpu/pb -I proto proto/flow.proto proto/packet.proto

# host-side native components (always buildable with g++)
native:
	$(PY) -c "from netobserv_tpu.datapath.flowpack import build_native; \
	          import sys; sys.exit(0 if build_native(force=True) else 1)"

# eBPF datapath object — needs clang with BPF target support
bpf:
	cmake -S netobserv_tpu/datapath/native -B netobserv_tpu/datapath/native/build \
	      -DDATAPATH_BPF=ON
	cmake --build netobserv_tpu/datapath/native/build

# consistency between the C map definitions and the canonical registry
verify-maps:
	$(PY) -m pytest tests/test_datapath.py -x -q

dryrun:
	$(CPU_ENV) $(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

# minimum end-to-end slice: synthetic datapath -> pipeline -> stdout flows,
# then one live alert raise→clear cycle against the real binary (zoo
# syn_flood pcap -> tpu-sketch -> alert engine -> /query/alerts HTTP —
# scripts/smoke_alerts.py)
smoke:
	DATAPATH=synthetic EXPORT=stdout CACHE_ACTIVE_TIMEOUT=300ms \
	  timeout 3 $(PY) -m netobserv_tpu | head -5 || true
	JAX_PLATFORMS=cpu $(PY) scripts/smoke_alerts.py

# federation e2e slice (~20s, non-gating CI artifact): two in-process
# agents stream delta frames over real gRPC into a local aggregator and
# the cluster-wide query surface answers merged top-K/frequency/cardinality
smoke-federation:
	JAX_PLATFORMS=cpu $(PY) scripts/smoke_federation.py

# federation RAINY-day slice (~30s, non-gating CI artifact): agents come
# up before the aggregator (cold-start catch-up), the aggregator restarts
# once mid-run restoring its checkpoint, a query poller asserts no torn
# snapshot — all with the delta-ingest fault point armed (every push eats
# an injected delay), so the retry/idempotency machinery is exercised live
smoke-federation-chaos:
	JAX_PLATFORMS=cpu FAULT_POINTS="federation.delta_ingest:delay:0.02" \
	  $(PY) scripts/smoke_federation.py --failure-path

# kernel capture-plane load rig: sendmmsg storm -> parity check (needs root)
perftest:
	$(PY) examples/performance/local_perftest.py --packets 1000000 --flows 256

# bpfman bytecode container (labels generated from the canonical sources)
bytecode-image:
	docker build -f Containerfile.bytecode \
	  --build-arg PROGRAMS="$$($(PY) scripts/gen_bytecode_labels.py programs)" \
	  --build-arg MAPS="$$($(PY) scripts/gen_bytecode_labels.py maps)" \
	  -t netobserv-tpu-bytecode .

clean:
	rm -rf netobserv_tpu/datapath/native/build
	find . -name __pycache__ -type d -exec rm -rf {} +

bench-micro:
	$(PY) benchmarks/micro_bench.py

gen-docs:
	$(PY) scripts/gen_config_docs.py

# full accuracy sweep -> docs/accuracy.md (detection sweeps included)
accuracy:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	  $(PY) scripts/accuracy_sweep.py

# host-path + per-stage device profiles (run on the real chip when healthy)
profile:
	$(PY) benchmarks/host_path_profile.py
	$(PY) benchmarks/ingest_stage_profile.py
