"""Capture plane: eBPF C datapath sources, loader, and the fetcher seam.

The narrow fetcher interface (`netobserv_tpu.datapath.fetcher`) is the testing
seam the whole agent hangs off — the reference's `ebpfFlowFetcher` /
`mapFetcher` / `ringBufReader` interfaces (`pkg/agent/agent.go:94-102`,
`pkg/flow/tracer_map.go:37-40`) reproduced so the fake-driven test strategy
ports (SURVEY.md §4).
"""
