"""Minimal BTF reader: kernel struct member offsets from /sys/kernel/btf/vmlinux.

The clang datapath gets CO-RE relocations resolved by libbpf at load time;
the assembler datapath gets the same result one level up — the loader reads
the running kernel's BTF and bakes the resolved offsets into the assembled
probe programs as immediates. Same mechanism, same source of truth, no
compiler. (Reference analog: the BPF_CORE_READ chains in
flowpath_probes.c / the reference's bpf2go CO-RE objects.)

Format reference: Documentation/bpf/btf.rst (struct btf_header, btf_type).
Only what offset resolution needs is implemented: STRUCT/UNION members
(including anonymous nesting), and the modifier/typedef chain.
"""

from __future__ import annotations

import struct
from typing import Optional

BTF_MAGIC = 0xEB9F

KIND_INT = 1
KIND_PTR = 2
KIND_ARRAY = 3
KIND_STRUCT = 4
KIND_UNION = 5
KIND_ENUM = 6
KIND_FWD = 7
KIND_TYPEDEF = 8
KIND_VOLATILE = 9
KIND_CONST = 10
KIND_RESTRICT = 11
KIND_FUNC = 12
KIND_FUNC_PROTO = 13
KIND_VAR = 14
KIND_DATASEC = 15
KIND_FLOAT = 16
KIND_DECL_TAG = 17
KIND_TYPE_TAG = 18
KIND_ENUM64 = 19

# extra payload per kind, in (fixed, per_vlen) u32 words after btf_type
_KIND_EXTRA = {
    KIND_INT: (1, 0),
    KIND_ARRAY: (3, 0),
    KIND_STRUCT: (0, 3),
    KIND_UNION: (0, 3),
    KIND_ENUM: (0, 2),
    KIND_FUNC_PROTO: (0, 2),
    KIND_VAR: (1, 0),
    KIND_DATASEC: (0, 3),
    KIND_DECL_TAG: (1, 0),
    KIND_ENUM64: (0, 3),
}

_MODIFIERS = (KIND_TYPEDEF, KIND_VOLATILE, KIND_CONST, KIND_RESTRICT,
              KIND_TYPE_TAG)


class BTF:
    """Parsed BTF type graph with struct member offset resolution."""

    def __init__(self, path: str = "/sys/kernel/btf/vmlinux"):
        with open(path, "rb") as fh:
            data = fh.read()
        magic, _ver, _flags, hdr_len = struct.unpack_from("=HBBI", data, 0)
        if magic != BTF_MAGIC:
            raise ValueError(f"{path}: not BTF (magic {magic:#x})")
        type_off, type_len, str_off, str_len = struct.unpack_from(
            "=IIII", data, 8)
        self._strs = data[hdr_len + str_off:hdr_len + str_off + str_len]
        # types[i] = (kind, name_off, size_or_type, members)
        # members = [(name_off, type_id, offset_bits)] for STRUCT/UNION
        self.types: list[tuple] = [(0, 0, 0, None)]  # type_id 0 = void
        self._by_name: dict[tuple[int, str], int] = {}
        off = hdr_len + type_off
        end = off + type_len
        tid = 0
        while off < end:
            name_off, info, size = struct.unpack_from("=III", data, off)
            off += 12
            kind = (info >> 24) & 0x1F
            vlen = info & 0xFFFF
            members = None
            if kind in (KIND_STRUCT, KIND_UNION):
                members = []
                for _ in range(vlen):
                    m_name, m_type, m_off = struct.unpack_from(
                        "=III", data, off)
                    off += 12
                    if (info >> 31) & 1:  # kind_flag: bitfield encoding
                        m_off = m_off & 0xFFFFFF
                    members.append((m_name, m_type, m_off))
            else:
                fixed, per = _KIND_EXTRA.get(kind, (0, 0))
                off += 4 * (fixed + per * vlen)
            tid += 1
            self.types.append((kind, name_off, size, members))
            if name_off and kind in (KIND_STRUCT, KIND_UNION, KIND_TYPEDEF,
                                     KIND_INT, KIND_FLOAT, KIND_ENUM,
                                     KIND_ENUM64):
                self._by_name.setdefault((kind, self._name(name_off)), tid)

    def _name(self, name_off: int) -> str:
        endp = self._strs.index(b"\x00", name_off)
        return self._strs[name_off:endp].decode()

    def _resolve(self, tid: int) -> int:
        """Skip typedef/const/volatile chains to the concrete type."""
        kind, _n, size_or_type, _m = self.types[tid]
        while kind in _MODIFIERS:
            tid = size_or_type
            kind, _n, size_or_type, _m = self.types[tid]
        return tid

    def struct_id(self, name: str) -> int:
        for kind in (KIND_STRUCT, KIND_UNION):
            tid = self._by_name.get((kind, name))
            if tid is not None:
                return tid
        raise LookupError(f"struct {name} not in BTF")

    def _find_member(self, tid: int, want: str,
                     base_bits: int) -> Optional[tuple[int, int]]:
        """(offset_bits, member_type_id) for `want` in struct `tid`,
        descending into anonymous members."""
        _kind, _n, _sz, members = self.types[tid]
        for m_name, m_type, m_off in members or ():
            if m_name and self._name(m_name) == want:
                return base_bits + m_off, m_type
            if not m_name:  # anonymous struct/union: search inside
                inner = self._resolve(m_type)
                if self.types[inner][0] in (KIND_STRUCT, KIND_UNION):
                    hit = self._find_member(inner, want, base_bits + m_off)
                    if hit:
                        return hit
        return None

    def offset_of(self, struct_name: str, path: str) -> int:
        """Byte offset of a (possibly nested) member, e.g.
        offset_of("sock", "__sk_common.skc_dport"). Raises on bitfields
        (none of the fields the datapath reads are bitfields)."""
        tid = self.struct_id(struct_name)
        bits = 0
        for comp in path.split("."):
            tid = self._resolve(tid)
            if self.types[tid][0] not in (KIND_STRUCT, KIND_UNION):
                raise LookupError(
                    f"{struct_name}.{path}: {comp} parent is not a struct")
            hit = self._find_member(tid, comp, bits)
            if hit is None:
                raise LookupError(f"{struct_name}.{path}: no member {comp}")
            bits, tid = hit
        if bits % 8:
            raise LookupError(f"{struct_name}.{path}: bitfield unsupported")
        return bits // 8


_cached: Optional[BTF] = None


def kernel_btf() -> BTF:
    """The running kernel's BTF (parsed once per process)."""
    global _cached
    if _cached is None:
        _cached = BTF()
    return _cached


def available() -> bool:
    import os

    return os.path.exists("/sys/kernel/btf/vmlinux")
