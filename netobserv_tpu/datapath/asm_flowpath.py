"""Hand-assembled kernel flow datapath (no compiler required).

Builds a TC classifier that aggregates IPv4 AND IPv6 TCP/UDP/ICMP packets
into the `aggregated_flows` hash (same no_flow_key/no_flow_stats layout as
the C datapath, so the entire userspace pipeline runs unchanged on top):

    parse eth/IPv4/IPv6 -> flow key on the stack
    -> map lookup: hit  -> first-seen-interface-deduped accounting
                   miss -> BPF_NOEXIST insert, EEXIST-race re-merge,
                           ring-buffer fallback when the map is full

Feature parity with flowpath.c (each gated on the map fds the loader hands
in, the moral equivalent of the C datapath's `volatile const` config):

- IPv4 + IPv6 keys (v4-mapped addresses), TCP/UDP ports, ICMP/ICMPv6
  type+code, MAC addresses, DSCP, TCP-flag accumulation
- multi-interface dedup: bytes/packets counted only from the first-seen
  interface; (ifindex, direction) observation list with overflow counting
  (reference semantics: bpf/flows.c:100-142)
- DNS tracking: query timestamps stashed in `dns_inflight` under the
  reversed tuple + transaction id; responses correlate to a latency and
  upsert the per-CPU `flows_dns` feature record (reference:
  bpf/dns_tracker.h; C twin: bpf/dns.h in this repo)
- hashmap-failure fallback into the `direct_flows` ring buffer with
  errno_fallback recorded (reference: bpf/flows.c fallback path)
- global error/health counters (PERCPU_ARRAY, enum no_counter_key)
- optional 1/N sampling baked in at build time (the loader rebuilds per
  config)

Also covered: the in-kernel flow-filter gate (filter.h twin — LPM rule
lookup with the full predicate set, src-first/dst-retry, peer-CIDR check,
accept/reject/no-match counters) and handshake RTT (SYN→SYN|ACK correlation
into per-CPU flows_extra records).

Beyond flowpath.c/the reference: IPv4-options packets key their real ports
(fill_iphdr assumes ihl=5 and mis-reads them, utils.h:113-118) and IPv6
flows behind extension headers key the real transport via a bounded chain
walk (fill_ip6hdr keys the first next-header).

Concurrency (the C twin spin-locks, flowpath.c:44-107; spin locks need
BTF-described map values this path doesn't have, so it is LOCK-FREE with
the same guarantees): bytes/packets via atomic adds, tcp_flags via an
atomic OR on the containing aligned word (no lost bits), observed-intf
appends via atomic fetch-add slot reservation (no lost/torn entries; the
counter saturates near capacity instead of wrapping). The one plain store
is last_seen — racing writers both store ~now, correct to a packet's skew.
Residual benign race: the SAME new interface appending twice under a race
(dedup'd again at read-out). Per-packet trackers run on BOTH parse paths:
TCP flags everywhere, and the UDP payload probes (DNS, QUIC) read at the
fast path's constant offset or the slow path's dynamic CURSOR via
bpf_skb_load_bytes (`udp_trackers`) — IPv4-options/IPv6-ext flows are
fully feature-enriched except passive TLS, which needs the TCP doff walk
and stays fast-path-only. Validated by the live verifier, end-to-end veth
traffic tests, and a cross-CPU stress test (tests/test_asm_flowpath.py).
"""

from __future__ import annotations

from netobserv_tpu.datapath.asm import (
    Asm, BPF_B, BPF_DW, BPF_H, BPF_W, HELPER_KTIME_GET_NS, HELPER_MAP_DELETE,
    HELPER_MAP_LOOKUP, HELPER_MAP_UPDATE, HELPER_RINGBUF_OUTPUT, R0, R1, R2,
    R3, R4, R5, R6, R7, R8, R9, R10,
)

# __sk_buff field offsets
SKB_LEN = 0
SKB_IFINDEX = 40
SKB_DATA = 76
SKB_DATA_END = 80

from netobserv_tpu.model import binfmt


def _st(field: str) -> int:
    """no_flow_stats field offset, derived from the layout-pinned dtype so
    the assembled stores can never drift from records.h/binfmt."""
    return binfmt.FLOW_STATS_DTYPE.fields[field][1]


def _ky(field: str) -> int:
    return binfmt.FLOW_KEY_DTYPE.fields[field][1]


def _dr(field: str) -> int:
    return binfmt.DNS_REC_DTYPE.fields[field][1]


def _xr(field: str) -> int:
    return binfmt.EXTRA_REC_DTYPE.fields[field][1]


def _qr(field: str) -> int:
    return binfmt.QUIC_REC_DTYPE.fields[field][1]


ST_FIRST = _st("first_seen_ns")
ST_LAST = _st("last_seen_ns")
ST_BYTES = _st("bytes")
ST_PACKETS = _st("packets")
ST_ETH = _st("eth_protocol")
ST_IFINDEX = _st("if_index_first")
ST_DIR = _st("direction_first")
ST_NOBS = _st("n_observed_intf")
ST_OBSDIR = _st("observed_direction")
ST_OBSIF = _st("observed_intf")
ST_FLAGS = _st("tcp_flags")
# atomic-OR staging: tcp_flags occupies memory bytes 2..3 of the 4-aligned
# word that starts at eth_protocol, so an atomic OR of `flags << _FLAGS_SHIFT`
# accumulates flag bits across CPUs without touching eth_protocol (which is
# only ever rewritten with the same value)
assert ST_FLAGS == ST_ETH + 2 and ST_ETH % 4 == 0
# slot-reservation staging: n_observed_intf is memory byte 3 of the
# 4-aligned word at direction_first, so an atomic fetch-add of
# `1 << _NOBS_SHIFT` hands each CPU an exclusive observed-list slot; the
# addend leaves the other three bytes (direction_first/errno_fallback/dscp)
# untouched
assert _st("n_observed_intf") == ST_DIR + 3 and ST_DIR % 4 == 0
# BPF programs execute in HOST byte order, so the staging shifts flip with
# endianness (the asm_flowpath twin of asm.py's _REGS_BYTE nibble flip): on
# little-endian, memory bytes 2..3 are the word's HIGH u16 and byte 3 its
# HIGH byte; on big-endian (s390x) both are the word's LOW bits, so the
# shifts collapse to 0 and the old-slot extraction masks instead of shifting.
# Big-endian bound: the LE counter harmlessly wraps out of the u32 at 256,
# while the BE low-byte counter would carry into dscp — unreachable in
# practice because the saturation-undo below keeps it ≤ capacity + the
# number of concurrently executing CPUs (≪ 255).
_FLAGS_SHIFT = 16 if __import__("sys").byteorder == "little" else 0
_NOBS_SHIFT = 24 if __import__("sys").byteorder == "little" else 0
ST_SRC_MAC = _st("src_mac")
ST_DST_MAC = _st("dst_mac")
ST_SAMPLING = _st("sampling")
ST_ERRNO = _st("errno_fallback")
ST_DSCP = _st("dscp")
KY_SRC_IP = _ky("src_ip")
KY_DST_IP = _ky("dst_ip")
KY_SPORT = _ky("src_port")
KY_DPORT = _ky("dst_port")
KY_PROTO = _ky("proto")
KY_ICMP_TYPE = _ky("icmp_type")
KY_ICMP_CODE = _ky("icmp_code")

HELPER_PRANDOM_U32 = 7

KEY_SIZE = binfmt.FLOW_KEY_DTYPE.itemsize        # 40
STATS_SIZE = binfmt.FLOW_STATS_DTYPE.itemsize    # 104
EVENT_SIZE = binfmt.FLOW_EVENT_DTYPE.itemsize    # 144
DNSREC_SIZE = binfmt.DNS_REC_DTYPE.itemsize      # 64

# stack layout (relative to r10, all 8-aligned). The flow event is laid out
# contiguously (key then stats, the no_flow_event wire layout) so the
# ring-buffer fallback can ship it with one helper call.
EV = -EVENT_SIZE          # -144: no_flow_event
KEY = EV                  # key at EV+0 (40B)
VAL = EV + KEY_SIZE       # stats at EV+40 (104B)
CORR = EV - 40            # -184: no_dns_corr_key (40B)
DNSREC = CORR - DNSREC_SIZE  # -248: no_dns_rec build slot
SPILL = DNSREC - 8        # -256: this packet's tcp flags
NOW = SPILL - 8           # -264: bpf_ktime_get_ns()
DNSMETA = NOW - 8         # -272: dns id (u16 @+0), flags (u16 @+2), seen (@+4)
LAT = DNSMETA - 8         # -280: dns latency (u64)
CTRKEY = LAT - 8          # -288: global-counter index (u32)
FKEY = CTRKEY - 24        # -312: no_filter_key (u32 prefix_len + 16B ip)
FACT = FKEY - 8           # -320: matched rule's action, saved across lookups
QMETA = FACT - 8          # -328: quic seen (u8 @+0), is_long (@+1), ver (@+4)
TLSBUF = QMETA - 16       # -344: TLS header bytes via bpf_skb_load_bytes
FSAMP = TLSBUF - 8        # -352: matched rule's sample override (u32)
FSKIP = FSAMP - 8         # -360: filter verdict says drop (reject/no-match)
CURSOR = FSKIP - 8        # -368: TLS extension-walk packet cursor
EXTREM = CURSOR - 8       # -376: remaining bytes in the extension list/ext
BESTV = EXTREM - 8        # -384: best supported_version seen (CH scan)
KNOWNF = BESTV - 8        # -392: best version is a known one (CH scan)

# extension-walk bound: the reference walks up to 30 extensions
# (tls_tracker.h); 16 covers real-world hellos at half the unrolled size
TLS_MAX_EXTS = 16

HELPER_SKB_LOAD_BYTES = 26

# no_dns_corr_key field offsets (bpf/maps.h struct no_dns_corr_key)
CK_SPORT, CK_DPORT, CK_SRC_IP, CK_DST_IP, CK_ID, CK_PROTO = 0, 2, 4, 20, 36, 38

DNS_QR_BIT = 0x8000

# enum no_counter_key (bpf/config.h) — must match model.flow.GlobalCounter
CTR_FAIL_UPDATE_FLOW = 0
CTR_FAIL_CREATE_FLOW = 1
CTR_FAIL_UPDATE_DNS = 2
CTR_FILTER_REJECT = 3
CTR_FILTER_ACCEPT = 4
CTR_FILTER_NOMATCH = 5
CTR_OBSERVED_INTF_MISSED = 12


def _fr(field: str) -> int:
    return binfmt.FILTER_RULE_DTYPE.fields[field][1]


class _Flow:
    """Emitter for one build of the flow program (holds the option fds)."""

    def __init__(self, map_fd: int, direction: int, sampling: int,
                 ringbuf_fd, counters_fd, dns_inflight_fd, flows_dns_fd,
                 dns_port: int, rtt_inflight_fd=None, flows_extra_fd=None,
                 filter_rules_fd=None, filter_peers_fd=None,
                 flows_quic_fd=None, quic_mode: int = 0,
                 enable_tls: bool = False, sampling_gate_fd=None,
                 has_filter_sampling: bool = False):
        self.a = Asm()
        self.map_fd = map_fd
        self.direction = direction
        self.sampling = sampling
        self.ringbuf_fd = ringbuf_fd
        self.counters_fd = counters_fd
        self.dns_inflight_fd = dns_inflight_fd
        self.flows_dns_fd = flows_dns_fd
        self.dns_port = dns_port
        self.rtt_inflight_fd = rtt_inflight_fd
        self.flows_extra_fd = flows_extra_fd
        self.filter_rules_fd = filter_rules_fd
        self.filter_peers_fd = filter_peers_fd
        self.flows_quic_fd = flows_quic_fd
        self.quic_mode = quic_mode
        self.enable_tls = enable_tls
        self.sampling_gate_fd = sampling_gate_fd
        # reference has_filter_sampling (flows.c:160-208): when any filter
        # rule carries a sample override, the 1/N gate moves to after the
        # filter so the matched rule's rate can replace the global one
        self.has_filter_sampling = (has_filter_sampling
                                    and filter_rules_fd is not None)
        self._ctr_n = 0

    def set_gate(self, value: int) -> None:
        """Record the per-CPU sampling decision for the aux kprobes
        (sampling_gate map; the C datapath's no_set_do_sampling twin).
        Clobbers r0-r3."""
        a = self.a
        self._gate_n = getattr(self, "_gate_n", 0) + 1
        lbl = f"gate_done_{value}_{self._gate_n}"
        a.st_imm(BPF_W, R10, CTRKEY, 0)
        a.ld_map_fd(R1, self.sampling_gate_fd)
        a.mov_reg(R2, R10)
        a.alu_imm(0x07, R2, CTRKEY)
        a.call(HELPER_MAP_LOOKUP)
        a.jmp_imm(0x15, R0, 0, lbl)
        a.st_imm(BPF_B, R0, 0, value)
        a.label(lbl)

    # --- helpers -----------------------------------------------------------
    def count(self, ctr: int) -> None:
        """Bump global_counters[ctr] (per-CPU slot; non-atomic is exact).
        Clobbers r0-r5 (embedded helper call); no-op when the counters map
        isn't wired."""
        if self.counters_fd is None:
            return
        a = self.a
        lbl = f"ctr_done_{self._ctr_n}"
        self._ctr_n += 1
        a.st_imm(BPF_W, R10, CTRKEY, ctr)
        a.ld_map_fd(R1, self.counters_fd)
        a.mov_reg(R2, R10)
        a.alu_imm(0x07, R2, CTRKEY)
        a.call(HELPER_MAP_LOOKUP)
        a.jmp_imm(0x15, R0, 0, lbl)
        a.ldx(BPF_DW, R3, R0, 0)
        a.alu_imm(0x07, R3, 1)
        a.stx(BPF_DW, R0, R3, 0)
        a.label(lbl)

    def classify_tcp_flags(self, tag: str) -> None:
        """Fold the synthetic composite bits into the raw flags byte in r3 —
        SYN_ACK/FIN_ACK/RST_ACK exactly like parse.h:93-102; feeds both the
        accumulated stats flags and the filter gate's tcp_flags predicate.
        Shared by the fast (constant-offset) and slow (cursor) parses."""
        a = self.a
        for combo, bit in ((0x12, 0x100), (0x11, 0x200), (0x14, 0x400)):
            a.mov_reg(R4, R3)
            a.alu_imm(0x57, R4, combo)
            a.jmp_imm(0x55, R4, combo, f"cls_{tag}_{bit:x}")
            a.alu_imm(0x47, R3, bit)
            a.label(f"cls_{tag}_{bit:x}")

    def bounds(self, need: int, fail: str) -> None:
        """if data + need > data_end goto fail (r7=data, r8=data_end)."""
        a = self.a
        a.mov_reg(R2, R7)
        a.alu_imm(0x07, R2, need)
        a.jmp_reg(0x2D, R2, R8, fail)

    # --- program sections --------------------------------------------------
    def parse_l4(self, l4: int, v: str, icmp_proto: int) -> None:
        """TCP/UDP/ICMP parse with constant offsets (emitted per IP version
        so the verifier sees only constant packet offsets)."""
        a = self.a
        a.jmp_imm(0x15, R9, 6, f"tcp_{v}")
        a.jmp_imm(0x15, R9, 17, f"udp_{v}")
        a.jmp_imm(0x15, R9, 132, f"ports_{v}")  # SCTP: same port offsets
        a.jmp_imm(0x15, R9, icmp_proto, f"icmp_{v}")
        # other protocols: keyed on addresses+proto, no ports (the
        # reference's fill_l4info default — GRE/ESP/... flows still count)
        a.jmp("key_done")

        a.label(f"tcp_{v}")
        self.bounds(l4 + 14, f"ports_{v}")      # flags byte at l4+13
        a.ldx(BPF_B, R3, R7, l4 + 13)
        self.classify_tcp_flags(v)
        a.stx(BPF_DW, R10, R3, SPILL)
        if self.enable_tls:
            self.parse_tls(l4, v)
        a.jmp(f"ports_{v}")

        a.label(f"icmp_{v}")
        self.bounds(l4 + 2, "out")
        a.ldx(BPF_B, R3, R7, l4)                # icmp type
        a.stx(BPF_B, R10, R3, KEY + KY_ICMP_TYPE)
        a.ldx(BPF_B, R3, R7, l4 + 1)            # icmp code
        a.stx(BPF_B, R10, R3, KEY + KY_ICMP_CODE)
        a.jmp("key_done")

        a.label(f"udp_{v}")
        a.label(f"ports_{v}")
        self.bounds(l4 + 4, "out")
        a.ldx(BPF_H, R3, R7, l4)                # bswap16 to host order
        a.endian_be(R3, 16)
        a.stx(BPF_H, R10, R3, KEY + KY_SPORT)
        a.ldx(BPF_H, R3, R7, l4 + 2)
        a.endian_be(R3, 16)
        a.stx(BPF_H, R10, R3, KEY + KY_DPORT)
        self.udp_trackers(tag=v, payload_base=l4 + 8)
        a.jmp("key_done")

    def udp_trackers(self, tag: str, payload_base: int | None) -> None:
        """DNS-header + QUIC-invariant probes over the UDP payload — shared
        by the constant-offset fast path (`payload_base` = l4 + 8) and the
        IPv4-options/IPv6-ext slow paths (`payload_base=None`: the UDP
        header sits at the dynamic CURSOR stack slot), closing the r3 gap
        where slow-path flows skipped DNS/QUIC tracking. All payload reads
        go through bpf_skb_load_bytes: it takes a RUNTIME offset (no
        verifier constant needed) and reads frag-resident payload (UDP GSO)
        that direct packet pointers cannot reach. Expects r9 = transport
        protocol; only UDP(17) rows enter the probes."""
        a = self.a

        def payload_addr(extra: int) -> None:
            """r2 = packet offset of UDP payload + extra."""
            if payload_base is not None:
                a.mov_imm(R2, payload_base + extra)
            else:
                a.ldx(BPF_DW, R2, R10, CURSOR)
                a.alu_imm(0x07, R2, 8 + extra)

        def load_payload(extra: int, n: int, fail: str) -> None:
            a.mov_reg(R1, R6)
            payload_addr(extra)
            a.mov_reg(R3, R10)
            a.alu_imm(0x07, R3, TLSBUF)
            a.mov_imm(R4, n)
            a.call(HELPER_SKB_LOAD_BYTES)
            a.jmp_imm(0x55, R0, 0, fail)        # payload too short

        def ntohs_from_buf(off: int) -> None:
            """r3 = host-order u16 from two BE bytes at TLSBUF+off."""
            a.ldx(BPF_B, R3, R10, TLSBUF + off)
            a.alu_imm(0x67, R3, 8)
            a.ldx(BPF_B, R4, R10, TLSBUF + off + 1)
            a.alu_reg(0x4F, R3, R4)

        done = f"udp_trk_done_{tag}"
        if self.dns_inflight_fd is not None:
            # DNS header parse (UDP on the DNS port only)
            a.jmp_imm(0x55, R9, 17, "key_done")     # TCP: no UDP trackers
            a.ldx(BPF_H, R3, R10, KEY + KY_SPORT)
            a.jmp_imm(0x15, R3, self.dns_port, f"dns_hdr_{tag}")
            a.ldx(BPF_H, R3, R10, KEY + KY_DPORT)
            a.jmp_imm(0x55, R3, self.dns_port, f"dns_done_{tag}")
            a.label(f"dns_hdr_{tag}")
            load_payload(0, 12, f"dns_done_{tag}")  # full no_dns_hdr
            ntohs_from_buf(0)                       # transaction id
            a.stx(BPF_H, R10, R3, DNSMETA)
            ntohs_from_buf(2)                       # flags
            a.stx(BPF_H, R10, R3, DNSMETA + 2)
            a.st_imm(BPF_W, R10, DNSMETA + 4, 1)    # header seen
            # qname starts after the 12-byte header; the offset differs per
            # IP version/path, so stash it for the common dns_rec block
            # (TLSBUF+8 held header bytes 8..11, already consumed; QUIC's
            # 5-byte scratch and TLS's TCP-only use never collide)
            payload_addr(12)
            a.stx(BPF_W, R10, R2, TLSBUF + 8)
            a.label(f"dns_done_{tag}")
        if self.flows_quic_fd is not None and self.quic_mode:
            # QUIC invariants (quic.h / RFC 8999): fixed bit, long-header
            # version, short-header established marker.
            a.jmp_imm(0x55, R9, 17, "key_done")     # UDP only
            if self.quic_mode == 1:                 # only UDP/443
                a.ldx(BPF_H, R3, R10, KEY + KY_SPORT)
                a.jmp_imm(0x15, R3, 443, f"quic_port_ok_{tag}")
                a.ldx(BPF_H, R3, R10, KEY + KY_DPORT)
                a.jmp_imm(0x55, R3, 443, done)
                a.label(f"quic_port_ok_{tag}")
            load_payload(0, 5, done)                # first byte + version
            a.ldx(BPF_B, R3, R10, TLSBUF)
            a.jmp_imm(0x45, R3, 0x40, f"quic_fixed_{tag}")  # fixed bit?
            a.jmp(done)
            a.label(f"quic_fixed_{tag}")
            a.jmp_imm(0x45, R3, 0x80, f"quic_long_{tag}")   # long header?
            a.st_imm(BPF_B, R10, QMETA, 1)          # short: established
            a.jmp(done)
            a.label(f"quic_long_{tag}")
            a.mov_imm(R4, 0)                        # version: 4 BE bytes
            for i in range(4):
                a.alu_imm(0x67, R4, 8)
                a.ldx(BPF_B, R3, R10, TLSBUF + 1 + i)
                a.alu_reg(0x4F, R4, R3)
            a.jmp_imm(0x15, R4, 0, done)            # version negotiation
            a.stx(BPF_W, R10, R4, QMETA + 4)
            a.st_imm(BPF_B, R10, QMETA, 1)
            a.st_imm(BPF_B, R10, QMETA + 1, 1)      # long header seen
        a.label(done)

    def parse_tls(self, l4: int, v: str) -> None:
        """Passive TLS metadata from the TCP payload (tls.h twin): record
        -type bitmap, ClientHello/ServerHello hello version — including the
        TLS 1.3 extension walk (reference tls_tracker.h:60-210): the CH
        supported_versions list is scanned with known-over-unknown-then-
        higher preference, the SH yields the selected version and the
        key-share group — plus the ServerHello cipher suite. Stored into the
        stack stats (VAL) — the miss path inserts them as-built; the hit
        path merges them (version-mismatch flagging included). The unrolled
        walk visits up to TLS_MAX_EXTS extensions (reference: 30).

        Reads go through bpf_skb_load_bytes, NOT direct packet pointers:
        locally-generated TCP payload usually lives in skb page frags, where
        data_end covers only the linear headers and pointer-based reads see
        nothing (the classic non-linear-skb trap).

        Runs inside the TCP branch with r9 = proto(6); r9 is used as scratch
        and restored on every exit path."""
        a = self.a
        t = f"tls_{v}"
        done = f"{t}_done"

        def load_bytes(off_reg_setup, dst_off: int, n: int) -> None:
            """bpf_skb_load_bytes(skb, r2=offset, r3=stack+dst_off, r4=n);
            jumps to `done` on failure (offset beyond the packet)."""
            a.mov_reg(R1, R6)
            off_reg_setup()                     # materialize r2 = offset
            a.mov_reg(R3, R10)
            a.alu_imm(0x07, R3, TLSBUF + dst_off)
            a.mov_imm(R4, n)
            a.call(HELPER_SKB_LOAD_BYTES)
            a.jmp_imm(0x55, R0, 0, done)

        # payload offset = l4 + doff; doff byte is always in the linear area
        a.ldx(BPF_B, R4, R7, l4 + 12)
        a.alu_imm(0x57, R4, 0xF0)
        a.alu_imm(0x77, R4, 2)
        a.jmp_imm(0xA5, R4, 20, done)           # doff < 20: not TCP
        a.mov_reg(R9, R4)
        a.alu_imm(0x07, R9, l4)                 # r9 = payload offset (kept)
        # payload-less segments (pure ACKs — the majority) skip the helper
        a.ldx(BPF_W, R3, R6, SKB_LEN)
        a.jmp_reg(0xBD, R3, R9, done)           # skb->len <= payload off

        # record header(5) + hs type(1) + len(3) + hello version(2) = 11
        load_bytes(lambda: a.mov_reg(R2, R9), 0, 11)
        a.ldx(BPF_B, R3, R10, TLSBUF + 1)       # record version hi byte
        a.jmp_imm(0x55, R3, 0x03, done)         # not SSL3.x: not TLS
        a.ldx(BPF_B, R3, R10, TLSBUF)           # record type
        for rec_type, bit in ((20, 0x01), (21, 0x02), (22, 0x04),
                              (23, 0x08), (24, 0x10)):
            a.jmp_imm(0x15, R3, rec_type, f"{t}_bit_{bit:x}")
        a.jmp(done)                             # unknown record type
        for rec_type, bit in ((20, 0x01), (21, 0x02), (22, 0x04),
                              (23, 0x08), (24, 0x10)):
            a.label(f"{t}_bit_{bit:x}")
            a.ldx(BPF_B, R3, R10, VAL + _st("tls_types"))
            a.alu_imm(0x47, R3, bit)
            a.stx(BPF_B, R10, R3, VAL + _st("tls_types"))
            if rec_type == 22:
                a.jmp(f"{t}_hs")                # handshake: parse the hello
            else:
                a.jmp(done)
        a.label(f"{t}_hs")
        a.ldx(BPF_B, R5, R10, TLSBUF + 5)       # handshake type
        a.jmp_imm(0x15, R5, 1, f"{t}_hello")    # ClientHello
        a.jmp_imm(0x55, R5, 2, done)            # not ServerHello either
        a.label(f"{t}_hello")
        a.ldx(BPF_B, R3, R10, TLSBUF + 9)       # legacy hello version (BE)
        a.alu_imm(0x67, R3, 8)
        a.ldx(BPF_B, R4, R10, TLSBUF + 10)
        a.alu_reg(0x4F, R3, R4)
        a.jmp_imm(0x15, R3, 0, f"{t}_sh")
        a.ldx(BPF_H, R4, R10, VAL + _st("ssl_version"))
        a.jmp_imm(0x55, R4, 0, f"{t}_sh")       # first hello version wins
        a.stx(BPF_H, R10, R3, VAL + _st("ssl_version"))
        def cur_load(delta: int, dst_off: int, n: int) -> None:
            """bpf_skb_load_bytes at CURSOR+delta into TLSBUF+dst_off."""
            a.mov_reg(R1, R6)
            a.ldx(BPF_DW, R2, R10, CURSOR)
            if delta:
                a.alu_imm(0x07, R2, delta)
            a.mov_reg(R3, R10)
            a.alu_imm(0x07, R3, TLSBUF + dst_off)
            a.mov_imm(R4, n)
            a.call(HELPER_SKB_LOAD_BYTES)
            a.jmp_imm(0x55, R0, 0, done)

        def ntohs_at(off: int, dst: int) -> None:
            """dst = big-endian u16 at TLSBUF+off (byte loads: no bswap)."""
            a.ldx(BPF_B, dst, R10, off)
            a.alu_imm(0x67, dst, 8)
            a.ldx(BPF_B, R4, R10, off + 1)
            a.alu_reg(0x4F, dst, R4)

        def ext_hdr_and_type() -> None:
            """Read the 4B extension header at CURSOR; r3=type, r4=len."""
            cur_load(0, 0, 4)
            ntohs_at(TLSBUF, R3)
            a.mov_reg(R5, R3)                   # keep type; r4 next
            ntohs_at(TLSBUF + 2, R3)
            a.mov_reg(R4, R3)                   # r4 = len
            a.mov_reg(R3, R5)                   # r3 = type

        def ext_advance(i: int, walk: str, end: str) -> None:
            """CURSOR/EXTREM += one extension; jump to `end` when the list
            is exhausted; fall through to the next iteration label."""
            a.label(f"{t}_{walk}_{i}_adv")
            ntohs_at(TLSBUF + 2, R3)            # re-derive len (regs free)
            a.mov_reg(R4, R3)
            a.alu_imm(0x07, R4, 4)              # step = 4 + len
            a.ldx(BPF_DW, R3, R10, EXTREM)
            a.jmp_reg(0x3D, R4, R3, end)        # step >= remaining: done
            a.alu_reg(0x1F, R3, R4)             # remaining -= step
            a.stx(BPF_DW, R10, R3, EXTREM)
            a.ldx(BPF_DW, R3, R10, CURSOR)
            a.alu_reg(0x0F, R3, R4)
            a.stx(BPF_DW, R10, R3, CURSOR)

        a.label(f"{t}_sh")
        a.jmp_imm(0x15, R5, 2, f"{t}_srv")      # ServerHello: cipher + exts
        # --- ClientHello: 1.2 vs 1.3 via supported_versions (tls.h twin) ---
        a.ldx(BPF_H, R3, R10, VAL + _st("ssl_version"))
        a.jmp_imm(0x55, R3, 0x0303, done)       # only 0x0303 is ambiguous
        a.mov_reg(R3, R9)
        a.alu_imm(0x07, R3, 43)                 # session-id length byte
        a.stx(BPF_DW, R10, R3, CURSOR)
        cur_load(0, 0, 1)
        a.ldx(BPF_B, R3, R10, TLSBUF)
        a.alu_imm(0x07, R3, 1)
        a.ldx(BPF_DW, R4, R10, CURSOR)
        a.alu_reg(0x0F, R4, R3)
        a.stx(BPF_DW, R10, R4, CURSOR)          # += 1 + sid_len
        cur_load(0, 0, 2)                       # cipher-suites list length
        ntohs_at(TLSBUF, R3)
        a.alu_imm(0x07, R3, 2)
        a.ldx(BPF_DW, R4, R10, CURSOR)
        a.alu_reg(0x0F, R4, R3)
        a.stx(BPF_DW, R10, R4, CURSOR)          # += 2 + cipher_len
        cur_load(0, 0, 1)                       # compression list length
        a.ldx(BPF_B, R3, R10, TLSBUF)
        a.alu_imm(0x07, R3, 1)
        a.ldx(BPF_DW, R4, R10, CURSOR)
        a.alu_reg(0x0F, R4, R3)
        a.stx(BPF_DW, R10, R4, CURSOR)          # += 1 + compr_len
        cur_load(0, 0, 2)                       # extensions total length
        ntohs_at(TLSBUF, R3)
        a.stx(BPF_DW, R10, R3, EXTREM)
        a.ldx(BPF_DW, R4, R10, CURSOR)
        a.alu_imm(0x07, R4, 2)
        a.stx(BPF_DW, R10, R4, CURSOR)          # -> first extension header
        a.st_imm(BPF_DW, R10, BESTV, 0)
        a.st_imm(BPF_DW, R10, KNOWNF, 0)
        for i in range(TLS_MAX_EXTS):
            a.label(f"{t}_che_{i}")
            a.ldx(BPF_DW, R3, R10, EXTREM)
            a.jmp_imm(0xA5, R3, 4, done)        # no room for a header
            ext_hdr_and_type()
            a.jmp_imm(0x15, R3, 0x002B, f"{t}_chsv")
            ext_advance(i, "che", done)
        a.jmp(done)
        # supported_versions list: <=5 versions, favor known then higher
        # (IS_KNOWN_VERSION_EXT semantics, tls_tracker.h:112-120)
        a.label(f"{t}_chsv")
        a.stx(BPF_DW, R10, R4, EXTREM)          # reuse: bytes in this ext
        for j in range(5):
            a.label(f"{t}_chv_{j}")
            a.ldx(BPF_DW, R3, R10, EXTREM)
            a.jmp_imm(0xA5, R3, 3 + 2 * j, f"{t}_chv_end")
            cur_load(4 + 1 + 2 * j, 4, 2)       # skip hdr(4) + list len(1)
            ntohs_at(TLSBUF + 4, R3)
            a.mov_imm(R4, 0)
            a.jmp_imm(0xA5, R3, 0x0300, f"{t}_chv{j}_k")
            a.jmp_imm(0x25, R3, 0x0304, f"{t}_chv{j}_k")
            a.mov_imm(R4, 1)                    # 0x0300..0x0304: known
            a.label(f"{t}_chv{j}_k")
            nxt = f"{t}_chv_{j + 1}" if j < 4 else f"{t}_chv_end"
            a.ldx(BPF_DW, R5, R10, KNOWNF)
            a.jmp_reg(0x1D, R5, R4, f"{t}_chv{j}_same")
            a.jmp_imm(0x15, R4, 1, f"{t}_chv{j}_take")  # known beats unknown
            a.jmp(nxt)
            a.label(f"{t}_chv{j}_same")
            a.ldx(BPF_DW, R5, R10, BESTV)
            a.jmp_reg(0xBD, R3, R5, nxt)        # JLE: not higher -> skip
            a.label(f"{t}_chv{j}_take")
            a.stx(BPF_DW, R10, R3, BESTV)
            a.stx(BPF_DW, R10, R4, KNOWNF)
        a.label(f"{t}_chv_end")
        a.ldx(BPF_DW, R3, R10, BESTV)
        a.jmp_imm(0x15, R3, 0, done)            # empty list: keep legacy
        a.stx(BPF_H, R10, R3, VAL + _st("ssl_version"))
        a.jmp(done)

        # --- ServerHello: cipher suite, then supported_versions/key_share --
        a.label(f"{t}_srv")
        # session id length at payload+43 (5 rec + 4 hs + 2 ver + 32 random)
        load_bytes(lambda: (a.mov_reg(R2, R9), a.alu_imm(0x07, R2, 43)),
                   11, 1)
        a.ldx(BPF_B, R5, R10, TLSBUF + 11)
        a.jmp_imm(0x25, R5, 32, done)           # sid_len > 32: implausible
        # CURSOR -> cipher suite (payload + 44 + sid_len); r1-r5 die at
        # every helper call, so the offset lives on the stack from here on
        a.mov_reg(R3, R9)
        a.alu_reg(0x0F, R3, R5)
        a.alu_imm(0x07, R3, 44)
        a.stx(BPF_DW, R10, R3, CURSOR)
        cur_load(0, 12, 2)
        ntohs_at(TLSBUF + 12, R3)
        a.stx(BPF_H, R10, R3, VAL + _st("tls_cipher_suite"))
        a.ldx(BPF_H, R3, R10, VAL + _st("ssl_version"))
        a.jmp_imm(0x55, R3, 0x0303, done)       # exts only disambiguate 1.3
        # layout after cipher: compression(1) + exts_len(2) + extensions
        cur_load(3, 0, 2)
        ntohs_at(TLSBUF, R3)
        a.stx(BPF_DW, R10, R3, EXTREM)
        a.ldx(BPF_DW, R4, R10, CURSOR)
        a.alu_imm(0x07, R4, 5)
        a.stx(BPF_DW, R10, R4, CURSOR)          # first extension header
        for i in range(TLS_MAX_EXTS):
            a.label(f"{t}_she_{i}")
            a.ldx(BPF_DW, R3, R10, EXTREM)
            a.jmp_imm(0xA5, R3, 4, done)
            ext_hdr_and_type()
            a.jmp_imm(0x15, R3, 0x002B, f"{t}_she_{i}_sv")
            a.jmp_imm(0x15, R3, 0x0033, f"{t}_she_{i}_ks")
            a.jmp(f"{t}_she_{i}_adv")
            a.label(f"{t}_she_{i}_sv")          # the selected 1.3 version
            a.jmp_imm(0xA5, R4, 2, f"{t}_she_{i}_adv")
            cur_load(4, 4, 2)
            ntohs_at(TLSBUF + 4, R3)
            a.stx(BPF_H, R10, R3, VAL + _st("ssl_version"))
            a.jmp(f"{t}_she_{i}_adv")
            a.label(f"{t}_she_{i}_ks")          # key-share group
            a.jmp_imm(0xA5, R4, 2, f"{t}_she_{i}_adv")
            cur_load(4, 4, 2)
            ntohs_at(TLSBUF + 4, R3)
            a.stx(BPF_H, R10, R3, VAL + _st("tls_key_share"))
            ext_advance(i, "she", done)
        a.label(done)
        a.mov_imm(R9, 6)                        # restore proto for the
        # shared ports/tracker gates downstream

    def slow_l4(self, v: str, icmp_proto: int) -> None:
        """L4 key fields at a DYNAMIC offset (stack slot CURSOR) via
        bpf_skb_load_bytes — used by the IPv4-options and IPv6-extension
        slow paths, where the L4 offset isn't a verifier-visible constant.
        Ports/ICMP + TCP FLAGS (into SPILL, so flag accumulation, the
        filter's tcp_flags predicate, and handshake-RTT stamping all work
        for slow-path TCP flows too), plus the UDP payload trackers
        (DNS/QUIC via the shared `udp_trackers`, reading at CURSOR+8);
        only passive TLS stays fast-path-only (it needs the TCP doff
        walk). r9 = final transport protocol. Truncated packets keep the
        address+proto key (reference behavior: fill_l4info leaves ports
        zero when the header doesn't fit)."""
        a = self.a
        t = f"slow_{v}"

        def load_at_cursor(n: int) -> None:
            a.mov_reg(R1, R6)
            a.ldx(BPF_DW, R2, R10, CURSOR)
            a.mov_reg(R3, R10)
            a.alu_imm(0x07, R3, TLSBUF)
            a.mov_imm(R4, n)
            a.call(HELPER_SKB_LOAD_BYTES)
            a.jmp_imm(0x55, R0, 0, "key_done")

        def ports_from_tlsbuf() -> None:
            a.ldx(BPF_B, R3, R10, TLSBUF)
            a.alu_imm(0x67, R3, 8)
            a.ldx(BPF_B, R4, R10, TLSBUF + 1)
            a.alu_reg(0x4F, R3, R4)
            a.stx(BPF_H, R10, R3, KEY + KY_SPORT)
            a.ldx(BPF_B, R3, R10, TLSBUF + 2)
            a.alu_imm(0x67, R3, 8)
            a.ldx(BPF_B, R4, R10, TLSBUF + 3)
            a.alu_reg(0x4F, R3, R4)
            a.stx(BPF_H, R10, R3, KEY + KY_DPORT)

        a.jmp_imm(0x15, R9, 6, f"{t}_t")
        a.jmp_imm(0x15, R9, 17, f"{t}_p")
        a.jmp_imm(0x15, R9, 132, f"{t}_p")
        a.jmp_imm(0x15, R9, icmp_proto, f"{t}_i")
        a.jmp("key_done")
        a.label(f"{t}_t")
        # TCP: ports + the flags byte (tcphdr+13), composite-classified
        # exactly like the fast path
        load_at_cursor(14)
        ports_from_tlsbuf()
        a.ldx(BPF_B, R3, R10, TLSBUF + 13)
        self.classify_tcp_flags(t)
        a.stx(BPF_DW, R10, R3, SPILL)
        a.jmp("key_done")
        a.label(f"{t}_p")
        load_at_cursor(4)
        ports_from_tlsbuf()
        # UDP payload trackers (DNS/QUIC) at the DYNAMIC offset: the UDP
        # header sits at CURSOR, so slow-path flows get the same feature
        # enrichment as the fast path (r3 gap closed; TLS stays fast-path
        # -only — its parse needs the TCP doff walk)
        self.udp_trackers(tag=t, payload_base=None)
        a.jmp("key_done")
        a.label(f"{t}_i")
        load_at_cursor(2)
        a.ldx(BPF_B, R3, R10, TLSBUF)
        a.stx(BPF_B, R10, R3, KEY + KY_ICMP_TYPE)
        a.ldx(BPF_B, R3, R10, TLSBUF + 1)
        a.stx(BPF_B, R10, R3, KEY + KY_ICMP_CODE)
        a.jmp("key_done")

    def copy_ip16(self, pkt_off: int, key_off: int) -> None:
        """Copy a 16-byte address from the packet to the key (word chunks:
        stack DW stores would be misaligned at these offsets)."""
        a = self.a
        for i in range(0, 16, 4):
            a.ldx(BPF_W, R3, R7, pkt_off + i)
            a.stx(BPF_W, R10, R3, key_off + i)

    def corr_key(self, reverse: bool) -> None:
        """Build no_dns_corr_key at CORR from the flow key on the stack.
        reverse=True swaps src/dst (query side: the response's own tuple
        must produce this key)."""
        a = self.a
        sp, dp = (KY_DPORT, KY_SPORT) if reverse else (KY_SPORT, KY_DPORT)
        si, di = (KY_DST_IP, KY_SRC_IP) if reverse else (KY_SRC_IP, KY_DST_IP)
        for off in range(CORR, CORR + 40, 8):
            a.st_imm(BPF_DW, R10, off, 0)
        a.ldx(BPF_H, R4, R10, KEY + sp)
        a.stx(BPF_H, R10, R4, CORR + CK_SPORT)
        a.ldx(BPF_H, R4, R10, KEY + dp)
        a.stx(BPF_H, R10, R4, CORR + CK_DPORT)
        for i in range(0, 16, 4):
            a.ldx(BPF_W, R4, R10, KEY + si + i)
            a.stx(BPF_W, R10, R4, CORR + CK_SRC_IP + i)
            a.ldx(BPF_W, R4, R10, KEY + di + i)
            a.stx(BPF_W, R10, R4, CORR + CK_DST_IP + i)
        a.ldx(BPF_H, R4, R10, DNSMETA)
        a.stx(BPF_H, R10, R4, CORR + CK_ID)
        a.ldx(BPF_B, R4, R10, KEY + KY_PROTO)
        a.stx(BPF_B, R10, R4, CORR + CK_PROTO)

    def stamp(self, fd: int) -> None:
        """rtt/dns shared half: record NOW in `fd` under the REVERSED tuple
        (the reply's own tuple will produce this key). Falls through with the
        update result in r0 for callers that count failures."""
        a = self.a
        self.corr_key(reverse=True)
        a.ld_map_fd(R1, fd)
        a.mov_reg(R2, R10)
        a.alu_imm(0x07, R2, CORR)
        a.mov_reg(R3, R10)
        a.alu_imm(0x07, R3, NOW)
        a.mov_imm(R4, 0)                        # BPF_ANY
        a.call(HELPER_MAP_UPDATE)

    def measure(self, fd: int, done: str, tag: str) -> None:
        """rtt/dns shared half: correlate the reply's own tuple against the
        stamp in `fd`, leave (NOW - stamp) in the LAT slot when the clocks
        agree, delete the stamp, and fall through to `done`."""
        a = self.a
        self.corr_key(reverse=False)
        a.ld_map_fd(R1, fd)
        a.mov_reg(R2, R10)
        a.alu_imm(0x07, R2, CORR)
        a.call(HELPER_MAP_LOOKUP)
        a.jmp_imm(0x15, R0, 0, done)
        a.ldx(BPF_DW, R3, R0, 0)                # stamp_ns
        a.ldx(BPF_DW, R4, R10, NOW)
        a.jmp_reg(0xBD, R4, R3, f"{tag}_del")   # now <= stamp: clock skew
        a.alu_reg(0x1F, R4, R3)                 # r4 = now - stamp
        a.stx(BPF_DW, R10, R4, LAT)
        a.label(f"{tag}_del")
        a.ld_map_fd(R1, fd)
        a.mov_reg(R2, R10)
        a.alu_imm(0x07, R2, CORR)
        a.call(HELPER_MAP_DELETE)

    def filter_key(self, ip_off: int) -> None:
        """Build no_filter_key at FKEY (prefix_len=128 + one key address)."""
        a = self.a
        a.st_imm(BPF_W, R10, FKEY, 128)
        for i in range(0, 16, 4):
            a.ldx(BPF_W, R3, R10, KEY + ip_off + i)
            a.stx(BPF_W, R10, R3, FKEY + 4 + i)

    def port_pred(self, port_off: int, base: str, fail: str, tag: str) -> None:
        """One side's port predicate vs the rule in r0 (filter.h
        no_port_pred_ok): range [start,end] when set, then 1-2 exact ports
        when set. `base` in {dport, sport}."""
        a = self.a
        a.ldx(BPF_H, R9, R10, KEY + port_off)
        a.ldx(BPF_H, R3, R0, _fr(f"{base}_start"))
        a.ldx(BPF_H, R4, R0, _fr(f"{base}_end"))
        a.mov_reg(R5, R3)
        a.alu_reg(0x4F, R5, R4)
        a.jmp_imm(0x15, R5, 0, f"{tag}_norange")
        a.jmp_reg(0xAD, R9, R3, fail)           # port < start
        a.jmp_reg(0x2D, R9, R4, fail)           # port > end
        a.label(f"{tag}_norange")
        a.ldx(BPF_H, R3, R0, _fr(f"{base}1"))
        a.ldx(BPF_H, R4, R0, _fr(f"{base}2"))
        a.mov_reg(R5, R3)
        a.alu_reg(0x4F, R5, R4)
        a.jmp_imm(0x15, R5, 0, f"{tag}_ok")
        a.jmp_reg(0x1D, R9, R3, f"{tag}_ok")    # == p1
        a.jmp_reg(0x5D, R9, R4, fail)           # != p2 either
        a.label(f"{tag}_ok")

    def filter_side(self, side: str, keyed_ip: int, peer_ip: int,
                    fail: str) -> None:
        """One evaluation of filter.h's no_filter_try: LPM rule lookup on
        `keyed_ip`, all predicates, optional peer-CIDR check, then verdict.
        Jumps to `fail` when this side produced no usable match (-1 in C)."""
        a = self.a
        t = f"flt_{side}"
        if self.has_filter_sampling:
            # reset per-side: a predicates-pass match that then fails the
            # peer-CIDR check must not leak its sample_override into the
            # retry/no-match sampling decision
            a.st_imm(BPF_DW, R10, FSAMP, 0)
        self.filter_key(keyed_ip)
        a.ld_map_fd(R1, self.filter_rules_fd)
        a.mov_reg(R2, R10)
        a.alu_imm(0x07, R2, FKEY)
        a.call(HELPER_MAP_LOOKUP)
        a.jmp_imm(0x15, R0, 0, fail)
        # r0 = rule; predicates (no helper calls until the peer check)
        a.ldx(BPF_B, R3, R0, _fr("proto"))
        a.jmp_imm(0x15, R3, 0, f"{t}_proto_ok")
        a.ldx(BPF_B, R4, R10, KEY + KY_PROTO)
        a.jmp_reg(0x5D, R3, R4, fail)
        a.label(f"{t}_proto_ok")
        a.ldx(BPF_B, R3, R0, _fr("direction"))
        a.jmp_imm(0x15, R3, 255, f"{t}_dir_ok")
        a.jmp_imm(0x55, R3, self.direction, fail)
        a.label(f"{t}_dir_ok")
        self.port_pred(KY_DPORT, "dport", fail, f"{t}_dp")
        self.port_pred(KY_SPORT, "sport", fail, f"{t}_sp")
        # either-direction range: sp in [start,end] OR dp in [start,end]
        a.ldx(BPF_H, R3, R0, _fr("port_start"))
        a.ldx(BPF_H, R4, R0, _fr("port_end"))
        a.mov_reg(R5, R3)
        a.alu_reg(0x4F, R5, R4)
        a.jmp_imm(0x15, R5, 0, f"{t}_norange")
        a.ldx(BPF_H, R9, R10, KEY + KY_SPORT)
        a.jmp_reg(0xAD, R9, R3, f"{t}_try_dp")  # sp < start
        a.jmp_reg(0xBD, R9, R4, f"{t}_range_ok")  # sp <= end
        a.label(f"{t}_try_dp")
        a.ldx(BPF_H, R9, R10, KEY + KY_DPORT)
        a.jmp_reg(0xAD, R9, R3, fail)
        a.jmp_reg(0x2D, R9, R4, fail)
        a.label(f"{t}_range_ok")
        a.label(f"{t}_norange")
        # either-direction exact ports: any of sp/dp == p1/p2
        a.ldx(BPF_H, R3, R0, _fr("port1"))
        a.ldx(BPF_H, R4, R0, _fr("port2"))
        a.mov_reg(R5, R3)
        a.alu_reg(0x4F, R5, R4)
        a.jmp_imm(0x15, R5, 0, f"{t}_ports_ok")
        a.ldx(BPF_H, R9, R10, KEY + KY_SPORT)
        a.jmp_reg(0x1D, R9, R3, f"{t}_ports_ok")
        a.jmp_reg(0x1D, R9, R4, f"{t}_ports_ok")
        a.ldx(BPF_H, R9, R10, KEY + KY_DPORT)
        a.jmp_reg(0x1D, R9, R3, f"{t}_ports_ok")
        a.jmp_reg(0x5D, R9, R4, fail)
        a.label(f"{t}_ports_ok")
        a.ldx(BPF_B, R3, R0, _fr("icmp_type"))
        a.jmp_imm(0x15, R3, 0, f"{t}_it_ok")
        a.ldx(BPF_B, R4, R10, KEY + KY_ICMP_TYPE)
        a.jmp_reg(0x5D, R3, R4, fail)
        a.label(f"{t}_it_ok")
        a.ldx(BPF_B, R3, R0, _fr("icmp_code"))
        a.jmp_imm(0x15, R3, 0, f"{t}_ic_ok")
        a.ldx(BPF_B, R4, R10, KEY + KY_ICMP_CODE)
        a.jmp_reg(0x5D, R3, R4, fail)
        a.label(f"{t}_ic_ok")
        a.ldx(BPF_H, R3, R0, _fr("tcp_flags"))
        a.jmp_imm(0x15, R3, 0, f"{t}_tf_ok")
        a.ldx(BPF_DW, R4, R10, SPILL)
        a.alu_reg(0x5F, R4, R3)                 # r4 &= rule flags
        a.jmp_imm(0x15, R4, 0, fail)
        a.label(f"{t}_tf_ok")
        a.ldx(BPF_B, R3, R0, _fr("want_drops"))
        a.jmp_imm(0x55, R3, 0, fail)            # TC path is never drops
        # predicates hold; save the verdict before any further lookup
        a.ldx(BPF_B, R3, R0, _fr("action"))
        a.stx(BPF_DW, R10, R3, FACT)
        if self.has_filter_sampling:
            a.ldx(BPF_W, R3, R0, _fr("sample_override"))
            a.stx(BPF_W, R10, R3, FSAMP)
        a.ldx(BPF_B, R3, R0, _fr("peer_cidr_check"))
        a.jmp_imm(0x15, R3, 0, f"{t}_verdict")
        self.filter_key(peer_ip)
        a.ld_map_fd(R1, self.filter_peers_fd)
        a.mov_reg(R2, R10)
        a.alu_imm(0x07, R2, FKEY)
        a.call(HELPER_MAP_LOOKUP)
        a.jmp_imm(0x15, R0, 0, fail)            # peer outside CIDR: retry
        a.label(f"{t}_verdict")
        a.ldx(BPF_DW, R3, R10, FACT)
        a.jmp_imm(0x15, R3, 1, "flt_reject")    # NO_FILTER_REJECT
        self.count(CTR_FILTER_ACCEPT)
        a.jmp("flt_done")

    def filter_block(self) -> None:
        """filter.h no_flow_filter: source CIDR first, dst CIDR retry, then
        reject-on-no-match. With has_filter_sampling, the 1/N gate runs here
        instead of at entry, using the matched rule's `sample_override` (else
        the global rate) — and, matching the reference, the aux-probe gate is
        set from that decision even for packets the verdict then drops."""
        a = self.a
        self.filter_side("src", KY_SRC_IP, KY_DST_IP, fail="flt_dst")
        a.label("flt_dst")
        self.filter_side("dst", KY_DST_IP, KY_SRC_IP, fail="flt_nomatch")
        a.label("flt_nomatch")
        self.count(CTR_FILTER_NOMATCH)
        if self.has_filter_sampling:
            a.st_imm(BPF_DW, R10, FSKIP, 1)
            a.jmp("flt_sample")
        else:
            a.jmp("out")        # rules configured but none matched
        a.label("flt_reject")
        self.count(CTR_FILTER_REJECT)
        if self.has_filter_sampling:
            a.st_imm(BPF_DW, R10, FSKIP, 1)
            a.jmp("flt_sample")
        else:
            a.jmp("out")
        a.label("flt_done")
        if self.has_filter_sampling:
            a.label("flt_sample")
            # effective rate: the matched rule's override, else the global
            a.ldx(BPF_W, R9, R10, FSAMP)
            a.jmp_imm(0x55, R9, 0, "fs_have")
            a.mov_imm(R9, self.sampling)
            a.label("fs_have")
            a.stx(BPF_W, R10, R9, VAL + ST_SAMPLING)
            a.jmp_imm(0x25, R9, 1, "fs_gate")   # JGT: rate > 1 -> 1/N
            if self.sampling_gate_fd is not None:
                self.set_gate(1)
            a.jmp("fs_skipchk")
            a.label("fs_gate")
            a.call(HELPER_PRANDOM_U32)
            a.alu_reg(0x9F, R0, R9)             # r0 %= rate (ALU64 MOD X)
            a.jmp_imm(0x15, R0, 0, "fs_sampled")
            if self.sampling_gate_fd is not None:
                self.set_gate(0)
            a.jmp("out")                        # not the sampled 1/N
            a.label("fs_sampled")
            if self.sampling_gate_fd is not None:
                self.set_gate(1)
            a.label("fs_skipchk")
            a.ldx(BPF_DW, R3, R10, FSKIP)
            a.jmp_imm(0x55, R3, 0, "out")       # verdict said drop

    def build(self) -> bytes:
        """entry/parse/filter head + the flow-aggregation tail."""
        self.emit_head()
        self.emit_tail()
        a = self.a
        a.label("out")
        a.mov_imm(R0, 0)                        # TC_ACT_OK
        a.exit()
        return a.assemble()

    def emit_head(self) -> None:
        """Everything up to a built+filtered flow key: sampling gate, parse
        (key/MACs/DSCP/flags + enabled tracker header parses), and the flow
        -filter gate. Falls through with the key at KEY and per-packet
        tracker metadata on the stack; unparseable/filtered packets jumped
        to \"out\" (the caller emits that label)."""
        a = self.a
        a.mov_reg(R6, R1)                       # r6 = ctx

        if self.sampling > 1 and not self.has_filter_sampling:
            # 1/N gate, baked in at build time (loader-rewritten-const analog)
            a.call(HELPER_PRANDOM_U32)
            a.alu_imm(0x97, R0, self.sampling)  # r0 %= N (ALU64 MOD K)
            if self.sampling_gate_fd is not None:
                a.jmp_imm(0x55, R0, 0, "unsampled")
                self.set_gate(1)
                a.jmp("sampled")
                a.label("unsampled")
                self.set_gate(0)
                a.jmp("out")
                a.label("sampled")
            else:
                a.jmp_imm(0x55, R0, 0, "out")   # not the sampled 1/N: out

        a.call(HELPER_KTIME_GET_NS)
        a.stx(BPF_DW, R10, R0, NOW)

        a.ldx(BPF_W, R7, R6, SKB_DATA)          # r7 = data
        a.ldx(BPF_W, R8, R6, SKB_DATA_END)      # r8 = data_end
        self.bounds(14, "out")

        # zero the event + scratch slots
        for off in range(EV, EV + EVENT_SIZE, 8):
            a.st_imm(BPF_DW, R10, off, 0)
        a.st_imm(BPF_DW, R10, SPILL, 0)
        a.st_imm(BPF_DW, R10, DNSMETA, 0)
        a.st_imm(BPF_DW, R10, LAT, 0)
        a.st_imm(BPF_DW, R10, QMETA, 0)

        # MACs: frame dst at 0..5, src at 6..11 (stats carry the packet's)
        a.ldx(BPF_W, R3, R7, 6)
        a.stx(BPF_W, R10, R3, VAL + ST_SRC_MAC)
        a.ldx(BPF_H, R3, R7, 10)
        a.stx(BPF_H, R10, R3, VAL + ST_SRC_MAC + 4)
        # dst_mac lands on a 2-aligned stack offset: half-word stores only
        for i in range(0, 6, 2):
            a.ldx(BPF_H, R3, R7, i)
            a.stx(BPF_H, R10, R3, VAL + ST_DST_MAC + i)

        a.ldx(BPF_H, R3, R7, 12)                # ethertype (LE view of BE)
        a.jmp_imm(0x15, R3, 0x0008, "v4")
        a.jmp_imm(0x15, R3, 0xDD86, "v6")
        a.jmp("out")

        # --- IPv4 ---------------------------------------------------------
        a.label("v4")
        self.bounds(38, "out")                  # eth+ip20+l4 first 4 bytes

        def v4_l3() -> None:
            """DSCP/proto/addresses — all within the fixed 20-byte header."""
            a.ldx(BPF_B, R3, R7, 15)            # TOS -> dscp
            a.alu_imm(0x77, R3, 2)
            a.stx(BPF_B, R10, R3, VAL + ST_DSCP)
            a.ldx(BPF_B, R9, R7, 23)            # protocol
            a.stx(BPF_B, R10, R9, KEY + KY_PROTO)
            # v4-mapped addresses: ::ffff prefix + 4 address bytes
            a.st_imm(BPF_H, R10, KEY + KY_SRC_IP + 10, 0xFFFF)
            a.ldx(BPF_W, R3, R7, 26)            # saddr (BE bytes as-is)
            a.stx(BPF_W, R10, R3, KEY + KY_SRC_IP + 12)
            a.st_imm(BPF_H, R10, KEY + KY_DST_IP + 10, 0xFFFF)
            a.ldx(BPF_W, R3, R7, 30)            # daddr
            a.stx(BPF_W, R10, R3, KEY + KY_DST_IP + 12)
            a.st_imm(BPF_H, R10, VAL + ST_ETH, 0x0800)
            # non-first fragments carry no L4 header: keep the addrs+proto
            # key, never read payload bytes as ports (the reference doesn't
            # check frag_off and mis-keys these). LE halfword view of the
            # BE flags/fragment-offset field: 0xFF1F covers the 13 offset
            # bits and excludes MF/DF, so first fragments still parse ports
            a.ldx(BPF_H, R3, R7, 20)
            a.alu_imm(0x57, R3, 0xFF1F)
            a.jmp_imm(0x55, R3, 0, "key_done")

        a.ldx(BPF_B, R3, R7, 14)                # version/ihl
        a.jmp_imm(0x15, R3, 0x45, "v4_std")
        # IP options present: the reference mis-parses these (fill_iphdr
        # assumes ihl=5, utils.h:113-118); here the L4 offset is computed
        # from ihl and the ports read via bpf_skb_load_bytes
        a.mov_reg(R4, R3)
        a.alu_imm(0x77, R4, 4)
        a.jmp_imm(0x55, R4, 4, "out")           # not IPv4: drop
        a.alu_imm(0x57, R3, 0x0F)
        a.jmp_imm(0xA5, R3, 5, "out")           # ihl < 5: malformed
        a.alu_imm(0x27, R3, 4)
        a.alu_imm(0x07, R3, 14)
        a.stx(BPF_DW, R10, R3, CURSOR)          # dynamic L4 offset
        v4_l3()
        self.slow_l4("v4", icmp_proto=1)

        a.label("v4_std")
        v4_l3()
        self.parse_l4(l4=34, v="v4", icmp_proto=1)

        # --- IPv6 ---------------------------------------------------------
        a.label("v6")
        self.bounds(54, "out")                  # eth + fixed v6 header
        # traffic class = low nibble of byte14 ++ high nibble of byte15;
        # dscp = tc >> 2
        a.ldx(BPF_B, R3, R7, 14)
        a.alu_imm(0x57, R3, 0x0F)
        a.alu_imm(0x67, R3, 2)
        a.ldx(BPF_B, R4, R7, 15)
        a.alu_imm(0x77, R4, 6)
        a.alu_reg(0x4F, R3, R4)
        a.stx(BPF_B, R10, R3, VAL + ST_DSCP)
        self.copy_ip16(22, KEY + KY_SRC_IP)
        self.copy_ip16(38, KEY + KY_DST_IP)
        a.st_imm(BPF_H, R10, VAL + ST_ETH, 0x86DD)
        a.ldx(BPF_B, R9, R7, 20)                # next header
        a.stx(BPF_B, R10, R9, KEY + KY_PROTO)
        _V6_EXT = (0, 43, 44, 60)               # hop/routing/frag/dst-opts
        for h in _V6_EXT:
            a.jmp_imm(0x15, R9, h, "v6_ext")
        self.parse_l4(l4=54, v="v6", icmp_proto=58)

        # extension-header chain walk (the reference skips this entirely —
        # utils.h:133-148 keys such flows on the FIRST next-header with no
        # ports; here a bounded walk finds the real transport). Each header
        # is [next-header, hdr-ext-len] with size 8 + len*8 bytes, except
        # the fragment header which is a fixed 8.
        a.label("v6_ext")
        a.st_imm(BPF_DW, R10, CURSOR, 54)
        for step in range(4):
            a.label(f"v6x_{step}")
            a.mov_reg(R1, R6)
            a.ldx(BPF_DW, R2, R10, CURSOR)
            a.mov_reg(R3, R10)
            a.alu_imm(0x07, R3, TLSBUF)
            a.mov_imm(R4, 4)    # [nh, len, frag-off hi, frag-off lo]
            a.call(HELPER_SKB_LOAD_BYTES)
            # truncated chain: keyed on the last seen next-header, no ports
            a.jmp_imm(0x55, R0, 0, "key_done")
            # size of the CURRENT header (its type is in the flow key slot)
            a.ldx(BPF_B, R3, R10, KEY + KY_PROTO)
            a.ldx(BPF_B, R4, R10, TLSBUF + 1)   # hdr-ext-len
            a.jmp_imm(0x55, R3, 44, f"v6x_{step}_var")
            a.mov_imm(R4, 0)                    # fragment: fixed 8 bytes
            # non-first fragment (13-bit offset != 0): no L4 header in this
            # packet — key on addrs + the fragment's next-header, portless
            a.ldx(BPF_B, R3, R10, TLSBUF + 2)
            a.alu_imm(0x67, R3, 8)
            a.ldx(BPF_B, R5, R10, TLSBUF + 3)
            a.alu_reg(0x4F, R3, R5)
            a.alu_imm(0x57, R3, 0xFFF8)
            a.jmp_imm(0x15, R3, 0, f"v6x_{step}_var")
            a.ldx(BPF_B, R3, R10, TLSBUF)
            a.stx(BPF_B, R10, R3, KEY + KY_PROTO)
            a.jmp("key_done")
            a.label(f"v6x_{step}_var")
            a.alu_imm(0x27, R4, 8)
            a.alu_imm(0x07, R4, 8)
            a.ldx(BPF_DW, R5, R10, CURSOR)
            a.alu_reg(0x0F, R5, R4)
            a.stx(BPF_DW, R10, R5, CURSOR)
            a.ldx(BPF_B, R9, R10, TLSBUF)       # chain's next-header
            a.stx(BPF_B, R10, R9, KEY + KY_PROTO)
            if step < 3:
                nxt = f"v6x_{step + 1}"
                for h in _V6_EXT:
                    a.jmp_imm(0x15, R9, h, nxt)
                a.jmp("v6x_done")
                # the jeqs above fall through to the next iteration only via
                # `nxt`; non-extension headers exit the walk
        a.label("v6x_done")
        self.slow_l4("v6", icmp_proto=58)

        a.label("key_done")

        # --- flow filter gate (filter.h twin; before trackers/upsert) ------
        if self.filter_rules_fd is not None:
            if self.has_filter_sampling:
                a.st_imm(BPF_DW, R10, FSKIP, 0)
            self.filter_block()

    def emit_tail(self) -> None:
        """Flow aggregation: correlations, upsert, feature records."""
        a = self.a
        # --- DNS correlation (stack-only; before the flow upsert) ----------
        if self.dns_inflight_fd is not None:
            a.ldx(BPF_W, R3, R10, DNSMETA + 4)
            a.jmp_imm(0x15, R3, 0, "rtt_chk")
            a.ldx(BPF_H, R3, R10, DNSMETA + 2)
            a.jmp_imm(0x45, R3, DNS_QR_BIT, "dns_resp")   # JSET: response
            # query: stash timestamp under the reversed tuple
            self.stamp(self.dns_inflight_fd)
            a.jmp_imm(0x15, R0, 0, "rtt_chk")
            self.count(CTR_FAIL_UPDATE_DNS)
            a.jmp("rtt_chk")
            # response: correlate to the stashed query and compute latency
            a.label("dns_resp")
            self.measure(self.dns_inflight_fd, done="rtt_chk", tag="dns")

        # --- TCP handshake RTT (SYN -> SYN|ACK correlation) ----------------
        # The clang path measures smoothed RTT from fentry:tcp_rcv_established
        # (flowpath_probes.c); without BTF the assembler measures the
        # handshake RTT instead: a pure SYN stamps rtt_inflight under the
        # reversed tuple (the corr key builder zero-pads dns_id for TCP) and
        # the returning SYN|ACK's own tuple correlates to a latency. DNS (UDP)
        # and RTT (TCP) are per-packet exclusive, so CORR/LAT slots are shared.
        a.label("rtt_chk")
        if self.rtt_inflight_fd is not None:
            a.ldx(BPF_B, R3, R10, KEY + KY_PROTO)
            a.jmp_imm(0x55, R3, 6, "flow_upsert")
            a.ldx(BPF_DW, R3, R10, SPILL)
            a.jmp_imm(0x45, R3, 0x02, "rtt_syn_any")      # SYN bit set?
            a.jmp("flow_upsert")
            a.label("rtt_syn_any")
            a.jmp_imm(0x45, R3, 0x10, "rtt_synack")       # ACK too?
            # pure SYN: stamp the reversed tuple (dns_id stays zero for TCP)
            self.stamp(self.rtt_inflight_fd)
            a.jmp("flow_upsert")
            a.label("rtt_synack")
            self.measure(self.rtt_inflight_fd, done="flow_upsert", tag="rtt")

        # --- flow upsert ---------------------------------------------------
        a.label("flow_upsert")
        a.ld_map_fd(R1, self.map_fd)
        a.mov_reg(R2, R10)
        a.alu_imm(0x07, R2, KEY)
        a.call(HELPER_MAP_LOOKUP)
        a.jmp_imm(0x15, R0, 0, "miss")

        # hit: multi-interface dedup (reference bpf/flows.c:100-110) — only
        # the interface that FIRST saw the flow counts bytes/packets; any
        # other interface updates last_seen/flags and the observed list
        a.label("hit_merge")
        a.ldx(BPF_W, R4, R6, SKB_IFINDEX)
        a.ldx(BPF_W, R3, R0, ST_IFINDEX)
        a.jmp_reg(0x5D, R3, R4, "hit_other")    # not the first-seen intf
        # counting path: bytes += skb->len (atomic), packets += 1 (atomic),
        # last_seen = now (plain store: racing writers both store ~now, so
        # the field is correct to within one packet's skew — the one update
        # the C twin's spin lock covers that stays lock-free here, since
        # spin locks need BTF-described map values the assembler path
        # doesn't have), flags |= packet flags (ATOMIC or: no lost bits)
        a.ldx(BPF_W, R3, R6, SKB_LEN)
        a.atomic_add(BPF_DW, R0, R3, ST_BYTES)
        a.mov_imm(R4, 1)
        a.atomic_add(BPF_W, R0, R4, ST_PACKETS)
        a.ldx(BPF_DW, R3, R10, NOW)
        a.stx(BPF_DW, R0, R3, ST_LAST)
        a.ldx(BPF_DW, R3, R10, SPILL)
        if _FLAGS_SHIFT:
            a.alu_imm(0x67, R3, _FLAGS_SHIFT)   # flags -> tcp_flags bytes (LE)
        a.atomic_or(BPF_W, R0, R3, ST_ETH)
        if self.has_filter_sampling:
            # latest effective rate wins (stored by flt_sample on the stack)
            a.ldx(BPF_W, R3, R10, VAL + ST_SAMPLING)
            a.stx(BPF_W, R0, R3, ST_SAMPLING)
        elif self.sampling > 1:
            a.mov_imm(R3, self.sampling)
            a.stx(BPF_W, R0, R3, ST_SAMPLING)
        if self.enable_tls:
            # TLS merge on the counting path (flowpath.c:64-80): first
            # version wins; a later conflicting hello sets the mismatch flag
            a.ldx(BPF_H, R3, R10, VAL + _st("ssl_version"))
            a.jmp_imm(0x15, R3, 0, "tlsm_ciph")
            a.ldx(BPF_H, R4, R0, _st("ssl_version"))
            a.jmp_imm(0x15, R4, 0, "tlsm_store")
            a.jmp_reg(0x1D, R4, R3, "tlsm_ciph")    # same version: ok
            a.ldx(BPF_B, R5, R0, _st("misc_flags"))
            a.alu_imm(0x47, R5, 0x01)               # NO_MISC_SSL_MISMATCH
            a.stx(BPF_B, R0, R5, _st("misc_flags"))
            a.jmp("tlsm_ciph")
            a.label("tlsm_store")
            a.stx(BPF_H, R0, R3, _st("ssl_version"))
            a.label("tlsm_ciph")
            a.ldx(BPF_H, R3, R10, VAL + _st("tls_cipher_suite"))
            a.jmp_imm(0x15, R3, 0, "tlsm_ks")
            a.stx(BPF_H, R0, R3, _st("tls_cipher_suite"))
            a.label("tlsm_ks")
            a.ldx(BPF_H, R3, R10, VAL + _st("tls_key_share"))
            a.jmp_imm(0x15, R3, 0, "tlsm_types")
            a.stx(BPF_H, R0, R3, _st("tls_key_share"))
            a.label("tlsm_types")
            a.ldx(BPF_B, R3, R10, VAL + _st("tls_types"))
            a.ldx(BPF_B, R4, R0, _st("tls_types"))
            a.alu_reg(0x4F, R3, R4)
            a.stx(BPF_B, R0, R3, _st("tls_types"))
        # dscp: latest nonzero wins (flowpath.c:62-63)
        a.ldx(BPF_B, R3, R10, VAL + ST_DSCP)
        a.jmp_imm(0x15, R3, 0, "dns_rec")
        a.stx(BPF_B, R0, R3, ST_DSCP)
        a.jmp("dns_rec")

        a.label("hit_other")
        # secondary interface: span/flags only — never re-count traffic
        a.ldx(BPF_DW, R3, R10, NOW)
        a.stx(BPF_DW, R0, R3, ST_LAST)
        a.ldx(BPF_DW, R3, R10, SPILL)
        if _FLAGS_SHIFT:
            a.alu_imm(0x67, R3, _FLAGS_SHIFT)   # flags -> tcp_flags bytes (LE)
        a.atomic_or(BPF_W, R0, R3, ST_ETH)
        # (ifindex, direction) dedup scan over the observed slots (r4 =
        # ifindex; direction is a build-time constant -> immediate compare)
        n_obs = binfmt.FLOW_STATS_DTYPE["observed_intf"].shape[0]
        for i in range(n_obs):
            a.ldx(BPF_W, R3, R0, ST_OBSIF + 4 * i)
            a.jmp_reg(0x5D, R3, R4, f"obs_next_{i}")
            a.ldx(BPF_B, R3, R0, ST_OBSDIR + i)
            a.jmp_imm(0x15, R3, self.direction, "dns_rec")  # recorded
            a.label(f"obs_next_{i}")
        # append via slot RESERVATION: fetch-add (1<<24) on the aligned word
        # holding n_observed_intf hands this CPU an exclusive slot index, so
        # concurrent appends can neither lose a slot nor tear each other's
        # entries. Readers tolerate the two residual artifacts: a reserved-
        # but-not-yet-written slot reads as ifindex 0 (skipped at read-out,
        # record.py), and a racing append of the SAME new interface may
        # duplicate it (dedup'd at read-out, record.py)
        a.mov_imm(R3, 1 << _NOBS_SHIFT)
        a.atomic_fetch_add(BPF_W, R0, R3, ST_DIR)  # r3 = old word
        if _NOBS_SHIFT:
            a.alu_imm(0x77, R3, _NOBS_SHIFT)    # r3 = old n (0..255)
        else:
            a.alu_imm(0x57, R3, 0xFF)           # BE: old n is the LOW byte
        a.jmp_imm(0x35, R3, n_obs, "obs_full")
        a.mov_reg(R5, R3)
        a.alu_imm(0x67, R5, 2)                  # n << 2
        a.mov_reg(R7, R0)
        a.alu_reg(0x0F, R7, R5)
        a.stx(BPF_W, R7, R4, ST_OBSIF)          # observed_intf[n] = ifindex
        a.mov_reg(R7, R0)
        a.alu_reg(0x0F, R7, R3)
        a.mov_imm(R5, self.direction)
        a.stx(BPF_B, R7, R5, ST_OBSDIR)         # observed_direction[n] = dir
        a.jmp("dns_rec")
        a.label("obs_full")
        # undo the reservation so the counter SATURATES near capacity (at
        # most +n_cpus transient) instead of wrapping at 256 and handing
        # out in-use slots; readers clamp at capacity
        a.mov_imm(R3, -(1 << _NOBS_SHIFT))
        a.atomic_add(BPF_W, R0, R3, ST_DIR)
        # overflow: count it, except for zero-proto traffic which routinely
        # saturates the array (reference bpf/flows.c:133-142)
        a.ldx(BPF_B, R3, R10, KEY + KY_PROTO)
        a.jmp_imm(0x15, R3, 0, "dns_rec")
        self.count(CTR_OBSERVED_INTF_MISSED)
        a.jmp("dns_rec")

        # miss: build fresh stats in the stack event and NOEXIST-insert
        a.label("miss")
        a.ldx(BPF_DW, R3, R10, NOW)
        a.stx(BPF_DW, R10, R3, VAL + ST_FIRST)
        a.stx(BPF_DW, R10, R3, VAL + ST_LAST)
        a.ldx(BPF_W, R3, R6, SKB_LEN)
        a.stx(BPF_DW, R10, R3, VAL + ST_BYTES)
        a.st_imm(BPF_W, R10, VAL + ST_PACKETS, 1)
        a.ldx(BPF_DW, R3, R10, SPILL)
        a.stx(BPF_H, R10, R3, VAL + ST_FLAGS)
        a.ldx(BPF_W, R4, R6, SKB_IFINDEX)
        a.stx(BPF_W, R10, R4, VAL + ST_IFINDEX)
        a.st_imm(BPF_B, R10, VAL + ST_DIR, self.direction)
        if not self.has_filter_sampling:
            # (with filter sampling, flt_sample already stored the rate)
            a.st_imm(BPF_W, R10, VAL + ST_SAMPLING, self.sampling)
        a.st_imm(BPF_B, R10, VAL + ST_NOBS, 1)
        a.st_imm(BPF_B, R10, VAL + ST_OBSDIR, self.direction)
        a.stx(BPF_W, R10, R4, VAL + ST_OBSIF)   # observed_intf[0]
        a.ld_map_fd(R1, self.map_fd)
        a.mov_reg(R2, R10)
        a.alu_imm(0x07, R2, KEY)
        a.mov_reg(R3, R10)
        a.alu_imm(0x07, R3, VAL)
        a.mov_imm(R4, 1)                        # BPF_NOEXIST
        a.call(HELPER_MAP_UPDATE)
        a.jmp_imm(0x15, R0, 0, "dns_rec")
        a.jmp_imm(0x15, R0, -17, "eexist")      # -EEXIST: lost insert race
        # map full (or other failure): count + ship the event upstairs
        a.mov_reg(R9, R0)                       # save err across count()
        self.count(CTR_FAIL_CREATE_FLOW)
        if self.ringbuf_fd is not None:
            a.mov_imm(R3, 0)
            a.alu_reg(0x1F, R3, R9)             # r3 = -err (positive errno)
            a.stx(BPF_B, R10, R3, VAL + ST_ERRNO)
            a.ld_map_fd(R1, self.ringbuf_fd)
            a.mov_reg(R2, R10)
            a.alu_imm(0x07, R2, EV)
            a.mov_imm(R3, EVENT_SIZE)
            a.mov_imm(R4, 0)
            a.call(HELPER_RINGBUF_OUTPUT)
        a.jmp("dns_rec")
        a.label("eexist")
        # another CPU created it between lookup and insert: merge into it
        a.ld_map_fd(R1, self.map_fd)
        a.mov_reg(R2, R10)
        a.alu_imm(0x07, R2, KEY)
        a.call(HELPER_MAP_LOOKUP)
        a.jmp_imm(0x55, R0, 0, "hit_merge")
        self.count(CTR_FAIL_UPDATE_FLOW)
        a.jmp("dns_rec")

        # --- DNS feature record (after the base flow update, dns.h twin) ---
        a.label("dns_rec")
        if self.flows_dns_fd is not None:
            a.ldx(BPF_W, R3, R10, DNSMETA + 4)
            a.jmp_imm(0x15, R3, 0, "extra_rec")
            a.ld_map_fd(R1, self.flows_dns_fd)
            a.mov_reg(R2, R10)
            a.alu_imm(0x07, R2, KEY)
            a.call(HELPER_MAP_LOOKUP)
            a.jmp_imm(0x15, R0, 0, "dnsrec_miss")
            # update this CPU's slot in place
            a.ldx(BPF_DW, R3, R0, _dr("first_seen_ns"))
            a.jmp_imm(0x55, R3, 0, "dnsrec_last")
            a.ldx(BPF_DW, R4, R10, NOW)
            a.stx(BPF_DW, R0, R4, _dr("first_seen_ns"))
            a.label("dnsrec_last")
            a.ldx(BPF_DW, R4, R10, NOW)
            a.stx(BPF_DW, R0, R4, _dr("last_seen_ns"))
            a.ldx(BPF_H, R3, R10, DNSMETA)
            a.stx(BPF_H, R0, R3, _dr("dns_id"))
            a.ldx(BPF_H, R3, R0, _dr("dns_flags"))
            a.ldx(BPF_H, R4, R10, DNSMETA + 2)
            a.alu_reg(0x4F, R3, R4)
            a.stx(BPF_H, R0, R3, _dr("dns_flags"))
            a.st_imm(BPF_B, R0, _dr("errno"), 0)
            # latency: max of observed (dns.h:116-117)
            a.ldx(BPF_DW, R3, R0, _dr("latency_ns"))
            a.ldx(BPF_DW, R4, R10, LAT)
            a.jmp_reg(0x3D, R3, R4, "extra_rec")  # existing >= new: keep
            a.stx(BPF_DW, R0, R4, _dr("latency_ns"))
            a.jmp("extra_rec")
            a.label("dnsrec_miss")
            for off in range(DNSREC, DNSREC + DNSREC_SIZE, 8):
                a.st_imm(BPF_DW, R10, off, 0)
            a.ldx(BPF_DW, R4, R10, NOW)
            a.stx(BPF_DW, R10, R4, DNSREC + _dr("first_seen_ns"))
            a.stx(BPF_DW, R10, R4, DNSREC + _dr("last_seen_ns"))
            a.ldx(BPF_DW, R4, R10, LAT)
            a.stx(BPF_DW, R10, R4, DNSREC + _dr("latency_ns"))
            a.ldx(BPF_H, R4, R10, DNSMETA)
            a.stx(BPF_H, R10, R4, DNSREC + _dr("dns_id"))
            a.ldx(BPF_H, R4, R10, DNSMETA + 2)
            a.stx(BPF_H, R10, R4, DNSREC + _dr("dns_flags"))
            a.ldx(BPF_H, R4, R10, VAL + ST_ETH)
            a.stx(BPF_H, R10, R4, DNSREC + _dr("eth_protocol"))
            # qname: copy min(32, remaining payload) raw label bytes into
            # the record (dns.h no_dns_copy_name analog; decode_qname stops
            # at the terminating NUL, so trailing qtype bytes are inert).
            # bpf_skb_load_bytes reads frag-resident payload too.
            a.ldx(BPF_W, R5, R10, TLSBUF + 8)   # qname packet offset
            a.ldx(BPF_W, R4, R6, SKB_LEN)
            a.jmp_reg(0xBD, R4, R5, "dnsname_done")  # no bytes past header
            a.alu_reg(0x1F, R4, R5)             # r4 = available bytes
            # slow-path queries carry a DYNAMIC qname offset (scalar, not
            # const), so the verifier cannot derive r4 >= 1 from the branch
            # above — pin it explicitly (skb_load_bytes rejects size 0)
            a.jmp_imm(0xB5, R4, 0, "dnsname_done")
            name_max = binfmt.DNS_REC_DTYPE["name"].itemsize
            a.jmp_imm(0xB5, R4, name_max, "dnsname_len_ok")
            a.mov_imm(R4, name_max)
            a.label("dnsname_len_ok")
            a.mov_reg(R1, R6)
            a.mov_reg(R2, R5)
            a.mov_reg(R3, R10)
            a.alu_imm(0x07, R3, DNSREC + _dr("name"))
            a.call(HELPER_SKB_LOAD_BYTES)       # failure leaves zeros
            a.label("dnsname_done")
            a.ld_map_fd(R1, self.flows_dns_fd)
            a.mov_reg(R2, R10)
            a.alu_imm(0x07, R2, KEY)
            a.mov_reg(R3, R10)
            a.alu_imm(0x07, R3, DNSREC)
            a.mov_imm(R4, 0)                    # BPF_ANY
            a.call(HELPER_MAP_UPDATE)
            a.jmp_imm(0x15, R0, 0, "extra_rec")
            self.count(CTR_FAIL_UPDATE_DNS)
            a.jmp("extra_rec")

        # --- RTT feature record (flows_extra; additional_metrics_t twin) ---
        a.label("extra_rec")
        if self.flows_extra_fd is not None:
            a.ldx(BPF_B, R3, R10, KEY + KY_PROTO)
            a.jmp_imm(0x55, R3, 6, "quic_rec")
            a.ldx(BPF_DW, R3, R10, LAT)         # measured handshake rtt
            a.jmp_imm(0x15, R3, 0, "quic_rec")
            a.ld_map_fd(R1, self.flows_extra_fd)
            a.mov_reg(R2, R10)
            a.alu_imm(0x07, R2, KEY)
            a.call(HELPER_MAP_LOOKUP)
            a.jmp_imm(0x15, R0, 0, "xrec_miss")
            a.ldx(BPF_DW, R4, R10, NOW)
            a.stx(BPF_DW, R0, R4, _xr("last_seen_ns"))
            a.ldx(BPF_DW, R3, R0, _xr("rtt_ns"))
            a.ldx(BPF_DW, R4, R10, LAT)
            a.jmp_reg(0x3D, R3, R4, "quic_rec")  # existing >= new: keep
            a.stx(BPF_DW, R0, R4, _xr("rtt_ns"))
            a.jmp("quic_rec")
            a.label("xrec_miss")
            # build in the DNSREC scratch (32B needed, 64B slot, same align)
            for off in range(DNSREC, DNSREC + 32, 8):
                a.st_imm(BPF_DW, R10, off, 0)
            a.ldx(BPF_DW, R4, R10, NOW)
            a.stx(BPF_DW, R10, R4, DNSREC + _xr("first_seen_ns"))
            a.stx(BPF_DW, R10, R4, DNSREC + _xr("last_seen_ns"))
            a.ldx(BPF_DW, R4, R10, LAT)
            a.stx(BPF_DW, R10, R4, DNSREC + _xr("rtt_ns"))
            a.ldx(BPF_H, R4, R10, VAL + ST_ETH)
            a.stx(BPF_H, R10, R4, DNSREC + _xr("eth_protocol"))
            a.ld_map_fd(R1, self.flows_extra_fd)
            a.mov_reg(R2, R10)
            a.alu_imm(0x07, R2, KEY)
            a.mov_reg(R3, R10)
            a.alu_imm(0x07, R3, DNSREC)
            a.mov_imm(R4, 0)                    # BPF_ANY
            a.call(HELPER_MAP_UPDATE)
            a.jmp_imm(0x15, R0, 0, "quic_rec")
            self.count(CTR_FAIL_UPDATE_FLOW)

        # --- QUIC feature record (flows_quic; quic.h twin) -----------------
        a.label("quic_rec")
        if self.flows_quic_fd is not None and self.quic_mode:
            a.ldx(BPF_B, R3, R10, QMETA)        # quic invariants seen?
            a.jmp_imm(0x15, R3, 0, "out")
            a.ld_map_fd(R1, self.flows_quic_fd)
            a.mov_reg(R2, R10)
            a.alu_imm(0x07, R2, KEY)
            a.call(HELPER_MAP_LOOKUP)
            a.jmp_imm(0x15, R0, 0, "qrec_miss")
            # NOTE: like quic.h:42-50, the hit path does not backfill
            # first_seen/eth into a fresh per-CPU slot (another CPU created
            # the entry); consumers read only version/header flags
            a.ldx(BPF_DW, R4, R10, NOW)
            a.stx(BPF_DW, R0, R4, _qr("last_seen_ns"))
            a.ldx(BPF_W, R3, R0, _qr("version"))
            a.ldx(BPF_W, R4, R10, QMETA + 4)
            a.jmp_reg(0x3D, R3, R4, "qrec_hdr")  # existing >= new: keep
            a.stx(BPF_W, R0, R4, _qr("version"))
            a.label("qrec_hdr")
            a.ldx(BPF_B, R3, R10, QMETA + 1)
            a.jmp_imm(0x15, R3, 0, "qrec_short")
            a.mov_imm(R4, 1)
            a.stx(BPF_B, R0, R4, _qr("seen_long_hdr"))
            a.jmp("out")
            a.label("qrec_short")
            a.mov_imm(R4, 1)
            a.stx(BPF_B, R0, R4, _qr("seen_short_hdr"))
            a.jmp("out")
            a.label("qrec_miss")
            for off in range(DNSREC, DNSREC + 24, 8):
                a.st_imm(BPF_DW, R10, off, 0)
            a.ldx(BPF_DW, R4, R10, NOW)
            a.stx(BPF_DW, R10, R4, DNSREC + _qr("first_seen_ns"))
            a.stx(BPF_DW, R10, R4, DNSREC + _qr("last_seen_ns"))
            a.ldx(BPF_W, R4, R10, QMETA + 4)
            a.stx(BPF_W, R10, R4, DNSREC + _qr("version"))
            a.ldx(BPF_H, R4, R10, VAL + ST_ETH)
            a.stx(BPF_H, R10, R4, DNSREC + _qr("eth_protocol"))
            a.ldx(BPF_B, R3, R10, QMETA + 1)
            a.jmp_imm(0x15, R3, 0, "qrec_fr_short")
            a.mov_imm(R4, 1)
            a.stx(BPF_B, R10, R4, DNSREC + _qr("seen_long_hdr"))
            a.jmp("qrec_write")
            a.label("qrec_fr_short")
            a.mov_imm(R4, 1)
            a.stx(BPF_B, R10, R4, DNSREC + _qr("seen_short_hdr"))
            a.label("qrec_write")
            a.ld_map_fd(R1, self.flows_quic_fd)
            a.mov_reg(R2, R10)
            a.alu_imm(0x07, R2, KEY)
            a.mov_reg(R3, R10)
            a.alu_imm(0x07, R3, DNSREC)
            a.mov_imm(R4, 0)                    # BPF_ANY
            a.call(HELPER_MAP_UPDATE)


def build_flow_program(map_fd: int, direction: int = 0, sampling: int = 0,
                       ringbuf_fd: int | None = None,
                       counters_fd: int | None = None,
                       dns_inflight_fd: int | None = None,
                       flows_dns_fd: int | None = None,
                       dns_port: int = 53,
                       rtt_inflight_fd: int | None = None,
                       flows_extra_fd: int | None = None,
                       filter_rules_fd: int | None = None,
                       filter_peers_fd: int | None = None,
                       flows_quic_fd: int | None = None,
                       quic_mode: int = 0,
                       enable_tls: bool = False,
                       sampling_gate_fd: int | None = None,
                       has_filter_sampling: bool = False) -> bytes:
    """Assemble one per-direction flow program. Optional map fds gate the
    corresponding feature blocks, mirroring the C datapath's loader-rewritten
    `cfg_enable_*` constants (a feature whose map isn't wired costs zero
    instructions)."""
    return _Flow(map_fd, direction, sampling, ringbuf_fd, counters_fd,
                 dns_inflight_fd, flows_dns_fd, dns_port,
                 rtt_inflight_fd, flows_extra_fd,
                 filter_rules_fd, filter_peers_fd,
                 flows_quic_fd, quic_mode, enable_tls,
                 sampling_gate_fd, has_filter_sampling).build()
