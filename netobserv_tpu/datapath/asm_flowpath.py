"""Hand-assembled minimal flow datapath (no compiler required).

Builds a TC classifier that aggregates IPv4 TCP/UDP packets into the
`aggregated_flows` hash (same no_flow_key/no_flow_stats layout as the full C
datapath, so the entire userspace pipeline runs unchanged on top):

    parse eth/IPv4 (no options) -> v4-mapped flow key on the stack
    -> map lookup: hit  -> atomic bytes/packets add + last_seen update
                   miss -> build a fresh no_flow_stats and insert

Covered: IPv4 TCP/UDP/ICMP keys (ports or icmp type/code), byte/packet
accounting, TCP-flag accumulation (racy-benign OR), per-direction program
instances, and optional 1/N sampling baked in at build time (the loader
rebuilds per config — the moral equivalent of the C datapath's
loader-rewritten `volatile const`).

Deliberate limits vs flowpath.c (the clang-built full datapath): IPv4 only,
no IP options, no filters/trackers, racy (non-spin-locked) last_seen/flags.
It exists so real kernel flow capture works in build environments without
clang — validated by the live verifier and by end-to-end veth traffic tests.
"""

from __future__ import annotations

from netobserv_tpu.datapath.asm import (
    Asm, BPF_B, BPF_DW, BPF_H, BPF_W, HELPER_KTIME_GET_NS, HELPER_MAP_LOOKUP,
    HELPER_MAP_UPDATE, R0, R1, R2, R3, R4, R5, R6, R7, R8, R9, R10,
)

# __sk_buff field offsets
SKB_LEN = 0
SKB_IFINDEX = 40
SKB_DATA = 76
SKB_DATA_END = 80

from netobserv_tpu.model import binfmt

# stack layout (relative to r10)
KEY = -binfmt.FLOW_KEY_DTYPE.itemsize              # no_flow_key, 40 bytes
VAL = KEY - binfmt.FLOW_STATS_DTYPE.itemsize       # no_flow_stats, 104 bytes


def _st(field: str) -> int:
    """no_flow_stats field offset, derived from the layout-pinned dtype so
    the assembled stores can never drift from records.h/binfmt."""
    return binfmt.FLOW_STATS_DTYPE.fields[field][1]


def _ky(field: str) -> int:
    return binfmt.FLOW_KEY_DTYPE.fields[field][1]


ST_FIRST = _st("first_seen_ns")
ST_LAST = _st("last_seen_ns")
ST_BYTES = _st("bytes")
ST_PACKETS = _st("packets")
ST_ETH = _st("eth_protocol")
ST_IFINDEX = _st("if_index_first")
ST_DIR = _st("direction_first")
ST_NOBS = _st("n_observed_intf")
ST_OBSDIR = _st("observed_direction")
ST_OBSIF = _st("observed_intf")
ST_FLAGS = _st("tcp_flags")
KY_SRC_IP = _ky("src_ip")
KY_DST_IP = _ky("dst_ip")
KY_SPORT = _ky("src_port")
KY_DPORT = _ky("dst_port")
KY_PROTO = _ky("proto")
KY_ICMP_TYPE = _ky("icmp_type")
KY_ICMP_CODE = _ky("icmp_code")

HELPER_PRANDOM_U32 = 7
FLAGS_SPILL = VAL - 8  # stack slot holding this packet's classified tcp flags


def build_flow_program(map_fd: int, direction: int = 0,
                       sampling: int = 0) -> bytes:
    a = Asm()
    a.mov_reg(R6, R1)                       # r6 = ctx

    if sampling > 1:
        # 1/N gate, baked in at build time (loader-rewritten-const analog)
        a.call(HELPER_PRANDOM_U32)
        a.alu_imm(0x97, R0, sampling)       # r0 %= N (ALU64 MOD K)
        a.jmp_imm(0x55, R0, 0, "out")       # not the sampled 1/N: out

    a.ldx(BPF_W, R7, R6, SKB_DATA)          # r7 = data
    a.ldx(BPF_W, R8, R6, SKB_DATA_END)      # r8 = data_end

    # need eth(14) + ip(20) + 4 bytes of L4 (ports / icmp type+code)
    a.mov_reg(R2, R7)
    a.alu_imm(0x07, R2, 38)                 # r2 = data + 38
    a.jmp_reg(0x2D, R2, R8, "out")          # if r2 > data_end: out

    a.ldx(BPF_H, R3, R7, 12)                # ethertype (LE view of BE bytes)
    a.jmp_imm(0x55, R3, 0x0008, "out")      # != IPv4: out
    a.ldx(BPF_B, R3, R7, 14)                # version/ihl
    a.alu_imm(0x57, R3, 0x0F)               # & 0x0f
    a.jmp_imm(0x55, R3, 5, "out")           # IP options: out (minimal path)
    a.ldx(BPF_B, R9, R7, 23)                # protocol

    # zero the 40-byte key + the flags spill slot
    for off in range(KEY, 0, 8):
        a.st_imm(BPF_DW, R10, off, 0)
    a.st_imm(BPF_DW, R10, FLAGS_SPILL, 0)
    # v4-mapped addresses: ::ffff prefix + 4 address bytes
    a.st_imm(BPF_H, R10, KEY + KY_SRC_IP + 10, 0xFFFF)
    a.ldx(BPF_W, R3, R7, 26)                    # saddr (BE bytes as-is)
    a.stx(BPF_W, R10, R3, KEY + KY_SRC_IP + 12)
    a.st_imm(BPF_H, R10, KEY + KY_DST_IP + 10, 0xFFFF)
    a.ldx(BPF_W, R3, R7, 30)                    # daddr
    a.stx(BPF_W, R10, R3, KEY + KY_DST_IP + 12)
    a.stx(BPF_B, R10, R9, KEY + KY_PROTO)

    a.jmp_imm(0x15, R9, 6, "tcp")
    a.jmp_imm(0x15, R9, 17, "udp")
    a.jmp_imm(0x15, R9, 1, "icmp")
    a.jmp("out")                                # other protocols: untracked

    a.label("tcp")
    a.mov_reg(R2, R7)
    a.alu_imm(0x07, R2, 48)                     # TCP flags byte needs +48
    a.jmp_reg(0x2D, R2, R8, "ports")            # truncated: skip flags
    a.ldx(BPF_B, R3, R7, 47)                    # TCP flags byte (l4 + 13)
    a.stx(BPF_DW, R10, R3, FLAGS_SPILL)
    a.jmp("ports")

    a.label("icmp")
    a.ldx(BPF_B, R3, R7, 34)                    # icmp type
    a.stx(BPF_B, R10, R3, KEY + KY_ICMP_TYPE)
    a.ldx(BPF_B, R3, R7, 35)                    # icmp code
    a.stx(BPF_B, R10, R3, KEY + KY_ICMP_CODE)
    a.jmp("key_done")

    a.label("udp")
    a.label("ports")
    a.ldx(BPF_H, R3, R7, 34)                    # bswap16 to host order
    a.endian_be(R3, 16)
    a.stx(BPF_H, R10, R3, KEY + KY_SPORT)
    a.ldx(BPF_H, R3, R7, 36)
    a.endian_be(R3, 16)
    a.stx(BPF_H, R10, R3, KEY + KY_DPORT)
    a.label("key_done")

    a.call(HELPER_KTIME_GET_NS)
    a.mov_reg(R9, R0)                           # r9 = now_ns

    a.ld_map_fd(R1, map_fd)
    a.mov_reg(R2, R10)
    a.alu_imm(0x07, R2, KEY)
    a.call(HELPER_MAP_LOOKUP)
    a.jmp_imm(0x15, R0, 0, "miss")

    # hit: multi-interface dedup (reference bpf/flows.c:100-110) — only the
    # interface that FIRST saw the flow counts bytes/packets; any other
    # interface updates last_seen/flags and the observed-interface list
    a.ldx(BPF_W, R4, R6, SKB_IFINDEX)
    a.ldx(BPF_W, R3, R0, ST_IFINDEX)
    a.jmp_reg(0x5D, R3, R4, "hit_other")        # not the first-seen intf
    # counting path: bytes += skb->len (atomic), packets += 1 (atomic),
    # last_seen = now, flags |= packet flags (read-modify-write; benign race:
    # bits only accumulate, a lost update costs one OR)
    a.ldx(BPF_W, R3, R6, SKB_LEN)
    a.atomic_add(BPF_DW, R0, R3, ST_BYTES)
    a.mov_imm(R4, 1)
    a.atomic_add(BPF_W, R0, R4, ST_PACKETS)
    a.stx(BPF_DW, R0, R9, ST_LAST)              # benign race (lock-free)
    a.ldx(BPF_H, R3, R0, ST_FLAGS)
    a.ldx(BPF_DW, R4, R10, FLAGS_SPILL)
    a.alu_reg(0x4F, R3, R4)                     # r3 |= packet flags
    a.stx(BPF_H, R0, R3, ST_FLAGS)
    a.jmp("out")

    a.label("hit_other")
    # secondary interface: span/flags only — never re-count traffic
    a.stx(BPF_DW, R0, R9, ST_LAST)
    a.ldx(BPF_H, R3, R0, ST_FLAGS)
    a.ldx(BPF_DW, R5, R10, FLAGS_SPILL)
    a.alu_reg(0x4F, R3, R5)
    a.stx(BPF_H, R0, R3, ST_FLAGS)
    # (ifindex, direction) dedup scan over the observed slots (r4 = ifindex;
    # direction is a build-time constant, so it compares as an immediate)
    n_obs = binfmt.FLOW_STATS_DTYPE["observed_intf"].shape[0]
    for i in range(n_obs):
        a.ldx(BPF_W, R3, R0, ST_OBSIF + 4 * i)
        a.jmp_reg(0x5D, R3, R4, f"obs_next_{i}")  # different intf: keep going
        a.ldx(BPF_B, R3, R0, ST_OBSDIR + i)
        a.jmp_imm(0x15, R3, direction, "out")     # same (intf, dir): recorded
        a.label(f"obs_next_{i}")
    # append (lock-free; a racing append can lose one slot — benign)
    a.ldx(BPF_B, R3, R0, ST_NOBS)
    a.jmp_imm(0x35, R3, n_obs, "out")           # array full: drop observation
    a.mov_reg(R5, R3)
    a.alu_imm(0x67, R5, 2)                      # n << 2
    a.mov_reg(R7, R0)
    a.alu_reg(0x0F, R7, R5)
    a.stx(BPF_W, R7, R4, ST_OBSIF)              # observed_intf[n] = ifindex
    a.mov_reg(R7, R0)
    a.alu_reg(0x0F, R7, R3)
    a.mov_imm(R5, direction)
    a.stx(BPF_B, R7, R5, ST_OBSDIR)             # observed_direction[n] = dir
    a.alu_imm(0x07, R3, 1)
    a.stx(BPF_B, R0, R3, ST_NOBS)
    a.jmp("out")

    a.label("miss")
    for off in range(VAL, KEY, 8):              # zero the 104-byte value
        a.st_imm(BPF_DW, R10, off, 0)
    a.stx(BPF_DW, R10, R9, VAL + ST_FIRST)
    a.stx(BPF_DW, R10, R9, VAL + ST_LAST)
    a.ldx(BPF_W, R3, R6, SKB_LEN)
    a.stx(BPF_DW, R10, R3, VAL + ST_BYTES)
    a.st_imm(BPF_W, R10, VAL + ST_PACKETS, 1)
    a.st_imm(BPF_H, R10, VAL + ST_ETH, 0x0800)
    a.ldx(BPF_DW, R3, R10, FLAGS_SPILL)
    a.stx(BPF_H, R10, R3, VAL + ST_FLAGS)
    a.ldx(BPF_W, R4, R6, SKB_IFINDEX)
    a.stx(BPF_W, R10, R4, VAL + ST_IFINDEX)
    a.st_imm(BPF_B, R10, VAL + ST_DIR, direction)
    a.st_imm(BPF_B, R10, VAL + ST_NOBS, 1)
    a.st_imm(BPF_B, R10, VAL + ST_OBSDIR, direction)
    a.stx(BPF_W, R10, R4, VAL + ST_OBSIF)       # observed_intf[0]
    a.ld_map_fd(R1, map_fd)
    a.mov_reg(R2, R10)
    a.alu_imm(0x07, R2, KEY)
    a.mov_reg(R3, R10)
    a.alu_imm(0x07, R3, VAL)
    a.mov_imm(R4, 0)                            # BPF_ANY (lossy race ok)
    a.call(HELPER_MAP_UPDATE)

    a.label("out")
    a.mov_imm(R0, 0)                            # TC_ACT_OK
    a.exit()
    return a.assemble()
