"""Uprobe attachment via perf_event_open(2) — no libbpf required.

Powers the self-managed OpenSSL plaintext tracer: resolve the target
function's file offset from the library's ELF symbol tables, open a uprobe
perf event on (path, offset), then bind a BPF_PROG_TYPE_KPROBE program to it
(PERF_EVENT_IOC_SET_BPF + ENABLE). Reference analog: the cilium/ebpf
link.Uprobe path used by pkg/tracer for SSL_write (tracer.go OpenSSL attach);
the mechanism here is the same one libbpf uses internally.
"""

from __future__ import annotations

import ctypes
import fcntl
import os
import struct

_libc = ctypes.CDLL(None, use_errno=True)
# syscall number and the pt_regs argument offsets (asm_ssl.py) are per-arch;
# only x86_64 is wired — other architectures must fail loudly, not call an
# unrelated syscall with a pointer argument
_PERF_EVENT_OPEN_BY_ARCH = {"x86_64": 298}


def _perf_event_open_nr() -> int:
    import platform

    machine = platform.machine()
    try:
        return _PERF_EVENT_OPEN_BY_ARCH[machine]
    except KeyError:
        raise RuntimeError(
            f"uprobe attach not wired for architecture {machine!r} "
            "(x86_64 only: syscall number + pt_regs offsets)") from None

PERF_FLAG_FD_CLOEXEC = 1 << 3
PERF_EVENT_IOC_ENABLE = 0x2400
PERF_EVENT_IOC_SET_BPF = 0x40042408

SHT_SYMTAB, SHT_DYNSYM = 2, 11
PT_LOAD = 1


def elf_func_offset(path: str, symbol: str) -> int:
    """File offset of `symbol` in the ELF at `path` (st_value translated
    through the containing PT_LOAD segment — libbpf's elf_find_func_offset)."""
    with open(path, "rb") as fh:
        data = fh.read()
    if data[:4] != b"\x7fELF" or data[4] != 2:
        raise ValueError(f"{path}: not a 64-bit ELF")
    (e_phoff,) = struct.unpack_from("=Q", data, 0x20)
    (e_shoff,) = struct.unpack_from("=Q", data, 0x28)
    e_phentsize, e_phnum = struct.unpack_from("=HH", data, 0x36)
    e_shentsize, e_shnum = struct.unpack_from("=HH", data, 0x3A)

    sections = []
    for i in range(e_shnum):
        off = e_shoff + i * e_shentsize
        (_name, stype, _flags, _addr, offset, size, link, _info, _align,
         entsize) = struct.unpack_from("=IIQQQQIIQQ", data, off)
        sections.append((stype, offset, size, link, entsize))

    vaddr = None
    for stype, offset, size, link, entsize in sections:
        if stype not in (SHT_SYMTAB, SHT_DYNSYM) or not entsize:
            continue
        _t, str_off, str_size, _l, _e = sections[link]
        for j in range(size // entsize):
            st = offset + j * entsize
            st_name, st_info = struct.unpack_from("=IB", data, st)
            (st_value,) = struct.unpack_from("=Q", data, st + 8)
            if not st_value or (st_info & 0xF) != 2:  # STT_FUNC
                continue
            end = data.index(b"\x00", str_off + st_name)
            if data[str_off + st_name:end].decode() == symbol:
                vaddr = st_value
                break
        if vaddr is not None:
            break
    if vaddr is None:
        raise LookupError(f"{symbol} not found in {path}")

    for i in range(e_phnum):
        off = e_phoff + i * e_phentsize
        p_type, _pf = struct.unpack_from("=II", data, off)
        p_offset, p_vaddr, _paddr, p_filesz = struct.unpack_from(
            "=QQQQ", data, off + 8)
        if p_type == PT_LOAD and p_vaddr <= vaddr < p_vaddr + p_filesz:
            return vaddr - p_vaddr + p_offset
    raise LookupError(f"{symbol}: vaddr {vaddr:#x} outside any PT_LOAD")


def uprobe_pmu_type() -> int:
    with open("/sys/bus/event_source/devices/uprobe/type") as fh:
        return int(fh.read())


class _PerfAttachment:
    """A BPF program bound to a perf event; the event fd keeps the probe
    alive (closing detaches). Subclasses fill the perf_event_attr:
    struct perf_event_attr (zero-padded to 128B, size=VER5=112):
    type@0, size@4, config@8, sample_period@16, config1@56, config2@64."""

    def _open_and_bind(self, attr: bytearray, prog_fd: int,
                       desc: str) -> None:
        buf = (ctypes.c_char * len(attr)).from_buffer(attr)
        fd = _libc.syscall(_perf_event_open_nr(), buf, -1, 0, -1,
                           PERF_FLAG_FD_CLOEXEC)
        if fd < 0:
            err = ctypes.get_errno()
            raise OSError(err,
                          f"perf_event_open({desc}): {os.strerror(err)}")
        self.fd = fd
        try:
            fcntl.ioctl(fd, PERF_EVENT_IOC_SET_BPF, prog_fd)
            fcntl.ioctl(fd, PERF_EVENT_IOC_ENABLE, 0)
        except OSError:
            os.close(fd)
            raise

    def close(self) -> None:
        try:
            os.close(self.fd)
        except OSError:
            pass


class UprobeAttachment(_PerfAttachment):
    """One live uprobe on (binary, file offset). The path buffer must
    outlive perf_event_open, so it is held."""

    def __init__(self, prog_fd: int, binary_path: str, file_offset: int):
        self._path_buf = ctypes.create_string_buffer(
            os.fsencode(binary_path) + b"\x00")
        attr = bytearray(128)
        struct.pack_into("=II", attr, 0, uprobe_pmu_type(), 112)
        struct.pack_into("=Q", attr, 56, ctypes.addressof(self._path_buf))
        struct.pack_into("=Q", attr, 64, file_offset)
        self._open_and_bind(attr, prog_fd,
                            f"uprobe {binary_path}+{file_offset:#x}")


PERF_TYPE_TRACEPOINT = 2
_TRACEFS = "/sys/kernel/tracing"


def ensure_tracefs() -> str:
    """Mount tracefs if absent (root; the image leaves it unmounted)."""
    if not os.path.isdir(os.path.join(_TRACEFS, "events")):
        import subprocess

        subprocess.run(["mount", "-t", "tracefs", "tracefs", _TRACEFS],
                       capture_output=True)
    if not os.path.isdir(os.path.join(_TRACEFS, "events")):
        raise RuntimeError("tracefs unavailable (mount tracefs "
                           f"{_TRACEFS})")
    return _TRACEFS


def tracepoint_id(category: str, name: str) -> int:
    with open(f"{ensure_tracefs()}/events/{category}/{name}/id") as fh:
        return int(fh.read())


def tracepoint_fields(category: str, name: str) -> dict[str, int]:
    """field name -> byte offset in the tracepoint context, parsed from the
    live format file — layouts shift between kernel versions (6.18 inserted
    rx_sk into skb/kfree_skb), so offsets must never be hardcoded."""
    import re

    out: dict[str, int] = {}
    path = f"{ensure_tracefs()}/events/{category}/{name}/format"
    with open(path) as fh:
        for line in fh:
            # array dims may be symbolic on older kernels:
            # "__u8 saddr[sizeof(struct sockaddr_in6)]"
            m = re.search(
                r"field:[^;]*?(\w+)(?:\[[^\]]*\])?;\s*offset:(\d+);", line)
            if m:
                out[m.group(1)] = int(m.group(2))
    return out


class TracepointAttachment(_PerfAttachment):
    """A BPF_PROG_TYPE_TRACEPOINT program bound to a perf tracepoint event
    (PERF_TYPE_TRACEPOINT, config = event id) — the attach mechanism behind
    the reference's tracepoint sections (SEC(\"tracepoint/skb/kfree_skb\"))."""

    def __init__(self, prog_fd: int, category: str, name: str):
        attr = bytearray(128)
        struct.pack_into("=II", attr, 0, PERF_TYPE_TRACEPOINT, 112)
        struct.pack_into("=Q", attr, 8, tracepoint_id(category, name))
        struct.pack_into("=Q", attr, 16, 1)  # sample_period (required != 0)
        self._open_and_bind(attr, prog_fd, f"tracepoint {category}/{name}")


def find_libssl() -> str | None:
    """Locate the OpenSSL shared library, preferring the newest ABI version
    (a leftover libssl.so.1.1 next to libssl.so.3 must not win — processes
    load the current SONAME) and real versioned files over dev symlinks."""
    candidates = []
    for libdir in ("/usr/lib/x86_64-linux-gnu", "/usr/lib64", "/usr/lib",
                   "/lib/x86_64-linux-gnu", "/lib64"):
        try:
            for name in os.listdir(libdir):
                if name.startswith("libssl.so"):
                    suffix = name[len("libssl.so"):].lstrip(".")
                    version = tuple(
                        int(p) for p in suffix.split(".") if p.isdigit())
                    candidates.append((version, os.path.join(libdir, name)))
        except OSError:
            continue
    return max(candidates)[1] if candidates else None


def resolve_ssl_library(preferred: str = "") -> tuple[str, int]:
    """(path, SSL_write file offset): the configured path when it carries
    the symbol (OPENSSL_PATH may point at a vendored library), else the
    system libssl."""
    if preferred:
        try:
            return preferred, elf_func_offset(preferred, "SSL_write")
        except (OSError, ValueError, LookupError) as exc:
            import logging

            logging.getLogger("netobserv_tpu.datapath.uprobe").warning(
                "OPENSSL_PATH %s unusable for the SSL_write uprobe (%s); "
                "falling back to the system libssl", preferred, exc)
    path = find_libssl()
    if path is None:
        raise RuntimeError("no libssl.so found (set OPENSSL_PATH to the "
                           "library your workload loads)")
    return path, elf_func_offset(path, "SSL_write")
