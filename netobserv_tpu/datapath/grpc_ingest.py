"""gRPC-ingest datapath: a TPU worker's record source.

Deployment story (docs/architecture.md): per-node agents export over gRPC
(pbflow wire format); a central TPU worker runs with `DATAPATH=grpc:<port>`
and `EXPORT=tpu-sketch`, turning the incoming stream into cluster-wide sketch
analytics. This replaces the reference's collector tier (flowlogs-pipeline)
with the sketch plane while speaking the identical wire format.

Implements the FlowFetcher seam: each lookup_and_delete() drains everything
received since the previous eviction.
"""

from __future__ import annotations

import logging
import queue
import time
from typing import Optional

import numpy as np

from netobserv_tpu.datapath.fetcher import EvictedFlows
from netobserv_tpu.model import binfmt
from netobserv_tpu.model.flow import GlobalCounter

log = logging.getLogger("netobserv_tpu.datapath.grpc_ingest")


def pb_records_to_events(entries) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """pbflow.Record list -> (FLOW_EVENT, EXTRA_REC, DNS_REC) arrays.

    Wall-clock pb timestamps are rebased against the local monotonic clock so
    the standard pipeline enrichment yields the original wall times.
    """
    n = len(entries)
    events = np.zeros(n, dtype=binfmt.FLOW_EVENT_DTYPE)
    extra = np.zeros(n, dtype=binfmt.EXTRA_REC_DTYPE)
    dns = np.zeros(n, dtype=binfmt.DNS_REC_DTYPE)
    mono_now = time.clock_gettime_ns(time.CLOCK_MONOTONIC)
    wall_now = time.time_ns()
    offset = wall_now - mono_now  # wall -> mono rebase
    from netobserv_tpu.exporter.pb_convert import _get_ip
    for i, pb in enumerate(entries):
        k = events[i]["key"]
        k["src_ip"] = np.frombuffer(_get_ip(pb.network.src_addr), np.uint8)
        k["dst_ip"] = np.frombuffer(_get_ip(pb.network.dst_addr), np.uint8)
        k["src_port"] = pb.transport.src_port
        k["dst_port"] = pb.transport.dst_port
        k["proto"] = pb.transport.protocol
        k["icmp_type"] = pb.icmp_type
        k["icmp_code"] = pb.icmp_code
        s = events[i]["stats"]
        s["bytes"] = pb.bytes
        s["packets"] = pb.packets
        s["eth_protocol"] = pb.eth_protocol
        s["tcp_flags"] = pb.flags
        s["direction_first"] = int(pb.direction)
        s["dscp"] = pb.network.dscp
        s["sampling"] = pb.sampling
        s["first_seen_ns"] = max(pb.time_flow_start.ToNanoseconds() - offset, 0)
        s["last_seen_ns"] = max(pb.time_flow_end.ToNanoseconds() - offset, 0)
        rtt = pb.time_flow_rtt.ToNanoseconds()
        if rtt:
            extra[i]["rtt_ns"] = rtt
            extra[i]["first_seen_ns"] = s["first_seen_ns"]
            extra[i]["last_seen_ns"] = s["last_seen_ns"]
        lat = pb.dns_latency.ToNanoseconds()
        if lat or pb.dns_id or pb.dns_errno:
            dns[i]["latency_ns"] = lat
            dns[i]["dns_id"] = pb.dns_id
            dns[i]["dns_flags"] = pb.dns_flags
            dns[i]["errno"] = pb.dns_errno
            dns[i]["name"] = pb.dns_name.encode()[:31]
            dns[i]["first_seen_ns"] = s["first_seen_ns"]
            dns[i]["last_seen_ns"] = s["last_seen_ns"]
    return events, extra, dns


class GrpcIngestFetcher:
    """FlowFetcher over an embedded pbflow.Collector server."""

    def __init__(self, port: int):
        from netobserv_tpu.grpc.flow import start_flow_collector
        self._server, self.port, self._inbox = start_flow_collector(port)
        log.info("grpc ingest listening on :%d", self.port)

    def lookup_and_delete(self) -> EvictedFlows:
        batches = []
        while True:
            try:
                batches.append(self._inbox.get_nowait())
            except queue.Empty:
                break
        if not batches:
            return EvictedFlows(np.zeros(0, dtype=binfmt.FLOW_EVENT_DTYPE))
        entries = [e for msg in batches for e in msg.entries]
        events, extra, dns = pb_records_to_events(entries)
        return EvictedFlows(
            events,
            extra=extra if extra["rtt_ns"].any() else None,
            dns=dns if (dns["latency_ns"].any() or dns["dns_id"].any()) else None)

    def read_ringbuf(self, timeout_s: float) -> Optional[bytes]:
        time.sleep(timeout_s)
        return None

    def read_global_counters(self) -> dict[GlobalCounter, int]:
        return {}

    def purge_stale(self, older_than_s: float) -> int:
        return 0

    def attach(self, if_index: int, if_name: str, direction: str,
               netns: str = "") -> None:
        pass

    def detach(self, if_index: int, if_name: str,
               netns: str = "") -> None:
        pass

    def close(self) -> None:
        self._server.stop(grace=0.5)
