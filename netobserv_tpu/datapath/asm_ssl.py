"""Hand-assembled OpenSSL SSL_write uprobe program (no compiler required).

The assembler twin of `flowpath_probes.c:380-399` (SEC("uprobe/SSL_write")):
at SSL_write(ssl, buf, num) entry, reserve a `no_ssl_event` in the
`ssl_events` ring buffer, stamp time + pid_tgid, clamp the caller's length
exactly like the C probe (negative -> 0, cap at NO_MAX_SSL_DATA), copy the
plaintext with bpf_probe_read_user, and submit. A failed user-memory read
discards the reservation instead of shipping uninitialized ring memory.

x86_64 calling convention: arg2 (buf) in rsi, arg3 (num) in rdx; pt_regs
field offsets are the stable kernel ABI for BPF_PROG_TYPE_KPROBE.
"""

from __future__ import annotations

from netobserv_tpu.datapath.asm import (
    Asm, BPF_DW, BPF_W, HELPER_KTIME_GET_NS, R0, R1, R2, R3, R6, R7, R8, R9,
)
from netobserv_tpu.model import binfmt

HELPER_GET_PID_TGID = 14
HELPER_PROBE_READ_USER = 112
HELPER_RINGBUF_RESERVE = 131
HELPER_RINGBUF_SUBMIT = 132
HELPER_RINGBUF_DISCARD = 133

# x86_64 struct pt_regs offsets (kernel ABI)
PT_REGS_RDX = 96   # arg3
PT_REGS_RSI = 104  # arg2

EV_SIZE = binfmt.SSL_EVENT_DTYPE.itemsize          # 24 + 16K
MAX_DATA = binfmt.MAX_SSL_DATA
EV_TS = binfmt.SSL_EVENT_DTYPE.fields["timestamp_ns"][1]
EV_PID = binfmt.SSL_EVENT_DTYPE.fields["pid_tgid"][1]
EV_LEN = binfmt.SSL_EVENT_DTYPE.fields["data_len"][1]
EV_TYPE = binfmt.SSL_EVENT_DTYPE.fields["ssl_type"][1]
EV_DATA = binfmt.SSL_EVENT_DTYPE.fields["data"][1]

SSL_TYPE_WRITE = 1


def build_ssl_write_program(ringbuf_fd: int) -> bytes:
    a = Asm()
    a.mov_reg(R6, R1)                       # r6 = pt_regs
    a.ldx(BPF_DW, R7, R6, PT_REGS_RSI)      # r7 = buf
    a.ldx(BPF_DW, R8, R6, PT_REGS_RDX)      # r8 = num (int arg)
    # int semantics like the C probe: negative -> 0, cap at MAX_DATA
    a.alu_imm(0x67, R8, 32)                 # zero-extend the low 32 bits
    a.alu_imm(0x77, R8, 32)
    a.jmp_imm(0xB5, R8, MAX_DATA, "len_ok")     # <= cap: as-is
    a.jmp_imm(0xB5, R8, 0x7FFFFFFF, "len_cap")  # positive int > cap
    a.mov_imm(R8, 0)                        # negative int -> 0
    a.jmp("len_ok")
    a.label("len_cap")
    a.mov_imm(R8, MAX_DATA)
    a.label("len_ok")

    a.ld_map_fd(R1, ringbuf_fd)
    a.mov_imm(R2, EV_SIZE)
    a.mov_imm(R3, 0)
    a.call(HELPER_RINGBUF_RESERVE)
    a.jmp_imm(0x55, R0, 0, "have")
    a.jmp("out")                            # ring full: drop the event
    a.label("have")
    a.mov_reg(R9, R0)                       # r9 = event
    a.call(HELPER_KTIME_GET_NS)
    a.stx(BPF_DW, R9, R0, EV_TS)
    a.call(HELPER_GET_PID_TGID)
    a.stx(BPF_DW, R9, R0, EV_PID)
    a.stx(BPF_W, R9, R8, EV_LEN)
    # one word covers ssl_type + the 3 pad bytes (zeroes them: ring memory
    # is not zero-initialized and pads must not leak)
    a.st_imm(BPF_W, R9, EV_TYPE, SSL_TYPE_WRITE)
    a.jmp_imm(0x15, R8, 0, "submit")        # empty write: header-only event
    a.mov_reg(R1, R9)
    a.alu_imm(0x07, R1, EV_DATA)
    a.mov_reg(R2, R8)
    a.mov_reg(R3, R7)
    a.call(HELPER_PROBE_READ_USER)
    a.jmp_imm(0x15, R0, 0, "submit")
    a.mov_reg(R1, R9)                       # unreadable user buffer: discard
    a.mov_imm(R2, 0)
    a.call(HELPER_RINGBUF_DISCARD)
    a.jmp("out")
    a.label("submit")
    a.mov_reg(R1, R9)
    a.mov_imm(R2, 0)
    a.call(HELPER_RINGBUF_SUBMIT)
    a.label("out")
    a.mov_imm(R0, 0)
    a.exit()
    return a.assemble()
