"""Kernel capability detection.

Reference analog: `pkg/kernel/kernel_utils.go` — uname-based version compare
driving the hook-pruning ladder (old kernels lose fentry/TCX/etc.) and
realtime-kernel detection.
"""

from __future__ import annotations

import functools
import os
import re

_VERSION_RE = re.compile(r"^(\d+)\.(\d+)(?:\.(\d+))?")


def version_code(release: str) -> int:
    """LINUX_VERSION_CODE-style comparable int from a release string."""
    m = _VERSION_RE.match(release)
    if not m:
        return 0
    major, minor, patch = int(m.group(1)), int(m.group(2)), int(m.group(3) or 0)
    return (major << 16) | (minor << 8) | min(patch, 255)


@functools.lru_cache(maxsize=1)
def current_release() -> str:
    return os.uname().release


def is_kernel_older_than(version: str, release: str | None = None) -> bool:
    cur = version_code(release if release is not None else current_release())
    return cur != 0 and cur < version_code(version)


def is_realtime_kernel(release: str | None = None) -> bool:
    """-rt kernels need some hooks avoided (reference: `:100-125`)."""
    rel = release if release is not None else current_release()
    if "-rt" in rel:
        return True
    try:
        with open("/sys/kernel/realtime") as fh:
            return fh.read().strip() == "1"
    except OSError:
        return False


# capability ladder used by the loader (reference: tracer.go:164-173,1219+)
def supports_tcx(release: str | None = None) -> bool:
    return not is_kernel_older_than("6.6", release)


def supports_fentry(release: str | None = None) -> bool:
    return not is_kernel_older_than("5.7", release)


def supports_lookup_and_delete_batch(release: str | None = None) -> bool:
    return not is_kernel_older_than("5.6", release)


def supports_ringbuf(release: str | None = None) -> bool:
    return not is_kernel_older_than("5.8", release)
