/*
 * tls.h — passive TLS metadata extraction, inline in the TC path.
 *
 * Behavior (reference analog: bpf/tls_tracker.h): inspect TCP payload bytes
 * that look like TLS records; remember which record types were seen (bitfield
 * into no_flow_stats.tls_types), the negotiated version (including TLS 1.3
 * via the supported_versions extension in ServerHello), the cipher suite and
 * key-share group.
 */
#ifndef NO_TLS_H
#define NO_TLS_H

#include "config.h"
#include "helpers.h"
#include "parse.h"

#define TLS_REC_CHANGE_CIPHER 20
#define TLS_REC_ALERT 21
#define TLS_REC_HANDSHAKE 22
#define TLS_REC_APPDATA 23
#define TLS_REC_HEARTBEAT 24

#define TLS_HS_CLIENT_HELLO 1
#define TLS_HS_SERVER_HELLO 2

#define TLS_EXT_SUPPORTED_VERSIONS 43
#define TLS_EXT_KEY_SHARE 51

struct no_tls_meta {
    __u16 version;
    __u16 cipher_suite;
    __u16 key_share;
    __u8 types_seen; /* bit per record type, bit0=ChangeCipherSpec */
};

NO_INLINE __u8 no_tls_type_bit(__u8 rec_type) {
    switch (rec_type) {
    case TLS_REC_CHANGE_CIPHER:
        return 0x01;
    case TLS_REC_ALERT:
        return 0x02;
    case TLS_REC_HANDSHAKE:
        return 0x04;
    case TLS_REC_APPDATA:
        return 0x08;
    case TLS_REC_HEARTBEAT:
        return 0x10;
    default:
        return 0;
    }
}

NO_INLINE __u16 no_be16_at(const __u8 *p, const void *end) {
    if (p + 2 > (const __u8 *)end)
        return 0;
    return ((__u16)p[0] << 8) | p[1];
}

/* walk ServerHello extensions for supported_versions / key_share (bounded) */
NO_INLINE void no_tls_walk_extensions(const __u8 *ext, const void *end,
                                      struct no_tls_meta *meta) {
    #pragma unroll
    for (int i = 0; i < 8; i++) { /* bounded extension walk */
        if (ext + 4 > (const __u8 *)end)
            return;
        __u16 ext_type = no_be16_at(ext, end);
        __u16 ext_len = no_be16_at(ext + 2, end);
        if (ext_type == TLS_EXT_SUPPORTED_VERSIONS && ext_len >= 2)
            meta->version = no_be16_at(ext + 4, end);
        else if (ext_type == TLS_EXT_KEY_SHARE && ext_len >= 2)
            meta->key_share = no_be16_at(ext + 4, end);
        if (ext_len > 256)
            return; /* suspicious; bail */
        ext += 4 + ext_len;
    }
}

NO_INLINE void no_track_tls(const struct no_pkt *pkt,
                            struct no_tls_meta *meta) {
    if (!cfg_enable_tls_tracking || pkt->key.proto != PROTO_TCP)
        return;
    const __u8 *rec = pkt->l4_payload;
    const void *end = pkt->payload_end;
    if (!rec || rec + 5 > (const __u8 *)end)
        return;
    __u8 rec_type = rec[0];
    __u16 legacy_ver = no_be16_at(rec + 1, end);
    /* plausibility gate: record version must be SSL3.x */
    if ((legacy_ver & 0xFF00) != 0x0300)
        return;
    meta->types_seen |= no_tls_type_bit(rec_type);
    if (rec_type != TLS_REC_HANDSHAKE)
        return;
    const __u8 *hs = rec + 5;
    if (hs + 4 > (const __u8 *)end)
        return;
    __u8 hs_type = hs[0];
    if (hs_type != TLS_HS_SERVER_HELLO && hs_type != TLS_HS_CLIENT_HELLO)
        return;
    /* legacy_version(2) random(32) */
    const __u8 *p = hs + 4;
    __u16 hello_ver = no_be16_at(p, end);
    if (hello_ver && !meta->version)
        meta->version = hello_ver;
    p += 2 + 32;
    if (p + 1 > (const __u8 *)end)
        return;
    __u8 sid_len = p[0];
    if (sid_len > 32)
        return;
    p += 1 + sid_len;
    if (hs_type == TLS_HS_SERVER_HELLO) {
        meta->cipher_suite = no_be16_at(p, end);
        p += 2 /* cipher */ + 1 /* compression */;
        __u16 ext_total = no_be16_at(p, end);
        if (ext_total)
            no_tls_walk_extensions(p + 2, end, meta);
    }
}

#endif /* NO_TLS_H */
