/*
 * filter.h — LPM-trie flow filtering.
 *
 * Semantics (reference-behavior analog: bpf/flows_filter.h): rules live in an
 * LPM trie keyed by CIDR; a packet is matched by source CIDR first, then by
 * destination CIDR; a matching rule's predicates (protocol, ports/ranges,
 * ICMP type/code, direction, TCP flags, drops-only) must all hold. A rule may
 * additionally require the peer address to fall in a second LPM trie
 * (peer_cidr_check), override sampling (sample_override), and ACCEPT or
 * REJECT the packet. Counters record accept/reject/no-match.
 */
#ifndef NO_FILTER_H
#define NO_FILTER_H

#include "config.h"
#include "helpers.h"
#include "maps.h"
#include "parse.h"

#define NO_FILTER_ACCEPT 0
#define NO_FILTER_REJECT 1
#define NO_DIR_ANY 255

NO_INLINE void no_count(__u32 key) {
    __u64 *val = bpf_map_lookup_elem(&global_counters, &key);
    if (val)
        no_atomic_add64(val, 1);
}

NO_INLINE int no_port_pred_ok(__u16 pkt_port, __u16 start, __u16 end,
                              __u16 p1, __u16 p2) {
    if (start || end) {
        if (pkt_port < start || pkt_port > end)
            return 0;
    }
    if (p1 || p2) {
        if (pkt_port != p1 && pkt_port != p2)
            return 0;
    }
    return 1;
}

NO_INLINE int no_rule_matches(const struct no_filter_rule *rule,
                              const struct no_pkt *pkt, __u8 direction,
                              __u8 is_drop_path) {
    const struct no_flow_key *k = &pkt->key;
    if (rule->proto && rule->proto != k->proto)
        return 0;
    if (rule->direction != NO_DIR_ANY && rule->direction != direction)
        return 0;
    if (!no_port_pred_ok(k->dst_port, rule->dport_start, rule->dport_end,
                         rule->dport1, rule->dport2))
        return 0;
    if (!no_port_pred_ok(k->src_port, rule->sport_start, rule->sport_end,
                         rule->sport1, rule->sport2))
        return 0;
    /* either-direction port predicate */
    if (rule->port_start || rule->port_end) {
        if (!((k->src_port >= rule->port_start &&
               k->src_port <= rule->port_end) ||
              (k->dst_port >= rule->port_start &&
               k->dst_port <= rule->port_end)))
            return 0;
    }
    if (rule->port1 || rule->port2) {
        if (k->src_port != rule->port1 && k->src_port != rule->port2 &&
            k->dst_port != rule->port1 && k->dst_port != rule->port2)
            return 0;
    }
    if (rule->icmp_type && rule->icmp_type != k->icmp_type)
        return 0;
    if (rule->icmp_code && rule->icmp_code != k->icmp_code)
        return 0;
    if (rule->tcp_flags && (pkt->tcp_flags & rule->tcp_flags) == 0)
        return 0;
    if (rule->want_drops && !is_drop_path)
        return 0;
    return 1;
}

NO_INLINE int no_peer_in_cidr(const __u8 *peer_ip) {
    struct no_filter_key key;
    key.prefix_len = 128;
    __builtin_memcpy(key.ip, peer_ip, NO_IP_LEN);
    return bpf_map_lookup_elem(&filter_peers, &key) != 0;
}

/* one side's evaluation: -1 = no usable match (caller may retry other side),
 * 0 = reject, 1 = accept */
NO_INLINE int no_filter_try(const struct no_pkt *pkt, const __u8 *keyed_ip,
                            const __u8 *peer_ip, __u8 direction,
                            __u8 is_drop_path, __u32 *sampling_out) {
    struct no_filter_key lkey;
    lkey.prefix_len = 128;
    __builtin_memcpy(lkey.ip, keyed_ip, NO_IP_LEN);
    const struct no_filter_rule *rule =
        bpf_map_lookup_elem(&filter_rules, &lkey);
    if (!rule)
        return -1;
    if (!no_rule_matches(rule, pkt, direction, is_drop_path))
        return -1;
    if (rule->peer_cidr_check && !no_peer_in_cidr(peer_ip))
        return -1;
    if (rule->action == NO_FILTER_REJECT) {
        no_count(NO_CTR_FILTER_REJECT);
        return 0;
    }
    if (rule->sample_override && sampling_out)
        *sampling_out = rule->sample_override;
    no_count(NO_CTR_FILTER_ACCEPT);
    return 1;
}

/*
 * Returns 1 = keep the packet, 0 = drop it from flow tracking.
 * `*sampling_out` is set when a matching rule overrides sampling.
 */
NO_INLINE int no_flow_filter(const struct no_pkt *pkt, __u8 direction,
                             __u8 is_drop_path, __u32 *sampling_out) {
    if (!cfg_enable_flow_filtering)
        return 1;

    /* source CIDR first; if the src-side rule exists but its full evaluation
     * (predicates + peer check) doesn't match, retry with the dst CIDR —
     * same fallback order as the parity target (flows_filter.h:251) */
    int verdict = no_filter_try(pkt, pkt->key.src_ip, pkt->key.dst_ip,
                                direction, is_drop_path, sampling_out);
    if (verdict < 0)
        verdict = no_filter_try(pkt, pkt->key.dst_ip, pkt->key.src_ip,
                                direction, is_drop_path, sampling_out);
    if (verdict < 0) {
        no_count(NO_CTR_FILTER_NOMATCH);
        return 0; /* rules configured but none matched -> not interesting */
    }
    return verdict;
}

#endif /* NO_FILTER_H */
