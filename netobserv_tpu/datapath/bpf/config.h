/*
 * config.h — load-time configuration constants and global counter keys.
 *
 * Every `volatile const` below is rewritten by the loader before program load
 * (reference analog: bpf/configs.h + pkg/tracer/tracer.go:2085-2183), so
 * disabled features are dead code the verifier prunes — no runtime branches.
 * The counter enum must stay in sync with netobserv_tpu/model/flow.py
 * GlobalCounter (tests pin the Python side; the C side is the same list).
 */
#ifndef NO_CONFIG_H
#define NO_CONFIG_H

/* global counter keys (PERCPU_ARRAY index) */
enum no_counter_key {
    NO_CTR_HASHMAP_FAIL_UPDATE_FLOW = 0,
    NO_CTR_HASHMAP_FAIL_CREATE_FLOW = 1,
    NO_CTR_HASHMAP_FAIL_UPDATE_DNS = 2,
    NO_CTR_FILTER_REJECT = 3,
    NO_CTR_FILTER_ACCEPT = 4,
    NO_CTR_FILTER_NOMATCH = 5,
    NO_CTR_NETWORK_EVENTS_ERR = 6,
    NO_CTR_NETWORK_EVENTS_ERR_GROUPID_MISMATCH = 7,
    NO_CTR_NETWORK_EVENTS_ERR_UPDATE_MAP_FLOWS = 8,
    NO_CTR_NETWORK_EVENTS_GOOD = 9,
    NO_CTR_NETWORK_EVENTS_OVERFLOW = 10,
    NO_CTR_NETWORK_EVENTS_COOKIE_TOO_BIG = 11,
    NO_CTR_OBSERVED_INTF_MISSED = 12,
    NO_COUNTER_MAX = 13,
};

/* loader-rewritten knobs (names are the loader's contract) */
volatile const __u32 cfg_sampling = 0;          /* 0/1 = all packets */
volatile const __u8 cfg_trace_messages = 0;
volatile const __u8 cfg_enable_rtt = 0;
volatile const __u8 cfg_enable_dns_tracking = 0;
volatile const __u16 cfg_dns_port = 53;
volatile const __u8 cfg_enable_pkt_drops = 0;
volatile const __u8 cfg_enable_flow_filtering = 0;
volatile const __u8 cfg_enable_network_events = 0;
volatile const __u8 cfg_network_events_group_id = 0;
volatile const __u8 cfg_enable_pkt_translation = 0;
volatile const __u8 cfg_enable_ipsec = 0;
volatile const __u8 cfg_enable_tls_tracking = 0;
volatile const __u8 cfg_quic_mode = 0; /* 0 off, 1 port-443, 2 any udp */
volatile const __u8 cfg_enable_ringbuf_fallback = 0;
volatile const __u8 cfg_enable_pca = 0;

/* set when any flow-filter rule carries a per-rule sampling override: the
 * sampling gate must then run AFTER filter evaluation (which may rewrite the
 * rate); when clear, sampling gates at the very top, before parsing
 * (reference: has_filter_sampling, bpf/flows.c:160-206) */
volatile const __u8 cfg_has_sampling = 0;

#endif /* NO_CONFIG_H */
