/*
 * maps.h — all datapath maps.
 *
 * The reference's 17-map surface (bpf/maps_definition.h) plus `sampling_gate`
 * (this design's per-CPU replacement for the reference's `do_sampling` .bss
 * global), declared in this project's style. Sizes marked "resized at load" are declared at their
 * maximum; the loader shrinks them according to enabled features and
 * CACHE_MAX_FLOWS before load (the reference does the same,
 * pkg/tracer/tracer.go:117-135). All maps are pinned by name so an external
 * lifecycle manager (bpfman mode) can own them across agent restarts.
 */
#ifndef NO_MAPS_H
#define NO_MAPS_H

#include "helpers.h"
#include "records.h"
#include "config.h" /* no_do_sampling() reads cfg_has_sampling/cfg_sampling */

#define NO_PIN_BY_NAME 1

/* filter key/rule structs live in records.h (userspace writes them) */

/* DNS query/response correlation key */
struct no_dns_corr_key {
    __u16 src_port;
    __u16 dst_port;
    __u8 src_ip[NO_IP_LEN];
    __u8 dst_ip[NO_IP_LEN];
    __u16 dns_id;
    __u8 proto;
    __u8 _pad;
};

/* scratch buffer for DNS name copies (dodges the 512B stack limit) */
struct no_dns_name_scratch {
    char name[NO_DNS_NAME_MAX_LEN];
};

#define DEF_MAP(_name, _type, _key, _value, _max)                              \
    struct {                                                                   \
        __uint(type, _type);                                                   \
        __type(key, _key);                                                     \
        __type(value, _value);                                                 \
        __uint(max_entries, _max);                                             \
        __uint(pinning, NO_PIN_BY_NAME);                                       \
    } _name SEC(".maps")

#define DEF_RINGBUF(_name, _size)                                              \
    struct {                                                                   \
        __uint(type, BPF_MAP_TYPE_RINGBUF);                                    \
        __uint(max_entries, _size);                                            \
        __uint(pinning, NO_PIN_BY_NAME);                                       \
    } _name SEC(".maps")

/* main aggregation map: shared HASH with per-entry spin lock (resized) */
DEF_MAP(aggregated_flows, BPF_MAP_TYPE_HASH, struct no_flow_key,
        struct no_flow_stats, 1 << 24);

/* map-full fallback ring buffer (flow events pushed to userspace) */
DEF_RINGBUF(direct_flows, 1 << 24);

/* per-feature per-CPU partial maps, merged by userspace at eviction */
DEF_MAP(flows_dns, BPF_MAP_TYPE_PERCPU_HASH, struct no_flow_key,
        struct no_dns_rec, 1 << 24);
DEF_MAP(flows_drops, BPF_MAP_TYPE_PERCPU_HASH, struct no_flow_key,
        struct no_drops_rec, 1 << 24);
DEF_MAP(flows_nevents, BPF_MAP_TYPE_PERCPU_HASH, struct no_flow_key,
        struct no_nevents_rec, 1 << 24);
DEF_MAP(flows_xlat, BPF_MAP_TYPE_PERCPU_HASH, struct no_flow_key,
        struct no_xlat_rec, 1 << 24);
DEF_MAP(flows_extra, BPF_MAP_TYPE_PERCPU_HASH, struct no_flow_key,
        struct no_extra_rec, 1 << 24);
DEF_MAP(flows_quic, BPF_MAP_TYPE_PERCPU_HASH, struct no_flow_key,
        struct no_quic_rec, 1 << 24);

/* PCA captured packets */
DEF_RINGBUF(packet_records, 1 << 21);

/* DNS query->response correlation (latency measurement) */
DEF_MAP(dns_inflight, BPF_MAP_TYPE_HASH, struct no_dns_corr_key, __u64,
        1 << 20);

/* per-CPU scratch for DNS name copy */
DEF_MAP(dns_scratch, BPF_MAP_TYPE_PERCPU_ARRAY, __u32,
        struct no_dns_name_scratch, 1);

/* datapath global counters, scraped+reset each eviction */
DEF_MAP(global_counters, BPF_MAP_TYPE_PERCPU_ARRAY, __u32, __u64,
        NO_COUNTER_MAX);

/* LPM filter tries: primary CIDR and peer CIDR */
DEF_MAP(filter_rules, BPF_MAP_TYPE_LPM_TRIE, struct no_filter_key,
        struct no_filter_rule, 16);
DEF_MAP(filter_peers, BPF_MAP_TYPE_LPM_TRIE, struct no_filter_key, __u8, 16);

/* IPsec xfrm correlation: pid_tgid -> flow key between entry/return probes */
DEF_MAP(ipsec_ingress_inflight, BPF_MAP_TYPE_HASH, __u64, struct no_flow_key,
        1 << 12);
DEF_MAP(ipsec_egress_inflight, BPF_MAP_TYPE_HASH, __u64, struct no_flow_key,
        1 << 12);

/* OpenSSL uprobe plaintext events (sized for 16KB * 1000/s * 5s window) */
DEF_RINGBUF(ssl_events, 1 << 27);

/* per-CPU record of the TC path's most recent sampling decision; the aux
 * hooks (rtt/drops/nevents/xlat/ipsec) gate on it so per-flow features are
 * only collected for sampled flows (reference: `static u8 do_sampling`,
 * bpf/utils.h:9 — a per-CPU map instead of a .bss global avoids that
 * global's cross-CPU races and loads through raw bpf(2) without .bss
 * relocation support) */
DEF_MAP(sampling_gate, BPF_MAP_TYPE_PERCPU_ARRAY, __u32, __u8, 1);

NO_INLINE void no_set_do_sampling(__u8 v) {
    __u32 k = 0;
    __u8 *g = bpf_map_lookup_elem(&sampling_gate, &k);
    if (g)
        *g = v;
}

NO_INLINE __u8 no_do_sampling(void) {
    /* sampling disabled: every packet is sampled — short-circuit so aux
     * hooks on CPUs the TC path never ran on (RPS steering, cold start)
     * are not suppressed by the zero-initialised gate; the verifier prunes
     * this to a constant (volatile const) */
    if (!cfg_has_sampling && cfg_sampling <= 1)
        return 1;
    __u32 k = 0;
    __u8 *g = bpf_map_lookup_elem(&sampling_gate, &k);
    return g ? *g : 0;
}

#endif /* NO_MAPS_H */
