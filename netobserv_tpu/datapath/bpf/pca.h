/*
 * pca.h — packet capture (PCA mode): copy filtered packet payloads to a ring
 * buffer for userspace pcap framing (reference analog: bpf/pca.h).
 */
#ifndef NO_PCA_H
#define NO_PCA_H

#include "config.h"
#include "filter.h"
#include "helpers.h"
#include "maps.h"
#include "parse.h"

NO_INLINE int no_pca_capture(struct __sk_buff *skb, __u8 direction) {
    if (!cfg_enable_pca)
        return TC_ACT_OK;
    struct no_pkt pkt;
    __builtin_memset(&pkt, 0, sizeof(pkt));
    if (no_parse_packet(skb, &pkt) != 0)
        return TC_ACT_OK;
    pkt.ts_ns = bpf_ktime_get_ns();
    __u32 sampling = cfg_sampling;
    if (!no_flow_filter(&pkt, direction, 0, &sampling))
        return TC_ACT_OK;
    if (sampling > 1 && bpf_get_prandom_u32() % sampling != 0)
        return TC_ACT_OK;

    struct no_packet_event *ev =
        bpf_ringbuf_reserve(&packet_records, sizeof(*ev), 0);
    if (!ev)
        return TC_ACT_OK;
    ev->if_index = skb->ifindex;
    ev->pkt_len = skb->len;
    ev->timestamp_ns = pkt.ts_ns;
    __u32 copy = skb->len < NO_MAX_PAYLOAD_SIZE ? skb->len
                                                : NO_MAX_PAYLOAD_SIZE;
    const __u8 *data = (const __u8 *)(long)skb->data;
    const __u8 *end = (const __u8 *)(long)skb->data_end;
    #pragma unroll
    for (__u32 i = 0; i < NO_MAX_PAYLOAD_SIZE; i++) {
        if (i >= copy || data + i + 1 > end)
            break;
        ev->payload[i] = data[i];
    }
    bpf_ringbuf_submit(ev, 0);
    return TC_ACT_OK;
}

#endif /* NO_PCA_H */
