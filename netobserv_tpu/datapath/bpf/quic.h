/*
 * quic.h — QUIC detection via RFC 8999 version-independent invariants,
 * inline in the TC path (reference analog: bpf/quic_tracker.h).
 *
 * Modes (cfg_quic_mode): 0 off; 1 only UDP/443; 2 any UDP port. Long headers
 * carry the version (recorded, max-merged); short headers mark an established
 * connection.
 */
#ifndef NO_QUIC_H
#define NO_QUIC_H

#include "config.h"
#include "helpers.h"
#include "maps.h"
#include "parse.h"

#define QUIC_LONG_HDR_BIT 0x80
#define QUIC_FIXED_BIT 0x40

NO_INLINE void no_track_quic(const struct no_pkt *pkt) {
    if (!cfg_quic_mode || pkt->key.proto != PROTO_UDP)
        return;
    if (cfg_quic_mode == 1 && pkt->key.src_port != 443 &&
        pkt->key.dst_port != 443)
        return;
    const __u8 *p = pkt->l4_payload;
    const void *end = pkt->payload_end;
    if (!p || p + 5 > (const __u8 *)end)
        return;
    __u8 first = p[0];
    if (!(first & QUIC_FIXED_BIT))
        return; /* fixed bit must be set in all QUIC packets */
    __u8 is_long = first & QUIC_LONG_HDR_BIT;
    __u32 version = 0;
    if (is_long) {
        version = ((__u32)p[1] << 24) | ((__u32)p[2] << 16) |
                  ((__u32)p[3] << 8) | p[4];
        if (version == 0)
            return; /* version negotiation packets carry version 0 */
    }
    struct no_quic_rec *rec = bpf_map_lookup_elem(&flows_quic, &pkt->key);
    if (rec) {
        rec->last_seen_ns = pkt->ts_ns;
        if (version > rec->version)
            rec->version = version;
        if (is_long)
            rec->seen_long_hdr = 1;
        else
            rec->seen_short_hdr = 1;
        return;
    }
    struct no_quic_rec fresh = {
        .first_seen_ns = pkt->ts_ns,
        .last_seen_ns = pkt->ts_ns,
        .version = version,
        .eth_protocol = pkt->eth_protocol,
        .seen_long_hdr = is_long ? 1 : 0,
        .seen_short_hdr = is_long ? 0 : 1,
    };
    bpf_map_update_elem(&flows_quic, &pkt->key, &fresh, BPF_ANY);
}

#endif /* NO_QUIC_H */
