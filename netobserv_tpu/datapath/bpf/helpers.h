/*
 * helpers.h — minimal BPF helper declarations and map-definition macros.
 *
 * Self-contained (no vendored libbpf headers): only the helpers this datapath
 * uses are declared, by their stable kernel helper IDs. For CO-RE tracing
 * paths (kprobes/fentry reading kernel structs) the build expects a
 * distro-provided vmlinux.h + bpf_core_read.h; those hooks are compiled only
 * when NO_HAVE_VMLINUX is defined (see flowpath.c).
 */
#ifndef NO_BPF_HELPERS_H
#define NO_BPF_HELPERS_H

/* When a TU already pulls in vmlinux.h + libbpf headers (the tracing-probe
 * build, flowpath_probes.c), skip everything those provide and only add this
 * project's small inline utilities (the #else branch at the bottom). */
#ifndef NO_HAVE_VMLINUX

typedef unsigned char __u8;
typedef unsigned short __u16;
typedef unsigned int __u32;
typedef unsigned long long __u64;
typedef signed char __s8;
typedef short __s16;
typedef int __s32;
typedef long long __s64;

#define SEC(name) __attribute__((section(name), used))
#define __uint(name, val) int(*name)[val]
#define __type(name, val) typeof(val) *name
#define NO_INLINE static __attribute__((always_inline)) inline

/* map types we use */
#define BPF_MAP_TYPE_HASH 1
#define BPF_MAP_TYPE_PERCPU_HASH 5
#define BPF_MAP_TYPE_PERCPU_ARRAY 6
#define BPF_MAP_TYPE_LPM_TRIE 11
#define BPF_MAP_TYPE_RINGBUF 27

#define BPF_ANY 0
#define BPF_NOEXIST 1
#define BPF_EXIST 2
#define BPF_F_NO_PREALLOC 1

#define NO_EEXIST 17
#define NO_ENOENT 2

/* TC verdicts */
#define TC_ACT_OK 0
#define TC_ACT_UNSPEC (-1)

struct bpf_spin_lock {
    __u32 val;
};

/* subset of struct __sk_buff (uapi/linux/bpf.h) accessed by the TC path */
struct __sk_buff {
    __u32 len;
    __u32 pkt_type;
    __u32 mark;
    __u32 queue_mapping;
    __u32 protocol;
    __u32 vlan_present;
    __u32 vlan_tci;
    __u32 vlan_proto;
    __u32 priority;
    __u32 ingress_ifindex;
    __u32 ifindex;
    __u32 tc_index;
    __u32 cb[5];
    __u32 hash;
    __u32 tc_classid;
    __u32 data;
    __u32 data_end;
    __u32 napi_id;
    /* remaining fields unused by this datapath */
};

/* helper IDs from uapi/linux/bpf.h */
static void *(*bpf_map_lookup_elem)(void *map, const void *key) = (void *)1;
static long (*bpf_map_update_elem)(void *map, const void *key,
                                   const void *value, __u64 flags) = (void *)2;
static long (*bpf_map_delete_elem)(void *map, const void *key) = (void *)3;
static long (*bpf_probe_read)(void *dst, __u32 size,
                              const void *src) = (void *)4;
static __u64 (*bpf_ktime_get_ns)(void) = (void *)5;
static long (*bpf_trace_printk)(const char *fmt, __u32 fmt_size,
                                ...) = (void *)6;
static __u32 (*bpf_get_prandom_u32)(void) = (void *)7;
static __u32 (*bpf_get_smp_processor_id)(void) = (void *)8;
static __u64 (*bpf_get_current_pid_tgid)(void) = (void *)14;
static long (*bpf_spin_lock)(struct bpf_spin_lock *lock) = (void *)93;
static long (*bpf_spin_unlock)(struct bpf_spin_lock *lock) = (void *)94;
static long (*bpf_probe_read_kernel)(void *dst, __u32 size,
                                     const void *src) = (void *)113;
static long (*bpf_probe_read_user)(void *dst, __u32 size,
                                   const void *src) = (void *)112;
static void *(*bpf_ringbuf_reserve)(void *ringbuf, __u64 size,
                                    __u64 flags) = (void *)131;
static void (*bpf_ringbuf_submit)(void *data, __u64 flags) = (void *)132;
static void (*bpf_ringbuf_discard)(void *data, __u64 flags) = (void *)133;
static long (*bpf_ringbuf_output)(void *ringbuf, void *data, __u64 size,
                                  __u64 flags) = (void *)130;

#else /* NO_HAVE_VMLINUX */
#define NO_INLINE static __always_inline
#define NO_EEXIST 17
#define NO_ENOENT 2
#endif /* NO_HAVE_VMLINUX */

#ifndef NO_HAVE_VMLINUX
#define no_printk(fmt, ...)                                                    \
    ({                                                                         \
        if (cfg_trace_messages) {                                              \
            const char _fmt[] = fmt;                                           \
            bpf_trace_printk(_fmt, sizeof(_fmt), ##__VA_ARGS__);               \
        }                                                                      \
    })

NO_INLINE __u16 no_bswap16(__u16 x) { return __builtin_bswap16(x); }
NO_INLINE __u32 no_bswap32(__u32 x) { return __builtin_bswap32(x); }

/* network byte order <-> host (BPF targets are little-endian on all arches we
 * ship: x86_64, arm64, ppc64le) */
#define no_ntohs(x) no_bswap16(x)
#define no_htons(x) no_bswap16(x)
#define no_ntohl(x) no_bswap32(x)
#endif /* NO_HAVE_VMLINUX */

NO_INLINE void no_atomic_add64(__u64 *dst, __u64 val) {
    __sync_fetch_and_add(dst, val);
}

NO_INLINE __u16 no_sat_add16(__u16 a, __u16 b) {
    __u32 s = (__u32)a + b;
    return s > 0xFFFF ? 0xFFFF : (__u16)s;
}

NO_INLINE __u32 no_sat_add32(__u32 a, __u32 b) {
    __u64 s = (__u64)a + b;
    return s > 0xFFFFFFFFull ? 0xFFFFFFFF : (__u32)s;
}

#endif /* NO_BPF_HELPERS_H */
