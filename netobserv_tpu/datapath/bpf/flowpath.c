/*
 * flowpath.c — the TC/TCX flow-aggregation datapath.
 *
 * One program per hook point (tc/tcx x ingress/egress) funnels into
 * no_flow_monitor(): sampling gate -> parse -> filter -> inline trackers
 * (DNS/TLS/QUIC) -> upsert into the `aggregated_flows` shared hash under a
 * per-entry spin lock, with multi-interface dedup bookkeeping; when the map
 * is full (or racing inserts fail), the whole event falls back to the
 * `direct_flows` ring buffer with the errno recorded.
 *
 * Behavioral parity target: bpf/flows.c in netobserv-ebpf-agent (flow_monitor,
 * update_existing_flow, the BPF_NOEXIST+EEXIST retry idiom, observed-interface
 * dedup). This is a fresh implementation in this project's layout/style.
 *
 * Build: clang -g -O2 -target bpf -DNO_BPF_BUILD -c flowpath.c
 * (see ../native/CMakeLists.txt, DATAPATH_BPF option).
 */
#include "helpers.h"
#include "records.h"
#include "config.h"
#include "maps.h"
#include "parse.h"
#include "filter.h"
#include "dns.h"
#include "tls.h"
#include "quic.h"
#include "pca.h"

char LICENSE[] SEC("license") = "GPL";

#define DIR_INGRESS 0
#define DIR_EGRESS 1

/* 1-in-N sampling gate; returns 1 when the packet should be processed */
NO_INLINE int no_sampled(__u32 sampling) {
    if (sampling <= 1)
        return 1;
    return bpf_get_prandom_u32() % sampling == 0;
}

/* merge one packet into an existing map entry (under its spin lock).
 * Returns 1 when the observed-interface array overflowed (counted by the
 * caller, outside the lock). */
NO_INLINE int no_update_flow(struct no_flow_stats *s,
                             const struct no_pkt *pkt, __u32 if_index,
                             __u8 direction, __u32 sampling,
                             const struct no_tls_meta *tls, __u32 len) {
    int overflow = 0;
    bpf_spin_lock(&s->lock);
    if (s->if_index_first == if_index) {
        /* count bytes/packets only from the first-seen interface, so a flow
         * crossing veth+bridge+phys is not double-counted (reference:
         * update_existing_flow, bpf/flows.c:100-110) */
        if (s->first_seen_ns == 0 || pkt->ts_ns < s->first_seen_ns)
            s->first_seen_ns = pkt->ts_ns;
        if (pkt->ts_ns > s->last_seen_ns)
            s->last_seen_ns = pkt->ts_ns;
        s->bytes += len;
        s->packets += 1;
        s->tcp_flags |= pkt->tcp_flags;
        s->sampling = sampling;
        if (pkt->dscp)
            s->dscp = pkt->dscp;
        if (tls) {
            if (tls->version && s->ssl_version != tls->version) {
                if (s->ssl_version == 0)
                    s->ssl_version = tls->version;
                else
                    /* client/server hellos disagree on version
                     * (reference: bpf/flows.c:111-118) */
                    s->misc_flags |= NO_MISC_SSL_MISMATCH;
            }
            /* cipher_suite/key_share only ever parse out of a ServerHello
             * (tls.h), matching the reference's SERVER_HELLO gate */
            if (tls->cipher_suite)
                s->tls_cipher_suite = tls->cipher_suite;
            if (tls->key_share)
                s->tls_key_share = tls->key_share;
            s->tls_types |= tls->types_seen;
        }
    } else if (if_index != 0) {
        /* secondary interface: extend the time span and flags, remember the
         * (ifindex, direction) observation — but never re-count traffic */
        if (pkt->ts_ns > s->last_seen_ns)
            s->last_seen_ns = pkt->ts_ns;
        s->tcp_flags |= pkt->tcp_flags;
        __u8 n = s->n_observed_intf;
        __u8 seen = 0;
        #pragma unroll
        for (int i = 0; i < NO_MAX_OBSERVED_INTERFACES; i++) {
            if (i < n && s->observed_intf[i] == if_index &&
                s->observed_direction[i] == direction)
                seen = 1;
        }
        if (!seen) {
            if (n < NO_MAX_OBSERVED_INTERFACES) {
                s->observed_intf[n] = if_index;
                s->observed_direction[n] = direction;
                s->n_observed_intf = n + 1;
            } else {
                overflow = 1;
            }
        }
    }
    bpf_spin_unlock(&s->lock);
    return overflow;
}

NO_INLINE void no_init_stats(struct no_flow_stats *s, const struct no_pkt *pkt,
                             __u32 if_index, __u8 direction, __u32 sampling,
                             const struct no_tls_meta *tls, __u32 len) {
    __builtin_memset(s, 0, sizeof(*s));
    s->first_seen_ns = pkt->ts_ns;
    s->last_seen_ns = pkt->ts_ns;
    s->bytes = len;
    s->packets = 1;
    s->eth_protocol = pkt->eth_protocol;
    s->tcp_flags = pkt->tcp_flags;
    __builtin_memcpy(s->src_mac, pkt->src_mac, NO_ETH_ALEN);
    __builtin_memcpy(s->dst_mac, pkt->dst_mac, NO_ETH_ALEN);
    s->if_index_first = if_index;
    s->sampling = sampling;
    s->direction_first = direction;
    s->dscp = pkt->dscp;
    s->n_observed_intf = 1;
    s->observed_intf[0] = if_index;
    s->observed_direction[0] = direction;
    if (tls) {
        s->ssl_version = tls->version;
        s->tls_cipher_suite = tls->cipher_suite;
        s->tls_key_share = tls->key_share;
        s->tls_types = tls->types_seen;
    }
}

/* ring buffer fallback when the hash map can't take the flow */
NO_INLINE void no_ringbuf_fallback(const struct no_pkt *pkt, __u32 if_index,
                                   __u8 direction, __u32 sampling,
                                   const struct no_tls_meta *tls, __u32 len,
                                   __u8 err) {
    if (!cfg_enable_ringbuf_fallback)
        return;
    struct no_flow_event *ev =
        bpf_ringbuf_reserve(&direct_flows, sizeof(*ev), 0);
    if (!ev)
        return;
    __builtin_memcpy(&ev->key, &pkt->key, sizeof(ev->key));
    no_init_stats(&ev->stats, pkt, if_index, direction, sampling, tls, len);
    ev->stats.errno_fallback = err;
    bpf_ringbuf_submit(ev, 0);
}

NO_INLINE int no_flow_monitor(struct __sk_buff *skb, __u8 direction) {
    __u32 sampling = 0;
    if (!cfg_has_sampling) {
        /* no filter rule carries a sampling override: gate at the earliest
         * point, before any parsing (reference: bpf/flows.c:160-171).
         * Skip the gate write entirely when sampling is off — the reader
         * (no_do_sampling) short-circuits that case, so the store would be
         * pure per-packet overhead the verifier can't prune */
        if (cfg_sampling > 1) {
            if (!no_sampled(cfg_sampling)) {
                no_set_do_sampling(0);
                return TC_ACT_OK;
            }
            sampling = cfg_sampling;
            no_set_do_sampling(1);
        }
    }
    struct no_pkt pkt;
    __builtin_memset(&pkt, 0, sizeof(pkt));

    if (no_parse_packet(skb, &pkt) != 0)
        return TC_ACT_OK;
    pkt.ts_ns = bpf_ktime_get_ns();

    int skip = !no_flow_filter(&pkt, direction, 0, &sampling);
    if (cfg_has_sampling) {
        /* filter evaluation may have rewritten the rate for this flow; gate
         * now and record the decision for the aux hooks — even for packets
         * the filter will skip (reference: bpf/flows.c:194-206) */
        if (sampling == 0)
            sampling = cfg_sampling;
        if (!no_sampled(sampling)) {
            no_set_do_sampling(0);
            return TC_ACT_OK;
        }
        no_set_do_sampling(1);
    }
    if (skip)
        return TC_ACT_OK;

    struct no_tls_meta tls = {};
    no_track_dns(&pkt);
    no_track_tls(&pkt, &tls);
    no_track_quic(&pkt);

    __u32 if_index = skb->ifindex;
    struct no_flow_stats *existing =
        bpf_map_lookup_elem(&aggregated_flows, &pkt.key);
    if (existing) {
        if (no_update_flow(existing, &pkt, if_index, direction, sampling,
                           &tls, skb->len) &&
            pkt.key.proto != 0)
            /* zero-proto traffic routinely saturates the array; only count
             * real protocols (reference: bpf/flows.c:133-142) */
            no_count(NO_CTR_OBSERVED_INTF_MISSED);
    } else {
        struct no_flow_stats fresh;
        no_init_stats(&fresh, &pkt, if_index, direction, sampling, &tls,
                      skb->len);
        long err = bpf_map_update_elem(&aggregated_flows, &pkt.key, &fresh,
                                       BPF_NOEXIST);
        if (err == -NO_EEXIST) {
            /* another CPU created it between lookup and insert: merge */
            existing = bpf_map_lookup_elem(&aggregated_flows, &pkt.key);
            if (existing) {
                if (no_update_flow(existing, &pkt, if_index, direction,
                                   sampling, &tls, skb->len) &&
                    pkt.key.proto != 0)
                    no_count(NO_CTR_OBSERVED_INTF_MISSED);
            } else {
                no_count(NO_CTR_HASHMAP_FAIL_UPDATE_FLOW);
            }
        } else if (err != 0) {
            /* map full (or other failure): ship the whole event upstairs */
            no_count(NO_CTR_HASHMAP_FAIL_CREATE_FLOW);
            no_ringbuf_fallback(&pkt, if_index, direction, sampling, &tls,
                                skb->len, (__u8)(-err));
        }
    }
    no_record_dns(&pkt);
    return TC_ACT_OK;
}

SEC("tc_ingress")
int tc_ingress_flow(struct __sk_buff *skb) {
    return no_flow_monitor(skb, DIR_INGRESS);
}

SEC("tc_egress")
int tc_egress_flow(struct __sk_buff *skb) {
    return no_flow_monitor(skb, DIR_EGRESS);
}

SEC("tcx/ingress")
int tcx_ingress_flow(struct __sk_buff *skb) {
    no_flow_monitor(skb, DIR_INGRESS);
    return TC_ACT_UNSPEC; /* tcx: continue the chain */
}

SEC("tcx/egress")
int tcx_egress_flow(struct __sk_buff *skb) {
    no_flow_monitor(skb, DIR_EGRESS);
    return TC_ACT_UNSPEC;
}

/* PCA (packet capture) entry points — mutually exclusive deployment with the
 * flow programs; gated by cfg_enable_pca */
SEC("tc_pca_ingress")
int tc_pca_ingress(struct __sk_buff *skb) {
    return no_pca_capture(skb, DIR_INGRESS);
}

SEC("tc_pca_egress")
int tc_pca_egress(struct __sk_buff *skb) {
    return no_pca_capture(skb, DIR_EGRESS);
}

SEC("tcx/pca_ingress")
int tcx_pca_ingress(struct __sk_buff *skb) {
    no_pca_capture(skb, DIR_INGRESS);
    return TC_ACT_UNSPEC;
}

SEC("tcx/pca_egress")
int tcx_pca_egress(struct __sk_buff *skb) {
    no_pca_capture(skb, DIR_EGRESS);
    return TC_ACT_UNSPEC;
}
