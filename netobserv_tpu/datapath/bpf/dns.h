/*
 * dns.h — DNS latency tracking, inline in the TC path.
 *
 * Behavior (reference analog: bpf/dns_tracker.h): a query on cfg_dns_port
 * stores its timestamp in `dns_inflight` keyed by the *reversed* tuple plus
 * the DNS transaction id, so the response (travelling the opposite direction)
 * finds it, yielding latency. The response's flags/rcode and the query name
 * (copied via a per-CPU scratch slot to dodge the 512B stack limit) are
 * recorded in the per-CPU `flows_dns` feature map.
 */
#ifndef NO_DNS_H
#define NO_DNS_H

#include "config.h"
#include "helpers.h"
#include "maps.h"
#include "parse.h"

struct no_dns_hdr {
    __u16 id;
    __u16 flags;
    __u16 qdcount;
    __u16 ancount;
    __u16 nscount;
    __u16 arcount;
};

#define NO_DNS_QR_BIT 0x8000

NO_INLINE void no_dns_corr_key_for_query(struct no_dns_corr_key *ck,
                                         const struct no_flow_key *k,
                                         __u16 dns_id) {
    /* reversed tuple: the response's own 5-tuple will produce this key */
    ck->src_port = k->dst_port;
    ck->dst_port = k->src_port;
    __builtin_memcpy(ck->src_ip, k->dst_ip, NO_IP_LEN);
    __builtin_memcpy(ck->dst_ip, k->src_ip, NO_IP_LEN);
    ck->dns_id = dns_id;
    ck->proto = k->proto;
    ck->_pad = 0;
}

NO_INLINE void no_dns_corr_key_for_response(struct no_dns_corr_key *ck,
                                            const struct no_flow_key *k,
                                            __u16 dns_id) {
    ck->src_port = k->src_port;
    ck->dst_port = k->dst_port;
    __builtin_memcpy(ck->src_ip, k->src_ip, NO_IP_LEN);
    __builtin_memcpy(ck->dst_ip, k->dst_ip, NO_IP_LEN);
    ck->dns_id = dns_id;
    ck->proto = k->proto;
    ck->_pad = 0;
}

/* copy a (possibly truncated) qname into out[NO_DNS_NAME_MAX_LEN] */
NO_INLINE void no_dns_copy_name(const __u8 *qname, const void *end,
                                char *out) {
    #pragma unroll
    for (int i = 0; i < NO_DNS_NAME_MAX_LEN; i++) {
        if (qname + i + 1 > (const __u8 *)end) {
            out[i] = 0;
            return;
        }
        out[i] = qname[i];
        if (qname[i] == 0)
            return;
    }
}

NO_INLINE void no_track_dns(struct no_pkt *pkt) {
    if (!cfg_enable_dns_tracking || pkt->key.proto != PROTO_UDP)
        return;
    if (pkt->key.src_port != cfg_dns_port && pkt->key.dst_port != cfg_dns_port)
        return;
    const struct no_dns_hdr *dns = pkt->l4_payload;
    if (!dns || (const void *)(dns + 1) > pkt->payload_end)
        return;
    __u16 id = no_ntohs(dns->id);
    __u16 flags = no_ntohs(dns->flags);
    struct no_dns_corr_key ck;

    if (!(flags & NO_DNS_QR_BIT)) {
        /* query: stash timestamp under the reversed tuple */
        no_dns_corr_key_for_query(&ck, &pkt->key, id);
        __u64 ts = pkt->ts_ns;
        if (bpf_map_update_elem(&dns_inflight, &ck, &ts, BPF_ANY) != 0)
            no_count(NO_CTR_HASHMAP_FAIL_UPDATE_DNS);
        pkt->dns_id = id;
        pkt->dns_flags = flags;
        return;
    }
    /* response: correlate and compute latency */
    no_dns_corr_key_for_response(&ck, &pkt->key, id);
    __u64 *sent = bpf_map_lookup_elem(&dns_inflight, &ck);
    pkt->dns_id = id;
    pkt->dns_flags = flags;
    if (sent) {
        if (pkt->ts_ns > *sent)
            pkt->dns_latency = pkt->ts_ns - *sent;
        bpf_map_delete_elem(&dns_inflight, &ck);
    }
}

/* upsert the per-CPU DNS feature record after the base flow update */
NO_INLINE void no_record_dns(const struct no_pkt *pkt) {
    if (!cfg_enable_dns_tracking || (!pkt->dns_id && !pkt->dns_latency))
        return;
    struct no_dns_rec *rec = bpf_map_lookup_elem(&flows_dns, &pkt->key);
    if (rec) {
        if (rec->first_seen_ns == 0)
            rec->first_seen_ns = pkt->ts_ns;
        rec->last_seen_ns = pkt->ts_ns;
        rec->dns_id = pkt->dns_id;
        rec->dns_flags |= pkt->dns_flags;
        rec->errno_code = 0;
        if (pkt->dns_latency > rec->latency_ns)
            rec->latency_ns = pkt->dns_latency;
        return;
    }
    struct no_dns_rec fresh = {
        .first_seen_ns = pkt->ts_ns,
        .last_seen_ns = pkt->ts_ns,
        .latency_ns = pkt->dns_latency,
        .dns_id = pkt->dns_id,
        .dns_flags = pkt->dns_flags,
        .eth_protocol = pkt->eth_protocol,
    };
    /* copy the qname through per-CPU scratch (stack budget) */
    __u32 zero = 0;
    struct no_dns_name_scratch *scratch =
        bpf_map_lookup_elem(&dns_scratch, &zero);
    const struct no_dns_hdr *dns = pkt->l4_payload;
    if (scratch && dns && (const void *)(dns + 1) <= pkt->payload_end) {
        no_dns_copy_name((const __u8 *)(dns + 1), pkt->payload_end,
                         scratch->name);
        __builtin_memcpy(fresh.name, scratch->name, NO_DNS_NAME_MAX_LEN);
    }
    if (bpf_map_update_elem(&flows_dns, &pkt->key, &fresh, BPF_ANY) != 0)
        no_count(NO_CTR_HASHMAP_FAIL_UPDATE_DNS);
}

#endif /* NO_DNS_H */
