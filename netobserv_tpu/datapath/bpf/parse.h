/*
 * parse.h — L2/L3/L4 header parsing for the TC/TCX path.
 *
 * Bounds-checked direct packet access (data/data_end), filling the flow key
 * and packet metadata. Reference-behavior analog: bpf/utils.h fill_*hdr.
 */
#ifndef NO_PARSE_H
#define NO_PARSE_H

#include "config.h"
#include "helpers.h"
#include "records.h"

#define ETH_P_IPV4 0x0800
#define ETH_P_IPV6 0x86DD
#define PROTO_TCP 6
#define PROTO_UDP 17
#define PROTO_SCTP 132
#define PROTO_ICMP 1
#define PROTO_ICMP6 58

/* synthetic exported flag bits on top of the RFC 9293 low byte */
#define NO_TCPF_SYN 0x02
#define NO_TCPF_ACK 0x10
#define NO_TCPF_FIN 0x01
#define NO_TCPF_RST 0x04
#define NO_TCPF_SYN_ACK 0x100
#define NO_TCPF_FIN_ACK 0x200
#define NO_TCPF_RST_ACK 0x400

struct no_pkt {
    struct no_flow_key key;
    __u64 ts_ns;
    __u16 eth_protocol;
    __u16 tcp_flags;
    __u8 dscp;
    __u8 src_mac[NO_ETH_ALEN];
    __u8 dst_mac[NO_ETH_ALEN];
    const void *l4_payload; /* first byte past the L4 header, or NULL */
    const void *payload_end;
    __u16 dns_id;           /* filled by the dns tracker */
    __u16 dns_flags;
    __u64 dns_latency;
};

struct no_ethhdr {
    __u8 dst[NO_ETH_ALEN];
    __u8 src[NO_ETH_ALEN];
    __u16 proto;
};

struct no_iphdr {
    __u8 ver_ihl;
    __u8 tos;
    __u16 tot_len;
    __u16 id;
    __u16 frag_off;
    __u8 ttl;
    __u8 protocol;
    __u16 check;
    __u32 saddr;
    __u32 daddr;
};

struct no_ip6hdr {
    __u32 ver_tc_fl;
    __u16 payload_len;
    __u8 next_hdr;
    __u8 hop_limit;
    __u8 saddr[16];
    __u8 daddr[16];
};

struct no_tcphdr {
    __u16 sport;
    __u16 dport;
    __u32 seq;
    __u32 ack;
    __u8 off_rsvd;  /* data offset in high nibble */
    __u8 flags;
    __u16 window;
    __u16 check;
    __u16 urg;
};

struct no_udphdr {
    __u16 sport;
    __u16 dport;
    __u16 len;
    __u16 check;
};

NO_INLINE __u16 no_classify_tcp_flags(__u8 raw) {
    __u16 flags = raw;
    if ((raw & (NO_TCPF_SYN | NO_TCPF_ACK)) == (NO_TCPF_SYN | NO_TCPF_ACK))
        flags |= NO_TCPF_SYN_ACK;
    if ((raw & (NO_TCPF_FIN | NO_TCPF_ACK)) == (NO_TCPF_FIN | NO_TCPF_ACK))
        flags |= NO_TCPF_FIN_ACK;
    if ((raw & (NO_TCPF_RST | NO_TCPF_ACK)) == (NO_TCPF_RST | NO_TCPF_ACK))
        flags |= NO_TCPF_RST_ACK;
    return flags;
}

NO_INLINE void no_v4_mapped(__u8 *dst16, __u32 addr_be) {
    __builtin_memset(dst16, 0, 10);
    dst16[10] = 0xFF;
    dst16[11] = 0xFF;
    __builtin_memcpy(dst16 + 12, &addr_be, 4);
}

/* parse L4 starting at `l4`; returns 0 on success */
NO_INLINE int no_parse_l4(const void *l4, const void *end, __u8 proto,
                          struct no_pkt *pkt) {
    struct no_flow_key *k = &pkt->key;
    k->proto = proto;
    switch (proto) {
    case PROTO_TCP: {
        const struct no_tcphdr *tcp = l4;
        if ((const void *)(tcp + 1) > end)
            return -1;
        k->src_port = no_ntohs(tcp->sport);
        k->dst_port = no_ntohs(tcp->dport);
        pkt->tcp_flags = no_classify_tcp_flags(tcp->flags);
        __u8 doff = (tcp->off_rsvd >> 4) * 4;
        const void *payload = (const __u8 *)l4 + doff;
        pkt->l4_payload = payload <= end ? payload : 0;
        break;
    }
    case PROTO_UDP: {
        const struct no_udphdr *udp = l4;
        if ((const void *)(udp + 1) > end)
            return -1;
        k->src_port = no_ntohs(udp->sport);
        k->dst_port = no_ntohs(udp->dport);
        pkt->l4_payload = (const void *)(udp + 1);
        break;
    }
    case PROTO_SCTP: {
        const __u16 *ports = l4;
        if ((const void *)(ports + 2) > end)
            return -1;
        k->src_port = no_ntohs(ports[0]);
        k->dst_port = no_ntohs(ports[1]);
        break;
    }
    case PROTO_ICMP:
    case PROTO_ICMP6: {
        const __u8 *icmp = l4;
        if (icmp + 2 > (const __u8 *)end)
            return -1;
        k->icmp_type = icmp[0];
        k->icmp_code = icmp[1];
        break;
    }
    default:
        break;
    }
    return 0;
}

/* parse a whole frame from a TC context; returns 0 when the packet is IP */
NO_INLINE int no_parse_packet(struct __sk_buff *skb, struct no_pkt *pkt) {
    const void *data = (const void *)(long)skb->data;
    const void *end = (const void *)(long)skb->data_end;
    const struct no_ethhdr *eth = data;
    if ((const void *)(eth + 1) > end)
        return -1;
    __builtin_memcpy(pkt->src_mac, eth->src, NO_ETH_ALEN);
    __builtin_memcpy(pkt->dst_mac, eth->dst, NO_ETH_ALEN);
    pkt->payload_end = end;
    __u16 proto = no_ntohs(eth->proto);
    pkt->eth_protocol = proto;
    if (proto == ETH_P_IPV4) {
        const struct no_iphdr *ip = (const void *)(eth + 1);
        if ((const void *)(ip + 1) > end)
            return -1;
        no_v4_mapped(pkt->key.src_ip, ip->saddr);
        no_v4_mapped(pkt->key.dst_ip, ip->daddr);
        pkt->dscp = ip->tos >> 2;
        __u8 ihl = (ip->ver_ihl & 0x0F) * 4;
        if (ihl < sizeof(*ip))
            return -1;
        const void *l4 = (const __u8 *)ip + ihl;
        if (l4 > end)
            return -1;
        return no_parse_l4(l4, end, ip->protocol, pkt);
    }
    if (proto == ETH_P_IPV6) {
        const struct no_ip6hdr *ip6 = (const void *)(eth + 1);
        if ((const void *)(ip6 + 1) > end)
            return -1;
        __builtin_memcpy(pkt->key.src_ip, ip6->saddr, 16);
        __builtin_memcpy(pkt->key.dst_ip, ip6->daddr, 16);
        pkt->dscp = (__u8)((no_ntohl(ip6->ver_tc_fl) >> 22) & 0x3F);
        /* no extension-header walk: next_hdr only (same tradeoff as the
         * reference takes on the fast path) */
        return no_parse_l4((const void *)(ip6 + 1), end, ip6->next_hdr, pkt);
    }
    return -1; /* non-IP traffic is not flow-tracked */
}

#endif /* NO_PARSE_H */
