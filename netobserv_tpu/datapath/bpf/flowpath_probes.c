/*
 * flowpath_probes.c — auxiliary kernel hooks feeding the per-CPU feature maps.
 *
 * Each hook fires on its own kernel event and writes partials keyed by the
 * same no_flow_key the TC path uses; userspace merges them at eviction.
 * Behavioral parity targets (each a fresh implementation):
 *   - TCP RTT:          fentry/tcp_rcv_established (bpf/rtt_tracker.h)
 *   - packet drops:     tracepoint/skb/kfree_skb   (bpf/pkt_drops.h)
 *   - network events:   kprobe/psample_sample_packet (bpf/network_events_monitoring.h)
 *   - NAT translation:  kprobe/nf_nat_manip_pkt    (bpf/pkt_translation.h)
 *   - IPsec:            k(ret)probe xfrm_input/xfrm_output (bpf/ipsec.h)
 *   - OpenSSL:          uprobe/SSL_write           (bpf/openssl_tracker.h)
 *
 * BUILD REQUIREMENT: this translation unit needs kernel type info — compile
 * with a distro vmlinux.h + libbpf's bpf_core_read.h on the include path and
 * -DNO_HAVE_VMLINUX. Without them only flowpath.c (the TC datapath) builds;
 * the loader attaches these hooks only when the object carries them, mirroring
 * the reference's optional-hook laddering (pkg/tracer/tracer.go:184-273).
 */
#ifdef NO_HAVE_VMLINUX

#include "vmlinux.h"
#include <bpf/bpf_core_read.h>
#include <bpf/bpf_helpers.h>
#include <bpf/bpf_tracing.h>

#include "records.h"
#include "config.h"
#include "maps.h"

char LICENSE[] SEC("license") = "GPL";

#define PROTO_TCP 6
#define PROTO_UDP 17
#define AF_INET_ 2
#define AF_INET6_ 10

static __always_inline void no_count_probe(__u32 key) {
    __u64 *val = bpf_map_lookup_elem(&global_counters, &key);
    if (val)
        __sync_fetch_and_add(val, 1);
}

static __always_inline __u16 no_sat_add16(__u16 a, __u16 b) {
    __u32 s = (__u32)a + b;
    return s > 0xFFFF ? 0xFFFF : (__u16)s;
}

/* --- shared helpers ------------------------------------------------------ */

static __always_inline void v4_mapped(__u8 *dst16, __be32 addr) {
    __builtin_memset(dst16, 0, 10);
    dst16[10] = 0xFF;
    dst16[11] = 0xFF;
    __builtin_memcpy(dst16 + 12, &addr, 4);
}

/* build a flow key from a struct sock. tcp_rcv_established fires on the
 * RECEIVE path, so the tracked flow's source is the REMOTE endpoint (the TC
 * ingress key) — remote goes in src, local in dst, matching how the TC path
 * keyed this flow. */
static __always_inline int key_from_sock_rx(struct sock *sk,
                                            struct no_flow_key *k) {
    __u16 family = BPF_CORE_READ(sk, __sk_common.skc_family);
    k->proto = PROTO_TCP;
    k->src_port = bpf_ntohs(BPF_CORE_READ(sk, __sk_common.skc_dport));
    k->dst_port = BPF_CORE_READ(sk, __sk_common.skc_num);
    if (family == AF_INET_) {
        v4_mapped(k->src_ip, BPF_CORE_READ(sk, __sk_common.skc_daddr));
        v4_mapped(k->dst_ip, BPF_CORE_READ(sk, __sk_common.skc_rcv_saddr));
        return 0;
    }
    if (family == AF_INET6_) {
        BPF_CORE_READ_INTO(&k->src_ip, sk,
                           __sk_common.skc_v6_daddr.in6_u.u6_addr8);
        BPF_CORE_READ_INTO(&k->dst_ip, sk,
                           __sk_common.skc_v6_rcv_saddr.in6_u.u6_addr8);
        return 0;
    }
    return -1;
}

/* build a flow key by re-parsing an skb's network/transport headers */
static __always_inline int key_from_skb(struct sk_buff *skb,
                                        struct no_flow_key *k,
                                        __u16 *eth_proto, __u16 *flags) {
    unsigned char *head = BPF_CORE_READ(skb, head);
    __u16 nh_off = BPF_CORE_READ(skb, network_header);
    __u16 th_off = BPF_CORE_READ(skb, transport_header);
    __u8 version;
    bpf_probe_read_kernel(&version, 1, head + nh_off);
    version >>= 4;
    __u8 proto = 0;
    if (version == 4) {
        struct iphdr ip;
        bpf_probe_read_kernel(&ip, sizeof(ip), head + nh_off);
        v4_mapped(k->src_ip, ip.saddr);
        v4_mapped(k->dst_ip, ip.daddr);
        proto = ip.protocol;
        *eth_proto = 0x0800;
    } else if (version == 6) {
        struct ipv6hdr ip6;
        bpf_probe_read_kernel(&ip6, sizeof(ip6), head + nh_off);
        bpf_probe_read_kernel(k->src_ip, 16, &ip6.saddr);
        bpf_probe_read_kernel(k->dst_ip, 16, &ip6.daddr);
        proto = ip6.nexthdr;
        *eth_proto = 0x86DD;
    } else {
        return -1;
    }
    k->proto = proto;
    if (proto == PROTO_TCP) {
        struct tcphdr tcp;
        bpf_probe_read_kernel(&tcp, sizeof(tcp), head + th_off);
        k->src_port = bpf_ntohs(tcp.source);
        k->dst_port = bpf_ntohs(tcp.dest);
        if (flags) {
            __u8 *fb = (__u8 *)&tcp + 13;
            *flags = *fb;
        }
    } else if (proto == PROTO_UDP) {
        struct udphdr udp;
        bpf_probe_read_kernel(&udp, sizeof(udp), head + th_off);
        k->src_port = bpf_ntohs(udp.source);
        k->dst_port = bpf_ntohs(udp.dest);
    }
    return 0;
}

/* --- TCP RTT (fentry with kprobe fallback section) ----------------------- */

static __always_inline int handle_rtt(struct sock *sk) {
    if (!cfg_enable_rtt || !no_do_sampling())
        return 0;
    struct no_flow_key k = {};
    if (key_from_sock_rx(sk, &k) != 0)
        return 0;
    struct tcp_sock *ts = (struct tcp_sock *)sk;
    __u32 srtt_us_8 = BPF_CORE_READ(ts, srtt_us);
    __u64 rtt_ns = ((__u64)(srtt_us_8 >> 3)) * 1000;
    __u64 now = bpf_ktime_get_ns();
    struct no_extra_rec *rec = bpf_map_lookup_elem(&flows_extra, &k);
    if (rec) {
        rec->last_seen_ns = now;
        if (rtt_ns > rec->rtt_ns)
            rec->rtt_ns = rtt_ns;
        return 0;
    }
    struct no_extra_rec fresh = {
        .first_seen_ns = now, .last_seen_ns = now, .rtt_ns = rtt_ns,
    };
    bpf_map_update_elem(&flows_extra, &k, &fresh, BPF_ANY);
    return 0;
}

SEC("fentry/tcp_rcv_established")
int BPF_PROG(rtt_fentry, struct sock *sk) { return handle_rtt(sk); }

SEC("kprobe/tcp_rcv_established")
int BPF_KPROBE(rtt_kprobe, struct sock *sk) { return handle_rtt(sk); }

/* --- packet drops (tracepoint skb/kfree_skb) ----------------------------- */

struct kfree_skb_ctx {
    __u64 _pad;
    struct sk_buff *skb;
    void *location;
    unsigned short protocol;
    int reason;
};

SEC("tracepoint/skb/kfree_skb")
int drops_tp(struct kfree_skb_ctx *ctx) {
    if (!cfg_enable_pkt_drops)
        return 0;
    /* reason <= 2 (NOT_SPECIFIED / NO_SOCKET boundary) is routine teardown;
     * filter it before paying the sampling-gate map lookup — this hook fires
     * for every freed skb on the host */
    if (ctx->reason <= 2)
        return 0;
    if (!no_do_sampling())
        return 0;
    struct no_flow_key k = {};
    __u16 eth_proto = 0, flags = 0;
    if (key_from_skb(ctx->skb, &k, &eth_proto, &flags) != 0)
        return 0;
    __u32 len = BPF_CORE_READ(ctx->skb, len);
    __u8 state = 0;
    struct sock *sk = BPF_CORE_READ(ctx->skb, sk);
    if (sk)
        state = BPF_CORE_READ(sk, __sk_common.skc_state);
    __u64 now = bpf_ktime_get_ns();
    struct no_drops_rec *rec = bpf_map_lookup_elem(&flows_drops, &k);
    if (rec) {
        rec->last_seen_ns = now;
        rec->bytes = no_sat_add16(rec->bytes, (__u16)len);
        rec->packets = no_sat_add16(rec->packets, 1);
        rec->latest_cause = ctx->reason;
        rec->latest_flags |= flags;
        rec->latest_state = state;
        return 0;
    }
    struct no_drops_rec fresh = {
        .first_seen_ns = now, .last_seen_ns = now,
        .bytes = (__u16)len, .packets = 1,
        .latest_cause = (__u32)ctx->reason, .latest_flags = flags,
        .eth_protocol = eth_proto, .latest_state = state,
    };
    bpf_map_update_elem(&flows_drops, &k, &fresh, BPF_ANY);
    return 0;
}

/* --- network events (OVN psample cookies) -------------------------------- */

SEC("kprobe/psample_sample_packet")
int BPF_KPROBE(nevents_kprobe, struct psample_group *group,
               struct sk_buff *skb, u32 sample_rate, void *md) {
    if (!cfg_enable_network_events || !no_do_sampling())
        return 0;
    __u32 group_id = BPF_CORE_READ(group, group_num);
    if (group_id != cfg_network_events_group_id) {
        no_count_probe(NO_CTR_NETWORK_EVENTS_ERR_GROUPID_MISMATCH);
        return 0;
    }
    struct no_flow_key k = {};
    __u16 eth_proto = 0;
    if (key_from_skb(skb, &k, &eth_proto, 0) != 0) {
        no_count_probe(NO_CTR_NETWORK_EVENTS_ERR);
        return 0;
    }
    /* the user cookie rides in the metadata; bounded copy */
    __u8 cookie[NO_MAX_EVENT_MD] = {};
    struct psample_metadata *meta = md;
    __u8 cookie_len = BPF_CORE_READ(meta, user_cookie_len);
    if (cookie_len > NO_MAX_EVENT_MD) {
        no_count_probe(NO_CTR_NETWORK_EVENTS_COOKIE_TOO_BIG);
        return 0;
    }
    void *cookie_src = BPF_CORE_READ(meta, user_cookie);
    if (!cookie_src || cookie_len == 0)
        return 0;
    /* read only the cookie's own length — over-reading can fault (zero-fill)
     * or capture trailing garbage that defeats the dedup memcmp */
    bpf_probe_read_kernel(cookie, cookie_len, cookie_src);
    __u32 len = BPF_CORE_READ(skb, len);
    __u64 now = bpf_ktime_get_ns();
    struct no_nevents_rec *rec = bpf_map_lookup_elem(&flows_nevents, &k);
    if (rec) {
        rec->last_seen_ns = now;
        __u8 idx = rec->n_events;
        #pragma unroll
        for (int i = 0; i < NO_MAX_NETWORK_EVENTS; i++) {
            if (__builtin_memcmp(rec->events[i], cookie,
                                 NO_MAX_EVENT_MD) == 0)
                return 0; /* duplicate event metadata */
        }
        if (idx < NO_MAX_NETWORK_EVENTS) {
            __builtin_memcpy(rec->events[idx], cookie, NO_MAX_EVENT_MD);
            rec->bytes[idx] = (__u16)len;
            rec->packets[idx] = 1;
            rec->n_events = idx + 1;
            no_count_probe(NO_CTR_NETWORK_EVENTS_GOOD);
        } else {
            no_count_probe(NO_CTR_NETWORK_EVENTS_OVERFLOW);
        }
        return 0;
    }
    struct no_nevents_rec fresh = {
        .first_seen_ns = now, .last_seen_ns = now,
        .eth_protocol = eth_proto, .n_events = 1,
    };
    __builtin_memcpy(fresh.events[0], cookie, NO_MAX_EVENT_MD);
    fresh.bytes[0] = (__u16)len;
    fresh.packets[0] = 1;
    if (bpf_map_update_elem(&flows_nevents, &k, &fresh, BPF_ANY) != 0)
        no_count_probe(NO_CTR_NETWORK_EVENTS_ERR_UPDATE_MAP_FLOWS);
    else
        no_count_probe(NO_CTR_NETWORK_EVENTS_GOOD);
    return 0;
}

/* --- NAT translation (kprobe nf_nat_manip_pkt) --------------------------- */

SEC("kprobe/nf_nat_manip_pkt")
int BPF_KPROBE(xlat_kprobe, struct sk_buff *skb, struct nf_conn *ct,
               int mtype, int dir) {
    if (!cfg_enable_pkt_translation || !no_do_sampling())
        return 0;
    struct no_flow_key k = {};
    __u16 eth_proto = 0;
    if (key_from_skb(skb, &k, &eth_proto, 0) != 0)
        return 0;
    /* post-NAT endpoints live in the reply-direction conntrack tuple */
    struct nf_conntrack_tuple reply;
    BPF_CORE_READ_INTO(&reply, ct, tuplehash[1].tuple);
    struct no_xlat_rec rec = {};
    __u64 now = bpf_ktime_get_ns();
    rec.first_seen_ns = now;
    rec.last_seen_ns = now;
    rec.eth_protocol = eth_proto;
    if (k.src_ip[10] == 0xFF && k.src_ip[11] == 0xFF) { /* v4 flow */
        v4_mapped(rec.src_ip, reply.dst.u3.ip);
        v4_mapped(rec.dst_ip, reply.src.u3.ip);
    } else {
        bpf_probe_read_kernel(rec.src_ip, 16, &reply.dst.u3.in6);
        bpf_probe_read_kernel(rec.dst_ip, 16, &reply.src.u3.in6);
    }
    rec.src_port = bpf_ntohs(reply.dst.u.all);
    rec.dst_port = bpf_ntohs(reply.src.u.all);
    __u16 zone = BPF_CORE_READ(ct, zone.id);
    rec.zone_id = zone;
    bpf_map_update_elem(&flows_xlat, &k, &rec, BPF_ANY);
    return 0;
}

/* --- IPsec (xfrm entry/return probe pairs) ------------------------------- */

static __always_inline int ipsec_entry(struct sk_buff *skb, void *map) {
    if (!cfg_enable_ipsec || !no_do_sampling())
        return 0;
    struct no_flow_key k = {};
    __u16 eth_proto = 0;
    if (key_from_skb(skb, &k, &eth_proto, 0) != 0)
        return 0;
    __u64 id = bpf_get_current_pid_tgid();
    bpf_map_update_elem(map, &id, &k, BPF_ANY);
    return 0;
}

static __always_inline int ipsec_return(int ret, void *map) {
    if (!cfg_enable_ipsec || !no_do_sampling())
        return 0;
    __u64 id = bpf_get_current_pid_tgid();
    struct no_flow_key *k = bpf_map_lookup_elem(map, &id);
    if (!k)
        return 0;
    __u64 now = bpf_ktime_get_ns();
    struct no_extra_rec *rec = bpf_map_lookup_elem(&flows_extra, k);
    if (rec) {
        rec->last_seen_ns = now;
        if (rec->ipsec_ret < ret) {
            rec->ipsec_ret = ret;
            rec->ipsec_encrypted = ret == 0;
        } else if (rec->ipsec_ret == ret && ret == 0) {
            rec->ipsec_encrypted = 1;
        }
    } else {
        struct no_extra_rec fresh = {
            .first_seen_ns = now, .last_seen_ns = now,
            .ipsec_ret = ret, .ipsec_encrypted = ret == 0,
        };
        bpf_map_update_elem(&flows_extra, k, &fresh, BPF_ANY);
    }
    bpf_map_delete_elem(map, &id);
    return 0;
}

SEC("kprobe/xfrm_input")
int BPF_KPROBE(ipsec_in_entry, struct sk_buff *skb) {
    return ipsec_entry(skb, &ipsec_ingress_inflight);
}

SEC("kretprobe/xfrm_input")
int BPF_KRETPROBE(ipsec_in_return, int ret) {
    return ipsec_return(ret, &ipsec_ingress_inflight);
}

SEC("kprobe/xfrm_output")
int BPF_KPROBE(ipsec_out_entry, struct sock *sk, struct sk_buff *skb) {
    return ipsec_entry(skb, &ipsec_egress_inflight);
}

SEC("kretprobe/xfrm_output")
int BPF_KRETPROBE(ipsec_out_return, int ret) {
    return ipsec_return(ret, &ipsec_egress_inflight);
}

/* --- OpenSSL plaintext (uprobe SSL_write) -------------------------------- */

SEC("uprobe/SSL_write")
int BPF_KPROBE(ssl_write_uprobe, void *ssl, const void *buf, int num) {
    struct no_ssl_event *ev =
        bpf_ringbuf_reserve(&ssl_events, sizeof(*ev), 0);
    if (!ev)
        return 0;
    ev->timestamp_ns = bpf_ktime_get_ns();
    ev->pid_tgid = bpf_get_current_pid_tgid();
    __u32 n = num < 0 ? 0 : (__u32)num;
    if (n > NO_MAX_SSL_DATA)
        n = NO_MAX_SSL_DATA;
    ev->data_len = n;
    ev->ssl_type = 1; /* write direction */
    /* read exactly the caller's length: over-reading past the user buffer
     * either faults (zero-filled payload) or leaks adjacent process memory */
    if (n > 0)
        bpf_probe_read_user(ev->data, n, buf);
    bpf_ringbuf_submit(ev, 0);
    return 0;
}

#endif /* NO_HAVE_VMLINUX */
