/*
 * records.h — layout-pinned record structs shared between the eBPF datapath and
 * the host decoder.
 *
 * CONTRACT: every struct here must match, byte for byte, the numpy dtypes in
 * netobserv_tpu/model/binfmt.py. Parity is machine-checked by
 * tests/test_layout_parity.py, which compiles this header with the host
 * compiler and diffs offsetof/sizeof against the dtypes. All padding is
 * explicit (`__pad*`) so the layout does not depend on compiler packing
 * decisions. Fields carry the machine's native byte order (shared
 * kernel<->user structs; userspace twins in model/binfmt.py are
 * native-endian dtypes, so LE and BE targets both decode correctly).
 *
 * This header is deliberately self-contained (fixed-width types only, no
 * kernel headers) so it can be compiled both by clang -target bpf and by a
 * host compiler for the layout check.
 *
 * Reference-design analog: bpf/types.h in netobserv-ebpf-agent, where the
 * same contract was comment-enforced ("must match byte-by-byte",
 * bpf/types.h:209-215) against Go's pkg/model decoding.
 */
#ifndef NO_RECORDS_H
#define NO_RECORDS_H

#ifdef NO_HOST_BUILD
#include <stdint.h>
typedef uint8_t __u8;
typedef uint16_t __u16;
typedef uint32_t __u32;
typedef uint64_t __u64;
typedef int32_t __s32;
#endif

#define NO_IP_LEN 16
#define NO_ETH_ALEN 6
#define NO_MAX_OBSERVED_INTERFACES 6
#define NO_MAX_NETWORK_EVENTS 4
#define NO_MAX_EVENT_MD 8
#define NO_DNS_NAME_MAX_LEN 32
#define NO_MAX_PAYLOAD_SIZE 256
#define NO_MAX_SSL_DATA (16 * 1024)

/* no_flow_stats.misc_flags bits (reference: bpf/types.h:75) */
#define NO_MISC_SSL_MISMATCH 0x01

/* Flow identity: 5-tuple plus ICMP discriminator. IPv4 addresses are stored
 * v4-in-v6 mapped (::ffff/96, RFC 4038). 40 bytes. */
struct no_flow_key {
    __u8 src_ip[NO_IP_LEN];
    __u8 dst_ip[NO_IP_LEN];
    __u16 src_port;
    __u16 dst_port;
    __u8 proto;
    __u8 icmp_type;
    __u8 icmp_code;
    __u8 __pad0;
};

/* Base per-flow statistics (the aggregated_flows map value). 104 bytes.
 * `lock` is a struct bpf_spin_lock in kernel builds and a plain u32 image on
 * the host side — both are exactly 4 bytes. */
struct no_flow_stats {
    __u64 first_seen_ns; /* bpf_ktime_get_ns() of first packet */
    __u64 last_seen_ns;
    __u64 bytes;
    __u32 packets;
    __u16 eth_protocol;
    __u16 tcp_flags; /* cumulative OR, incl. synthetic SYN_ACK/FIN_ACK/RST_ACK */
    __u8 src_mac[NO_ETH_ALEN];
    __u8 dst_mac[NO_ETH_ALEN];
    __u32 if_index_first;
#ifdef NO_BPF_BUILD
    struct bpf_spin_lock lock;
#else
    __u32 lock;
#endif
    __u32 sampling;
    __u8 direction_first;
    __u8 errno_fallback; /* errno of the failed map insert that forced ringbuf */
    __u8 dscp;
    __u8 n_observed_intf;
    __u8 observed_direction[NO_MAX_OBSERVED_INTERFACES];
    __u8 __pad0[2];
    __u32 observed_intf[NO_MAX_OBSERVED_INTERFACES];
    __u16 ssl_version;
    __u16 tls_cipher_suite;
    __u16 tls_key_share;
    __u8 tls_types;
    __u8 misc_flags;
    __u8 __pad1[4];
};

/* Ringbuffer fallback payload: identity + stats in one blob. 144 bytes. */
struct no_flow_event {
    struct no_flow_key key;
    struct no_flow_stats stats;
};

/* DNS correlation result (per-CPU feature map value). 64 bytes. */
struct no_dns_rec {
    __u64 first_seen_ns;
    __u64 last_seen_ns;
    __u64 latency_ns;
    __u16 dns_id;
    __u16 dns_flags;
    __u16 eth_protocol;
    __u8 errno_code;
    char name[NO_DNS_NAME_MAX_LEN];
    __u8 __pad0[1];
};

/* Packet-drop tracker record. 32 bytes. */
struct no_drops_rec {
    __u64 first_seen_ns;
    __u64 last_seen_ns;
    __u16 bytes;
    __u16 packets;
    __u32 latest_cause;
    __u16 latest_flags;
    __u16 eth_protocol;
    __u8 latest_state;
    __u8 __pad0[3];
};

/* Network-events (psample cookie) record. 72 bytes. */
struct no_nevents_rec {
    __u64 first_seen_ns;
    __u64 last_seen_ns;
    __u8 events[NO_MAX_NETWORK_EVENTS][NO_MAX_EVENT_MD];
    __u16 bytes[NO_MAX_NETWORK_EVENTS];
    __u16 packets[NO_MAX_NETWORK_EVENTS];
    __u16 eth_protocol;
    __u8 n_events;
    __u8 __pad0[5];
};

/* NAT translation record. 56 bytes. */
struct no_xlat_rec {
    __u64 first_seen_ns;
    __u64 last_seen_ns;
    __u8 src_ip[NO_IP_LEN];
    __u8 dst_ip[NO_IP_LEN];
    __u16 src_port;
    __u16 dst_port;
    __u16 zone_id;
    __u16 eth_protocol;
};

/* RTT + IPsec record. 32 bytes. */
struct no_extra_rec {
    __u64 first_seen_ns;
    __u64 last_seen_ns;
    __u64 rtt_ns;
    __s32 ipsec_ret;
    __u16 eth_protocol;
    __u8 ipsec_encrypted;
    __u8 __pad0[1];
};

/* QUIC record. 24 bytes. */
struct no_quic_rec {
    __u64 first_seen_ns;
    __u64 last_seen_ns;
    __u32 version;
    __u16 eth_protocol;
    __u8 seen_long_hdr;
    __u8 seen_short_hdr;
};

/* LPM filter-trie key: prefix length + 16B address (v4 mapped). 20 bytes.
 * Written by the userspace rule compiler (datapath/filter_compile.py). */
struct no_filter_key {
    __u32 prefix_len;
    __u8 ip[NO_IP_LEN];
};

/* One flow-filter rule (LPM trie value). 40 bytes.
 * Written by the userspace rule compiler; matched in bpf/filter.h. */
struct no_filter_rule {
    __u8 proto;
    __u8 icmp_type;
    __u8 icmp_code;
    __u8 direction;      /* 0 ingress, 1 egress, 255 any */
    __u8 action;         /* 0 accept, 1 reject */
    __u8 want_drops;
    __u8 peer_cidr_check;
    __u8 __pad0;
    __u16 dport_start, dport_end, dport1, dport2;
    __u16 sport_start, sport_end, sport1, sport2;
    __u16 port_start, port_end, port1, port2;
    __u16 tcp_flags;
    __u8 __pad1[2];
    __u32 sample_override;
};

/* PCA captured-packet record (packet ringbuf payload). 272 bytes. */
struct no_packet_event {
    __u32 if_index;
    __u32 pkt_len; /* original length; payload truncated at NO_MAX_PAYLOAD_SIZE */
    __u64 timestamp_ns;
    __u8 payload[NO_MAX_PAYLOAD_SIZE];
};

/* OpenSSL-uprobe plaintext event (ssl ringbuf payload). 16408 bytes. */
struct no_ssl_event {
    __u64 timestamp_ns;
    __u64 pid_tgid;
    __s32 data_len;
    __u8 ssl_type;
    __u8 __pad0[3];
    __u8 data[NO_MAX_SSL_DATA];
};

#endif /* NO_RECORDS_H */
