// flowpack — native host-side hot path for the capture plane.
//
// Converts raw flow-event buffers (as drained from kernel maps / ring buffers)
// into the columnar tensors the TPU analytics plane consumes, and merges
// per-CPU feature-map partials. This is the native replacement for the
// reference's per-record decode loop (pkg/model/record.go:227, its hottest
// allocation site) — done as flat array passes instead.
//
// Layout contract: struct definitions come from ../bpf/records.h, the same
// header the eBPF datapath compiles; tests/test_layout_parity.py pins both
// sides against the numpy dtypes.
//
// C ABI only (consumed via ctypes). All output buffers are caller-allocated.

#include <cstdint>
#include <cstring>

#define NO_HOST_BUILD 1
#include "../bpf/records.h"

extern "C" {

// Column pointers for fp_pack. Each points at a caller-allocated array of
// capacity >= n rows (keys: n*10 u32, row-major).
struct fp_columns {
    uint32_t *keys;        // [n][10] packed key words
    uint64_t *bytes;       // [n]
    uint32_t *packets;     // [n]
    uint32_t *tcp_flags;   // [n]
    uint32_t *eth_protocol;// [n]
    uint32_t *direction;   // [n]
    uint32_t *if_index;    // [n]
    uint32_t *dscp;        // [n]
    uint32_t *sampling;    // [n]
    uint64_t *first_seen_ns; // [n]
    uint64_t *last_seen_ns;  // [n]
};

// Pack n contiguous no_flow_event records into columns. Returns n.
size_t fp_pack(const uint8_t *events, size_t n, struct fp_columns *out) {
    const struct no_flow_event *ev =
        reinterpret_cast<const struct no_flow_event *>(events);
    for (size_t i = 0; i < n; i++) {
        const struct no_flow_key *k = &ev[i].key;
        const struct no_flow_stats *s = &ev[i].stats;
        uint32_t *kw = out->keys + i * 10;
        std::memcpy(kw, k->src_ip, 16);      // words 0..3
        std::memcpy(kw + 4, k->dst_ip, 16);  // words 4..7
        kw[8] = (static_cast<uint32_t>(k->src_port) << 16) | k->dst_port;
        kw[9] = (static_cast<uint32_t>(k->proto) << 16) |
                (static_cast<uint32_t>(k->icmp_type) << 8) | k->icmp_code;
        out->bytes[i] = s->bytes;
        out->packets[i] = s->packets;
        out->tcp_flags[i] = s->tcp_flags;
        out->eth_protocol[i] = s->eth_protocol;
        out->direction[i] = s->direction_first;
        out->if_index[i] = s->if_index_first;
        out->dscp[i] = s->dscp;
        out->sampling[i] = s->sampling;
        out->first_seen_ns[i] = s->first_seen_ns;
        out->last_seen_ns[i] = s->last_seen_ns;
    }
    return n;
}

// Dense TPU feed: one (batch_size, FP_DENSE_WORDS) u32 row-major array per
// batch instead of six column arrays — a single host->device transfer on a
// tunneled/PCIe link instead of six round trips, and a single pass over the
// raw event bytes (no intermediate FlowBatch, no Python copies). Row layout
// (must match flowpack.py pack_dense/DENSE_WORDS and the device-side unpack
// in sketch/state.py dense_to_arrays):
//   words 0..9   packed key words (same packing as fp_pack)
//   word  10     bytes as float32 bitcast (sketch planes are f32)
//   word  11     packets
//   word  12     rtt_us        (from the extra record, else 0)
//   word  13     dns_latency_us (from the dns record, else 0)
//   word  14     valid flag (1 for live rows; padding rows are all-zero)
//   word  15     sampling
//   word  16     tcp_flags | dscp << 16 | markers << 24
//                (markers: bit0 QUIC seen, bit1 NAT translation observed,
//                 bit2 IPsec encrypted, bit3 IPsec error)
//   word  17     drop bytes | drop packets << 16   (from the drops record)
//   word  18     drop latest_cause (low u16) | latest_state << 16
//   word  19     reserved (0)
#define FP_DENSE_WORDS 20

static inline uint8_t feature_markers(const struct no_extra_rec *ex,
                                      const struct no_xlat_rec *xl,
                                      const struct no_quic_rec *qc,
                                      size_t i) {
    uint8_t m = 0;
    if (qc && (qc[i].version || qc[i].seen_long_hdr || qc[i].seen_short_hdr))
        m |= 1;
    if (xl) {
        // complete translation = both endpoints observed (fp_merge_xlat rule)
        bool src_set = false, dst_set = false;
        for (int b = 0; b < NO_IP_LEN; b++) {
            if (xl[i].src_ip[b]) src_set = true;
            if (xl[i].dst_ip[b]) dst_set = true;
        }
        if (src_set && dst_set) m |= 2;
    }
    if (ex && ex[i].ipsec_encrypted) m |= 4;
    if (ex && ex[i].ipsec_ret != 0) m |= 8;
    return m;
}

static inline void fill_feature_words(const struct no_flow_stats *s,
                                      const struct no_extra_rec *ex,
                                      const struct no_xlat_rec *xl,
                                      const struct no_quic_rec *qc,
                                      const struct no_drops_rec *dr,
                                      size_t i, uint32_t *w16) {
    w16[0] = (s->tcp_flags & 0xFFFFu) |
             (static_cast<uint32_t>(s->dscp & 0xFFu) << 16) |
             (static_cast<uint32_t>(feature_markers(ex, xl, qc, i)) << 24);
    w16[1] = dr ? (static_cast<uint32_t>(dr[i].bytes) |
                   (static_cast<uint32_t>(dr[i].packets) << 16))
                : 0;
    // saturate, don't mask: subsystem drop reasons (kernel >= 6.0) carry
    // the subsystem in bits 16+ — masking would alias them onto unrelated
    // core reasons; saturation lands them in the histogram's overflow bucket
    uint32_t cause = dr ? dr[i].latest_cause : 0;
    if (cause > 0xFFFFu) cause = 0xFFFFu;
    w16[2] = dr ? (cause | (static_cast<uint32_t>(dr[i].latest_state) << 16))
                : 0;
    w16[3] = 0;
}

void fp_pack_dense(const uint8_t *events, size_t n,
                   const uint8_t *extra, const uint8_t *dns,
                   const uint8_t *drops, const uint8_t *xlat,
                   const uint8_t *quic,
                   uint32_t *out, size_t batch_size) {
    const struct no_flow_event *ev =
        reinterpret_cast<const struct no_flow_event *>(events);
    const struct no_extra_rec *ex =
        reinterpret_cast<const struct no_extra_rec *>(extra);
    const struct no_dns_rec *dn =
        reinterpret_cast<const struct no_dns_rec *>(dns);
    const struct no_drops_rec *dr =
        reinterpret_cast<const struct no_drops_rec *>(drops);
    const struct no_xlat_rec *xl =
        reinterpret_cast<const struct no_xlat_rec *>(xlat);
    const struct no_quic_rec *qc =
        reinterpret_cast<const struct no_quic_rec *>(quic);
    for (size_t i = 0; i < n; i++) {
        const struct no_flow_key *k = &ev[i].key;
        const struct no_flow_stats *s = &ev[i].stats;
        uint32_t *row = out + i * FP_DENSE_WORDS;
        std::memcpy(row, k->src_ip, 16);      // words 0..3
        std::memcpy(row + 4, k->dst_ip, 16);  // words 4..7
        row[8] = (static_cast<uint32_t>(k->src_port) << 16) | k->dst_port;
        row[9] = (static_cast<uint32_t>(k->proto) << 16) |
                 (static_cast<uint32_t>(k->icmp_type) << 8) | k->icmp_code;
        float b = static_cast<float>(s->bytes);
        std::memcpy(&row[10], &b, 4);
        row[11] = s->packets;
        row[12] = ex ? static_cast<uint32_t>(ex[i].rtt_ns / 1000) : 0;
        row[13] = dn ? static_cast<uint32_t>(dn[i].latency_ns / 1000) : 0;
        row[14] = 1;
        row[15] = s->sampling;
        fill_feature_words(s, ex, xl, qc, dr, i, row + 16);
    }
    if (n < batch_size)
        std::memset(out + n * FP_DENSE_WORDS, 0,
                    (batch_size - n) * FP_DENSE_WORDS * sizeof(uint32_t));
}

// Compact TPU feed: the host->device link (not compute) bounds the host
// path, so shrink bytes/record. IPv4 flows (v4-in-v6 mapped keys, RFC 4038
// — the common case) collapse their 10 key words to 4; non-v4 rows — and
// rows carrying DROP data, which are rare outside drop storms — spill to a
// small full-width (FP_DENSE_WORDS) side lane. One flat buffer:
//   [batch_size * 10 compact words | spill_cap * 20 dense words]
// Compact row (must match sketch/state.py compact_to_arrays):
//   w0 src_v4 (key word 3)   w1 dst_v4 (key word 7)   w2 ports (src<<16|dst)
//   w3 bit31 = valid, low 24 = proto<<16|icmp_type<<8|icmp_code
//   w4 bytes f32 bitcast     w5 packets     w6 rtt_us     w7 dns_latency_us
//   w8 sampling              w9 tcp_flags | dscp << 16 | markers << 24
// Returns the number of spill rows used, or -1 if spill_cap would overflow
// (caller falls back to the full dense pack for that batch).
#define FP_COMPACT_WORDS 10
#define FP_V4_PREFIX_WORD2 0xffff0000u  // bytes 8..11 of a mapped address

static inline bool is_v4_mapped(const uint8_t *ip16) {
    uint32_t w0, w1, w2;
    std::memcpy(&w0, ip16, 4);
    std::memcpy(&w1, ip16 + 4, 4);
    std::memcpy(&w2, ip16 + 8, 4);
    return w0 == 0 && w1 == 0 && w2 == FP_V4_PREFIX_WORD2;
}

int fp_pack_compact(const uint8_t *events, size_t n,
                    const uint8_t *extra, const uint8_t *dns,
                    const uint8_t *drops, const uint8_t *xlat,
                    const uint8_t *quic,
                    uint32_t *out, size_t batch_size, size_t spill_cap) {
    const struct no_flow_event *ev =
        reinterpret_cast<const struct no_flow_event *>(events);
    const struct no_extra_rec *ex =
        reinterpret_cast<const struct no_extra_rec *>(extra);
    const struct no_dns_rec *dn =
        reinterpret_cast<const struct no_dns_rec *>(dns);
    const struct no_drops_rec *dr =
        reinterpret_cast<const struct no_drops_rec *>(drops);
    const struct no_xlat_rec *xl =
        reinterpret_cast<const struct no_xlat_rec *>(xlat);
    const struct no_quic_rec *qc =
        reinterpret_cast<const struct no_quic_rec *>(quic);
    uint32_t *spill = out + batch_size * FP_COMPACT_WORDS;
    size_t nc = 0, ns = 0;
    for (size_t i = 0; i < n; i++) {
        const struct no_flow_key *k = &ev[i].key;
        const struct no_flow_stats *s = &ev[i].stats;
        uint32_t rtt = ex ? static_cast<uint32_t>(ex[i].rtt_ns / 1000) : 0;
        uint32_t dlat = dn ? static_cast<uint32_t>(dn[i].latency_ns / 1000) : 0;
        bool has_drops = dr && (dr[i].bytes || dr[i].packets);
        if (!has_drops && is_v4_mapped(k->src_ip) && is_v4_mapped(k->dst_ip)) {
            uint32_t *row = out + nc * FP_COMPACT_WORDS;
            std::memcpy(&row[0], k->src_ip + 12, 4);
            std::memcpy(&row[1], k->dst_ip + 12, 4);
            row[2] = (static_cast<uint32_t>(k->src_port) << 16) | k->dst_port;
            row[3] = 0x80000000u | (static_cast<uint32_t>(k->proto) << 16) |
                     (static_cast<uint32_t>(k->icmp_type) << 8) | k->icmp_code;
            float b = static_cast<float>(s->bytes);
            std::memcpy(&row[4], &b, 4);
            row[5] = s->packets;
            row[6] = rtt;
            row[7] = dlat;
            row[8] = s->sampling;
            row[9] = (s->tcp_flags & 0xFFFFu) |
                     (static_cast<uint32_t>(s->dscp & 0xFFu) << 16) |
                     (static_cast<uint32_t>(feature_markers(ex, xl, qc, i))
                      << 24);
            nc++;
        } else {
            if (ns >= spill_cap)
                return -1;
            uint32_t *row = spill + ns * FP_DENSE_WORDS;
            std::memcpy(row, k->src_ip, 16);
            std::memcpy(row + 4, k->dst_ip, 16);
            row[8] = (static_cast<uint32_t>(k->src_port) << 16) | k->dst_port;
            row[9] = (static_cast<uint32_t>(k->proto) << 16) |
                     (static_cast<uint32_t>(k->icmp_type) << 8) | k->icmp_code;
            float b = static_cast<float>(s->bytes);
            std::memcpy(&row[10], &b, 4);
            row[11] = s->packets;
            row[12] = rtt;
            row[13] = dlat;
            row[14] = 1;
            row[15] = s->sampling;
            fill_feature_words(s, ex, xl, qc, dr, i, row + 16);
            ns++;
        }
    }
    if (nc < batch_size)
        std::memset(out + nc * FP_COMPACT_WORDS, 0,
                    (batch_size - nc) * FP_COMPACT_WORDS * sizeof(uint32_t));
    if (ns < spill_cap)
        std::memset(spill + ns * FP_DENSE_WORDS, 0,
                    (spill_cap - ns) * FP_DENSE_WORDS * sizeof(uint32_t));
    return static_cast<int>(ns);
}

// ---------------------------------------------------------------------------
// Resident-key feed: the lowest-bytes-per-record TPU feed. The host keeps a
// key -> slot dictionary (this file); the DEVICE keeps a (slot_cap, 10) u32
// key table in HBM, updated from the new-key lane and gathered by slot id —
// steady-state records ship as THREE words instead of ten (the transfer
// link, not compute, bounds the host path; see docs/tpu_sketch.md byte
// budget). Flat buffer layout (must match sketch/state.py
// resident_to_arrays and flowpack.py pack_resident):
//   [0..3]    header: w0 default sampling, w1 n_newkey, w2 n_spill,
//             w3 n_dns | n_drop << 16   (w1..w3 diagnostic only)
//   hot lane    batch_size * 3 words:
//     w0  bit31 valid | bits 28..30 rtt exp | bits 20..27 rtt mant
//         | bits 0..19 slot id          (rtt_us ~= mant << (2*exp))
//     w1  bytes as float32 bitcast
//     w2  packets (bits 0..10) | tcp_flags (11..21) | dscp (22..27)
//         | markers (28..31)
//   dns lane    dns_cap words:  row_idx << 16 | dns code
//         (code: bits 12..15 exp e, bits 0..11 mant m; value_us = m << e)
//   drop lane   drop_cap * 2 words:
//     w0  row_idx << 16 | latest_cause (saturated u16)
//     w1  drop packets << 16 | drop bytes
//   newkey lane nk_cap * 11 words: w0 = bit31 | slot id, w1..w10 key words
//   spill lane  spill_cap * FP_DENSE_WORDS dense rows (anything the hot
//               row can't carry exactly: packets/flags overflow, sampling
//               mismatch, rtt beyond the code range, lane overflows)
//
// fp_pack_resident packs events[start..n) until the hot lane or the spill
// lane fills, and returns the number of rows CONSUMED — partial packing
// with continuation: the caller ships the (always self-consistent) prefix
// and packs the remainder into the next buffer, so the dictionary and the
// device table learn monotonically even under cold-start floods (no
// rollback, no dense fallback). A full dictionary is the caller's policy
// decision: reset it between calls — stale device-table rows are harmless
// because every live slot is redefined through the new-key lane before any
// hot row references it. Lane counts land in header words 1..3.
#define FP_HOT_WORDS 3
#define FP_RESIDENT_HDR 4
#define FP_NK_WORDS 11
#define FP_SLOT_MASK 0xFFFFFu
#define FP_RTT_MAX_US (0xFFu << 14)

// 16-byte entries: a 64-bit key FINGERPRINT instead of the 40-byte key.
// The table must be probed once per record at line rate; 48-byte entries
// made every probe a random DRAM access (measured 9.8M rec/s on the pack
// loop). A fingerprint collision (p ~ n^2/2^65 — ~1e-6 at a full 2^18
// table) maps a new flow onto an existing slot: its records fold under
// that slot's key words, a bounded mis-attribution of the same order as a
// Count-Min collision. The sketch plane hashes the (gathered) key words
// themselves, so nothing downstream amplifies it.
struct fp_dict_entry {
    uint64_t fp;       // 0 = empty (fingerprints of 0 are remapped to 1)
    uint32_t slot;
    uint32_t pad_;
};

struct fp_dict {
    struct fp_dict_entry *tab;
    size_t mask;       // hash table size - 1 (power of two)
    uint32_t slot_cap;
    uint32_t next_slot;
};

static inline uint64_t key_fp64(const uint32_t *kw) {
    // 40 key bytes = 5 u64 lanes; murmur-style mix per lane + finalizer
    uint64_t h = 0x9E3779B97F4A7C15ull;
    for (int i = 0; i < 5; i++) {
        uint64_t k;
        std::memcpy(&k, reinterpret_cast<const uint8_t *>(kw) + i * 8, 8);
        k *= 0xC2B2AE3D27D4EB4Full;
        k = (k << 31) | (k >> 33);
        k *= 0x9E3779B185EBCA87ull;
        h ^= k;
        h = ((h << 27) | (h >> 37)) * 5 + 0x52DCE729ull;
    }
    h ^= h >> 33;
    h *= 0xFF51AFD7ED558CCDull;
    h ^= h >> 33;
    h *= 0xC4CEB9FE1A85EC53ull;
    h ^= h >> 33;
    return h ? h : 1;
}

void *fp_dict_new(uint32_t slot_cap) {
    if (slot_cap == 0 || slot_cap > (FP_SLOT_MASK + 1))
        return nullptr;
    size_t cap = 1;
    while (cap < static_cast<size_t>(slot_cap) * 2)
        cap <<= 1;
    fp_dict *d = new fp_dict;
    d->tab = new fp_dict_entry[cap]();
    d->mask = cap - 1;
    d->slot_cap = slot_cap;
    d->next_slot = 0;
    return d;
}

void fp_dict_free(void *h) {
    if (!h) return;
    fp_dict *d = static_cast<fp_dict *>(h);
    delete[] d->tab;
    delete d;
}

void fp_dict_reset(void *h) {
    fp_dict *d = static_cast<fp_dict *>(h);
    std::memset(d->tab, 0, (d->mask + 1) * sizeof(fp_dict_entry));
    d->next_slot = 0;
}

uint32_t fp_dict_count(void *h) {
    return static_cast<fp_dict *>(h)->next_slot;
}

// Find the fingerprint's hash-table index; *found says whether it's there.
static inline size_t dict_probe(const fp_dict *d, uint64_t fp, bool *found) {
    size_t i = fp & d->mask;
    for (;;) {
        const fp_dict_entry *e = &d->tab[i];
        if (!e->fp) {
            *found = false;
            return i;
        }
        if (e->fp == fp) {
            *found = true;
            return i;
        }
        i = (i + 1) & d->mask;
    }
}

static inline void make_kw(const struct no_flow_key *k, uint32_t *kw) {
    std::memcpy(kw, k->src_ip, 16);
    std::memcpy(kw + 4, k->dst_ip, 16);
    kw[8] = (static_cast<uint32_t>(k->src_port) << 16) | k->dst_port;
    kw[9] = (static_cast<uint32_t>(k->proto) << 16) |
            (static_cast<uint32_t>(k->icmp_type) << 8) | k->icmp_code;
}

static inline uint32_t rtt_code11(uint32_t rtt_us) {
    // bits 0..7 mantissa, bits 8..10 exponent; value ~= m << (2*e)
    uint32_t e = 0;
    while ((rtt_us >> (2 * e)) > 0xFFu)
        e++;
    return ((rtt_us >> (2 * e)) & 0xFFu) | (e << 8);
}

static inline uint32_t lat_code16(uint64_t us) {
    // bits 0..11 mantissa, bits 12..15 exponent; value ~= m << e
    uint32_t e = 0;
    while ((us >> e) > 0xFFFu && e < 15)
        e++;
    uint64_t m = us >> e;
    if (m > 0xFFFu) m = 0xFFFu;  // saturate at ~134s
    return static_cast<uint32_t>(m) | (e << 12);
}

int64_t fp_pack_resident(const uint8_t *events, size_t start, size_t n,
                         const uint8_t *extra, const uint8_t *dns,
                         const uint8_t *drops, const uint8_t *xlat,
                         const uint8_t *quic,
                         void *dict_h, uint32_t *out, size_t batch_size,
                         size_t dns_cap, size_t drop_cap, size_t nk_cap,
                         size_t spill_cap) {
    fp_dict *d = static_cast<fp_dict *>(dict_h);
    const struct no_flow_event *ev =
        reinterpret_cast<const struct no_flow_event *>(events);
    const struct no_extra_rec *ex =
        reinterpret_cast<const struct no_extra_rec *>(extra);
    const struct no_dns_rec *dn =
        reinterpret_cast<const struct no_dns_rec *>(dns);
    const struct no_drops_rec *dr =
        reinterpret_cast<const struct no_drops_rec *>(drops);
    const struct no_xlat_rec *xl =
        reinterpret_cast<const struct no_xlat_rec *>(xlat);
    const struct no_quic_rec *qc =
        reinterpret_cast<const struct no_quic_rec *>(quic);
    uint32_t *hot = out + FP_RESIDENT_HDR;
    uint32_t *dnsl = hot + batch_size * FP_HOT_WORDS;
    uint32_t *dropl = dnsl + dns_cap;
    uint32_t *nkl = dropl + drop_cap * 2;
    uint32_t *spill = nkl + nk_cap * FP_NK_WORDS;
    size_t nh = 0, nd = 0, nr = 0, nk = 0, ns = 0;
    uint32_t def_sampling = start < n ? ev[start].stats.sampling : 0;

    // fingerprint lookahead pipeline: compute row i+PF's fingerprint and
    // prefetch its table line while processing row i — the probe is a
    // random access into a multi-MB table, and exposed DRAM latency was
    // the pack loop's measured bottleneck
    enum { PF = 16 };
    uint64_t fpbuf[PF];
    for (size_t j = start; j < n && j < start + PF; j++) {
        uint32_t kwp[10];
        make_kw(&ev[j].key, kwp);
        fpbuf[j % PF] = key_fp64(kwp);
        __builtin_prefetch(&d->tab[fpbuf[j % PF] & d->mask]);
    }
    size_t i = start;
    for (; i < n && nh < batch_size; i++) {
        const struct no_flow_key *k = &ev[i].key;
        const struct no_flow_stats *s = &ev[i].stats;
        // row i's fingerprint FIRST: the ring slot is about to be reused
        // for row i+PF
        uint64_t fp = fpbuf[i % PF];
        if (i + PF < n) {
            uint32_t kwp[10];
            make_kw(&ev[i + PF].key, kwp);
            fpbuf[(i + PF) % PF] = key_fp64(kwp);
            __builtin_prefetch(&d->tab[fpbuf[(i + PF) % PF] & d->mask]);
        }
        uint32_t kw[10];
        make_kw(k, kw);
        // ensure the key has a slot (insert through the new-key lane);
        // nk-lane or dictionary exhaustion just routes the row to spill —
        // the key is learned by a later chunk
        bool found;
        size_t hi = dict_probe(d, fp, &found);
        bool have_slot = found;
        uint32_t slot = found ? d->tab[hi].slot : 0;
        if (!found && nk < nk_cap && d->next_slot < d->slot_cap) {
            slot = d->next_slot++;
            d->tab[hi].fp = fp;
            d->tab[hi].slot = slot;
            uint32_t *row = nkl + nk * FP_NK_WORDS;
            row[0] = 0x80000000u | slot;
            std::memcpy(row + 1, kw, 40);
            nk++;
            have_slot = true;
        }
        uint32_t rtt = ex ? static_cast<uint32_t>(ex[i].rtt_ns / 1000) : 0;
        uint64_t dlat = dn ? dn[i].latency_ns / 1000 : 0;
        bool has_drops = dr && (dr[i].bytes || dr[i].packets);
        bool hot_ok = have_slot && s->packets < 0x800 &&
                      s->tcp_flags < 0x800 && s->dscp < 0x40 &&
                      s->sampling == def_sampling && rtt <= FP_RTT_MAX_US &&
                      (!dlat || nd < dns_cap) &&
                      (!has_drops || nr < drop_cap);
        if (hot_ok) {
            uint32_t *row = hot + nh * FP_HOT_WORDS;
            row[0] = 0x80000000u | (rtt_code11(rtt) << 20) | slot;
            float b = static_cast<float>(s->bytes);
            std::memcpy(&row[1], &b, 4);
            row[2] = (s->packets & 0x7FFu) |
                     (static_cast<uint32_t>(s->tcp_flags & 0x7FFu) << 11) |
                     (static_cast<uint32_t>(s->dscp & 0x3Fu) << 22) |
                     (static_cast<uint32_t>(feature_markers(ex, xl, qc, i))
                      << 28);
            if (dlat) {
                dnsl[nd++] = (static_cast<uint32_t>(nh) << 16) |
                             lat_code16(dlat);
            }
            if (has_drops) {
                uint32_t cause = dr[i].latest_cause;
                if (cause > 0xFFFFu) cause = 0xFFFFu;
                uint32_t *de = dropl + nr * 2;
                de[0] = (static_cast<uint32_t>(nh) << 16) | cause;
                de[1] = (static_cast<uint32_t>(dr[i].packets) << 16) |
                        dr[i].bytes;
                nr++;
            }
            nh++;
        } else {
            if (ns >= spill_cap)
                break;  // chunk full: caller continues from row i
            uint32_t *row = spill + ns * FP_DENSE_WORDS;
            std::memcpy(row, kw, 40);
            float b = static_cast<float>(s->bytes);
            std::memcpy(&row[10], &b, 4);
            row[11] = s->packets;
            row[12] = rtt;
            row[13] = static_cast<uint32_t>(dlat);
            row[14] = 1;
            row[15] = s->sampling;
            fill_feature_words(s, ex, xl, qc, dr, i, row + 16);
            ns++;
        }
    }
    out[0] = def_sampling;
    out[1] = static_cast<uint32_t>(nk);
    out[2] = static_cast<uint32_t>(ns);
    out[3] = static_cast<uint32_t>(nd) | (static_cast<uint32_t>(nr) << 16);
    if (nh < batch_size)
        std::memset(hot + nh * FP_HOT_WORDS, 0,
                    (batch_size - nh) * FP_HOT_WORDS * sizeof(uint32_t));
    if (nd < dns_cap)
        std::memset(dnsl + nd, 0, (dns_cap - nd) * sizeof(uint32_t));
    if (nr < drop_cap)
        std::memset(dropl + nr * 2, 0, (drop_cap - nr) * 2 * sizeof(uint32_t));
    if (nk < nk_cap)
        std::memset(nkl + nk * FP_NK_WORDS, 0,
                    (nk_cap - nk) * FP_NK_WORDS * sizeof(uint32_t));
    if (ns < spill_cap)
        std::memset(spill + ns * FP_DENSE_WORDS, 0,
                    (spill_cap - ns) * FP_DENSE_WORDS * sizeof(uint32_t));
    return static_cast<int64_t>(i - start);
}

static inline void merge_times(uint64_t *dfirst, uint64_t *dlast,
                               uint64_t sfirst, uint64_t slast) {
    if (*dfirst == 0 || (sfirst != 0 && sfirst < *dfirst))
        *dfirst = sfirst;
    if (slast > *dlast)
        *dlast = slast;
}

static inline uint16_t sat_add16(uint16_t a, uint16_t b) {
    uint32_t s = static_cast<uint32_t>(a) + b;
    return s > 0xFFFF ? 0xFFFF : static_cast<uint16_t>(s);
}

// Merge per-CPU partials of the base stats struct.
// values: n_cpu consecutive no_flow_stats images for ONE map entry.
// out: one no_flow_stats. Mirrors model/accumulate.py accumulate_base.
void fp_merge_stats(const uint8_t *values, size_t n_cpu, uint8_t *out_buf) {
    struct no_flow_stats out;
    std::memcpy(&out, values, sizeof(out));
    // the datapath's lock-free slot reservation can leave the counter
    // TRANSIENTLY above capacity (saturation undo in flight) — clamp before
    // any indexing
    if (out.n_observed_intf > NO_MAX_OBSERVED_INTERFACES)
        out.n_observed_intf = NO_MAX_OBSERVED_INTERFACES;
    const struct no_flow_stats *v =
        reinterpret_cast<const struct no_flow_stats *>(values);
    for (size_t c = 1; c < n_cpu; c++) {
        const struct no_flow_stats *s = &v[c];
        bool dst_empty = out.first_seen_ns == 0 && out.packets == 0;
        merge_times(&out.first_seen_ns, &out.last_seen_ns,
                    s->first_seen_ns, s->last_seen_ns);
        uint64_t nb = out.bytes + s->bytes;
        out.bytes = nb < out.bytes ? UINT64_MAX : nb;  // saturate on wrap
        uint64_t np = static_cast<uint64_t>(out.packets) + s->packets;
        out.packets = np > UINT32_MAX ? UINT32_MAX
                                      : static_cast<uint32_t>(np);
        out.tcp_flags |= s->tcp_flags;
        if (s->eth_protocol) out.eth_protocol = s->eth_protocol;
        if (s->dscp) out.dscp = s->dscp;
        if (s->sampling) out.sampling = s->sampling;
        if (s->errno_fallback) out.errno_fallback = s->errno_fallback;
        bool src_mac_zero = true, dst_mac_zero = true;
        for (int i = 0; i < NO_ETH_ALEN; i++) {
            if (out.src_mac[i]) src_mac_zero = false;
            if (out.dst_mac[i]) dst_mac_zero = false;
        }
        if (src_mac_zero) std::memcpy(out.src_mac, s->src_mac, NO_ETH_ALEN);
        if (dst_mac_zero) std::memcpy(out.dst_mac, s->dst_mac, NO_ETH_ALEN);
        if (dst_empty) {
            out.if_index_first = s->if_index_first;
            out.direction_first = s->direction_first;
        }
        // ssl_version: first non-zero wins; a conflicting later version sets
        // the mismatch flag (mirrors accumulate_base / kernel entry rule)
        if (s->ssl_version) {
            if (out.ssl_version == 0)
                out.ssl_version = s->ssl_version;
            else if (out.ssl_version != s->ssl_version)
                out.misc_flags |= NO_MISC_SSL_MISMATCH;
        }
        if (s->tls_cipher_suite) out.tls_cipher_suite = s->tls_cipher_suite;
        if (s->tls_key_share) out.tls_key_share = s->tls_key_share;
        out.tls_types |= s->tls_types;
        out.misc_flags |= s->misc_flags;
        int ns_obs = s->n_observed_intf > NO_MAX_OBSERVED_INTERFACES
                         ? NO_MAX_OBSERVED_INTERFACES
                         : s->n_observed_intf;
        for (int j = 0; j < ns_obs; j++) {
            bool seen = false;
            for (int i = 0; i < out.n_observed_intf; i++) {
                if (out.observed_intf[i] == s->observed_intf[j] &&
                    out.observed_direction[i] == s->observed_direction[j]) {
                    seen = true;
                    break;
                }
            }
            if (!seen && out.n_observed_intf < NO_MAX_OBSERVED_INTERFACES) {
                out.observed_intf[out.n_observed_intf] = s->observed_intf[j];
                out.observed_direction[out.n_observed_intf] =
                    s->observed_direction[j];
                out.n_observed_intf++;
            }
        }
    }
    std::memcpy(out_buf, &out, sizeof(out));
}

// Merge per-CPU partials of the extra (rtt/ipsec) record.
void fp_merge_extra(const uint8_t *values, size_t n_cpu, uint8_t *out_buf) {
    struct no_extra_rec out;
    std::memcpy(&out, values, sizeof(out));
    const struct no_extra_rec *v =
        reinterpret_cast<const struct no_extra_rec *>(values);
    for (size_t c = 1; c < n_cpu; c++) {
        const struct no_extra_rec *s = &v[c];
        merge_times(&out.first_seen_ns, &out.last_seen_ns,
                    s->first_seen_ns, s->last_seen_ns);
        if (s->rtt_ns > out.rtt_ns) out.rtt_ns = s->rtt_ns;
        if (out.ipsec_ret < s->ipsec_ret) {
            out.ipsec_ret = s->ipsec_ret;
            out.ipsec_encrypted = s->ipsec_encrypted;
        } else if (out.ipsec_ret == s->ipsec_ret && s->ipsec_encrypted) {
            out.ipsec_encrypted = s->ipsec_encrypted;
        }
    }
    std::memcpy(out_buf, &out, sizeof(out));
}

// Merge per-CPU partials of the drops record.
void fp_merge_drops(const uint8_t *values, size_t n_cpu, uint8_t *out_buf) {
    struct no_drops_rec out;
    std::memcpy(&out, values, sizeof(out));
    const struct no_drops_rec *v =
        reinterpret_cast<const struct no_drops_rec *>(values);
    for (size_t c = 1; c < n_cpu; c++) {
        const struct no_drops_rec *s = &v[c];
        merge_times(&out.first_seen_ns, &out.last_seen_ns,
                    s->first_seen_ns, s->last_seen_ns);
        out.bytes = sat_add16(out.bytes, s->bytes);
        out.packets = sat_add16(out.packets, s->packets);
        out.latest_flags |= s->latest_flags;
        if (s->latest_cause) out.latest_cause = s->latest_cause;
        if (s->latest_state) out.latest_state = s->latest_state;
    }
    std::memcpy(out_buf, &out, sizeof(out));
}

// Merge per-CPU partials of the DNS record (max latency wins).
void fp_merge_dns(const uint8_t *values, size_t n_cpu, uint8_t *out_buf) {
    struct no_dns_rec out;
    std::memcpy(&out, values, sizeof(out));
    const struct no_dns_rec *v =
        reinterpret_cast<const struct no_dns_rec *>(values);
    for (size_t c = 1; c < n_cpu; c++) {
        const struct no_dns_rec *s = &v[c];
        merge_times(&out.first_seen_ns, &out.last_seen_ns,
                    s->first_seen_ns, s->last_seen_ns);
        out.dns_flags |= s->dns_flags;
        if (s->dns_id) out.dns_id = s->dns_id;
        if (out.errno_code != s->errno_code) out.errno_code = s->errno_code;
        if (s->latency_ns > out.latency_ns) out.latency_ns = s->latency_ns;
        if (s->name[0]) std::memcpy(out.name, s->name, NO_DNS_NAME_MAX_LEN);
    }
    std::memcpy(out_buf, &out, sizeof(out));
}

// Merge per-CPU partials of the network-events record: dedup-append into a
// wrapping ring of NO_MAX_NETWORK_EVENTS slots (n_events is the ring CURSOR,
// not a count — renderers scan slots keyed on packets[i] != 0). Mirrors
// model/accumulate.py accumulate_network_events.
void fp_merge_nevents(const uint8_t *values, size_t n_cpu, uint8_t *out_buf) {
    struct no_nevents_rec out;
    std::memcpy(&out, values, sizeof(out));
    const struct no_nevents_rec *v =
        reinterpret_cast<const struct no_nevents_rec *>(values);
    for (size_t c = 1; c < n_cpu; c++) {
        const struct no_nevents_rec *s = &v[c];
        merge_times(&out.first_seen_ns, &out.last_seen_ns,
                    s->first_seen_ns, s->last_seen_ns);
        uint8_t idx = out.n_events % NO_MAX_NETWORK_EVENTS;
        for (int j = 0; j < NO_MAX_NETWORK_EVENTS; j++) {
            if (s->packets[j] == 0)
                continue;
            bool dup = false;
            for (int i = 0; i < NO_MAX_NETWORK_EVENTS; i++) {
                if (std::memcmp(out.events[i], s->events[j],
                                NO_MAX_EVENT_MD) == 0) {
                    dup = true;
                    break;
                }
            }
            if (!dup) {
                std::memcpy(out.events[idx], s->events[j], NO_MAX_EVENT_MD);
                out.bytes[idx] = sat_add16(out.bytes[idx], s->bytes[j]);
                out.packets[idx] = sat_add16(out.packets[idx], s->packets[j]);
                idx = (idx + 1) % NO_MAX_NETWORK_EVENTS;
            }
        }
        out.n_events = idx;
    }
    std::memcpy(out_buf, &out, sizeof(out));
}

// Merge per-CPU partials of the NAT-translation record: a complete
// (both-endpoints) observation replaces. Mirrors accumulate_xlat.
void fp_merge_xlat(const uint8_t *values, size_t n_cpu, uint8_t *out_buf) {
    struct no_xlat_rec out;
    std::memcpy(&out, values, sizeof(out));
    const struct no_xlat_rec *v =
        reinterpret_cast<const struct no_xlat_rec *>(values);
    for (size_t c = 1; c < n_cpu; c++) {
        const struct no_xlat_rec *s = &v[c];
        merge_times(&out.first_seen_ns, &out.last_seen_ns,
                    s->first_seen_ns, s->last_seen_ns);
        bool src_set = false, dst_set = false;
        for (int i = 0; i < NO_IP_LEN; i++) {
            if (s->src_ip[i]) src_set = true;
            if (s->dst_ip[i]) dst_set = true;
        }
        if (src_set && dst_set) {
            std::memcpy(out.src_ip, s->src_ip, NO_IP_LEN);
            std::memcpy(out.dst_ip, s->dst_ip, NO_IP_LEN);
            out.src_port = s->src_port;
            out.dst_port = s->dst_port;
            out.zone_id = s->zone_id;
        }
    }
    std::memcpy(out_buf, &out, sizeof(out));
}

// Merge per-CPU partials of the QUIC record: max version wins, header-seen
// flags accumulate. Mirrors accumulate_quic.
void fp_merge_quic(const uint8_t *values, size_t n_cpu, uint8_t *out_buf) {
    struct no_quic_rec out;
    std::memcpy(&out, values, sizeof(out));
    const struct no_quic_rec *v =
        reinterpret_cast<const struct no_quic_rec *>(values);
    for (size_t c = 1; c < n_cpu; c++) {
        const struct no_quic_rec *s = &v[c];
        merge_times(&out.first_seen_ns, &out.last_seen_ns,
                    s->first_seen_ns, s->last_seen_ns);
        if (s->version > out.version) out.version = s->version;
        if (s->seen_long_hdr > out.seen_long_hdr)
            out.seen_long_hdr = s->seen_long_hdr;
        if (s->seen_short_hdr > out.seen_short_hdr)
            out.seen_short_hdr = s->seen_short_hdr;
    }
    std::memcpy(out_buf, &out, sizeof(out));
}

// ---------------------------------------------------------------------------
// Batched per-CPU merges: one call for ALL keys of a drained feature map.
// values: n_keys * n_cpu consecutive record images (the kernel's
// LOOKUP_AND_DELETE_BATCH value buffer, padding already stripped/absent —
// every record struct here is 8-byte-aligned so the per-CPU stride equals
// sizeof); out: n_keys records. One ctypes round trip replaces n_keys of
// them — the eviction plane's native fast path (columnar python twin:
// model/accumulate.py COLUMNAR_MERGES; equivalence pinned in
// tests/test_evict_columnar.py).
// ---------------------------------------------------------------------------
#define FP_MERGE_BATCH(name, type)                                          \
    void name##_batch(const uint8_t *values, size_t n_keys, size_t n_cpu,   \
                      uint8_t *out) {                                       \
        for (size_t k = 0; k < n_keys; k++)                                 \
            name(values + k * n_cpu * sizeof(type), n_cpu,                  \
                 out + k * sizeof(type));                                   \
    }

FP_MERGE_BATCH(fp_merge_stats, struct no_flow_stats)
FP_MERGE_BATCH(fp_merge_extra, struct no_extra_rec)
FP_MERGE_BATCH(fp_merge_drops, struct no_drops_rec)
FP_MERGE_BATCH(fp_merge_dns, struct no_dns_rec)
FP_MERGE_BATCH(fp_merge_nevents, struct no_nevents_rec)
FP_MERGE_BATCH(fp_merge_xlat, struct no_xlat_rec)
FP_MERGE_BATCH(fp_merge_quic, struct no_quic_rec)

// ---------------------------------------------------------------------------
// FLOW_EVENT interleave: compose contiguous no_flow_event rows (key 40B |
// stats 104B) from the two columns a batched map drain yields — the columnar
// eviction plane's single copy boundary done as one native pass instead of
// two strided numpy field assignments (python twin:
// model/binfmt.py events_from_keys_stats; equivalence pinned in
// tests/test_evict_parallel.py). `out` must hold n events; tail rows beyond
// n (the loader's ringbuf-orphan appendix) are the caller's to zero.
// ---------------------------------------------------------------------------
void fp_events_from_keys_stats(const uint8_t *keys, const uint8_t *stats,
                               size_t n, uint8_t *out) {
    for (size_t i = 0; i < n; i++) {
        struct no_flow_event *ev =
            reinterpret_cast<struct no_flow_event *>(
                out + i * sizeof(struct no_flow_event));
        std::memcpy(&ev->key, keys + i * sizeof(struct no_flow_key),
                    sizeof(struct no_flow_key));
        std::memcpy(&ev->stats, stats + i * sizeof(struct no_flow_stats),
                    sizeof(struct no_flow_stats));
    }
}

// crc32c (Castagnoli) — slice-by-8; used by the Kafka record-batch encoder.
static uint32_t crc32c_table[8][256];
static bool crc32c_ready = false;

static void crc32c_init() {
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? (c >> 1) ^ 0x82F63B78u : c >> 1;
        crc32c_table[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = crc32c_table[0][i];
        for (int t = 1; t < 8; t++) {
            c = crc32c_table[0][c & 0xFF] ^ (c >> 8);
            crc32c_table[t][i] = c;
        }
    }
    crc32c_ready = true;
}

#if defined(__x86_64__)
// Hardware CRC32C (SSE4.2) — ~10x the sliced table walk; the key
// dictionary hashes 40 bytes per record, so this sits on the pack hot path.
__attribute__((target("sse4.2")))
static uint32_t crc32c_hw(const uint8_t *data, size_t n) {
    uint64_t crc = 0xFFFFFFFFu;
    size_t i = 0;
    for (; n - i >= 8; i += 8) {
        uint64_t v;
        std::memcpy(&v, data + i, 8);
        crc = __builtin_ia32_crc32di(crc, v);
    }
    for (; i < n; i++)
        crc = __builtin_ia32_crc32qi(static_cast<uint32_t>(crc), data[i]);
    return static_cast<uint32_t>(crc) ^ 0xFFFFFFFFu;
}
static int crc32c_have_hw = -1;
#endif

uint32_t fp_crc32c(const uint8_t *data, size_t n) {
#if defined(__x86_64__)
    if (crc32c_have_hw < 0)
        crc32c_have_hw = __builtin_cpu_supports("sse4.2") ? 1 : 0;
    if (crc32c_have_hw)
        return crc32c_hw(data, n);
#endif
    if (!crc32c_ready)
        crc32c_init();
    uint32_t crc = 0xFFFFFFFFu;
    size_t i = 0;
    while (n - i >= 8) {
        uint32_t lo, hi;
        std::memcpy(&lo, data + i, 4);
        std::memcpy(&hi, data + i + 4, 4);
        crc ^= lo;
        crc = crc32c_table[7][crc & 0xFF] ^ crc32c_table[6][(crc >> 8) & 0xFF] ^
              crc32c_table[5][(crc >> 16) & 0xFF] ^
              crc32c_table[4][(crc >> 24) & 0xFF] ^
              crc32c_table[3][hi & 0xFF] ^ crc32c_table[2][(hi >> 8) & 0xFF] ^
              crc32c_table[1][(hi >> 16) & 0xFF] ^
              crc32c_table[0][(hi >> 24) & 0xFF];
        i += 8;
    }
    for (; i < n; i++)
        crc = crc32c_table[0][(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
    return crc ^ 0xFFFFFFFFu;
}

uint32_t fp_abi_version(void) { return 9; }

}  // extern "C"
