// flowpack — native host-side hot path for the capture plane.
//
// Converts raw flow-event buffers (as drained from kernel maps / ring buffers)
// into the columnar tensors the TPU analytics plane consumes, and merges
// per-CPU feature-map partials. This is the native replacement for the
// reference's per-record decode loop (pkg/model/record.go:227, its hottest
// allocation site) — done as flat array passes instead.
//
// Layout contract: struct definitions come from ../bpf/records.h, the same
// header the eBPF datapath compiles; tests/test_layout_parity.py pins both
// sides against the numpy dtypes.
//
// C ABI only (consumed via ctypes). All output buffers are caller-allocated.

#include <algorithm>
#include <cstdint>
#include <cstdlib>
#include <cstring>

#if defined(__linux__)
#include <errno.h>
#include <pthread.h>
#include <sys/syscall.h>
#include <time.h>
#include <unistd.h>
#endif

#define NO_HOST_BUILD 1
#include "../bpf/records.h"

extern "C" {

// Column pointers for fp_pack. Each points at a caller-allocated array of
// capacity >= n rows (keys: n*10 u32, row-major).
struct fp_columns {
    uint32_t *keys;        // [n][10] packed key words
    uint64_t *bytes;       // [n]
    uint32_t *packets;     // [n]
    uint32_t *tcp_flags;   // [n]
    uint32_t *eth_protocol;// [n]
    uint32_t *direction;   // [n]
    uint32_t *if_index;    // [n]
    uint32_t *dscp;        // [n]
    uint32_t *sampling;    // [n]
    uint64_t *first_seen_ns; // [n]
    uint64_t *last_seen_ns;  // [n]
};

// Pack n contiguous no_flow_event records into columns. Returns n.
size_t fp_pack(const uint8_t *events, size_t n, struct fp_columns *out) {
    const struct no_flow_event *ev =
        reinterpret_cast<const struct no_flow_event *>(events);
    for (size_t i = 0; i < n; i++) {
        const struct no_flow_key *k = &ev[i].key;
        const struct no_flow_stats *s = &ev[i].stats;
        uint32_t *kw = out->keys + i * 10;
        std::memcpy(kw, k->src_ip, 16);      // words 0..3
        std::memcpy(kw + 4, k->dst_ip, 16);  // words 4..7
        kw[8] = (static_cast<uint32_t>(k->src_port) << 16) | k->dst_port;
        kw[9] = (static_cast<uint32_t>(k->proto) << 16) |
                (static_cast<uint32_t>(k->icmp_type) << 8) | k->icmp_code;
        out->bytes[i] = s->bytes;
        out->packets[i] = s->packets;
        out->tcp_flags[i] = s->tcp_flags;
        out->eth_protocol[i] = s->eth_protocol;
        out->direction[i] = s->direction_first;
        out->if_index[i] = s->if_index_first;
        out->dscp[i] = s->dscp;
        out->sampling[i] = s->sampling;
        out->first_seen_ns[i] = s->first_seen_ns;
        out->last_seen_ns[i] = s->last_seen_ns;
    }
    return n;
}

// Dense TPU feed: one (batch_size, FP_DENSE_WORDS) u32 row-major array per
// batch instead of six column arrays — a single host->device transfer on a
// tunneled/PCIe link instead of six round trips, and a single pass over the
// raw event bytes (no intermediate FlowBatch, no Python copies). Row layout
// (must match flowpack.py pack_dense/DENSE_WORDS and the device-side unpack
// in sketch/state.py dense_to_arrays):
//   words 0..9   packed key words (same packing as fp_pack)
//   word  10     bytes as float32 bitcast (sketch planes are f32)
//   word  11     packets
//   word  12     rtt_us        (from the extra record, else 0)
//   word  13     dns_latency_us (from the dns record, else 0)
//   word  14     valid flag (1 for live rows; padding rows are all-zero)
//   word  15     sampling
//   word  16     tcp_flags | dscp << 16 | markers << 24
//                (markers: bit0 QUIC seen, bit1 NAT translation observed,
//                 bit2 IPsec encrypted, bit3 IPsec error)
//   word  17     drop bytes | drop packets << 16   (from the drops record)
//   word  18     drop latest_cause (low u16) | latest_state << 16
//   word  19     reserved (0)
#define FP_DENSE_WORDS 20

static inline uint8_t feature_markers(const struct no_extra_rec *ex,
                                      const struct no_xlat_rec *xl,
                                      const struct no_quic_rec *qc,
                                      size_t i) {
    uint8_t m = 0;
    if (qc && (qc[i].version || qc[i].seen_long_hdr || qc[i].seen_short_hdr))
        m |= 1;
    if (xl) {
        // complete translation = both endpoints observed (fp_merge_xlat rule)
        bool src_set = false, dst_set = false;
        for (int b = 0; b < NO_IP_LEN; b++) {
            if (xl[i].src_ip[b]) src_set = true;
            if (xl[i].dst_ip[b]) dst_set = true;
        }
        if (src_set && dst_set) m |= 2;
    }
    if (ex && ex[i].ipsec_encrypted) m |= 4;
    if (ex && ex[i].ipsec_ret != 0) m |= 8;
    return m;
}

static inline void fill_feature_words(const struct no_flow_stats *s,
                                      const struct no_extra_rec *ex,
                                      const struct no_xlat_rec *xl,
                                      const struct no_quic_rec *qc,
                                      const struct no_drops_rec *dr,
                                      size_t i, uint32_t *w16) {
    w16[0] = (s->tcp_flags & 0xFFFFu) |
             (static_cast<uint32_t>(s->dscp & 0xFFu) << 16) |
             (static_cast<uint32_t>(feature_markers(ex, xl, qc, i)) << 24);
    w16[1] = dr ? (static_cast<uint32_t>(dr[i].bytes) |
                   (static_cast<uint32_t>(dr[i].packets) << 16))
                : 0;
    // saturate, don't mask: subsystem drop reasons (kernel >= 6.0) carry
    // the subsystem in bits 16+ — masking would alias them onto unrelated
    // core reasons; saturation lands them in the histogram's overflow bucket
    uint32_t cause = dr ? dr[i].latest_cause : 0;
    if (cause > 0xFFFFu) cause = 0xFFFFu;
    w16[2] = dr ? (cause | (static_cast<uint32_t>(dr[i].latest_state) << 16))
                : 0;
    w16[3] = 0;
}

void fp_pack_dense(const uint8_t *events, size_t n,
                   const uint8_t *extra, const uint8_t *dns,
                   const uint8_t *drops, const uint8_t *xlat,
                   const uint8_t *quic,
                   uint32_t *out, size_t batch_size) {
    const struct no_flow_event *ev =
        reinterpret_cast<const struct no_flow_event *>(events);
    const struct no_extra_rec *ex =
        reinterpret_cast<const struct no_extra_rec *>(extra);
    const struct no_dns_rec *dn =
        reinterpret_cast<const struct no_dns_rec *>(dns);
    const struct no_drops_rec *dr =
        reinterpret_cast<const struct no_drops_rec *>(drops);
    const struct no_xlat_rec *xl =
        reinterpret_cast<const struct no_xlat_rec *>(xlat);
    const struct no_quic_rec *qc =
        reinterpret_cast<const struct no_quic_rec *>(quic);
    for (size_t i = 0; i < n; i++) {
        const struct no_flow_key *k = &ev[i].key;
        const struct no_flow_stats *s = &ev[i].stats;
        uint32_t *row = out + i * FP_DENSE_WORDS;
        std::memcpy(row, k->src_ip, 16);      // words 0..3
        std::memcpy(row + 4, k->dst_ip, 16);  // words 4..7
        row[8] = (static_cast<uint32_t>(k->src_port) << 16) | k->dst_port;
        row[9] = (static_cast<uint32_t>(k->proto) << 16) |
                 (static_cast<uint32_t>(k->icmp_type) << 8) | k->icmp_code;
        float b = static_cast<float>(s->bytes);
        std::memcpy(&row[10], &b, 4);
        row[11] = s->packets;
        row[12] = ex ? static_cast<uint32_t>(ex[i].rtt_ns / 1000) : 0;
        row[13] = dn ? static_cast<uint32_t>(dn[i].latency_ns / 1000) : 0;
        row[14] = 1;
        row[15] = s->sampling;
        fill_feature_words(s, ex, xl, qc, dr, i, row + 16);
    }
    if (n < batch_size)
        std::memset(out + n * FP_DENSE_WORDS, 0,
                    (batch_size - n) * FP_DENSE_WORDS * sizeof(uint32_t));
}

// Compact TPU feed: the host->device link (not compute) bounds the host
// path, so shrink bytes/record. IPv4 flows (v4-in-v6 mapped keys, RFC 4038
// — the common case) collapse their 10 key words to 4; non-v4 rows — and
// rows carrying DROP data, which are rare outside drop storms — spill to a
// small full-width (FP_DENSE_WORDS) side lane. One flat buffer:
//   [batch_size * 10 compact words | spill_cap * 20 dense words]
// Compact row (must match sketch/state.py compact_to_arrays):
//   w0 src_v4 (key word 3)   w1 dst_v4 (key word 7)   w2 ports (src<<16|dst)
//   w3 bit31 = valid, low 24 = proto<<16|icmp_type<<8|icmp_code
//   w4 bytes f32 bitcast     w5 packets     w6 rtt_us     w7 dns_latency_us
//   w8 sampling              w9 tcp_flags | dscp << 16 | markers << 24
// Returns the number of spill rows used, or -1 if spill_cap would overflow
// (caller falls back to the full dense pack for that batch).
#define FP_COMPACT_WORDS 10
#define FP_V4_PREFIX_WORD2 0xffff0000u  // bytes 8..11 of a mapped address

static inline bool is_v4_mapped(const uint8_t *ip16) {
    uint32_t w0, w1, w2;
    std::memcpy(&w0, ip16, 4);
    std::memcpy(&w1, ip16 + 4, 4);
    std::memcpy(&w2, ip16 + 8, 4);
    return w0 == 0 && w1 == 0 && w2 == FP_V4_PREFIX_WORD2;
}

int fp_pack_compact(const uint8_t *events, size_t n,
                    const uint8_t *extra, const uint8_t *dns,
                    const uint8_t *drops, const uint8_t *xlat,
                    const uint8_t *quic,
                    uint32_t *out, size_t batch_size, size_t spill_cap) {
    const struct no_flow_event *ev =
        reinterpret_cast<const struct no_flow_event *>(events);
    const struct no_extra_rec *ex =
        reinterpret_cast<const struct no_extra_rec *>(extra);
    const struct no_dns_rec *dn =
        reinterpret_cast<const struct no_dns_rec *>(dns);
    const struct no_drops_rec *dr =
        reinterpret_cast<const struct no_drops_rec *>(drops);
    const struct no_xlat_rec *xl =
        reinterpret_cast<const struct no_xlat_rec *>(xlat);
    const struct no_quic_rec *qc =
        reinterpret_cast<const struct no_quic_rec *>(quic);
    uint32_t *spill = out + batch_size * FP_COMPACT_WORDS;
    size_t nc = 0, ns = 0;
    for (size_t i = 0; i < n; i++) {
        const struct no_flow_key *k = &ev[i].key;
        const struct no_flow_stats *s = &ev[i].stats;
        uint32_t rtt = ex ? static_cast<uint32_t>(ex[i].rtt_ns / 1000) : 0;
        uint32_t dlat = dn ? static_cast<uint32_t>(dn[i].latency_ns / 1000) : 0;
        bool has_drops = dr && (dr[i].bytes || dr[i].packets);
        if (!has_drops && is_v4_mapped(k->src_ip) && is_v4_mapped(k->dst_ip)) {
            uint32_t *row = out + nc * FP_COMPACT_WORDS;
            std::memcpy(&row[0], k->src_ip + 12, 4);
            std::memcpy(&row[1], k->dst_ip + 12, 4);
            row[2] = (static_cast<uint32_t>(k->src_port) << 16) | k->dst_port;
            row[3] = 0x80000000u | (static_cast<uint32_t>(k->proto) << 16) |
                     (static_cast<uint32_t>(k->icmp_type) << 8) | k->icmp_code;
            float b = static_cast<float>(s->bytes);
            std::memcpy(&row[4], &b, 4);
            row[5] = s->packets;
            row[6] = rtt;
            row[7] = dlat;
            row[8] = s->sampling;
            row[9] = (s->tcp_flags & 0xFFFFu) |
                     (static_cast<uint32_t>(s->dscp & 0xFFu) << 16) |
                     (static_cast<uint32_t>(feature_markers(ex, xl, qc, i))
                      << 24);
            nc++;
        } else {
            if (ns >= spill_cap)
                return -1;
            uint32_t *row = spill + ns * FP_DENSE_WORDS;
            std::memcpy(row, k->src_ip, 16);
            std::memcpy(row + 4, k->dst_ip, 16);
            row[8] = (static_cast<uint32_t>(k->src_port) << 16) | k->dst_port;
            row[9] = (static_cast<uint32_t>(k->proto) << 16) |
                     (static_cast<uint32_t>(k->icmp_type) << 8) | k->icmp_code;
            float b = static_cast<float>(s->bytes);
            std::memcpy(&row[10], &b, 4);
            row[11] = s->packets;
            row[12] = rtt;
            row[13] = dlat;
            row[14] = 1;
            row[15] = s->sampling;
            fill_feature_words(s, ex, xl, qc, dr, i, row + 16);
            ns++;
        }
    }
    if (nc < batch_size)
        std::memset(out + nc * FP_COMPACT_WORDS, 0,
                    (batch_size - nc) * FP_COMPACT_WORDS * sizeof(uint32_t));
    if (ns < spill_cap)
        std::memset(spill + ns * FP_DENSE_WORDS, 0,
                    (spill_cap - ns) * FP_DENSE_WORDS * sizeof(uint32_t));
    return static_cast<int>(ns);
}

// ---------------------------------------------------------------------------
// Resident-key feed: the lowest-bytes-per-record TPU feed. The host keeps a
// key -> slot dictionary (this file); the DEVICE keeps a (slot_cap, 10) u32
// key table in HBM, updated from the new-key lane and gathered by slot id —
// steady-state records ship as THREE words instead of ten (the transfer
// link, not compute, bounds the host path; see docs/tpu_sketch.md byte
// budget). Flat buffer layout (must match sketch/state.py
// resident_to_arrays and flowpack.py pack_resident):
//   [0..3]    header: w0 default sampling, w1 n_newkey, w2 n_spill,
//             w3 n_dns | n_drop << 16   (w1..w3 diagnostic only)
//   hot lane    batch_size * 3 words:
//     w0  bit31 valid | bits 28..30 rtt exp | bits 20..27 rtt mant
//         | bits 0..19 slot id          (rtt_us ~= mant << (2*exp))
//     w1  bytes as float32 bitcast
//     w2  packets (bits 0..10) | tcp_flags (11..21) | dscp (22..27)
//         | markers (28..31)
//   dns lane    dns_cap words:  row_idx << 16 | dns code
//         (code: bits 12..15 exp e, bits 0..11 mant m; value_us = m << e)
//   drop lane   drop_cap * 2 words:
//     w0  row_idx << 16 | latest_cause (saturated u16)
//     w1  drop packets << 16 | drop bytes
//   newkey lane nk_cap * 11 words: w0 = bit31 | slot id, w1..w10 key words
//   spill lane  spill_cap * FP_DENSE_WORDS dense rows (anything the hot
//               row can't carry exactly: packets/flags overflow, sampling
//               mismatch, rtt beyond the code range, lane overflows)
//
// fp_pack_resident packs events[start..n) until the hot lane or the spill
// lane fills, and returns the number of rows CONSUMED — partial packing
// with continuation: the caller ships the (always self-consistent) prefix
// and packs the remainder into the next buffer, so the dictionary and the
// device table learn monotonically even under cold-start floods (no
// rollback, no dense fallback). A full dictionary is the caller's policy
// decision: reset it between calls — stale device-table rows are harmless
// because every live slot is redefined through the new-key lane before any
// hot row references it. Lane counts land in header words 1..3.
#define FP_HOT_WORDS 3
#define FP_RESIDENT_HDR 4
#define FP_NK_WORDS 11
#define FP_SLOT_MASK 0xFFFFFu
#define FP_RTT_MAX_US (0xFFu << 14)

// 16-byte entries: a 64-bit key FINGERPRINT instead of the 40-byte key.
// The table must be probed once per record at line rate; 48-byte entries
// made every probe a random DRAM access (measured 9.8M rec/s on the pack
// loop). A fingerprint collision (p ~ n^2/2^65 — ~1e-6 at a full 2^18
// table) maps a new flow onto an existing slot: its records fold under
// that slot's key words, a bounded mis-attribution of the same order as a
// Count-Min collision. The sketch plane hashes the (gathered) key words
// themselves, so nothing downstream amplifies it.
struct fp_dict_entry {
    uint64_t fp;       // 0 = empty (fingerprints of 0 are remapped to 1)
    uint32_t slot;
    uint32_t pad_;
};

struct fp_dict {
    struct fp_dict_entry *tab;
    size_t mask;       // hash table size - 1 (power of two)
    uint32_t slot_cap;
    uint32_t next_slot;
};

static inline uint64_t key_fp64(const uint32_t *kw) {
    // 40 key bytes = 5 u64 lanes; murmur-style mix per lane + finalizer
    uint64_t h = 0x9E3779B97F4A7C15ull;
    for (int i = 0; i < 5; i++) {
        uint64_t k;
        std::memcpy(&k, reinterpret_cast<const uint8_t *>(kw) + i * 8, 8);
        k *= 0xC2B2AE3D27D4EB4Full;
        k = (k << 31) | (k >> 33);
        k *= 0x9E3779B185EBCA87ull;
        h ^= k;
        h = ((h << 27) | (h >> 37)) * 5 + 0x52DCE729ull;
    }
    h ^= h >> 33;
    h *= 0xFF51AFD7ED558CCDull;
    h ^= h >> 33;
    h *= 0xC4CEB9FE1A85EC53ull;
    h ^= h >> 33;
    return h ? h : 1;
}

void *fp_dict_new(uint32_t slot_cap) {
    if (slot_cap == 0 || slot_cap > (FP_SLOT_MASK + 1))
        return nullptr;
    size_t cap = 1;
    while (cap < static_cast<size_t>(slot_cap) * 2)
        cap <<= 1;
    fp_dict *d = new fp_dict;
    d->tab = new fp_dict_entry[cap]();
    d->mask = cap - 1;
    d->slot_cap = slot_cap;
    d->next_slot = 0;
    return d;
}

void fp_dict_free(void *h) {
    if (!h) return;
    fp_dict *d = static_cast<fp_dict *>(h);
    delete[] d->tab;
    delete d;
}

void fp_dict_reset(void *h) {
    fp_dict *d = static_cast<fp_dict *>(h);
    std::memset(d->tab, 0, (d->mask + 1) * sizeof(fp_dict_entry));
    d->next_slot = 0;
}

uint32_t fp_dict_count(void *h) {
    return static_cast<fp_dict *>(h)->next_slot;
}

// Find the fingerprint's hash-table index; *found says whether it's there.
static inline size_t dict_probe(const fp_dict *d, uint64_t fp, bool *found) {
    size_t i = fp & d->mask;
    for (;;) {
        const fp_dict_entry *e = &d->tab[i];
        if (!e->fp) {
            *found = false;
            return i;
        }
        if (e->fp == fp) {
            *found = true;
            return i;
        }
        i = (i + 1) & d->mask;
    }
}

static inline void make_kw(const struct no_flow_key *k, uint32_t *kw) {
    std::memcpy(kw, k->src_ip, 16);
    std::memcpy(kw + 4, k->dst_ip, 16);
    kw[8] = (static_cast<uint32_t>(k->src_port) << 16) | k->dst_port;
    kw[9] = (static_cast<uint32_t>(k->proto) << 16) |
            (static_cast<uint32_t>(k->icmp_type) << 8) | k->icmp_code;
}

static inline uint32_t rtt_code11(uint32_t rtt_us) {
    // bits 0..7 mantissa, bits 8..10 exponent; value ~= m << (2*e)
    uint32_t e = 0;
    while ((rtt_us >> (2 * e)) > 0xFFu)
        e++;
    return ((rtt_us >> (2 * e)) & 0xFFu) | (e << 8);
}

static inline uint32_t lat_code16(uint64_t us) {
    // bits 0..11 mantissa, bits 12..15 exponent; value ~= m << e
    uint32_t e = 0;
    while ((us >> e) > 0xFFFu && e < 15)
        e++;
    uint64_t m = us >> e;
    if (m > 0xFFFu) m = 0xFFFu;  // saturate at ~134s
    return static_cast<uint32_t>(m) | (e << 12);
}

int64_t fp_pack_resident(const uint8_t *events, size_t start, size_t n,
                         const uint8_t *extra, const uint8_t *dns,
                         const uint8_t *drops, const uint8_t *xlat,
                         const uint8_t *quic,
                         void *dict_h, uint32_t *out, size_t batch_size,
                         size_t dns_cap, size_t drop_cap, size_t nk_cap,
                         size_t spill_cap) {
    fp_dict *d = static_cast<fp_dict *>(dict_h);
    const struct no_flow_event *ev =
        reinterpret_cast<const struct no_flow_event *>(events);
    const struct no_extra_rec *ex =
        reinterpret_cast<const struct no_extra_rec *>(extra);
    const struct no_dns_rec *dn =
        reinterpret_cast<const struct no_dns_rec *>(dns);
    const struct no_drops_rec *dr =
        reinterpret_cast<const struct no_drops_rec *>(drops);
    const struct no_xlat_rec *xl =
        reinterpret_cast<const struct no_xlat_rec *>(xlat);
    const struct no_quic_rec *qc =
        reinterpret_cast<const struct no_quic_rec *>(quic);
    uint32_t *hot = out + FP_RESIDENT_HDR;
    uint32_t *dnsl = hot + batch_size * FP_HOT_WORDS;
    uint32_t *dropl = dnsl + dns_cap;
    uint32_t *nkl = dropl + drop_cap * 2;
    uint32_t *spill = nkl + nk_cap * FP_NK_WORDS;
    size_t nh = 0, nd = 0, nr = 0, nk = 0, ns = 0;
    uint32_t def_sampling = start < n ? ev[start].stats.sampling : 0;

    // fingerprint lookahead pipeline: compute row i+PF's fingerprint and
    // prefetch its table line while processing row i — the probe is a
    // random access into a multi-MB table, and exposed DRAM latency was
    // the pack loop's measured bottleneck
    enum { PF = 16 };
    uint64_t fpbuf[PF];
    for (size_t j = start; j < n && j < start + PF; j++) {
        uint32_t kwp[10];
        make_kw(&ev[j].key, kwp);
        fpbuf[j % PF] = key_fp64(kwp);
        __builtin_prefetch(&d->tab[fpbuf[j % PF] & d->mask]);
    }
    size_t i = start;
    for (; i < n && nh < batch_size; i++) {
        const struct no_flow_key *k = &ev[i].key;
        const struct no_flow_stats *s = &ev[i].stats;
        // row i's fingerprint FIRST: the ring slot is about to be reused
        // for row i+PF
        uint64_t fp = fpbuf[i % PF];
        if (i + PF < n) {
            uint32_t kwp[10];
            make_kw(&ev[i + PF].key, kwp);
            fpbuf[(i + PF) % PF] = key_fp64(kwp);
            __builtin_prefetch(&d->tab[fpbuf[(i + PF) % PF] & d->mask]);
        }
        uint32_t kw[10];
        make_kw(k, kw);
        // ensure the key has a slot (insert through the new-key lane);
        // nk-lane or dictionary exhaustion just routes the row to spill —
        // the key is learned by a later chunk
        bool found;
        size_t hi = dict_probe(d, fp, &found);
        bool have_slot = found;
        uint32_t slot = found ? d->tab[hi].slot : 0;
        if (!found && nk < nk_cap && d->next_slot < d->slot_cap) {
            slot = d->next_slot++;
            d->tab[hi].fp = fp;
            d->tab[hi].slot = slot;
            uint32_t *row = nkl + nk * FP_NK_WORDS;
            row[0] = 0x80000000u | slot;
            std::memcpy(row + 1, kw, 40);
            nk++;
            have_slot = true;
        }
        uint32_t rtt = ex ? static_cast<uint32_t>(ex[i].rtt_ns / 1000) : 0;
        uint64_t dlat = dn ? dn[i].latency_ns / 1000 : 0;
        bool has_drops = dr && (dr[i].bytes || dr[i].packets);
        bool hot_ok = have_slot && s->packets < 0x800 &&
                      s->tcp_flags < 0x800 && s->dscp < 0x40 &&
                      s->sampling == def_sampling && rtt <= FP_RTT_MAX_US &&
                      (!dlat || nd < dns_cap) &&
                      (!has_drops || nr < drop_cap);
        if (hot_ok) {
            uint32_t *row = hot + nh * FP_HOT_WORDS;
            row[0] = 0x80000000u | (rtt_code11(rtt) << 20) | slot;
            float b = static_cast<float>(s->bytes);
            std::memcpy(&row[1], &b, 4);
            row[2] = (s->packets & 0x7FFu) |
                     (static_cast<uint32_t>(s->tcp_flags & 0x7FFu) << 11) |
                     (static_cast<uint32_t>(s->dscp & 0x3Fu) << 22) |
                     (static_cast<uint32_t>(feature_markers(ex, xl, qc, i))
                      << 28);
            if (dlat) {
                dnsl[nd++] = (static_cast<uint32_t>(nh) << 16) |
                             lat_code16(dlat);
            }
            if (has_drops) {
                uint32_t cause = dr[i].latest_cause;
                if (cause > 0xFFFFu) cause = 0xFFFFu;
                uint32_t *de = dropl + nr * 2;
                de[0] = (static_cast<uint32_t>(nh) << 16) | cause;
                de[1] = (static_cast<uint32_t>(dr[i].packets) << 16) |
                        dr[i].bytes;
                nr++;
            }
            nh++;
        } else {
            if (ns >= spill_cap)
                break;  // chunk full: caller continues from row i
            uint32_t *row = spill + ns * FP_DENSE_WORDS;
            std::memcpy(row, kw, 40);
            float b = static_cast<float>(s->bytes);
            std::memcpy(&row[10], &b, 4);
            row[11] = s->packets;
            row[12] = rtt;
            row[13] = static_cast<uint32_t>(dlat);
            row[14] = 1;
            row[15] = s->sampling;
            fill_feature_words(s, ex, xl, qc, dr, i, row + 16);
            ns++;
        }
    }
    out[0] = def_sampling;
    out[1] = static_cast<uint32_t>(nk);
    out[2] = static_cast<uint32_t>(ns);
    out[3] = static_cast<uint32_t>(nd) | (static_cast<uint32_t>(nr) << 16);
    if (nh < batch_size)
        std::memset(hot + nh * FP_HOT_WORDS, 0,
                    (batch_size - nh) * FP_HOT_WORDS * sizeof(uint32_t));
    if (nd < dns_cap)
        std::memset(dnsl + nd, 0, (dns_cap - nd) * sizeof(uint32_t));
    if (nr < drop_cap)
        std::memset(dropl + nr * 2, 0, (drop_cap - nr) * 2 * sizeof(uint32_t));
    if (nk < nk_cap)
        std::memset(nkl + nk * FP_NK_WORDS, 0,
                    (nk_cap - nk) * FP_NK_WORDS * sizeof(uint32_t));
    if (ns < spill_cap)
        std::memset(spill + ns * FP_DENSE_WORDS, 0,
                    (spill_cap - ns) * FP_DENSE_WORDS * sizeof(uint32_t));
    return static_cast<int64_t>(i - start);
}

static inline void merge_times(uint64_t *dfirst, uint64_t *dlast,
                               uint64_t sfirst, uint64_t slast) {
    if (*dfirst == 0 || (sfirst != 0 && sfirst < *dfirst))
        *dfirst = sfirst;
    if (slast > *dlast)
        *dlast = slast;
}

static inline uint16_t sat_add16(uint16_t a, uint16_t b) {
    uint32_t s = static_cast<uint32_t>(a) + b;
    return s > 0xFFFF ? 0xFFFF : static_cast<uint16_t>(s);
}

// Merge per-CPU partials of the base stats struct.
// values: n_cpu consecutive no_flow_stats images for ONE map entry.
// out: one no_flow_stats. Mirrors model/accumulate.py accumulate_base.
void fp_merge_stats(const uint8_t *values, size_t n_cpu, uint8_t *out_buf) {
    struct no_flow_stats out;
    std::memcpy(&out, values, sizeof(out));
    // the datapath's lock-free slot reservation can leave the counter
    // TRANSIENTLY above capacity (saturation undo in flight) — clamp before
    // any indexing
    if (out.n_observed_intf > NO_MAX_OBSERVED_INTERFACES)
        out.n_observed_intf = NO_MAX_OBSERVED_INTERFACES;
    const struct no_flow_stats *v =
        reinterpret_cast<const struct no_flow_stats *>(values);
    for (size_t c = 1; c < n_cpu; c++) {
        const struct no_flow_stats *s = &v[c];
        bool dst_empty = out.first_seen_ns == 0 && out.packets == 0;
        merge_times(&out.first_seen_ns, &out.last_seen_ns,
                    s->first_seen_ns, s->last_seen_ns);
        uint64_t nb = out.bytes + s->bytes;
        out.bytes = nb < out.bytes ? UINT64_MAX : nb;  // saturate on wrap
        uint64_t np = static_cast<uint64_t>(out.packets) + s->packets;
        out.packets = np > UINT32_MAX ? UINT32_MAX
                                      : static_cast<uint32_t>(np);
        out.tcp_flags |= s->tcp_flags;
        if (s->eth_protocol) out.eth_protocol = s->eth_protocol;
        if (s->dscp) out.dscp = s->dscp;
        if (s->sampling) out.sampling = s->sampling;
        if (s->errno_fallback) out.errno_fallback = s->errno_fallback;
        bool src_mac_zero = true, dst_mac_zero = true;
        for (int i = 0; i < NO_ETH_ALEN; i++) {
            if (out.src_mac[i]) src_mac_zero = false;
            if (out.dst_mac[i]) dst_mac_zero = false;
        }
        if (src_mac_zero) std::memcpy(out.src_mac, s->src_mac, NO_ETH_ALEN);
        if (dst_mac_zero) std::memcpy(out.dst_mac, s->dst_mac, NO_ETH_ALEN);
        if (dst_empty) {
            out.if_index_first = s->if_index_first;
            out.direction_first = s->direction_first;
        }
        // ssl_version: first non-zero wins; a conflicting later version sets
        // the mismatch flag (mirrors accumulate_base / kernel entry rule)
        if (s->ssl_version) {
            if (out.ssl_version == 0)
                out.ssl_version = s->ssl_version;
            else if (out.ssl_version != s->ssl_version)
                out.misc_flags |= NO_MISC_SSL_MISMATCH;
        }
        if (s->tls_cipher_suite) out.tls_cipher_suite = s->tls_cipher_suite;
        if (s->tls_key_share) out.tls_key_share = s->tls_key_share;
        out.tls_types |= s->tls_types;
        out.misc_flags |= s->misc_flags;
        int ns_obs = s->n_observed_intf > NO_MAX_OBSERVED_INTERFACES
                         ? NO_MAX_OBSERVED_INTERFACES
                         : s->n_observed_intf;
        for (int j = 0; j < ns_obs; j++) {
            bool seen = false;
            for (int i = 0; i < out.n_observed_intf; i++) {
                if (out.observed_intf[i] == s->observed_intf[j] &&
                    out.observed_direction[i] == s->observed_direction[j]) {
                    seen = true;
                    break;
                }
            }
            if (!seen && out.n_observed_intf < NO_MAX_OBSERVED_INTERFACES) {
                out.observed_intf[out.n_observed_intf] = s->observed_intf[j];
                out.observed_direction[out.n_observed_intf] =
                    s->observed_direction[j];
                out.n_observed_intf++;
            }
        }
    }
    std::memcpy(out_buf, &out, sizeof(out));
}

// Merge per-CPU partials of the extra (rtt/ipsec) record.
void fp_merge_extra(const uint8_t *values, size_t n_cpu, uint8_t *out_buf) {
    struct no_extra_rec out;
    std::memcpy(&out, values, sizeof(out));
    const struct no_extra_rec *v =
        reinterpret_cast<const struct no_extra_rec *>(values);
    for (size_t c = 1; c < n_cpu; c++) {
        const struct no_extra_rec *s = &v[c];
        merge_times(&out.first_seen_ns, &out.last_seen_ns,
                    s->first_seen_ns, s->last_seen_ns);
        if (s->rtt_ns > out.rtt_ns) out.rtt_ns = s->rtt_ns;
        if (out.ipsec_ret < s->ipsec_ret) {
            out.ipsec_ret = s->ipsec_ret;
            out.ipsec_encrypted = s->ipsec_encrypted;
        } else if (out.ipsec_ret == s->ipsec_ret && s->ipsec_encrypted) {
            out.ipsec_encrypted = s->ipsec_encrypted;
        }
    }
    std::memcpy(out_buf, &out, sizeof(out));
}

// Merge per-CPU partials of the drops record.
void fp_merge_drops(const uint8_t *values, size_t n_cpu, uint8_t *out_buf) {
    struct no_drops_rec out;
    std::memcpy(&out, values, sizeof(out));
    const struct no_drops_rec *v =
        reinterpret_cast<const struct no_drops_rec *>(values);
    for (size_t c = 1; c < n_cpu; c++) {
        const struct no_drops_rec *s = &v[c];
        merge_times(&out.first_seen_ns, &out.last_seen_ns,
                    s->first_seen_ns, s->last_seen_ns);
        out.bytes = sat_add16(out.bytes, s->bytes);
        out.packets = sat_add16(out.packets, s->packets);
        out.latest_flags |= s->latest_flags;
        if (s->latest_cause) out.latest_cause = s->latest_cause;
        if (s->latest_state) out.latest_state = s->latest_state;
    }
    std::memcpy(out_buf, &out, sizeof(out));
}

// Merge per-CPU partials of the DNS record (max latency wins).
void fp_merge_dns(const uint8_t *values, size_t n_cpu, uint8_t *out_buf) {
    struct no_dns_rec out;
    std::memcpy(&out, values, sizeof(out));
    const struct no_dns_rec *v =
        reinterpret_cast<const struct no_dns_rec *>(values);
    for (size_t c = 1; c < n_cpu; c++) {
        const struct no_dns_rec *s = &v[c];
        merge_times(&out.first_seen_ns, &out.last_seen_ns,
                    s->first_seen_ns, s->last_seen_ns);
        out.dns_flags |= s->dns_flags;
        if (s->dns_id) out.dns_id = s->dns_id;
        if (out.errno_code != s->errno_code) out.errno_code = s->errno_code;
        if (s->latency_ns > out.latency_ns) out.latency_ns = s->latency_ns;
        if (s->name[0]) std::memcpy(out.name, s->name, NO_DNS_NAME_MAX_LEN);
    }
    std::memcpy(out_buf, &out, sizeof(out));
}

// Merge per-CPU partials of the network-events record: dedup-append into a
// wrapping ring of NO_MAX_NETWORK_EVENTS slots (n_events is the ring CURSOR,
// not a count — renderers scan slots keyed on packets[i] != 0). Mirrors
// model/accumulate.py accumulate_network_events.
void fp_merge_nevents(const uint8_t *values, size_t n_cpu, uint8_t *out_buf) {
    struct no_nevents_rec out;
    std::memcpy(&out, values, sizeof(out));
    const struct no_nevents_rec *v =
        reinterpret_cast<const struct no_nevents_rec *>(values);
    for (size_t c = 1; c < n_cpu; c++) {
        const struct no_nevents_rec *s = &v[c];
        merge_times(&out.first_seen_ns, &out.last_seen_ns,
                    s->first_seen_ns, s->last_seen_ns);
        uint8_t idx = out.n_events % NO_MAX_NETWORK_EVENTS;
        for (int j = 0; j < NO_MAX_NETWORK_EVENTS; j++) {
            if (s->packets[j] == 0)
                continue;
            bool dup = false;
            for (int i = 0; i < NO_MAX_NETWORK_EVENTS; i++) {
                if (std::memcmp(out.events[i], s->events[j],
                                NO_MAX_EVENT_MD) == 0) {
                    dup = true;
                    break;
                }
            }
            if (!dup) {
                std::memcpy(out.events[idx], s->events[j], NO_MAX_EVENT_MD);
                out.bytes[idx] = sat_add16(out.bytes[idx], s->bytes[j]);
                out.packets[idx] = sat_add16(out.packets[idx], s->packets[j]);
                idx = (idx + 1) % NO_MAX_NETWORK_EVENTS;
            }
        }
        out.n_events = idx;
    }
    std::memcpy(out_buf, &out, sizeof(out));
}

// Merge per-CPU partials of the NAT-translation record: a complete
// (both-endpoints) observation replaces. Mirrors accumulate_xlat.
void fp_merge_xlat(const uint8_t *values, size_t n_cpu, uint8_t *out_buf) {
    struct no_xlat_rec out;
    std::memcpy(&out, values, sizeof(out));
    const struct no_xlat_rec *v =
        reinterpret_cast<const struct no_xlat_rec *>(values);
    for (size_t c = 1; c < n_cpu; c++) {
        const struct no_xlat_rec *s = &v[c];
        merge_times(&out.first_seen_ns, &out.last_seen_ns,
                    s->first_seen_ns, s->last_seen_ns);
        bool src_set = false, dst_set = false;
        for (int i = 0; i < NO_IP_LEN; i++) {
            if (s->src_ip[i]) src_set = true;
            if (s->dst_ip[i]) dst_set = true;
        }
        if (src_set && dst_set) {
            std::memcpy(out.src_ip, s->src_ip, NO_IP_LEN);
            std::memcpy(out.dst_ip, s->dst_ip, NO_IP_LEN);
            out.src_port = s->src_port;
            out.dst_port = s->dst_port;
            out.zone_id = s->zone_id;
        }
    }
    std::memcpy(out_buf, &out, sizeof(out));
}

// Merge per-CPU partials of the QUIC record: max version wins, header-seen
// flags accumulate. Mirrors accumulate_quic.
void fp_merge_quic(const uint8_t *values, size_t n_cpu, uint8_t *out_buf) {
    struct no_quic_rec out;
    std::memcpy(&out, values, sizeof(out));
    const struct no_quic_rec *v =
        reinterpret_cast<const struct no_quic_rec *>(values);
    for (size_t c = 1; c < n_cpu; c++) {
        const struct no_quic_rec *s = &v[c];
        merge_times(&out.first_seen_ns, &out.last_seen_ns,
                    s->first_seen_ns, s->last_seen_ns);
        if (s->version > out.version) out.version = s->version;
        if (s->seen_long_hdr > out.seen_long_hdr)
            out.seen_long_hdr = s->seen_long_hdr;
        if (s->seen_short_hdr > out.seen_short_hdr)
            out.seen_short_hdr = s->seen_short_hdr;
    }
    std::memcpy(out_buf, &out, sizeof(out));
}

// ---------------------------------------------------------------------------
// Batched per-CPU merges: one call for ALL keys of a drained feature map.
// values: n_keys * n_cpu consecutive record images (the kernel's
// LOOKUP_AND_DELETE_BATCH value buffer, padding already stripped/absent —
// every record struct here is 8-byte-aligned so the per-CPU stride equals
// sizeof); out: n_keys records. One ctypes round trip replaces n_keys of
// them — the eviction plane's native fast path (columnar python twin:
// model/accumulate.py COLUMNAR_MERGES; equivalence pinned in
// tests/test_evict_columnar.py).
// ---------------------------------------------------------------------------
#define FP_MERGE_BATCH(name, type)                                          \
    void name##_batch(const uint8_t *values, size_t n_keys, size_t n_cpu,   \
                      uint8_t *out) {                                       \
        for (size_t k = 0; k < n_keys; k++)                                 \
            name(values + k * n_cpu * sizeof(type), n_cpu,                  \
                 out + k * sizeof(type));                                   \
    }

FP_MERGE_BATCH(fp_merge_stats, struct no_flow_stats)
FP_MERGE_BATCH(fp_merge_extra, struct no_extra_rec)
FP_MERGE_BATCH(fp_merge_drops, struct no_drops_rec)
FP_MERGE_BATCH(fp_merge_dns, struct no_dns_rec)
FP_MERGE_BATCH(fp_merge_nevents, struct no_nevents_rec)
FP_MERGE_BATCH(fp_merge_xlat, struct no_xlat_rec)
FP_MERGE_BATCH(fp_merge_quic, struct no_quic_rec)

// ---------------------------------------------------------------------------
// FLOW_EVENT interleave: compose contiguous no_flow_event rows (key 40B |
// stats 104B) from the two columns a batched map drain yields — the columnar
// eviction plane's single copy boundary done as one native pass instead of
// two strided numpy field assignments (python twin:
// model/binfmt.py events_from_keys_stats; equivalence pinned in
// tests/test_evict_parallel.py). `out` must hold n events; tail rows beyond
// n (the loader's ringbuf-orphan appendix) are the caller's to zero.
// ---------------------------------------------------------------------------
void fp_events_from_keys_stats(const uint8_t *keys, const uint8_t *stats,
                               size_t n, uint8_t *out) {
    for (size_t i = 0; i < n; i++) {
        struct no_flow_event *ev =
            reinterpret_cast<struct no_flow_event *>(
                out + i * sizeof(struct no_flow_event));
        std::memcpy(&ev->key, keys + i * sizeof(struct no_flow_key),
                    sizeof(struct no_flow_key));
        std::memcpy(&ev->stats, stats + i * sizeof(struct no_flow_stats),
                    sizeof(struct no_flow_stats));
    }
}

// crc32c (Castagnoli) — slice-by-8; used by the Kafka record-batch encoder.
static uint32_t crc32c_table[8][256];
static bool crc32c_ready = false;

static void crc32c_init() {
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? (c >> 1) ^ 0x82F63B78u : c >> 1;
        crc32c_table[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = crc32c_table[0][i];
        for (int t = 1; t < 8; t++) {
            c = crc32c_table[0][c & 0xFF] ^ (c >> 8);
            crc32c_table[t][i] = c;
        }
    }
    crc32c_ready = true;
}

#if defined(__x86_64__)
// Hardware CRC32C (SSE4.2) — ~10x the sliced table walk; the key
// dictionary hashes 40 bytes per record, so this sits on the pack hot path.
__attribute__((target("sse4.2")))
static uint32_t crc32c_hw(const uint8_t *data, size_t n) {
    uint64_t crc = 0xFFFFFFFFu;
    size_t i = 0;
    for (; n - i >= 8; i += 8) {
        uint64_t v;
        std::memcpy(&v, data + i, 8);
        crc = __builtin_ia32_crc32di(crc, v);
    }
    for (; i < n; i++)
        crc = __builtin_ia32_crc32qi(static_cast<uint32_t>(crc), data[i]);
    return static_cast<uint32_t>(crc) ^ 0xFFFFFFFFu;
}
static int crc32c_have_hw = -1;
#endif

uint32_t fp_crc32c(const uint8_t *data, size_t n) {
#if defined(__x86_64__)
    if (crc32c_have_hw < 0)
        crc32c_have_hw = __builtin_cpu_supports("sse4.2") ? 1 : 0;
    if (crc32c_have_hw)
        return crc32c_hw(data, n);
#endif
    if (!crc32c_ready)
        crc32c_init();
    uint32_t crc = 0xFFFFFFFFu;
    size_t i = 0;
    while (n - i >= 8) {
        uint32_t lo, hi;
        std::memcpy(&lo, data + i, 4);
        std::memcpy(&hi, data + i + 4, 4);
        crc ^= lo;
        crc = crc32c_table[7][crc & 0xFF] ^ crc32c_table[6][(crc >> 8) & 0xFF] ^
              crc32c_table[5][(crc >> 16) & 0xFF] ^
              crc32c_table[4][(crc >> 24) & 0xFF] ^
              crc32c_table[3][hi & 0xFF] ^ crc32c_table[2][(hi >> 8) & 0xFF] ^
              crc32c_table[1][(hi >> 16) & 0xFF] ^
              crc32c_table[0][(hi >> 24) & 0xFF];
        i += 8;
    }
    for (; i < n; i++)
        crc = crc32c_table[0][(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
    return crc ^ 0xFFFFFFFFu;
}

// ===========================================================================
// Fused one-call eviction pipeline (fp_drain_to_resident). ONE native call
// owns the whole host chain of a drain: batched bpf(2) lookup-and-delete
// over every map, per-CPU columnar merge, hash-sort key join (the
// loader._join_keys twin), feature alignment, and — optionally — the direct
// resident-region pack replicating ShardedResidentStagingRing._fold_chunk.
// The call releases the GIL for its whole duration (ctypes), so drain lanes
// scale with cores instead of re-entering the interpreter between islands.
//
// SCHEDULING ONLY: the merge semantics are the very fp_merge_*_batch calls
// above (never a fifth merge form), the pack is the very fp_pack_resident
// above (never a fourth resident layout), and the join replicates
// loader._join_keys bit-exactly (stable hash sort, collision fallback to the
// lexicographic order, orphan appendix in sorted-group order, last-agg-row
// match). tests/test_native_pipeline.py pins the fused output against the
// Python-orchestrated chain.
//
// Buffer ownership: per-map drain scratch, merged/aligned arrays, the event
// compose buffer and the chunk table live in the fp_pipe handle and are
// valid until the next fp_drain_to_resident call (the caller copies at the
// EvictedFlows boundary — the same cached-buffer lifetime rule as
// drain_batched_arrays). The packed arena is malloc'd fresh per call and
// ownership passes to the caller (fp_buf_free) because packed regions may
// outlive the next drain in the overlap handoff.
// ===========================================================================

enum {
    FPK_STATS = 0, FPK_EXTRA = 1, FPK_DNS = 2, FPK_DROPS = 3,
    FPK_NEVENTS = 4, FPK_XLAT = 5, FPK_QUIC = 6,
};

#define FP_PIPE_MAX_MAPS 8
#define FP_PIPE_MAX_LADDER 8
#define FP_BPF_LOOKUP_AND_DELETE_BATCH 25

#if defined(__linux__)
#if defined(SYS_bpf)
#define FP_SYS_BPF SYS_bpf
#elif defined(__x86_64__)
#define FP_SYS_BPF 321
#elif defined(__aarch64__) || defined(__riscv)
#define FP_SYS_BPF 280
#elif defined(__powerpc64__)
#define FP_SYS_BPF 361
#elif defined(__s390x__)
#define FP_SYS_BPF 351
#endif
#endif

struct fp_pipe_map_cfg {
    int32_t fd;            // >= 0: drain via batched bpf(2); < 0: injected
    uint32_t kind;         // FPK_*
    uint32_t value_size;   // sizeof record struct (8-aligned)
    uint32_t n_cpus;       // per-CPU images per entry (1 = plain map)
    uint32_t max_entries;  // drain capacity bound
};

struct fp_pipe_ladder {
    uint32_t k;             // superbatch ladder entry
    uint32_t nr;            // regions per k-chunk (n_shards * k * lanes)
    const uint64_t *dicts;  // [nr] fp_dict handles (ring.kdicts mapping)
};

struct fp_pipe_pack_cfg {
    uint32_t n_ladder, batch_size, batch_per_region, slot_cap;
    uint32_t dns_cap, drop_cap, nk_cap, spill_cap;
    struct fp_pipe_ladder ladder[FP_PIPE_MAX_LADDER];  // ascending k; [0].k==1
};

struct fp_pipe_chunk {
    uint64_t row_start;   // first event row of this chunk
    uint64_t rows;        // rows packed by this chunk
    uint64_t arena_off;   // word offset of the chunk's first segment
    uint32_t k, n_segs, spills, resets;
};

struct fp_pipe_result {
    uint64_t n_events, n_agg, n_orphans, packed_rows;
    uint64_t drain_ns, merge_ns, join_ns, pack_ns;   // drain/merge: summed lane CPU
    uint64_t syscalls, lex_fallback, batch_err_mask, n_chunks;
    uint64_t arena_words, spill_rows, dict_resets, segs;
    const uint8_t *events;               // [n_events] no_flow_event (handle-owned)
    uint32_t *arena;                     // packed regions (caller frees: fp_buf_free)
    const struct fp_pipe_chunk *chunks;  // [n_chunks] (handle-owned)
    const uint8_t *aligned[FP_PIPE_MAX_MAPS];  // per map; NULL when absent/empty
    uint64_t map_rows[FP_PIPE_MAX_MAPS];       // drained rows per map
};

struct fp_pipe_buf {
    uint8_t *p;
    size_t cap;
};

static int pipe_reserve(struct fp_pipe_buf *b, size_t need) {
    if (need == 0 || b->cap >= need)
        return 0;
    size_t cap = b->cap ? b->cap : 4096;
    while (cap < need)
        cap *= 2;
    uint8_t *np = static_cast<uint8_t *>(realloc(b->p, cap));
    if (!np)
        return -1;
    b->p = np;
    b->cap = cap;
    return 0;
}

struct fp_pipe_map_state {
    int32_t fd;
    uint32_t kind, value_size, n_cpus, max_entries;
    struct fp_pipe_buf keys, vals, merged, aligned;
    uint32_t n;       // drained rows this call (injected rows when fd < 0)
    int32_t err;      // last drain/merge errno (0 = ok)
    uint64_t drain_ns, merge_ns, syscalls;
    uint8_t tok_a[64], tok_b[64];  // batch iteration tokens (>= key size)
};

struct fp_pipe {
    uint32_t n_maps, lanes;
    struct fp_pipe_map_state maps[FP_PIPE_MAX_MAPS];
    struct fp_pipe_buf events, join;
    struct fp_pipe_chunk *chunks;
    size_t chunks_cap;
};

static uint64_t pipe_now_ns(void) {
#if defined(__linux__)
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return static_cast<uint64_t>(ts.tv_sec) * 1000000000ull +
           static_cast<uint64_t>(ts.tv_nsec);
#else
    return 0;
#endif
}

void *fp_pipe_new(const struct fp_pipe_map_cfg *cfgs, uint32_t n_maps,
                  uint32_t lanes) {
    if (!cfgs || n_maps == 0 || n_maps > FP_PIPE_MAX_MAPS)
        return NULL;
    if (cfgs[0].kind != FPK_STATS || cfgs[0].n_cpus != 1)
        return NULL;  // map 0 is the aggregation map, used verbatim
    for (uint32_t i = 0; i < n_maps; i++) {
        if (cfgs[i].kind > FPK_QUIC || cfgs[i].n_cpus == 0 ||
            cfgs[i].max_entries == 0 || cfgs[i].value_size == 0 ||
            cfgs[i].value_size % 8 != 0)  // padded stride == struct size
            return NULL;
    }
    struct fp_pipe *p =
        static_cast<struct fp_pipe *>(calloc(1, sizeof(struct fp_pipe)));
    if (!p)
        return NULL;
    p->n_maps = n_maps;
    p->lanes = lanes ? lanes : 1;
    for (uint32_t i = 0; i < n_maps; i++) {
        p->maps[i].fd = cfgs[i].fd;
        p->maps[i].kind = cfgs[i].kind;
        p->maps[i].value_size = cfgs[i].value_size;
        p->maps[i].n_cpus = cfgs[i].n_cpus;
        p->maps[i].max_entries = cfgs[i].max_entries;
    }
    return p;
}

void fp_pipe_free(void *h) {
    if (!h)
        return;
    struct fp_pipe *p = static_cast<struct fp_pipe *>(h);
    for (uint32_t i = 0; i < p->n_maps; i++) {
        free(p->maps[i].keys.p);
        free(p->maps[i].vals.p);
        free(p->maps[i].merged.p);
        free(p->maps[i].aligned.p);
    }
    free(p->events.p);
    free(p->join.p);
    free(p->chunks);
    free(p);
}

void fp_buf_free(void *ptr) { free(ptr); }

// Test/bench injection for fd < 0 maps: pre-load one drain's (keys, vals)
// as if the batched syscall had produced them. vals layout is the kernel's:
// n rows x n_cpus images x value_size bytes, contiguous.
int fp_pipe_set_drained(void *h, uint32_t idx, const uint8_t *keys,
                        const uint8_t *vals, uint32_t n) {
    struct fp_pipe *p = static_cast<struct fp_pipe *>(h);
    if (!p || idx >= p->n_maps || p->maps[idx].fd >= 0)
        return -1;
    struct fp_pipe_map_state *m = &p->maps[idx];
    size_t ks = sizeof(struct no_flow_key);
    size_t vstride = static_cast<size_t>(m->value_size) * m->n_cpus;
    if (pipe_reserve(&m->keys, n * ks) || pipe_reserve(&m->vals, n * vstride))
        return -1;
    if (n) {
        std::memcpy(m->keys.p, keys, n * ks);
        std::memcpy(m->vals.p, vals, n * vstride);
    }
    m->n = n;
    return 0;
}

// One map's batched lookup-and-delete loop — the drain_batched_arrays twin
// (same attr layout, same token handoff, same partial-round banking). The
// caller pre-probed batch support through the Python chain's first drain,
// so a hard error here is recorded, never retried per-key.
static void pipe_drain_map(struct fp_pipe_map_state *m) {
    m->err = 0;
    m->syscalls = 0;
    if (m->fd < 0)
        return;  // injected rows (fp_pipe_set_drained) stay as-is
    m->n = 0;
#if defined(__linux__) && defined(FP_SYS_BPF)
    const size_t ks = sizeof(struct no_flow_key);
    const size_t vstride = static_cast<size_t>(m->value_size) * m->n_cpus;
    if (pipe_reserve(&m->keys, static_cast<size_t>(m->max_entries) * ks) ||
        pipe_reserve(&m->vals, static_cast<size_t>(m->max_entries) * vstride)) {
        m->err = ENOMEM;
        return;
    }
    struct {
        uint64_t in_batch, out_batch, keys, values;
        uint32_t count, map_fd;
        uint64_t elem_flags, flags;
    } attr;
    bool first = true;
    uint32_t total = 0;
    while (total < m->max_entries) {
        std::memset(&attr, 0, sizeof(attr));
        attr.in_batch =
            first ? 0 : static_cast<uint64_t>(reinterpret_cast<uintptr_t>(m->tok_a));
        attr.out_batch =
            static_cast<uint64_t>(reinterpret_cast<uintptr_t>(m->tok_b));
        attr.keys = static_cast<uint64_t>(
            reinterpret_cast<uintptr_t>(m->keys.p + static_cast<size_t>(total) * ks));
        attr.values = static_cast<uint64_t>(reinterpret_cast<uintptr_t>(
            m->vals.p + static_cast<size_t>(total) * vstride));
        attr.count = m->max_entries - total;
        attr.map_fd = static_cast<uint32_t>(m->fd);
        long rc = syscall(FP_SYS_BPF, FP_BPF_LOOKUP_AND_DELETE_BATCH, &attr,
                          static_cast<unsigned long>(sizeof(attr)));
        int err = rc < 0 ? errno : 0;
        m->syscalls++;
        if (rc == 0 || err == ENOENT) {
            total += attr.count;  // partial counts on ENOENT are valid
        } else {
            m->err = err;  // keep banked rounds: their entries are deleted
            break;
        }
        if (rc < 0 || attr.count == 0)
            break;  // drained to empty
        std::memcpy(m->tok_a, m->tok_b, sizeof(m->tok_a));
        first = false;
    }
    m->n = total;
#else
    m->err = 38;  // ENOSYS: no bpf(2) on this platform — fd<0 mode only
#endif
}

static void pipe_merge_map(struct fp_pipe_map_state *m) {
    if (m->kind == FPK_STATS || m->n == 0)
        return;  // aggregation rows are used verbatim (no per-CPU images)
    size_t need = static_cast<size_t>(m->n) * m->value_size;
    if (pipe_reserve(&m->merged, need)) {
        m->err = ENOMEM;
        return;
    }
    switch (m->kind) {
    case FPK_EXTRA:
        fp_merge_extra_batch(m->vals.p, m->n, m->n_cpus, m->merged.p);
        break;
    case FPK_DNS:
        fp_merge_dns_batch(m->vals.p, m->n, m->n_cpus, m->merged.p);
        break;
    case FPK_DROPS:
        fp_merge_drops_batch(m->vals.p, m->n, m->n_cpus, m->merged.p);
        break;
    case FPK_NEVENTS:
        fp_merge_nevents_batch(m->vals.p, m->n, m->n_cpus, m->merged.p);
        break;
    case FPK_XLAT:
        fp_merge_xlat_batch(m->vals.p, m->n, m->n_cpus, m->merged.p);
        break;
    case FPK_QUIC:
        fp_merge_quic_batch(m->vals.p, m->n, m->n_cpus, m->merged.p);
        break;
    default:
        break;
    }
}

static void pipe_run_map(struct fp_pipe_map_state *m) {
    uint64_t t0 = pipe_now_ns();
    pipe_drain_map(m);
    uint64_t t1 = pipe_now_ns();
    pipe_merge_map(m);
    m->drain_ns = t1 - t0;
    m->merge_ns = pipe_now_ns() - t1;
}

#if defined(__linux__)
struct fp_pipe_job {
    struct fp_pipe *p;
    uint32_t next;
    pthread_mutex_t mu;
};

static void *pipe_worker(void *arg) {
    struct fp_pipe_job *job = static_cast<struct fp_pipe_job *>(arg);
    for (;;) {
        pthread_mutex_lock(&job->mu);
        uint32_t i = job->next++;
        pthread_mutex_unlock(&job->mu);
        if (i >= job->p->n_maps)
            return NULL;
        pipe_run_map(&job->p->maps[i]);
    }
}
#endif

// loader._hash_keys_u64 twin: the join's pre-sort hash over the 5 key words.
static inline uint64_t pipe_key_hash(const uint8_t *k) {
    uint64_t w[5];
    std::memcpy(w, k, sizeof(w));
    uint64_t h = w[0];
    for (int i = 1; i < 5; i++) {
        h = (h ^ (w[i] * 0xC2B2AE3D27D4EB4FULL)) * 0x9E3779B97F4A7C15ULL;
        h ^= h >> 29;  // per-round mix, exactly like the numpy twin
    }
    return h;
}

static int64_t pipe_pack(struct fp_pipe *p, const struct fp_pipe_pack_cfg *pk,
                         struct fp_pipe_result *res) {
    const uint64_t n_events = res->n_events;
    if (pk->n_ladder == 0 || pk->n_ladder > FP_PIPE_MAX_LADDER ||
        pk->ladder[0].k != 1 || pk->batch_per_region == 0 ||
        pk->spill_cap == 0 || pk->nk_cap == 0)
        return -2;
    const size_t region_words =
        FP_RESIDENT_HDR + static_cast<size_t>(pk->batch_per_region) * FP_HOT_WORDS +
        pk->dns_cap + static_cast<size_t>(pk->drop_cap) * 2 +
        static_cast<size_t>(pk->nk_cap) * FP_NK_WORDS +
        static_cast<size_t>(pk->spill_cap) * FP_DENSE_WORDS;
    // per-kind aligned feature bases the resident pack consumes (nevents
    // rides EvictedFlows only — the fold lanes never carry it)
    const uint8_t *ali[FPK_QUIC + 1] = {NULL, NULL, NULL, NULL, NULL, NULL, NULL};
    for (uint32_t i = 1; i < p->n_maps; i++)
        if (p->maps[i].n)
            ali[p->maps[i].kind] = p->maps[i].aligned.p;
    uint32_t *arena = NULL;
    size_t arena_cap_words = 0, arena_words = 0;
    uint64_t row = 0, starts[1u << 10];
    while (row < n_events) {
        const uint64_t remaining = n_events - row;
        // the ring's ladder rule: largest available k whose k*batch fits
        uint32_t sel = 0;
        for (uint32_t L = 0; L < pk->n_ladder; L++)
            if (static_cast<uint64_t>(pk->ladder[L].k) * pk->batch_size <=
                remaining)
                sel = L;
        const struct fp_pipe_ladder *lad = &pk->ladder[sel];
        const uint32_t nr = lad->nr;
        if (nr == 0 || nr > (1u << 10)) {
            free(arena);
            return -2;
        }
        const uint64_t take =
            remaining < static_cast<uint64_t>(lad->k) * pk->batch_size
                ? remaining
                : static_cast<uint64_t>(lad->k) * pk->batch_size;
        // chunk bookkeeping
        if (res->n_chunks >= p->chunks_cap) {
            size_t cap = p->chunks_cap ? p->chunks_cap * 2 : 16;
            struct fp_pipe_chunk *nc = static_cast<struct fp_pipe_chunk *>(
                realloc(p->chunks, cap * sizeof(*nc)));
            if (!nc) {
                free(arena);
                return -1;
            }
            p->chunks = nc;
            p->chunks_cap = cap;
        }
        struct fp_pipe_chunk *ch = &p->chunks[res->n_chunks];
        std::memset(ch, 0, sizeof(*ch));
        ch->row_start = row;
        ch->rows = take;
        ch->k = lad->k;
        ch->arena_off = arena_words;
        for (uint32_t i = 0; i < nr; i++)
            starts[i] = 0;
        bool done = false;
        while (!done) {
            // one segment = one shipped ring-slot image of nr regions (the
            // continuation loop of _fold_chunk)
            size_t need_words = arena_words + static_cast<size_t>(nr) * region_words;
            if (need_words > arena_cap_words) {
                size_t cap = arena_cap_words ? arena_cap_words : 65536;
                while (cap < need_words)
                    cap *= 2;
                uint32_t *na =
                    static_cast<uint32_t *>(realloc(arena, cap * sizeof(uint32_t)));
                if (!na) {
                    free(arena);
                    return -1;
                }
                arena = na;
                arena_cap_words = cap;
            }
            done = true;
            for (uint32_t i = 0; i < nr; i++) {
                uint32_t *region = arena + arena_words + i * region_words;
                const uint64_t lo = row + take * i / nr;
                const uint64_t hi = row + take * (i + 1) / nr;
                const uint64_t len = hi - lo;
                if (starts[i] >= len) {
                    // exhausted region in a continuation segment: the
                    // zero_resident_region mask, done as a full memset so
                    // the arena is deterministic (the device reads only the
                    // validity words either way)
                    std::memset(region, 0, region_words * sizeof(uint32_t));
                    continue;
                }
                fp_dict *d = reinterpret_cast<fp_dict *>(
                    static_cast<uintptr_t>(lad->dicts[i]));
                if (d->next_slot >= pk->slot_cap) {
                    fp_dict_reset(d);  // per-region epoch roll (_fold_chunk)
                    ch->resets++;
                }
                int64_t consumed = fp_pack_resident(
                    reinterpret_cast<const uint8_t *>(
                        reinterpret_cast<const struct no_flow_event *>(
                            p->events.p) + lo),
                    starts[i], len,
                    ali[FPK_EXTRA] ? ali[FPK_EXTRA] + lo * sizeof(struct no_extra_rec) : NULL,
                    ali[FPK_DNS] ? ali[FPK_DNS] + lo * sizeof(struct no_dns_rec) : NULL,
                    ali[FPK_DROPS] ? ali[FPK_DROPS] + lo * sizeof(struct no_drops_rec) : NULL,
                    ali[FPK_XLAT] ? ali[FPK_XLAT] + lo * sizeof(struct no_xlat_rec) : NULL,
                    ali[FPK_QUIC] ? ali[FPK_QUIC] + lo * sizeof(struct no_quic_rec) : NULL,
                    d, region, pk->batch_per_region, pk->dns_cap, pk->drop_cap,
                    pk->nk_cap, pk->spill_cap);
                if (consumed <= 0) {
                    free(arena);
                    return -3;  // no progress: caps violate the guarantee
                }
                ch->spills += region[2];
                starts[i] += static_cast<uint64_t>(consumed);
                if (starts[i] < len)
                    done = false;
            }
            arena_words += static_cast<size_t>(nr) * region_words;
            ch->n_segs++;
        }
        res->spill_rows += ch->spills;
        res->dict_resets += ch->resets;
        res->segs += ch->n_segs;
        res->n_chunks++;
        row += take;
    }
    res->arena = arena;
    res->arena_words = arena_words;
    res->packed_rows = n_events;
    res->chunks = p->chunks;
    return 0;
}

// The fused drain: every map's batched drain + per-CPU merge (fanned out
// over `lanes` worker threads), the key join + feature alignment, and —
// when `pack` is non-NULL — the resident-region pack. Returns n_events
// (>= 0) or a negative error (-1 alloc, -2 bad args, -3 pack stuck).
int64_t fp_drain_to_resident(void *h, const struct fp_pipe_pack_cfg *pack,
                             struct fp_pipe_result *res) {
    struct fp_pipe *p = static_cast<struct fp_pipe *>(h);
    if (!p || !res)
        return -2;
    std::memset(res, 0, sizeof(*res));
    // ---- drain + merge (per-map, worker fan-out) ----
    uint32_t nw = p->lanes < p->n_maps ? p->lanes : p->n_maps;
#if defined(__linux__)
    if (nw > 1) {
        struct fp_pipe_job job;
        job.p = p;
        job.next = 0;
        pthread_mutex_init(&job.mu, NULL);
        pthread_t tids[FP_PIPE_MAX_MAPS];
        uint32_t started = 0;
        for (uint32_t t = 0; t + 1 < nw; t++)
            if (pthread_create(&tids[started], NULL, pipe_worker, &job) == 0)
                started++;
        pipe_worker(&job);  // the calling thread is a worker too
        for (uint32_t t = 0; t < started; t++)
            pthread_join(tids[t], NULL);
        pthread_mutex_destroy(&job.mu);
    } else
#endif
    {
        for (uint32_t i = 0; i < p->n_maps; i++)
            pipe_run_map(&p->maps[i]);
    }
    uint64_t total = 0;
    for (uint32_t i = 0; i < p->n_maps; i++) {
        struct fp_pipe_map_state *m = &p->maps[i];
        res->drain_ns += m->drain_ns;
        res->merge_ns += m->merge_ns;
        res->syscalls += m->syscalls;
        res->map_rows[i] = m->n;
        total += m->n;
        if (m->err == ENOMEM)
            return -1;
        if (m->err)
            res->batch_err_mask |= 1ull << i;
    }
    const uint64_t n_agg = p->maps[0].n;
    // ---- join (loader._join_keys twin) + event compose + alignment ----
    uint64_t t_join = pipe_now_ns();
    const size_t ptr_sz = sizeof(const uint8_t *);
    if (pipe_reserve(&p->join, total * (2 * ptr_sz + 5 * sizeof(uint64_t))))
        return -1;
    const uint8_t **kp = reinterpret_cast<const uint8_t **>(p->join.p);
    const uint8_t **app_key = kp + total;
    uint64_t *hs = reinterpret_cast<uint64_t *>(app_key + total);
    uint64_t *ord = hs + total;
    uint64_t *feat_eidx = ord + total;
    uint64_t *app_first = feat_eidx + total;
    uint64_t *app_last = app_first + total;
    {
        uint64_t g = 0;
        for (uint32_t mi = 0; mi < p->n_maps; mi++) {
            struct fp_pipe_map_state *m = &p->maps[mi];
            for (uint32_t r = 0; r < m->n; r++, g++) {
                kp[g] = m->keys.p + static_cast<size_t>(r) * sizeof(struct no_flow_key);
                hs[g] = pipe_key_hash(kp[g]);
                ord[g] = g;
            }
        }
    }
    std::sort(ord, ord + total, [hs](uint64_t a, uint64_t b) {
        return hs[a] != hs[b] ? hs[a] < hs[b] : a < b;  // stable argsort twin
    });
    // collision check: distinct keys vs distinct hashes over the sort
    uint64_t key_groups = total ? 1 : 0, hash_groups = total ? 1 : 0;
    for (uint64_t j = 1; j < total; j++) {
        if (std::memcmp(kp[ord[j]], kp[ord[j - 1]], sizeof(struct no_flow_key)))
            key_groups++;
        if (hs[ord[j]] != hs[ord[j - 1]])
            hash_groups++;
    }
    if (key_groups != hash_groups) {
        // u64 hash collision (~never): the exact lexicographic order twin
        res->lex_fallback = 1;
        std::sort(ord, ord + total, [kp](uint64_t a, uint64_t b) {
            uint64_t wa[5], wb[5];
            std::memcpy(wa, kp[a], sizeof(wa));
            std::memcpy(wb, kp[b], sizeof(wb));
            for (int i = 0; i < 5; i++)
                if (wa[i] != wb[i])
                    return wa[i] < wb[i];
            return a < b;
        });
    }
    // group walk: stable sort puts agg members (src < n_agg) first in each
    // group, so the match is the LAST agg member; groups with none append
    // one orphan event, in sorted-group order (the searchsorted twin)
    uint64_t n_app = 0;
    {
        uint64_t a = 0;
        while (a < total) {
            uint64_t b = a + 1;
            while (b < total && !std::memcmp(kp[ord[b]], kp[ord[a]],
                                             sizeof(struct no_flow_key)))
                b++;
            int64_t agg_max = -1;
            for (uint64_t j = a; j < b && ord[j] < n_agg; j++)
                agg_max = static_cast<int64_t>(ord[j]);
            uint64_t eidx;
            if (agg_max >= 0) {
                eidx = static_cast<uint64_t>(agg_max);
            } else {
                app_key[n_app] = kp[ord[a]];
                app_first[n_app] = UINT64_MAX;
                app_last[n_app] = 0;
                eidx = n_agg + n_app++;
            }
            for (uint64_t j = a; j < b; j++)
                if (ord[j] >= n_agg)
                    feat_eidx[ord[j] - n_agg] = eidx;
            a = b;
        }
    }
    const uint64_t n_events = n_agg + n_app;
    res->n_events = n_events;
    res->n_agg = n_agg;
    res->n_orphans = n_app;
    if (pipe_reserve(&p->events, n_events * sizeof(struct no_flow_event)))
        return -1;
    std::memset(p->events.p, 0, n_events * sizeof(struct no_flow_event));
    if (n_agg)
        fp_events_from_keys_stats(p->maps[0].keys.p, p->maps[0].vals.p, n_agg,
                                  p->events.p);
    struct no_flow_event *ev =
        reinterpret_cast<struct no_flow_event *>(p->events.p);
    for (uint64_t a = 0; a < n_app; a++)
        std::memcpy(&ev[n_agg + a].key, app_key[a],
                    sizeof(struct no_flow_key));
    // feature alignment: scatter merged rows to their event row (ascending —
    // duplicate keys across drain chunks: last wins, like `out[idx] = recs`)
    uint64_t fbase = 0;
    for (uint32_t mi = 1; mi < p->n_maps; mi++) {
        struct fp_pipe_map_state *m = &p->maps[mi];
        if (m->n == 0 || n_events == 0) {
            fbase += m->n;
            continue;
        }
        const size_t vs = m->value_size;
        if (pipe_reserve(&m->aligned, n_events * vs))
            return -1;
        std::memset(m->aligned.p, 0, n_events * vs);
        for (uint32_t r = 0; r < m->n; r++) {
            const uint8_t *rec = m->merged.p + static_cast<size_t>(r) * vs;
            const uint64_t e = feat_eidx[fbase + r];
            std::memcpy(m->aligned.p + e * vs, rec, vs);
            if (e >= n_agg) {
                // orphan times: every record type leads with first/last u64s
                uint64_t ft, lt;
                std::memcpy(&ft, rec, 8);
                std::memcpy(&lt, rec + 8, 8);
                uint64_t *af = &app_first[e - n_agg];
                uint64_t *al = &app_last[e - n_agg];
                if (ft == 0)
                    ft = UINT64_MAX;  // the 0 -> U64_MAX sentinel (loader)
                if (ft < *af)
                    *af = ft;
                if (lt > *al)
                    *al = lt;
            }
        }
        res->aligned[mi] = m->aligned.p;
        fbase += m->n;
    }
    for (uint64_t a = 0; a < n_app; a++) {
        ev[n_agg + a].stats.first_seen_ns =
            app_first[a] == UINT64_MAX ? 0 : app_first[a];
        ev[n_agg + a].stats.last_seen_ns = app_last[a];
    }
    res->events = p->events.p;
    res->join_ns = pipe_now_ns() - t_join;
    // ---- resident-region pack (_fold_chunk twin) ----
    if (pack && n_events) {
        uint64_t t_pack = pipe_now_ns();
        int64_t rc = pipe_pack(p, pack, res);
        res->pack_ns = pipe_now_ns() - t_pack;
        if (rc < 0)
            return rc;
    }
    return static_cast<int64_t>(n_events);
}

#ifndef FP_ABI_VERSION
#define FP_ABI_VERSION 10
#endif

uint32_t fp_abi_version(void) { return FP_ABI_VERSION; }

}  // extern "C"
