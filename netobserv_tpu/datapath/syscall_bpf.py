"""Direct bpf(2) syscall access to BPF maps — no libbpf dependency.

Powers EBPF_PROGRAM_MANAGER_MODE (bpfman): an external lifecycle manager owns
the programs and pins the maps on bpffs; the agent opens the pinned maps and
evicts through them (reference analog: `pkg/tracer/tracer.go:275-384`). Also
used by tests to create scratch maps and exercise the real kernel eviction
path where CAP_BPF is available.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import errno
import logging
import os
import platform
import struct
from typing import Optional

import numpy as np

log = logging.getLogger("netobserv_tpu.datapath.syscall_bpf")

# syscall numbers for bpf(2)
_SYSCALL_TABLE = {
    "x86_64": 321,
    "aarch64": 280,
    "ppc64le": 361,
    "s390x": 351,
    "riscv64": 280,
}
_MACHINE = platform.machine()
if _MACHINE not in _SYSCALL_TABLE:
    raise ImportError(
        f"bpf(2) syscall number unknown for architecture {_MACHINE!r}")
_SYSCALL_NR = _SYSCALL_TABLE[_MACHINE]

# bpf(2) commands
BPF_MAP_CREATE = 0
BPF_MAP_LOOKUP_ELEM = 1
BPF_MAP_UPDATE_ELEM = 2
BPF_MAP_DELETE_ELEM = 3
BPF_MAP_GET_NEXT_KEY = 4
BPF_OBJ_PIN = 6
BPF_OBJ_GET = 7
BPF_MAP_LOOKUP_AND_DELETE_ELEM = 21
BPF_OBJ_GET_INFO_BY_FD = 15
BPF_MAP_LOOKUP_AND_DELETE_BATCH = 25  # only the delete variant is used here

# per-CPU map types (kernel enum bpf_map_type, uapi/linux/bpf.h): values
# cross the syscall boundary at round_up(value_size, 8) per possible CPU.
# PERCPU_HASH=5, PERCPU_ARRAY=6, LRU_PERCPU_HASH=10, PERCPU_CGROUP_STORAGE=21
PERCPU_MAP_TYPES = frozenset({5, 6, 10, 21})

# kernel-internal "operation not supported" — what BPF_DO_BATCH returns when
# the map type has no batch ops; distinct from errno.ENOTSUP (95) and has no
# errno.h name, so spell it out
ENOTSUPP_KERNEL = 524

BPF_ANY = 0
BPF_NOEXIST = 1

_libc = ctypes.CDLL(None, use_errno=True)


def _bpf(cmd: int, attr: bytes) -> int:
    buf = ctypes.create_string_buffer(attr, len(attr))
    ret = _libc.syscall(_SYSCALL_NR, cmd, buf, len(attr))
    if ret < 0:
        err = ctypes.get_errno()
        raise OSError(err, os.strerror(err))
    return ret


def _bpf_inout(cmd: int, attr: bytearray) -> int:
    buf = (ctypes.c_char * len(attr)).from_buffer(attr)
    ret = _libc.syscall(_SYSCALL_NR, cmd, buf, len(attr))
    if ret < 0:
        err = ctypes.get_errno()
        raise OSError(err, os.strerror(err))
    return ret


class BpfMap:
    """One open BPF map fd with typed key/value byte access."""

    def __init__(self, fd: int, key_size: int, value_size: int,
                 max_entries: int = 0, n_cpus: int = 1,
                 percpu: bool = False):
        self.fd = fd
        self.key_size = key_size
        self.value_size = value_size
        self.max_entries = max_entries
        self.n_cpus = n_cpus  # per-CPU maps: values are per-cpu arrays
        # per-CPU-ness must come from the map TYPE, not n_cpus>1: on a
        # 1-CPU machine a per-CPU map still crosses the syscall boundary at
        # the kernel's round_up(value_size, 8) stride
        self.percpu = percpu
        self._no_lookup_and_delete = False  # latched capability probe
        self._no_batch_ops = False          # latched (kernels < 5.6)
        self._batch_bufs = None             # cached drain_batched buffers

    # --- constructors ---
    @classmethod
    def create(cls, map_type: int, key_size: int, value_size: int,
               max_entries: int, name: bytes = b"",
               flags: int = 0) -> "BpfMap":
        attr = struct.pack("=IIII", map_type, key_size, value_size,
                           max_entries)
        attr += struct.pack("=I", flags)  # map_flags (LPM needs NO_PREALLOC)
        attr += b"\x00" * 4  # inner_map_fd
        attr += b"\x00" * 4  # numa_node
        attr += name[:15].ljust(16, b"\x00")
        fd = _bpf(BPF_MAP_CREATE, attr)
        percpu = map_type in PERCPU_MAP_TYPES
        return cls(fd, key_size, value_size, max_entries,
                   # per-CPU buffers must span every possible CPU from the
                   # start — waiting for call sites to set n_cpus is how
                   # value-buffer overruns happen
                   n_cpus=n_possible_cpus() if percpu else 1,
                   percpu=percpu)

    def pin(self, path: str) -> None:
        pathbuf = ctypes.create_string_buffer(path.encode() + b"\x00")
        attr = struct.pack("=QI", ctypes.addressof(pathbuf), self.fd)
        _bpf(BPF_OBJ_PIN, attr)

    @staticmethod
    def get_info(fd: int) -> tuple[int, int, int, int]:
        """(map_type, key_size, value_size, max_entries) via
        BPF_OBJ_GET_INFO_BY_FD."""
        info = ctypes.create_string_buffer(88)  # struct bpf_map_info
        attr = struct.pack("=IIQ", fd, len(info), ctypes.addressof(info))
        _bpf(BPF_OBJ_GET_INFO_BY_FD, attr)
        map_type, _id, key_size, value_size, max_entries = struct.unpack_from(
            "=IIIII", info.raw, 0)
        return map_type, key_size, value_size, max_entries

    @classmethod
    def open_pinned(cls, path: str, key_size: int, value_size: int,
                    n_cpus: Optional[int] = None) -> "BpfMap":
        pathbuf = path.encode() + b"\x00"
        str_ptr = ctypes.create_string_buffer(pathbuf)
        attr = struct.pack("=Q", ctypes.addressof(str_ptr))
        fd = _bpf(BPF_OBJ_GET, attr)
        # validate the pinned map's REAL sizes: a layout mismatch would let
        # the kernel write past our value buffer (heap corruption)
        _mtype, real_key, real_value, _max_entries = cls.get_info(fd)
        if real_key != key_size or real_value != value_size:
            os.close(fd)
            raise ValueError(
                f"pinned map {path} layout mismatch: kernel has "
                f"key={real_key}/value={real_value}, expected "
                f"key={key_size}/value={value_size} (datapath version skew?)")
        percpu = _mtype in PERCPU_MAP_TYPES
        if n_cpus is None:
            # per-CPU buffers must span every possible CPU from the start;
            # relying on callers to pass n_cpus is how overruns happen
            n_cpus = n_possible_cpus() if percpu else 1
        return cls(fd, key_size, value_size, _max_entries, n_cpus=n_cpus,
                   percpu=percpu)

    # --- element ops ---
    # Per-CPU maps: the kernel transfers round_up(value_size, 8) bytes per
    # CPU (kernel/bpf/syscall.c bpf_map_value_size) in BOTH directions —
    # buffers must use the padded stride or copy_to_user overruns them for
    # any non-8-aligned value struct. The public API keeps the unpadded
    # value_size * n_cpus concatenation.
    @property
    def _pad_vs(self) -> int:
        return ((self.value_size + 7) & ~7) if self.percpu \
            else self.value_size

    def _unpad_value(self, raw: bytes) -> bytes:
        pad = self._pad_vs
        if pad == self.value_size:
            return raw[:self.value_size * self.n_cpus]
        return b"".join(raw[c * pad:c * pad + self.value_size]
                        for c in range(self.n_cpus))

    def _ptr_attr(self, key: bytes, value_buf=None, flags: int = 0) -> tuple:
        kbuf = ctypes.create_string_buffer(key, self.key_size)
        vbuf = value_buf if value_buf is not None else \
            ctypes.create_string_buffer(self._pad_vs * self.n_cpus)
        attr = struct.pack("=IxxxxQQQ", self.fd, ctypes.addressof(kbuf),
                           ctypes.addressof(vbuf), flags)
        return attr, kbuf, vbuf

    def update(self, key: bytes, value: bytes, flags: int = BPF_ANY) -> None:
        pad, vs = self._pad_vs, self.value_size
        if pad != vs and len(value) == vs * self.n_cpus:
            value = b"".join(value[c * vs:(c + 1) * vs].ljust(pad, b"\x00")
                             for c in range(self.n_cpus))
        vbuf = ctypes.create_string_buffer(value, pad * self.n_cpus)
        attr, _k, _v = self._ptr_attr(key, vbuf, flags)
        _bpf(BPF_MAP_UPDATE_ELEM, attr)

    def lookup(self, key: bytes) -> Optional[bytes]:
        attr, _k, vbuf = self._ptr_attr(key)
        try:
            _bpf(BPF_MAP_LOOKUP_ELEM, attr)
        except OSError as exc:
            if exc.errno == errno.ENOENT:
                return None
            raise
        return self._unpad_value(vbuf.raw)

    def lookup_and_delete(self, key: bytes) -> Optional[bytes]:
        attr, _k, vbuf = self._ptr_attr(key)
        try:
            _bpf(BPF_MAP_LOOKUP_AND_DELETE_ELEM, attr)
        except OSError as exc:
            if exc.errno == errno.ENOENT:
                return None
            if exc.errno in (errno.EINVAL, errno.ENOTSUP, errno.EPERM):
                raise NotImplementedError(
                    "LOOKUP_AND_DELETE unsupported for this map/kernel") from exc
            raise
        return self._unpad_value(vbuf.raw)

    def delete(self, key: bytes) -> bool:
        kbuf = ctypes.create_string_buffer(key, self.key_size)
        attr = struct.pack("=IxxxxQQQ", self.fd, ctypes.addressof(kbuf), 0, 0)
        try:
            _bpf(BPF_MAP_DELETE_ELEM, attr)
            return True
        except OSError as exc:
            if exc.errno == errno.ENOENT:
                return False
            raise

    def next_key(self, key: Optional[bytes]) -> Optional[bytes]:
        kbuf = ctypes.create_string_buffer(
            key if key is not None else b"\x00" * self.key_size, self.key_size)
        nbuf = ctypes.create_string_buffer(self.key_size)
        attr = struct.pack("=IxxxxQQQ", self.fd,
                           0 if key is None else ctypes.addressof(kbuf),
                           ctypes.addressof(nbuf), 0)
        try:
            _bpf(BPF_MAP_GET_NEXT_KEY, attr)
        except OSError as exc:
            if exc.errno == errno.ENOENT:
                return None
            raise
        return nbuf.raw

    def keys(self) -> list[bytes]:
        out = []
        key = self.next_key(None)
        while key is not None:
            out.append(key)
            key = self.next_key(key)
        return out

    def drain_batched_arrays(
            self, chunk: int = 2048
    ) -> Optional[tuple[np.ndarray, np.ndarray]]:
        """Bulk eviction via BPF_MAP_LOOKUP_AND_DELETE_BATCH, decoded
        straight from the syscall buffers: returns ``(keys, values)`` u8
        arrays of shape ``(n, key_size)`` and ``(n, padded_value_stride)``.

        ZERO-COPY CONTRACT: when the drain completes in one syscall round
        (the steady state — `chunk` is clamped to the map size), the returned
        arrays are VIEWS of the cached ``_batch_bufs`` storage and are
        INVALIDATED by the next drain on this map. Callers copy exactly once,
        at their output boundary (the columnar eviction plane copies at
        EvictedFlows construction — pinned by the aliasing regression in
        tests/test_bpfman.py). Multi-round drains concatenate (fresh
        arrays). Per-CPU values keep the kernel's round_up(value_size, 8)
        stride; every record dtype in binfmt is 8-aligned, so the stride is
        normally the plain itemsize.

        Returns None (latched) when the kernel or map type doesn't support
        batch ops (< 5.6)."""
        if self._no_batch_ops:
            return None
        vstride = self._pad_vs * self.n_cpus
        # no point sizing rounds past the map itself; buffers are cached on
        # the object so steady-state eviction ticks don't re-zero hundreds
        # of KB per drain
        if self.max_entries:
            chunk = min(chunk, self.max_entries)
        # the batch token is opaque (u32 bucket cursor for hash maps); size
        # it generously and let the kernel use what it needs
        tok_a = ctypes.create_string_buffer(max(self.key_size, 8))
        tok_b = ctypes.create_string_buffer(max(self.key_size, 8))
        cached = self._batch_bufs
        if cached is not None and cached[0] >= chunk:
            _cap, kbuf, vbuf = cached  # reuse storage; keep caller's chunk
        else:
            kbuf = ctypes.create_string_buffer(self.key_size * chunk)
            vbuf = ctypes.create_string_buffer(vstride * chunk)
            self._batch_bufs = (chunk, kbuf, vbuf)
        done_k: list[np.ndarray] = []  # banked earlier rounds (copies)
        done_v: list[np.ndarray] = []
        pend_k = pend_v = None         # latest round: views into kbuf/vbuf

        def result() -> tuple[np.ndarray, np.ndarray]:
            if not done_k:
                if pend_k is None:
                    return (np.empty((0, self.key_size), np.uint8),
                            np.empty((0, vstride), np.uint8))
                return pend_k, pend_v  # single round: zero-copy views
            ks = done_k + ([pend_k] if pend_k is not None else [])
            vs = done_v + ([pend_v] if pend_v is not None else [])
            return np.concatenate(ks), np.concatenate(vs)

        first = True
        while True:
            if pend_k is not None:
                # the buffers are about to be rewritten: bank this round
                done_k.append(pend_k.copy())
                done_v.append(pend_v.copy())
                pend_k = pend_v = None
            attr = bytearray(struct.pack(
                "=QQQQIIQQ",
                0 if first else ctypes.addressof(tok_a),
                ctypes.addressof(tok_b),
                ctypes.addressof(kbuf), ctypes.addressof(vbuf),
                chunk, self.fd, 0, 0))
            done = False
            try:
                _bpf_inout(BPF_MAP_LOOKUP_AND_DELETE_BATCH, attr)
            except OSError as exc:
                if exc.errno == errno.ENOENT:
                    done = True          # iterated to the end; count is valid
                elif exc.errno == errno.ENOSPC:
                    # a single bucket holds more entries than `chunk`
                    chunk *= 2
                    kbuf = ctypes.create_string_buffer(self.key_size * chunk)
                    vbuf = ctypes.create_string_buffer(vstride * chunk)
                    self._batch_bufs = (chunk, kbuf, vbuf)
                    continue
                elif (first and not done_k
                      and exc.errno in (errno.EINVAL, errno.EPERM,
                                        errno.ENOTSUP, ENOTSUPP_KERNEL)):
                    self._no_batch_ops = True
                    return None
                elif done_k:
                    # banked entries are already DELETED from the kernel
                    # map; raising would lose them for good (the per-key
                    # idiom loses at most one). Return the partial drain —
                    # the remainder is picked up next eviction tick.
                    log.warning(
                        "batched drain aborted mid-iteration after %d "
                        "entries: %s (returning partial result)",
                        sum(len(k) for k in done_k), exc)
                    return result()
                else:
                    raise
            count = struct.unpack_from("=I", attr, 32)[0]
            if count:
                pend_k = np.frombuffer(
                    kbuf, dtype=np.uint8, count=count * self.key_size
                ).reshape(count, self.key_size)
                pend_v = np.frombuffer(
                    vbuf, dtype=np.uint8, count=count * vstride
                ).reshape(count, vstride)
            if done or count == 0:
                return result()
            ctypes.memmove(tok_a, tok_b, len(tok_b))
            first = False

    def drain_batched(self,
                      chunk: int = 2048) -> Optional[list[tuple[bytes, bytes]]]:
        """Bulk eviction via BPF_MAP_LOOKUP_AND_DELETE_BATCH: one syscall per
        `chunk` entries instead of two per entry — the batched analog of the
        reference's per-key eviction loop (`tracer.go:1022-1054`). The pairs
        view over drain_batched_arrays (values re-packed to the unpadded
        concatenation); returns None (latched) when the kernel or map type
        doesn't support batch ops (< 5.6)."""
        res = self.drain_batched_arrays(chunk)
        if res is None:
            return None
        keys, vals = res
        return [(keys[i].tobytes(), self._unpad_value(vals[i].tobytes()))
                for i in range(len(keys))]

    def drain(self) -> list[tuple[bytes, bytes]]:
        """Eviction: batched lookup-and-delete when the kernel supports it,
        else the two-phase per-key idiom (iterate keys, then lookup-and-
        delete each, falling back to lookup+delete on old kernels, latched
        after the first failure) — the reference's eviction loop
        (`tracer.go:1022-1054`, legacy `tracer_legacy.go:11-35`)."""
        batched = self.drain_batched()
        if batched is not None:
            return batched
        out = []
        for key in self.keys():
            if self._no_lookup_and_delete:
                val = self.lookup(key)
                self.delete(key)
            else:
                try:
                    val = self.lookup_and_delete(key)
                except NotImplementedError:
                    self._no_lookup_and_delete = True
                    val = self.lookup(key)
                    self.delete(key)
            if val is not None:
                out.append((key, val))
        return out

    def close(self) -> None:
        try:
            os.close(self.fd)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# program load: raw instruction assembly + BPF_PROG_LOAD
# ---------------------------------------------------------------------------
BPF_PROG_LOAD = 5
BPF_PROG_TYPE_KPROBE = 2
BPF_PROG_TYPE_SCHED_CLS = 3
BPF_PROG_TYPE_TRACEPOINT = 5


def insn(opcode: int, dst: int = 0, src: int = 0, off: int = 0,
         imm: int = 0) -> bytes:
    """Encode one eBPF instruction (delegates to the single encoding
    definition in datapath.asm)."""
    from netobserv_tpu.datapath.asm import encode
    return encode(opcode, dst, src, off, imm)


def ld_map_fd(dst: int, map_fd: int) -> bytes:
    """BPF_LD_IMM64 with BPF_PSEUDO_MAP_FD (two instruction slots)."""
    from netobserv_tpu.datapath.asm import encode_ld_map_fd
    return encode_ld_map_fd(dst, map_fd)


def packet_counter_prog(map_fd: int) -> bytes:
    """A minimal TC classifier: atomically bump slot 0 of an array map and
    pass the packet. Used to validate the load/attach path end-to-end with a
    real program when no compiler is available."""
    return b"".join([
        insn(0x62, 10, 0, -4, 0),      # *(u32*)(r10-4) = 0   (key)
        insn(0xBF, 2, 10),             # r2 = r10
        insn(0x07, 2, 0, 0, -4),       # r2 += -4
        ld_map_fd(1, map_fd),          # r1 = map
        insn(0x85, 0, 0, 0, 1),        # call map_lookup_elem
        insn(0x15, 0, 0, 3, 0),        # if r0 == 0 goto +3
        insn(0xB7, 1, 0, 0, 1),        # r1 = 1
        insn(0xDB, 0, 1, 0, 0x00),     # lock *(u64*)(r0+0) += r1
        insn(0xB7, 0, 0, 0, 0),        # r0 = TC_ACT_OK
        insn(0x95),                    # exit
    ])


def prog_load(insns: bytes, prog_type: int = BPF_PROG_TYPE_SCHED_CLS,
              license_: bytes = b"GPL", name: bytes = b"netobserv") -> int:
    """BPF_PROG_LOAD; returns the program fd (raises OSError with the
    verifier log on rejection).

    libbpf's two-phase strategy: first load with no log (a verbose log for a
    program of any size overflows fixed buffers — the kernel then fails the
    load with ENOSPC even when the program is valid); only on rejection retry
    at log_level=1 with a large buffer to harvest the actual error."""
    n_insns = len(insns) // 8
    insn_buf = ctypes.create_string_buffer(insns, len(insns))
    lic_buf = ctypes.create_string_buffer(license_ + b"\x00")

    def attempt(log_level: int, log_buf) -> int:
        attr = struct.pack(
            "=IIQQIIQI",
            prog_type, n_insns, ctypes.addressof(insn_buf),
            ctypes.addressof(lic_buf),
            log_level, len(log_buf) if log_buf is not None else 0,
            ctypes.addressof(log_buf) if log_buf is not None else 0,
            0)  # kern_version
        attr += struct.pack("=I", 0)  # prog_flags
        attr += name[:15].ljust(16, b"\x00")
        return _bpf(BPF_PROG_LOAD, attr)

    try:
        return attempt(0, None)
    except OSError:
        log_buf = ctypes.create_string_buffer(1 << 23)
        try:
            # reproduce with the error log enabled (fd is equally valid if
            # the rejection somehow doesn't reproduce)
            return attempt(1, log_buf)
        except OSError as exc2:
            log_txt = log_buf.value.decode(errors="replace").strip()
            raise OSError(exc2.errno,
                          f"{exc2.strerror}; verifier log:\n{log_txt}") \
                from exc2


def obj_pin(fd: int, path: str) -> None:
    pathbuf = ctypes.create_string_buffer(path.encode() + b"\x00")
    attr = struct.pack("=QI", ctypes.addressof(pathbuf), fd)
    _bpf(BPF_OBJ_PIN, attr)


# --- TCX links (kernel >= 6.6) -------------------------------------------

BPF_LINK_CREATE = 28
BPF_LINK_DETACH = 34
BPF_TCX_INGRESS = 46
BPF_TCX_EGRESS = 47


def link_create_tcx(prog_fd: int, if_index: int, direction: str) -> int:
    """Attach a SCHED_CLS program to an interface's TCX hook via
    BPF_LINK_CREATE; returns the bpf_link fd — closing it detaches (reference
    analog: cilium/ebpf link.AttachTCX used at tracer.go:454-459). Raises
    OSError(ENOTSUP/EINVAL) on pre-6.6 kernels, letting callers fall back to
    legacy TC; OSError(EEXIST) when mprog rejects a duplicate attachment."""
    attach_type = BPF_TCX_INGRESS if direction == "ingress" else BPF_TCX_EGRESS
    # union bpf_attr link_create: prog_fd, target_ifindex, attach_type, flags
    # + zeroed tcx { relative_fd/id, expected_revision } tail (= default
    # anchor position, no revision check)
    attr = struct.pack("=IIII", prog_fd, if_index, attach_type, 0)
    attr += b"\x00" * 16
    return _bpf(BPF_LINK_CREATE, attr)


def link_detach(link_fd: int) -> None:
    """Explicit BPF_LINK_DETACH (the link fd alone also detaches on close)."""
    attr = struct.pack("=I", link_fd)
    _bpf(BPF_LINK_DETACH, attr)


BPF_LINK_GET_FD_BY_ID = 30
BPF_LINK_GET_NEXT_ID = 31
BPF_LINK_TYPE_TCX = 11


def prog_id_of(prog_fd: int) -> int:
    """Kernel-assigned program id (bpf_prog_info.id)."""
    info = ctypes.create_string_buffer(256)
    attr = struct.pack("=IIQ", prog_fd, len(info), ctypes.addressof(info))
    _bpf(BPF_OBJ_GET_INFO_BY_FD, attr)
    return struct.unpack_from("=I", info.raw, 4)[0]


def link_info(link_fd: int) -> tuple[int, int, int, int, int]:
    """(link_type, link_id, prog_id, tcx_ifindex, tcx_attach_type) — the tcx
    fields are only meaningful when link_type == BPF_LINK_TYPE_TCX."""
    info = ctypes.create_string_buffer(256)
    attr = struct.pack("=IIQ", link_fd, len(info), ctypes.addressof(info))
    _bpf(BPF_OBJ_GET_INFO_BY_FD, attr)
    ltype, lid, pid = struct.unpack_from("=III", info.raw, 0)
    ifindex, attach_type = struct.unpack_from("=II", info.raw, 16)
    return ltype, lid, pid, ifindex, attach_type


def iter_link_ids():
    """Yield every bpf_link id on the system (CAP_BPF required)."""
    cur = 0
    while True:
        attr = bytearray(struct.pack("=III", cur, 0, 0))
        try:
            _bpf_inout(BPF_LINK_GET_NEXT_ID, attr)
        except OSError as exc:
            if exc.errno == errno.ENOENT:
                return
            raise
        cur = struct.unpack_from("=I", attr, 4)[0]
        yield cur


def find_tcx_link(if_index: int, direction: str,
                  prog_id: Optional[int] = None) -> Optional[int]:
    """Open the existing TCX link on (if_index, direction), optionally
    requiring it to carry a specific program — the adoption path when
    link_create returns EEXIST (reference: link.QueryPrograms + NewFromID,
    tracer.go:464-480). Returns a link fd or None."""
    want = BPF_TCX_INGRESS if direction == "ingress" else BPF_TCX_EGRESS
    for lid in iter_link_ids():
        attr = struct.pack("=I", lid)
        try:
            fd = _bpf(BPF_LINK_GET_FD_BY_ID, attr)
        except OSError:
            continue
        try:
            ltype, _lid, pid, ifx, atype = link_info(fd)
        except OSError:
            os.close(fd)
            continue  # unrelated link whose info query fails; keep scanning
        if (ltype == BPF_LINK_TYPE_TCX and ifx == if_index and atype == want
                and (prog_id is None or pid == prog_id)):
            return fd
        os.close(fd)
    return None


RINGBUF_BUSY_BIT = 0x80000000
RINGBUF_DISCARD_BIT = 0x40000000
_RB_HDR_SIZE = 8


def parse_ringbuf_records(data, consumer_pos: int, producer_pos: int,
                          mask: int) -> tuple[list[bytes], int]:
    """Walk ring records in [consumer_pos, producer_pos); returns
    (records, new_consumer_pos). Stops at a BUSY (still-being-written)
    record. Pure function so the wire format is unit-testable."""
    out: list[bytes] = []
    pos = consumer_pos
    while pos < producer_pos:
        off = pos & mask
        hdr = int.from_bytes(data[off:off + 4], "little")
        if hdr & RINGBUF_BUSY_BIT:
            break
        length = hdr & ~(RINGBUF_BUSY_BIT | RINGBUF_DISCARD_BIT)
        if not (hdr & RINGBUF_DISCARD_BIT):
            start = off + _RB_HDR_SIZE
            out.append(bytes(data[start:start + length]))
        pos += (_RB_HDR_SIZE + length + 7) & ~7  # 8-byte aligned advance
    return out, pos


class RingBufReader:
    """mmap consumer for a BPF_MAP_TYPE_RINGBUF map (libbpf ring layout:
    consumer page rw at offset 0; producer page + data ro at PAGE_SIZE)."""

    def __init__(self, ringbuf_map: BpfMap):
        import mmap as _mmap
        import select

        self._map = ringbuf_map
        _mtype, _k, _v, max_entries = BpfMap.get_info(ringbuf_map.fd)
        self._size = max_entries
        self._mask = max_entries - 1
        page = _mmap.PAGESIZE
        self._cons = _mmap.mmap(ringbuf_map.fd, page, _mmap.MAP_SHARED,
                                _mmap.PROT_READ | _mmap.PROT_WRITE, offset=0)
        self._prod = _mmap.mmap(ringbuf_map.fd, page + 2 * max_entries,
                                _mmap.MAP_SHARED, _mmap.PROT_READ,
                                offset=page)
        self._data_off = page
        self._epoll = select.epoll()
        self._epoll.register(ringbuf_map.fd, select.EPOLLIN)
        self._pending: list[bytes] = []

    def _positions(self) -> tuple[int, int]:
        cons = int.from_bytes(self._cons[0:8], "little")
        prod = int.from_bytes(self._prod[0:8], "little")
        return cons, prod

    def read(self, timeout_s: float) -> Optional[bytes]:
        """One record, or None on timeout."""
        if self._pending:
            return self._pending.pop(0)
        cons, prod = self._positions()
        if cons >= prod:
            if not self._epoll.poll(timeout_s):
                return None
            cons, prod = self._positions()
        data = memoryview(self._prod)[self._data_off:]
        records, new_cons = parse_ringbuf_records(data, cons, prod, self._mask)
        self._cons[0:8] = new_cons.to_bytes(8, "little")
        if not records:
            return None
        self._pending = records[1:]
        return records[0]

    def close(self) -> None:
        self._epoll.close()
        self._cons.close()
        self._prod.close()


def n_possible_cpus() -> int:
    try:
        with open("/sys/devices/system/cpu/possible") as fh:
            spec = fh.read().strip()
        last = spec.split("-")[-1].split(",")[-1]
        return int(last) + 1
    except (OSError, ValueError):
        return os.cpu_count() or 1


def bpf_available() -> bool:
    """Can this process create BPF maps? (CAP_BPF or root + kernel support)"""
    try:
        m = BpfMap.create(1, 4, 8, 4, b"probe")  # BPF_MAP_TYPE_HASH
        m.close()
        return True
    except OSError:
        return False
