"""Hand-assembled tracepoint trackers (drops + smoothed RTT), no compiler.

This kernel compiles out kprobes (CONFIG_KPROBES unset) but exposes the
tracepoint PMU, which is also what the C twin uses for drops
(flowpath_probes.c SEC("tracepoint/skb/kfree_skb")). Two layers of runtime
resolution replace CO-RE:

- tracepoint context offsets come from the live tracefs format files
  (uprobe.tracepoint_fields) — 6.18 inserted rx_sk into skb/kfree_skb, so
  hardcoded layouts would silently read the wrong fields;
- kernel struct offsets (walking the dropped skb's headers) come from
  /sys/kernel/btf/vmlinux (datapath/btf.py), baked into the assembled
  program as immediates — the same relocation libbpf performs at load time.

Programs:
- build_rtt_tracepoint_program — tcp/tcp_probe: smoothed RTT and the
  receive-path tuple straight from the tracepoint context
  (flowpath_probes.c:60-155 handle_rtt/key_from_sock_rx analog)
- build_drops_program — skb/kfree_skb: packet drops re-keyed from the skb's
  network/transport headers via bpf_probe_read_kernel
  (flowpath_probes.c:172-208 twin)
"""

from __future__ import annotations

from netobserv_tpu.datapath.asm import (
    Asm, BPF_B, BPF_DW, BPF_H, BPF_W, HELPER_KTIME_GET_NS, HELPER_MAP_LOOKUP,
    HELPER_MAP_UPDATE, R0, R1, R2, R3, R4, R5, R6, R7, R8, R9, R10,
)
from netobserv_tpu.model import binfmt

HELPER_PROBE_READ_KERNEL = 113

# struct sockaddr_in / sockaddr_in6 member offsets (uapi, stable)
SA_V4_ADDR = 4
SA_V6_ADDR = 8

AF_INET = 2
AF_INET6 = 10

KEY_SIZE = binfmt.FLOW_KEY_DTYPE.itemsize


def _ky(field: str) -> int:
    return binfmt.FLOW_KEY_DTYPE.fields[field][1]


def _xr(field: str) -> int:
    return binfmt.EXTRA_REC_DTYPE.fields[field][1]


def _dp(field: str) -> int:
    return binfmt.DROPS_REC_DTYPE.fields[field][1]


# stack layout (shared by both programs; all 8-aligned)
KEY = -KEY_SIZE            # no_flow_key build slot (40B)
REC = KEY - 64             # -104: record build slot (extra 32B / drops 32B)
SCR = REC - 48             # -152: probe_read scratch (headers, fields)
NOW = SCR - 8              # -160: timestamp


class _Probe:
    def __init__(self):
        self.a = Asm()

    def read_kernel(self, src_reg: int, src_off: int, dst_off: int,
                    n: int, fail: str) -> None:
        """bpf_probe_read_kernel(r10+dst_off, n, src_reg+src_off); jumps to
        `fail` on error. Clobbers r0-r5."""
        a = self.a
        a.mov_reg(R1, R10)
        a.alu_imm(0x07, R1, dst_off)
        a.mov_imm(R2, n)
        a.mov_reg(R3, src_reg)
        if src_off:
            a.alu_imm(0x07, R3, src_off)
        a.call(HELPER_PROBE_READ_KERNEL)
        a.jmp_imm(0x55, R0, 0, fail)

    def zero_key(self) -> None:
        for off in range(KEY, 0, 8):
            self.a.st_imm(BPF_DW, R10, off, 0)

    def gate_sampling(self, gate_fd) -> None:
        """Exit unless the TC path's latest per-CPU sampling decision was
        'sampled' (sampling_gate map; reference do_sampling gate,
        flowpath_probes.c aux-hook pattern)."""
        if gate_fd is None:
            return
        a = self.a
        a.st_imm(BPF_W, R10, SCR, 0)
        a.ld_map_fd(R1, gate_fd)
        a.mov_reg(R2, R10)
        a.alu_imm(0x07, R2, SCR)
        a.call(HELPER_MAP_LOOKUP)
        a.jmp_imm(0x15, R0, 0, "out")           # gate absent: skip
        a.ldx(BPF_B, R3, R0, 0)
        a.jmp_imm(0x15, R3, 0, "out")           # last packet unsampled


def build_rtt_tracepoint_program(fields: dict[str, int], flows_extra_fd: int,
                                 sampling_gate_fd=None) -> bytes:
    """tcp/tcp_probe fires in tcp_rcv_established with the socket tuple and
    the smoothed RTT already in the context. `fields` comes from
    uprobe.tracepoint_fields("tcp", "tcp_probe"): saddr/daddr are sockaddr
    blobs (LOCAL/REMOTE respectively), sport/dport host-order. The
    receive-path key maps remote->src, local->dst (key_from_sock_rx)."""
    p = _Probe()
    a = p.a
    f_saddr, f_daddr = fields["saddr"], fields["daddr"]
    f_sport, f_dport = fields["sport"], fields["dport"]
    f_family, f_srtt = fields["family"], fields["srtt"]

    a.mov_reg(R6, R1)                           # r6 = tracepoint ctx
    p.gate_sampling(sampling_gate_fd)
    p.zero_key()
    a.st_imm(BPF_B, R10, KEY + _ky("proto"), 6)
    a.ldx(BPF_H, R3, R6, f_family)
    a.jmp_imm(0x15, R3, AF_INET, "v4")
    a.jmp_imm(0x55, R3, AF_INET6, "out")
    # v6: remote (daddr) -> src, local (saddr) -> dst
    for i in range(0, 16, 4):
        a.ldx(BPF_W, R3, R6, f_daddr + SA_V6_ADDR + i)
        a.stx(BPF_W, R10, R3, KEY + _ky("src_ip") + i)
        a.ldx(BPF_W, R3, R6, f_saddr + SA_V6_ADDR + i)
        a.stx(BPF_W, R10, R3, KEY + _ky("dst_ip") + i)
    a.jmp("ports")
    a.label("v4")
    a.st_imm(BPF_H, R10, KEY + _ky("src_ip") + 10, 0xFFFF)
    a.ldx(BPF_W, R3, R6, f_daddr + SA_V4_ADDR)
    a.stx(BPF_W, R10, R3, KEY + _ky("src_ip") + 12)
    a.st_imm(BPF_H, R10, KEY + _ky("dst_ip") + 10, 0xFFFF)
    a.ldx(BPF_W, R3, R6, f_saddr + SA_V4_ADDR)
    a.stx(BPF_W, R10, R3, KEY + _ky("dst_ip") + 12)
    a.label("ports")
    a.ldx(BPF_H, R3, R6, f_dport)               # remote port (host order)
    a.stx(BPF_H, R10, R3, KEY + _ky("src_port"))
    a.ldx(BPF_H, R3, R6, f_sport)               # local port
    a.stx(BPF_H, R10, R3, KEY + _ky("dst_port"))
    # rtt_ns = srtt_us * 1000 (tcp_probe reports srtt_us>>3 already)
    a.ldx(BPF_W, R8, R6, f_srtt)
    a.alu_imm(0x27, R8, 1000)                   # r8 = rtt_ns
    a.jmp_imm(0x15, R8, 0, "out")               # unmeasured connection
    a.call(HELPER_KTIME_GET_NS)
    a.stx(BPF_DW, R10, R0, NOW)
    a.ld_map_fd(R1, flows_extra_fd)
    a.mov_reg(R2, R10)
    a.alu_imm(0x07, R2, KEY)
    a.call(HELPER_MAP_LOOKUP)
    a.jmp_imm(0x15, R0, 0, "miss")
    a.ldx(BPF_DW, R3, R10, NOW)
    a.stx(BPF_DW, R0, R3, _xr("last_seen_ns"))
    a.ldx(BPF_DW, R3, R0, _xr("rtt_ns"))        # max-merge (handle_rtt)
    a.jmp_reg(0x3D, R3, R8, "out")
    a.stx(BPF_DW, R0, R8, _xr("rtt_ns"))
    a.jmp("out")
    a.label("miss")
    for off in range(REC, REC + 32, 8):
        a.st_imm(BPF_DW, R10, off, 0)
    a.ldx(BPF_DW, R3, R10, NOW)
    a.stx(BPF_DW, R10, R3, REC + _xr("first_seen_ns"))
    a.stx(BPF_DW, R10, R3, REC + _xr("last_seen_ns"))
    a.stx(BPF_DW, R10, R8, REC + _xr("rtt_ns"))
    a.ld_map_fd(R1, flows_extra_fd)
    a.mov_reg(R2, R10)
    a.alu_imm(0x07, R2, KEY)
    a.mov_reg(R3, R10)
    a.alu_imm(0x07, R3, REC)
    a.mov_imm(R4, 0)
    a.call(HELPER_MAP_UPDATE)
    a.label("out")
    a.mov_imm(R0, 0)
    a.exit()
    return a.assemble()


def build_drops_program(offs, flows_drops_fd: int, fields: dict[str, int],
                        min_reason: int = 3,
                        sampling_gate_fd=None) -> bytes:
    """Tracepoint skb/kfree_skb: re-key the dropped packet from its
    network/transport headers and record cause/state (drops_tp twin —
    reasons below `min_reason` are routine teardown and skipped). `offs` is
    the BTF reader (skb walking), `fields` the tracepoint context offsets
    (skbaddr/reason moved between kernel versions)."""
    p = _Probe()
    a = p.a
    skb_ctx_off = fields["skbaddr"]
    reason_ctx_off = fields["reason"]
    o_len = offs.offset_of("sk_buff", "len")
    o_head = offs.offset_of("sk_buff", "head")
    o_nh = offs.offset_of("sk_buff", "network_header")
    o_th = offs.offset_of("sk_buff", "transport_header")
    o_sk = offs.offset_of("sk_buff", "sk")
    o_state = offs.offset_of("sock", "__sk_common.skc_state")

    a.mov_reg(R6, R1)                           # r6 = tracepoint ctx
    a.ldx(BPF_DW, R7, R6, skb_ctx_off)          # r7 = skb
    a.ldx(BPF_W, R9, R6, reason_ctx_off)        # r9 = reason
    a.jmp_imm(0xA5, R9, min_reason, "out")      # routine teardown: skip
    p.gate_sampling(sampling_gate_fd)
    p.zero_key()
    for off in range(REC, REC + 32, 8):         # parse pre-fills REC fields
        a.st_imm(BPF_DW, R10, off, 0)
    # head + network_header -> r8 = network header address
    p.read_kernel(R7, o_head, SCR, 8, "out")
    p.read_kernel(R7, o_nh, SCR + 8, 2, "out")
    a.ldx(BPF_DW, R8, R10, SCR)
    a.ldx(BPF_H, R3, R10, SCR + 8)
    a.jmp_imm(0x15, R3, 0xFFFF, "out")          # header never set
    a.alu_reg(0x0F, R8, R3)
    # IP version nibble picks the parse (key_from_skb:84-110)
    p.read_kernel(R8, 0, SCR, 1, "out")
    a.ldx(BPF_B, R3, R10, SCR)
    a.alu_imm(0x77, R3, 4)
    a.jmp_imm(0x15, R3, 4, "v4")
    a.jmp_imm(0x55, R3, 6, "out")
    # v6: fixed header at r8; addresses at +8/+24
    p.read_kernel(R8, 8, KEY + _ky("src_ip"), 16, "out")
    p.read_kernel(R8, 24, KEY + _ky("dst_ip"), 16, "out")
    p.read_kernel(R8, 6, SCR, 1, "out")         # next header
    a.st_imm(BPF_H, R10, REC + _dp("eth_protocol"), 0x86DD)
    a.jmp("l4")
    a.label("v4")
    p.read_kernel(R8, 9, SCR, 1, "out")         # protocol
    a.st_imm(BPF_H, R10, KEY + _ky("src_ip") + 10, 0xFFFF)
    a.st_imm(BPF_H, R10, KEY + _ky("dst_ip") + 10, 0xFFFF)
    p.read_kernel(R8, 12, KEY + _ky("src_ip") + 12, 4, "out")
    p.read_kernel(R8, 16, KEY + _ky("dst_ip") + 12, 4, "out")
    a.st_imm(BPF_H, R10, REC + _dp("eth_protocol"), 0x0800)
    a.label("l4")
    a.ldx(BPF_B, R3, R10, SCR)
    a.stx(BPF_B, R10, R3, KEY + _ky("proto"))
    # transport header -> r8 (head must be re-read: SCR was reused)
    p.read_kernel(R7, o_th, SCR + 8, 2, "out")
    p.read_kernel(R7, o_head, SCR, 8, "out")
    a.ldx(BPF_DW, R8, R10, SCR)
    a.ldx(BPF_H, R4, R10, SCR + 8)
    a.jmp_imm(0x15, R4, 0xFFFF, "rec")          # no transport header
    a.alu_reg(0x0F, R8, R4)
    a.ldx(BPF_B, R3, R10, KEY + _ky("proto"))
    a.jmp_imm(0x15, R3, 6, "tcp")
    a.jmp_imm(0x15, R3, 17, "udp")
    a.jmp("rec")
    a.label("tcp")
    p.read_kernel(R8, 13, SCR + 16, 1, "rec")   # raw flags byte
    a.ldx(BPF_B, R3, R10, SCR + 16)
    # composite-flag classification, same encoding as every other flags
    # field (parse.h:93-102 / asm_flowpath tcp branch)
    for combo, bit in ((0x12, 0x100), (0x11, 0x200), (0x14, 0x400)):
        a.mov_reg(R4, R3)
        a.alu_imm(0x57, R4, combo)
        a.jmp_imm(0x55, R4, combo, f"dcls_{bit:x}")
        a.alu_imm(0x47, R3, bit)
        a.label(f"dcls_{bit:x}")
    a.stx(BPF_H, R10, R3, REC + _dp("latest_flags"))
    a.label("udp")
    p.read_kernel(R8, 0, SCR + 8, 4, "rec")     # src/dst ports (BE)
    a.ldx(BPF_H, R3, R10, SCR + 8)
    a.endian_be(R3, 16)
    a.stx(BPF_H, R10, R3, KEY + _ky("src_port"))
    a.ldx(BPF_H, R3, R10, SCR + 10)
    a.endian_be(R3, 16)
    a.stx(BPF_H, R10, R3, KEY + _ky("dst_port"))
    a.label("rec")
    # skb->len and socket state
    p.read_kernel(R7, o_len, SCR, 4, "out")
    a.ldx(BPF_W, R8, R10, SCR)                  # r8 = len
    a.st_imm(BPF_B, R10, REC + _dp("latest_state"), 0)
    p.read_kernel(R7, o_sk, SCR, 8, "out")
    a.ldx(BPF_DW, R3, R10, SCR)
    a.jmp_imm(0x15, R3, 0, "nostate")
    p.read_kernel(R3, o_state, SCR + 8, 1, "nostate")
    a.ldx(BPF_B, R4, R10, SCR + 8)
    a.stx(BPF_B, R10, R4, REC + _dp("latest_state"))
    a.label("nostate")
    a.call(HELPER_KTIME_GET_NS)
    a.stx(BPF_DW, R10, R0, NOW)
    a.ld_map_fd(R1, flows_drops_fd)
    a.mov_reg(R2, R10)
    a.alu_imm(0x07, R2, KEY)
    a.call(HELPER_MAP_LOOKUP)
    a.jmp_imm(0x15, R0, 0, "miss")
    a.ldx(BPF_DW, R3, R10, NOW)
    a.stx(BPF_DW, R0, R3, _dp("last_seen_ns"))
    # saturating u16 adds (no_sat_add16)
    a.ldx(BPF_H, R3, R0, _dp("bytes"))
    a.alu_reg(0x0F, R3, R8)
    a.jmp_imm(0xB5, R3, 0xFFFF, "bytes_ok")
    a.mov_imm(R3, 0xFFFF)
    a.label("bytes_ok")
    a.stx(BPF_H, R0, R3, _dp("bytes"))
    a.ldx(BPF_H, R3, R0, _dp("packets"))
    a.alu_imm(0x07, R3, 1)
    a.jmp_imm(0xB5, R3, 0xFFFF, "pkts_ok")
    a.mov_imm(R3, 0xFFFF)
    a.label("pkts_ok")
    a.stx(BPF_H, R0, R3, _dp("packets"))
    a.stx(BPF_W, R0, R9, _dp("latest_cause"))
    a.ldx(BPF_H, R3, R10, REC + _dp("latest_flags"))
    a.ldx(BPF_H, R4, R0, _dp("latest_flags"))
    a.alu_reg(0x4F, R3, R4)
    a.stx(BPF_H, R0, R3, _dp("latest_flags"))
    a.ldx(BPF_B, R3, R10, REC + _dp("latest_state"))
    a.stx(BPF_B, R0, R3, _dp("latest_state"))
    a.jmp("out")
    a.label("miss")
    # REC already carries eth_protocol/flags/state; fill the rest
    a.ldx(BPF_DW, R3, R10, NOW)
    a.stx(BPF_DW, R10, R3, REC + _dp("first_seen_ns"))
    a.stx(BPF_DW, R10, R3, REC + _dp("last_seen_ns"))
    a.mov_reg(R3, R8)
    a.jmp_imm(0xB5, R3, 0xFFFF, "fb_ok")
    a.mov_imm(R3, 0xFFFF)
    a.label("fb_ok")
    a.stx(BPF_H, R10, R3, REC + _dp("bytes"))
    a.st_imm(BPF_H, R10, REC + _dp("packets"), 1)
    a.stx(BPF_W, R10, R9, REC + _dp("latest_cause"))
    a.ld_map_fd(R1, flows_drops_fd)
    a.mov_reg(R2, R10)
    a.alu_imm(0x07, R2, KEY)
    a.mov_reg(R3, R10)
    a.alu_imm(0x07, R3, REC)
    a.mov_imm(R4, 0)
    a.call(HELPER_MAP_UPDATE)
    a.label("out")
    a.mov_imm(R0, 0)
    a.exit()
    return a.assemble()
