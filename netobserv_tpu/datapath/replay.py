"""Replay fetchers: synthetic traffic and pcap files as a datapath.

These implement the same FlowFetcher seam as the kernel loader, enabling:
- BASELINE.json config 1 (pcap replay -> CPU baseline / sketch oracle),
- running the full agent end-to-end without kernel privileges,
- load generation for benchmarks (the reference's perftest analog).
"""

from __future__ import annotations

import struct
import threading
import time
from typing import Optional

import numpy as np

from netobserv_tpu.datapath.fetcher import EvictedFlows
from netobserv_tpu.model import binfmt
from netobserv_tpu.model.flow import classify_tcp_flags
from netobserv_tpu.model.flow import GlobalCounter, ip_to_16


class SyntheticFetcher:
    """Generates zipf-skewed synthetic flows, aggregated per eviction window —
    what the kernel map would hold after one CACHE_ACTIVE_TIMEOUT."""

    def __init__(self, flows_per_eviction: int = 1000, n_distinct: int = 10000,
                 zipf_a: float = 1.2, seed: int = 0):
        self._n = flows_per_eviction
        self._rng = np.random.default_rng(seed)
        self._universe = self._make_universe(n_distinct)
        self._zipf_a = zipf_a
        self.attached: dict[int, str] = {}

    def _make_universe(self, n: int) -> np.ndarray:
        keys = np.zeros(n, dtype=binfmt.FLOW_KEY_DTYPE)
        ips = self._rng.integers(1, 2**32 - 1, size=(n, 2), dtype=np.uint64)
        for i in range(n):
            keys[i]["src_ip"] = np.frombuffer(
                ip_to_16(struct.pack(">I", int(ips[i, 0]) & 0xFFFFFFFF)), np.uint8)
            keys[i]["dst_ip"] = np.frombuffer(
                ip_to_16(struct.pack(">I", int(ips[i, 1]) & 0xFFFFFFFF)), np.uint8)
        keys["src_port"] = self._rng.integers(1024, 65535, n)
        keys["dst_port"] = self._rng.choice(
            [53, 80, 123, 443, 8080], n).astype(np.uint16)
        keys["proto"] = self._rng.choice([6, 17], n).astype(np.uint8)
        return keys

    def lookup_and_delete(self) -> EvictedFlows:
        n = self._n
        ranks = np.minimum(self._rng.zipf(self._zipf_a, n) - 1,
                           len(self._universe) - 1)
        # aggregate duplicates like the kernel map would
        uniq, inv = np.unique(ranks, return_inverse=True)
        events = np.zeros(len(uniq), dtype=binfmt.FLOW_EVENT_DTYPE)
        events["key"] = self._universe[uniq]
        pkts = np.zeros(len(uniq), np.int64)
        byts = np.zeros(len(uniq), np.int64)
        np.add.at(pkts, inv, self._rng.integers(1, 10, n))
        np.add.at(byts, inv, self._rng.integers(64, 9000, n))
        now = time.clock_gettime_ns(time.CLOCK_MONOTONIC)
        events["stats"]["packets"] = pkts
        events["stats"]["bytes"] = byts
        events["stats"]["first_seen_ns"] = now - 5_000_000_000
        events["stats"]["last_seen_ns"] = now
        events["stats"]["eth_protocol"] = 0x0800
        events["stats"]["if_index_first"] = 1
        extra = np.zeros(len(uniq), dtype=binfmt.EXTRA_REC_DTYPE)
        extra["rtt_ns"] = self._rng.integers(100_000, 200_000_000, len(uniq))
        return EvictedFlows(events, extra=extra)

    def read_ringbuf(self, timeout_s: float) -> Optional[bytes]:
        time.sleep(timeout_s)
        return None

    def read_global_counters(self) -> dict[GlobalCounter, int]:
        return {}

    def purge_stale(self, older_than_s: float) -> int:
        return 0

    def attach(self, if_index: int, if_name: str, direction: str,
               netns: str = "") -> None:
        self.attached[if_index] = if_name

    def detach(self, if_index: int, if_name: str,
               netns: str = "") -> None:
        self.attached.pop(if_index, None)

    def close(self) -> None:
        pass


class PcapReplayFetcher:
    """Parses a pcap file and aggregates its packets into flow events,
    releasing one eviction window per lookup_and_delete() call.

    Minimal classic-pcap parser (no external deps): ethernet/IPv4/IPv6 + TCP/
    UDP/ICMP; non-IP packets are skipped.

    Two kernel-datapath feature twins run during the parse so replayed
    traffic exercises the same sketch signal planes as live capture
    (the scenario zoo leans on both — netobserv_tpu/scenarios):

    - DNS latency: UDP port-53 query/response pairs are correlated by
      transaction id + client endpoint (the kernel's dns tracker analog);
      the measured latency rides the RESPONSE flow's DNS feature record.
    - QUIC: a long-header first byte on UDP/443 marks the flow's QUIC
      feature record (the kernel datapath's payload probe analog).
    """

    def __init__(self, path: str, window_s: float = 5.0):
        self._windows = self._parse(path, window_s)
        self._idx = 0
        self._lock = threading.Lock()
        self.attached: dict[int, str] = {}
        # rebase capture timestamps into the monotonic domain so the standard
        # mono->wall reconstruction yields sane (current) wall times
        if self._windows:
            first_ts = min(int(w[0]["stats"]["first_seen_ns"].min())
                           for w in self._windows if len(w[0]))
            offset = time.clock_gettime_ns(time.CLOCK_MONOTONIC) - first_ts
            for w in self._windows:
                for arr in w:
                    if arr is None:
                        continue
                    stats = arr["stats"] if "stats" in (
                        arr.dtype.names or ()) else arr
                    for fld in ("first_seen_ns", "last_seen_ns"):
                        stats[fld] = (stats[fld].astype(np.int64) + offset
                                      ).astype(np.uint64)

    @property
    def n_windows(self) -> int:
        return len(self._windows)

    def exhausted(self) -> bool:
        with self._lock:
            return self._idx >= len(self._windows)

    def _parse(self, path: str, window_s: float) -> list[np.ndarray]:
        with open(path, "rb") as fh:
            data = fh.read()
        if len(data) < 24:
            raise ValueError(f"not a pcap file (too short): {path}")
        magic = struct.unpack("<I", data[:4])[0]
        if magic == 0xA1B2C3D4:
            endian, tscale = "<", 1_000  # usec -> ns
        elif magic == 0xA1B23C4D:
            endian, tscale = "<", 1  # nanosecond pcap
        elif magic == 0xD4C3B2A1:
            endian, tscale = ">", 1_000
        else:
            raise ValueError(f"not a pcap file: magic {magic:#x}")
        linktype = struct.unpack(endian + "I", data[20:24])[0]
        if linktype != 1:
            raise ValueError(f"unsupported linktype {linktype} (want ethernet)")
        off = 24
        flows: dict[bytes, list] = {}
        windows: list[tuple] = []
        window_start: Optional[int] = None
        #: (txid, client ip, client port) -> send timestamp (the kernel dns
        #: tracker's in-flight map analog; response packets pop it). The
        #: client endpoint is part of the key: 16-bit txids collide
        #: routinely across clients in real captures
        pending_dns: dict[tuple, int] = {}
        while off + 16 <= len(data):
            ts_sec, ts_sub, incl, orig = struct.unpack(
                endian + "IIII", data[off:off + 16])
            off += 16
            pkt = data[off:off + incl]
            off += incl
            ts_ns = ts_sec * 1_000_000_000 + ts_sub * tscale
            if window_start is None:
                window_start = ts_ns
            if ts_ns - window_start > window_s * 1e9 and flows:
                windows.append(self._to_events(flows))
                flows = {}
                window_start = ts_ns
            parsed = _parse_packet(pkt)
            if parsed is None:
                continue
            key_bytes, length, flags, meta = parsed
            ent = flows.get(key_bytes)
            if ent is None:
                # [bytes, pkts, flags, first, last,
                #  dns_lat_ns, dns_id, dns_errno, quic_ver, quic_long]
                ent = flows[key_bytes] = [length, 1, flags, ts_ns, ts_ns,
                                          0, 0, 0, 0, 0]
            else:
                ent[0] += length
                ent[1] += 1
                ent[2] |= flags
                ent[4] = ts_ns
            if meta is None:
                continue
            if meta[0] == "dns":
                _kind, txid, is_response, rcode, client = meta
                if not is_response:
                    pending_dns[(txid, *client)] = ts_ns
                else:
                    sent = pending_dns.pop((txid, *client), None)
                    if sent is not None:
                        # latency rides the RESPONSE flow (server->client)
                        ent[5] = max(ent[5], ts_ns - sent)
                        ent[6] = txid
                        ent[7] = rcode
            else:  # quic long header
                ent[8] = meta[1]
                ent[9] = 1
        if flows:
            windows.append(self._to_events(flows))
        return windows

    @staticmethod
    def _to_events(flows: dict[bytes, list]) -> tuple:
        """One window's (events, dns, quic) arrays; the feature arrays are
        None when no flow in the window carried that feature (exactly like
        a kernel datapath with the tracker disabled)."""
        events = np.zeros(len(flows), dtype=binfmt.FLOW_EVENT_DTYPE)
        dns = quic = None
        for i, (kb, ent) in enumerate(flows.items()):
            (byts, pkts, flags, first, last,
             dns_lat, dns_id, dns_errno, quic_ver, quic_long) = ent
            events[i]["key"] = np.frombuffer(
                kb, dtype=binfmt.FLOW_KEY_DTYPE)[0]
            s = events[i]["stats"]
            s["bytes"] = byts
            s["packets"] = pkts
            s["tcp_flags"] = flags
            s["first_seen_ns"] = first
            s["last_seen_ns"] = last
            s["eth_protocol"] = 0x0800
            s["if_index_first"] = 1
            if dns_lat:
                if dns is None:
                    dns = np.zeros(len(flows), binfmt.DNS_REC_DTYPE)
                dns[i]["latency_ns"] = dns_lat
                dns[i]["dns_id"] = dns_id
                dns[i]["errno"] = dns_errno
                dns[i]["first_seen_ns"] = first
                dns[i]["last_seen_ns"] = last
            if quic_long:
                if quic is None:
                    quic = np.zeros(len(flows), binfmt.QUIC_REC_DTYPE)
                quic[i]["version"] = quic_ver
                quic[i]["seen_long_hdr"] = 1
                quic[i]["first_seen_ns"] = first
                quic[i]["last_seen_ns"] = last
        return events, dns, quic

    def lookup_and_delete(self) -> EvictedFlows:
        with self._lock:
            if self._idx >= len(self._windows):
                return EvictedFlows(
                    np.zeros(0, dtype=binfmt.FLOW_EVENT_DTYPE))
            events, dns, quic = self._windows[self._idx]
            self._idx += 1
        return EvictedFlows(events, dns=dns, quic=quic)

    def read_ringbuf(self, timeout_s: float) -> Optional[bytes]:
        time.sleep(timeout_s)
        return None

    def read_global_counters(self) -> dict[GlobalCounter, int]:
        return {}

    def purge_stale(self, older_than_s: float) -> int:
        return 0

    def attach(self, if_index: int, if_name: str, direction: str,
               netns: str = "") -> None:
        self.attached[if_index] = if_name

    def detach(self, if_index: int, if_name: str,
               netns: str = "") -> None:
        self.attached.pop(if_index, None)

    def close(self) -> None:
        pass


class PcapPacketFetcher:
    """PCA-mode packet source from a pcap file: each captured frame becomes a
    packet event (payload truncated at NO_MAX_PAYLOAD_SIZE), released in
    arrival order at a configurable pace. Implements the PacketFetcher seam
    so the full PCA pipeline (PerfTracer -> PerfBuffer -> pcap gRPC stream)
    runs without kernel privileges."""

    def __init__(self, path: str, rate_pps: float = 0.0):
        self._events: list[bytes] = []
        self._idx = 0
        self._lock = threading.Lock()
        self._interval = 1.0 / rate_pps if rate_pps > 0 else 0.0
        self._parse(path)

    def _parse(self, path: str) -> None:
        with open(path, "rb") as fh:
            data = fh.read()
        if len(data) < 24:
            raise ValueError(f"not a pcap file (too short): {path}")
        magic = struct.unpack("<I", data[:4])[0]
        if magic == 0xA1B2C3D4:
            endian, tscale = "<", 1_000
        elif magic == 0xA1B23C4D:
            endian, tscale = "<", 1
        elif magic == 0xD4C3B2A1:
            endian, tscale = ">", 1_000
        else:
            raise ValueError(f"not a pcap file: magic {magic:#x}")
        mono_now = time.clock_gettime_ns(time.CLOCK_MONOTONIC)
        first_ts = None
        off = 24
        while off + 16 <= len(data):
            ts_sec, ts_sub, incl, orig = struct.unpack(
                endian + "IIII", data[off:off + 16])
            off += 16
            payload = data[off:off + incl]
            off += incl
            ts_ns = ts_sec * 1_000_000_000 + ts_sub * tscale
            if first_ts is None:
                first_ts = ts_ns
            ev = np.zeros(1, dtype=binfmt.PACKET_EVENT_DTYPE)
            ev[0]["if_index"] = 1
            ev[0]["pkt_len"] = orig
            ev[0]["timestamp_ns"] = mono_now - first_ts + ts_ns
            n = min(len(payload), binfmt.MAX_PAYLOAD_SIZE)
            ev[0]["payload"][:n] = np.frombuffer(payload[:n], np.uint8)
            self._events.append(ev.tobytes())

    def read_packet(self, timeout_s: float) -> Optional[bytes]:
        with self._lock:
            if self._idx >= len(self._events):
                time.sleep(timeout_s)
                return None
            ev = self._events[self._idx]
            self._idx += 1
        if self._interval:
            time.sleep(self._interval)
        return ev

    def exhausted(self) -> bool:
        with self._lock:
            return self._idx >= len(self._events)

    def close(self) -> None:
        pass


#: dns/quic feature-probe ports (kernel twins: DNS_TRACKING_PORT and the
#: QUIC payload probe's UDP/443 gate)
_DNS_PORT = 53
_QUIC_PORT = 443


def _parse_packet(pkt: bytes):
    """Ethernet frame -> (flow_key bytes, ip_len, tcp_flags, meta) or None.

    `meta` is the feature-probe result: ``("dns", txid, is_response,
    rcode, (client_ip16, client_port))`` for a UDP port-53 packet with a
    DNS header, ``("quic", version)`` for a long-header QUIC packet on
    UDP/443, else None.
    """
    if len(pkt) < 14:
        return None
    ethertype = struct.unpack(">H", pkt[12:14])[0]
    key = np.zeros(1, dtype=binfmt.FLOW_KEY_DTYPE)[0]
    if ethertype == 0x0800 and len(pkt) >= 34:  # IPv4
        ihl = (pkt[14] & 0x0F) * 4
        if len(pkt) < 14 + ihl:
            return None
        total_len = struct.unpack(">H", pkt[16:18])[0]
        proto = pkt[23]
        key["src_ip"] = np.frombuffer(ip_to_16(pkt[26:30]), np.uint8)
        key["dst_ip"] = np.frombuffer(ip_to_16(pkt[30:34]), np.uint8)
        l4 = pkt[14 + ihl:]
    elif ethertype == 0x86DD and len(pkt) >= 54:  # IPv6
        total_len = struct.unpack(">H", pkt[18:20])[0] + 40
        proto = pkt[20]
        key["src_ip"] = np.frombuffer(pkt[22:38], np.uint8)
        key["dst_ip"] = np.frombuffer(pkt[38:54], np.uint8)
        l4 = pkt[54:]
    else:
        return None
    key["proto"] = proto
    flags = 0
    meta = None
    if proto in (6, 17) and len(l4) >= 4:  # TCP/UDP ports
        sport, dport = struct.unpack(">HH", l4[:4])
        key["src_port"], key["dst_port"] = sport, dport
        if proto == 6 and len(l4) >= 14:
            flags = classify_tcp_flags(l4[13])
        elif proto == 17:
            payload = l4[8:]
            if _DNS_PORT in (sport, dport) and len(payload) >= 4:
                txid = struct.unpack(">H", payload[:2])[0]
                is_resp = bool(payload[2] & 0x80)
                # the pairing key carries the CLIENT endpoint (query src /
                # response dst) — txids collide across clients
                client = ((key["dst_ip"].tobytes(), dport) if is_resp
                          else (key["src_ip"].tobytes(), sport))
                meta = ("dns", txid, is_resp, payload[3] & 0x0F, client)
            elif (_QUIC_PORT in (sport, dport) and len(payload) >= 5
                  and payload[0] & 0xC0 == 0xC0):
                meta = ("quic", struct.unpack(">I", payload[1:5])[0])
    elif proto in (1, 58) and len(l4) >= 2:  # ICMP type/code
        key["icmp_type"], key["icmp_code"] = l4[0], l4[1]
    # L2 frame length (IP total + ethernet header) — the same accounting as
    # the kernel datapath's skb->len
    return key.tobytes(), total_len + 14, flags, meta
