"""Kernel datapath loaders.

Two modes (reference analog: `pkg/tracer/tracer.go`):

- `KernelFetcher` — self-managed: load the compiled BPF object, rewrite config
  constants, attach TCX/TC (requires libbpf + a clang-built object; gated).
- `BpfmanFetcher` — EBPF_PROGRAM_MANAGER_MODE: an external lifecycle manager
  (bpfman) owns programs and pins the maps on bpffs; the agent opens the
  pinned maps and evicts through direct bpf(2) syscalls — no libbpf needed
  (reference: `tracer.go:275-384`). Kernel aggregation state survives agent
  restarts in this mode.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional

import numpy as np

from netobserv_tpu.config import AgentConfig
from netobserv_tpu.datapath import flowpack, syscall_bpf
from netobserv_tpu.datapath.fetcher import EvictedFlows
from netobserv_tpu.model import binfmt
from netobserv_tpu.model.flow import GlobalCounter
from netobserv_tpu.utils import tracing

log = logging.getLogger("netobserv_tpu.datapath.loader")

_U64_MAX = np.uint64(0xFFFF_FFFF_FFFF_FFFF)

_OBJ_PATH = os.path.join(os.path.dirname(__file__), "native", "build",
                         "flowpath.bpf.o")


class KernelFetcher:
    """Self-managed kernel datapath entry point (reference analog:
    `pkg/tracer/tracer.go:92-273` NewFlowFetcher).

    When the CI-built CO-RE object (datapath/native/CMakeLists.txt
    DATAPATH_BPF) is present and libbpf is available, loads the FULL C
    datapath through `LibbpfKernelFetcher` (every inline tracker from
    flowpath.c). Otherwise provisions the in-tree assembler datapath
    (`MinimalKernelFetcher`) — verifier-loaded IPv4/IPv6 flows, DNS, RTT,
    drops, filters, TLS/QUIC, sampling — which needs no compiler or libbpf.
    """

    needs_iface_discovery = True  # the agent starts an InterfaceListener

    @classmethod
    def load(cls, cfg: AgentConfig):
        return _load_clang_or_fallback(
            cfg, lambda c: LibbpfKernelFetcher(c, _OBJ_PATH),
            MinimalKernelFetcher.load, "datapath")


# (map name, value dtype, EvictedFlows attr) — ALL per-CPU feature maps the
# fetcher drains at eviction (reference merges every enabled feature map,
# pkg/tracer/tracer.go:1057-1110, incl. quic_flows at :1098-1110). The attr
# doubles as the flowpack merge kind.
_FEATURE_MAPS = [
    ("flows_extra", binfmt.EXTRA_REC_DTYPE, "extra"),
    ("flows_dns", binfmt.DNS_REC_DTYPE, "dns"),
    ("flows_drops", binfmt.DROPS_REC_DTYPE, "drops"),
    ("flows_nevents", binfmt.NEVENTS_REC_DTYPE, "nevents"),
    ("flows_xlat", binfmt.XLAT_REC_DTYPE, "xlat"),
    ("flows_quic", binfmt.QUIC_REC_DTYPE, "quic"),
]


# ---------------------------------------------------------------------------
# Columnar eviction plane (docs/architecture.md "Eviction plane"): the drain
# decodes as whole arrays straight from the batch-syscall buffers, per-CPU
# partials merge as one native/columnar pass per feature map, and key
# alignment is a void-view sort/searchsorted join — no per-record Python
# anywhere. bench.py --evict-only drives decode_eviction directly.
# ---------------------------------------------------------------------------

_KEY_SIZE = binfmt.FLOW_KEY_DTYPE.itemsize
_KEY_WORDS64 = _KEY_SIZE // 8


def _hash_keys_u64(keys_u8: np.ndarray) -> np.ndarray:
    """(n, 40) u8 -> (n,) u64 mixing hash — the sort key for the alignment
    join. numpy sorts/compares of void dtypes go through per-element memcmp
    (measured ~10x slower than a u64 sort at 100k keys), so the join orders
    by hash and falls back to an exact lexsort only when a 64-bit collision
    between DISTINCT keys is detected in the drain (adjacent-group check in
    _join_keys) — correctness never rides the hash."""
    w = np.ascontiguousarray(keys_u8).view(np.uint64)  # (n, 5)
    h = w[:, 0].copy()
    c1 = np.uint64(0x9E3779B97F4A7C15)
    c2 = np.uint64(0xC2B2AE3D27D4EB4F)
    for i in range(1, _KEY_WORDS64):
        h = (h ^ (w[:, i] * c2)) * c1
        h ^= h >> np.uint64(29)
    return h


def _join_keys(agg_u8: np.ndarray, blocks: list[np.ndarray]
               ) -> tuple[list[np.ndarray], list[np.ndarray], np.ndarray]:
    """Vectorized key-alignment join (replaces the per-drain python dict):
    one sort over [agg keys | every feature block], group by key, and a
    segmented forward-fill of the last agg index per group.

    Returns (scatter_idx_per_block, orphan_mask_per_block, appended_keys):
    scatter_idx maps each feature row to its event row — the agg drain row
    (LAST occurrence for duplicate agg keys, dict-idiom parity) or
    len(agg) + appended-row for keys absent from the aggregation drain;
    appended_keys are the unique orphan keys, one event row each."""
    n = len(agg_u8)
    allk = np.concatenate([agg_u8] + blocks)
    total = len(allk)
    w = allk.view(np.uint64)                       # (total, 5)
    h = _hash_keys_u64(allk)
    order = np.argsort(h, kind="stable")
    ws = w[order]
    newk = np.empty(total, bool)
    newk[0] = True
    newk[1:] = (ws[1:] != ws[:-1]).any(axis=1)
    hs = h[order]
    n_hash_groups = 1 + int((hs[1:] != hs[:-1]).sum())
    if int(newk.sum()) != n_hash_groups:
        # distinct keys collided on the 64-bit hash inside ONE drain
        # (p ~ total^2 / 2^65): hash order may interleave equal keys —
        # redo with the exact (slower) lexicographic order
        order = np.lexsort(tuple(w[:, i]
                                 for i in range(_KEY_WORDS64 - 1, -1, -1)))
        ws = w[order]
        newk[0] = True
        newk[1:] = (ws[1:] != ws[:-1]).any(axis=1)
    g = np.cumsum(newk) - 1
    # last-agg-index forward fill, reset at group boundaries: offset each
    # group into its own disjoint value range so maximum.accumulate can
    # never leak a previous group's index (-1 = no agg row yet)
    val = np.where(order < n, order, -1).astype(np.int64)
    span = np.int64(n + 1)
    fill = np.maximum.accumulate(g * span + val + 1) - g * span - 1
    match = np.empty(total, np.int64)
    match[order] = fill
    g_orig = np.empty(total, np.int64)
    g_orig[order] = g
    feat_match = match[n:]
    feat_g = g_orig[n:]
    orphan = feat_match < 0
    if orphan.any():
        uniq_g = np.unique(feat_g[orphan])
        group_start = np.nonzero(newk)[0]
        appended_keys = np.ascontiguousarray(
            ws[group_start[uniq_g]]).view(np.uint8).reshape(-1, _KEY_SIZE)
        feat_match = feat_match.copy()
        feat_match[orphan] = n + np.searchsorted(uniq_g, feat_g[orphan])
    else:
        appended_keys = np.empty((0, _KEY_SIZE), np.uint8)
    idx_blocks, orphan_blocks = [], []
    off = 0
    for b in blocks:
        idx_blocks.append(feat_match[off:off + len(b)])
        orphan_blocks.append(orphan[off:off + len(b)])
        off += len(b)
    return idx_blocks, orphan_blocks, appended_keys


def _drain_map_arrays(bmap, dtype) -> tuple[np.ndarray, np.ndarray]:
    """Drain one map -> (keys_u8 (n, key_size), values (n, n_cpus) dtype).
    Zero-copy from the batch-syscall buffers when the kernel supports batch
    ops (the arrays may alias bmap's cached buffers — decode_eviction copies
    once, at the EvictedFlows boundary); falls back to the per-key drain
    idiom on old kernels."""
    res = bmap.drain_batched_arrays()
    if res is not None:
        keys_u8, vals_u8 = res
        n = len(keys_u8)
        pad = bmap._pad_vs
        if pad == dtype.itemsize:
            vals = vals_u8.view(dtype)          # (n, n_cpus) — zero-copy
        else:
            # non-8-aligned value struct: strip the kernel's padded stride
            vals = np.ascontiguousarray(
                vals_u8.reshape(n, bmap.n_cpus, pad)[:, :, :dtype.itemsize]
            ).view(dtype)[..., 0]
        return keys_u8, vals
    pairs = bmap.drain()
    n = len(pairs)
    if not n:
        return (np.empty((0, bmap.key_size), np.uint8),
                np.empty((0, bmap.n_cpus), dtype))
    keys_u8 = np.frombuffer(b"".join(k for k, _ in pairs),
                            np.uint8).reshape(n, bmap.key_size)
    vals = np.frombuffer(b"".join(v for _, v in pairs),
                         dtype=dtype).reshape(n, bmap.n_cpus)
    return keys_u8, vals


def decode_eviction(agg_keys: np.ndarray, agg_vals: np.ndarray,
                    drained: dict[str, tuple[np.ndarray, np.ndarray]],
                    trace=None, merged: Optional[dict] = None,
                    merge_threads: int = 1) -> EvictedFlows:
    """Merge + align halves of the columnar eviction plane.

    agg_keys: (n, 40) u8; agg_vals: (n, 1) FLOW_STATS (the aggregation map
    is not per-CPU); drained: attr -> (keys_u8 (m, 40), partials
    (m, n_cpus) feature dtype). Inputs may alias kernel drain buffers —
    every output array is freshly allocated here (the one copy).

    `merged` (attr -> (m,) merged records) skips the per-CPU merge stage —
    the parallel drain lanes (BpfmanFetcher) merge inside each lane worker
    and hand only the align half here, keeping `_join_keys` the single join
    point of the fused stream; `drained`'s partials half is then unused
    (callers pass None rather than repurposing the slot). `merge_threads`
    row-shards each map's native merge (flowpack.merge_percpu_batch
    lanes) on the sequential path.

    Feature records whose flow is missing from the aggregation drain
    (ringbuf-fallback flows, or a racing eviction) become standalone
    appended events so their metrics aren't lost (reference:
    tracer.go:1138-1143); one appended row per unique orphan key, shared by
    every feature that saw it, with min/max seen times across them."""
    trace = trace if trace is not None else tracing.NULL_TRACE
    t0 = time.perf_counter()
    if merged is None:
        with trace.stage("merge_percpu"):
            merged = {attr: flowpack.merge_percpu_batch(
                attr, vals, threads=merge_threads)
                for attr, (_keys, vals) in drained.items()}
    t1 = time.perf_counter()
    with trace.stage("align"):
        n_agg = len(agg_keys)
        attrs = [a for a, (k, _v) in drained.items() if len(k)]
        if attrs:
            idx_blocks, orphan_blocks, appended_keys = _join_keys(
                np.ascontiguousarray(agg_keys),
                [np.ascontiguousarray(drained[a][0]) for a in attrs])
            joins = {a: (idx_blocks[i], orphan_blocks[i])
                     for i, a in enumerate(attrs)}
        else:
            joins, appended_keys = {}, np.empty((0, _KEY_SIZE), np.uint8)
        n = n_agg + len(appended_keys)
        events = flowpack.events_from_keys_stats(
            agg_keys if n_agg else np.empty((0, _KEY_SIZE), np.uint8),
            agg_vals[:, 0] if n_agg else np.empty(0, binfmt.FLOW_STATS_DTYPE),
            n_total=n)
        n_app = len(appended_keys)
        if n_app:
            events["key"][n_agg:] = appended_keys.view(
                binfmt.FLOW_KEY_DTYPE).reshape(-1)
        first_acc = np.full(n_app, _U64_MAX, np.uint64)
        last_acc = np.zeros(n_app, np.uint64)
        features: dict[str, Optional[np.ndarray]] = {}
        for attr in drained:
            recs = merged[attr]
            if n == 0 or not len(recs):
                features[attr] = None
                continue
            idx, orphan = joins[attr]
            if orphan.any():
                oi = idx[orphan] - n_agg
                of = recs["first_seen_ns"][orphan]
                np.minimum.at(first_acc, oi,
                              np.where(of == 0, _U64_MAX, of))
                np.maximum.at(last_acc, oi, recs["last_seen_ns"][orphan])
            out = np.zeros(n, recs.dtype)
            out[idx] = recs  # duplicate keys across drain chunks: last wins
            features[attr] = out
        if n_app:
            s = events["stats"]
            s["first_seen_ns"][n_agg:] = np.where(
                first_acc == _U64_MAX, np.uint64(0), first_acc)
            s["last_seen_ns"][n_agg:] = last_acc
    evicted = EvictedFlows(events, **features)
    evicted.decode_stats = {"merge_s": t1 - t0,
                            "align_s": time.perf_counter() - t1,
                            # appended standalone rows: ringbuf-fallback
                            # singles (or a racing eviction) whose flow
                            # missed the aggregation drain — the bounded
                            # double-count overload path, surfaced per
                            # drain (evict_ringbuf_fallback_total)
                            "fallback_rows": n_app}
    return evicted


class PackedEviction:
    """Pre-packed resident regions riding an EvictedFlows (the fused
    pipeline packed at drain time with the exporter ring's own
    dictionaries). `arena` is owned by this object (free()); `chunks` is
    the pack plan (flowpack.PipeChunk). `epoch` is the pack-surface epoch
    at pack time — the exporter ships the arena only while the epoch still
    matches (ship order must equal dict-mutation order; see
    staging.ResidentPackSurface), otherwise it frees the arena and folds
    the EvictedFlows' raw arrays instead."""

    __slots__ = ("arena", "chunks", "epoch", "spill_rows", "dict_resets",
                 "segs", "_res")

    def __init__(self, res: "flowpack.PipeResult", epoch: int):
        self.arena = res.arena
        self.chunks = res.chunks
        self.epoch = epoch
        self.spill_rows = res.spill_rows
        self.dict_resets = res.dict_resets
        self.segs = res.segs
        self._res = res

    def free(self) -> None:
        self._res.free()
        self.arena = None


class NativeEvictPipeline:
    """EVICT_NATIVE_PIPELINE gate: run the whole per-drain host chain as
    ONE GIL-releasing native call (flowpack.fp_drain_to_resident) —
    batched bpf(2) drain, per-CPU merge, key join, and (with a bound pack
    surface) the resident-region pack. SCHEDULING ONLY: output is
    equivalence-pinned against the island chain
    (tests/test_native_pipeline.py pins it bit-exact).

    Engagement rules: the FIRST drain always runs the python chain — it
    probes kernel batch-op support (syscall_bpf latches `_no_batch_ops`)
    and warms the eviction path; the pipe builds on drain #2 only when
    every map kept batch support, the native library is at the current
    ABI, and the kernel reported map capacities. Any disqualifier (or a
    mid-flight batch error, recorded in batch_err_mask) disables the
    pipeline permanently for this process and the island chain carries
    on — enabled-but-degraded must never crash or stall a drain."""

    def __init__(self, fetcher: "BpfmanFetcher", lanes: int):
        self._fetcher = fetcher
        self._lanes = max(1, lanes)
        self._pipe: Optional[flowpack.NativePipe] = None
        self._surface = None
        self._drains = 0
        self.disabled = False

    def bind_pack_surface(self, surface) -> None:
        """Attach the exporter ring's ResidentPackSurface — fused drains
        then also pack, handing the exporter pre-built regions."""
        self._surface = surface

    def _disable(self, why: str) -> None:
        self.disabled = True
        log.warning("native evict pipeline disabled: %s (island chain "
                    "carries on)", why)

    def _build(self) -> bool:
        f = self._fetcher
        if not flowpack.native_available():
            self._disable("flowpack library unavailable or ABI-stale")
            return False
        if not f._features:
            self._disable("no feature maps")
            return False
        maps = [(f._agg.fd, "stats", binfmt.FLOW_STATS_DTYPE.itemsize, 1,
                 int(getattr(f._agg, "max_entries", 0) or 0))]
        for attr, (fmap, dtype) in f._features.items():
            maps.append((fmap.fd, attr, dtype.itemsize, fmap.n_cpus,
                         int(getattr(fmap, "max_entries", 0) or 0)))
        for bmap in [f._agg] + [fm for fm, _dt in f._features.values()]:
            if getattr(bmap, "_no_batch_ops", True):
                self._disable("kernel lacks batch map ops")
                return False
        if any(m[4] <= 0 for m in maps):
            self._disable("unknown map capacity")
            return False
        for attr, (fmap, dtype) in f._features.items():
            if fmap._pad_vs != dtype.itemsize:
                self._disable(f"{attr} value stride is kernel-padded")
                return False
        try:
            self._pipe = flowpack.NativePipe(maps, lanes=self._lanes)
        except (RuntimeError, ValueError) as exc:
            self._disable(str(exc))
            return False
        log.info("native evict pipeline engaged: %d maps, %d lanes%s",
                 len(maps), self._lanes,
                 ", pack surface bound" if self._surface else "")
        return True

    def drain(self, trace, t0: float) -> Optional[EvictedFlows]:
        """One fused drain; None = not engaged (caller runs the island
        chain — which is also how batch support gets probed on drain 1)."""
        if self.disabled:
            return None
        self._drains += 1
        if self._drains == 1:
            return None  # probe drain: python chain latches batch support
        if self._pipe is None and not self._build():
            return None
        surface = self._surface
        epoch = 0
        try:
            with trace.stage("decode"):
                if surface is not None:
                    # the surface lock spans spec + native call: the ladder
                    # set and dictionary handles must not move, and raw-fold
                    # invalidations must serialize against the pack
                    with surface.lock:
                        res = self._pipe.drain(pack=surface.pack_spec())
                        epoch = surface.epoch
                        if res.arena is not None and res.chunks:
                            surface.outstanding += 1
                else:
                    res = self._pipe.drain()
        except RuntimeError as exc:
            # alloc failure or a stuck pack — rare enough to bail on
            self._disable(str(exc))
            return None
        if res.batch_err_mask:
            # a map's batch drain errored mid-flight; banked rounds are in
            # this result (their entries are deleted) — consume it, then
            # hand future drains back to the python chain
            self._disable(f"batch drain error mask {res.batch_err_mask:#x}")
        # the one copy: EvictedFlows owns fresh arrays (res views alias
        # pipe scratch reused by the next drain)
        if res.events is not None:
            events = res.events.copy()
        else:
            events = np.zeros(0, binfmt.FLOW_EVENT_DTYPE)
        feats = {kind: (a.copy() if a is not None else None)
                 for kind, a in res.aligned.items()}
        evicted = EvictedFlows(events, **feats)
        evicted.decode_stats = {
            "merge_s": res.merge_s,      # summed lane CPU (the lanes rule)
            "align_s": res.join_s,
            "fallback_rows": res.n_orphans,
            "decode_s": res.drain_s + res.merge_s + res.join_s,
            "drain_lanes": self._lanes,
            "seconds": time.perf_counter() - t0,
            "native_path": "fused",
            "native": {"drain_s": res.drain_s, "merge_s": res.merge_s,
                       "join_s": res.join_s, "pack_s": res.pack_s},
        }
        if res.arena is not None and res.chunks:
            evicted.packed = PackedEviction(res, epoch)
        return evicted

    def close(self) -> None:
        if self._pipe is not None:
            self._pipe.close()
            self._pipe = None


#: sanity ceiling on explicit EVICT_DRAIN_LANES (pool threads + merge
#: row-shards per map are both derived from it)
_MAX_DRAIN_LANES = 16


def resolve_drain_lanes(requested: int, n_feature_maps: int) -> int:
    """EVICT_DRAIN_LANES resolution — the ONE definition of the 0 = auto
    rule: one worker lane per drained feature map, bounded by the host's
    cores (a 1-core box stays sequential — lanes there only add pool
    overhead). 1 forces the sequential drain. An explicit N > 1 is
    trusted up to a sanity ceiling and MAY exceed the feature-map count:
    the drain pool itself never needs more workers than maps, but the
    surplus becomes per-map merge row-shards (`merge_percpu_batch
    threads=` — the big-map relief when one map, typically flows_extra,
    dominates the drain)."""
    if requested == 1 or n_feature_maps == 0:
        return 1
    if requested <= 0:
        return max(1, min(n_feature_maps, os.cpu_count() or 1))
    return min(requested, _MAX_DRAIN_LANES)


class BpfmanFetcher:
    """FlowFetcher over maps pinned by an external manager (bpfman mode).

    Eviction runs the columnar plane (decode_eviction); with more than one
    DRAIN LANE (EVICT_DRAIN_LANES) the per-feature-map drain→per-CPU-merge
    pairs run on a worker pool — one batched bpf(2) syscall stream per lane
    — while the calling thread drains the aggregation map, and the
    vectorized `_join_keys` alignment stays the single join point. The
    zero-copy drain-view lifetime rule holds PER LANE: a lane's views alias
    only its own map's cached batch buffers, each map is owned by exactly
    one lane per drain, and every view is copied out at the EvictedFlows
    boundary before lookup_and_delete returns (pinned by
    tests/test_evict_parallel.py + the bpffs aliasing suite). Drains
    serialize (MapTracer's eviction lock), so a lane's buffers are never
    redrained while its views are still being aligned."""

    needs_iface_discovery = False  # program lifecycle is externally managed
    # class-level default so partially-constructed fetchers (subclasses
    # mid-__init__, test stubs) read an absent gate, never AttributeError
    _native_gate: Optional["NativeEvictPipeline"] = None

    def __init__(self, bpf_fs_path: str, drain_lanes: int = 0,
                 native_pipeline: bool = False):
        self._n_cpus = syscall_bpf.n_possible_cpus()
        self._base = bpf_fs_path

        def openmap(name, value_size, per_cpu):
            return syscall_bpf.BpfMap.open_pinned(
                os.path.join(bpf_fs_path, name),
                key_size=binfmt.FLOW_KEY_DTYPE.itemsize,
                value_size=value_size,
                n_cpus=self._n_cpus if per_cpu else 1)

        self._agg = openmap("aggregated_flows",
                            binfmt.FLOW_STATS_DTYPE.itemsize, False)
        self._features = {}
        for name, dtype, attr in _FEATURE_MAPS:
            try:
                self._features[attr] = (openmap(name, dtype.itemsize, True),
                                        dtype)
            except OSError:
                log.debug("pinned map %s absent (feature disabled)", name)
        try:
            self._counters = syscall_bpf.BpfMap.open_pinned(
                os.path.join(bpf_fs_path, "global_counters"), key_size=4,
                value_size=8, n_cpus=self._n_cpus)
        except OSError:
            self._counters = None
        # map-full fallback ring buffer (consumed via mmap when pinned)
        self._ringbuf = None
        try:
            rb_map = syscall_bpf.BpfMap.open_pinned(
                os.path.join(bpf_fs_path, "direct_flows"), key_size=0,
                value_size=0)
            self._ringbuf = syscall_bpf.RingBufReader(rb_map)
        except (OSError, ValueError):
            log.debug("pinned direct_flows ringbuf absent; fallback disabled")
        # OpenSSL-uprobe plaintext events (consumed via mmap when pinned)
        self._ssl_rb = None
        try:
            ssl_map = syscall_bpf.BpfMap.open_pinned(
                os.path.join(bpf_fs_path, "ssl_events"), key_size=0,
                value_size=0)
            self._ssl_rb = syscall_bpf.RingBufReader(ssl_map)
        except (OSError, ValueError):
            log.debug("pinned ssl_events ringbuf absent")
        self._init_drain_lanes(drain_lanes)
        if native_pipeline and self._features:
            self._native_gate = NativeEvictPipeline(self, self._drain_lanes)

    def _init_drain_lanes(self, drain_lanes: int) -> None:
        """Provision the drain-lane pool (shared by the subclassed
        self-managed fetchers, which call this after their own map setup).
        Sequential resolution (1 lane) keeps the pool unbuilt — the
        parallel path is then one is-None check."""
        self._drain_lanes = resolve_drain_lanes(drain_lanes,
                                                len(self._features))
        self._drain_pool = None
        # EVICT_NATIVE_PIPELINE gate (bpfman mode only; unset = one
        # is-None check on the drain path)
        self._native_gate: Optional[NativeEvictPipeline] = None
        if self._drain_lanes > 1:
            from concurrent.futures import ThreadPoolExecutor
            # the pool never needs more workers than maps — lanes beyond
            # the map count become per-map merge row-shards instead
            # (_lookup_and_delete_lanes mthreads)
            self._drain_pool = ThreadPoolExecutor(
                max_workers=min(self._drain_lanes, len(self._features)),
                thread_name_prefix="evict-drain")
            log.info("eviction drain lanes: %d (feature maps: %d)",
                     self._drain_lanes, len(self._features))

    @classmethod
    def load(cls, cfg: AgentConfig) -> "BpfmanFetcher":
        return cls(cfg.bpfman_bpf_fs_path,
                   drain_lanes=cfg.evict_drain_lanes,
                   native_pipeline=cfg.evict_native_pipeline)

    def bind_pack_surface(self, surface) -> None:
        """Exporter hook: with EVICT_NATIVE_PIPELINE engaged, fused drains
        also pack resident regions with the exporter ring's dictionaries
        (staging.ResidentPackSurface). No-op when the gate is off."""
        if self._native_gate is not None:
            self._native_gate.bind_pack_surface(surface)

    def map_capacity(self) -> int:
        """max_entries of the kernel aggregation map — the denominator of
        the map-pressure watermark. In bpfman mode the external manager
        sized the map, so the agent reads the REAL capacity instead of
        trusting its own CACHE_MAX_FLOWS; 0 when unknown."""
        if self._agg is None:
            return 0
        return int(getattr(self._agg, "max_entries", 0) or 0)

    def lookup_and_delete(self) -> EvictedFlows:
        # columnar eviction plane: whole-array drain decode -> one batched
        # per-CPU merge per feature map -> vectorized key alignment. Child
        # spans ride the batch trace map_tracer bound for this drain (per
        # drain, never per record; unsampled drains get the null trace).
        trace = tracing.active_trace()
        t0 = time.perf_counter()
        if self._native_gate is not None:
            evicted = self._native_gate.drain(trace, t0)
            if evicted is not None:
                return evicted
            # probe drain or disqualified: island chain carries this one
        if self._drain_pool is not None and self._features:
            evicted = self._lookup_and_delete_lanes(trace, t0)
            if self._native_gate is not None:
                evicted.decode_stats["native_path"] = "chain"
            return evicted
        with trace.stage("decode"):
            agg_keys, agg_vals = _drain_map_arrays(
                self._agg, binfmt.FLOW_STATS_DTYPE)
            drained = {attr: _drain_map_arrays(fmap, dtype)
                       for attr, (fmap, dtype) in self._features.items()}
        t1 = time.perf_counter()
        evicted = decode_eviction(agg_keys, agg_vals, drained, trace=trace)
        evicted.decode_stats["decode_s"] = t1 - t0
        evicted.decode_stats["drain_lanes"] = 1
        evicted.decode_stats["seconds"] = time.perf_counter() - t0
        if self._native_gate is not None:
            evicted.decode_stats["native_path"] = "chain"
        return evicted

    def _lookup_and_delete_lanes(self, trace, t0: float) -> EvictedFlows:
        """Parallel drain lanes: each worker owns one feature map for this
        drain — batched drain syscalls + the native per-CPU merge, both of
        which release the GIL, run concurrently across maps while the
        calling thread drains the (largest) aggregation map. Merged records
        are fresh arrays; only the key views still alias lane buffers, and
        `decode_eviction` copies them out before returning (the per-lane
        zero-copy lifetime rule — class docstring)."""
        # maps with fewer lanes than workers row-shard their native merge
        mthreads = max(1, self._drain_lanes // max(1, len(self._features)))

        def lane(attr, fmap, dtype):
            ks, vals = _drain_map_arrays(fmap, dtype)
            tm = time.perf_counter()
            recs = flowpack.merge_percpu_batch(attr, vals,
                                               threads=mthreads)
            return attr, ks, recs, time.perf_counter() - tm

        with trace.stage("decode"):
            futs = [self._drain_pool.submit(lane, attr, fmap, dtype)
                    for attr, (fmap, dtype) in self._features.items()]
            agg_keys, agg_vals = _drain_map_arrays(
                self._agg, binfmt.FLOW_STATS_DTYPE)
            lanes = [f.result() for f in futs]
        t1 = time.perf_counter()
        # vals half None: the per-CPU partials were consumed in-lane —
        # decode_eviction's merged= contract (never smuggle merged
        # records into the partials slot)
        drained = {attr: (ks, None) for attr, ks, _recs, _dt in lanes}
        evicted = decode_eviction(
            agg_keys, agg_vals, drained, trace=trace,
            merged={attr: recs for attr, _ks, recs, _dt in lanes})
        # merge ran inside the lanes: report the summed lane CPU (the
        # overlap evidence — decode_s is the whole section's WALL)
        evicted.decode_stats["merge_s"] = sum(dt for *_x, dt in lanes)
        evicted.decode_stats["decode_s"] = t1 - t0
        evicted.decode_stats["drain_lanes"] = self._drain_lanes
        evicted.decode_stats["seconds"] = time.perf_counter() - t0
        return evicted

    def read_ringbuf(self, timeout_s: float) -> Optional[bytes]:
        """Consume the map-full fallback ring buffer (mmap reader) — the
        reference's bpfman branch also runs the ringbuf reader over the
        pinned map."""
        if self._ringbuf is None:
            time.sleep(timeout_s)
            return None
        return self._ringbuf.read(timeout_s)

    def read_ssl(self, timeout_s: float) -> Optional[bytes]:
        if self._ssl_rb is None:
            time.sleep(timeout_s)
            return None
        return self._ssl_rb.read(timeout_s)

    def read_global_counters(self) -> dict[GlobalCounter, int]:
        out: dict[GlobalCounter, int] = {}
        if self._counters is None:
            return out
        import struct as _struct
        for ctr in GlobalCounter:
            if ctr is GlobalCounter.MAX:
                continue
            key = _struct.pack("=I", ctr.value)
            raw = self._counters.lookup(key)
            if raw is None:
                continue
            total = sum(_struct.unpack_from("=Q", raw, off)[0]
                        for off in range(0, len(raw), 8))
            if total:
                out[ctr] = total
                # reset by writing zeros
                self._counters.update(key, b"\x00" * len(raw))
        return out

    def program_filters(self, rules) -> int:
        """Compile FLOW_FILTER_RULES into the pinned LPM tries (reference:
        Filter.ProgramFilter). Returns the number of rules written; 0 when
        the filter maps aren't pinned."""
        from netobserv_tpu.datapath import filter_compile

        compiled = filter_compile.compile_filters(rules)
        rules_map = peers_map = None
        try:
            try:
                rules_map = syscall_bpf.BpfMap.open_pinned(
                    os.path.join(self._base, "filter_rules"),
                    key_size=filter_compile.FILTER_KEY_SIZE,
                    value_size=filter_compile.FILTER_RULE_SIZE)
                peers_map = syscall_bpf.BpfMap.open_pinned(
                    os.path.join(self._base, "filter_peers"),
                    key_size=filter_compile.FILTER_KEY_SIZE, value_size=1)
            except OSError:
                log.warning("filter maps not pinned; FLOW_FILTER_RULES ignored")
                return 0
            if (rules_map.max_entries
                    and len(compiled.rules) > rules_map.max_entries):
                raise ValueError(
                    f"{len(compiled.rules)} filter rules exceed the pinned "
                    f"trie capacity {rules_map.max_entries}")
            for key, value in compiled.rules:
                rules_map.update(key, value)
            for key, value in compiled.peers:
                peers_map.update(key, value)
        finally:
            if rules_map is not None:
                rules_map.close()
            if peers_map is not None:
                peers_map.close()
        # NOTE: matching only takes effect if the external manager loaded the
        # datapath with cfg_enable_flow_filtering=1 (a load-time constant this
        # process cannot flip)
        log.info("wrote %d filter rules (+%d peer CIDRs); effective only if "
                 "the datapath was loaded with filtering enabled",
                 len(compiled.rules), len(compiled.peers))
        return len(compiled.rules)

    # no_dns_corr_key layout (bpf/maps.h); value = u64 query timestamp (mono)
    DNS_CORR_KEY_SIZE = 40

    def purge_stale(self, older_than_s: float) -> int:
        """Drop unanswered DNS/RTT correlations older than the deadline
        (reference: DeleteMapsStaleEntries, `tracer.go:1188-1216`). Lazily
        opens the pinned correlation maps; returns the purge count."""
        for attr, pin in (("_dns_inflight", "dns_inflight"),
                          ("_rtt_inflight", "rtt_inflight")):
            if not hasattr(self, attr):
                try:
                    setattr(self, attr, syscall_bpf.BpfMap.open_pinned(
                        os.path.join(self._base, pin),
                        key_size=self.DNS_CORR_KEY_SIZE, value_size=8))
                except (OSError, ValueError):
                    setattr(self, attr, None)
        import struct as _struct

        deadline = time.clock_gettime_ns(time.CLOCK_MONOTONIC) - int(
            older_than_s * 1e9)
        purged = 0
        # both correlation maps hold a u64 monotonic stamp per 40-byte key
        for corr in (self._dns_inflight, self._rtt_inflight):
            if corr is None:
                continue
            for key in corr.keys():
                raw = corr.lookup(key)
                if raw is None:
                    continue
                (sent_ns,) = _struct.unpack_from("=Q", raw, 0)
                if sent_ns < deadline:
                    if corr.delete(key):
                        purged += 1
        if purged:
            log.debug("purged %d stale correlations (dns/rtt)", purged)
        return purged

    def attach(self, if_index: int, if_name: str, direction: str,
               netns: str = "") -> None:
        pass  # programs are attached by the external manager

    def detach(self, if_index: int, if_name: str,
               netns: str = "") -> None:
        pass

    def close(self) -> None:
        if getattr(self, "_drain_pool", None) is not None:
            self._drain_pool.shutdown(wait=True)
            self._drain_pool = None
        if getattr(self, "_native_gate", None) is not None:
            self._native_gate.close()
            self._native_gate = None
        self._agg.close()
        for fmap, _ in self._features.values():
            fmap.close()
        if self._counters is not None:
            self._counters.close()
        if self._ringbuf is not None:
            self._ringbuf.close()
        if self._ssl_rb is not None:
            self._ssl_rb.close()
        for attr in ("_dns_inflight", "_rtt_inflight"):
            corr = getattr(self, attr, None)
            if corr is not None:
                corr.close()


BPF_MAP_TYPE_LPM_TRIE = 11
BPF_F_NO_PREALLOC = 1


def _create_filter_tries():
    """(filter_rules, filter_peers) LPM tries — shared by the flow and PCA
    self-managed fetchers."""
    from netobserv_tpu.datapath import filter_compile

    rules = syscall_bpf.BpfMap.create(
        BPF_MAP_TYPE_LPM_TRIE, filter_compile.FILTER_KEY_SIZE,
        filter_compile.FILTER_RULE_SIZE, filter_compile.MAX_FILTER_RULES,
        b"filter_rules", flags=BPF_F_NO_PREALLOC)
    peers = syscall_bpf.BpfMap.create(
        BPF_MAP_TYPE_LPM_TRIE, filter_compile.FILTER_KEY_SIZE, 1,
        filter_compile.MAX_FILTER_RULES, b"filter_peers",
        flags=BPF_F_NO_PREALLOC)
    return rules, peers


def _program_filter_tries(rules_map, peers_map, rules) -> int:
    """Compile FLOW_FILTER_RULES into live LPM tries; returns rules written."""
    from netobserv_tpu.datapath import filter_compile

    compiled = filter_compile.compile_filters(rules)
    for key, value in compiled.rules:
        rules_map.update(key, value)
    for key, value in compiled.peers:
        peers_map.update(key, value)
    return len(compiled.rules)


class _SelfManagedAttach:
    """TC/TCX attach lifecycle shared by the self-managed fetchers (flow +
    PCA): per-direction pinned programs, tcx/tc/any mode dispatch, netns
    entry, stale legacy cleanup, and full detach on close. Users provide
    `self._prog_fds`/`self._pins` (direction -> fd / pin path) and
    `self._mode`; `self._attached` maps (netns, if_index) -> (name, dir ->
    Attachment)."""

    def attach(self, if_index: int, if_name: str, direction: str,
               netns: str = "") -> None:
        """Attach inside `netns` when named: the calling thread enters the
        namespace for the attach syscalls (ifindex resolves there; TCX links
        and tc subprocesses bind to it) and restores itself after (reference:
        interfaces_listener.go:272-298 netns-scoped attach)."""
        from netobserv_tpu.datapath import tc_attach
        from netobserv_tpu.ifaces.netns import netns_context

        wanted = (["ingress", "egress"] if direction == "both"
                  else [direction])
        name, done = self._attached.setdefault(
            (netns, if_index), (if_name, {}))

        def stale_cleanup():
            # first legacy attach on this interface: drop stale clsact state
            # from prior runs (reference removeTCFilters, tracer.go:542-566);
            # never run when TCX succeeded — it would destroy third-party
            # clsact filters for nothing
            if not any(a.kind == "tc" for a in done.values()):
                tc_attach.remove_clsact(if_name)

        with netns_context(netns):
            for d in wanted:
                if d in done:
                    continue  # idempotent across listener retries
                done[d] = tc_attach.attach_mode(
                    self._prog_fds[d], self._pins[d], if_name, if_index, d,
                    mode=self._mode, pre_legacy=stale_cleanup)

    def detach(self, if_index: int, if_name: str,
               netns: str = "") -> None:
        from netobserv_tpu.ifaces.netns import netns_context

        entry = self._attached.pop((netns, if_index), None)
        if entry is None:
            return
        name, done = entry
        # TCX link closes are namespace-agnostic (fd-bound); legacy tc CLI
        # detaches must run inside the namespace. Enter it separately so a
        # failed entry/exit never re-runs detach on already-closed link fds.
        ctx = netns_context(netns)
        entered = True
        try:
            ctx.__enter__()
        except OSError as exc:
            entered = False
            log.debug("cannot enter netns %r to detach %s (%s); tc filters "
                      "die with the namespace", netns, name, exc)
        try:
            for d, att in done.items():
                if att.kind == "tc" and not entered:
                    continue  # tc CLI would hit the wrong namespace
                try:
                    att.detach()
                except Exception as exc:
                    log.debug("detach %s %s failed: %s", name, d, exc)
        finally:
            if entered:
                try:
                    ctx.__exit__(None, None, None)
                except OSError as exc:  # pragma: no cover - setns restore
                    log.warning("failed to restore netns after detach: %s",
                                exc)

    def _sweep_stale_pins(self) -> None:
        """Unpin leftovers from crashed runs (their TC filters die with the
        clsact qdisc, which attach() resets per interface; TCX links die with
        their fds at process exit — only the pins linger)."""
        import glob

        for path in glob.glob(self._PIN_PREFIX + "*"):
            try:
                os.unlink(path)
                log.info("removed stale program pin %s", path)
            except OSError:
                pass

    def _init_empty_maps(self) -> None:
        """The inherited eviction path expects these BpfmanFetcher fields;
        everything close() touches is initialized here so a failed
        _provision can clean up safely."""
        self._n_cpus = syscall_bpf.n_possible_cpus()
        self._base = ""
        self._features = {}
        self._drain_pool = None
        self._drain_lanes = 1
        self._agg = None
        self._prog_fds = {}
        self._pins = {}
        self._attached = {}
        self._counters = None
        self._ringbuf = None
        self._ssl_rb = None
        self._ssl_map = None
        self._ssl_uprobe = None
        self._kprobes = []
        self._gate_map = None
        self._dns_inflight = None
        self._rtt_inflight = None
        self._rb_map = None
        self._filter_rules = None
        self._filter_peers = None

    def _teardown_attachments(self) -> None:
        from netobserv_tpu.datapath import tc_attach
        from netobserv_tpu.ifaces.netns import netns_context

        for key in list(self._attached):
            netns, if_index = key
            name, dirs = self._attached[key]
            legacy = any(att.kind == "tc" for att in dirs.values())
            try:
                self.detach(if_index, name, netns=netns)
                if legacy:
                    with netns_context(netns):
                        tc_attach.remove_clsact(name)
            except Exception as exc:
                log.debug("cleanup of %s failed: %s", name, exc)
        for fd in set(self._prog_fds.values()):
            try:
                os.close(fd)
            except OSError:
                pass
        for pin in set(self._pins.values()):
            if os.path.exists(pin):
                os.unlink(pin)


class MinimalKernelFetcher(_SelfManagedAttach, BpfmanFetcher):
    """Self-managed kernel datapath from the hand-assembled flow program
    (datapath/asm_flowpath.py): creates the maps, loads one program per
    direction through the live verifier, attaches/detaches interfaces via
    TCX/TC, and evicts with the same syscall drain as bpfman mode.

    Feature coverage (each gated on config, like the C datapath's
    loader-rewritten constants): IPv4+IPv6 TCP/UDP/ICMP flows with MACs/DSCP/
    TCP flags, first-seen-interface dedup, 1/N sampling, DNS latency tracking
    (dns_inflight correlation + per-CPU flows_dns feature map), map-full
    fallback into the direct_flows ring buffer, and global health counters.
    Remaining clang-object-only features: in-kernel flow filter, TLS/QUIC
    inline trackers, RTT/drops/network-events probes (reference:
    pkg/tracer/tracer.go:92-273 loads the CO-RE object instead)."""

    needs_iface_discovery = True
    _PIN_PREFIX = "/sys/fs/bpf/netobserv_minflow_"

    BPF_MAP_TYPE_HASH = 1
    BPF_MAP_TYPE_LPM_TRIE = 11
    BPF_MAP_TYPE_PERCPU_HASH = 5
    BPF_MAP_TYPE_PERCPU_ARRAY = 6
    BPF_MAP_TYPE_RINGBUF = 27
    BPF_F_NO_PREALLOC = 1

    def __init__(self, cache_max_flows: int = 5000,
                 attach_mode: str = "tcx", sampling: int = 0,
                 enable_dns: bool = False, dns_port: int = 53,
                 enable_rtt: bool = False, enable_pkt_drops: bool = False,
                 enable_filters: bool = False, quic_mode: int = 0,
                 has_filter_sampling: bool = False,
                 enable_tls: bool = False,
                 enable_openssl: bool = False, libssl_path: str = "",
                 enable_ringbuf_fallback: bool = True,
                 ringbuf_bytes: int = 1 << 17,
                 drain_lanes: int = 0,
                 native_pipeline: bool = False,
                 # maps.h DEF_RINGBUF(ssl_events, 1<<27): 16KB * 1000/s * 5s
                 ssl_ring_bytes: int = 1 << 27):
        self._init_empty_maps()
        self._sweep_stale_pins()
        self._mode = attach_mode
        try:
            self._has_filter_sampling = (has_filter_sampling
                                         and enable_filters)
            self._provision(
                cache_max_flows, sampling, enable_dns, dns_port, enable_rtt,
                enable_pkt_drops, enable_filters, quic_mode, enable_tls,
                enable_openssl, libssl_path, enable_ringbuf_fallback,
                ringbuf_bytes, ssl_ring_bytes)
            self._init_drain_lanes(drain_lanes)
            if native_pipeline and self._features:
                self._native_gate = NativeEvictPipeline(self,
                                                        self._drain_lanes)
        except Exception:
            # a half-provisioned fetcher must not leak map/prog fds (a
            # supervisor retrying construction would exhaust fds)
            self.close()
            raise

    def _provision(self, cache_max_flows, sampling, enable_dns, dns_port,
                   enable_rtt, enable_pkt_drops, enable_filters, quic_mode,
                   enable_tls, enable_openssl, libssl_path,
                   enable_ringbuf_fallback, ringbuf_bytes, ssl_ring_bytes):
        from netobserv_tpu.datapath import asm_flowpath
        from netobserv_tpu.model.flow import GlobalCounter

        log.info("assembler datapath features: dns=%s rtt=%s drops=%s "
                 "filters=%s quic=%d tls=%s openssl=%s sampling=%d "
                 "filter_sampling=%s", enable_dns, enable_rtt,
                 enable_pkt_drops, enable_filters, quic_mode, enable_tls,
                 enable_openssl, sampling, self._has_filter_sampling)
        self._agg = syscall_bpf.BpfMap.create(
            self.BPF_MAP_TYPE_HASH, binfmt.FLOW_KEY_DTYPE.itemsize,
            binfmt.FLOW_STATS_DTYPE.itemsize, cache_max_flows, b"agg_flows")
        self._counters = syscall_bpf.BpfMap.create(
            self.BPF_MAP_TYPE_PERCPU_ARRAY, 4, 8, int(GlobalCounter.MAX),
            b"global_counters")
        dns_q_fd = dns_rec_fd = None
        if enable_dns:
            self._dns_inflight = syscall_bpf.BpfMap.create(
                self.BPF_MAP_TYPE_HASH, self.DNS_CORR_KEY_SIZE, 8,
                max(cache_max_flows, 1024), b"dns_inflight")
            dns_rec = syscall_bpf.BpfMap.create(
                self.BPF_MAP_TYPE_PERCPU_HASH, binfmt.FLOW_KEY_DTYPE.itemsize,
                binfmt.DNS_REC_DTYPE.itemsize, cache_max_flows, b"flows_dns")
            self._features["dns"] = (dns_rec, binfmt.DNS_REC_DTYPE)
            dns_q_fd, dns_rec_fd = self._dns_inflight.fd, dns_rec.fd
        rtt_q_fd = rtt_rec_fd = None
        if enable_rtt:
            self._rtt_inflight = syscall_bpf.BpfMap.create(
                self.BPF_MAP_TYPE_HASH, self.DNS_CORR_KEY_SIZE, 8,
                max(cache_max_flows, 1024), b"rtt_inflight")
            extra_rec = syscall_bpf.BpfMap.create(
                self.BPF_MAP_TYPE_PERCPU_HASH, binfmt.FLOW_KEY_DTYPE.itemsize,
                binfmt.EXTRA_REC_DTYPE.itemsize, cache_max_flows,
                b"flows_extra")
            self._features["extra"] = (extra_rec, binfmt.EXTRA_REC_DTYPE)
            rtt_q_fd, rtt_rec_fd = self._rtt_inflight.fd, extra_rec.fd
        # per-CPU sampling gate: only needed when sampling can skip packets
        # AND a kprobe consumes the decision (reference do_sampling pattern)
        self._gate_map = None
        want_probes = enable_rtt or enable_pkt_drops
        if (sampling > 1 or self._has_filter_sampling) and want_probes:
            self._gate_map = syscall_bpf.BpfMap.create(
                self.BPF_MAP_TYPE_PERCPU_ARRAY, 4, 1, 1, b"sampling_gate")
        gate_fd = self._gate_map.fd if self._gate_map else None
        if enable_rtt:
            # smoothed-RTT tracepoint (tcp/tcp_probe) alongside the TC
            # handshake RTT: both max-merge into flows_extra (handle_rtt).
            # Best-effort: a locked-down tracefs must not take down the
            # still-functional handshake-RTT path.
            from netobserv_tpu.datapath import asm_probes, uprobe

            try:
                self._attach_tracepoint(
                    asm_probes.build_rtt_tracepoint_program(
                        uprobe.tracepoint_fields("tcp", "tcp_probe"),
                        self._features["extra"][0].fd, gate_fd),
                    "tcp", "tcp_probe", b"rtt_srtt")
                log.info("smoothed-RTT tracepoint attached (tcp/tcp_probe)")
            except (OSError, RuntimeError, KeyError) as exc:
                log.warning("smoothed-RTT tracepoint unavailable (%s); "
                            "handshake RTT only", exc)
        if enable_pkt_drops:
            from netobserv_tpu.datapath import asm_probes, btf, uprobe

            if not btf.available():
                raise RuntimeError("ENABLE_PKT_DROPS needs "
                                   "/sys/kernel/btf/vmlinux to walk the "
                                   "dropped skb's headers")
            drops_rec = syscall_bpf.BpfMap.create(
                self.BPF_MAP_TYPE_PERCPU_HASH,
                binfmt.FLOW_KEY_DTYPE.itemsize,
                binfmt.DROPS_REC_DTYPE.itemsize, cache_max_flows,
                b"flows_drops")
            self._features["drops"] = (drops_rec, binfmt.DROPS_REC_DTYPE)
            self._attach_tracepoint(
                asm_probes.build_drops_program(
                    btf.kernel_btf(), drops_rec.fd,
                    uprobe.tracepoint_fields("skb", "kfree_skb"),
                    sampling_gate_fd=gate_fd),
                "skb", "kfree_skb", b"pkt_drops")
            log.info("packet-drop tracepoint attached (skb/kfree_skb, "
                     "BTF-resolved skb offsets)")
        quic_fd = None
        if quic_mode:
            quic_rec = syscall_bpf.BpfMap.create(
                self.BPF_MAP_TYPE_PERCPU_HASH, binfmt.FLOW_KEY_DTYPE.itemsize,
                binfmt.QUIC_REC_DTYPE.itemsize, cache_max_flows,
                b"flows_quic")
            self._features["quic"] = (quic_rec, binfmt.QUIC_REC_DTYPE)
            quic_fd = quic_rec.fd
        flt_rules_fd = flt_peers_fd = None
        if enable_filters:
            self._filter_rules, self._filter_peers = _create_filter_tries()
            flt_rules_fd = self._filter_rules.fd
            flt_peers_fd = self._filter_peers.fd
        rb_fd = None
        if enable_ringbuf_fallback:
            self._rb_map = syscall_bpf.BpfMap.create(
                self.BPF_MAP_TYPE_RINGBUF, 0, 0, ringbuf_bytes,
                b"direct_flows")
            self._ringbuf = syscall_bpf.RingBufReader(self._rb_map)
            rb_fd = self._rb_map.fd
        if enable_openssl:
            from netobserv_tpu.datapath import asm_ssl, uprobe

            path, sym_off = uprobe.resolve_ssl_library(libssl_path)
            self._ssl_map = syscall_bpf.BpfMap.create(
                self.BPF_MAP_TYPE_RINGBUF, 0, 0, ssl_ring_bytes,
                b"ssl_events")
            ssl_prog = syscall_bpf.prog_load(
                asm_ssl.build_ssl_write_program(self._ssl_map.fd),
                prog_type=syscall_bpf.BPF_PROG_TYPE_KPROBE,
                name=b"ssl_write")
            try:
                self._ssl_uprobe = uprobe.UprobeAttachment(
                    ssl_prog, path, sym_off)
            finally:
                os.close(ssl_prog)  # the perf event holds its own reference
            self._ssl_rb = syscall_bpf.RingBufReader(self._ssl_map)
            log.info("OpenSSL plaintext tracer attached: uprobe on %s", path)
        # one program instance per direction so direction_first is correct
        self._prog_fds: dict[str, int] = {}
        self._pins: dict[str, str] = {}
        for name, code in (("ingress", 0), ("egress", 1)):
            fd = syscall_bpf.prog_load(
                asm_flowpath.build_flow_program(
                    self._agg.fd, direction=code, sampling=sampling,
                    ringbuf_fd=rb_fd, counters_fd=self._counters.fd,
                    dns_inflight_fd=dns_q_fd, flows_dns_fd=dns_rec_fd,
                    dns_port=dns_port, rtt_inflight_fd=rtt_q_fd,
                    flows_extra_fd=rtt_rec_fd,
                    filter_rules_fd=flt_rules_fd,
                    filter_peers_fd=flt_peers_fd,
                    flows_quic_fd=quic_fd, quic_mode=quic_mode,
                    enable_tls=enable_tls, sampling_gate_fd=gate_fd,
                    has_filter_sampling=self._has_filter_sampling))
            pin = f"{self._PIN_PREFIX}{os.getpid()}_{name}"
            if os.path.exists(pin):
                os.unlink(pin)
            syscall_bpf.obj_pin(fd, pin)
            self._prog_fds[name] = fd
            self._pins[name] = pin
        # (netns, if_index) -> (if_name, direction -> live Attachment)
        self._attached: dict[tuple[str, int], tuple[str, dict]] = {}

    _UNSUPPORTED_FEATURES = (
        ("enable_network_events_monitoring", "network events (psample)"),
        ("enable_pkt_translation", "packet translation (nf_nat)"),
        ("enable_ipsec_tracking", "IPsec (xfrm)"),
    )

    @classmethod
    def load(cls, cfg: AgentConfig) -> "MinimalKernelFetcher":
        import shutil

        if os.geteuid() != 0:
            raise RuntimeError("kernel datapath requires root/CAP_BPF")
        if cfg.tc_attach_mode != "tcx" and shutil.which("tc") is None:
            raise RuntimeError("tc (iproute2) not found; cannot attach")
        wanted = [label for attr, label in cls._UNSUPPORTED_FEATURES
                  if getattr(cfg, attr)]
        if wanted:
            log.warning("enabled features need kprobe/fentry hooks the "
                        "assembler datapath cannot provide: %s — they will "
                        "produce no data (build the clang probes object or "
                        "use bpfman mode)", ", ".join(wanted))
        has_filter_sampling = bool(cfg.flow_filter_rules) and any(
            getattr(r, "sample", 0) for r in cfg.parsed_filter_rules())
        return cls(cache_max_flows=cfg.cache_max_flows,
                   attach_mode=cfg.tc_attach_mode, sampling=cfg.sampling,
                   enable_dns=cfg.enable_dns_tracking,
                   dns_port=cfg.dns_tracking_port,
                   enable_rtt=cfg.enable_rtt,
                   enable_pkt_drops=cfg.enable_pkt_drops,
                   enable_filters=bool(cfg.flow_filter_rules),
                   has_filter_sampling=has_filter_sampling,
                   quic_mode=cfg.quic_tracking_mode,
                   enable_tls=cfg.enable_tls_tracking,
                   enable_openssl=cfg.enable_openssl_tracking,
                   libssl_path=cfg.openssl_path,
                   enable_ringbuf_fallback=cfg.enable_flows_ringbuf_fallback,
                   drain_lanes=cfg.evict_drain_lanes,
                   native_pipeline=cfg.evict_native_pipeline)

    def _attach_tracepoint(self, prog_bytes: bytes, category: str,
                           name: str, prog_name: bytes) -> None:
        """Load a tracepoint program and bind it to its perf event; the
        live attachment owns the program (the prog fd is dropped)."""
        from netobserv_tpu.datapath import uprobe

        prog = syscall_bpf.prog_load(
            prog_bytes, prog_type=syscall_bpf.BPF_PROG_TYPE_TRACEPOINT,
            name=prog_name)
        try:
            self._kprobes.append(
                uprobe.TracepointAttachment(prog, category, name))
        finally:
            os.close(prog)

    def program_filters(self, rules) -> int:
        """Compile FLOW_FILTER_RULES into this fetcher's own LPM tries (the
        bpfman override programs pinned tries instead). The kernel-side gate
        is active because the programs were built with the trie fds wired."""
        from netobserv_tpu.datapath import filter_compile

        if self._filter_rules is None:
            if rules:
                log.warning("filter maps not provisioned (enable_filters "
                            "was off at load); FLOW_FILTER_RULES ignored")
            return 0
        if (any(getattr(r, "sample", 0) for r in rules)
                and not getattr(self, "_has_filter_sampling", False)):
            log.warning("rules carry sample overrides but the programs were "
                        "built without has_filter_sampling; overrides will "
                        "not take effect (reload with the flag)")
        n = _program_filter_tries(self._filter_rules, self._filter_peers,
                                  rules)
        log.info("programmed %d filter rules into the kernel gate", n)
        return n

    def close(self) -> None:
        if getattr(self, "_drain_pool", None) is not None:
            self._drain_pool.shutdown(wait=True)
            self._drain_pool = None
        self._teardown_attachments()
        if self._agg is not None:
            self._agg.close()
        if self._counters is not None:
            self._counters.close()
        if self._ringbuf is not None:
            self._ringbuf.close()
        if self._rb_map is not None:
            self._rb_map.close()
        if self._dns_inflight is not None:
            self._dns_inflight.close()
        if self._rtt_inflight is not None:
            self._rtt_inflight.close()
        if self._filter_rules is not None:
            self._filter_rules.close()
        if self._filter_peers is not None:
            self._filter_peers.close()
        if self._ssl_uprobe is not None:
            self._ssl_uprobe.close()
        if self._ssl_rb is not None:
            self._ssl_rb.close()
        if self._ssl_map is not None:
            self._ssl_map.close()
        for kp in self._kprobes:
            kp.close()
        if self._gate_map is not None:
            self._gate_map.close()
        for fmap, _dtype in self._features.values():
            fmap.close()


class MinimalPacketFetcher(_SelfManagedAttach):
    """Self-managed PCA datapath from the hand-assembled capture program
    (datapath/asm_pca.py): creates the packet_records ring buffer, loads the
    program through the live verifier, attaches via TCX/tc, and serves raw
    `no_packet_event` records to PerfTracer through the mmap ring reader —
    the compiler-free analog of the reference's PCA fetcher
    (pkg/tracer/tracer.go:1552-2076)."""

    needs_iface_discovery = True
    _PIN_PREFIX = "/sys/fs/bpf/netobserv_minpca_"

    def __init__(self, ring_bytes: int = 1 << 21, attach_mode: str = "tcx",
                 sampling: int = 0, enable_filters: bool = False):
        self._mode = attach_mode
        self._sweep_stale_pins()
        self._filter_rules = self._filter_peers = None
        self._rb_map = None
        self._reader = None
        self._prog_fds = {}
        self._pins = {}
        self._attached: dict[tuple[str, int], tuple[str, dict]] = {}
        try:
            self._provision(ring_bytes, sampling, enable_filters)
        except Exception:
            self.close()  # a half-provisioned fetcher must not leak fds
            raise

    def _provision(self, ring_bytes, sampling, enable_filters) -> None:
        from netobserv_tpu.datapath import asm_pca

        BPF_MAP_TYPE_RINGBUF = 27
        flt_rules_fd = flt_peers_fd = None
        if enable_filters:
            self._filter_rules, self._filter_peers = _create_filter_tries()
            flt_rules_fd = self._filter_rules.fd
            flt_peers_fd = self._filter_peers.fd
        self._rb_map = syscall_bpf.BpfMap.create(
            BPF_MAP_TYPE_RINGBUF, 0, 0, ring_bytes, b"pkt_records")
        if enable_filters:
            # filters evaluate a direction predicate, so each hook needs its
            # own program instance (like the flow datapath)
            for name, code in (("ingress", 0), ("egress", 1)):
                fd = syscall_bpf.prog_load(
                    asm_pca.build_pca_program(
                        self._rb_map.fd, sampling=sampling, direction=code,
                        filter_rules_fd=flt_rules_fd,
                        filter_peers_fd=flt_peers_fd),
                    name=b"netobserv_pca")
                pin = f"{self._PIN_PREFIX}{os.getpid()}_{name}"
                if os.path.exists(pin):
                    os.unlink(pin)
                syscall_bpf.obj_pin(fd, pin)
                self._prog_fds[name] = fd
                self._pins[name] = pin
        else:
            # one program serves both hooks (the record carries no direction)
            fd = syscall_bpf.prog_load(
                asm_pca.build_pca_program(self._rb_map.fd, sampling=sampling),
                name=b"netobserv_pca")
            pin = f"{self._PIN_PREFIX}{os.getpid()}"
            if os.path.exists(pin):
                os.unlink(pin)
            syscall_bpf.obj_pin(fd, pin)
            self._prog_fds = {"ingress": fd, "egress": fd}
            self._pins = {"ingress": pin, "egress": pin}
        self._reader = syscall_bpf.RingBufReader(self._rb_map)

    @classmethod
    def load(cls, cfg: AgentConfig) -> "MinimalPacketFetcher":
        import shutil

        if os.geteuid() != 0:
            raise RuntimeError("kernel datapath requires root/CAP_BPF")
        if cfg.tc_attach_mode != "tcx" and shutil.which("tc") is None:
            raise RuntimeError("tc (iproute2) not found; cannot attach")
        return cls(attach_mode=cfg.tc_attach_mode, sampling=cfg.sampling,
                   enable_filters=bool(cfg.flow_filter_rules))

    def program_filters(self, rules) -> int:
        """Same kernel-gate programming as the flow fetcher: captured
        packets are the ones an Accept rule matches (pca.h parity)."""
        if self._filter_rules is None:
            if rules:
                log.warning("PCA filter maps not provisioned; "
                            "FLOW_FILTER_RULES ignored")
            return 0
        return _program_filter_tries(self._filter_rules, self._filter_peers,
                                     rules)

    def read_packet(self, timeout_s: float):
        return self._reader.read(timeout_s)

    def close(self) -> None:
        self._teardown_attachments()
        if self._reader is not None:
            self._reader.close()
        if self._rb_map is not None:
            self._rb_map.close()
        if self._filter_rules is not None:
            self._filter_rules.close()
        if self._filter_peers is not None:
            self._filter_peers.close()



def _libbpf_open_and_load(obj_path: str, resize: dict, knobs: dict,
                          entry_names: dict):
    """Shared clang-object lifecycle (both fetcher twins): open, pinning
    strip, map resize, volatile-const patch (ELF-symtab offsets), entry-
    point check, prune everything but the selected entries, verifier load.
    Returns the loaded BpfObject."""
    from netobserv_tpu.datapath import libbpf as lb

    obj = lb.BpfObject(obj_path)
    try:
        for m in obj.maps():
            m.disable_pinning()
            want = resize.get(m.name)
            if want:
                m.set_max_entries(want)
        syms = lb.rodata_symbols(obj_path)
        patches = {}
        for name, val in knobs.items():
            if name in syms:
                off, size = syms[name]
                patches[off] = (size, int(val))
            else:
                log.debug("const %s absent in %s", name, obj_path)
        if patches:
            obj.patch_rodata(patches)
        for pname in entry_names.values():
            if obj.program(pname) is None:
                raise RuntimeError(f"object lacks program {pname}")
        wanted = set(entry_names.values())
        for p in obj.programs():
            if p.name not in wanted:
                p.set_autoload(False)
            else:
                # force SCHED_CLS on EVERY entry: this tree's "tc_*"
                # sections are custom, and "tcx/..." sec_defs only exist in
                # libbpf >= 1.3 (v1.1 leaves them UNSPEC and load fails);
                # plain SCHED_CLS attaches through both the TCX link and
                # legacy tc paths, exactly like the assembler programs
                p.set_type(3)
        obj.load()
        return obj
    except Exception:
        obj.close()
        raise


def _libbpf_default_resize(cache: int) -> dict:
    """Every oversized map in maps.h must shrink BEFORE load — libbpf
    creates ALL object maps regardless of program autoload, and the
    declared 1<<24-entry preallocated per-CPU hashes would ENOMEM."""
    return {"aggregated_flows": cache, "flows_dns": cache,
            "flows_drops": cache, "flows_nevents": cache,
            "flows_xlat": cache, "flows_extra": cache,
            "flows_quic": cache, "dns_inflight": max(cache, 1024),
            "direct_flows": 1 << 17, "ssl_events": 1 << 20,
            "packet_records": 1 << 17}


def _libbpf_pin_entries(obj, entry_names: dict, prefix: str):
    """(prog_fds, pins): dup per-direction entry fds and pin them (the
    legacy tc attach path needs a pinned program path)."""
    prog_fds, pins = {}, {}
    for d, pname in entry_names.items():
        fd = os.dup(obj.program(pname).fd)
        pin = f"{prefix}{os.getpid()}_{d}"
        if os.path.exists(pin):
            os.unlink(pin)
        syscall_bpf.obj_pin(fd, pin)
        prog_fds[d] = fd
        pins[d] = pin
    return prog_fds, pins


def _libbpf_release(self) -> None:
    """Shared teardown for the libbpf fetchers' fds/pins/object."""
    for fd in self._prog_fds.values():
        try:
            os.close(fd)
        except OSError:
            pass
    self._prog_fds = {}
    for pin in self._pins.values():
        try:
            os.unlink(pin)
        except OSError:
            pass
    self._pins = {}
    if self._obj is not None:
        self._obj.close()
        self._obj = None


class LibbpfKernelFetcher(_SelfManagedAttach, BpfmanFetcher):
    """Full C datapath: loads the CI-built CO-RE object (flowpath.c — every
    inline tracker) through the system libbpf, with the reference's load
    lifecycle (`pkg/tracer/tracer.go:92-273`): map resize per config,
    pinning strip, `volatile const` rewrite from the parsed env config,
    capability-based program pruning, verifier load, per-direction TCX/TC
    attach, and the shared per-CPU drain at eviction.

    The lifecycle machinery is kernel-proven in this image against a real
    clang CO-RE artifact (tests/test_libbpf_loader.py); the object itself
    is produced where clang exists (CI `make bpf`)."""

    needs_iface_discovery = True
    _PIN_PREFIX = "/sys/fs/bpf/netobserv_cobj_"

    def __init__(self, cfg: AgentConfig, obj_path: str = _OBJ_PATH):
        self._init_empty_maps()
        self._sweep_stale_pins()
        self._mode = cfg.tc_attach_mode
        self._obj = None
        try:
            self._provision_object(cfg, obj_path)
            self._init_drain_lanes(cfg.evict_drain_lanes)
            if cfg.evict_native_pipeline and self._features:
                self._native_gate = NativeEvictPipeline(self,
                                                        self._drain_lanes)
        except Exception:
            self.close()
            raise

    def _provision_object(self, cfg: AgentConfig, obj_path: str) -> None:
        use_tcx = self._mode != "tc"
        entry_names = {"ingress": ("tcx_ingress_flow" if use_tcx
                                   else "tc_ingress_flow"),
                       "egress": ("tcx_egress_flow" if use_tcx
                                  else "tc_egress_flow")}
        knobs = {
            "cfg_sampling": cfg.sampling,
            "cfg_trace_messages": int(cfg.log_level.lower() in
                                      ("debug", "trace")),
            "cfg_enable_rtt": int(cfg.enable_rtt),
            "cfg_enable_dns_tracking": int(cfg.enable_dns_tracking),
            "cfg_dns_port": cfg.dns_tracking_port,
            "cfg_enable_pkt_drops": int(cfg.enable_pkt_drops),
            "cfg_enable_flow_filtering": int(bool(cfg.flow_filter_rules)),
            "cfg_enable_tls_tracking": int(cfg.enable_tls_tracking),
            "cfg_quic_mode": cfg.quic_tracking_mode,
            "cfg_enable_ringbuf_fallback":
                int(cfg.enable_flows_ringbuf_fallback),
            "cfg_enable_ipsec": int(cfg.enable_ipsec_tracking),
            "cfg_enable_network_events":
                int(cfg.enable_network_events_monitoring),
            "cfg_network_events_group_id":
                cfg.network_events_monitoring_group_id,
            "cfg_enable_pkt_translation": int(cfg.enable_pkt_translation),
        }
        if cfg.flow_filter_rules:
            # per-rule sampling moves the 1/N gate after the filter
            # (config.h:52, flowpath.c:155-180)
            knobs["cfg_has_sampling"] = int(any(
                getattr(r, "sample", 0) for r in cfg.parsed_filter_rules()))
        obj = _libbpf_open_and_load(
            obj_path, _libbpf_default_resize(cfg.cache_max_flows), knobs,
            entry_names)
        self._obj = obj
        # layout contract: the object's maps must match the binfmt dtypes
        # byte-for-byte or the drain would mis-decode (records.h <-> binfmt
        # is machine-checked in tests; this guards a stale/foreign object)
        agg_h = obj.map("aggregated_flows")
        if agg_h is None:
            raise RuntimeError("object lacks aggregated_flows")
        if (agg_h.key_size != binfmt.FLOW_KEY_DTYPE.itemsize
                or agg_h.value_size != binfmt.FLOW_STATS_DTYPE.itemsize):
            raise RuntimeError(
                f"object layout mismatch: aggregated_flows "
                f"{agg_h.key_size}/{agg_h.value_size} != binfmt "
                f"{binfmt.FLOW_KEY_DTYPE.itemsize}/"
                f"{binfmt.FLOW_STATS_DTYPE.itemsize} — rebuild the object "
                "against this tree's records.h")
        for name, dtype, _attr in _FEATURE_MAPS:
            h = obj.map(name)
            if h is not None and h.value_size != dtype.itemsize:
                raise RuntimeError(
                    f"object layout mismatch: {name} value {h.value_size} "
                    f"!= {dtype.itemsize}")

        def wrap(name: str, n_cpus: int = 1):
            h = obj.map(name)
            if h is None:
                return None
            bm = syscall_bpf.BpfMap(
                os.dup(h.fd), h.key_size, h.value_size, h.max_entries,
                n_cpus=n_cpus,
                percpu=h.type in syscall_bpf.PERCPU_MAP_TYPES)
            return bm

        ncpu = self._n_cpus
        self._agg = wrap("aggregated_flows")
        self._counters = wrap("global_counters", ncpu)
        for name, dtype, attr in _FEATURE_MAPS:
            bm = wrap(name, ncpu)
            if bm is not None:
                self._features[attr] = (bm, dtype)
        self._dns_inflight = wrap("dns_inflight")
        self._filter_rules = wrap("filter_rules")
        self._filter_peers = wrap("filter_peers")
        if cfg.enable_flows_ringbuf_fallback:
            self._rb_map = wrap("direct_flows")
            if self._rb_map is not None:
                self._ringbuf = syscall_bpf.RingBufReader(self._rb_map)
        self._prog_fds, self._pins = _libbpf_pin_entries(
            obj, entry_names, self._PIN_PREFIX)
        self._probe_links = []
        self._probes_obj = None
        probes_path = os.path.join(os.path.dirname(obj_path),
                                   "flowpath_probes.bpf.o")
        if os.path.exists(probes_path):
            try:
                self._load_probes(cfg, probes_path, knobs)
            except Exception as exc:
                log.warning("probes object %s unusable (%s); probe-based "
                            "features degrade to the inline trackers",
                            probes_path, exc)

    # SEC-prefix -> (config gate, capability) for the aux hook programs
    # (reference attach ladder, tracer.go:184-273). rtt_tier selects the RTT
    # hook flavor: "fentry" -> "kprobe" (trampoline unusable) -> "none"
    # (both RTT twins rejected; every OTHER wanted probe still loads).
    @staticmethod
    def _probe_wanted(cfg, section: str, rtt_tier: str,
                      have_kprobes: bool, have_tracepoints: bool) -> bool:
        if section.startswith("tracepoint/skb/kfree_skb"):
            return cfg.enable_pkt_drops and have_tracepoints
        if section.startswith("fentry/tcp_rcv"):
            return cfg.enable_rtt and rtt_tier == "fentry"
        if section.startswith("kprobe/tcp_rcv"):
            # kprobe fallback only when fentry is off the table
            return cfg.enable_rtt and have_kprobes and rtt_tier == "kprobe"
        if section.startswith("kprobe/psample"):
            return cfg.enable_network_events_monitoring and have_kprobes
        if section.startswith("kprobe/nf_nat"):
            return cfg.enable_pkt_translation and have_kprobes
        if section.startswith(("kprobe/xfrm", "kretprobe/xfrm")):
            return cfg.enable_ipsec_tracking and have_kprobes
        return False                            # uprobe/...: asm path owns it

    def _load_probes(self, cfg, probes_path: str, knobs: dict) -> None:
        """Load the aux-hook object, sharing the flow object's maps
        (bpf_map__reuse_fd) so probe records land in the maps the drain
        reads. fentry needs trampoline support libbpf only reveals at load
        — ladder: try with fentry, retry without (reference fentry->kprobe
        fallback, tracer.go:203-222)."""
        from netobserv_tpu.datapath import libbpf as lb

        have_tracepoints = any(os.path.isdir(p) for p in (
            "/sys/kernel/tracing/events",
            "/sys/kernel/debug/tracing/events"))
        have_kprobes = (os.path.isdir("/sys/bus/event_source/devices/kprobe")
                        or any(os.path.exists(p) for p in (
                            "/sys/kernel/tracing/kprobe_events",
                            "/sys/kernel/debug/tracing/kprobe_events")))
        syms = lb.rodata_symbols(probes_path)
        last_exc: Exception | None = None
        rtt_ladder = ["fentry"]
        if have_kprobes:
            rtt_ladder.append("kprobe")
        rtt_ladder.append("none")
        for rtt_tier in rtt_ladder:
            pobj = lb.BpfObject(probes_path)
            try:
                wanted_any = False
                for p in pobj.programs():
                    want = self._probe_wanted(cfg, p.section, rtt_tier,
                                              have_kprobes, have_tracepoints)
                    if not want:
                        p.set_autoload(False)
                    wanted_any = wanted_any or want
                if not wanted_any:
                    pobj.close()
                    log.info("no probe hooks wanted/attachable on this "
                             "kernel; skipping %s", probes_path)
                    return
                resize = _libbpf_default_resize(cfg.cache_max_flows)
                for m in pobj.maps():
                    m.disable_pinning()
                    # internal maps are named '<8-char-obj-prefix>.rodata'
                    # etc. — never share those: the probes object needs its
                    # OWN patched consts, not the flow object's image
                    if "." in m.name:
                        continue
                    shared = self._obj.map(m.name)
                    if shared is not None:
                        m.reuse_fd(shared.fd)
                    elif m.name in resize:
                        # unshared probes-only maps get the same pre-load
                        # shrink the flow object does: libbpf creates every
                        # object map at its declared size regardless of
                        # program autoload, and maps.h declares 1<<24-scale
                        m.set_max_entries(resize[m.name])
                patches = {}
                for name, val in knobs.items():
                    if name in syms:
                        off, size = syms[name]
                        patches[off] = (size, int(val))
                if patches:
                    pobj.patch_rodata(patches)
                pobj.load()
                links = []
                fentry_attach_failed = False
                # fentry first: if its trampoline is rejected at ATTACH we
                # rerun the whole ladder, so don't attach anything else
                # before that verdict is in
                progs = sorted((p for p in pobj.programs() if p.autoload),
                               key=lambda p:
                               not p.section.startswith("fentry/"))
                for p in progs:
                    try:
                        links.append(p.attach())
                        log.info("probe attached: %s", p.section)
                    except OSError as exc:
                        if (rtt_tier == "fentry"
                                and p.section.startswith("fentry/")):
                            # some kernels accept the fentry program at load
                            # but reject the trampoline at ATTACH; the
                            # reference falls back to the kprobe twin there
                            # too (tracer.go:203-222), so rerun the ladder
                            fentry_attach_failed = True
                            log.warning(
                                "fentry probe %s attach failed (%s); %s",
                                p.section, exc,
                                "retrying with the kprobe fallback"
                                if have_kprobes else
                                "no kprobe support here — RTT probe dropped")
                            break
                        log.warning("probe %s attach failed: %s",
                                    p.section, exc)
                if fentry_attach_failed:
                    for link in links:
                        link.destroy()
                    pobj.close()
                    continue
                self._probes_obj = pobj
                self._probe_links = links
                return
            except OSError as exc:
                pobj.close()
                last_exc = exc
                if rtt_tier != "none":
                    log.debug("probes load at RTT tier %r failed (%s); "
                              "laddering down", rtt_tier, exc)
        raise last_exc if last_exc else RuntimeError("probes load failed")

    def program_filters(self, rules) -> int:
        if self._filter_rules is None:
            if rules:
                log.warning("object has no filter maps; rules ignored")
            return 0
        return _program_filter_tries(self._filter_rules, self._filter_peers,
                                     rules)

    def close(self) -> None:
        if getattr(self, "_drain_pool", None) is not None:
            self._drain_pool.shutdown(wait=True)
            self._drain_pool = None
        self._teardown_attachments()
        for link in getattr(self, "_probe_links", []):
            link.destroy()
        self._probe_links = []
        pobj = getattr(self, "_probes_obj", None)
        if pobj is not None:
            pobj.close()
            self._probes_obj = None
        if self._ringbuf is not None:
            self._ringbuf.close()
            self._ringbuf = None
        for bm in [self._agg, self._counters, self._dns_inflight,
                   self._filter_rules, self._filter_peers, self._rb_map]:
            if bm is not None:
                bm.close()
        for bm, _dtype in self._features.values():
            bm.close()
        self._features = {}
        _libbpf_release(self)


class LibbpfPacketFetcher(_SelfManagedAttach):
    """PCA twin of LibbpfKernelFetcher (reference PacketFetcher,
    tracer.go:1552-2076): loads the CI-built object with cfg_enable_pca
    patched on, autoloads only the PCA entry points, and serves raw
    packet_records to the packets agent through the mmap ring reader."""

    needs_iface_discovery = True
    _PIN_PREFIX = "/sys/fs/bpf/netobserv_cpca_"

    def __init__(self, cfg: AgentConfig, obj_path: str = _OBJ_PATH,
                 ring_bytes: int = 1 << 21):
        self._mode = cfg.tc_attach_mode
        self._sweep_stale_pins()
        self._filter_rules = self._filter_peers = None
        self._rb_map = None
        self._reader = None
        self._obj = None
        self._prog_fds = {}
        self._pins = {}
        self._attached = {}
        try:
            self._provision_object(cfg, obj_path, ring_bytes)
        except Exception:
            self.close()
            raise

    def _provision_object(self, cfg, obj_path, ring_bytes) -> None:
        use_tcx = self._mode != "tc"
        entry_names = {"ingress": ("tcx_pca_ingress" if use_tcx
                                   else "tc_pca_ingress"),
                       "egress": ("tcx_pca_egress" if use_tcx
                                  else "tc_pca_egress")}
        # the flow maps still get created at load (libbpf creates every
        # object map regardless of autoload) — shrink them all
        resize = _libbpf_default_resize(cache=512)
        resize["packet_records"] = ring_bytes
        knobs = {"cfg_enable_pca": 1, "cfg_sampling": cfg.sampling,
                 "cfg_enable_flow_filtering":
                     int(bool(cfg.flow_filter_rules))}
        obj = _libbpf_open_and_load(obj_path, resize, knobs, entry_names)
        self._obj = obj
        rb = obj.map("packet_records")
        if rb is None:
            raise RuntimeError("object lacks packet_records")
        self._rb_map = syscall_bpf.BpfMap(os.dup(rb.fd), 0, 0)
        self._reader = syscall_bpf.RingBufReader(self._rb_map)
        fr, fp = obj.map("filter_rules"), obj.map("filter_peers")
        if fr is not None and fp is not None:
            self._filter_rules = syscall_bpf.BpfMap(
                os.dup(fr.fd), fr.key_size, fr.value_size)
            self._filter_peers = syscall_bpf.BpfMap(
                os.dup(fp.fd), fp.key_size, fp.value_size)
        self._prog_fds, self._pins = _libbpf_pin_entries(
            obj, entry_names, self._PIN_PREFIX)

    def program_filters(self, rules) -> int:
        if self._filter_rules is None:
            if rules:
                log.warning("object has no filter maps; rules ignored")
            return 0
        return _program_filter_tries(self._filter_rules, self._filter_peers,
                                     rules)

    def read_packet(self, timeout_s: float):
        return self._reader.read(timeout_s)

    def close(self) -> None:
        self._teardown_attachments()
        if self._reader is not None:
            self._reader.close()
            self._reader = None
        for bm in (self._rb_map, self._filter_rules, self._filter_peers):
            if bm is not None:
                bm.close()
        self._rb_map = self._filter_rules = self._filter_peers = None
        _libbpf_release(self)


def load_packet_fetcher(cfg: AgentConfig):
    """PCA fetcher dispatch, mirroring KernelFetcher.load: the CI-built
    clang object when present+loadable, else the assembler PCA program."""
    return _load_clang_or_fallback(
        cfg, lambda c: LibbpfPacketFetcher(c, _OBJ_PATH),
        MinimalPacketFetcher.load, "PCA datapath")


def _load_clang_or_fallback(cfg: AgentConfig, clang_ctor, fallback,
                            noun: str):
    """Shared dispatch ladder: clang object via libbpf when present and
    loadable, else the assembler implementation, with one log line per
    branch so a degraded start is always explained."""
    if os.geteuid() != 0:
        raise RuntimeError("kernel datapath requires root/CAP_BPF")
    if os.path.exists(_OBJ_PATH):
        from netobserv_tpu.datapath import libbpf as lb

        if lb.available():
            try:
                fetcher = clang_ctor(cfg)
                log.info("loaded the clang-built %s %s via libbpf",
                         noun, _OBJ_PATH)
                return fetcher
            except Exception as exc:
                log.warning("clang %s failed to load (%s); falling back "
                            "to the assembler implementation", noun, exc)
        else:
            log.warning("clang object %s present but libbpf is not "
                        "available; using the assembler %s",
                        _OBJ_PATH, noun)
    else:
        log.info("no clang-built BPF object (%s); using the assembler %s",
                 _OBJ_PATH, noun)
    return fallback(cfg)
