"""Kernel datapath loader (libbpf-backed), gated on environment support.

Reference analog: `pkg/tracer/tracer.go` (NewFlowFetcher: load spec, resize
maps, rewrite config constants, attach TCX/TC, evict via lookup-and-delete).

The BPF object is compiled from `netobserv_tpu/datapath/bpf/` by the cmake
build (`netobserv_tpu/datapath/native/`), which requires clang with BPF target
support — not present in every environment, so everything here degrades to a
clear error and the agent falls back to replay datapaths.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import os

from netobserv_tpu.config import AgentConfig

_OBJ_PATH = os.path.join(os.path.dirname(__file__), "native", "build",
                         "flowpath.bpf.o")


class KernelFetcher:
    """FlowFetcher backed by real kernel maps. Requires:
    - CAP_BPF + CAP_PERFMON (or root),
    - a compiled BPF object (see datapath/native/CMakeLists.txt),
    - libbpf.so available to the dynamic linker.
    """

    needs_iface_discovery = True  # the agent starts an InterfaceListener

    @classmethod
    def load(cls, cfg: AgentConfig) -> "KernelFetcher":
        lib = ctypes.util.find_library("bpf")
        if lib is None:
            raise RuntimeError("libbpf not found")
        if not os.path.exists(_OBJ_PATH):
            raise RuntimeError(
                f"BPF object not built ({_OBJ_PATH}); run the datapath build "
                "(requires clang with -target bpf)")
        if os.geteuid() != 0:
            raise RuntimeError("kernel datapath requires root/CAP_BPF")
        raise NotImplementedError(
            "kernel loader attach path lands with the native evictor")
