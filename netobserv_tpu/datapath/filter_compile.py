"""Flow-filter rule compiler: config rules -> LPM trie entries.

Reference analog: `pkg/tracer/flow_filter.go` — converts the JSON
FLOW_FILTER_RULES into the datapath's `filter_rules` LPM entries (struct
no_filter_rule in bpf/maps.h, byte layout pinned here) plus `filter_peers`
entries for peer-CIDR predicates. Used by the kernel loader at program time;
pure and fully testable without a kernel.
"""

from __future__ import annotations

import ipaddress
import struct
from dataclasses import dataclass

from netobserv_tpu.config import FlowFilterRule
from netobserv_tpu.model.flow import TcpFlags, ip_to_16

_PROTOS = {"TCP": 6, "UDP": 17, "SCTP": 132, "ICMP": 1, "ICMPV6": 58}
_DIRECTIONS = {"": 255, "INGRESS": 0, "EGRESS": 1}
_TCP_FLAG_NAMES = {
    "FIN": TcpFlags.FIN, "SYN": TcpFlags.SYN, "RST": TcpFlags.RST,
    "PSH": TcpFlags.PSH, "ACK": TcpFlags.ACK, "URG": TcpFlags.URG,
    "ECE": TcpFlags.ECE, "CWR": TcpFlags.CWR,
    "SYN-ACK": TcpFlags.SYN_ACK, "FIN-ACK": TcpFlags.FIN_ACK,
    "RST-ACK": TcpFlags.RST_ACK,
}

import numpy as np

from netobserv_tpu.model import binfmt

# layouts are pinned against the C structs by tests/test_layout_parity.py;
# the dtype is the single source of truth for the value encoding
FILTER_KEY_SIZE = binfmt.FILTER_KEY_DTYPE.itemsize  # 20
FILTER_RULE_SIZE = binfmt.FILTER_RULE_DTYPE.itemsize  # 40
# LPM trie capacity in bpf/maps.h (MAX_FILTER_ENTRIES analog)
MAX_FILTER_RULES = 16


@dataclass(frozen=True)
class CompiledFilter:
    rules: list[tuple[bytes, bytes]]  # (lpm key, rule value)
    peers: list[tuple[bytes, bytes]]  # (lpm key, 1-byte marker)


def _check_port(p: int) -> int:
    if not 0 <= p <= 65535:
        raise ValueError(f"port {p} out of range 0-65535")
    return p


def _parse_ports(single: int, range_: str, list_: str) -> tuple[int, int, int, int]:
    """-> (start, end, p1, p2); reference semantics: range XOR up-to-2 ports."""
    if range_ and (single or list_):
        raise ValueError("port range is exclusive with port/ports")
    if range_:
        lo, _, hi = range_.partition("-")
        start, end = _check_port(int(lo)), _check_port(int(hi))
        if start >= end:
            raise ValueError(f"invalid port range {range_!r}")
        return start, end, 0, 0
    if list_:
        ports = [_check_port(int(p)) for p in list_.split(",") if p.strip()]
        if not 1 <= len(ports) <= 2:
            raise ValueError("ports list supports one or two ports")
        p1 = ports[0]
        p2 = ports[1] if len(ports) > 1 else ports[0]
        return 0, 0, p1, p2
    if single:
        _check_port(single)
        return 0, 0, single, single
    return 0, 0, 0, 0


def _lpm_key(cidr: str) -> bytes:
    net = ipaddress.ip_network(cidr, strict=False)
    raw = ip_to_16(str(net.network_address))
    prefix = net.prefixlen + (96 if net.version == 4 else 0)
    return struct.pack("=I", prefix) + raw


def _tcp_flags_value(name: str) -> int:
    if not name:
        return 0
    key = name.strip().upper()
    if key not in _TCP_FLAG_NAMES:
        raise ValueError(f"unknown tcp flag {name!r}")
    return int(_TCP_FLAG_NAMES[key])


def compile_rule(rule: FlowFilterRule) -> tuple[bytes, bytes, list[bytes]]:
    """-> (lpm key, rule value bytes, peer lpm keys)."""
    proto = 0
    if rule.protocol:
        key = rule.protocol.strip().upper()
        if key not in _PROTOS:
            raise ValueError(f"unknown protocol {rule.protocol!r}")
        proto = _PROTOS[key]
    direction = _DIRECTIONS.get(rule.direction.strip().upper(), None)
    if direction is None:
        raise ValueError(f"unknown direction {rule.direction!r}")
    action = {"ACCEPT": 0, "REJECT": 1}.get(rule.action.strip().upper())
    if action is None:
        raise ValueError(f"unknown action {rule.action!r}")

    dstart, dend, d1, d2 = _parse_ports(
        rule.destination_port, rule.destination_port_range,
        rule.destination_ports)
    sstart, send_, s1, s2 = _parse_ports(
        rule.source_port, rule.source_port_range, rule.source_ports)
    pstart, pend, p1, p2 = _parse_ports(rule.port, rule.port_range, rule.ports)

    peer_keys: list[bytes] = []
    peer_cidr = rule.peer_cidr or (f"{rule.peer_ip}/32" if rule.peer_ip and
                                   ":" not in rule.peer_ip else
                                   f"{rule.peer_ip}/128" if rule.peer_ip else "")
    if peer_cidr:
        peer_keys.append(_lpm_key(peer_cidr))

    rec = np.zeros(1, dtype=binfmt.FILTER_RULE_DTYPE)[0]
    rec["proto"] = proto
    rec["icmp_type"] = rule.icmp_type
    rec["icmp_code"] = rule.icmp_code
    rec["direction"] = direction
    rec["action"] = action
    rec["want_drops"] = 1 if rule.drops else 0
    rec["peer_cidr_check"] = 1 if peer_keys else 0
    rec["dport_start"], rec["dport_end"] = dstart, dend
    rec["dport1"], rec["dport2"] = d1, d2
    rec["sport_start"], rec["sport_end"] = sstart, send_
    rec["sport1"], rec["sport2"] = s1, s2
    rec["port_start"], rec["port_end"] = pstart, pend
    rec["port1"], rec["port2"] = p1, p2
    rec["tcp_flags"] = _tcp_flags_value(rule.tcp_flags)
    rec["sample_override"] = rule.sample
    return _lpm_key(rule.ip_cidr), rec.tobytes(), peer_keys


def compile_filters(rules: list[FlowFilterRule]) -> CompiledFilter:
    if len(rules) > MAX_FILTER_RULES:
        raise ValueError(
            f"{len(rules)} filter rules exceed the datapath LPM capacity of "
            f"{MAX_FILTER_RULES}")
    out_rules: list[tuple[bytes, bytes]] = []
    out_peers: list[tuple[bytes, bytes]] = []
    seen_keys: set[bytes] = set()
    for rule in rules:
        key, value, peers = compile_rule(rule)
        if key in seen_keys:
            raise ValueError(
                f"duplicate filter CIDR {rule.ip_cidr!r}: LPM tries hold one "
                "rule per prefix")
        seen_keys.add(key)
        out_rules.append((key, value))
        for pk in peers:
            out_peers.append((pk, b"\x01"))
    return CompiledFilter(rules=out_rules, peers=out_peers)
