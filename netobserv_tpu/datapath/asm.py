"""A minimal eBPF assembler: named registers, labels, patched jumps.

Exists so the datapath can ship a REAL in-kernel flow program in environments
without clang (the image this framework was built in): programs are assembled
instruction-by-instruction and validated by the live kernel verifier
(tests/test_prog_load.py, test_asm_flowpath.py). The clang-built flowpath.c
remains the full-featured datapath; this is the minimal subset.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

R0, R1, R2, R3, R4, R5, R6, R7, R8, R9, R10 = range(11)

# opcode building blocks
BPF_LDX, BPF_ST, BPF_STX = 0x61, 0x62, 0x63
BPF_W, BPF_H, BPF_B, BPF_DW = 0x00, 0x08, 0x10, 0x18
BPF_ALU64_K, BPF_ALU64_X = 0x07, 0x0F
BPF_MOV_K, BPF_MOV_X = 0xB7, 0xBF
BPF_JMP_CALL, BPF_EXIT = 0x85, 0x95

HELPER_MAP_LOOKUP = 1
HELPER_MAP_UPDATE = 2
HELPER_MAP_DELETE = 3
HELPER_KTIME_GET_NS = 5
HELPER_RINGBUF_OUTPUT = 130


#: struct bpf_insn packs dst_reg:4/src_reg:4 as C BITFIELDS, so the nibble
#: order follows the host's bitfield allocation: dst in the LOW nibble on
#: little-endian, the HIGH nibble on big-endian (s390x)
_REGS_BYTE = ((lambda dst, src: (src << 4) | dst)
              if __import__("sys").byteorder == "little"
              else (lambda dst, src: (dst << 4) | src))


def encode(opcode: int, dst: int = 0, src: int = 0, off: int = 0,
           imm: int = 0) -> bytes:
    """Encode one eBPF instruction (struct bpf_insn) — the single encoding
    definition shared with syscall_bpf."""
    return struct.pack("=BBhi", opcode, _REGS_BYTE(dst, src), off, imm)


def encode_ld_map_fd(dst: int, map_fd: int) -> bytes:
    """BPF_LD_IMM64 with BPF_PSEUDO_MAP_FD (two instruction slots)."""
    return encode(0x18, dst, 1, 0, map_fd) + encode(0x00)

BPF_ANY = 0
BPF_NOEXIST = 1


@dataclass
class Asm:
    _insns: list[tuple] = field(default_factory=list)  # (bytes | jump tuple)
    _labels: dict[str, int] = field(default_factory=dict)

    def _emit(self, raw: bytes) -> None:
        self._insns.append(("raw", raw))

    def label(self, name: str) -> None:
        self._labels[name] = len(self._insns)

    # --- moves / alu ---
    def mov_imm(self, dst: int, imm: int) -> None:
        self._emit(encode(0xB7, dst, 0, 0, imm))

    def mov_reg(self, dst: int, src: int) -> None:
        self._emit(encode(0xBF, dst, src))

    def alu_imm(self, op: int, dst: int, imm: int) -> None:
        """op: 0x07 add, 0x17 sub, 0x47 or, 0x57 and, 0x67 lsh, 0x77 rsh,
        0xa7 xor, 0x27 mul (all ALU64 K forms)."""
        self._emit(encode(op, dst, 0, 0, imm))

    def alu_reg(self, op: int, dst: int, src: int) -> None:
        """op ALU64 X forms: 0x0f add, 0x1f sub, 0x4f or, 0x5f and, 0x2f mul."""
        self._emit(encode(op, dst, src))

    def endian_be(self, dst: int, bits: int) -> None:
        """bswap to big-endian interpretation (BPF_END | BPF_TO_BE)."""
        self._emit(encode(0xDC, dst, 0, 0, bits))

    # --- memory ---
    def ldx(self, size: int, dst: int, src: int, off: int) -> None:
        self._emit(encode(0x61 | size, dst, src, off))

    def st_imm(self, size: int, dst: int, off: int, imm: int) -> None:
        self._emit(encode(0x62 | size, dst, 0, off, imm))

    def stx(self, size: int, dst: int, src: int, off: int) -> None:
        self._emit(encode(0x63 | size, dst, src, off))

    def atomic_add(self, size: int, dst: int, src: int, off: int) -> None:
        self._emit(encode(0xC3 | size, dst, src, off))

    def atomic_or(self, size: int, dst: int, src: int, off: int) -> None:
        """*(dst+off) |= src, atomically (BPF_ATOMIC imm=BPF_OR, kernel
        5.12+) — lock-free accumulation of flag bits across CPUs."""
        self._emit(encode(0xC3 | size, dst, src, off, 0x40))

    def atomic_fetch_add(self, size: int, dst: int, src: int, off: int) -> None:
        """src = fetch_add(*(dst+off), src) (BPF_ATOMIC imm=BPF_ADD|FETCH,
        kernel 5.12+) — reserves unique slots/sequence numbers across CPUs."""
        self._emit(encode(0xC3 | size, dst, src, off, 0x01))

    def ld_map_fd(self, dst: int, map_fd: int) -> None:
        self._emit(encode_ld_map_fd(dst, map_fd)[:8])
        self._emit(encode_ld_map_fd(dst, map_fd)[8:])

    # --- control flow ---
    def jmp(self, target: str) -> None:
        self._insns.append(("jump", 0x05, 0, 0, target))

    def jmp_imm(self, op: int, dst: int, imm: int, target: str) -> None:
        """op: 0x15 jeq, 0x55 jne, 0x25 jgt, 0x35 jge, 0xa5 jlt, 0xb5 jle
        (K forms)."""
        self._insns.append(("jump", op, dst, imm, target))

    def jmp_reg(self, op: int, dst: int, src: int, target: str) -> None:
        """op X forms: 0x1d jeq, 0x5d jne, 0x2d jgt, 0x3d jge, 0xad jlt."""
        self._insns.append(("jumpx", op, dst, src, target))

    def call(self, helper: int) -> None:
        self._emit(struct.pack("=BBhi", 0x85, 0, 0, helper))

    def exit(self) -> None:
        self._emit(struct.pack("=BBhi", 0x95, 0, 0, 0))

    # --- assembly ---
    def assemble(self) -> bytes:
        out = []
        for i, item in enumerate(self._insns):
            if item[0] == "raw":
                out.append(item[1])
            elif item[0] == "jump":
                _tag, op, dst, imm, target = item
                off = self._labels[target] - i - 1
                out.append(struct.pack("=BBhi", op, dst, off, imm))
            else:  # jumpx
                _tag, op, dst, src, target = item
                off = self._labels[target] - i - 1
                out.append(struct.pack("=BBhi", op, _REGS_BYTE(dst, src),
                                       off, 0))
        return b"".join(out)
