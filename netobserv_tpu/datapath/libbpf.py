"""ctypes bindings for the system libbpf: load clang-built CO-RE objects.

Reference analog: `pkg/tracer/tracer.go:92-273` — the reference loads its
bpf2go-embedded object with cilium/ebpf (spec open, map resize, rodata
const rewrite, kernel-version program pruning, load, attach). This module
is the same lifecycle over the distro's libbpf (v1.x API): it exists so the
CI-built `flowpath.bpf.o` (datapath/native, built where clang is available)
can drive the FULL C datapath — the in-tree assembler datapath remains the
no-compiler fallback.

Only the object/map/program handles needed by the loader are bound; all
calls are checked and raise OSError with errno context on failure.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import logging
import os
from typing import Iterator, Optional

log = logging.getLogger("netobserv_tpu.datapath.libbpf")

_lib: Optional[ctypes.CDLL] = None


def available() -> bool:
    return _load_lib() is not None


def _load_lib() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    for name in ("libbpf.so.1", "libbpf.so",
                 ctypes.util.find_library("bpf") or ""):
        if not name:
            continue
        try:
            lib = ctypes.CDLL(name, use_errno=True)
            _bind(lib)
        except (OSError, AttributeError) as exc:
            # libbpf 0.x lacks some of the v1 symbols bound here: treat it
            # as unavailable so the loader falls back to the assembler path
            log.debug("libbpf candidate %s unusable: %s", name, exc)
            continue
        _lib = lib
        ver = lib.libbpf_version_string().decode()
        log.debug("libbpf %s loaded (%s)", ver, name)
        return _lib
    return None


def _bind(lib: ctypes.CDLL) -> None:
    p = ctypes.c_void_p
    lib.libbpf_version_string.restype = ctypes.c_char_p
    lib.bpf_object__open_file.restype = p
    lib.bpf_object__open_file.argtypes = [ctypes.c_char_p, p]
    lib.bpf_object__load.argtypes = [p]
    lib.bpf_object__close.argtypes = [p]
    lib.bpf_object__next_map.restype = p
    lib.bpf_object__next_map.argtypes = [p, p]
    lib.bpf_object__next_program.restype = p
    lib.bpf_object__next_program.argtypes = [p, p]
    lib.bpf_object__find_map_by_name.restype = p
    lib.bpf_object__find_map_by_name.argtypes = [p, ctypes.c_char_p]
    lib.bpf_object__find_program_by_name.restype = p
    lib.bpf_object__find_program_by_name.argtypes = [p, ctypes.c_char_p]
    lib.bpf_map__name.restype = ctypes.c_char_p
    lib.bpf_map__name.argtypes = [p]
    lib.bpf_map__fd.argtypes = [p]
    lib.bpf_map__type.argtypes = [p]
    lib.bpf_map__key_size.argtypes = [p]
    lib.bpf_map__value_size.argtypes = [p]
    lib.bpf_map__max_entries.argtypes = [p]
    lib.bpf_map__set_max_entries.argtypes = [p, ctypes.c_uint]
    lib.bpf_map__set_pin_path.argtypes = [p, ctypes.c_char_p]
    lib.bpf_map__initial_value.restype = p
    lib.bpf_map__initial_value.argtypes = [p, ctypes.POINTER(ctypes.c_size_t)]
    lib.bpf_program__name.restype = ctypes.c_char_p
    lib.bpf_program__name.argtypes = [p]
    lib.bpf_program__section_name.restype = ctypes.c_char_p
    lib.bpf_program__section_name.argtypes = [p]
    lib.bpf_program__type.argtypes = [p]
    lib.bpf_program__set_type.argtypes = [p, ctypes.c_int]
    lib.bpf_program__set_autoload.argtypes = [p, ctypes.c_bool]
    lib.bpf_program__autoload.argtypes = [p]
    lib.bpf_program__autoload.restype = ctypes.c_bool
    lib.bpf_program__fd.argtypes = [p]
    lib.bpf_program__attach.restype = p
    lib.bpf_program__attach.argtypes = [p]
    lib.bpf_link__destroy.argtypes = [p]
    lib.bpf_map__reuse_fd.argtypes = [p, ctypes.c_int]


class _Elf:
    """Just enough ELF64 parsing to read a BPF object's sections and the
    .rodata symbol offsets (the `volatile const` config knobs; in an ET_REL
    object the DATASEC BTF offsets are unfilled — the symbol table is the
    authoritative source, exactly what libbpf itself uses at open time)."""

    def __init__(self, path: str):
        import struct as _struct

        self._s = _struct
        with open(path, "rb") as fh:
            self.data = fh.read()
        if self.data[:4] != b"\x7fELF" or self.data[4] != 2:
            raise ValueError(f"{path}: not an ELF64 object")
        self.e_shoff, = _struct.unpack_from("<Q", self.data, 0x28)
        (self.e_shentsize, self.e_shnum,
         self.e_shstrndx) = _struct.unpack_from("<HHH", self.data, 0x3A)
        _n, _t, self._shstr_off, _sz = self._shdr(self.e_shstrndx)

    def _shdr(self, i: int):
        base = self.e_shoff + i * self.e_shentsize
        sh_name, sh_type = self._s.unpack_from("<II", self.data, base)
        sh_offset, sh_size = self._s.unpack_from("<QQ", self.data,
                                                 base + 0x18)
        return sh_name, sh_type, sh_offset, sh_size

    def _str(self, tab_off: int, off: int) -> str:
        end = self.data.index(b"\x00", tab_off + off)
        return self.data[tab_off + off:end].decode()

    def section_index(self, name: str) -> Optional[int]:
        for i in range(self.e_shnum):
            sh_name, _t, _o, _sz = self._shdr(i)
            if self._str(self._shstr_off, sh_name) == name:
                return i
        return None

    def symbols_in(self, section_name: str) -> dict:
        """{symbol name: (offset, size)} for symbols defined in a section."""
        target = self.section_index(section_name)
        out: dict = {}
        if target is None:
            return out
        for i in range(self.e_shnum):
            _n, sh_type, off, size = self._shdr(i)
            if sh_type != 2:                     # SHT_SYMTAB
                continue
            base = self.e_shoff + i * self.e_shentsize
            sh_link, = self._s.unpack_from("<I", self.data, base + 0x28)
            _sn, _st, strtab_off, _ss = self._shdr(sh_link)
            for so in range(off, off + size, 24):  # Elf64_Sym
                st_name, _info, _other, st_shndx = self._s.unpack_from(
                    "<IBBH", self.data, so)
                st_value, st_size = self._s.unpack_from("<QQ", self.data,
                                                        so + 8)
                if st_shndx == target and st_name:
                    out[self._str(strtab_off, st_name)] = (st_value, st_size)
        return out


def rodata_symbols(path: str) -> dict:
    """{const name: (offset, size)} in the object's .rodata."""
    return _Elf(path).symbols_in(".rodata")


class BpfMapHandle:
    def __init__(self, lib, ptr):
        self._lib, self._ptr = lib, ptr

    @property
    def name(self) -> str:
        return self._lib.bpf_map__name(self._ptr).decode()

    @property
    def fd(self) -> int:
        return self._lib.bpf_map__fd(self._ptr)

    @property
    def type(self) -> int:
        return self._lib.bpf_map__type(self._ptr)

    @property
    def key_size(self) -> int:
        return self._lib.bpf_map__key_size(self._ptr)

    @property
    def value_size(self) -> int:
        return self._lib.bpf_map__value_size(self._ptr)

    @property
    def max_entries(self) -> int:
        return self._lib.bpf_map__max_entries(self._ptr)

    def set_max_entries(self, n: int) -> None:
        rc = self._lib.bpf_map__set_max_entries(self._ptr, n)
        if rc:
            raise OSError(-rc, f"set_max_entries({self.name}, {n})")

    def disable_pinning(self) -> None:
        self._lib.bpf_map__set_pin_path(self._ptr, None)

    def reuse_fd(self, fd: int) -> None:
        """Share another object's already-created map instead of creating
        a new one at load (cross-object map sharing: the probes object
        writes into the flow object's feature maps)."""
        rc = self._lib.bpf_map__reuse_fd(self._ptr, fd)
        if rc:
            raise OSError(-rc, f"reuse_fd({self.name})")

    def initial_value(self) -> Optional[memoryview]:
        """Writable view of a .rodata/.data/.bss map's initial contents;
        None for ordinary maps. Patch before load() to rewrite `volatile
        const` config knobs (the reference's configureFlowSpecVariables)."""
        size = ctypes.c_size_t(0)
        ptr = self._lib.bpf_map__initial_value(self._ptr,
                                               ctypes.byref(size))
        if not ptr or size.value == 0:
            return None
        buf = (ctypes.c_char * size.value).from_address(ptr)
        return memoryview(buf).cast("B")


class BpfProgHandle:
    def __init__(self, lib, ptr):
        self._lib, self._ptr = lib, ptr

    @property
    def name(self) -> str:
        return self._lib.bpf_program__name(self._ptr).decode()

    @property
    def section(self) -> str:
        return self._lib.bpf_program__section_name(self._ptr).decode()

    @property
    def type(self) -> int:
        return self._lib.bpf_program__type(self._ptr)

    @property
    def fd(self) -> int:
        return self._lib.bpf_program__fd(self._ptr)

    @property
    def autoload(self) -> bool:
        return self._lib.bpf_program__autoload(self._ptr)

    def set_autoload(self, on: bool) -> None:
        rc = self._lib.bpf_program__set_autoload(self._ptr, on)
        if rc:
            raise OSError(-rc, f"set_autoload({self.name})")

    def set_type(self, prog_type: int) -> None:
        """Needed for legacy section names libbpf can't infer (bpf2go's
        `classifier/...` sections land as UNSPEC)."""
        rc = self._lib.bpf_program__set_type(self._ptr, prog_type)
        if rc:
            raise OSError(-rc, f"set_type({self.name}, {prog_type})")

    def attach(self) -> "BpfLink":
        """libbpf auto-attach by section type (tracepoint/kprobe/fentry
        ...). Raises OSError on failure."""
        ctypes.set_errno(0)
        ptr = self._lib.bpf_program__attach(self._ptr)
        if not ptr:
            raise OSError(ctypes.get_errno() or 22,
                          f"bpf_program__attach({self.name})")
        return BpfLink(self._lib, ptr)


class BpfLink:
    """An attached program's link; destroy() detaches."""

    def __init__(self, lib, ptr):
        self._lib, self._ptr = lib, ptr

    def destroy(self) -> None:
        if self._ptr:
            self._lib.bpf_link__destroy(self._ptr)
            self._ptr = None


class BpfObject:
    """An opened (then loaded) BPF ELF object."""

    def __init__(self, path: str):
        lib = _load_lib()
        if lib is None:
            raise RuntimeError("libbpf not available on this system")
        self._lib = lib
        ctypes.set_errno(0)
        self._obj = lib.bpf_object__open_file(
            os.fsencode(path), None)
        if not self._obj:
            err = ctypes.get_errno()
            raise OSError(err, f"bpf_object__open_file({path})")
        self.loaded = False

    def maps(self) -> Iterator[BpfMapHandle]:
        cur = None
        while True:
            cur = self._lib.bpf_object__next_map(self._obj, cur)
            if not cur:
                return
            yield BpfMapHandle(self._lib, cur)

    def programs(self) -> Iterator[BpfProgHandle]:
        cur = None
        while True:
            cur = self._lib.bpf_object__next_program(self._obj, cur)
            if not cur:
                return
            yield BpfProgHandle(self._lib, cur)

    def map(self, name: str) -> Optional[BpfMapHandle]:
        ptr = self._lib.bpf_object__find_map_by_name(self._obj,
                                                     name.encode())
        return BpfMapHandle(self._lib, ptr) if ptr else None

    def program(self, name: str) -> Optional[BpfProgHandle]:
        ptr = self._lib.bpf_object__find_program_by_name(self._obj,
                                                         name.encode())
        return BpfProgHandle(self._lib, ptr) if ptr else None

    def patch_rodata(self, values: dict) -> int:
        """Rewrite `volatile const` knobs in the .rodata map image before
        load. `values` maps byte offsets to (size, int) or bytes. Returns
        the number of patches applied; raises if .rodata is absent."""
        import struct as _struct

        rodata = None
        for m in self.maps():
            if m.name.endswith(".rodata"):
                rodata = m
                break
        if rodata is None:
            raise RuntimeError("object has no .rodata map to patch")
        view = rodata.initial_value()
        if view is None:
            raise RuntimeError(".rodata has no initial value")
        n = 0
        for off, val in values.items():
            if isinstance(val, bytes):
                view[off:off + len(val)] = val
            else:
                size, num = val
                fmt = {1: "<B", 2: "<H", 4: "<I", 8: "<Q"}[size]
                view[off:off + size] = _struct.pack(fmt, num)
            n += 1
        return n

    def load(self) -> None:
        rc = self._lib.bpf_object__load(self._obj)
        if rc:
            raise OSError(-rc if rc < 0 else rc,
                          "bpf_object__load (see libbpf stderr for the "
                          "verifier log)")
        self.loaded = True

    def close(self) -> None:
        if self._obj:
            self._lib.bpf_object__close(self._obj)
            self._obj = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
