"""Canonical datapath map-name registry.

Reference analog: `pkg/maps/maps.go` + `make verify-maps` — one authoritative
list, consistency-tested against the C source (tests/test_datapath.py) so the
loader, bpfman deployment args, and the C can never drift apart.
"""

MAPS = [
    "aggregated_flows",
    "direct_flows",
    "flows_dns",
    "flows_drops",
    "flows_nevents",
    "flows_xlat",
    "flows_extra",
    "flows_quic",
    "packet_records",
    "dns_inflight",
    "dns_scratch",
    "global_counters",
    "filter_rules",
    "filter_peers",
    "ipsec_ingress_inflight",
    "ipsec_egress_inflight",
    "ssl_events",
    "sampling_gate",
]
