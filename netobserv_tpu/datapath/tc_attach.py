"""TC/TCX attachment of BPF programs.

Reference analog: the attach half of `pkg/tracer/tracer.go:431-598` — TCX
bpf_link attachment (with EEXIST adoption of an existing link) and a legacy
TC clsact/filter path, selected by TC_ATTACH_MODE (tcx | tc | any, reference
`pkg/agent/interfaces_listener.go:104-113`):

- **tcx**: BPF_LINK_CREATE on the interface's TCX hook (kernel >= 6.6) via
  raw bpf(2) — link-fd lifetime IS the attachment; no qdisc involved; other
  TCX programs on the hook keep running (mprog chain).
- **tc**: clsact qdisc + filter through the iproute2 `tc` binary (the path
  operators can replay by hand), with stale-filter cleanup between runs.
- **any**: try tcx, fall back to tc on kernels without TCX.
"""

from __future__ import annotations

import errno
import logging
import os
import subprocess
from dataclasses import dataclass

log = logging.getLogger("netobserv_tpu.datapath.tc")


class TcError(RuntimeError):
    pass


@dataclass
class Attachment:
    """One live attachment; `kind` is "tcx" (link_fd valid) or "tc"."""

    kind: str
    if_name: str
    if_index: int
    direction: str
    link_fd: int = -1
    priority: int = 0

    def detach(self) -> None:
        if self.kind == "tcx":
            try:
                os.close(self.link_fd)  # closing the bpf_link detaches
            except OSError:
                pass
        else:
            detach(self.if_name, self.direction, self.priority)


def attach_tcx(prog_fd: int, if_name: str, if_index: int,
               direction: str) -> Attachment:
    """TCX bpf_link attach with EEXIST adoption (reference
    tracer.go:454-488)."""
    from netobserv_tpu.datapath import syscall_bpf

    try:
        fd = syscall_bpf.link_create_tcx(prog_fd, if_index, direction)
        log.info("TCX link attached to %s %s (link fd %d)", if_name,
                 direction, fd)
        return Attachment("tcx", if_name, if_index, direction, link_fd=fd)
    except OSError as exc:
        if exc.errno != errno.EEXIST:
            raise
        # this exact program is already in the hook's mprog chain (previous
        # instance / listener retry): adopt the existing link
        pid = syscall_bpf.prog_id_of(prog_fd)
        fd = syscall_bpf.find_tcx_link(if_index, direction, prog_id=pid)
        if fd is None:
            raise TcError(
                f"TCX attach to {if_name} {direction} returned EEXIST but "
                "no matching link found to adopt") from exc
        log.info("adopted existing TCX link on %s %s (link fd %d)", if_name,
                 direction, fd)
        return Attachment("tcx", if_name, if_index, direction, link_fd=fd)


def attach_mode(prog_fd: int, pin_path: str, if_name: str, if_index: int,
                direction: str, mode: str = "tcx", priority: int = 1,
                pre_legacy=None) -> Attachment:
    """Attach per TC_ATTACH_MODE: tcx | tc | any (try tcx, fall back).

    `pre_legacy` (optional callable) runs immediately before a legacy tc
    attach — the hook for once-per-interface stale clsact cleanup. It is NOT
    invoked when the TCX path succeeds, so third-party clsact state survives
    on TCX-capable kernels."""
    if mode not in ("tcx", "tc", "any"):
        raise ValueError(f"unknown TC_ATTACH_MODE {mode!r}")
    if mode in ("tcx", "any"):
        try:
            return attach_tcx(prog_fd, if_name, if_index, direction)
        except OSError as exc:
            if mode == "tcx":
                raise
            log.info("TCX unavailable on %s (%s); falling back to legacy tc",
                     if_name, exc)
    if pre_legacy is not None:
        pre_legacy()
    attach_pinned(if_name, direction, pin_path, priority=priority)
    return Attachment("tc", if_name, if_index, direction, priority=priority)


def _tc(*args: str) -> str:
    res = subprocess.run(["tc", *args], capture_output=True, text=True,
                         timeout=10)
    if res.returncode != 0:
        raise TcError(f"tc {' '.join(args)}: {res.stderr.strip()}")
    return res.stdout


def ensure_clsact(ifname: str) -> None:
    """Create the clsact qdisc if absent (idempotent)."""
    try:
        _tc("qdisc", "add", "dev", ifname, "clsact")
    except TcError as exc:
        if "Exclusivity flag on" not in str(exc) and "File exists" not in str(exc):
            raise


def attach_pinned(ifname: str, direction: str, pin_path: str,
                  priority: int = 1) -> None:
    """Attach a pinned classifier at <direction> (ingress|egress)."""
    ensure_clsact(ifname)
    _tc("filter", "add", "dev", ifname, direction, "prio", str(priority),
        "bpf", "object-pinned", pin_path, "direct-action")
    log.info("attached %s to %s %s", pin_path, ifname, direction)


def detach(ifname: str, direction: str, priority: int = 1) -> None:
    _tc("filter", "del", "dev", ifname, direction, "prio", str(priority))


def remove_clsact(ifname: str) -> None:
    """Remove the clsact qdisc (drops all our filters with it) — the stale
    cleanup used between agent restarts."""
    try:
        _tc("qdisc", "del", "dev", ifname, "clsact")
    except TcError:
        pass


def list_filters(ifname: str, direction: str) -> str:
    return _tc("filter", "show", "dev", ifname, direction)
