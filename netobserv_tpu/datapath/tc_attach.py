"""TC/TCX attachment of pinned BPF programs.

Reference analog: the attach half of `pkg/tracer/tracer.go` (TCX links with
legacy TC qdisc/filter fallback, stale cleanup). Programs arrive pinned on
bpffs (loaded by this process via syscall_bpf.prog_load, by the cmake-built
object through libbpf, or by an external manager); attachment drives the
iproute2 `tc` binary — the netlink encoding is deferred until the full
self-managed loader lands (the CLI path covers both clsact setup and filter
lifecycle and is what operators can replay by hand).
"""

from __future__ import annotations

import logging
import subprocess

log = logging.getLogger("netobserv_tpu.datapath.tc")


class TcError(RuntimeError):
    pass


def _tc(*args: str) -> str:
    res = subprocess.run(["tc", *args], capture_output=True, text=True,
                         timeout=10)
    if res.returncode != 0:
        raise TcError(f"tc {' '.join(args)}: {res.stderr.strip()}")
    return res.stdout


def ensure_clsact(ifname: str) -> None:
    """Create the clsact qdisc if absent (idempotent)."""
    try:
        _tc("qdisc", "add", "dev", ifname, "clsact")
    except TcError as exc:
        if "Exclusivity flag on" not in str(exc) and "File exists" not in str(exc):
            raise


def attach_pinned(ifname: str, direction: str, pin_path: str,
                  priority: int = 1) -> None:
    """Attach a pinned classifier at <direction> (ingress|egress)."""
    ensure_clsact(ifname)
    _tc("filter", "add", "dev", ifname, direction, "prio", str(priority),
        "bpf", "object-pinned", pin_path, "direct-action")
    log.info("attached %s to %s %s", pin_path, ifname, direction)


def detach(ifname: str, direction: str, priority: int = 1) -> None:
    _tc("filter", "del", "dev", ifname, direction, "prio", str(priority))


def remove_clsact(ifname: str) -> None:
    """Remove the clsact qdisc (drops all our filters with it) — the stale
    cleanup used between agent restarts."""
    try:
        _tc("qdisc", "del", "dev", ifname, "clsact")
    except TcError:
        pass


def list_filters(ifname: str, direction: str) -> str:
    return _tc("filter", "show", "dev", ifname, direction)
