"""ctypes binding for the native flowpack library, with numpy fallback.

The native path packs raw flow-event buffers into columnar arrays and merges
per-CPU partials without Python-level per-record loops. When the shared
library isn't built, a vectorized numpy implementation provides identical
results (tests assert equivalence).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from typing import Optional

import numpy as np

from netobserv_tpu.model import accumulate, binfmt
from netobserv_tpu.model.columnar import (
    KEY_WORDS, FlowBatch, overlay_features, pack_key_words,
)

log = logging.getLogger("netobserv_tpu.datapath.flowpack")

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_LIB_PATHS = [
    os.path.join(_NATIVE_DIR, "build", "libflowpack.so"),
    os.path.join(_NATIVE_DIR, "libflowpack.so"),
]


class _Columns(ctypes.Structure):
    _fields_ = [
        ("keys", ctypes.c_void_p), ("bytes", ctypes.c_void_p),
        ("packets", ctypes.c_void_p), ("tcp_flags", ctypes.c_void_p),
        ("eth_protocol", ctypes.c_void_p), ("direction", ctypes.c_void_p),
        ("if_index", ctypes.c_void_p), ("dscp", ctypes.c_void_p),
        ("sampling", ctypes.c_void_p), ("first_seen_ns", ctypes.c_void_p),
        ("last_seen_ns", ctypes.c_void_p),
    ]


_lib: Optional[ctypes.CDLL] = None


_ABI_VERSION = 10

#: count of library loads rejected for ABI/symbol mismatch (stale `make
#: native` build) — the agent degrades to the numpy/python twin chain
#: instead of dying at import; MapTracer syncs this into the registry's
#: flowpack_abi_fallback_total once per process.
abi_fallbacks = 0

#: dense TPU-feed row width (words); layout documented in flowpack.cc
DENSE_WORDS = 20
#: compact (v4) TPU-feed row width; layout documented in flowpack.cc
COMPACT_WORDS = 10
#: resident feed constants; layout documented in flowpack.cc fp_pack_resident
RESIDENT_HDR = 4
HOT_WORDS = 3
NK_WORDS = 11
#: hot-row rtt code ceiling (µs); larger samples spill full-width
RTT_MAX_US = 0xFF << 14
#: bytes 8..11 of a v4-in-v6 mapped address as a LE u32
_V4_PREFIX_WORD2 = 0xFFFF0000


def compact_buf_len(batch_size: int, spill_cap: int) -> int:
    """Flat word count of a compact feed buffer: compact lane + spill lane."""
    return batch_size * COMPACT_WORDS + spill_cap * DENSE_WORDS


class ResidentCaps:
    """Static side-lane capacities of the resident feed (fixed shapes keep
    the jitted unpack retrace-free; overflows fall back to the dense feed)."""

    __slots__ = ("dns", "drop", "nk", "spill")

    def __init__(self, dns: int, drop: int, nk: int, spill: int):
        self.dns, self.drop, self.nk, self.spill = dns, drop, nk, spill

    def __iter__(self):
        return iter((self.dns, self.drop, self.nk, self.spill))

    def __eq__(self, other):
        return tuple(self) == tuple(other)

    def __repr__(self):
        return (f"ResidentCaps(dns={self.dns}, drop={self.drop}, "
                f"nk={self.nk}, spill={self.spill})")


def default_resident_caps(batch_size: int) -> ResidentCaps:
    """Production sizing (byte budget in docs/tpu_sketch.md): DNS-latency
    and drop rows are minorities of live traffic; new keys per batch are a
    trickle once the flow table is warm; the spill lane only carries rows
    the hot row cannot represent exactly."""
    return ResidentCaps(dns=max(batch_size // 16, 64),
                        drop=max(batch_size // 16, 64),
                        nk=max(batch_size // 32, 64),
                        spill=max(batch_size // 64, 32))


def resident_buf_len(batch_size: int, caps: ResidentCaps) -> int:
    """Flat word count of a resident feed buffer (header + all lanes)."""
    return (RESIDENT_HDR + batch_size * HOT_WORDS + caps.dns + caps.drop * 2
            + caps.nk * NK_WORDS + caps.spill * DENSE_WORDS)


def zero_resident_region(out: np.ndarray, batch_size: int,
                         caps: ResidentCaps) -> None:
    """Mask a resident region as EMPTY by zeroing only the words the device
    unpack (`sketch.state.resident_to_arrays`) reads as validity gates:
    hot-row word 0 (valid bit + slot + rtt code), the sparse dns/drop lanes
    (their entries scatter by embedded row index), new-key word 0 (defined
    bit) and spill word 14 (valid). Every other word of an invalid row is
    masked on device, so stale content there is unreadable — this writes
    ~1/3 of a full `region[:] = 0` memset, which is what the exhausted-shard
    continuation path used to pay per chunk."""
    hot_off = RESIDENT_HDR
    dns_off = hot_off + batch_size * HOT_WORDS
    nk_off = dns_off + caps.dns + caps.drop * 2
    spill_off = nk_off + caps.nk * NK_WORDS
    out[:RESIDENT_HDR] = 0
    out[hot_off:dns_off:HOT_WORDS] = 0   # hot valid|rtt|slot words
    out[dns_off:nk_off] = 0              # dns + drop lanes (row-idx entries)
    out[nk_off:spill_off:NK_WORDS] = 0   # new-key defined bits
    out[spill_off + 14::DENSE_WORDS] = 0  # spill valid words


class KeyDict:
    """Host key->slot dictionary backing the resident feed — native
    (flowpack.cc fp_dict) when the library is built, pure-python twin
    otherwise (tests pin their equivalence). Slots are assigned sequentially
    in first-seen order; reset() empties the dictionary (the device key
    table needs no matching reset: every live slot is redefined through the
    new-key lane before a hot row references it)."""

    def __init__(self, slot_cap: int = 1 << 18,
                 use_native: Optional[bool] = None):
        if slot_cap <= 0 or slot_cap > (1 << 20):
            raise ValueError("slot_cap must be in 1..2^20 (20-bit slot ids)")
        self.slot_cap = slot_cap
        if use_native is None:
            use_native = native_available()
        self.native = bool(use_native and native_available())
        if self.native:
            _lib.fp_dict_new.restype = ctypes.c_void_p
            self._handle = _lib.fp_dict_new(ctypes.c_uint32(slot_cap))
            if not self._handle:
                raise MemoryError("fp_dict_new failed")
            self._py = None
        else:
            self._handle = None
            self._py: Optional[dict] = {}

    def _live_handle(self) -> int:
        if not self._handle:
            raise ValueError("KeyDict is closed")
        return self._handle

    def count(self) -> int:
        if self.native:
            _lib.fp_dict_count.restype = ctypes.c_uint32
            return int(_lib.fp_dict_count(ctypes.c_void_p(
                self._live_handle())))
        return len(self._py)

    def reset(self) -> None:
        if self.native:
            _lib.fp_dict_reset(ctypes.c_void_p(self._live_handle()))
        else:
            self._py.clear()

    def close(self) -> None:
        if self.native and self._handle:
            _lib.fp_dict_free(ctypes.c_void_p(self._handle))
            self._handle = None

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:
            pass


def _find_lib() -> Optional[ctypes.CDLL]:
    global abi_fallbacks
    for path in _LIB_PATHS:
        if os.path.exists(path):
            # a stale .so (wrong ABI, or so old it predates fp_abi_version)
            # must degrade to the python twin chain, never raise at import
            try:
                lib = ctypes.CDLL(path)
                ver = int(lib.fp_abi_version())
            except (OSError, AttributeError) as exc:
                abi_fallbacks += 1
                log.warning("flowpack library unusable at %s (%s) — falling "
                            "back to the python chain; rebuild with "
                            "`make native`", path, exc)
                continue
            if ver == _ABI_VERSION:
                lib.fp_crc32c.restype = ctypes.c_uint32
                return lib
            abi_fallbacks += 1
            log.warning("flowpack ABI mismatch at %s (built %d, need %d) — "
                        "falling back to the python chain; rebuild with "
                        "`make native`", path, ver, _ABI_VERSION)
    return None


def crc32c(data: bytes) -> Optional[int]:
    """Native crc32c, or None when the library isn't built."""
    if not native_available():
        return None
    return int(_lib.fp_crc32c(data, ctypes.c_size_t(len(data))))


def build_native(force: bool = False, out: Optional[str] = None,
                 abi: Optional[int] = None) -> bool:
    """Compile libflowpack.so with g++ (no cmake configure round trip).
    The ABI version is stamped into the .so at compile time
    (-DFP_ABI_VERSION) so the loader's mismatch fallback is a build
    property, not a source edit; `abi`/`out` let tests build a deliberately
    stale library somewhere harmless."""
    want_abi = _ABI_VERSION if abi is None else abi
    out = _LIB_PATHS[0] if out is None else out
    os.makedirs(os.path.dirname(out), exist_ok=True)
    if os.path.exists(out) and not force:
        # a stale build from another ABI must be rebuilt, not kept
        try:
            if ctypes.CDLL(out).fp_abi_version() == want_abi:
                return True
        except (OSError, AttributeError):
            pass
    src = os.path.join(_NATIVE_DIR, "flowpack.cc")
    try:
        subprocess.run(
            ["g++", "-O3", "-fno-exceptions", "-Wall", "-Werror", "-pthread",
             f"-DFP_ABI_VERSION={want_abi}", "-shared", "-fPIC",
             src, "-o", out],
            check=True, capture_output=True, text=True)
        return True
    except (OSError, subprocess.CalledProcessError) as exc:
        log.warning("flowpack native build failed: %s", exc)
        return False


def native_available() -> bool:
    global _lib
    if _lib is None:
        _lib = _find_lib()
    return _lib is not None


def _ptr(a: np.ndarray) -> ctypes.c_void_p:
    return ctypes.c_void_p(a.ctypes.data)


def pack_events(events_raw: bytes | np.ndarray,
                batch_size: Optional[int] = None,
                extra: Optional[np.ndarray] = None,
                dns: Optional[np.ndarray] = None,
                drops: Optional[np.ndarray] = None,
                use_native: Optional[bool] = None) -> FlowBatch:
    """Raw flow-event buffer (+ optional feature arrays) -> columnar FlowBatch."""
    if isinstance(events_raw, np.ndarray):
        events = np.ascontiguousarray(events_raw, dtype=binfmt.FLOW_EVENT_DTYPE)
    else:
        events = binfmt.decode_flow_events(events_raw)
    if use_native is None:
        use_native = native_available()
    if not (use_native and native_available()):
        # the pure-python path IS FlowBatch.from_events — one definition
        return FlowBatch.from_events(events, batch_size=batch_size,
                                     extra=extra, dns=dns, drops=drops)
    n = len(events)
    batch_size = batch_size or max(n, 1)
    if n > batch_size:
        raise ValueError(f"{n} events exceed batch size {batch_size}")
    b = FlowBatch.empty(batch_size)
    if n == 0:
        return b
    cols = _Columns(
        keys=_ptr(b.keys), bytes=_ptr(b.bytes), packets=_ptr(b.packets),
        tcp_flags=_ptr(b.tcp_flags), eth_protocol=_ptr(b.eth_protocol),
        direction=_ptr(b.direction), if_index=_ptr(b.if_index),
        dscp=_ptr(b.dscp), sampling=_ptr(b.sampling),
        first_seen_ns=_ptr(b.first_seen_ns),
        last_seen_ns=_ptr(b.last_seen_ns))
    raw = events.tobytes()
    _lib.fp_pack(raw, ctypes.c_size_t(n), ctypes.byref(cols))
    overlay_features(b, n, extra=extra, dns=dns, drops=drops)
    b.valid[:n] = True
    return b


def _fit_rows(arr, n, dtype):
    """Contiguous, exactly n rows (zero-padded) — the native pack loops index
    row i for every i < n, so a short array must never reach them."""
    if arr is None or not len(arr):
        return None
    a = np.ascontiguousarray(arr[:n], dtype=dtype)
    if len(a) < n:
        a = np.concatenate([a, np.zeros(n - len(a), dtype)])
    return np.ascontiguousarray(a)


def _feature_words(stats, ex, xl, qc, dr) -> np.ndarray:
    """(n, 4) u32 feature words 16..19 of the dense row — the numpy twin of
    flowpack.cc fill_feature_words (w16 = tcp_flags|dscp<<16|markers<<24,
    w17 = drop bytes|packets<<16, w18 = drop cause|state<<16, w19 = 0)."""
    n = len(stats)
    w = np.zeros((n, 4), np.uint32)
    markers = np.zeros(n, np.uint32)
    if qc is not None:
        markers |= ((qc["version"] != 0) | (qc["seen_long_hdr"] != 0)
                    | (qc["seen_short_hdr"] != 0)).astype(np.uint32)
    if xl is not None:
        # complete translation = both endpoints observed (fp_merge_xlat rule)
        both = xl["src_ip"].any(axis=1) & xl["dst_ip"].any(axis=1)
        markers |= both.astype(np.uint32) << 1
    if ex is not None:
        markers |= (ex["ipsec_encrypted"] != 0).astype(np.uint32) << 2
        markers |= (ex["ipsec_ret"] != 0).astype(np.uint32) << 3
    w[:, 0] = (stats["tcp_flags"].astype(np.uint32)
               | (stats["dscp"].astype(np.uint32) << 16)
               | (markers << 24))
    if dr is not None:
        w[:, 1] = (dr["bytes"].astype(np.uint32)
                   | (dr["packets"].astype(np.uint32) << 16))
        # saturate, don't mask: subsystem drop reasons (kernel >= 6.0) carry
        # the subsystem in bits 16+ — masking would alias them onto core
        # reasons; saturation lands them in the histogram overflow bucket
        w[:, 2] = (np.minimum(dr["latest_cause"], np.uint32(0xFFFF))
                   | (dr["latest_state"].astype(np.uint32) << 16))
    return w


def pack_dense(events_raw: bytes | np.ndarray,
               batch_size: Optional[int] = None,
               extra: Optional[np.ndarray] = None,
               dns: Optional[np.ndarray] = None,
               drops: Optional[np.ndarray] = None,
               xlat: Optional[np.ndarray] = None,
               quic: Optional[np.ndarray] = None,
               out: Optional[np.ndarray] = None,
               use_native: Optional[bool] = None) -> np.ndarray:
    """Raw flow-event buffer -> one (batch_size, DENSE_WORDS) u32 array, the
    single-transfer TPU feed (row layout documented in flowpack.cc; unpacked
    on-device by sketch.state.dense_to_arrays). Pass a preallocated `out` to
    skip the per-batch allocation — the tail rows are zeroed either way, so a
    reused buffer never leaks stale rows into the padding."""
    if isinstance(events_raw, np.ndarray):
        events = np.ascontiguousarray(events_raw, dtype=binfmt.FLOW_EVENT_DTYPE)
    else:
        events = binfmt.decode_flow_events(events_raw)
    n = len(events)
    batch_size = batch_size or max(n, 1)
    if n > batch_size:
        raise ValueError(f"{n} events exceed batch size {batch_size}")
    if out is None:
        out = np.empty((batch_size, DENSE_WORDS), dtype=np.uint32)
    elif (out.shape != (batch_size, DENSE_WORDS)
          or out.dtype != np.uint32 or not out.flags.c_contiguous):
        raise ValueError(
            f"out must be C-contiguous (batch_size, {DENSE_WORDS}) uint32")
    ex = _fit_rows(extra, n, binfmt.EXTRA_REC_DTYPE)
    dn = _fit_rows(dns, n, binfmt.DNS_REC_DTYPE)
    dr = _fit_rows(drops, n, binfmt.DROPS_REC_DTYPE)
    xl = _fit_rows(xlat, n, binfmt.XLAT_REC_DTYPE)
    qc = _fit_rows(quic, n, binfmt.QUIC_REC_DTYPE)
    if use_native is None:
        use_native = native_available()
    if use_native and native_available():
        _lib.fp_pack_dense(
            ctypes.c_void_p(events.ctypes.data), ctypes.c_size_t(n),
            ctypes.c_void_p(ex.ctypes.data if ex is not None else None),
            ctypes.c_void_p(dn.ctypes.data if dn is not None else None),
            ctypes.c_void_p(dr.ctypes.data if dr is not None else None),
            ctypes.c_void_p(xl.ctypes.data if xl is not None else None),
            ctypes.c_void_p(qc.ctypes.data if qc is not None else None),
            ctypes.c_void_p(out.ctypes.data), ctypes.c_size_t(batch_size))
        return out
    out[n:] = 0
    if n:
        stats = events["stats"]
        out[:n, :10] = pack_key_words(events["key"])
        out[:n, 10] = stats["bytes"].astype(np.float32).view(np.uint32)
        out[:n, 11] = stats["packets"]
        out[:n, 12] = ex["rtt_ns"] // 1000 if ex is not None else 0
        out[:n, 13] = dn["latency_ns"] // 1000 if dn is not None else 0
        out[:n, 14] = 1
        out[:n, 15] = stats["sampling"]
        out[:n, 16:] = _feature_words(stats, ex, xl, qc, dr)
    return out


_PACK_POOL = None
_PACK_POOL_SIZE = 0
_PACK_POOL_LOCK = __import__("threading").Lock()


def _pack_submit(threads: int, fns):
    """Submit shard jobs under the pool lock: creation, growth (with
    retirement of the old pool's workers) and submission are one atomic
    step, so a concurrent grower can never shut a pool down between another
    caller obtaining it and submitting to it. shutdown(wait=False) lets
    already-submitted futures run to completion."""
    global _PACK_POOL, _PACK_POOL_SIZE
    with _PACK_POOL_LOCK:
        if _PACK_POOL is None or _PACK_POOL_SIZE < threads:
            from concurrent.futures import ThreadPoolExecutor
            if _PACK_POOL is not None:
                _PACK_POOL.shutdown(wait=False)
            _PACK_POOL = ThreadPoolExecutor(max_workers=threads,
                                            thread_name_prefix="flowpack")
            _PACK_POOL_SIZE = threads
        return [_PACK_POOL.submit(fn) for fn in fns]


def pack_dense_sharded(events_raw: bytes | np.ndarray,
                       batch_size: int,
                       threads: int,
                       extra: Optional[np.ndarray] = None,
                       dns: Optional[np.ndarray] = None,
                       drops: Optional[np.ndarray] = None,
                       xlat: Optional[np.ndarray] = None,
                       quic: Optional[np.ndarray] = None,
                       out: Optional[np.ndarray] = None) -> np.ndarray:
    """pack_dense with the rows sharded across `threads` packer threads —
    each thread runs the native single-pass pack on a disjoint row range of
    the SAME output buffer (ctypes releases the GIL, so the passes execute
    in true parallel). Identical output to pack_dense (equivalence-tested);
    the eviction-buffer sharding the host path needs once the transfer link
    stops being the bottleneck (PCIe-attached chips — docs/tpu_sketch.md)."""
    if isinstance(events_raw, np.ndarray):
        events = np.ascontiguousarray(events_raw, dtype=binfmt.FLOW_EVENT_DTYPE)
    else:
        events = binfmt.decode_flow_events(events_raw)
    n = len(events)
    if n > batch_size:
        raise ValueError(f"{n} events exceed batch size {batch_size}")
    if threads <= 1 or n < 2 * threads or not native_available():
        return pack_dense(events, batch_size=batch_size, extra=extra,
                          dns=dns, drops=drops, xlat=xlat, quic=quic, out=out)
    if out is None:
        out = np.empty((batch_size, DENSE_WORDS), dtype=np.uint32)
    feats = {"extra": extra, "dns": dns, "drops": drops, "xlat": xlat,
             "quic": quic}
    bounds = [n * i // threads for i in range(threads + 1)]

    def shard(i):
        lo, hi = bounds[i], bounds[i + 1]
        # the LAST shard also zero-pads the buffer tail (rows n..batch_size)
        bs = (batch_size - lo) if i == threads - 1 else (hi - lo)
        pack_dense(events[lo:hi], batch_size=bs, out=out[lo:lo + bs],
                   **{k: (v[lo:hi] if v is not None and len(v) else None)
                      for k, v in feats.items()})

    for f in _pack_submit(threads, [lambda i=i: shard(i)
                                    for i in range(threads)]):
        f.result()
    return out


def pack_compact(events_raw: bytes | np.ndarray,
                 batch_size: int,
                 spill_cap: int,
                 extra: Optional[np.ndarray] = None,
                 dns: Optional[np.ndarray] = None,
                 drops: Optional[np.ndarray] = None,
                 xlat: Optional[np.ndarray] = None,
                 quic: Optional[np.ndarray] = None,
                 out: Optional[np.ndarray] = None,
                 use_native: Optional[bool] = None) -> Optional[np.ndarray]:
    """Raw flow-event buffer -> ONE flat u32 buffer
    `[batch_size*10 compact v4 rows | spill_cap*20 dense rows]` — the
    low-bytes-per-record TPU feed for v4-dominant traffic (the transfer
    link, not compute, bounds the host path; a v4 key needs 4 words, not
    10). Non-v4 flows — and rows carrying drop data, rare outside drop
    storms — go to the full-width spill lane; returns None when they exceed
    `spill_cap` (caller falls back to pack_dense for that batch). Layout is
    pinned in flowpack.cc fp_pack_compact; device unpack is
    sketch.state.compact_to_arrays."""
    if isinstance(events_raw, np.ndarray):
        events = np.ascontiguousarray(events_raw, dtype=binfmt.FLOW_EVENT_DTYPE)
    else:
        events = binfmt.decode_flow_events(events_raw)
    n = len(events)
    if n > batch_size:
        raise ValueError(f"{n} events exceed batch size {batch_size}")
    total = compact_buf_len(batch_size, spill_cap)
    if out is None:
        out = np.empty(total, dtype=np.uint32)
    elif (out.shape != (total,) or out.dtype != np.uint32
          or not out.flags.c_contiguous):
        raise ValueError(f"out must be C-contiguous ({total},) uint32")

    ex = _fit_rows(extra, n, binfmt.EXTRA_REC_DTYPE)
    dn = _fit_rows(dns, n, binfmt.DNS_REC_DTYPE)
    dr = _fit_rows(drops, n, binfmt.DROPS_REC_DTYPE)
    xl = _fit_rows(xlat, n, binfmt.XLAT_REC_DTYPE)
    qc = _fit_rows(quic, n, binfmt.QUIC_REC_DTYPE)
    if use_native is None:
        use_native = native_available()
    if use_native and native_available():
        _lib.fp_pack_compact.restype = ctypes.c_int
        ns = _lib.fp_pack_compact(
            ctypes.c_void_p(events.ctypes.data), ctypes.c_size_t(n),
            ctypes.c_void_p(ex.ctypes.data if ex is not None else None),
            ctypes.c_void_p(dn.ctypes.data if dn is not None else None),
            ctypes.c_void_p(dr.ctypes.data if dr is not None else None),
            ctypes.c_void_p(xl.ctypes.data if xl is not None else None),
            ctypes.c_void_p(qc.ctypes.data if qc is not None else None),
            ctypes.c_void_p(out.ctypes.data), ctypes.c_size_t(batch_size),
            ctypes.c_size_t(spill_cap))
        return None if ns < 0 else out
    # numpy twin (layout oracle for the native path)
    comp = out[:batch_size * COMPACT_WORDS].reshape(batch_size, COMPACT_WORDS)
    spill = out[batch_size * COMPACT_WORDS:].reshape(spill_cap, DENSE_WORDS)
    comp[:] = 0
    spill[:] = 0
    if not n:
        return out
    kw = pack_key_words(events["key"])
    stats = events["stats"]
    fw = _feature_words(stats, ex, xl, qc, dr)
    has_drops = (fw[:, 1] != 0) if dr is not None else np.zeros(n, np.bool_)
    is4 = ((kw[:, 0] == 0) & (kw[:, 1] == 0)
           & (kw[:, 2] == _V4_PREFIX_WORD2)
           & (kw[:, 4] == 0) & (kw[:, 5] == 0)
           & (kw[:, 6] == _V4_PREFIX_WORD2)
           & ~has_drops)
    n_sp = int((~is4).sum())
    if n_sp > spill_cap:
        return None
    rtt = (ex["rtt_ns"] // 1000).astype(np.uint32) if ex is not None \
        else np.zeros(n, np.uint32)
    dlat = (dn["latency_ns"] // 1000).astype(np.uint32) if dn is not None \
        else np.zeros(n, np.uint32)
    c = comp[:int(is4.sum())]
    c[:, 0] = kw[is4, 3]
    c[:, 1] = kw[is4, 7]
    c[:, 2] = kw[is4, 8]
    c[:, 3] = kw[is4, 9] | np.uint32(0x80000000)
    c[:, 4] = stats["bytes"][is4].astype(np.float32).view(np.uint32)
    c[:, 5] = stats["packets"][is4]
    c[:, 6] = rtt[is4]
    c[:, 7] = dlat[is4]
    c[:, 8] = stats["sampling"][is4]
    c[:, 9] = fw[is4, 0]
    if n_sp:
        s = spill[:n_sp]
        s[:, :10] = kw[~is4]
        s[:, 10] = stats["bytes"][~is4].astype(np.float32).view(np.uint32)
        s[:, 11] = stats["packets"][~is4]
        s[:, 12] = rtt[~is4]
        s[:, 13] = dlat[~is4]
        s[:, 14] = 1
        s[:, 15] = stats["sampling"][~is4]
        s[:, 16:] = fw[~is4]
    return out


def _rtt_code11(rtt_us: int) -> int:
    e = 0
    while (rtt_us >> (2 * e)) > 0xFF:
        e += 1
    return ((rtt_us >> (2 * e)) & 0xFF) | (e << 8)


def _lat_code16(us: int) -> int:
    e = 0
    while (us >> e) > 0xFFF and e < 15:
        e += 1
    return min(us >> e, 0xFFF) | (e << 12)


def pack_resident(events_raw: bytes | np.ndarray,
                  batch_size: int,
                  kdict: KeyDict,
                  caps: ResidentCaps,
                  start: int = 0,
                  extra: Optional[np.ndarray] = None,
                  dns: Optional[np.ndarray] = None,
                  drops: Optional[np.ndarray] = None,
                  xlat: Optional[np.ndarray] = None,
                  quic: Optional[np.ndarray] = None,
                  out: Optional[np.ndarray] = None
                  ) -> tuple[np.ndarray, int]:
    """Raw flow-event buffer -> the resident feed (layout pinned in
    flowpack.cc fp_pack_resident; device unpack is
    sketch.state.resident_to_arrays). Packs events[start:] until the hot or
    spill lane fills; returns (buffer, rows_consumed) — partial packing
    with continuation (the caller ships the prefix and calls again with the
    next start), so the dictionary and the device key table learn
    monotonically even under cold-start key floods. Whether the native or
    the python path runs follows the dictionary's own nativeness — the two
    sides share per-row state and cannot be mixed."""
    if isinstance(events_raw, np.ndarray):
        events = np.ascontiguousarray(events_raw, dtype=binfmt.FLOW_EVENT_DTYPE)
    else:
        events = binfmt.decode_flow_events(events_raw)
    n = len(events)
    if batch_size > 0xFFFF:
        raise ValueError("resident feed row indices are 16-bit")
    if min(caps.spill, caps.nk) < 1:
        raise ValueError("resident caps must be >= 1 (progress guarantee)")
    if not 0 <= start <= n:
        raise ValueError(f"start {start} out of range 0..{n}")
    total = resident_buf_len(batch_size, caps)
    if out is None:
        out = np.empty(total, dtype=np.uint32)
    elif (out.shape != (total,) or out.dtype != np.uint32
          or not out.flags.c_contiguous):
        raise ValueError(f"out must be C-contiguous ({total},) uint32")
    ex = _fit_rows(extra, n, binfmt.EXTRA_REC_DTYPE)
    dn = _fit_rows(dns, n, binfmt.DNS_REC_DTYPE)
    dr = _fit_rows(drops, n, binfmt.DROPS_REC_DTYPE)
    xl = _fit_rows(xlat, n, binfmt.XLAT_REC_DTYPE)
    qc = _fit_rows(quic, n, binfmt.QUIC_REC_DTYPE)
    if kdict.native:
        _lib.fp_pack_resident.restype = ctypes.c_int64
        consumed = _lib.fp_pack_resident(
            ctypes.c_void_p(events.ctypes.data), ctypes.c_size_t(start),
            ctypes.c_size_t(n),
            ctypes.c_void_p(ex.ctypes.data if ex is not None else None),
            ctypes.c_void_p(dn.ctypes.data if dn is not None else None),
            ctypes.c_void_p(dr.ctypes.data if dr is not None else None),
            ctypes.c_void_p(xl.ctypes.data if xl is not None else None),
            ctypes.c_void_p(qc.ctypes.data if qc is not None else None),
            ctypes.c_void_p(kdict._live_handle()),
            ctypes.c_void_p(out.ctypes.data),
            ctypes.c_size_t(batch_size), ctypes.c_size_t(caps.dns),
            ctypes.c_size_t(caps.drop), ctypes.c_size_t(caps.nk),
            ctypes.c_size_t(caps.spill))
        return out, int(consumed)
    # ---- python twin (the layout oracle; per-row because the dictionary
    # state evolves first-seen-sequentially, exactly like the native side)
    hot_off = RESIDENT_HDR
    dns_off = hot_off + batch_size * HOT_WORDS
    drop_off = dns_off + caps.dns
    nk_off = drop_off + caps.drop * 2
    spill_off = nk_off + caps.nk * NK_WORDS
    out[:] = 0
    def_sampling = int(events["stats"]["sampling"][start]) if start < n else 0
    out[0] = def_sampling
    if start >= n:
        return out, 0
    # derived arrays over the REMAINDER only — a batch split into many
    # continuation chunks must not recompute the full batch per chunk
    sl = slice(start, n)
    kw_rel = pack_key_words(events["key"][sl])
    fw_rel = _feature_words(events["stats"][sl],
                            ex[sl] if ex is not None else None,
                            xl[sl] if xl is not None else None,
                            qc[sl] if qc is not None else None,
                            dr[sl] if dr is not None else None)
    stats = events["stats"]
    # u32 wrap matches the native cast (and the dense path's u32 column)
    rtt_rel = ((ex["rtt_ns"][sl] // 1000).astype(np.uint32)
               if ex is not None else np.zeros(n - start, np.uint32))
    dlat_rel = ((dn["latency_ns"][sl] // 1000).astype(np.uint64)
                if dn is not None else np.zeros(n - start, np.uint64))
    py = kdict._py
    nh = nd = nr = nk = ns = 0
    i = start
    while i < n and nh < batch_size:
        j = i - start
        kb = kw_rel[j].tobytes()
        slot = py.get(kb)
        if slot is None and nk < caps.nk and len(py) < kdict.slot_cap:
            slot = len(py)
            py[kb] = slot
            row = nk_off + nk * NK_WORDS
            out[row] = 0x80000000 | slot
            out[row + 1:row + 11] = kw_rel[j]
            nk += 1
        rtt = int(rtt_rel[j])
        dlat = int(dlat_rel[j])
        has_drops = dr is not None and bool(dr["bytes"][i] or dr["packets"][i])
        pk, fl = int(stats["packets"][i]), int(stats["tcp_flags"][i])
        hot_ok = (slot is not None and pk < 0x800 and fl < 0x800
                  and int(stats["dscp"][i]) < 0x40
                  and int(stats["sampling"][i]) == def_sampling
                  and rtt <= RTT_MAX_US
                  and (not dlat or nd < caps.dns)
                  and (not has_drops or nr < caps.drop))
        if hot_ok:
            row = hot_off + nh * HOT_WORDS
            out[row] = 0x80000000 | (_rtt_code11(rtt) << 20) | slot
            out[row + 1] = np.float32(stats["bytes"][i]).view(np.uint32)
            out[row + 2] = (pk | (fl << 11)
                            | (int(stats["dscp"][i]) << 22)
                            | ((int(fw_rel[j, 0]) >> 24) << 28))
            if dlat:
                out[dns_off + nd] = (nh << 16) | _lat_code16(dlat)
                nd += 1
            if has_drops:
                cause = min(int(dr["latest_cause"][i]), 0xFFFF)
                out[drop_off + nr * 2] = (nh << 16) | cause
                out[drop_off + nr * 2 + 1] = ((int(dr["packets"][i]) << 16)
                                              | int(dr["bytes"][i]))
                nr += 1
            nh += 1
        else:
            if ns >= caps.spill:
                break  # chunk full: caller continues from row i
            row = spill_off + ns * DENSE_WORDS
            out[row:row + 10] = kw_rel[j]
            out[row + 10] = np.float32(stats["bytes"][i]).view(np.uint32)
            out[row + 11] = pk
            out[row + 12] = rtt
            # explicit u32 wrap: the native packer casts (uint32_t)dlat, and
            # np.uint32(x) raises OverflowError for x >= 2^32 (a DNS latency
            # over ~71 minutes in µs) instead of wrapping like the C++ side
            out[row + 13] = np.uint32(dlat & 0xFFFFFFFF)
            out[row + 14] = 1
            out[row + 15] = stats["sampling"][i]
            out[row + 16:row + 20] = fw_rel[j]
            ns += 1
        i += 1
    out[1], out[2], out[3] = nk, ns, nd | (nr << 16)
    return out, i - start


_MERGE_FNS = {
    "stats": ("fp_merge_stats", binfmt.FLOW_STATS_DTYPE,
              accumulate.accumulate_base),
    "extra": ("fp_merge_extra", binfmt.EXTRA_REC_DTYPE,
              accumulate.accumulate_extra),
    "drops": ("fp_merge_drops", binfmt.DROPS_REC_DTYPE,
              accumulate.accumulate_drops),
    "dns": ("fp_merge_dns", binfmt.DNS_REC_DTYPE, accumulate.accumulate_dns),
    "nevents": ("fp_merge_nevents", binfmt.NEVENTS_REC_DTYPE,
                accumulate.accumulate_network_events),
    "xlat": ("fp_merge_xlat", binfmt.XLAT_REC_DTYPE,
             accumulate.accumulate_xlat),
    "quic": ("fp_merge_quic", binfmt.QUIC_REC_DTYPE,
             accumulate.accumulate_quic),
}


def merge_percpu(kind: str, values: np.ndarray,
                 use_native: Optional[bool] = None) -> np.ndarray:
    """Merge per-CPU partial records (shape (n_cpu,) structured) into one.
    Single-key API (the accounter path); drains use merge_percpu_batch."""
    fn_name, dtype, py_fn = _MERGE_FNS[kind]
    values = np.ascontiguousarray(values, dtype=dtype)
    if use_native is None:
        use_native = native_available()
    if use_native and native_available():
        out = np.zeros(1, dtype=dtype)
        # pass the already-contiguous array pointer — materializing a bytes
        # object per call doubled the per-flow cost of the old drain loop
        getattr(_lib, fn_name)(
            _ptr(values), ctypes.c_size_t(len(values)), _ptr(out))
        return out[0]
    return accumulate.merge_percpu(values, py_fn)


#: row floor below which lane-sharding a batch merge costs more than the
#: pool round trip saves (one fp_merge_*_batch call is already ~ns/row)
_MERGE_LANE_MIN_ROWS = 4096


def merge_percpu_batch(kind: str, values: np.ndarray,
                       use_native: Optional[bool] = None,
                       out: Optional[np.ndarray] = None,
                       threads: int = 1) -> np.ndarray:
    """Merge per-CPU partials for a WHOLE drained map: values shaped
    (n_keys, n_cpus) structured -> (n_keys,) merged records. Native path is
    one fp_merge_*_batch call over a single pointer (no per-key ctypes round
    trips); fallback is the columnar numpy twin in model/accumulate.py.
    Both are equivalence-pinned against the per-record accumulate_* loop
    (tests/test_evict_columnar.py).

    `out` writes into a caller buffer (must be (n_keys,) of the record
    dtype). `threads > 1` row-shards ONE map's merge across that many pack
    lanes — each lane is its own fp_merge_*_batch call over a disjoint
    contiguous row range of the same buffers (the native call releases the
    GIL, so lanes merge in true parallel; per-key semantics make row
    sharding trivially equivalent). Engages only for native merges past
    `_MERGE_LANE_MIN_ROWS` rows — the eviction plane's big-map (flows_extra)
    relief when one map dominates the drain."""
    fn_name, dtype, _py_fn = _MERGE_FNS[kind]
    values = np.ascontiguousarray(values, dtype=dtype)
    if values.ndim != 2:
        raise ValueError(f"values must be (n_keys, n_cpus), got "
                         f"{values.shape}")
    n_keys, n_cpus = values.shape
    if out is not None and (out.dtype != dtype or len(out) != n_keys
                            or not out.flags.c_contiguous):
        raise ValueError("out must be a contiguous (n_keys,) array of the "
                         "record dtype")
    if use_native is None:
        use_native = native_available()
    if use_native and native_available() and n_keys:
        if out is None:
            out = np.zeros(n_keys, dtype=dtype)
        fn = getattr(_lib, fn_name + "_batch")

        def run(lo: int, hi: int) -> None:
            fn(_ptr(values[lo:hi]), ctypes.c_size_t(hi - lo),
               ctypes.c_size_t(n_cpus), _ptr(out[lo:hi]))

        if threads > 1 and n_keys >= max(_MERGE_LANE_MIN_ROWS, 2 * threads):
            bounds = [n_keys * i // threads for i in range(threads + 1)]
            for f in _pack_submit(threads,
                                  [lambda i=i: run(bounds[i], bounds[i + 1])
                                   for i in range(threads)]):
                f.result()
        else:
            run(0, n_keys)
        return out
    merged = accumulate.COLUMNAR_MERGES[kind](values)
    if out is not None:
        out[:] = merged
        return out
    return merged


def events_from_keys_stats(keys: np.ndarray, stats: np.ndarray,
                           n_total: Optional[int] = None,
                           use_native: Optional[bool] = None) -> np.ndarray:
    """Compose FLOW_EVENT rows from the two columns a batched drain yields —
    the columnar eviction plane's single copy boundary, done as ONE native
    interleave pass (fp_events_from_keys_stats) instead of two strided numpy
    field assignments. `keys` is (n, 40) u8 or (n,) FLOW_KEY; `stats` is
    (n,) FLOW_STATS. The numpy twin is binfmt.events_from_keys_stats
    (equivalence pinned in tests/test_evict_parallel.py); semantics are
    identical, including the zeroed `n_total` tail the loader appends
    ringbuf-orphan events into."""
    if keys.dtype != np.uint8:
        keys = np.ascontiguousarray(keys).view(np.uint8).reshape(
            -1, binfmt.FLOW_KEY_DTYPE.itemsize)
    n = len(keys)
    if len(stats) != n:
        raise ValueError(f"keys/stats length mismatch: {n} vs {len(stats)}")
    if n_total is not None and n_total < n:
        # the numpy twin raises on broadcast; the native memcpy loop would
        # silently write past the short buffer instead — refuse first
        raise ValueError(f"n_total {n_total} < {n} rows")
    if use_native is None:
        use_native = native_available()
    if not (use_native and native_available()):
        return binfmt.events_from_keys_stats(
            keys.view(binfmt.FLOW_KEY_DTYPE).reshape(-1) if n
            else np.empty(0, binfmt.FLOW_KEY_DTYPE),
            stats, n_total=n_total)
    keys = np.ascontiguousarray(keys)
    stats = np.ascontiguousarray(stats, dtype=binfmt.FLOW_STATS_DTYPE)
    out = np.zeros(n_total if n_total is not None else n,
                   dtype=binfmt.FLOW_EVENT_DTYPE)
    if n:
        _lib.fp_events_from_keys_stats(
            _ptr(keys), _ptr(stats), ctypes.c_size_t(n), _ptr(out))
    return out


# ---------------------------------------------------------------------------
# Fused one-call eviction pipeline (flowpack.cc fp_drain_to_resident).
# SCHEDULING ONLY: the native call chains the very same batched drain,
# fp_merge_*_batch, _join_keys-twin join and fp_pack_resident the Python
# chain orchestrates — never a fifth merge form, never a fourth resident
# layout. The Python chain stays the equivalence oracle
# (tests/test_native_pipeline.py pins the fused output bit-exact).
# ---------------------------------------------------------------------------

#: map kind ids of the fused pipeline (flowpack.cc FPK_*); map 0 of a pipe
#: must be "stats" (the aggregation map, rows used verbatim)
PIPE_KINDS = {"stats": 0, "extra": 1, "dns": 2, "drops": 3,
              "nevents": 4, "xlat": 5, "quic": 6}

#: record dtype per pipe kind (the aligned-feature view dtypes)
PIPE_DTYPES = {
    "stats": binfmt.FLOW_STATS_DTYPE, "extra": binfmt.EXTRA_REC_DTYPE,
    "dns": binfmt.DNS_REC_DTYPE, "drops": binfmt.DROPS_REC_DTYPE,
    "nevents": binfmt.NEVENTS_REC_DTYPE, "xlat": binfmt.XLAT_REC_DTYPE,
    "quic": binfmt.QUIC_REC_DTYPE,
}

_PIPE_MAX_MAPS = 8
_PIPE_MAX_LADDER = 8


class _PipeMapCfg(ctypes.Structure):
    _fields_ = [("fd", ctypes.c_int32), ("kind", ctypes.c_uint32),
                ("value_size", ctypes.c_uint32), ("n_cpus", ctypes.c_uint32),
                ("max_entries", ctypes.c_uint32)]


class _PipeLadder(ctypes.Structure):
    _fields_ = [("k", ctypes.c_uint32), ("nr", ctypes.c_uint32),
                ("dicts", ctypes.POINTER(ctypes.c_uint64))]


class _PipePackCfg(ctypes.Structure):
    _fields_ = [("n_ladder", ctypes.c_uint32), ("batch_size", ctypes.c_uint32),
                ("batch_per_region", ctypes.c_uint32),
                ("slot_cap", ctypes.c_uint32), ("dns_cap", ctypes.c_uint32),
                ("drop_cap", ctypes.c_uint32), ("nk_cap", ctypes.c_uint32),
                ("spill_cap", ctypes.c_uint32),
                ("ladder", _PipeLadder * _PIPE_MAX_LADDER)]


class _PipeChunk(ctypes.Structure):
    _fields_ = [("row_start", ctypes.c_uint64), ("rows", ctypes.c_uint64),
                ("arena_off", ctypes.c_uint64), ("k", ctypes.c_uint32),
                ("n_segs", ctypes.c_uint32), ("spills", ctypes.c_uint32),
                ("resets", ctypes.c_uint32)]


class _PipeResult(ctypes.Structure):
    _fields_ = [("n_events", ctypes.c_uint64), ("n_agg", ctypes.c_uint64),
                ("n_orphans", ctypes.c_uint64),
                ("packed_rows", ctypes.c_uint64),
                ("drain_ns", ctypes.c_uint64), ("merge_ns", ctypes.c_uint64),
                ("join_ns", ctypes.c_uint64), ("pack_ns", ctypes.c_uint64),
                ("syscalls", ctypes.c_uint64),
                ("lex_fallback", ctypes.c_uint64),
                ("batch_err_mask", ctypes.c_uint64),
                ("n_chunks", ctypes.c_uint64),
                ("arena_words", ctypes.c_uint64),
                ("spill_rows", ctypes.c_uint64),
                ("dict_resets", ctypes.c_uint64), ("segs", ctypes.c_uint64),
                ("events", ctypes.c_void_p), ("arena", ctypes.c_void_p),
                ("chunks", ctypes.c_void_p),
                ("aligned", ctypes.c_void_p * _PIPE_MAX_MAPS),
                ("map_rows", ctypes.c_uint64 * _PIPE_MAX_MAPS)]


def _pipe_view(addr: Optional[int], nbytes: int, dtype) -> Optional[np.ndarray]:
    if not addr or nbytes == 0:
        return None
    buf = (ctypes.c_uint8 * nbytes).from_address(addr)
    return np.frombuffer(buf, dtype=dtype)


class PipeChunk:
    """One pack chunk of a fused drain — mirrors one outer iteration of
    ShardedResidentStagingRing._fold_chunk (k-ladder selection, continuation
    segments). The caller ships arena[arena_off : arena_off + n_segs *
    (nr(k) * region_words)] as n_segs ring-slot images."""

    __slots__ = ("row_start", "rows", "arena_off", "k", "n_segs", "spills",
                 "resets")

    def __init__(self, c: "_PipeChunk"):
        self.row_start = int(c.row_start)
        self.rows = int(c.rows)
        self.arena_off = int(c.arena_off)
        self.k = int(c.k)
        self.n_segs = int(c.n_segs)
        self.spills = int(c.spills)
        self.resets = int(c.resets)


class PipeResult:
    """Outputs of one fused drain. `events`/`aligned[kind]` are zero-copy
    VIEWS of pipe-handle scratch — valid only until the pipe's next drain
    (the drain_batched_arrays cached-buffer rule; the one copy happens at
    the EvictedFlows boundary). The packed `arena` is owned by THIS object:
    call free() (or let __del__ catch it) after the regions are shipped."""

    __slots__ = ("n_events", "n_agg", "n_orphans", "packed_rows", "drain_s",
                 "merge_s", "join_s", "pack_s", "syscalls", "lex_fallback",
                 "batch_err_mask", "map_rows", "events", "aligned", "arena",
                 "chunks", "spill_rows", "dict_resets", "segs", "_arena_ptr")

    def __init__(self, res: _PipeResult, kinds: list):
        self.n_events = int(res.n_events)
        self.n_agg = int(res.n_agg)
        self.n_orphans = int(res.n_orphans)
        self.packed_rows = int(res.packed_rows)
        self.drain_s = res.drain_ns * 1e-9
        self.merge_s = res.merge_ns * 1e-9
        self.join_s = res.join_ns * 1e-9
        self.pack_s = res.pack_ns * 1e-9
        self.syscalls = int(res.syscalls)
        self.lex_fallback = int(res.lex_fallback)
        self.batch_err_mask = int(res.batch_err_mask)
        self.spill_rows = int(res.spill_rows)
        self.dict_resets = int(res.dict_resets)
        self.segs = int(res.segs)
        self.map_rows = [int(res.map_rows[i]) for i in range(len(kinds))]
        self.events = _pipe_view(
            res.events, self.n_events * binfmt.FLOW_EVENT_DTYPE.itemsize,
            binfmt.FLOW_EVENT_DTYPE)
        self.aligned = {}
        for i, kind in enumerate(kinds):
            if i == 0:
                continue  # the stats map composes into events, not aligned
            dt = PIPE_DTYPES[kind]
            self.aligned[kind] = _pipe_view(
                res.aligned[i], self.n_events * dt.itemsize, dt)
        self._arena_ptr = res.arena or 0
        self.arena = _pipe_view(self._arena_ptr,
                                int(res.arena_words) * 4, np.uint32)
        self.chunks = []
        if res.n_chunks and res.chunks:
            carr = (_PipeChunk * int(res.n_chunks)).from_address(res.chunks)
            self.chunks = [PipeChunk(c) for c in carr]

    def free(self) -> None:
        if self._arena_ptr:
            _lib.fp_buf_free(ctypes.c_void_p(self._arena_ptr))
            self._arena_ptr = 0
            self.arena = None

    def __del__(self):  # best-effort; free() is the real API
        try:
            self.free()
        except Exception:
            pass


class NativePipe:
    """Handle on one fp_drain_to_resident pipeline over a fixed set of maps.
    `maps` is [(fd, kind, value_size, n_cpus, max_entries)] with map 0 the
    aggregation map (kind "stats", n_cpus 1); fd < 0 makes a map injected
    (set_drained) for tests and bench. `lanes` fans the per-map drain+merge
    over that many native worker threads (GIL released for the whole call)."""

    def __init__(self, maps: list, lanes: int = 1):
        if not native_available():
            raise RuntimeError("native flowpack library unavailable")
        if not maps or len(maps) > _PIPE_MAX_MAPS:
            raise ValueError(f"1..{_PIPE_MAX_MAPS} maps required")
        self.kinds = [m[1] for m in maps]
        cfgs = (_PipeMapCfg * len(maps))()
        for i, (fd, kind, value_size, n_cpus, max_entries) in enumerate(maps):
            cfgs[i] = _PipeMapCfg(fd=fd, kind=PIPE_KINDS[kind],
                                  value_size=value_size, n_cpus=n_cpus,
                                  max_entries=max_entries)
        _lib.fp_pipe_new.restype = ctypes.c_void_p
        _lib.fp_drain_to_resident.restype = ctypes.c_int64
        _lib.fp_pipe_set_drained.restype = ctypes.c_int
        self._handle = _lib.fp_pipe_new(cfgs, ctypes.c_uint32(len(maps)),
                                        ctypes.c_uint32(max(lanes, 1)))
        if not self._handle:
            raise ValueError("fp_pipe_new rejected the map configuration")

    def set_drained(self, idx: int, keys: np.ndarray,
                    vals: np.ndarray) -> None:
        """Inject one drain's (keys, vals) for an fd<0 map: keys (n, 40) u8,
        vals the kernel layout (n rows x n_cpus images, contiguous)."""
        keys = np.ascontiguousarray(keys)
        vals = np.ascontiguousarray(vals)
        n = len(keys)
        rc = _lib.fp_pipe_set_drained(
            ctypes.c_void_p(self._handle), ctypes.c_uint32(idx),
            _ptr(keys), _ptr(vals), ctypes.c_uint32(n))
        if rc != 0:
            raise ValueError(f"fp_pipe_set_drained({idx}) failed")

    def drain(self, pack: Optional[dict] = None) -> PipeResult:
        """Run the fused chain. `pack` (None = drain/merge/join only) is
        {"batch_size", "batch_per_region", "slot_cap", "caps": ResidentCaps,
        "ladder": [(k, [dict handles])]} with ladder ks ascending, k=1
        first, handles from KeyDict._live_handle() in the ring's per-region
        dictionary order."""
        res = _PipeResult()
        keepalive = []
        pk_ref = None
        if pack is not None:
            caps = pack["caps"]
            ladder = pack["ladder"]
            if len(ladder) > _PIPE_MAX_LADDER:
                raise ValueError("ladder too deep")
            pk = _PipePackCfg(
                n_ladder=len(ladder), batch_size=pack["batch_size"],
                batch_per_region=pack["batch_per_region"],
                slot_cap=pack["slot_cap"], dns_cap=caps.dns,
                drop_cap=caps.drop, nk_cap=caps.nk, spill_cap=caps.spill)
            for li, (k, handles) in enumerate(ladder):
                arr = (ctypes.c_uint64 * len(handles))(*handles)
                keepalive.append(arr)
                pk.ladder[li] = _PipeLadder(
                    k=k, nr=len(handles),
                    dicts=ctypes.cast(arr, ctypes.POINTER(ctypes.c_uint64)))
            pk_ref = ctypes.byref(pk)
            keepalive.append(pk)
        rc = int(_lib.fp_drain_to_resident(
            ctypes.c_void_p(self._handle), pk_ref, ctypes.byref(res)))
        del keepalive
        if rc < 0:
            raise RuntimeError(f"fp_drain_to_resident failed (rc={rc})")
        return PipeResult(res, self.kinds)

    def close(self) -> None:
        if self._handle:
            _lib.fp_pipe_free(ctypes.c_void_p(self._handle))
            self._handle = None

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:
            pass
