"""Hand-assembled PCA (packet capture) datapath — no compiler required.

Builds a TC/TCX classifier that copies each packet's first
NO_MAX_PAYLOAD_SIZE bytes into the `packet_records` ring buffer as a
`no_packet_event` (records.h:195), the same layout the clang-built `pca.h`
program produces — so PerfTracer/PerfBuffer/pcap framing run unchanged.

Shape: reserve a record in the ring buffer, fill (if_index, pkt_len,
timestamp), zero the payload area (ringbuf memory is NOT zeroed — an
unwritten tail would leak stale kernel bytes to userspace), then
bpf_skb_load_bytes a min(skb->len, snap) prefix; discard the reservation on
copy failure. Verified by the live kernel (tests/test_asm_flowpath.py PCA
e2e).
"""

from __future__ import annotations

from netobserv_tpu.datapath.asm import (
    Asm, BPF_DW, BPF_W, HELPER_KTIME_GET_NS, R0, R1, R2, R3, R4, R6, R7, R8,
    R10,
)
from netobserv_tpu.model import binfmt

HELPER_PRANDOM_U32 = 7
HELPER_SKB_LOAD_BYTES = 26
HELPER_RINGBUF_RESERVE = 131
HELPER_RINGBUF_SUBMIT = 132
HELPER_RINGBUF_DISCARD = 133

SKB_LEN = 0
SKB_IFINDEX = 40

_REC = binfmt.PACKET_EVENT_DTYPE
_OFF_IFINDEX = _REC.fields["if_index"][1]
_OFF_PKT_LEN = _REC.fields["pkt_len"][1]
_OFF_TS = _REC.fields["timestamp_ns"][1]
_OFF_PAYLOAD = _REC.fields["payload"][1]
SNAP = binfmt.MAX_PAYLOAD_SIZE


def build_pca_program(ringbuf_fd: int, sampling: int = 0,
                      direction: int = 0,
                      filter_rules_fd: int | None = None,
                      filter_peers_fd: int | None = None,
                      counters_fd: int | None = None) -> bytes:
    """One program serves both directions (the record carries no direction;
    reference parity — `no_packet_event` has if_index/len/timestamp only).
    `sampling` > 1 bakes in a 1/N gate, the loader-rewritten-const analog.

    With filter trie fds wired, the program front-loads the flow datapath's
    shared parse + filter gate (asm_flowpath emit_head): only packets an
    Accept rule matches are captured — the pca.h in-kernel filtering
    behavior, previously clang-object-only."""
    if filter_rules_fd is not None:
        from netobserv_tpu.datapath.asm_flowpath import _Flow

        # direction matters here: filter rules carry a direction predicate,
        # so the loader builds one program per hook when filtering
        emitter = _Flow(map_fd=0, direction=direction, sampling=sampling,
                        ringbuf_fd=None, counters_fd=counters_fd,
                        dns_inflight_fd=None, flows_dns_fd=None, dns_port=53,
                        filter_rules_fd=filter_rules_fd,
                        filter_peers_fd=filter_peers_fd)
        emitter.emit_head()              # parse + filter; drops go to "out"
        _emit_capture(emitter.a, ringbuf_fd)
        a = emitter.a
        a.label("out")
        a.mov_imm(R0, 0)
        a.exit()
        return a.assemble()
    a = Asm()
    a.mov_reg(R6, R1)                        # r6 = ctx

    if sampling > 1:
        a.call(HELPER_PRANDOM_U32)
        a.alu_imm(0x97, R0, sampling)        # r0 %= N (ALU64 MOD K)
        a.jmp_imm(0x55, R0, 0, "out")        # not the sampled 1/N: out

    _emit_capture(a, ringbuf_fd)
    a.label("out")
    a.mov_imm(R0, 0)                         # TC_ACT_OK
    a.exit()
    return a.assemble()


def _emit_capture(a: Asm, ringbuf_fd: int) -> None:
    """Reserve + fill + submit one no_packet_event (falls through to the
    caller's \"out\" label; needs only r6 = ctx live)."""
    a.ld_map_fd(R1, ringbuf_fd)
    a.mov_imm(R2, _REC.itemsize)
    a.mov_imm(R3, 0)
    a.call(HELPER_RINGBUF_RESERVE)
    a.jmp_imm(0x15, R0, 0, "out")            # ring full: drop
    a.mov_reg(R7, R0)                        # r7 = record ptr

    a.ldx(BPF_W, R3, R6, SKB_IFINDEX)
    a.stx(BPF_W, R7, R3, _OFF_IFINDEX)
    a.ldx(BPF_W, R8, R6, SKB_LEN)            # r8 = original length
    a.stx(BPF_W, R7, R8, _OFF_PKT_LEN)
    a.call(HELPER_KTIME_GET_NS)
    a.stx(BPF_DW, R7, R0, _OFF_TS)

    # zero the payload area: ringbuf_reserve memory is recycled, and the
    # tail past the captured prefix must not leak stale kernel bytes
    for off in range(_OFF_PAYLOAD, _REC.itemsize, 8):
        a.st_imm(BPF_DW, R7, off, 0)

    # n = min(skb->len, SNAP); empty frames discard
    a.jmp_imm(0xB5, R8, SNAP, "len_ok")      # JLE imm
    a.mov_imm(R8, SNAP)
    a.label("len_ok")
    a.jmp_imm(0x15, R8, 0, "discard")

    a.mov_reg(R1, R6)                        # skb_load_bytes(ctx, 0, dst, n)
    a.mov_imm(R2, 0)
    a.mov_reg(R3, R7)
    a.alu_imm(0x07, R3, _OFF_PAYLOAD)
    a.mov_reg(R4, R8)
    a.call(HELPER_SKB_LOAD_BYTES)
    a.jmp_imm(0x55, R0, 0, "discard")        # copy failed: drop the record

    a.mov_reg(R1, R7)
    a.mov_imm(R2, 0)
    a.call(HELPER_RINGBUF_SUBMIT)
    a.jmp("out")

    a.label("discard")
    a.mov_reg(R1, R7)
    a.mov_imm(R2, 0)
    a.call(HELPER_RINGBUF_DISCARD)
