"""Fetcher protocol + in-memory fake.

`FlowFetcher` is the seam between the kernel datapath and the userspace
pipeline (reference: `pkg/tracer/tracer.go:52-76` FlowFetcher; fake analog:
`pkg/test/tracer_fake.go`). The real libbpf-backed implementation lives in
`netobserv_tpu.datapath.loader`; everything above this seam is kernel-free and
fully testable.
"""

from __future__ import annotations

import queue
import threading
from typing import Optional, Protocol

import numpy as np

from netobserv_tpu.model import binfmt
from netobserv_tpu.model.flow import GlobalCounter


class EvictedFlows:
    """One map eviction: base flow events + per-feature parallel arrays.

    `events` is a FLOW_EVENT structured array (per-CPU partials already
    merged); feature arrays are aligned with `events` rows (or None when the
    feature is disabled).

    Ownership contract: every array is OWNED by this object — the columnar
    drain decode reads zero-copy views of the kernel batch buffers, and
    construction here is the single copy boundary (a later drain must never
    mutate an earlier EvictedFlows; pinned by the aliasing regression in
    tests/test_bpfman.py). `decode_stats` carries the producing drain's
    per-stage seconds (decode/merge/align) when the columnar eviction plane
    built it; map_tracer feeds it to `eviction_decode_seconds`."""

    def __init__(self, events: np.ndarray,
                 dns: Optional[np.ndarray] = None,
                 drops: Optional[np.ndarray] = None,
                 extra: Optional[np.ndarray] = None,
                 xlat: Optional[np.ndarray] = None,
                 nevents: Optional[np.ndarray] = None,
                 quic: Optional[np.ndarray] = None):
        self.events = events
        self.dns = dns
        self.drops = drops
        self.extra = extra
        self.xlat = xlat
        self.nevents = nevents
        self.quic = quic
        self.decode_stats: Optional[dict] = None
        #: fused-pipeline extra (loader.PackedEviction): resident regions
        #: pre-packed at drain time. The raw arrays above are ALWAYS the
        #: full eviction regardless — a consumer that can't ship the packed
        #: arena (epoch moved, no surface) frees it and folds these.
        self.packed = None

    def __len__(self) -> int:
        return len(self.events)


class FlowFetcher(Protocol):
    """What the pipeline needs from the datapath."""

    def lookup_and_delete(self) -> EvictedFlows:
        """Drain the kernel aggregation map (one eviction)."""
        ...

    def read_ringbuf(self, timeout_s: float) -> Optional[bytes]:
        """Block up to timeout_s for one raw flow event (map-full fallback).
        Returns None on timeout."""
        ...

    def read_ssl(self, timeout_s: float) -> Optional[bytes]:
        """Block up to timeout_s for one raw SSL plaintext event (OpenSSL
        uprobe ring buffer). Returns None on timeout."""
        ...

    def read_global_counters(self) -> dict[GlobalCounter, int]:
        """Scrape-and-reset the datapath's global counters."""
        ...

    def purge_stale(self, older_than_s: float) -> int:
        """Drop auxiliary-map entries (e.g. unanswered DNS correlations) older
        than the deadline; returns how many were purged. (Reference analog:
        DeleteMapsStaleEntries, `pkg/tracer/tracer.go:1188-1216`.)"""
        ...

    def attach(self, if_index: int, if_name: str, direction: str,
               netns: str = "") -> None: ...

    def detach(self, if_index: int, if_name: str,
               netns: str = "") -> None: ...

    def close(self) -> None: ...


class FakeFetcher:
    """Injectable fetcher for tests and pcap/synthetic replay.

    Push map dumps with `inject_eviction`, ringbuf events with
    `inject_ringbuf` (reference analog: `pkg/test/tracer_fake.go:17-84`)."""

    def __init__(self):
        self._evictions: queue.Queue[EvictedFlows] = queue.Queue()
        self._ringbuf: queue.Queue[bytes] = queue.Queue()
        self._ssl: queue.Queue[bytes] = queue.Queue()
        self._counters: dict[GlobalCounter, int] = {}
        self._lock = threading.Lock()
        self.attached: dict[int, str] = {}
        self.closed = False

    # --- injection side ---
    def inject_eviction(self, evicted: EvictedFlows) -> None:
        self._evictions.put(evicted)

    def inject_events(self, events: np.ndarray, **features) -> None:
        self.inject_eviction(EvictedFlows(events, **features))

    def inject_ringbuf(self, event: np.ndarray | bytes) -> None:
        if isinstance(event, np.ndarray):
            event = np.ascontiguousarray(
                event, dtype=binfmt.FLOW_EVENT_DTYPE).tobytes()
        self._ringbuf.put(event)

    def inject_ssl(self, event: bytes) -> None:
        self._ssl.put(event)

    def bump_counter(self, key: GlobalCounter, n: int = 1) -> None:
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + n

    # --- FlowFetcher side ---
    def lookup_and_delete(self) -> EvictedFlows:
        try:
            return self._evictions.get_nowait()
        except queue.Empty:
            return EvictedFlows(np.zeros(0, dtype=binfmt.FLOW_EVENT_DTYPE))

    def read_ringbuf(self, timeout_s: float) -> Optional[bytes]:
        try:
            return self._ringbuf.get(timeout=timeout_s)
        except queue.Empty:
            return None

    def read_ssl(self, timeout_s: float) -> Optional[bytes]:
        try:
            return self._ssl.get(timeout=timeout_s)
        except queue.Empty:
            return None

    def read_global_counters(self) -> dict[GlobalCounter, int]:
        with self._lock:
            out, self._counters = self._counters, {}
        return out

    def purge_stale(self, older_than_s: float) -> int:
        self.purged_calls = getattr(self, "purged_calls", 0) + 1
        return 0

    def attach(self, if_index: int, if_name: str, direction: str,
               netns: str = "") -> None:
        # keyed like the real fetchers: ifindex values repeat across netns
        self.attached[(netns, if_index) if netns else if_index] = if_name

    def detach(self, if_index: int, if_name: str,
               netns: str = "") -> None:
        self.attached.pop((netns, if_index) if netns else if_index, None)

    def close(self) -> None:
        self.closed = True
