"""Federation gRPC plumbing (service `pbsketch.Federation`).

Same shape as `grpc/flow.py` (the proven Collector plumbing): a thin unary
client and an in-process server helper. Delta frames travel as RAW BYTES on
both ends (serializer/deserializer pass-through) — the one
encode/decode site is `federation.delta`, so the gRPC layer cannot drift
from the frame format, and the aggregator can count/reject malformed frames
itself instead of dying in the transport.
"""

from __future__ import annotations

import logging
import queue
from typing import Callable, Optional

import grpc

from netobserv_tpu.grpc.flow import _channel_credentials
from netobserv_tpu.pb import sketch_delta_pb2

log = logging.getLogger("netobserv_tpu.grpc.federation")

_PUSH = "/pbsketch.Federation/Push"

_identity = lambda b: b  # noqa: E731 — raw-bytes pass-through

#: gRPC status codes worth a retry of the SAME frame bytes. UNAVAILABLE is
#: the aggregator restarting/rebalancing; DEADLINE_EXCEEDED is the
#: *ambiguous* one — the push may have been applied — and retrying it is
#: only safe because v2 frames carry an idempotency key (agent/epoch/
#: window_seq/frame_uuid) the aggregator dedups on.
RETRY_SAFE_CODES = frozenset((
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.DEADLINE_EXCEEDED,
    grpc.StatusCode.RESOURCE_EXHAUSTED,
    grpc.StatusCode.ABORTED,
    grpc.StatusCode.INTERNAL,      # transient stream resets land here
    grpc.StatusCode.UNKNOWN,       # connectivity errors without a verdict
))

#: codes where resending the same bytes CANNOT succeed (a broken client, a
#: wrong target, an auth failure) — burning the retry ladder on them only
#: delays the local report pipeline.
TERMINAL_CODES = frozenset((
    grpc.StatusCode.INVALID_ARGUMENT,
    grpc.StatusCode.UNIMPLEMENTED,
    grpc.StatusCode.FAILED_PRECONDITION,
    grpc.StatusCode.PERMISSION_DENIED,
    grpc.StatusCode.UNAUTHENTICATED,
    grpc.StatusCode.NOT_FOUND,
))


def classify_rpc_error(exc: Exception) -> str:
    """`retry` / `terminal` for a push failure. Non-gRPC exceptions (bugs
    in the stack below us) classify as terminal — retrying a TypeError
    three times with backoff is pure stall."""
    code = exc.code() if isinstance(exc, grpc.RpcError) else None
    if code in TERMINAL_CODES:
        return "terminal"
    if code in RETRY_SAFE_CODES:
        return "retry"
    return "retry" if code is not None else "terminal"


class FederationClient:
    """Unary Push client; `send` takes an ALREADY-SERIALIZED delta frame."""

    def __init__(self, host: str, port: int, tls_ca: str = "",
                 tls_cert: str = "", tls_key: str = ""):
        self._target = f"{host}:{port}"
        self._creds = _channel_credentials(tls_ca, tls_cert, tls_key)
        self._channel: Optional[grpc.Channel] = None
        self._push = None
        self.connect()

    def connect(self) -> None:
        self.close()
        # a LOCAL subchannel pool makes reconnect() an actual fresh start:
        # by default grpc-python shares subchannels per target process-wide,
        # so a "new" channel inherits the old subchannel's TRANSIENT_FAILURE
        # backoff (seconds-to-minutes) and every retry fails fast with
        # UNAVAILABLE even after the aggregator came back — a cold-started
        # agent would never deliver a frame (pinned by the smoke failure
        # path / tests/test_federation_chaos.py cold-start test)
        opts = (("grpc.use_local_subchannel_pool", 1),)
        if self._creds is not None:
            self._channel = grpc.secure_channel(self._target, self._creds,
                                                options=opts)
        else:
            self._channel = grpc.insecure_channel(self._target,
                                                  options=opts)
        self._push = self._channel.unary_unary(
            _PUSH,
            request_serializer=_identity,
            response_deserializer=sketch_delta_pb2.DeltaAck.FromString,
        )

    def send(self, frame: bytes,
             timeout_s: float = 10.0) -> sketch_delta_pb2.DeltaAck:
        return self._push(frame, timeout=timeout_s)

    def close(self) -> None:
        if self._channel is not None:
            self._channel.close()
            self._channel = None


def start_federation_collector(
        port: int = 0,
        handler: Optional[Callable[[bytes], sketch_delta_pb2.DeltaAck]] = None,
        out: Optional["queue.Queue[bytes]"] = None,
        tls_cert: str = "", tls_key: str = "", max_workers: int = 4):
    """In-process Federation server; returns (server, bound_port, queue).

    `handler(frame_bytes) -> DeltaAck` is the aggregator's ingest entry;
    without one, frames land on `out` and are blanket-acked (test harness
    shape, like `start_flow_collector`). A handler exception acks
    `accepted=0` with the reason — a malformed frame must never tear down
    the stream every OTHER agent is pushing on.
    """
    from concurrent import futures

    out = out if out is not None else queue.Queue()

    def push(request: bytes, context) -> sketch_delta_pb2.DeltaAck:
        if handler is None:
            out.put(request)
            return sketch_delta_pb2.DeltaAck(accepted=1)
        try:
            return handler(request)
        except Exception as exc:  # swallow: one bad frame, not the server
            log.error("federation push handler failed: %s", exc)
            return sketch_delta_pb2.DeltaAck(accepted=0, reason=str(exc))

    generic = grpc.method_handlers_generic_handler(
        "pbsketch.Federation",
        {"Push": grpc.unary_unary_rpc_method_handler(
            push,
            request_deserializer=_identity,
            response_serializer=sketch_delta_pb2.DeltaAck.SerializeToString)})
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers((generic,))
    if tls_cert and tls_key:
        creds = grpc.ssl_server_credentials(
            [(open(tls_key, "rb").read(), open(tls_cert, "rb").read())])
        bound = server.add_secure_port(f"0.0.0.0:{port}", creds)
    else:
        bound = server.add_insecure_port(f"0.0.0.0:{port}")
    server.start()
    return server, bound, out
