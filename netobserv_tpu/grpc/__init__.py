"""gRPC plumbing: flow/packet clients and test-oriented collector servers.

Reference analog: `pkg/grpc/` (client with TLS/mTLS options; in-process
collector server forwarding to a channel for tests/examples). Service stubs are
hand-written over grpcio's generic API since grpc_tools isn't available for
codegen in this image — the method path and message types match proto/flow.proto.
"""

from netobserv_tpu.grpc.flow import (  # noqa: F401
    FlowClient, start_flow_collector,
)
