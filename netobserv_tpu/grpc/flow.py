"""Flow collector gRPC client/server (service `pbflow.Collector`)."""

from __future__ import annotations

import logging
import queue
from typing import Optional

import grpc

from netobserv_tpu.pb import flow_pb2

log = logging.getLogger("netobserv_tpu.grpc.flow")

_SEND = "/pbflow.Collector/Send"


def _channel_credentials(ca_path: str = "", cert_path: str = "",
                         key_path: str = "") -> Optional[grpc.ChannelCredentials]:
    if not ca_path and not cert_path:
        return None
    root = open(ca_path, "rb").read() if ca_path else None
    if cert_path and key_path:  # mTLS
        return grpc.ssl_channel_credentials(
            root_certificates=root,
            private_key=open(key_path, "rb").read(),
            certificate_chain=open(cert_path, "rb").read())
    return grpc.ssl_channel_credentials(root_certificates=root)


class FlowClient:
    """Thin client for Collector.Send (reference: `pkg/grpc/flow/client.go`)."""

    def __init__(self, host: str, port: int, tls_ca: str = "",
                 tls_cert: str = "", tls_key: str = ""):
        self._target = f"{host}:{port}"
        self._creds = _channel_credentials(tls_ca, tls_cert, tls_key)
        self._channel: Optional[grpc.Channel] = None
        self._send = None
        self.connect()

    def connect(self) -> None:
        self.close()
        if self._creds is not None:
            self._channel = grpc.secure_channel(self._target, self._creds)
        else:
            self._channel = grpc.insecure_channel(self._target)
        self._send = self._channel.unary_unary(
            _SEND,
            request_serializer=flow_pb2.Records.SerializeToString,
            response_deserializer=flow_pb2.CollectorReply.FromString,
        )

    def send(self, records: flow_pb2.Records,
             timeout_s: float = 10.0) -> flow_pb2.CollectorReply:
        return self._send(records, timeout=timeout_s)

    def close(self) -> None:
        if self._channel is not None:
            self._channel.close()
            self._channel = None


def start_flow_collector(port: int = 0,
                         out: Optional["queue.Queue[flow_pb2.Records]"] = None,
                         tls_cert: str = "", tls_key: str = ""):
    """In-process collector server; returns (server, bound_port, queue).

    Reference analog: `pkg/grpc/flow/server.go:34-77` — forwards every received
    Records message to a queue (used by tests and the flowlogs-dump example).
    """
    from concurrent import futures

    out = out if out is not None else queue.Queue()

    def send(request: flow_pb2.Records, context) -> flow_pb2.CollectorReply:
        out.put(request)
        return flow_pb2.CollectorReply()

    handler = grpc.method_handlers_generic_handler(
        "pbflow.Collector",
        {"Send": grpc.unary_unary_rpc_method_handler(
            send,
            request_deserializer=flow_pb2.Records.FromString,
            response_serializer=flow_pb2.CollectorReply.SerializeToString)})
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
    server.add_generic_rpc_handlers((handler,))
    if tls_cert and tls_key:
        creds = grpc.ssl_server_credentials(
            [(open(tls_key, "rb").read(), open(tls_cert, "rb").read())])
        bound = server.add_secure_port(f"0.0.0.0:{port}", creds)
    else:
        bound = server.add_insecure_port(f"0.0.0.0:{port}")
    server.start()
    return server, bound, out
