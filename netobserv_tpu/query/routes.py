"""Route handler for the agent's `/query/*` surface.

HTTP-host-agnostic: the metrics server (`metrics/server.py`) hands parsed
``(path, params)`` in and writes the returned ``(status, json-able)`` out,
and tests can drive the routes without a socket. Every request is counted
in ``query_requests_total{route, result}``; every answer reads only the
published snapshot (`query/snapshot.py`) — never a device op, never an
exporter lock.

Routes (all GET, JSON):

- /query/topk          this agent's heavy hitters (?n= caps the list),
                       with the same CM error bars /query/frequency
                       renders (slot counts ARE CM point estimates)
- /query/frequency     CM estimate + error bars for one 5-tuple
                       (?src=&dst=&src_port=&dst_port=&proto=)
- /query/churn         per-key heavy-hitter churn of the window: flow
                       ascents/descents, new-heavy entries, evicted keys
                       (the persistent-slot table's cross-window diff)
- /query/cardinality   distinct-source estimate + window totals
- /query/victims       suspect buckets per signal with victim names
- /query/alerts        the continuous detection plane's live view
                       (active alerts + recent transitions; 404 when
                       ALERT_RULES is unset — no engine exists)
- /query/status        snapshot freshness + plane counters
                       (incl. the back-scroll ring's window ids)
- /query/range         sketch-warehouse time-range answers
                       (?from=&to=; /query/range/topk|frequency|
                       cardinality|victims views) — served by the archive
                       plane (netobserv_tpu/archive), which merges the
                       covering on-disk segments in one device dispatch;
                       404 when ARCHIVE_DIR is unset (no archive exists)

Back-scroll: every data route accepts ``?window=<id>`` for a
point-in-time read of a PAST closed window, served from the publisher's
snapshot ring (`SnapshotPublisher(history=N)`) — still snapshot-only.
Evicted or never-rolled ids answer 404 (listing what IS available);
without a ring the parameter always 404s.

Tenancy: with SKETCH_TENANTS set, every DATA route (topk/frequency/churn/
cardinality/victims) additionally REQUIRES ``?tenant=<id>`` — each tenant
plane has its own publisher (snapshot + back-scroll ring), and there is no
cross-tenant merged view to default to (planes are independent by
construction). A missing tenant answers 400 listing the tenant count;
out-of-range answers 404. /query/status, /query/alerts and /query/range
keep their own tenant semantics (status reports all tenants; range takes
?tenant= through the archive plane's own resolver).
"""

from __future__ import annotations

import logging
from typing import Callable, Optional

from netobserv_tpu.query import core

log = logging.getLogger("netobserv_tpu.query")

ROUTES = ("topk", "frequency", "churn", "cardinality", "victims",
          "alerts", "status", "range")


class QueryRoutes:
    """Dispatch `/query/<route>` requests against a snapshot source.

    `snapshot_fn` returns the published snapshot dict (or None);
    `status_fn` returns the freshness/counters dict for /query/status.
    """

    def __init__(self, snapshot_fn: Callable[[], Optional[dict]],
                 status_fn: Callable[[], dict], metrics=None,
                 history_fn: Optional[Callable[[int], Optional[dict]]] = None,
                 windows_fn: Optional[Callable[[], list]] = None,
                 alerts=None, archive=None, tenant_publishers=None):
        self._snapshot = snapshot_fn
        self._status = status_fn
        self._metrics = metrics
        self._history = history_fn
        self._windows = windows_fn
        #: the alert engine (alerts/engine.py) or None when ALERT_RULES is
        #: unset — the route then answers 404 (alerting disabled)
        self._alerts = alerts
        #: the sketch warehouse (archive.SketchArchive) or None when
        #: ARCHIVE_DIR is unset — /query/range then answers 404
        self._archive = archive
        #: SKETCH_TENANTS mode: the per-tenant SnapshotPublisher list —
        #: data routes then resolve snapshot/history/windows from the
        #: requested tenant's publisher instead of the top-level fns
        self._tenant_pubs = tenant_publishers

    def index(self) -> dict:
        return {"routes": [f"/query/{r}" for r in ROUTES]}

    def handle(self, path: str, params: dict) -> tuple[int, dict]:
        """`path` is the URL path (e.g. "/query/topk"), `params` the parsed
        single-valued query dict. Returns (http status, JSON-able body)."""
        parts = [p for p in path.split("/") if p]
        # /query/range/<view> nests one level deeper than the snapshot
        # routes: the view rides as a pseudo-param so the route counter
        # still aggregates under "range"
        if len(parts) >= 2 and parts[1] == "range":
            route = "range"
            if len(parts) > 2:
                params = dict(params, view=parts[2])
        else:
            route = path.rstrip("/").rpartition("/")[2] or "index"
        try:
            code, body = self._dispatch(route, params)
        except ValueError as exc:  # malformed params (e.g. ?n=bogus)
            code, body = 400, {"error": str(exc)}
        except Exception as exc:  # the query surface must keep answering
            log.error("query route %s failed: %s", path, exc)
            code, body = 500, {"error": str(exc)}
        self._count(route, code)
        return code, body

    def _count(self, route: str, code: int) -> None:
        if self._metrics is None:
            return
        result = ("ok" if code == 200 else
                  "no_window" if code == 503 else
                  "bad_request" if code == 400 else
                  "not_found" if code == 404 else "error")
        self._metrics.query_requests_total.labels(route, result).inc()

    def _dispatch(self, route: str, params: dict) -> tuple[int, dict]:
        if route in ("index", "query"):
            return 200, self.index()
        if route not in ROUTES:
            return 404, {"error": f"unknown query route {route!r}",
                         **self.index()}
        if route == "status":
            return 200, self._status()
        if route == "alerts":
            # the alert view has its own closed-window ring (the engine's)
            # with the same ?window= back-scroll contract as the snapshot
            # routes: 404 + available ids on evicted/unknown windows
            if self._alerts is None:
                return 404, {"error": "alerting disabled "
                                      "(ALERT_RULES unset)"}
            return self._alerts.route_payload(params.get("window"))
        if route == "range":
            # the sketch warehouse's time-range surface: answered entirely
            # by the archive plane (device merge of on-disk segments —
            # never the live snapshot, never the exporter lock)
            if self._archive is None:
                return 404, {"error": "archive disabled "
                                      "(ARCHIVE_DIR unset)"}
            return self._archive.route_payload(params)
        snapshot_fn, history_fn, windows_fn = (
            self._snapshot, self._history, self._windows)
        if self._tenant_pubs is not None:
            # tenant mode: data routes answer from ONE tenant's publisher
            # (snapshot + ring) — there is no merged cross-tenant view
            if params.get("tenant") is None:
                return 400, {
                    "error": "tenant is required (SKETCH_TENANTS mode)",
                    "tenants": len(self._tenant_pubs)}
            tid = int(params["tenant"])  # malformed -> ValueError -> 400
            if not 0 <= tid < len(self._tenant_pubs):
                return 404, {"error": f"unknown tenant {tid}",
                             "tenants": len(self._tenant_pubs)}
            pub = self._tenant_pubs[tid]
            snapshot_fn, history_fn, windows_fn = (
                pub.get, pub.get_window, pub.windows)
        if params.get("window") is not None:
            wid = int(params["window"])  # malformed -> ValueError -> 400
            snap = history_fn(wid) if history_fn is not None else None
            if snap is None:
                return 404, {
                    "error": f"window {wid} not in the snapshot ring",
                    "windows": (windows_fn() if windows_fn is not None
                                else [])}
        else:
            snap = snapshot_fn()
        if snap is None:
            return 503, {"error": "no window published yet"}
        if route == "topk":
            return 200, core.topk_payload(snap, params.get("n", 100))
        if route == "churn":
            return 200, core.churn_payload(snap)
        if route == "cardinality":
            return 200, core.cardinality_payload(snap)
        if route == "victims":
            return 200, core.victims_payload(snap)
        # frequency
        if not params.get("src") or not params.get("dst"):
            return 400, {"error": "src and dst are required"}
        out = core.frequency_payload(
            snap, params["src"], params["dst"],
            int(params.get("src_port", 0)), int(params.get("dst_port", 0)),
            int(params.get("proto", 0)))
        if out is None:
            return 503, {"error": "no whole-width CM snapshot on this "
                                  "deployment (width-sharded mesh)"}
        return 200, out
