"""Agent-side query snapshot publisher (the federation poller pattern).

The tpu-sketch exporter publishes one snapshot per window roll (and,
optionally, mid-window refreshes) from the supervised timer thread. Readers
— the metrics server's `/query/*` routes — call :meth:`get` from arbitrary
HTTP threads. Torn reads are impossible by construction: a publish builds a
FRESH dict, stamps it with the next ``seq`` under the lock, and swaps the
whole reference; a reader holding a snapshot therefore always sees one
window's internally consistent view, and pollers detect ordering by
``(window, seq)`` exactly like the federation smoke's poller.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Optional


class SnapshotPublisher:
    """Thread-safe single-slot snapshot store with a publish sequence.

    `history > 0` additionally keeps a ring of the last N CLOSED-window
    snapshots (ROLL publishes only — mid-window refreshes are the live
    view, not history; a refresh and its eventual roll share a window id,
    and the roll's final snapshot is what the ring keeps). The ring powers
    the `/query/*?window=<id>` back-scroll: point-in-time reads of past
    windows, still snapshot-only — published dicts are immutable by the
    publish contract, so a ring entry is as torn-read-proof as the live
    slot. Evicted (or never-published) ids read as None → the routes
    answer 404."""

    def __init__(self, history: int = 0):
        self._lock = threading.Lock()
        self._snap: Optional[dict] = None
        self._seq = 0
        self._published = 0
        self._refreshes = 0
        self._history_cap = max(0, int(history))
        #: window id -> closed-window snapshot, oldest first
        self._history: "collections.OrderedDict[int, dict]" = \
            collections.OrderedDict()
        # age is measured from construction until the first publish so the
        # gauge reads "how stale is the queryable view" even before any
        # window closed
        self._last_pub_mono = time.monotonic()

    def publish(self, snap: dict, mid_window: bool = False) -> int:
        """Stamp `snap` with the next seq and swap it in. `snap` must be a
        fresh dict the caller never mutates afterwards."""
        with self._lock:
            self._seq += 1
            snap["seq"] = self._seq
            snap["mid_window"] = bool(mid_window)
            self._snap = snap
            self._published += 1
            if mid_window:
                self._refreshes += 1
            elif self._history_cap:
                wid = int(snap["window"])
                self._history.pop(wid, None)  # re-publish: move to newest
                self._history[wid] = snap
                while len(self._history) > self._history_cap:
                    self._history.popitem(last=False)
            self._last_pub_mono = time.monotonic()
            return self._seq

    def get(self) -> Optional[dict]:
        """The last published snapshot (None before the first publish)."""
        with self._lock:
            return self._snap

    def get_window(self, window: int) -> Optional[dict]:
        """Point-in-time read: the CLOSED-window snapshot for `window`, or
        None when it was evicted from the ring (or never rolled)."""
        with self._lock:
            return self._history.get(int(window))

    def windows(self) -> list[int]:
        """Window ids currently held by the back-scroll ring (oldest
        first) — the /query/status discovery surface."""
        with self._lock:
            return list(self._history.keys())

    def age_s(self) -> float:
        """Seconds since the last publish (since construction when none) —
        the `query_snapshot_age_seconds` gauge source."""
        with self._lock:
            return max(0.0, time.monotonic() - self._last_pub_mono)

    def stats(self) -> dict:
        with self._lock:
            return {
                "published": self._snap is not None,
                "seq": self._seq,
                "window": None if self._snap is None
                else self._snap["window"],
                "mid_window": bool(self._snap and self._snap["mid_window"]),
                "snapshots_published": self._published,
                "mid_window_refreshes": self._refreshes,
                "history_cap": self._history_cap,
                "history_windows": list(self._history.keys()),
                "snapshot_age_s": round(
                    max(0.0, time.monotonic() - self._last_pub_mono), 3),
            }
