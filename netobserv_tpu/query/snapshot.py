"""Agent-side query snapshot publisher (the federation poller pattern).

The tpu-sketch exporter publishes one snapshot per window roll (and,
optionally, mid-window refreshes) from the supervised timer thread. Readers
— the metrics server's `/query/*` routes — call :meth:`get` from arbitrary
HTTP threads. Torn reads are impossible by construction: a publish builds a
FRESH dict, stamps it with the next ``seq`` under the lock, and swaps the
whole reference; a reader holding a snapshot therefore always sees one
window's internally consistent view, and pollers detect ordering by
``(window, seq)`` exactly like the federation smoke's poller.
"""

from __future__ import annotations

import threading
import time
from typing import Optional


class SnapshotPublisher:
    """Thread-safe single-slot snapshot store with a publish sequence."""

    def __init__(self):
        self._lock = threading.Lock()
        self._snap: Optional[dict] = None
        self._seq = 0
        self._published = 0
        self._refreshes = 0
        # age is measured from construction until the first publish so the
        # gauge reads "how stale is the queryable view" even before any
        # window closed
        self._last_pub_mono = time.monotonic()

    def publish(self, snap: dict, mid_window: bool = False) -> int:
        """Stamp `snap` with the next seq and swap it in. `snap` must be a
        fresh dict the caller never mutates afterwards."""
        with self._lock:
            self._seq += 1
            snap["seq"] = self._seq
            snap["mid_window"] = bool(mid_window)
            self._snap = snap
            self._published += 1
            if mid_window:
                self._refreshes += 1
            self._last_pub_mono = time.monotonic()
            return self._seq

    def get(self) -> Optional[dict]:
        """The last published snapshot (None before the first publish)."""
        with self._lock:
            return self._snap

    def age_s(self) -> float:
        """Seconds since the last publish (since construction when none) —
        the `query_snapshot_age_seconds` gauge source."""
        with self._lock:
            return max(0.0, time.monotonic() - self._last_pub_mono)

    def stats(self) -> dict:
        with self._lock:
            return {
                "published": self._snap is not None,
                "seq": self._seq,
                "window": None if self._snap is None
                else self._snap["window"],
                "mid_window": bool(self._snap and self._snap["mid_window"]),
                "snapshots_published": self._published,
                "mid_window_refreshes": self._refreshes,
                "snapshot_age_s": round(
                    max(0.0, time.monotonic() - self._last_pub_mono), 3),
            }
