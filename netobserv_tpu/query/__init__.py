"""Shared sketch query plane (jax-free).

One query core serves BOTH tiers: the per-agent `/query/*` routes on the
metrics server read the snapshot the tpu-sketch exporter publishes at every
window roll (plus the optional `SKETCH_QUERY_REFRESH` mid-window refresh),
and the central aggregator's `/federation/*` routes (`federation/query.py`)
read the snapshot it publishes at each cluster roll. Every answer is pure
host-side numpy over an immutable snapshot dict — a query never dispatches
a device op, takes an ingest lock, or waits on anything the fold path
needs (the /debug/traces off-hot-path rules).
"""

from netobserv_tpu.query.core import (  # noqa: F401
    cardinality_payload, frequency_payload, topk_payload, victim_bucket_names,
    victims_payload,
)
from netobserv_tpu.query.routes import QueryRoutes  # noqa: F401
from netobserv_tpu.query.snapshot import SnapshotPublisher  # noqa: F401
