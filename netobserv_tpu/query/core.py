"""The ONE implementation of sketch query math, shared by the agent and
federation query surfaces (jax-free: numpy + the `ops/hashing` numpy twins
only, so it runs on accelerator-less hosts and never blocks on a device).

All functions operate on an immutable host-side **snapshot dict** published
at a window boundary:

- ``window``   int — the closed (or live, for a mid-window refresh) window id
- ``ts_ms``    int — publish wall time
- ``seq``      int — monotonically increasing publish sequence (the
                torn-read guard: snapshots swap as WHOLE dicts, so any
                reader holding one sees a single window's consistent view;
                pollers order responses by ``(window, seq)``)
- ``report``   dict — the rendered window report (`report_to_json` shape)
- ``cm_bytes``/``cm_pkts`` — f32[depth, width] Count-Min planes, or None
                when the deployment has no whole-width snapshot
                (width-sharded meshes)

The CM error-bar math (Cormode–Muthukrishnan) and the victim-bucket naming
(DST_BUCKET_SEED via `ops/hashing`, never inlined) live ONLY here.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def victim_bucket_names(heavy_words: np.ndarray, heavy: list[dict],
                        n_buckets: int) -> dict[int, list]:
    """Best-effort victim names: heavy-hitter addresses hashed into the same
    EWMA victim buckets the anomaly signals use (numpy hash twin — naming
    must never dispatch a device op). BOTH directions name a victim: its
    inbound traffic buckets via the dst words, its outbound (e.g. a flooded
    server still serving) via the src words — the device folds both into one
    bucket family (state.py src_sym/dst_h1 share DST_BUCKET_SEED). Spoofed
    floods' own flows rarely make the heavy table, but the victim's
    legitimate traffic does.

    `heavy_words` are the (n, KEY_WORDS) packed key words of exactly the
    rows rendered into `heavy` (same order)."""
    from netobserv_tpu.ops.hashing import DST_BUCKET_SEED, hash_words_np

    names: dict[int, list] = {}
    if not len(heavy):
        return names
    for cols, field in ((heavy_words[:, 4:8], "DstAddr"),
                        (heavy_words[:, 0:4], "SrcAddr")):
        buckets = hash_words_np(cols, seed=DST_BUCKET_SEED) & (n_buckets - 1)
        for j, b in enumerate(buckets):
            lst = names.setdefault(int(b), [])
            if len(lst) < 3 and heavy[j][field] not in lst:
                lst.append(heavy[j][field])
    return names


def _stamp(snap: dict, payload: dict) -> dict:
    """Prefix every snapshot-backed payload with the (window, ts_ms, seq)
    triple pollers order by."""
    return {"window": snap["window"], "ts_ms": snap["ts_ms"],
            "seq": snap.get("seq", 0), **payload}


def cm_error_bars(snap: dict) -> Optional[dict]:
    """The Cormode–Muthukrishnan overestimate bound of the snapshot's CM
    planes — THE error-bar math (shared by /query/frequency and
    /query/topk; the slot-table counts ARE CM point estimates, so the
    same bound applies to every rendered heavy hitter). None when the
    deployment has no whole-width CM snapshot (width-sharded meshes)."""
    cm = snap.get("cm_bytes")
    if cm is None:
        return None
    d, w = cm.shape
    eps = np.e / w
    return {
        "overestimate_bound_bytes": eps * float(np.sum(cm[0])),
        "confidence": 1.0 - float(np.exp(-d)),
    }


def topk_payload(snap: dict, n: int = 100) -> dict:
    n = max(1, min(int(n), 1024))
    payload = {"topk": snap["report"]["HeavyHitters"][:n]}
    bars = cm_error_bars(snap)
    if bars is not None:
        # every EstBytes (and churn count) is a CM point estimate: true
        # count <= estimate <= true + bound with the stated confidence —
        # the same bars /query/frequency renders, from the ONE helper
        payload.update(bars)
    return _stamp(snap, payload)


def churn_payload(snap: dict) -> dict:
    """Per-key heavy-hitter churn of the snapshot's window: ascents,
    descents, new-heavy entries, evicted keys and the table's eviction
    pressure, as rendered by the exporter under its configured
    SKETCH_CHURN_* gates (the one threshold truth). Counts carry the same
    CM error bars as /query/topk."""
    report = snap["report"]
    payload = {
        "ascents": report.get("FlowAscents", []),
        "descents": report.get("FlowDescents", []),
        "new_heavy": report.get("NewHeavyKeys", []),
        "evicted": report.get("EvictedKeys", []),
        "summary": report.get("HeavyChurn", {}),
    }
    bars = cm_error_bars(snap)
    if bars is not None:
        payload.update(bars)
    return _stamp(snap, payload)


def cardinality_payload(snap: dict) -> dict:
    report = snap["report"]
    return _stamp(snap, {
        "distinct_src_estimate": report["DistinctSrcEstimate"],
        "records": report["Records"],
        "bytes": report["Bytes"]})


def victims_payload(snap: dict) -> dict:
    # the signal -> report-key map is the alerting plane's SIGNAL_FIELDS
    # (one truth: /query/victims, the zoo's SIGNALS tuple and the default
    # alert rules can never disagree about what a signal is called)
    from netobserv_tpu.alerts.rules import SIGNAL_FIELDS
    report = snap["report"]
    return _stamp(snap, {sig: report[key]
                         for sig, key in SIGNAL_FIELDS.items()})


def frequency_payload(snap: dict, src: str, dst: str, src_port: int = 0,
                      dst_port: int = 0, proto: int = 0) -> Optional[dict]:
    """CM point query with error bars against the snapshot's merged planes —
    pure host numpy through the hashing twins. Returns None when the
    snapshot carries no whole-width CM planes (width-sharded mesh)."""
    cm = snap.get("cm_bytes")
    cm_pkts = snap.get("cm_pkts")
    if cm is None or cm_pkts is None:
        return None
    from netobserv_tpu.model import binfmt
    from netobserv_tpu.model.columnar import pack_key_words
    from netobserv_tpu.model.flow import FlowKey
    from netobserv_tpu.ops.hashing import base_hashes_multi_np

    fk = FlowKey.make(src, dst, src_port, dst_port, proto)
    karr = np.zeros(1, binfmt.FLOW_KEY_DTYPE)
    karr["src_ip"][0] = np.frombuffer(fk.src_ip, np.uint8)
    karr["dst_ip"][0] = np.frombuffer(fk.dst_ip, np.uint8)
    karr["src_port"] = src_port
    karr["dst_port"] = dst_port
    karr["proto"] = proto
    words = pack_key_words(karr)
    h = base_hashes_multi_np(words)
    d, w = cm.shape
    with np.errstate(over="ignore"):
        idx = (h["h1"][0] + np.arange(d, dtype=np.uint32) * h["h2"][0]) \
            & np.uint32(w - 1)
    est_bytes = float(np.min(cm[np.arange(d), idx]))
    est_pkts = float(np.min(cm_pkts[np.arange(d), idx]))
    # Cormode–Muthukrishnan: overestimate <= (e/w)*N with prob 1-e^-d
    n_bytes = float(np.sum(cm[0]))
    n_pkts = float(np.sum(cm_pkts[0]))
    eps = np.e / w
    return _stamp(snap, {
        "est_bytes": est_bytes,
        "est_packets": est_pkts,
        "overestimate_bound_bytes": eps * n_bytes,
        "overestimate_bound_packets": eps * n_pkts,
        "confidence": 1.0 - float(np.exp(-d)),
    })
