"""TLS metadata decoding (reference analog: `pkg/model/tls_types.go`)."""

from __future__ import annotations

# TLS record-type bits set by the passive TLS tracker (one bit per content type
# seen on the connection).
TLS_TYPE_CHANGE_CIPHER_SPEC = 0x01
TLS_TYPE_ALERT = 0x02
TLS_TYPE_HANDSHAKE = 0x04
TLS_TYPE_APPLICATION_DATA = 0x08
TLS_TYPE_HEARTBEAT = 0x10

_VERSION_NAMES = {
    0x0300: "SSLv3",
    0x0301: "TLS1.0",
    0x0302: "TLS1.1",
    0x0303: "TLS1.2",
    0x0304: "TLS1.3",
}

# a small subset of IANA cipher-suite names; unknown suites render as hex
_CIPHER_NAMES = {
    0x1301: "TLS_AES_128_GCM_SHA256",
    0x1302: "TLS_AES_256_GCM_SHA384",
    0x1303: "TLS_CHACHA20_POLY1305_SHA256",
    0xC02B: "TLS_ECDHE_ECDSA_WITH_AES_128_GCM_SHA256",
    0xC02C: "TLS_ECDHE_ECDSA_WITH_AES_256_GCM_SHA384",
    0xC02F: "TLS_ECDHE_RSA_WITH_AES_128_GCM_SHA256",
    0xC030: "TLS_ECDHE_RSA_WITH_AES_256_GCM_SHA384",
}

_GROUP_NAMES = {
    0x0017: "secp256r1",
    0x0018: "secp384r1",
    0x0019: "secp521r1",
    0x001D: "x25519",
    0x001E: "x448",
    0x0100: "ffdhe2048",
    0x11EC: "X25519MLKEM768",
}


def tls_version_name(version: int) -> str:
    return _VERSION_NAMES.get(version, f"0x{version:04x}" if version else "")


def cipher_suite_name(suite: int) -> str:
    return _CIPHER_NAMES.get(suite, f"0x{suite:04x}" if suite else "")


def key_share_name(group: int) -> str:
    return _GROUP_NAMES.get(group, f"0x{group:04x}" if group else "")


def tls_types_names(bits: int) -> list[str]:
    names = []
    for bit, name in (
        (TLS_TYPE_CHANGE_CIPHER_SPEC, "ChangeCipherSpec"),
        (TLS_TYPE_ALERT, "Alert"),
        (TLS_TYPE_HANDSHAKE, "Handshake"),
        (TLS_TYPE_APPLICATION_DATA, "ApplicationData"),
        (TLS_TYPE_HEARTBEAT, "Heartbeat"),
    ):
        if bits & bit:
            names.append(name)
    return names
