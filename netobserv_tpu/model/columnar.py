"""Fixed-shape columnar flow batches — the TPU feed format.

This is the Accounter-equivalent that builds arrays instead of a hashmap
(SURVEY.md §7.2 step 4). Every batch has a static shape `(batch_size,)` per column
with a validity mask, so the jitted sketch-ingest step never retraces.

Key packing: the 37-byte flow identity is packed into `KEY_WORDS`=10 little-endian
uint32 lanes (4 src words, 4 dst words, ports word, proto/icmp word) — byte-wise
hashing reformulated as wide integer vector math (SURVEY.md §7.3).
"""

from __future__ import annotations

from dataclasses import dataclass, fields as dfields
from typing import Iterable, Optional

import numpy as np

from netobserv_tpu.model import binfmt
from netobserv_tpu.model.record import Record

KEY_WORDS = 10

_COLUMNS: list[tuple[str, np.dtype, tuple]] = [
    ("keys", np.uint32, (KEY_WORDS,)),
    ("bytes", np.uint64, ()),
    ("packets", np.uint32, ()),
    ("tcp_flags", np.uint32, ()),
    ("eth_protocol", np.uint32, ()),
    ("direction", np.uint32, ()),
    ("if_index", np.uint32, ()),
    ("dscp", np.uint32, ()),
    ("sampling", np.uint32, ()),
    ("first_seen_ns", np.uint64, ()),
    ("last_seen_ns", np.uint64, ()),
    ("rtt_us", np.uint32, ()),
    ("dns_latency_us", np.uint32, ()),
    ("dns_id", np.uint32, ()),
    ("dns_flags", np.uint32, ()),
    ("dns_errno", np.uint32, ()),
    ("drop_bytes", np.uint32, ()),
    ("drop_packets", np.uint32, ()),
    ("valid", np.bool_, ()),
]


def pack_key_words(key_arr: np.ndarray) -> np.ndarray:
    """Pack a structured FLOW_KEY array (N,) into uint32 words (N, KEY_WORDS)."""
    n = len(key_arr)
    out = np.zeros((n, KEY_WORDS), dtype=np.uint32)
    if n == 0:
        return out
    src = np.ascontiguousarray(key_arr["src_ip"]).view(np.uint32).reshape(n, 4)
    dst = np.ascontiguousarray(key_arr["dst_ip"]).view(np.uint32).reshape(n, 4)
    out[:, 0:4] = src
    out[:, 4:8] = dst
    out[:, 8] = (key_arr["src_port"].astype(np.uint32) << np.uint32(16)) | \
        key_arr["dst_port"].astype(np.uint32)
    out[:, 9] = (key_arr["proto"].astype(np.uint32) << np.uint32(16)) | \
        (key_arr["icmp_type"].astype(np.uint32) << np.uint32(8)) | \
        key_arr["icmp_code"].astype(np.uint32)
    return out


def unpack_key_words(words: np.ndarray) -> np.ndarray:
    """Inverse of pack_key_words — back to a structured FLOW_KEY array."""
    n = len(words)
    out = np.zeros(n, dtype=binfmt.FLOW_KEY_DTYPE)
    if n == 0:
        return out
    out["src_ip"] = np.ascontiguousarray(words[:, 0:4]).view(np.uint8).reshape(n, 16)
    out["dst_ip"] = np.ascontiguousarray(words[:, 4:8]).view(np.uint8).reshape(n, 16)
    out["src_port"] = (words[:, 8] >> np.uint32(16)).astype(np.uint16)
    out["dst_port"] = (words[:, 8] & np.uint32(0xFFFF)).astype(np.uint16)
    out["proto"] = ((words[:, 9] >> np.uint32(16)) & np.uint32(0xFF)).astype(np.uint8)
    out["icmp_type"] = ((words[:, 9] >> np.uint32(8)) & np.uint32(0xFF)).astype(np.uint8)
    out["icmp_code"] = (words[:, 9] & np.uint32(0xFF)).astype(np.uint8)
    return out


@dataclass
class FlowBatch:
    """One fixed-shape columnar batch of flows.

    `valid[i]` marks live rows; padding rows are all-zero and must be masked by
    every consumer. `epoch_wall_ns - epoch_mono_ns` converts the mono timestamps
    to wall clock (clock reconstruction happens on-host; SURVEY.md §7.3).
    """

    keys: np.ndarray
    bytes: np.ndarray
    packets: np.ndarray
    tcp_flags: np.ndarray
    eth_protocol: np.ndarray
    direction: np.ndarray
    if_index: np.ndarray
    dscp: np.ndarray
    sampling: np.ndarray
    first_seen_ns: np.ndarray
    last_seen_ns: np.ndarray
    rtt_us: np.ndarray
    dns_latency_us: np.ndarray
    dns_id: np.ndarray
    dns_flags: np.ndarray
    dns_errno: np.ndarray
    drop_bytes: np.ndarray
    drop_packets: np.ndarray
    valid: np.ndarray
    epoch_mono_ns: int = 0
    epoch_wall_ns: int = 0

    @property
    def size(self) -> int:
        return len(self.valid)

    @property
    def n_valid(self) -> int:
        return int(self.valid.sum())

    @classmethod
    def empty(cls, batch_size: int) -> "FlowBatch":
        cols = {name: np.zeros((batch_size,) + shape, dtype=dt)
                for name, dt, shape in _COLUMNS}
        return cls(**cols)

    @classmethod
    def from_events(cls, events: np.ndarray, batch_size: Optional[int] = None,
                    extra: Optional[np.ndarray] = None,
                    dns: Optional[np.ndarray] = None,
                    drops: Optional[np.ndarray] = None) -> "FlowBatch":
        """Build a batch from a decoded FLOW_EVENT structured array.

        `extra`/`dns`/`drops` are optional parallel arrays of the per-feature
        record dtypes (already merged per flow, aligned with `events`).
        """
        n = len(events)
        batch_size = batch_size or n
        if n > batch_size:
            raise ValueError(f"{n} events exceed batch size {batch_size}")
        b = cls.empty(batch_size)
        if n == 0:
            return b
        stats = events["stats"]
        b.keys[:n] = pack_key_words(events["key"])
        b.bytes[:n] = stats["bytes"]
        b.packets[:n] = stats["packets"]
        b.tcp_flags[:n] = stats["tcp_flags"]
        b.eth_protocol[:n] = stats["eth_protocol"]
        b.direction[:n] = stats["direction_first"]
        b.if_index[:n] = stats["if_index_first"]
        b.dscp[:n] = stats["dscp"]
        b.sampling[:n] = stats["sampling"]
        b.first_seen_ns[:n] = stats["first_seen_ns"]
        b.last_seen_ns[:n] = stats["last_seen_ns"]
        overlay_features(b, n, extra=extra, dns=dns, drops=drops)
        b.valid[:n] = True
        return b

    @classmethod
    def from_records(cls, records: Iterable[Record],
                     batch_size: Optional[int] = None) -> "FlowBatch":
        recs = list(records)
        n = len(recs)
        batch_size = batch_size or max(n, 1)
        if n > batch_size:
            raise ValueError(f"{n} records exceed batch size {batch_size}")
        b = cls.empty(batch_size)
        key_arr = np.zeros(n, dtype=binfmt.FLOW_KEY_DTYPE)
        for i, r in enumerate(recs):
            key_arr[i]["src_ip"] = np.frombuffer(r.key.src_ip, dtype=np.uint8)
            key_arr[i]["dst_ip"] = np.frombuffer(r.key.dst_ip, dtype=np.uint8)
            key_arr[i]["src_port"] = r.key.src_port
            key_arr[i]["dst_port"] = r.key.dst_port
            key_arr[i]["proto"] = r.key.proto
            key_arr[i]["icmp_type"] = r.key.icmp_type
            key_arr[i]["icmp_code"] = r.key.icmp_code
            b.bytes[i] = r.bytes_
            b.packets[i] = r.packets
            b.tcp_flags[i] = r.tcp_flags
            b.eth_protocol[i] = r.eth_protocol
            b.direction[i] = r.direction
            b.if_index[i] = r.if_index
            b.dscp[i] = r.dscp
            b.sampling[i] = r.sampling
            b.first_seen_ns[i] = r.mono_start_ns
            b.last_seen_ns[i] = r.mono_end_ns
            b.rtt_us[i] = r.features.rtt_ns // 1000
            b.dns_latency_us[i] = r.features.dns_latency_ns // 1000
            b.dns_id[i] = r.features.dns_id
            b.dns_flags[i] = r.features.dns_flags
            b.dns_errno[i] = r.features.dns_errno
            b.drop_bytes[i] = r.features.drop_bytes
            b.drop_packets[i] = r.features.drop_packets
        if n:
            b.keys[:n] = pack_key_words(key_arr)
            b.valid[:n] = True
        return b

    def columns(self) -> dict[str, np.ndarray]:
        return {f.name: getattr(self, f.name) for f in dfields(self)
                if f.name not in ("epoch_mono_ns", "epoch_wall_ns")}


def overlay_features(b: FlowBatch, n: int,
                     extra: Optional[np.ndarray] = None,
                     dns: Optional[np.ndarray] = None,
                     drops: Optional[np.ndarray] = None) -> None:
    """Overlay per-feature record arrays onto the first n rows of a batch.

    The single definition shared by FlowBatch.from_events, the native
    flowpack pack path, and the tpu-sketch columnar fold — so the feature
    column set can never drift between paths."""
    if extra is not None and len(extra):
        b.rtt_us[:n] = extra["rtt_ns"][:n] // 1000
    if dns is not None and len(dns):
        b.dns_latency_us[:n] = dns["latency_ns"][:n] // 1000
        b.dns_id[:n] = dns["dns_id"][:n]
        b.dns_flags[:n] = dns["dns_flags"][:n]
        b.dns_errno[:n] = dns["errno"][:n]
    if drops is not None and len(drops):
        b.drop_bytes[:n] = drops["bytes"][:n]
        b.drop_packets[:n] = drops["packets"][:n]


def exact_aggregate(batches: Iterable[FlowBatch]) -> dict[bytes, tuple[int, int]]:
    """Exact per-key (bytes, packets) aggregation — the CPU oracle.

    This is the reference's `Accounter`/hashmap aggregation semantics
    (`pkg/flow/account.go:204-246`) that sketch outputs are scored against
    (BASELINE.md acceptance bound: <1% heavy-hitter recall loss).
    """
    acc: dict[bytes, tuple[int, int]] = {}
    for b in batches:
        idx = np.nonzero(b.valid)[0]
        if len(idx) == 0:
            continue
        kb = np.ascontiguousarray(b.keys[idx]).view(np.uint8).reshape(len(idx), -1)
        for i, row in zip(idx, kb):
            k = row.tobytes()
            by, pk = acc.get(k, (0, 0))
            acc[k] = (by + int(b.bytes[i]), pk + int(b.packets[i]))
    return acc
