"""Data model (cross-cutting layer X1 in SURVEY.md §1).

- `binfmt` — numpy structured dtypes that pin the byte layout of the datapath's C
  structs (the C side is `netobserv_tpu/datapath/bpf/records.h`; parity is enforced by
  `tests/test_layout_parity.py` which compiles the header with g++ and compares
  offsets). Reference analog: `pkg/model/record.go:63` + `bpf/types.h:209-215`.
- `flow` — enums and Python-facing key/stats views.
- `accumulate` — per-feature merge semantics (the CPU oracle the TPU sketches are
  validated against). Reference analog: `pkg/model/flow_content.go:28-197`.
- `columnar` — fixed-shape columnar FlowBatch fed to the TPU analytics plane.
- `record` — enriched flow record handed to exporters.
"""

from netobserv_tpu.model.flow import (  # noqa: F401
    Direction, TcpFlags, GlobalCounter, FlowKey,
)
from netobserv_tpu.model.record import Record  # noqa: F401
from netobserv_tpu.model.columnar import FlowBatch, KEY_WORDS  # noqa: F401
