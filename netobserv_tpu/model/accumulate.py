"""Per-feature merge semantics — the single source of truth for "how two partial
observations of the same flow combine".

Reference analog: `pkg/model/flow_content.go:24-197`. These rules are applied in
three places and must agree everywhere (SURVEY.md §7.3 "merge semantics fidelity"):
1. host-side merge of per-CPU feature-map partials at eviction,
2. userspace re-aggregation of ringbuffer singles (Accounter),
3. on-device sketch folds (bytes/packets add, RTT max, DNS-latency max).

All functions mutate `dst` (a numpy structured scalar or 1-element view) in place,
merging `src` into it. Semantics follow the reference function by function; tests
in `tests/test_model.py` pin them.
"""

from __future__ import annotations

import numpy as np

U16_MAX = np.uint64(0xFFFF)

# no_flow_stats.misc_flags bits (records.h NO_MISC_SSL_MISMATCH)
MISC_SSL_MISMATCH = 0x01
U32_MAX = np.uint64(0xFFFF_FFFF)
U64_MAX = np.uint64(0xFFFF_FFFF_FFFF_FFFF)


def _sat_add(a, b, cap) -> int:
    s = int(a) + int(b)
    return int(cap) if s > int(cap) else s


def _merge_times(dst, src) -> None:
    """first_seen = min (zero means unset), last_seen = max."""
    s_first, s_last = int(src["first_seen_ns"]), int(src["last_seen_ns"])
    d_first = int(dst["first_seen_ns"])
    if d_first == 0 or (s_first != 0 and s_first < d_first):
        dst["first_seen_ns"] = s_first
    if int(dst["last_seen_ns"]) < s_last:
        dst["last_seen_ns"] = s_last


def accumulate_base(dst, src) -> None:
    """Merge two base flow_stats partials (reference: AccumulateBase,
    `flow_content.go:28-63`): add bytes/packets, OR flags, min/max times,
    latest-non-zero wins for eth_protocol/dscp/sampling, MACs fill if unset."""
    dst_was_empty = int(dst["first_seen_ns"]) == 0 and int(dst["packets"]) == 0
    _merge_times(dst, src)
    dst["bytes"] = _sat_add(dst["bytes"], src["bytes"], U64_MAX)
    dst["packets"] = _sat_add(dst["packets"], src["packets"], U32_MAX)
    dst["tcp_flags"] = int(dst["tcp_flags"]) | int(src["tcp_flags"])
    if int(src["eth_protocol"]) != 0:
        dst["eth_protocol"] = src["eth_protocol"]
    if int(src["dscp"]) != 0:
        dst["dscp"] = src["dscp"]
    if int(src["sampling"]) != 0:
        dst["sampling"] = src["sampling"]
    if not np.any(dst["src_mac"]):
        dst["src_mac"] = src["src_mac"]
    if not np.any(dst["dst_mac"]):
        dst["dst_mac"] = src["dst_mac"]
    if int(src["errno_fallback"]) != 0:
        dst["errno_fallback"] = src["errno_fallback"]
    # first-seen identity fields: keep dst's unless dst was a fresh zero entry
    if dst_was_empty:
        dst["if_index_first"] = src["if_index_first"]
        dst["direction_first"] = src["direction_first"]
    # ssl_version: first non-zero observation wins; a conflicting later
    # version raises the mismatch flag instead of overwriting (same rule the
    # kernel applies at entry time, reference bpf/flows.c:111-118)
    if int(src["ssl_version"]) != 0:
        if int(dst["ssl_version"]) == 0:
            dst["ssl_version"] = src["ssl_version"]
        elif int(dst["ssl_version"]) != int(src["ssl_version"]):
            dst["misc_flags"] = int(dst["misc_flags"]) | MISC_SSL_MISMATCH
    for fld in ("tls_cipher_suite", "tls_key_share"):
        if int(src[fld]) != 0:
            dst[fld] = src[fld]
    dst["tls_types"] = int(dst["tls_types"]) | int(src["tls_types"])
    dst["misc_flags"] = int(dst["misc_flags"]) | int(src["misc_flags"])
    # observed-interfaces dedup (bounded at MAX_OBSERVED_INTERFACES; the
    # datapath's lock-free slot reservation can leave the counter
    # transiently above capacity — clamp before indexing)
    cap = len(dst["observed_intf"])
    n_dst = min(int(dst["n_observed_intf"]), cap)
    for j in range(min(int(src["n_observed_intf"]), cap)):
        oi, od = int(src["observed_intf"][j]), int(src["observed_direction"][j])
        seen = any(
            int(dst["observed_intf"][i]) == oi
            and int(dst["observed_direction"][i]) == od
            for i in range(n_dst))
        if not seen and n_dst < cap:
            dst["observed_intf"][n_dst] = oi
            dst["observed_direction"][n_dst] = od
            n_dst += 1
    dst["n_observed_intf"] = n_dst


def accumulate_dns(dst, src) -> None:
    """DNS: max latency wins, flags OR, latest id/errno observation adopted
    (reference: AccumulateDNS, `flow_content.go:76-96` — errno is assigned from
    the incoming partial even when it clears a previous error)."""
    _merge_times(dst, src)
    dst["dns_flags"] = int(dst["dns_flags"]) | int(src["dns_flags"])
    if int(src["dns_id"]) != 0:
        dst["dns_id"] = src["dns_id"]
    if int(dst["errno"]) != int(src["errno"]):
        dst["errno"] = src["errno"]
    if int(src["latency_ns"]) > int(dst["latency_ns"]):
        dst["latency_ns"] = src["latency_ns"]
    if bytes(src["name"]).rstrip(b"\x00"):
        dst["name"] = src["name"]


def accumulate_drops(dst, src) -> None:
    """Packet drops: saturating u16 adds, flags OR, latest non-zero cause/state
    win (reference: AccumulateDrops, `flow_content.go:98-117`)."""
    _merge_times(dst, src)
    dst["bytes"] = _sat_add(dst["bytes"], src["bytes"], U16_MAX)
    dst["packets"] = _sat_add(dst["packets"], src["packets"], U16_MAX)
    dst["latest_flags"] = int(dst["latest_flags"]) | int(src["latest_flags"])
    if int(src["latest_cause"]) != 0:
        dst["latest_cause"] = src["latest_cause"]
    if int(src["latest_state"]) != 0:
        dst["latest_state"] = src["latest_state"]


def accumulate_extra(dst, src) -> None:
    """RTT max-merge + IPsec highest-return-code priority (reference:
    AccumulateAdditional, `flow_content.go:154-178`)."""
    _merge_times(dst, src)
    if int(src["rtt_ns"]) > int(dst["rtt_ns"]):
        dst["rtt_ns"] = src["rtt_ns"]
    if int(dst["ipsec_ret"]) < int(src["ipsec_ret"]):
        dst["ipsec_ret"] = src["ipsec_ret"]
        dst["ipsec_encrypted"] = src["ipsec_encrypted"]
    elif int(dst["ipsec_ret"]) == int(src["ipsec_ret"]) and int(src["ipsec_encrypted"]):
        dst["ipsec_encrypted"] = src["ipsec_encrypted"]


def accumulate_xlat(dst, src) -> None:
    """NAT translation: a complete (both-endpoints) observation replaces
    (reference: AccumulateXlat, `flow_content.go:139-152`)."""
    _merge_times(dst, src)
    if np.any(src["src_ip"]) and np.any(src["dst_ip"]):
        for fld in ("src_ip", "dst_ip", "src_port", "dst_port", "zone_id"):
            dst[fld] = src[fld]


def accumulate_network_events(dst, src) -> None:
    """Network events: dedup append into a wrapping ring of MAX_NETWORK_EVENTS
    (reference: AccumulateNetworkEvents, `flow_content.go:119-137`)."""
    _merge_times(dst, src)
    idx = int(dst["n_events"]) % dst["events"].shape[0]
    cap = dst["events"].shape[0]
    for j in range(src["events"].shape[0]):
        ev = src["events"][j]
        if int(src["packets"][j]) == 0:
            continue
        dup = any(np.array_equal(dst["events"][i], ev) for i in range(cap))
        if not dup:
            dst["events"][idx] = ev
            dst["bytes"][idx] = _sat_add(dst["bytes"][idx], src["bytes"][j], U16_MAX)
            dst["packets"][idx] = _sat_add(dst["packets"][idx], src["packets"][j], U16_MAX)
            idx = (idx + 1) % cap
    dst["n_events"] = idx


def accumulate_quic(dst, src) -> None:
    """QUIC: max version wins, header-seen flags max/OR (reference:
    AccumulateQuic, `flow_content.go:179-197`)."""
    _merge_times(dst, src)
    if int(src["version"]) > int(dst["version"]):
        dst["version"] = src["version"]
    if int(dst["seen_long_hdr"]) < int(src["seen_long_hdr"]):
        dst["seen_long_hdr"] = src["seen_long_hdr"]
    if int(dst["seen_short_hdr"]) < int(src["seen_short_hdr"]):
        dst["seen_short_hdr"] = src["seen_short_hdr"]


def merge_percpu(values: np.ndarray, accumulate_fn) -> np.ndarray:
    """Merge per-CPU partial records (shape (n_cpu,) structured) into one."""
    out = values[0].copy()
    if "n_observed_intf" in (out.dtype.names or ()):
        # the datapath's lock-free slot reservation can leave the counter
        # transiently above capacity — clamp exactly like the native twin
        # (flowpack.cc fp_merge_stats), including the n_cpu==1 fast path
        cap = len(out["observed_intf"])
        if int(out["n_observed_intf"]) > cap:
            out["n_observed_intf"] = cap
    for i in range(1, len(values)):
        accumulate_fn(out, values[i])
    return out


# ---------------------------------------------------------------------------
# Columnar per-CPU merge: the whole-drain twins of the per-record functions
# above. Each takes `values` of shape (n_keys, n_cpus) and returns (n_keys,)
# merged records, bit-exact against running the matching `accumulate_*`
# sequentially per key (pinned by tests/test_evict_columnar.py, alongside the
# native fp_merge_*_batch twins) — the merge-semantics contract now has FOUR
# pinned forms (per-record python, per-key native, columnar python, batch
# native) and semantics change in all or none.
# ---------------------------------------------------------------------------

def _col_times(values: np.ndarray, out: np.ndarray) -> None:
    """first_seen = min over non-zero (zero means unset), last_seen = max."""
    first = values["first_seen_ns"]
    masked = np.where(first == np.uint64(0), U64_MAX, first)
    fmin = masked.min(axis=1)
    out["first_seen_ns"] = np.where(fmin == U64_MAX, np.uint64(0), fmin)
    out["last_seen_ns"] = values["last_seen_ns"].max(axis=1)


def _col_latest_nonzero(field: np.ndarray) -> np.ndarray:
    """(n, c) -> (n,): last non-zero value per row, else column 0's value —
    the vectorized 'latest non-zero observation wins' rule."""
    n, c = field.shape
    nz = field != 0
    has = nz.any(axis=1)
    last = c - 1 - nz[:, ::-1].argmax(axis=1)
    idx = np.where(has, last, 0)
    return field[np.arange(n), idx]


def _col_observed_intf(values: np.ndarray, out: np.ndarray) -> None:
    """Observed-interface dedup-append, vectorized over keys. Candidate
    positions are walked sequentially ((n_cpus-1) * cap iterations, each a
    whole-axis op over the keys that have any src entries at all), because
    each append changes what later candidates dedup against."""
    n, c = values.shape
    cap = values.dtype["observed_intf"].shape[0]
    src_n = np.minimum(values["n_observed_intf"][:, 1:], cap)
    active = np.nonzero(src_n.any(axis=1))[0]
    if not len(active):
        return
    v = values[active]
    m = len(active)
    cnt = np.minimum(v["n_observed_intf"][:, 0], cap).astype(np.int64)
    d_int = v["observed_intf"][:, 0].copy()
    d_dir = v["observed_direction"][:, 0].copy()
    slot = np.arange(cap)[None, :]
    for ci in range(1, c):
        s_cnt = np.minimum(v["n_observed_intf"][:, ci], cap)
        for j in range(cap):
            valid = j < s_cnt
            if not valid.any():
                continue
            cint = v["observed_intf"][:, ci, j]
            cdir = v["observed_direction"][:, ci, j]
            # dedup only against the OCCUPIED dst slots (i < n_dst)
            seen = ((d_int == cint[:, None]) & (d_dir == cdir[:, None])
                    & (slot < cnt[:, None])).any(axis=1)
            rows = np.nonzero(valid & ~seen & (cnt < cap))[0]
            if len(rows):
                d_int[rows, cnt[rows]] = cint[rows]
                d_dir[rows, cnt[rows]] = cdir[rows]
                cnt[rows] += 1
    out["observed_intf"][active] = d_int
    out["observed_direction"][active] = d_dir
    out["n_observed_intf"][active] = cnt


def merge_base_columnar(values: np.ndarray) -> np.ndarray:
    """Columnar twin of accumulate_base over (n_keys, n_cpus) flow_stats."""
    n, c = values.shape
    out = values[:, 0].copy()
    cap = values.dtype["observed_intf"].shape[0]
    np.minimum(out["n_observed_intf"], cap, out=out["n_observed_intf"])
    if c == 1 or n == 0:
        return out
    ar = np.arange(n)
    _col_times(values, out)
    # bytes: saturating u64 — cumulative clamp per CPU column (8-ish columns)
    # mirrors the native wrap-detect exactly; a plain sum could overflow
    acc = values["bytes"][:, 0].astype(np.uint64)
    for j in range(1, c):
        s = acc + values["bytes"][:, j]
        acc = np.where(s < acc, U64_MAX, s)
    out["bytes"] = acc
    psum = values["packets"].astype(np.uint64).sum(axis=1)
    out["packets"] = np.minimum(psum, U32_MAX).astype(np.uint32)
    out["tcp_flags"] = np.bitwise_or.reduce(values["tcp_flags"], axis=1)
    for fld in ("eth_protocol", "dscp", "sampling", "errno_fallback",
                "tls_cipher_suite", "tls_key_share"):
        out[fld] = _col_latest_nonzero(values[fld])
    out["tls_types"] = np.bitwise_or.reduce(values["tls_types"], axis=1)
    # MACs fill-if-unset: the first column (in merge order) with any non-zero
    # byte wins; all-zero keeps column 0's zeros
    for fld in ("src_mac", "dst_mac"):
        first = values[fld].any(axis=2).argmax(axis=1)
        out[fld] = values[fld][ar, first]
    # first-seen identity: adopted from each src while the accumulated dst is
    # still an all-empty entry -> the column at (first non-empty index), or
    # the last column when every partial is empty
    nonempty = (values["first_seen_ns"] != 0) | (values["packets"] != 0)
    j = np.where(nonempty.any(axis=1), nonempty.argmax(axis=1), c - 1)
    j = np.minimum(j, c - 1)
    out["if_index_first"] = values["if_index_first"][ar, j]
    out["direction_first"] = values["direction_first"][ar, j]
    # ssl_version: first non-zero wins; any DIFFERENT later non-zero raises
    # the mismatch flag (kernel entry rule)
    sv = values["ssl_version"]
    nzv = sv != 0
    firstv = sv[ar, nzv.argmax(axis=1)]
    out["ssl_version"] = np.where(nzv.any(axis=1), firstv, 0)
    mismatch = (nzv & (sv != firstv[:, None])).any(axis=1)
    out["misc_flags"] = (np.bitwise_or.reduce(values["misc_flags"], axis=1)
                         | np.where(mismatch, np.uint8(MISC_SSL_MISMATCH),
                                    np.uint8(0)))
    _col_observed_intf(values, out)
    return out


def merge_dns_columnar(values: np.ndarray) -> np.ndarray:
    n, c = values.shape
    out = values[:, 0].copy()
    if c == 1 or n == 0:
        return out
    _col_times(values, out)
    out["dns_flags"] = np.bitwise_or.reduce(values["dns_flags"], axis=1)
    out["dns_id"] = _col_latest_nonzero(values["dns_id"])
    # errno adopts EVERY incoming partial (even clearing): last column wins
    out["errno"] = values["errno"][:, -1]
    out["latency_ns"] = values["latency_ns"].max(axis=1)
    names = values["name"]
    nz = names != b""  # S-dtype: trailing-NUL-stripped compare (python rule)
    has = nz.any(axis=1)
    last = c - 1 - nz[:, ::-1].argmax(axis=1)
    out["name"] = names[np.arange(n), np.where(has, last, 0)]
    return out


def merge_drops_columnar(values: np.ndarray) -> np.ndarray:
    n, c = values.shape
    out = values[:, 0].copy()
    if c == 1 or n == 0:
        return out
    _col_times(values, out)
    for fld in ("bytes", "packets"):
        s = values[fld].astype(np.uint64).sum(axis=1)
        out[fld] = np.minimum(s, U16_MAX).astype(np.uint16)
    out["latest_flags"] = np.bitwise_or.reduce(values["latest_flags"], axis=1)
    out["latest_cause"] = _col_latest_nonzero(values["latest_cause"])
    out["latest_state"] = _col_latest_nonzero(values["latest_state"])
    return out


def merge_extra_columnar(values: np.ndarray) -> np.ndarray:
    n, c = values.shape
    out = values[:, 0].copy()
    if c == 1 or n == 0:
        return out
    ar = np.arange(n)
    _col_times(values, out)
    out["rtt_ns"] = values["rtt_ns"].max(axis=1)
    # ipsec: highest return code wins its encrypted flag; among columns tied
    # at the max, a later non-zero encrypted overrides (sequential adoption)
    ret = values["ipsec_ret"]
    enc = values["ipsec_encrypted"]
    rstar = ret.max(axis=1)
    elig = ret == rstar[:, None]
    encnz = elig & (enc != 0)
    has = encnz.any(axis=1)
    last_nz = c - 1 - encnz[:, ::-1].argmax(axis=1)
    idx = np.where(has, last_nz, elig.argmax(axis=1))
    out["ipsec_ret"] = rstar
    out["ipsec_encrypted"] = enc[ar, idx]
    return out


def merge_xlat_columnar(values: np.ndarray) -> np.ndarray:
    n, c = values.shape
    out = values[:, 0].copy()
    if c == 1 or n == 0:
        return out
    _col_times(values, out)
    complete = values["src_ip"].any(axis=2) & values["dst_ip"].any(axis=2)
    has = complete.any(axis=1)
    last = c - 1 - complete[:, ::-1].argmax(axis=1)
    idx = np.where(has, last, 0)
    ar = np.arange(n)
    for fld in ("src_ip", "dst_ip", "src_port", "dst_port", "zone_id"):
        out[fld] = values[fld][ar, idx]
    return out


def merge_quic_columnar(values: np.ndarray) -> np.ndarray:
    n, c = values.shape
    out = values[:, 0].copy()
    if c == 1 or n == 0:
        return out
    _col_times(values, out)
    out["version"] = values["version"].max(axis=1)
    out["seen_long_hdr"] = values["seen_long_hdr"].max(axis=1)
    out["seen_short_hdr"] = values["seen_short_hdr"].max(axis=1)
    return out


def merge_nevents_columnar(values: np.ndarray) -> np.ndarray:
    """Columnar twin of accumulate_network_events: dedup-append into each
    key's wrapping ring. The ring evolves entry by entry (each append changes
    the dedup set AND the cursor), so candidates are walked sequentially —
    (n_cpus-1) * MAX_NETWORK_EVENTS iterations, each vectorized over keys."""
    n, c = values.shape
    out = values[:, 0].copy()
    if c == 1 or n == 0:
        return out
    _col_times(values, out)
    cap = values.dtype["events"].shape[0]
    idx = (out["n_events"].astype(np.int64)) % cap
    for ci in range(1, c):
        for j in range(cap):
            act = values["packets"][:, ci, j] != 0
            if not act.any():
                continue
            cand = values["events"][:, ci, j]                  # (n, md)
            dup = (out["events"] == cand[:, None, :]).all(axis=2).any(axis=1)
            rows = np.nonzero(act & ~dup)[0]
            if len(rows):
                ri = idx[rows]
                out["events"][rows, ri] = cand[rows]
                nb = (out["bytes"][rows, ri].astype(np.uint64)
                      + values["bytes"][rows, ci, j])
                out["bytes"][rows, ri] = np.minimum(nb, U16_MAX)
                npk = (out["packets"][rows, ri].astype(np.uint64)
                       + values["packets"][rows, ci, j])
                out["packets"][rows, ri] = np.minimum(npk, U16_MAX)
                idx[rows] = (ri + 1) % cap
        out["n_events"] = idx
    return out


#: kind -> columnar merge fn (kind names shared with flowpack._MERGE_FNS)
COLUMNAR_MERGES = {
    "stats": merge_base_columnar,
    "dns": merge_dns_columnar,
    "drops": merge_drops_columnar,
    "extra": merge_extra_columnar,
    "xlat": merge_xlat_columnar,
    "quic": merge_quic_columnar,
    "nevents": merge_nevents_columnar,
}
