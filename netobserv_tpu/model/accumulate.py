"""Per-feature merge semantics — the single source of truth for "how two partial
observations of the same flow combine".

Reference analog: `pkg/model/flow_content.go:24-197`. These rules are applied in
three places and must agree everywhere (SURVEY.md §7.3 "merge semantics fidelity"):
1. host-side merge of per-CPU feature-map partials at eviction,
2. userspace re-aggregation of ringbuffer singles (Accounter),
3. on-device sketch folds (bytes/packets add, RTT max, DNS-latency max).

All functions mutate `dst` (a numpy structured scalar or 1-element view) in place,
merging `src` into it. Semantics follow the reference function by function; tests
in `tests/test_model.py` pin them.
"""

from __future__ import annotations

import numpy as np

U16_MAX = np.uint64(0xFFFF)

# no_flow_stats.misc_flags bits (records.h NO_MISC_SSL_MISMATCH)
MISC_SSL_MISMATCH = 0x01
U32_MAX = np.uint64(0xFFFF_FFFF)
U64_MAX = np.uint64(0xFFFF_FFFF_FFFF_FFFF)


def _sat_add(a, b, cap) -> int:
    s = int(a) + int(b)
    return int(cap) if s > int(cap) else s


def _merge_times(dst, src) -> None:
    """first_seen = min (zero means unset), last_seen = max."""
    s_first, s_last = int(src["first_seen_ns"]), int(src["last_seen_ns"])
    d_first = int(dst["first_seen_ns"])
    if d_first == 0 or (s_first != 0 and s_first < d_first):
        dst["first_seen_ns"] = s_first
    if int(dst["last_seen_ns"]) < s_last:
        dst["last_seen_ns"] = s_last


def accumulate_base(dst, src) -> None:
    """Merge two base flow_stats partials (reference: AccumulateBase,
    `flow_content.go:28-63`): add bytes/packets, OR flags, min/max times,
    latest-non-zero wins for eth_protocol/dscp/sampling, MACs fill if unset."""
    dst_was_empty = int(dst["first_seen_ns"]) == 0 and int(dst["packets"]) == 0
    _merge_times(dst, src)
    dst["bytes"] = _sat_add(dst["bytes"], src["bytes"], U64_MAX)
    dst["packets"] = _sat_add(dst["packets"], src["packets"], U32_MAX)
    dst["tcp_flags"] = int(dst["tcp_flags"]) | int(src["tcp_flags"])
    if int(src["eth_protocol"]) != 0:
        dst["eth_protocol"] = src["eth_protocol"]
    if int(src["dscp"]) != 0:
        dst["dscp"] = src["dscp"]
    if int(src["sampling"]) != 0:
        dst["sampling"] = src["sampling"]
    if not np.any(dst["src_mac"]):
        dst["src_mac"] = src["src_mac"]
    if not np.any(dst["dst_mac"]):
        dst["dst_mac"] = src["dst_mac"]
    if int(src["errno_fallback"]) != 0:
        dst["errno_fallback"] = src["errno_fallback"]
    # first-seen identity fields: keep dst's unless dst was a fresh zero entry
    if dst_was_empty:
        dst["if_index_first"] = src["if_index_first"]
        dst["direction_first"] = src["direction_first"]
    # ssl_version: first non-zero observation wins; a conflicting later
    # version raises the mismatch flag instead of overwriting (same rule the
    # kernel applies at entry time, reference bpf/flows.c:111-118)
    if int(src["ssl_version"]) != 0:
        if int(dst["ssl_version"]) == 0:
            dst["ssl_version"] = src["ssl_version"]
        elif int(dst["ssl_version"]) != int(src["ssl_version"]):
            dst["misc_flags"] = int(dst["misc_flags"]) | MISC_SSL_MISMATCH
    for fld in ("tls_cipher_suite", "tls_key_share"):
        if int(src[fld]) != 0:
            dst[fld] = src[fld]
    dst["tls_types"] = int(dst["tls_types"]) | int(src["tls_types"])
    dst["misc_flags"] = int(dst["misc_flags"]) | int(src["misc_flags"])
    # observed-interfaces dedup (bounded at MAX_OBSERVED_INTERFACES; the
    # datapath's lock-free slot reservation can leave the counter
    # transiently above capacity — clamp before indexing)
    cap = len(dst["observed_intf"])
    n_dst = min(int(dst["n_observed_intf"]), cap)
    for j in range(min(int(src["n_observed_intf"]), cap)):
        oi, od = int(src["observed_intf"][j]), int(src["observed_direction"][j])
        seen = any(
            int(dst["observed_intf"][i]) == oi
            and int(dst["observed_direction"][i]) == od
            for i in range(n_dst))
        if not seen and n_dst < cap:
            dst["observed_intf"][n_dst] = oi
            dst["observed_direction"][n_dst] = od
            n_dst += 1
    dst["n_observed_intf"] = n_dst


def accumulate_dns(dst, src) -> None:
    """DNS: max latency wins, flags OR, latest id/errno observation adopted
    (reference: AccumulateDNS, `flow_content.go:76-96` — errno is assigned from
    the incoming partial even when it clears a previous error)."""
    _merge_times(dst, src)
    dst["dns_flags"] = int(dst["dns_flags"]) | int(src["dns_flags"])
    if int(src["dns_id"]) != 0:
        dst["dns_id"] = src["dns_id"]
    if int(dst["errno"]) != int(src["errno"]):
        dst["errno"] = src["errno"]
    if int(src["latency_ns"]) > int(dst["latency_ns"]):
        dst["latency_ns"] = src["latency_ns"]
    if bytes(src["name"]).rstrip(b"\x00"):
        dst["name"] = src["name"]


def accumulate_drops(dst, src) -> None:
    """Packet drops: saturating u16 adds, flags OR, latest non-zero cause/state
    win (reference: AccumulateDrops, `flow_content.go:98-117`)."""
    _merge_times(dst, src)
    dst["bytes"] = _sat_add(dst["bytes"], src["bytes"], U16_MAX)
    dst["packets"] = _sat_add(dst["packets"], src["packets"], U16_MAX)
    dst["latest_flags"] = int(dst["latest_flags"]) | int(src["latest_flags"])
    if int(src["latest_cause"]) != 0:
        dst["latest_cause"] = src["latest_cause"]
    if int(src["latest_state"]) != 0:
        dst["latest_state"] = src["latest_state"]


def accumulate_extra(dst, src) -> None:
    """RTT max-merge + IPsec highest-return-code priority (reference:
    AccumulateAdditional, `flow_content.go:154-178`)."""
    _merge_times(dst, src)
    if int(src["rtt_ns"]) > int(dst["rtt_ns"]):
        dst["rtt_ns"] = src["rtt_ns"]
    if int(dst["ipsec_ret"]) < int(src["ipsec_ret"]):
        dst["ipsec_ret"] = src["ipsec_ret"]
        dst["ipsec_encrypted"] = src["ipsec_encrypted"]
    elif int(dst["ipsec_ret"]) == int(src["ipsec_ret"]) and int(src["ipsec_encrypted"]):
        dst["ipsec_encrypted"] = src["ipsec_encrypted"]


def accumulate_xlat(dst, src) -> None:
    """NAT translation: a complete (both-endpoints) observation replaces
    (reference: AccumulateXlat, `flow_content.go:139-152`)."""
    _merge_times(dst, src)
    if np.any(src["src_ip"]) and np.any(src["dst_ip"]):
        for fld in ("src_ip", "dst_ip", "src_port", "dst_port", "zone_id"):
            dst[fld] = src[fld]


def accumulate_network_events(dst, src) -> None:
    """Network events: dedup append into a wrapping ring of MAX_NETWORK_EVENTS
    (reference: AccumulateNetworkEvents, `flow_content.go:119-137`)."""
    _merge_times(dst, src)
    idx = int(dst["n_events"]) % dst["events"].shape[0]
    cap = dst["events"].shape[0]
    for j in range(src["events"].shape[0]):
        ev = src["events"][j]
        if int(src["packets"][j]) == 0:
            continue
        dup = any(np.array_equal(dst["events"][i], ev) for i in range(cap))
        if not dup:
            dst["events"][idx] = ev
            dst["bytes"][idx] = _sat_add(dst["bytes"][idx], src["bytes"][j], U16_MAX)
            dst["packets"][idx] = _sat_add(dst["packets"][idx], src["packets"][j], U16_MAX)
            idx = (idx + 1) % cap
    dst["n_events"] = idx


def accumulate_quic(dst, src) -> None:
    """QUIC: max version wins, header-seen flags max/OR (reference:
    AccumulateQuic, `flow_content.go:179-197`)."""
    _merge_times(dst, src)
    if int(src["version"]) > int(dst["version"]):
        dst["version"] = src["version"]
    if int(dst["seen_long_hdr"]) < int(src["seen_long_hdr"]):
        dst["seen_long_hdr"] = src["seen_long_hdr"]
    if int(dst["seen_short_hdr"]) < int(src["seen_short_hdr"]):
        dst["seen_short_hdr"] = src["seen_short_hdr"]


def merge_percpu(values: np.ndarray, accumulate_fn) -> np.ndarray:
    """Merge per-CPU partial records (shape (n_cpu,) structured) into one."""
    out = values[0].copy()
    if "n_observed_intf" in (out.dtype.names or ()):
        # the datapath's lock-free slot reservation can leave the counter
        # transiently above capacity — clamp exactly like the native twin
        # (flowpack.cc fp_merge_stats), including the n_cpu==1 fast path
        cap = len(out["observed_intf"])
        if int(out["n_observed_intf"]) > cap:
            out["n_observed_intf"] = cap
    for i in range(1, len(values)):
        accumulate_fn(out, values[i])
    return out
