"""Flow identity and enums.

Reference analog: `bpf/types.h` (flow_id/flags/direction/global counter enums). The
wire layout lives in `netobserv_tpu.model.binfmt`; this module is the ergonomic
Python view.
"""

from __future__ import annotations

import enum
import ipaddress
import socket
from dataclasses import dataclass, field

IP_LEN = 16  # all addresses stored as 16B; IPv4 as ::ffff:a.b.c.d (RFC 4038)
IP4_IN_6_PREFIX = b"\x00" * 10 + b"\xff\xff"


class Direction(enum.IntEnum):
    """IPFIX field 61 semantics (reference: `bpf/types.h` direction_t)."""

    INGRESS = 0
    EGRESS = 1
    BOTH = 3  # observed-both marker used in per-interface dedup bookkeeping


class TcpFlags(enum.IntFlag):
    """RFC 9293 flags plus the reference's synthetic combination flags."""

    FIN = 0x01
    SYN = 0x02
    RST = 0x04
    PSH = 0x08
    ACK = 0x10
    URG = 0x20
    ECE = 0x40
    CWR = 0x80
    # Synthetic flags exported by the datapath (reference: `bpf/types.h` tcp_flags_t)
    SYN_ACK = 0x100
    FIN_ACK = 0x200
    RST_ACK = 0x400


def classify_tcp_flags(raw: int) -> int:
    """Raw TCP flags byte -> datapath flag encoding with the synthetic
    composite bits (single source for every userspace packet parser; kernel
    twins: parse.h no_classify_tcp_flags, asm_flowpath tcp branch)."""
    flags = raw
    if raw & (TcpFlags.SYN | TcpFlags.ACK) == (TcpFlags.SYN | TcpFlags.ACK):
        flags |= TcpFlags.SYN_ACK
    if raw & (TcpFlags.FIN | TcpFlags.ACK) == (TcpFlags.FIN | TcpFlags.ACK):
        flags |= TcpFlags.FIN_ACK
    if raw & (TcpFlags.RST | TcpFlags.ACK) == (TcpFlags.RST | TcpFlags.ACK):
        flags |= TcpFlags.RST_ACK
    return int(flags)


class GlobalCounter(enum.IntEnum):
    """Keys of the datapath's per-CPU global counter array.

    Reference: `bpf/types.h` global_counters_key_t; scraped each eviction into
    Prometheus (`pkg/tracer/tracer.go:1149-1185`).
    """

    HASHMAP_FAIL_UPDATE_FLOW = 0
    HASHMAP_FAIL_CREATE_FLOW = 1
    HASHMAP_FAIL_UPDATE_DNS = 2
    FILTER_REJECT = 3
    FILTER_ACCEPT = 4
    FILTER_NOMATCH = 5
    NETWORK_EVENTS_ERR = 6
    NETWORK_EVENTS_ERR_GROUPID_MISMATCH = 7
    NETWORK_EVENTS_ERR_UPDATE_MAP_FLOWS = 8
    NETWORK_EVENTS_GOOD = 9
    NETWORK_EVENTS_OVERFLOW = 10
    NETWORK_EVENTS_COOKIE_TOO_BIG = 11
    OBSERVED_INTF_MISSED = 12
    MAX = 13


MAX_OBSERVED_INTERFACES = 6
MAX_NETWORK_EVENTS = 4
MAX_EVENT_MD = 8
DNS_NAME_MAX_LEN = 32


def ip_to_16(addr: str | bytes) -> bytes:
    """Normalize an address to the 16-byte form used everywhere in the datapath."""
    if isinstance(addr, bytes):
        if len(addr) == 16:
            return addr
        if len(addr) == 4:
            return IP4_IN_6_PREFIX + addr
        raise ValueError(f"bad raw IP length {len(addr)}")
    ip = ipaddress.ip_address(addr)
    if ip.version == 4:
        return IP4_IN_6_PREFIX + ip.packed
    return ip.packed


def ip_from_16(raw: bytes) -> str:
    """Render a 16-byte address, collapsing v4-mapped back to dotted quad."""
    if raw[:12] == IP4_IN_6_PREFIX:
        return socket.inet_ntop(socket.AF_INET, raw[12:16])
    return socket.inet_ntop(socket.AF_INET6, raw)


@dataclass(frozen=True, slots=True)
class FlowKey:
    """The 5-tuple-ish flow identity (reference: `bpf/types.h` flow_id_t)."""

    src_ip: bytes = b"\x00" * IP_LEN  # always 16B
    dst_ip: bytes = b"\x00" * IP_LEN
    src_port: int = 0
    dst_port: int = 0
    proto: int = 0
    icmp_type: int = 0
    icmp_code: int = 0

    def __post_init__(self):
        if len(self.src_ip) != IP_LEN or len(self.dst_ip) != IP_LEN:
            raise ValueError("FlowKey IPs must be 16 bytes (use ip_to_16)")

    @classmethod
    def make(cls, src: str, dst: str, sport: int = 0, dport: int = 0,
             proto: int = 0, icmp_type: int = 0, icmp_code: int = 0) -> "FlowKey":
        return cls(ip_to_16(src), ip_to_16(dst), sport, dport, proto,
                   icmp_type, icmp_code)

    @property
    def src(self) -> str:
        return ip_from_16(self.src_ip)

    @property
    def dst(self) -> str:
        return ip_from_16(self.dst_ip)

    def normalized(self) -> "FlowKey":
        """Direction-normalized key: both directions of a conversation map to the
        same value (used for Kafka partitioning; reference:
        `pkg/exporter/kafka_proto.go:181-191`)."""
        if (self.src_ip, self.src_port) <= (self.dst_ip, self.dst_port):
            return self
        return FlowKey(self.dst_ip, self.src_ip, self.dst_port, self.src_port,
                       self.proto, self.icmp_type, self.icmp_code)


@dataclass(slots=True)
class FlowFeatures:
    """Optional per-feature metrics attached to a flow at eviction time.

    Mirrors the reference's per-feature per-CPU maps, already merged
    (`pkg/model/flow_content.go:9-22`). All times are monotonic ns.
    """

    dns_id: int = 0
    dns_flags: int = 0
    dns_latency_ns: int = 0
    dns_errno: int = 0
    dns_name: str = ""
    drop_bytes: int = 0
    drop_packets: int = 0
    drop_latest_flags: int = 0
    drop_latest_state: int = 0
    drop_latest_cause: int = 0
    rtt_ns: int = 0
    ipsec_encrypted: bool = False
    ipsec_encrypted_ret: int = 0
    xlat_src_ip: bytes = b""
    xlat_dst_ip: bytes = b""
    xlat_src_port: int = 0
    xlat_dst_port: int = 0
    xlat_zone_id: int = 0
    network_events: list[bytes] = field(default_factory=list)
    quic_version: int = 0
    quic_seen_long_hdr: bool = False
    quic_seen_short_hdr: bool = False
    # OpenSSL-uprobe plaintext<->flow correlation (userspace, procfs-based;
    # goes beyond the reference, which only logs/counts SSL events —
    # tracer_ringbuf.go:136-190)
    ssl_plaintext_events: int = 0
    ssl_plaintext_bytes: int = 0
