"""Enriched flow record — what exporters consume.

Reference analog: `pkg/model/record.go:66-159` (`Record`, `NewRecord`): reconstructs
wall-clock times from the datapath's monotonic timestamps, names interfaces, and
attaches per-feature metrics. Unlike the reference (which decodes one record per Go
struct), enrichment here operates per *batch* where possible; `Record` objects are
only materialized at exporter boundaries that need them (gRPC/IPFIX/stdout), while
the tpu-sketch backend consumes the columnar batch directly.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field as dfield
from typing import Callable, Optional

import numpy as np

from netobserv_tpu.model.flow import (
    Direction, FlowFeatures, FlowKey, ip_from_16,
)

# interfaceNamer hook (reference: `model.SetInterfaceNamer`,
# `pkg/agent/interfaces_listener.go:74-81`)
InterfaceNamer = Callable[[int, bytes], str]


def default_namer(if_index: int, mac: bytes) -> str:
    return str(if_index)


_namer: InterfaceNamer = default_namer


def set_interface_namer(namer: InterfaceNamer) -> None:
    global _namer
    _namer = namer


def interface_namer() -> InterfaceNamer:
    return _namer


class MonotonicClock:
    """Maps datapath monotonic-ns timestamps to wall-clock epochs.

    Reference analog: `pkg/model/record.go:90-97` — current wall time minus the
    (current mono - sample mono) delta. One instance is shared per agent so every
    batch uses a consistent mapping.
    """

    def now_pair(self) -> tuple[int, int]:
        return time.clock_gettime_ns(time.CLOCK_MONOTONIC), time.time_ns()

    def wall_ns(self, mono_ns: int) -> int:
        cur_mono, cur_wall = self.now_pair()
        return cur_wall - (cur_mono - mono_ns)

    def wall_ns_array(self, mono_ns: np.ndarray) -> np.ndarray:
        cur_mono, cur_wall = self.now_pair()
        offset = cur_wall - cur_mono
        return mono_ns.astype(np.int64) + offset


@dataclass(slots=True)
class Record:
    """One enriched flow (reference: `pkg/model/record.go:66-80`)."""

    key: FlowKey
    bytes_: int = 0
    packets: int = 0
    eth_protocol: int = 0
    tcp_flags: int = 0
    direction: int = int(Direction.INGRESS)
    src_mac: bytes = b"\x00" * 6
    dst_mac: bytes = b"\x00" * 6
    if_index: int = 0
    interface: str = ""
    udn: str = ""
    dscp: int = 0
    sampling: int = 0
    errno_fallback: int = 0
    time_flow_start_ns: int = 0  # wall clock
    time_flow_end_ns: int = 0
    mono_start_ns: int = 0
    mono_end_ns: int = 0
    agent_ip: str = ""
    # (interface, direction, udn) observations across NICs — the reference's DupMap
    dup_list: list[tuple[str, int, str]] = dfield(default_factory=list)
    features: FlowFeatures = dfield(default_factory=FlowFeatures)
    ssl_version: int = 0
    tls_cipher_suite: int = 0
    tls_key_share: int = 0
    tls_types: int = 0
    ssl_mismatch: bool = False

    def to_json_obj(self) -> dict:
        """Stable JSON shape for the stdout exporter. Field NAMES follow the
        FLP GenericMap naming (exporter/flp_map.py) so consumers can switch
        exporters without remapping; this surface keeps raw numeric values
        where flp_map decodes strings (drop causes, TCP states)."""
        f = self.features
        obj = {
            "SrcAddr": self.key.src,
            "DstAddr": self.key.dst,
            "SrcPort": self.key.src_port,
            "DstPort": self.key.dst_port,
            "Proto": self.key.proto,
            "Bytes": self.bytes_,
            "Packets": self.packets,
            "Flags": self.tcp_flags,
            "Etype": self.eth_protocol,
            "Dscp": self.dscp,
            "IfDirection": self.direction,
            "Interface": self.interface or str(self.if_index),
            "TimeFlowStartMs": self.time_flow_start_ns // 1_000_000,
            "TimeFlowEndMs": self.time_flow_end_ns // 1_000_000,
            "AgentIP": self.agent_ip,
            "Sampling": self.sampling,
        }
        if self.key.proto in (1, 58):  # ICMP / ICMPv6
            obj["IcmpType"] = self.key.icmp_type
            obj["IcmpCode"] = self.key.icmp_code
        if f.dns_id or f.dns_latency_ns:
            obj.update(DnsId=f.dns_id, DnsFlags=f.dns_flags,
                       DnsLatencyMs=f.dns_latency_ns // 1_000_000,
                       DnsErrno=f.dns_errno)
            if f.dns_name:
                obj["DnsName"] = f.dns_name
        if f.drop_packets or f.drop_bytes:
            obj.update(PktDropBytes=f.drop_bytes, PktDropPackets=f.drop_packets,
                       PktDropLatestFlags=f.drop_latest_flags,
                       PktDropLatestState=f.drop_latest_state,
                       PktDropLatestDropCause=f.drop_latest_cause)
        if f.rtt_ns:
            obj["TimeFlowRttNs"] = f.rtt_ns
        if f.xlat_src_ip:
            obj.update(XlatSrcAddr=ip_from_16(f.xlat_src_ip),
                       XlatDstAddr=ip_from_16(f.xlat_dst_ip),
                       XlatSrcPort=f.xlat_src_port, XlatDstPort=f.xlat_dst_port,
                       ZoneId=f.xlat_zone_id)
        if f.ipsec_encrypted or f.ipsec_encrypted_ret:
            obj.update(IPSecRet=f.ipsec_encrypted_ret,
                       IPSecStatus="success" if f.ipsec_encrypted
                       else "failure")
        if (self.ssl_version or self.tls_types or self.tls_cipher_suite
                or self.tls_key_share):
            # tls_types/cipher can be set without a hello version (e.g. the
            # agent attached mid-connection and saw only ApplicationData)
            from netobserv_tpu.model import tls_types as _tt
            if self.ssl_version:
                obj["TlsVersion"] = _tt.tls_version_name(self.ssl_version)
            if self.tls_cipher_suite:
                obj["TlsCipher"] = _tt.cipher_suite_name(self.tls_cipher_suite)
            if self.tls_key_share:
                obj["TlsKeyShare"] = _tt.key_share_name(self.tls_key_share)
            if self.tls_types:
                obj["TlsTypes"] = _tt.tls_types_names(self.tls_types)
            if self.ssl_mismatch:
                obj["TlsMismatch"] = True
        if f.ssl_plaintext_events:
            obj.update(SslPlaintextEvents=f.ssl_plaintext_events,
                       SslPlaintextBytes=f.ssl_plaintext_bytes)
        if f.quic_version or f.quic_seen_long_hdr or f.quic_seen_short_hdr:
            obj.update(QuicVersion=f.quic_version,
                       QuicLongHdr=f.quic_seen_long_hdr,
                       QuicShortHdr=f.quic_seen_short_hdr)
        if f.network_events:
            from netobserv_tpu.utils.ovn_decoder import decode_event
            obj["NetworkEvents"] = [decode_event(ev)
                                    for ev in f.network_events]
        return obj


def records_from_events(
    events: np.ndarray,
    clock: Optional[MonotonicClock] = None,
    agent_ip: str = "",
    namer: Optional[InterfaceNamer] = None,
) -> list[Record]:
    """Materialize Record objects from a decoded structured array of flow events."""
    clock = clock or MonotonicClock()
    namer = namer or _namer
    if len(events) == 0:
        return []
    cur_mono, cur_wall = clock.now_pair()
    offset = cur_wall - cur_mono  # one offset per batch keeps spans exact
    stats = events["stats"]
    keys = events["key"]
    n = len(events)
    # bulk-convert columns ONCE (C-speed) instead of per-element numpy scalar
    # conversions — this loop is the Record-path hot spot (the reference's
    # "single hottest allocation site", pkg/model/record_bench_test.go)
    starts = (stats["first_seen_ns"].astype(np.int64) + offset).tolist()
    ends = (stats["last_seen_ns"].astype(np.int64) + offset).tolist()
    monos_s = stats["first_seen_ns"].tolist()
    monos_e = stats["last_seen_ns"].tolist()
    ip_w = keys["src_ip"].shape[1]  # stride from the dtype, not a literal
    mac_w = stats["src_mac"].shape[1]
    src_ip_buf = np.ascontiguousarray(keys["src_ip"]).tobytes()
    dst_ip_buf = np.ascontiguousarray(keys["dst_ip"]).tobytes()
    src_mac_buf = np.ascontiguousarray(stats["src_mac"]).tobytes()
    dst_mac_buf = np.ascontiguousarray(stats["dst_mac"]).tobytes()
    sports = keys["src_port"].tolist()
    dports = keys["dst_port"].tolist()
    protos = keys["proto"].tolist()
    itypes = keys["icmp_type"].tolist()
    icodes = keys["icmp_code"].tolist()
    nbytes = stats["bytes"].tolist()
    pkts = stats["packets"].tolist()
    eths = stats["eth_protocol"].tolist()
    flags = stats["tcp_flags"].tolist()
    dirs = stats["direction_first"].tolist()
    ifidx = stats["if_index_first"].tolist()
    dscps = stats["dscp"].tolist()
    samplings = stats["sampling"].tolist()
    errnos = stats["errno_fallback"].tolist()
    ssl_vers = stats["ssl_version"].tolist()
    ciphers = stats["tls_cipher_suite"].tolist()
    shares = stats["tls_key_share"].tolist()
    ttypes = stats["tls_types"].tolist()
    miscs = stats["misc_flags"].tolist()
    n_obs = stats["n_observed_intf"].tolist()
    obs_if = stats["observed_intf"].tolist()
    obs_dir = stats["observed_direction"].tolist()

    out: list[Record] = []
    for i in range(n):
        key = FlowKey(
            src_ip=src_ip_buf[i * ip_w:(i + 1) * ip_w],
            dst_ip=dst_ip_buf[i * ip_w:(i + 1) * ip_w],
            src_port=sports[i], dst_port=dports[i], proto=protos[i],
            icmp_type=itypes[i], icmp_code=icodes[i],
        )
        mac = src_mac_buf[i * mac_w:(i + 1) * mac_w]
        rec = Record(
            key=key,
            bytes_=nbytes[i], packets=pkts[i],
            eth_protocol=eths[i], tcp_flags=flags[i], direction=dirs[i],
            src_mac=mac, dst_mac=dst_mac_buf[i * mac_w:(i + 1) * mac_w],
            if_index=ifidx[i], interface=namer(ifidx[i], mac),
            dscp=dscps[i], sampling=samplings[i], errno_fallback=errnos[i],
            time_flow_start_ns=starts[i], time_flow_end_ns=ends[i],
            mono_start_ns=monos_s[i], mono_end_ns=monos_e[i],
            agent_ip=agent_ip,
            ssl_version=ssl_vers[i], tls_cipher_suite=ciphers[i],
            tls_key_share=shares[i], tls_types=ttypes[i],
            ssl_mismatch=bool(miscs[i] & 0x01),
        )
        seen_obs = set()
        for j in range(min(n_obs[i], len(obs_if[i]))):
            # skip slots a racing reservation published but hasn't written
            # yet (ifindex 0 is never a real interface), and dedup entries a
            # same-interface append race may have duplicated
            pair = (int(obs_if[i][j]), int(obs_dir[i][j]))
            if pair[0] == 0 or pair in seen_obs:
                continue
            seen_obs.add(pair)
            rec.dup_list.append((namer(obs_if[i][j], mac), obs_dir[i][j], ""))
        out.append(rec)
    return out
