"""Enriched flow record — what exporters consume.

Reference analog: `pkg/model/record.go:66-159` (`Record`, `NewRecord`): reconstructs
wall-clock times from the datapath's monotonic timestamps, names interfaces, and
attaches per-feature metrics. Unlike the reference (which decodes one record per Go
struct), enrichment here operates per *batch* where possible; `Record` objects are
only materialized at exporter boundaries that need them (gRPC/IPFIX/stdout), while
the tpu-sketch backend consumes the columnar batch directly.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field as dfield
from typing import Callable, Optional

import numpy as np

from netobserv_tpu.model.flow import (
    Direction, FlowFeatures, FlowKey, ip_from_16,
)

# interfaceNamer hook (reference: `model.SetInterfaceNamer`,
# `pkg/agent/interfaces_listener.go:74-81`)
InterfaceNamer = Callable[[int, bytes], str]


def default_namer(if_index: int, mac: bytes) -> str:
    return str(if_index)


_namer: InterfaceNamer = default_namer


def set_interface_namer(namer: InterfaceNamer) -> None:
    global _namer
    _namer = namer


def interface_namer() -> InterfaceNamer:
    return _namer


class MonotonicClock:
    """Maps datapath monotonic-ns timestamps to wall-clock epochs.

    Reference analog: `pkg/model/record.go:90-97` — current wall time minus the
    (current mono - sample mono) delta. One instance is shared per agent so every
    batch uses a consistent mapping.
    """

    def now_pair(self) -> tuple[int, int]:
        return time.clock_gettime_ns(time.CLOCK_MONOTONIC), time.time_ns()

    def wall_ns(self, mono_ns: int) -> int:
        cur_mono, cur_wall = self.now_pair()
        return cur_wall - (cur_mono - mono_ns)

    def wall_ns_array(self, mono_ns: np.ndarray) -> np.ndarray:
        cur_mono, cur_wall = self.now_pair()
        offset = cur_wall - cur_mono
        return mono_ns.astype(np.int64) + offset


@dataclass
class Record:
    """One enriched flow (reference: `pkg/model/record.go:66-80`)."""

    key: FlowKey
    bytes_: int = 0
    packets: int = 0
    eth_protocol: int = 0
    tcp_flags: int = 0
    direction: int = int(Direction.INGRESS)
    src_mac: bytes = b"\x00" * 6
    dst_mac: bytes = b"\x00" * 6
    if_index: int = 0
    interface: str = ""
    udn: str = ""
    dscp: int = 0
    sampling: int = 0
    errno_fallback: int = 0
    time_flow_start_ns: int = 0  # wall clock
    time_flow_end_ns: int = 0
    mono_start_ns: int = 0
    mono_end_ns: int = 0
    agent_ip: str = ""
    # (interface, direction, udn) observations across NICs — the reference's DupMap
    dup_list: list[tuple[str, int, str]] = dfield(default_factory=list)
    features: FlowFeatures = dfield(default_factory=FlowFeatures)
    ssl_version: int = 0
    tls_cipher_suite: int = 0
    tls_key_share: int = 0
    tls_types: int = 0
    ssl_mismatch: bool = False

    def to_json_obj(self) -> dict:
        """Stable JSON shape for the stdout/direct exporter."""
        f = self.features
        obj = {
            "SrcAddr": self.key.src,
            "DstAddr": self.key.dst,
            "SrcPort": self.key.src_port,
            "DstPort": self.key.dst_port,
            "Proto": self.key.proto,
            "Bytes": self.bytes_,
            "Packets": self.packets,
            "Flags": self.tcp_flags,
            "Etype": self.eth_protocol,
            "Dscp": self.dscp,
            "IfDirection": self.direction,
            "Interface": self.interface or str(self.if_index),
            "TimeFlowStartMs": self.time_flow_start_ns // 1_000_000,
            "TimeFlowEndMs": self.time_flow_end_ns // 1_000_000,
            "AgentIP": self.agent_ip,
            "Sampling": self.sampling,
        }
        if self.key.proto in (1, 58):  # ICMP / ICMPv6
            obj["IcmpType"] = self.key.icmp_type
            obj["IcmpCode"] = self.key.icmp_code
        if f.dns_id or f.dns_latency_ns:
            obj.update(DnsId=f.dns_id, DnsFlags=f.dns_flags,
                       DnsLatencyMs=f.dns_latency_ns // 1_000_000,
                       DnsErrno=f.dns_errno)
            if f.dns_name:
                obj["DnsName"] = f.dns_name
        if f.drop_packets or f.drop_bytes:
            obj.update(PktDropBytes=f.drop_bytes, PktDropPackets=f.drop_packets,
                       PktDropLatestFlags=f.drop_latest_flags,
                       PktDropLatestState=f.drop_latest_state,
                       PktDropLatestDropCause=f.drop_latest_cause)
        if f.rtt_ns:
            obj["TimeFlowRttNs"] = f.rtt_ns
        if f.xlat_src_ip:
            obj.update(XlatSrcAddr=ip_from_16(f.xlat_src_ip),
                       XlatDstAddr=ip_from_16(f.xlat_dst_ip),
                       XlatSrcPort=f.xlat_src_port, XlatDstPort=f.xlat_dst_port,
                       XlatZoneId=f.xlat_zone_id)
        return obj


def records_from_events(
    events: np.ndarray,
    clock: Optional[MonotonicClock] = None,
    agent_ip: str = "",
    namer: Optional[InterfaceNamer] = None,
) -> list[Record]:
    """Materialize Record objects from a decoded structured array of flow events."""
    clock = clock or MonotonicClock()
    namer = namer or _namer
    if len(events) == 0:
        return []
    cur_mono, cur_wall = clock.now_pair()
    offset = cur_wall - cur_mono  # one offset per batch keeps spans exact
    starts = np.asarray(events["stats"]["first_seen_ns"]).astype(np.int64) + offset
    ends = np.asarray(events["stats"]["last_seen_ns"]).astype(np.int64) + offset
    out: list[Record] = []
    for i in range(len(events)):
        k = events["key"][i]
        s = events["stats"][i]
        key = FlowKey(
            src_ip=k["src_ip"].tobytes(), dst_ip=k["dst_ip"].tobytes(),
            src_port=int(k["src_port"]), dst_port=int(k["dst_port"]),
            proto=int(k["proto"]), icmp_type=int(k["icmp_type"]),
            icmp_code=int(k["icmp_code"]),
        )
        mac = s["src_mac"].tobytes()
        if_index = int(s["if_index_first"])
        rec = Record(
            key=key,
            bytes_=int(s["bytes"]), packets=int(s["packets"]),
            eth_protocol=int(s["eth_protocol"]), tcp_flags=int(s["tcp_flags"]),
            direction=int(s["direction_first"]),
            src_mac=mac, dst_mac=s["dst_mac"].tobytes(),
            if_index=if_index, interface=namer(if_index, mac),
            dscp=int(s["dscp"]), sampling=int(s["sampling"]),
            errno_fallback=int(s["errno_fallback"]),
            time_flow_start_ns=int(starts[i]), time_flow_end_ns=int(ends[i]),
            mono_start_ns=int(s["first_seen_ns"]), mono_end_ns=int(s["last_seen_ns"]),
            agent_ip=agent_ip,
            ssl_version=int(s["ssl_version"]),
            tls_cipher_suite=int(s["tls_cipher_suite"]),
            tls_key_share=int(s["tls_key_share"]), tls_types=int(s["tls_types"]),
            ssl_mismatch=bool(int(s["misc_flags"]) & 0x01),
        )
        n = int(s["n_observed_intf"])
        for j in range(min(n, len(s["observed_intf"]))):
            oi = int(s["observed_intf"][j])
            od = int(s["observed_direction"][j])
            rec.dup_list.append((namer(oi, mac), od, ""))
        out.append(rec)
    return out
