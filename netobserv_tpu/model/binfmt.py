"""Binary layout of datapath records, as numpy structured dtypes.

This module is the **host-side half of the layout contract** with the eBPF C
datapath (`netobserv_tpu/datapath/bpf/records.h`). The reference enforced the same
contract with a comment (`bpf/types.h:209-215` "must match byte-by-byte") plus
round-trip tests; here the contract is machine-checked: `tests/test_layout_parity.py`
compiles the C header with g++, prints `offsetof`/`sizeof` for every field, and
compares against these dtypes.

Decode is bulk and zero-copy: `np.frombuffer(raw, dtype=FLOW_EVENT_DTYPE)` turns a
ringbuffer drain or a map dump into a structured array in one call — the analog of
the reference's per-record `binary.Read` loop (`pkg/model/record.go:227-231`), which
was its hottest allocation site, done columnar instead.

All layouts are NATIVE-endian + naturally aligned: these structs are shared
with the in-kernel datapath on the same machine, so they carry the machine's
byte order — native dtypes are bit-identical to the old explicit-LE ones on
every little-endian arch (amd64/arm64/ppc64le/riscv64). Every kernel-ABI
module follows the same rule (guard: tests/test_layout_parity.py native-
endian scan), and the instruction assembler additionally flips the
bpf_insn register-bitfield nibble on big-endian hosts — s390x is therefore
correct by design but NOT CI-verified (no big-endian runners); amd64 and
real arm64 both run the full suite in CI (.github/workflows/ci.yml).
"""

from __future__ import annotations

import numpy as np

from netobserv_tpu.model import flow as _flow

# ---------------------------------------------------------------------------
# flow key — C: struct no_flow_key (40 bytes)
# ---------------------------------------------------------------------------
FLOW_KEY_DTYPE = np.dtype([
    ("src_ip", "u1", 16),
    ("dst_ip", "u1", 16),
    ("src_port", "u2"),
    ("dst_port", "u2"),
    ("proto", "u1"),
    ("icmp_type", "u1"),
    ("icmp_code", "u1"),
    ("pad0", "u1"),
])
assert FLOW_KEY_DTYPE.itemsize == 40

# ---------------------------------------------------------------------------
# base flow stats — C: struct no_flow_stats (104 bytes)
# The spin lock used by the kernel to guard concurrent updates is a plain u32
# placeholder on the host side.
# ---------------------------------------------------------------------------
NIFS = _flow.MAX_OBSERVED_INTERFACES

FLOW_STATS_DTYPE = np.dtype([
    ("first_seen_ns", "u8"),
    ("last_seen_ns", "u8"),
    ("bytes", "u8"),
    ("packets", "u4"),
    ("eth_protocol", "u2"),
    ("tcp_flags", "u2"),
    ("src_mac", "u1", 6),
    ("dst_mac", "u1", 6),
    ("if_index_first", "u4"),
    ("lock", "u4"),
    ("sampling", "u4"),
    ("direction_first", "u1"),
    ("errno_fallback", "u1"),
    ("dscp", "u1"),
    ("n_observed_intf", "u1"),
    ("observed_direction", "u1", NIFS),
    ("pad0", "u1", 2),  # aligns observed_intf (u32[]) to 4 in the C struct
    ("observed_intf", "u4", NIFS),
    ("ssl_version", "u2"),
    ("tls_cipher_suite", "u2"),
    ("tls_key_share", "u2"),
    ("tls_types", "u1"),
    ("misc_flags", "u1"),
    ("pad1", "u1", 4),
])
assert FLOW_STATS_DTYPE.itemsize == 104, FLOW_STATS_DTYPE.itemsize

# ---------------------------------------------------------------------------
# ringbuffer fallback payload — C: struct no_flow_event (key + stats)
# ---------------------------------------------------------------------------
FLOW_EVENT_DTYPE = np.dtype([
    ("key", FLOW_KEY_DTYPE),
    ("stats", FLOW_STATS_DTYPE),
])
assert FLOW_EVENT_DTYPE.itemsize == 144

# ---------------------------------------------------------------------------
# per-feature records (values of the per-CPU feature maps, merged at eviction)
# ---------------------------------------------------------------------------
DNS_REC_DTYPE = np.dtype([
    ("first_seen_ns", "u8"),
    ("last_seen_ns", "u8"),
    ("latency_ns", "u8"),
    ("dns_id", "u2"),
    ("dns_flags", "u2"),
    ("eth_protocol", "u2"),
    ("errno", "u1"),
    ("name", "S32"),  # DNS_NAME_MAX_LEN
    ("pad0", "u1", 1),
])
assert DNS_REC_DTYPE.itemsize == 64, DNS_REC_DTYPE.itemsize

DROPS_REC_DTYPE = np.dtype([
    ("first_seen_ns", "u8"),
    ("last_seen_ns", "u8"),
    ("bytes", "u2"),
    ("packets", "u2"),
    ("latest_cause", "u4"),
    ("latest_flags", "u2"),
    ("eth_protocol", "u2"),
    ("latest_state", "u1"),
    ("pad0", "u1", 3),
])
assert DROPS_REC_DTYPE.itemsize == 32, DROPS_REC_DTYPE.itemsize

NEVENTS_REC_DTYPE = np.dtype([
    ("first_seen_ns", "u8"),
    ("last_seen_ns", "u8"),
    ("events", "u1", (_flow.MAX_NETWORK_EVENTS, _flow.MAX_EVENT_MD)),
    ("bytes", "u2", _flow.MAX_NETWORK_EVENTS),
    ("packets", "u2", _flow.MAX_NETWORK_EVENTS),
    ("eth_protocol", "u2"),
    ("n_events", "u1"),
    ("pad0", "u1", 5),
])
assert NEVENTS_REC_DTYPE.itemsize == 72, NEVENTS_REC_DTYPE.itemsize

XLAT_REC_DTYPE = np.dtype([
    ("first_seen_ns", "u8"),
    ("last_seen_ns", "u8"),
    ("src_ip", "u1", 16),
    ("dst_ip", "u1", 16),
    ("src_port", "u2"),
    ("dst_port", "u2"),
    ("zone_id", "u2"),
    ("eth_protocol", "u2"),
])
assert XLAT_REC_DTYPE.itemsize == 56, XLAT_REC_DTYPE.itemsize

EXTRA_REC_DTYPE = np.dtype([  # rtt + ipsec (reference: additional_metrics_t)
    ("first_seen_ns", "u8"),
    ("last_seen_ns", "u8"),
    ("rtt_ns", "u8"),
    ("ipsec_ret", "i4"),
    ("eth_protocol", "u2"),
    ("ipsec_encrypted", "u1"),
    ("pad0", "u1", 1),
])
assert EXTRA_REC_DTYPE.itemsize == 32, EXTRA_REC_DTYPE.itemsize

QUIC_REC_DTYPE = np.dtype([
    ("first_seen_ns", "u8"),
    ("last_seen_ns", "u8"),
    ("version", "u4"),
    ("eth_protocol", "u2"),
    ("seen_long_hdr", "u1"),
    ("seen_short_hdr", "u1"),
])
assert QUIC_REC_DTYPE.itemsize == 24, QUIC_REC_DTYPE.itemsize

# ---------------------------------------------------------------------------
# flow-filter LPM entries — C: struct no_filter_key / no_filter_rule
# (written by datapath/filter_compile.py, matched by bpf/filter.h)
# ---------------------------------------------------------------------------
FILTER_KEY_DTYPE = np.dtype([
    ("prefix_len", "u4"),
    ("ip", "u1", 16),
])
assert FILTER_KEY_DTYPE.itemsize == 20

FILTER_RULE_DTYPE = np.dtype([
    ("proto", "u1"),
    ("icmp_type", "u1"),
    ("icmp_code", "u1"),
    ("direction", "u1"),
    ("action", "u1"),
    ("want_drops", "u1"),
    ("peer_cidr_check", "u1"),
    ("pad0", "u1"),
    ("dport_start", "u2"), ("dport_end", "u2"),
    ("dport1", "u2"), ("dport2", "u2"),
    ("sport_start", "u2"), ("sport_end", "u2"),
    ("sport1", "u2"), ("sport2", "u2"),
    ("port_start", "u2"), ("port_end", "u2"),
    ("port1", "u2"), ("port2", "u2"),
    ("tcp_flags", "u2"),
    ("pad1", "u1", 2),
    ("sample_override", "u4"),
])
assert FILTER_RULE_DTYPE.itemsize == 40, FILTER_RULE_DTYPE.itemsize

# ---------------------------------------------------------------------------
# PCA packet payload record — C: struct no_packet_event
# ---------------------------------------------------------------------------
MAX_PAYLOAD_SIZE = 256

PACKET_EVENT_DTYPE = np.dtype([
    ("if_index", "u4"),
    ("pkt_len", "u4"),
    ("timestamp_ns", "u8"),
    ("payload", "u1", MAX_PAYLOAD_SIZE),
])
assert PACKET_EVENT_DTYPE.itemsize == 272

# ---------------------------------------------------------------------------
# SSL (OpenSSL uprobe) event — C: struct no_ssl_event
# ---------------------------------------------------------------------------
MAX_SSL_DATA = 16 * 1024

SSL_EVENT_DTYPE = np.dtype([
    ("timestamp_ns", "u8"),
    ("pid_tgid", "u8"),
    ("data_len", "i4"),
    ("ssl_type", "u1"),
    ("pad0", "u1", 3),
    ("data", "u1", MAX_SSL_DATA),
])
assert SSL_EVENT_DTYPE.itemsize == 24 + MAX_SSL_DATA


def decode_flow_events(raw: bytes | bytearray | memoryview) -> np.ndarray:
    """Bulk-decode a byte buffer of contiguous flow events (ringbuf drain)."""
    if len(raw) % FLOW_EVENT_DTYPE.itemsize:
        raise ValueError(
            f"buffer length {len(raw)} not a multiple of flow event size "
            f"{FLOW_EVENT_DTYPE.itemsize}")
    return np.frombuffer(raw, dtype=FLOW_EVENT_DTYPE)


def encode_flow_events(events: np.ndarray) -> bytes:
    """Inverse of decode (used by tests and the fake tracer)."""
    return np.ascontiguousarray(events, dtype=FLOW_EVENT_DTYPE).tobytes()


def events_from_keys_stats(keys: np.ndarray, stats: np.ndarray,
                           n_total: int | None = None) -> np.ndarray:
    """Compose FLOW_EVENT rows from separate key/stats arrays — the columnar
    drain's single copy boundary (replaces the old ``b"".join(k + v)``
    interleave over the eviction pairs). ``n_total`` over-allocates zeroed
    tail rows (the loader appends ringbuf-extra standalone events there).

    This is the NUMPY TWIN of the native single-pass interleave
    (`flowpack.events_from_keys_stats` -> fp_events_from_keys_stats, what
    the eviction decode actually calls); the two are equivalence-pinned by
    tests/test_evict_parallel.py — semantics change in both or neither."""
    n = len(keys)
    if len(stats) != n:
        raise ValueError(f"keys/stats length mismatch: {n} vs {len(stats)}")
    out = np.zeros(n_total if n_total is not None else n,
                   dtype=FLOW_EVENT_DTYPE)
    out["key"][:n] = keys
    out["stats"][:n] = stats
    return out
