"""PCA packet records + pcap framing (reference analog: `pkg/model/packet_record.go`
and `pkg/utils/packets/packets.go`)."""

from __future__ import annotations

import struct
from dataclasses import dataclass

import numpy as np

from netobserv_tpu.model import binfmt

PCAP_MAGIC = 0xA1B2C3D4
PCAP_VERSION_MAJOR = 2
PCAP_VERSION_MINOR = 4
LINKTYPE_ETHERNET = 1
PCAP_SNAP_LEN = binfmt.MAX_PAYLOAD_SIZE


@dataclass
class PacketRecord:
    if_index: int
    timestamp_ns: int  # wall clock after reconstruction
    payload: bytes


def pcap_file_header(snap_len: int = PCAP_SNAP_LEN) -> bytes:
    return struct.pack(
        "<IHHiIII", PCAP_MAGIC, PCAP_VERSION_MAJOR, PCAP_VERSION_MINOR,
        0, 0, snap_len, LINKTYPE_ETHERNET)


def pcap_packet_header(ts_ns: int, captured_len: int, orig_len: int) -> bytes:
    return struct.pack(
        "<IIII", ts_ns // 1_000_000_000, (ts_ns % 1_000_000_000) // 1000,
        captured_len, orig_len)


def frame_packet(rec: PacketRecord) -> bytes:
    """One pcap-framed packet (header + captured payload)."""
    captured = len(rec.payload)
    return pcap_packet_header(rec.timestamp_ns, captured, captured) + rec.payload


def packets_from_events(events: np.ndarray, mono_to_wall_offset_ns: int) -> list[PacketRecord]:
    """Decode a PACKET_EVENT structured array into PacketRecords."""
    out = []
    for i in range(len(events)):
        e = events[i]
        n = min(int(e["pkt_len"]), binfmt.MAX_PAYLOAD_SIZE)
        out.append(PacketRecord(
            if_index=int(e["if_index"]),
            timestamp_ns=int(e["timestamp_ns"]) + mono_to_wall_offset_ns,
            payload=e["payload"][:n].tobytes(),
        ))
    return out
