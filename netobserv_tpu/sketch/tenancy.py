"""Multi-tenant sketch planes: one dispatch folds every tenant.

Many *independent* observation domains (namespaces, customers, VPCs) per
chip is the ROADMAP's "millions of users" shape — and a full exporter per
tenant would pay N jit dispatches, N staging rings and N roll timers for
work whose per-dispatch overhead, not compute, bounds the host seam
(SALSA's thesis, PAPERS.md). `TenantStack` amortizes it: N tenant
`SketchState`s stack along a leading axis (SketchState is a pytree), ONE
vmapped+donated ingest executable folds every tenant's evictions and ONE
vmapped roll closes every tenant's window.

Routing happens in the columnar host path: evicted rows pack once to dense
rows (`flowpack.pack_dense`), each row's tenant owner is a key-derived hash
(`ops/hashing.tenant_of_np`, the numpy twin of the device `tenant_of` —
decorrelated from every sketch family), and rows accumulate into per-tenant
fixed-shape (B, 20) buffers. When any tenant's buffer fills, ALL buffers
ship as one zero-padded (N, B*20) stacked fold — invalid (all-zero) rows
are the fold's no-op identity, so padding costs nothing but transfer bytes.
Fixed shapes everywhere: zero data-dependent shapes, zero retraces across
the tenant-count ladder (each N is its own watched executable, the
`tenants=` attribution in utils/retrace).

Per-tenant bit-exactness is the contract that makes this a pure perf
change: tenant t's lane of the stacked fold receives exactly the (B, 20)
array a single-tenant exporter fed the routed slice would ingest, and the
vmapped scatter core (`ops/countmin._scatter_add_two`'s custom_vmap rule)
applies the same adds per cell in the same order — tests/test_tenancy.py
pins stacked-vs-routed-slice equality for every table.

Scheduling notes:
- the slot/token protocol is inherited from `sketch.staging._SlotRing`
  verbatim (the CPU backend zero-copies aligned host arrays, so blocking
  on the put result is NOT sufficient — the token is a slice of the
  ingest's input and becomes ready only when the executable finished).
- `TenantStack` duck-types the staging rings' `fold`/`slot_wait_p95`
  surface, so the exporter's eviction path, overload coupling and
  PendingEventBuffer compose unchanged.
- mesh composition is refused-with-warning (the SKETCH_TIERED pattern;
  config.validate names SKETCH_TENANTS + SKETCH_MESH_SHAPE).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from netobserv_tpu.datapath import flowpack
from netobserv_tpu.model.columnar import KEY_WORDS
from netobserv_tpu.ops import hashing
from netobserv_tpu.sketch import state as sk
from netobserv_tpu.sketch.staging import StagingWedged, _SlotRing
from netobserv_tpu.utils import retrace, tracing

DENSE_WORDS = sk.DENSE_WORDS


def init_stacked_state(cfg: sk.SketchConfig, n_tenants: int):
    """N independent fresh tenant states stacked on a leading axis — every
    leaf of the SketchState pytree (tiered included) gains dim 0 = N."""
    import jax
    import jax.numpy as jnp

    base = sk.init_state(cfg)
    return jax.tree.map(lambda x: jnp.stack([x] * n_tenants), base)


def split_tenants(tree, n_tenants: int) -> list:
    """Slice a stacked pytree (roll report / table dict) into N per-tenant
    host trees. One np.asarray per leaf (one device pull for the whole
    stack), then zero-copy views per tenant."""
    import jax

    host = jax.tree.map(np.asarray, tree)
    return [jax.tree.map(lambda x: x[t], host) for t in range(n_tenants)]


class TenantStack(_SlotRing):
    """The stacked multi-tenant sketch plane: host router + per-tenant
    fill buffers + ONE vmapped ingest/roll pair.

    Duck-types the staging-ring fold surface the exporter drives:
    ``fold(state, events, extra=, dns=, drops=, xlat=, quic=, trace=)`` and
    ``slot_wait_p95()``. `flush()` ships any partially-filled tenant
    buffers (window close calls it before the stacked roll).
    """

    def __init__(self, n_tenants: int, cfg: sk.SketchConfig,
                 batch_size: int, metrics=None, n_slots: int = 4,
                 reset_sketches: bool = True,
                 decay_factor: Optional[float] = None):
        import jax

        if n_tenants < 1:
            raise ValueError("TenantStack needs n_tenants >= 1")
        self.n_tenants = n_tenants
        self.batch_size = batch_size
        self.cfg = cfg
        self.folds = 0          #: stacked ingest dispatches
        self.routed_rows = 0    #: rows routed to tenant buffers
        self._put = jax.device_put
        # per-tenant fill buffers (host, reused): rows accumulate here in
        # arrival order until any tenant's buffer fills
        self._fillbuf = np.zeros((n_tenants, batch_size, DENSE_WORDS),
                                 np.uint32)
        self._fill = [0] * n_tenants
        self._init_slots(
            [np.empty((n_tenants, batch_size * DENSE_WORDS), np.uint32)
             for _ in range(n_slots)], metrics)

        def one(s, flat):
            return sk.ingest(s, sk.dense_to_arrays(flat),
                             use_pallas=cfg.use_pallas,
                             enable_fanout=cfg.enable_fanout,
                             enable_asym=cfg.enable_asym)

        def ingest_fn(s, dense):
            # dense: (N, B*20) u32 — flat per tenant lane (the same
            # device-layout-padding dodge the dense ring ships). Token =
            # a slice of the input (the _SlotRing slot-reuse guard).
            s = jax.vmap(one)(s, dense)
            return s, dense.reshape(-1)[:1]

        # donation is load-bearing: the stacked state is N x the resident
        # footprint, and an undonated vmapped fold copies all of it per
        # dispatch (measured 10x+ slower at N=64)
        self._ingest = retrace.watch(
            jax.jit(ingest_fn, donate_argnums=(0,)), "tenant_ingest",
            tenants=n_tenants)

        def roll_one(s):
            # mirrors make_roll_fn(with_tables=True): the report and the
            # mergeable tables are of the PRE-roll state, one executable
            new_state, report = sk.roll_window(s, cfg, reset_sketches,
                                               decay_factor)
            return new_state, report, sk.state_tables(s)

        self._roll = retrace.watch(
            jax.jit(jax.vmap(roll_one)), "tenant_roll", tenants=n_tenants)
        if metrics is not None:
            metrics.sketch_tenants_active.set(n_tenants)

    # -- host router ------------------------------------------------------
    def route(self, events, extra=None, dns=None, drops=None, xlat=None,
              quic=None) -> tuple[np.ndarray, np.ndarray]:
        """Pack `events` once to dense rows and derive each row's tenant
        owner. Returns (rows (M, 20) u32, owners int32[M]). Split out so
        tests (and the bench) reuse the exact production routing."""
        rows = flowpack.pack_dense(events, batch_size=max(len(events), 1),
                                   extra=extra, dns=dns, drops=drops,
                                   xlat=xlat, quic=quic)
        owners = hashing.tenant_of_np(rows[:, :KEY_WORDS], self.n_tenants)
        return rows, owners

    def fold(self, state, events, extra=None, dns=None, drops=None,
             xlat=None, quic=None, trace=None):
        """Route `events` to tenant buffers; every time a tenant's buffer
        fills, ship ONE stacked fold of all tenants' pending rows (async —
        not blocked on). Returns the new stacked state."""
        if len(events) == 0:
            return state
        trace, owned = self._fold_trace(trace)
        try:
            with trace.stage("tenant_route"):
                rows, owners = self.route(events, extra=extra, dns=dns,
                                          drops=drops, xlat=xlat, quic=quic)
            return self._fold_routed(state, rows, owners, trace)
        finally:
            if owned:
                trace.finish()

    def fold_rows(self, state, rows: np.ndarray, trace=None):
        """Fold pre-packed dense rows ((M, 20) u32 — the Record/batch path,
        which already packed through the columnar twin). Same routing and
        dispatch as `fold`."""
        if len(rows) == 0:
            return state
        trace, owned = self._fold_trace(trace)
        try:
            owners = hashing.tenant_of_np(rows[:, :KEY_WORDS],
                                          self.n_tenants)
            return self._fold_routed(state, rows, owners, trace)
        finally:
            if owned:
                trace.finish()

    def _fold_routed(self, state, rows, owners, trace):
        self.routed_rows += len(rows)
        try:
            for t in range(self.n_tenants):
                sel = rows[owners == t]
                off = 0
                while off < len(sel):
                    take = min(len(sel) - off,
                               self.batch_size - self._fill[t])
                    lo = self._fill[t]
                    self._fillbuf[t, lo:lo + take] = sel[off:off + take]
                    self._fill[t] += take
                    off += take
                    if self._fill[t] == self.batch_size:
                        state = self._dispatch(state, trace)
        except StagingWedged as exc:
            # earlier dispatches of this fold DONATED the state they were
            # handed — the caller's pre-fold reference is deleted by then.
            # `state` here is the last valid reference (identical to the
            # caller's when nothing dispatched): the catcher must adopt it
            # (the staging-ring wedge contract).
            exc.state = state
            raise
        return state

    def flush(self, state, trace=None):
        """Ship any partially-filled tenant buffers as one stacked fold
        (no-op when all buffers are empty) — window close calls this so a
        roll never strands buffered rows."""
        if not any(self._fill):
            return state
        try:
            return self._dispatch(state, trace or tracing.NULL_TRACE)
        except StagingWedged as exc:
            exc.state = state  # nothing dispatched: caller's own state
            raise

    def _dispatch(self, state, trace):
        """One stacked fold: copy every tenant's fill prefix into a ship
        slot (zero-padding the tail — invalid rows are the fold identity),
        device_put, dispatch the vmapped ingest, advance the token ring."""
        slot = self._wait_slot(trace)
        buf = self._bufs[slot]
        for t in range(self.n_tenants):
            f = self._fill[t] * DENSE_WORDS
            if f:
                buf[t, :f] = self._fillbuf[t].reshape(-1)[:f]
            buf[t, f:] = 0
            self._fill[t] = 0
        with trace.stage("ingest_dispatch"):
            state, token = self._ingest(state, self._put(buf))
        self._advance(slot, token)
        self.folds += 1
        if self._metrics is not None:
            self._metrics.sketch_tenant_folds_total.inc()
        return state

    # -- roll / teardown --------------------------------------------------
    def roll(self, state):
        """ONE stacked roll closing every tenant's window: returns
        (new stacked state, stacked report, stacked pre-roll tables)."""
        return self._roll(state)

    def close(self) -> None:
        """Tenant-series label hygiene (the federation agent-eviction
        pattern): drained/removed tenants must not leave their labelled
        series behind — evict every per-tenant series and zero the
        active-tenants gauge."""
        m = self._metrics
        if m is None:
            return
        for t in range(self.n_tenants):
            m.remove_labeled(m.sketch_tenant_window_records, str(t))
        m.sketch_tenants_active.set(0)
