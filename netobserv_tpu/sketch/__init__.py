"""The TPU analytics plane: streaming sketch state + ingest/window pipeline.

This package replaces the reference's CPU eviction→aggregation→export hot loop
(`pkg/flow/tracer_map.go:103-146`, `pkg/flow/account.go:204-270` — its
acknowledged hottest path) with constant-size sketch state folded on-device.
"""

from netobserv_tpu.sketch.state import (  # noqa: F401
    SketchConfig, SketchState, init_state, ingest, make_ingest_fn,
    batch_to_device, roll_window,
)
