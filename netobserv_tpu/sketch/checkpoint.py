"""Sketch-state checkpoint/restore.

The reference is stateless across restarts (flows are a lossy stream; the only
persistence is bpfman-pinned kernel maps, SURVEY.md §5.4). Sketches are
long-lived accumulators, so the rebuild adds real checkpointing: the whole
SketchState pytree (single-device or distributed) is saved with orbax and
restored with the same sharding layout.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Optional

import jax
import numpy as np

from netobserv_tpu.utils.atomicio import write_json_atomic

try:
    import orbax.checkpoint as ocp
    HAVE_ORBAX = True
except Exception:  # pragma: no cover - orbax is baked into the image
    HAVE_ORBAX = False

log = logging.getLogger("netobserv_tpu.sketch.checkpoint")

#: checkpoint FORMAT version, stamped next to every save. Version 1 is the
#: legacy unstamped era (accepted with an upgrade log — its pytree either
#: restores or fails the structural check exactly as before); bump this
#: whenever the state layout / table-snapshot spec changes incompatibly.
#: The federation delta frame reuses the same table snapshot layout, so the
#: stamp also records `federation.delta`'s spec fingerprint + format
#: version — the two surfaces are pinned against the same goldens and must
#: move together (tests/test_federation_golden.py).
#: v3: the persistent-slot heavy-hitter table (SketchState.heavy gained
#: prev_counts/first_seen/epoch + the heavy_evictions scalar). v2-stamped
#: checkpoints have NO upgrade path — their pytree cannot restore into the
#: v3 layout — and are rejected by `check_format` BEFORE any tensor read
#: (callers degrade to a fresh window, never crash).
CHECKPOINT_FORMAT_VERSION = 3
_LEGACY_VERSION = 1
_STAMP_FILE = "FORMAT.json"

#: known upgrade paths: stamped version -> upgrader (state-identity when the
#: pytree itself is compatible). Missing entry = reject.
_UPGRADERS = {_LEGACY_VERSION: lambda state: state}


def _spec_fingerprint() -> int:
    from netobserv_tpu.federation import delta as fdelta
    return fdelta.table_spec_fingerprint()


class SketchCheckpointer:
    """Versioned checkpoints of a sketch-state pytree under `directory`."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        if not HAVE_ORBAX:
            raise RuntimeError("orbax is not available")
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True),
        )

    def _stamp_path(self) -> str:
        return os.path.join(self._dir, _STAMP_FILE)

    def _write_stamp(self) -> None:
        from netobserv_tpu.federation import delta as fdelta
        stamp = {"format_version": CHECKPOINT_FORMAT_VERSION,
                 "table_spec_crc": _spec_fingerprint(),
                 "delta_format_version": fdelta.DELTA_FORMAT_VERSION}
        # temp + fsync + rename (utils/atomicio): a crash mid-write must
        # never leave a torn stamp that misreads as a legacy checkpoint
        write_json_atomic(self._stamp_path(), stamp)

    def read_stamp(self) -> dict:
        """The directory's format stamp; legacy (pre-stamp) checkpoints
        report version 1."""
        try:
            with open(self._stamp_path()) as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return {"format_version": _LEGACY_VERSION}

    def check_format(self) -> Optional[int]:
        """Validate the stamp BEFORE any tensor restore. Returns the
        stamped version when an upgrade path exists (None = current);
        raises RuntimeError when the checkpoint must be rejected."""
        stamp = self.read_stamp()
        version = int(stamp.get("format_version", _LEGACY_VERSION))
        if version == CHECKPOINT_FORMAT_VERSION:
            crc = stamp.get("table_spec_crc")
            if crc is not None and crc != _spec_fingerprint():
                raise RuntimeError(
                    f"checkpoint under {self._dir} stamps format "
                    f"{version} but a different table-snapshot layout "
                    f"(crc {crc} != {_spec_fingerprint()}): the layout "
                    "changed without a format bump — refuse rather than "
                    "restore silently-misaligned tables")
            return None
        if version in _UPGRADERS:
            return version
        raise RuntimeError(
            f"checkpoint under {self._dir} has format version {version}; "
            f"this build reads {CHECKPOINT_FORMAT_VERSION} (known upgrade "
            f"paths: {sorted(_UPGRADERS)}) — refusing to restore")

    def save(self, step: int, state: Any, wait: bool = False) -> None:
        self._mngr.save(step, args=ocp.args.StandardSave(state))
        self._write_stamp()
        if wait:
            self._mngr.wait_until_finished()

    # --- per-step JSON metadata sidecars (federation aggregator ledger) --
    # Host-side metadata that must restore ATOMICALLY with a step's tensors
    # (the aggregator's per-agent delivery ledger — restoring tensors with
    # a ledger from another step would re-admit or falsely-discard frames).
    # Contract: write the sidecar for step N BEFORE saving step N's tensors;
    # restore reads the sidecar of the step it actually restored. A crash
    # between the two writes leaves latest_step at N-1, whose sidecar
    # already exists — (state, ledger) pairs can never tear.

    def _meta_path(self, step: int) -> str:
        return os.path.join(self._dir, f"META-{int(step)}.json")

    def save_metadata(self, step: int, meta: dict) -> None:
        """Atomically write step-paired JSON metadata (call BEFORE save());
        old sidecars beyond the manager's retention are pruned."""
        write_json_atomic(self._meta_path(step),
                          {"step": int(step), "meta": meta})
        keep = set(self._mngr.all_steps()) | {int(step)}
        for name in os.listdir(self._dir):
            if name.startswith("META-") and name.endswith(".json"):
                try:
                    s = int(name[len("META-"):-len(".json")])
                except ValueError:
                    continue
                if s not in keep:
                    try:
                        os.remove(os.path.join(self._dir, name))
                    except OSError:
                        pass

    def read_metadata(self, step: Optional[int] = None) -> Optional[dict]:
        """The metadata paired with `step` (default: latest step). None when
        the sidecar is absent (pre-metadata checkpoints) or unreadable —
        callers must treat that as an EMPTY ledger, never a failure."""
        step = self._mngr.latest_step() if step is None else step
        if step is None:
            return None
        try:
            with open(self._meta_path(step)) as fh:
                payload = json.load(fh)
        except (OSError, ValueError):
            return None
        if int(payload.get("step", -1)) != int(step):
            return None
        return payload.get("meta")

    # --- publish-commit marker (federation aggregator) -------------------
    # A tiny atomic JSON updated at every WINDOW PUBLISH (not every tensor
    # save): the last published window id plus the delivery ledger as of
    # that publish. With FEDERATION_CHECKPOINT_EVERY > 1 the newest tensor
    # checkpoint can trail published windows; the marker lets a restore
    # fast-forward the window counter past every id that already reached
    # the sink (closed windows never re-publish) and overlay the ledger
    # those windows committed (their redelivered frames dedup, never
    # double-count), at the cost of losing the skipped windows' tensor
    # contribution — the documented every-N durability tradeoff.

    def _publish_marker_path(self) -> str:
        return os.path.join(self._dir, "PUBLISHED.json")

    def save_publish_marker(self, window: int, meta: dict) -> None:
        write_json_atomic(self._publish_marker_path(),
                          {"window": int(window), "meta": meta})

    def read_publish_marker(self) -> Optional[dict]:
        """{"window": int, "meta": {...}} of the last publish, or None
        (absent/unreadable markers mean no fast-forward, never a failure)."""
        try:
            with open(self._publish_marker_path()) as fh:
                payload = json.load(fh)
            return {"window": int(payload["window"]),
                    "meta": payload.get("meta") or {}}
        except (OSError, ValueError, KeyError, TypeError):
            return None

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def restore(self, template: Any, step: Optional[int] = None) -> Any:
        """Restore into the shardings/dtypes of `template` (an abstract or
        concrete state pytree laid out as desired). Rejects checkpoints
        whose format stamp has no upgrade path; legacy/upgradable stamps
        restore through their upgrader (the structural template check
        still guards the pytree itself)."""
        old_version = self.check_format()  # raises on reject
        step = self._mngr.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self._dir}")
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=getattr(x, "sharding", None)),
            template)
        restored = self._mngr.restore(
            step, args=ocp.args.StandardRestore(abstract))
        if old_version is not None:
            log.info("upgrading sketch checkpoint format %d -> %d",
                     old_version, CHECKPOINT_FORMAT_VERSION)
            restored = _UPGRADERS[old_version](restored)
        return restored

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()
