"""Sketch-state checkpoint/restore.

The reference is stateless across restarts (flows are a lossy stream; the only
persistence is bpfman-pinned kernel maps, SURVEY.md §5.4). Sketches are
long-lived accumulators, so the rebuild adds real checkpointing: the whole
SketchState pytree (single-device or distributed) is saved with orbax and
restored with the same sharding layout.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np

try:
    import orbax.checkpoint as ocp
    HAVE_ORBAX = True
except Exception:  # pragma: no cover - orbax is baked into the image
    HAVE_ORBAX = False


class SketchCheckpointer:
    """Versioned checkpoints of a sketch-state pytree under `directory`."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        if not HAVE_ORBAX:
            raise RuntimeError("orbax is not available")
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True),
        )

    def save(self, step: int, state: Any, wait: bool = False) -> None:
        self._mngr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mngr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def restore(self, template: Any, step: Optional[int] = None) -> Any:
        """Restore into the shardings/dtypes of `template` (an abstract or
        concrete state pytree laid out as desired)."""
        step = self._mngr.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self._dir}")
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=getattr(x, "sharding", None)),
            template)
        return self._mngr.restore(step, args=ocp.args.StandardRestore(abstract))

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()
