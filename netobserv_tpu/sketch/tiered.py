"""Tiered counter planes: self-adjusting sketch memory (SKETCH_TIERED).

The SALSA/additive-error-counter direction from PAPERS.md, TPU-idiomatic:
every sketch table today burns a full-width element per counter, yet in
heavy-tailed traffic the overwhelming majority of counters never leave the
bottom few bits. Tiered mode keeps the RESIDENT form of the big counter
tables narrow and decodes to the canonical wide tables only transiently,
inside the fold/roll executables:

- **Count-Min planes** — a u8 base plane covering the full ``[d, w]``
  geometry (the bytes plane counts in ``bytes_unit``-byte units, ceil per
  fold — overestimate-preserving, the additive-error-counter tradeoff;
  the packets plane counts raw) plus two fixed-shape overflow tiers:
  a direct-mapped u16 MID tier (one cell per ``mid_group`` columns) and a
  u32 TOP tier (one cell per ``top_group`` columns). A counter that
  saturates its base cell is *promoted*: the overflow mass spills into its
  group's mid cell (and from a saturated mid cell into the top cell, which
  finally clamps — sat-add, like the 16-bit drop lanes). Promotion is a
  masked in-place update over fixed shapes — never a reshape, never a
  data-dependent shape, zero retraces. Decode attributes a shared overflow
  cell to every promoted member of its group, so estimates are
  OVERESTIMATES only — exactly the Count-Min error direction, and the min
  over depth rows bounds the aliasing like any other CM collision.
- **HLL banks** (global src + both per-bucket grids) — registers hold
  ranks <= 33 (6 bits); they pack LOSSLESSLY four-per-three-bytes
  (i32 -> 0.75 B/register, 5.33x) and unpack transiently in the fold.

Tiers are a steady-state representation only: the fold decodes to wide,
runs the EXISTING equivalence-pinned update forms (the scatter chain and
the fused Pallas batch walk — both unchanged, still bit-exact against each
other in tiered mode), and re-encodes the per-fold delta into the tiers.
Window roll, ``state_tables`` (the delta wire / query snapshot), and
checkpoints all see the canonical wide tables via the decode folded into
the same executables — no wire v4, no checkpoint format bump.

Semantics (pinned bit-exact against the numpy twin in
tests/test_tiered.py; per plane, per fold):

1. ``du = ceil(max(delta, 0) / unit)`` — the fold's per-counter delta in
   units (unit 1 for packets: exact).
2. ``s = base + du``; ``base' = min(s, 255)``; base overflow ``s - base'``
   group-sums into the mid tier; ``mid' = min(mid + spill, 65535)``; mid
   overflow group-sums into the top tier; ``top' = min(top + spill,
   TOP_MAX)`` — the top tier clamps (sat-add).
3. decode: ``units = base + [base==255] * (mid_g + [mid_g==65535] *
   top_G)``; value = ``units * unit``.

Promotion is lossless while a mid/top cell has a single promoted group
member (decode == wide exactly across every tier boundary); shared cells
alias — overestimate-only, like CM columns themselves.
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from netobserv_tpu.ops import countmin, hll

#: base plane saturation point (u8)
BASE_MAX = 255
#: mid tier saturation point (u16)
MID_MAX = 65535
#: top tier clamp (u32 storage; kept at a power of two so the f32 clamp
#: arithmetic the twin pins is exact) — "sat-add" semantics: overflow past
#: this is dropped, the cell saturates
TOP_MAX = 1 << 30


class TierSpec(NamedTuple):
    """Static tier geometry (hashable — rides SketchConfig / jit cache
    keys). ``mid_group``/``top_group`` are COLUMNS per overflow cell;
    ``bytes_unit`` is the byte quantum of the bytes plane's units."""

    mid_group: int = 32
    top_group: int = 256
    bytes_unit: int = 256

    def check(self, cm_width: int) -> None:
        for name, v in (("mid_group", self.mid_group),
                        ("top_group", self.top_group)):
            if v < 2 or v & (v - 1):
                raise ValueError(
                    f"tier {name} must be a power of two >= 2 (got {v})")
        if self.bytes_unit < 1 or self.bytes_unit & (self.bytes_unit - 1):
            raise ValueError("tier bytes_unit must be a power of two >= 1 "
                             f"(got {self.bytes_unit})")
        if self.top_group <= self.mid_group:
            raise ValueError(
                f"tier top_group ({self.top_group}) must exceed mid_group "
                f"({self.mid_group}) — tiers must narrow as they widen")
        if cm_width % self.top_group:
            raise ValueError(
                f"tier top_group ({self.top_group}) must divide "
                f"SKETCH_CM_WIDTH ({cm_width})")


class TieredPlane(NamedTuple):
    """One Count-Min counter plane in tiered form (values in UNITS)."""

    base: jax.Array  # u8  [d, w]
    mid: jax.Array   # u16 [d, w // mid_group]
    top: jax.Array   # u32 [d, w // top_group]


class TieredTables(NamedTuple):
    """The resident narrow form of every tier-covered sketch table."""

    cm_bytes: TieredPlane
    cm_pkts: TieredPlane
    hll_src: jax.Array      # u8 [m//4*3] — 6-bit packed registers
    hll_per_dst: jax.Array  # u8 [D, m//4*3]
    hll_per_src: jax.Array  # u8 [S, m//4*3]


@jax.tree_util.register_pytree_node_class
class TieredState:
    """Sketch state with the big counter tables resident in tiered form.

    ``rest`` is an ordinary SketchState whose cm/hll fields hold ZERO-SIZE
    placeholders (they cost nothing and are never read — every consumer
    goes through :func:`decode_state` / the fold's transient wide view).
    ``spec`` is static pytree aux data, so each tier geometry is its own
    jit cache entry — same rule as batch shapes."""

    __slots__ = ("tables", "rest", "spec")

    def __init__(self, tables: TieredTables, rest, spec: TierSpec):
        self.tables = tables
        self.rest = rest
        self.spec = spec

    def tree_flatten(self):
        return (self.tables, self.rest), self.spec

    @classmethod
    def tree_unflatten(cls, spec, children):
        return cls(children[0], children[1], spec)

    # ergonomic pass-throughs for the fields that stay wide (bench recall,
    # exporters reading the window counter)
    @property
    def heavy(self):
        return self.rest.heavy

    @property
    def window(self):
        return self.rest.window


# --------------------------------------------------------------------------
# plane encode / decode / fold-add (the promotion path)
# --------------------------------------------------------------------------

def _group_sum(x: jax.Array, g: int) -> jax.Array:
    d, n = x.shape
    return x.reshape(d, n // g, g).sum(axis=-1)


def _expand(x: jax.Array, g: int) -> jax.Array:
    d, n = x.shape
    return jnp.broadcast_to(x[:, :, None], (d, n, g)).reshape(d, n * g)


def _spill(over: jax.Array, mid_f: jax.Array, top_u: jax.Array,
           spec: TierSpec) -> tuple[jax.Array, jax.Array]:
    """Cascade base-level overflow (units, f32 [d, w]) through the mid and
    top tiers: group-sum, saturate, spill, clamp (sat-add at the top).

    The mid math stays f32 (cells cap at 65535 between folds and per-fold
    spills are far below 2^24 units, so every add is exact). The TOP cell
    accumulates in u32 INTEGER arithmetic: a top cell aggregates a whole
    top_group's overflow and crosses 2^24 units long before any single
    wide counter would — f32 accumulation there would silently round away
    small per-fold spills, an UNDERCOUNT (the one direction this module
    forbids). `top_u` is the resident u32 array."""
    s2 = mid_f + _group_sum(over, spec.mid_group)
    new_mid = jnp.minimum(s2, float(MID_MAX))
    spill = _group_sum(s2 - new_mid, spec.top_group // spec.mid_group)
    # per-fold spill is f32-exact (< 2^24 units per fold by construction);
    # clamp BEFORE the u32 cast, then saturate against the remaining room
    inc = jnp.minimum(spill, float(TOP_MAX)).astype(jnp.uint32)
    room = jnp.uint32(TOP_MAX) - top_u
    new_top = top_u + jnp.minimum(inc, room)
    return new_mid.astype(jnp.uint16), new_top


def init_plane(depth: int, width: int, spec: TierSpec) -> TieredPlane:
    return TieredPlane(
        base=jnp.zeros((depth, width), jnp.uint8),
        mid=jnp.zeros((depth, width // spec.mid_group), jnp.uint16),
        top=jnp.zeros((depth, width // spec.top_group), jnp.uint32))


def encode_plane(wide: jax.Array, spec: TierSpec, unit: int) -> TieredPlane:
    """From-scratch encode of a wide value table (init / window roll /
    decay / checkpoint restore). NOT the per-fold path — that is
    :func:`plane_add`, which preserves the tiers' overflow attribution."""
    # ALWAYS ceil, unit 1 included: fractional values (a decayed window)
    # must round UP into whole units — truncation would undercount, the
    # one error direction Count-Min forbids
    vu = jnp.ceil(wide.astype(jnp.float32) / unit)
    base = jnp.minimum(vu, float(BASE_MAX))
    d, w = wide.shape
    mid, top = _spill(vu - base,
                      jnp.zeros((d, w // spec.mid_group), jnp.float32),
                      jnp.zeros((d, w // spec.top_group), jnp.uint32), spec)
    return TieredPlane(base=base.astype(jnp.uint8), mid=mid, top=top)


def plane_add(plane: TieredPlane, delta: jax.Array, spec: TierSpec,
              unit: int) -> TieredPlane:
    """Fold one batch's per-counter delta (raw value domain, >= 0) into the
    tiered plane. Saturation promotion = the masked in-place spill below;
    every shape is fixed, so the jitted fold never retraces."""
    du = jnp.ceil(jnp.maximum(delta, 0.0) / unit)  # ceil: overestimate-only
    s = plane.base.astype(jnp.float32) + du
    new_base = jnp.minimum(s, float(BASE_MAX))
    mid, top = _spill(s - new_base, plane.mid.astype(jnp.float32),
                      plane.top, spec)
    return TieredPlane(base=new_base.astype(jnp.uint8), mid=mid, top=top)


def decay_plane(plane: TieredPlane, factor: float) -> TieredPlane:
    """Window decay at the REPRESENTATION level: scale each tier array
    elementwise (ceil — overestimate-only), keeping SATURATED base/mid
    cells saturated so their overflow attribution survives the decay.

    Deliberately NOT decode -> decay -> encode: decode attributes a shared
    overflow cell to every promoted group member, so a from-scratch
    re-encode would re-SUM those attributed values back into the cell and
    COMPOUND the aliasing every window (counts would grow under decay).
    Elementwise scaling never re-sums, so shared-cell overestimates decay
    like everything else. The floor this buys — a promoted counter never
    reads below BASE_MAX units — is a bounded overestimate, same class as
    the aliasing itself."""
    basef = jnp.ceil(plane.base.astype(jnp.float32) * factor)
    new_base = jnp.where(plane.base == BASE_MAX, plane.base,
                         basef.astype(jnp.uint8))
    midf = jnp.ceil(plane.mid.astype(jnp.float32) * factor)
    new_mid = jnp.where(plane.mid == MID_MAX, plane.mid,
                        midf.astype(jnp.uint16))
    new_top = jnp.ceil(plane.top.astype(jnp.float32) * factor).astype(
        jnp.uint32)
    return TieredPlane(base=new_base, mid=new_mid, top=new_top)


def decode_plane(plane: TieredPlane, spec: TierSpec, unit: int) -> jax.Array:
    """Wide f32 [d, w] view. A shared overflow cell is attributed to EVERY
    promoted member of its group — overestimate-only, the CM direction."""
    mid_f = plane.mid.astype(jnp.float32)
    top_per_mid = _expand(plane.top.astype(jnp.float32),
                          spec.top_group // spec.mid_group)
    mid_tot = mid_f + jnp.where(plane.mid == MID_MAX, top_per_mid, 0.0)
    per_col = _expand(mid_tot, spec.mid_group)
    units = plane.base.astype(jnp.float32) + jnp.where(
        plane.base == BASE_MAX, per_col, 0.0)
    return units * unit if unit > 1 else units


# --------------------------------------------------------------------------
# HLL register packing (6-bit, lossless — ranks are <= 33)
# --------------------------------------------------------------------------

def pack_hll(regs: jax.Array) -> jax.Array:
    """int32[..., m] registers -> u8[..., m//4*3] (4 regs per 3 bytes)."""
    *lead, m = regs.shape
    assert m % 4 == 0, f"HLL register count {m} must be a multiple of 4"
    r = regs.astype(jnp.uint32).reshape(*lead, m // 4, 4)
    v = r[..., 0] | (r[..., 1] << 6) | (r[..., 2] << 12) | (r[..., 3] << 18)
    b = jnp.stack([v & 0xFF, (v >> 8) & 0xFF, (v >> 16) & 0xFF], axis=-1)
    return b.astype(jnp.uint8).reshape(*lead, (m // 4) * 3)


def unpack_hll(packed: jax.Array) -> jax.Array:
    """Inverse of :func:`pack_hll` -> int32[..., m]."""
    *lead, n = packed.shape
    b = packed.astype(jnp.uint32).reshape(*lead, n // 3, 3)
    v = b[..., 0] | (b[..., 1] << 8) | (b[..., 2] << 16)
    r = jnp.stack([v & 63, (v >> 6) & 63, (v >> 12) & 63, (v >> 18) & 63],
                  axis=-1)
    return r.astype(jnp.int32).reshape(*lead, (n // 3) * 4)


# --------------------------------------------------------------------------
# state-level encode / decode (used by sketch/state.py's one-branch hooks)
# --------------------------------------------------------------------------

def _strip(wide) -> "object":
    """A SketchState with the tier-covered tables replaced by zero-size
    placeholders (shape info for re-widening lives in the tier arrays)."""
    return wide._replace(
        cm_bytes=countmin.CountMin(jnp.zeros((0, 0), jnp.float32)),
        cm_pkts=countmin.CountMin(jnp.zeros((0, 0), jnp.float32)),
        hll_src=hll.HLL(jnp.zeros((0,), jnp.int32)),
        hll_per_dst=hll.PerDstHLL(jnp.zeros((0, 0), jnp.int32)),
        hll_per_src=hll.PerDstHLL(jnp.zeros((0, 0), jnp.int32)))


def widen(ts: TieredState, cmb_wide: jax.Array, cmp_wide: jax.Array):
    """The transient wide SketchState a fold/roll operates on, given the
    two CM planes already decoded (so the fold can reuse them for the
    delta extraction without decoding twice)."""
    t = ts.tables
    return ts.rest._replace(
        cm_bytes=countmin.CountMin(cmb_wide),
        cm_pkts=countmin.CountMin(cmp_wide),
        hll_src=hll.HLL(unpack_hll(t.hll_src)),
        hll_per_dst=hll.PerDstHLL(unpack_hll(t.hll_per_dst)),
        hll_per_src=hll.PerDstHLL(unpack_hll(t.hll_per_src)))


def widen_interior(ts: TieredState, fuse_hll_src: bool):
    """The transient SketchState the TIER-INTERIOR fold operates on: the
    CM planes keep their zero-size placeholders (the interior kernel folds
    the tier arrays directly — no wide decode), and the global-src HLL
    bank stays packed too when the fused signal lane handles it
    (``fuse_hll_src``). Only the per-bucket HLL grids unpack — their fold
    is scatter-only by the measured gating verdict."""
    t = ts.tables
    rest = ts.rest._replace(
        hll_per_dst=hll.PerDstHLL(unpack_hll(t.hll_per_dst)),
        hll_per_src=hll.PerDstHLL(unpack_hll(t.hll_per_src)))
    if not fuse_hll_src:
        rest = rest._replace(hll_src=hll.HLL(unpack_hll(t.hll_src)))
    return rest


def interior_encode(ts: TieredState, cm_bytes: TieredPlane,
                    cm_pkts: TieredPlane, hll_src_packed,
                    new_work) -> TieredState:
    """Close one tier-interior fold: the CM planes arrive already promoted
    by the kernel, the global-src bank arrives packed when the fused lane
    folded it (else re-packs from the wide work state), the per-bucket
    grids re-pack losslessly, everything else rides ``new_work``."""
    tables = TieredTables(
        cm_bytes=cm_bytes,
        cm_pkts=cm_pkts,
        hll_src=(hll_src_packed if hll_src_packed is not None
                 else pack_hll(new_work.hll_src.regs)),
        hll_per_dst=pack_hll(new_work.hll_per_dst.regs),
        hll_per_src=pack_hll(new_work.hll_per_src.regs))
    return TieredState(tables, _strip(new_work), ts.spec)


def decode_state(ts: TieredState):
    """The canonical wide SketchState (what roll / state_tables /
    checkpoints see)."""
    spec = ts.spec
    return widen(ts,
                 decode_plane(ts.tables.cm_bytes, spec, spec.bytes_unit),
                 decode_plane(ts.tables.cm_pkts, spec, 1))


def decay_encode(ts: TieredState, wide_decayed,
                 factor: float) -> TieredState:
    """The decayed-window re-encode: CM tiers scale at the representation
    level (:func:`decay_plane` — shared-cell attribution is never
    re-summed, so aliasing cannot compound window over window), the HLL
    banks re-pack from the decayed wide (decay resets their registers),
    everything else rides the decayed wide ``rest``."""
    t = ts.tables
    tables = TieredTables(
        cm_bytes=decay_plane(t.cm_bytes, factor),
        cm_pkts=decay_plane(t.cm_pkts, factor),
        hll_src=pack_hll(wide_decayed.hll_src.regs),
        hll_per_dst=pack_hll(wide_decayed.hll_per_dst.regs),
        hll_per_src=pack_hll(wide_decayed.hll_per_src.regs))
    return TieredState(tables, _strip(wide_decayed), ts.spec)


def encode_state(wide, spec: TierSpec) -> TieredState:
    """From-scratch encode (init / reset-roll / checkpoint restore — paths
    whose wide tables are fresh zeros or a restore). NEVER the decay/keep
    roll path: re-encoding a table whose promoted counters share overflow
    cells re-SUMS the decode's per-member attribution back into the cell
    and compounds it every window — decay rolls go through
    :func:`decay_encode`, keep rolls keep the tier arrays verbatim. On a
    checkpoint restore a shared cell inflates ONCE (overestimate-only,
    bounded, restore-rate); the per-fold path (:func:`fold_encode`) never
    round-trips at all."""
    tables = TieredTables(
        cm_bytes=encode_plane(wide.cm_bytes.counts, spec, spec.bytes_unit),
        cm_pkts=encode_plane(wide.cm_pkts.counts.astype(jnp.float32),
                             spec, 1),
        hll_src=pack_hll(wide.hll_src.regs),
        hll_per_dst=pack_hll(wide.hll_per_dst.regs),
        hll_per_src=pack_hll(wide.hll_per_src.regs))
    return TieredState(tables, _strip(wide), spec)


def fold_encode(ts: TieredState, cmb_wide: jax.Array, cmp_wide: jax.Array,
                new_wide) -> TieredState:
    """Re-encode after one fold: the CM planes advance by the fold's exact
    per-counter delta (new - decoded, untouched counters contribute 0);
    the HLL banks re-pack losslessly; everything else rides ``rest``."""
    spec = ts.spec
    tables = TieredTables(
        cm_bytes=plane_add(ts.tables.cm_bytes,
                           new_wide.cm_bytes.counts - cmb_wide,
                           spec, spec.bytes_unit),
        cm_pkts=plane_add(ts.tables.cm_pkts,
                          new_wide.cm_pkts.counts - cmp_wide, spec, 1),
        hll_src=pack_hll(new_wide.hll_src.regs),
        hll_per_dst=pack_hll(new_wide.hll_per_dst.regs),
        hll_per_src=pack_hll(new_wide.hll_per_src.regs))
    return TieredState(tables, _strip(new_wide), spec)


# --------------------------------------------------------------------------
# accounting (the bench/metrics surface — host-side, never on the fold path)
# --------------------------------------------------------------------------

#: the sketch tables the tiered representation covers — the byte-reduction
#: claim in the bench artifact is computed over exactly these
COUNTER_TABLES = ("cm_bytes", "cm_pkts", "hll_src", "hll_per_dst",
                  "hll_per_src")


def array_bytes(tree) -> int:
    """Total bytes of a pytree's arrays (shape math — no transfer)."""
    return sum(math.prod(leaf.shape) * np.dtype(leaf.dtype).itemsize
               for leaf in jax.tree.leaves(tree))


def counter_table_bytes(state) -> dict[str, int]:
    """Per-table resident bytes of the tier-covered tables, for either
    representation (wide SketchState or TieredState)."""
    if isinstance(state, TieredState):
        t = state.tables
        return {name: array_bytes(getattr(t, name))
                for name in COUNTER_TABLES}
    return {"cm_bytes": array_bytes(state.cm_bytes),
            "cm_pkts": array_bytes(state.cm_pkts),
            "hll_src": array_bytes(state.hll_src),
            "hll_per_dst": array_bytes(state.hll_per_dst),
            "hll_per_src": array_bytes(state.hll_per_src)}


def plane_occupancy(plane: TieredPlane) -> dict[str, int]:
    """Host-side tier occupancy of one CM plane (device->host transfer —
    bench/publish time only)."""
    base = np.asarray(plane.base)
    mid = np.asarray(plane.mid)
    top = np.asarray(plane.top)
    return {
        "base_counters": int(base.size),
        "promoted": int((base == BASE_MAX).sum()),
        "mid_cells": int(mid.size),
        "mid_active": int((mid > 0).sum()),
        "mid_saturated": int((mid == MID_MAX).sum()),
        "top_cells": int(top.size),
        "top_active": int((top > 0).sum()),
        "top_saturated": int((top == TOP_MAX).sum()),
    }
