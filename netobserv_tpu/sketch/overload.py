"""Closed-loop overload control at the exporter seam (the tpu-sketch
admission controller).

When the device folds slower than eviction feeds it, staging-ring slot
waits backpressure the export thread, queues fill, and — with nothing
shedding — the kernel map overflows into the ringbuf fallback where
accuracy silently degrades. SALSA's observation (PAPERS.md) is that
update/merge THROUGHPUT, not sketch math, bounds streaming measurement;
the principled response to overload is therefore *sampling*, which
sketches absorb without bias: the device ingest already de-biases a
per-row ``sampling`` lane (``sketch/state.py`` — ``factor =
max(sampling, 1)`` scales CM bytes/packets, drop mass and the signal
planes), so a host-side 1-in-N thin that multiplies N into each
surviving row's ``sampling`` field keeps the estimates unbiased AND
composes with kernel-configured sampling (the factors multiply).

The controller is AIMD on the shed factor: pressure doubles it
(multiplicative decrease of the admitted fraction — drains a backlog in
O(log) steps), calm subtracts one (additive recovery — probes capacity
gently), and a window roll with no pressure since the last roll snaps it
back to 1 (recovery within one window of the pressure clearing, even on
an idle feed). Pressure is a dimensionless score in "batches":

    score = (pending_rows / batch_size) * busy
            + slot_wait_p95 / SLOT_WAIT_REF_S

``pending_rows`` is the fold backlog at admission time (rows already
buffered plus the incoming eviction); ``busy`` in [0, 1] is the seam's
recent fold-duty fraction (seconds spent folding per second of wall
clock between arrivals, EWMA — measured by the exporter). The weighting
is load-bearing: folds run synchronously on the export thread, so
arrival SIZE alone is not backlog — a healthy device folding a
many-batch eviction instantly must not shed (busy ~0 zeroes the depth
term), while a seam spending its whole wall clock folding (busy ~1)
counts the full depth. ``slot_wait_p95`` comes from the staging ring's
recent-wait window. ``SLOT_WAIT_REF_S`` converts device backpressure
into batch-equivalents: a quarter second of slot wait per fold is
severe (healthy folds measure ~ms, bench.py), so p95 == the reference
counts like one full batch of backlog.

Disabled (``SKETCH_SHED_WATERMARK`` unset) the exporter never constructs
a controller — no RNG, no extra copies, no per-batch branches beyond one
``is None`` check: the same zero-cost bar as tracing and fault points.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from netobserv_tpu.utils import faultinject

#: slot-wait p95 that counts as ONE batch of pending-fold depth in the
#: pressure score (see module docstring)
SLOT_WAIT_REF_S = 0.25

#: feature lanes thinned alongside events (the EvictedFlows parallel
#: arrays; a lane shorter than events — allowed by the pending buffer's
#: zero-pad contract — is thinned over its own prefix)
_LANES = ("extra", "dns", "drops", "xlat", "nevents", "quic")


class OverloadController:
    """AIMD admission control for ``TpuSketchExporter.export_evicted``.

    ``update`` runs once per incoming eviction batch (a stage boundary,
    never per record) and moves the shed factor; ``admit`` applies it.
    Not thread-safe on its own — the exporter calls both under its lock.
    """

    def __init__(self, batch_size: int, watermark: float,
                 shed_max: int = 64, seed: int = 2026, metrics=None):
        if watermark <= 0:
            raise ValueError("watermark must be > 0 (unset disables "
                             "shedding at the exporter instead)")
        self.batch_size = batch_size
        self.high = float(watermark)
        #: hysteresis: recovery starts only below half the high watermark,
        #: so the factor doesn't oscillate across one boundary
        self.low = self.high / 2.0
        self.shed_max = max(2, int(shed_max))
        self.shed = 1
        # fixed schedule under a seeded generator: the unbiasedness suite
        # replays the exact keep/drop decisions (tests/test_overload.py)
        self._rng = np.random.default_rng(seed)
        self._metrics = metrics
        self.shed_rows = 0
        self.shed_batches = 0
        self.last_score = 0.0
        self.last_busy = 0.0
        self._pressured_since_roll = False
        if metrics is not None:
            metrics.sketch_shed_factor.set(1)

    @property
    def overloaded(self) -> bool:
        """True while load is being shed — the /healthz OVERLOADED
        condition (distinct from DEGRADED: the agent is healthy and
        serving, deliberately trading resolution for stability)."""
        return self.shed > 1

    def snapshot(self) -> dict:
        """Machine-readable controller state for the health surface."""
        return {
            "shed_factor": self.shed,
            "shed_max": self.shed_max,
            "watermark": self.high,
            "pressure_score": round(self.last_score, 3),
            "busy": round(self.last_busy, 3),
            "shed_rows": self.shed_rows,
            "shed_batches": self.shed_batches,
        }

    def update(self, pending_rows: int, slot_wait_p95: float,
               busy: float = 1.0) -> int:
        """Move the AIMD factor from the current pressure observation and
        return it. Multiplicative increase above the high watermark,
        additive decrease below the low one, hold in between. ``busy``
        weights the depth term (module docstring) — 1.0 when the caller
        has no duty-cycle measurement."""
        busy = min(1.0, max(0.0, busy))
        score = (pending_rows / self.batch_size) * busy \
            + slot_wait_p95 / SLOT_WAIT_REF_S
        self.last_score = score
        self.last_busy = busy
        if score >= self.high:
            self._pressured_since_roll = True
            if self.shed < self.shed_max:
                self.shed = min(self.shed * 2, self.shed_max)
                self._set_gauge()
        elif score <= self.low and self.shed > 1:
            self.shed -= 1
            self._set_gauge()
        return self.shed

    def window_roll(self) -> None:
        """Called at each window close: a full window with no pressure
        snaps the factor back to 1 (bounded recovery even when the feed
        goes idle and ``update`` stops running)."""
        if not self._pressured_since_roll and self.shed > 1:
            self.shed = 1
            self._set_gauge()
        self._pressured_since_roll = False

    def _set_gauge(self) -> None:
        if self._metrics is not None:
            self._metrics.sketch_shed_factor.set(self.shed)

    def admit(self, evicted):
        """Thin ``evicted`` by the current 1-in-N factor, multiplying N
        into each surviving row's ``sampling`` field (0 = unsampled counts
        as 1, matching the device de-bias; kernel sampling composes
        multiplicatively). Returns ``evicted`` untouched at factor 1;
        otherwise a thinned EvictedFlows carrying the same trace."""
        if self.shed == 1:
            return evicted
        n = len(evicted.events)
        if n == 0:
            return evicted
        # stage-boundary fault seam (chaos suite): per batch, never per row
        faultinject.fire("sketch.overload_shed")
        keep = self._rng.random(n) < (1.0 / self.shed)
        kept = int(keep.sum())
        dropped = n - kept
        self.shed_rows += dropped
        self.shed_batches += 1
        if self._metrics is not None:
            self._metrics.sketch_shed_batches_total.inc()
            if dropped:
                self._metrics.sketch_shed_rows_total.inc(dropped)
        events = evicted.events[keep]  # fancy index: a fresh copy, safe to
        samp = events["stats"]["sampling"]  # scale without aliasing input
        np.multiply(np.maximum(samp, 1), np.uint32(self.shed), out=samp)
        from netobserv_tpu.datapath.fetcher import EvictedFlows
        feats = {}
        for name in _LANES:
            col = getattr(evicted, name, None)
            if col is None or not len(col):
                continue
            # lanes may be shorter than events (zero-pad contract): thin
            # each over its own aligned prefix
            feats[name] = col[keep[:len(col)]]
        thinned = EvictedFlows(events, **feats)
        thinned.decode_stats = evicted.decode_stats
        trace = getattr(evicted, "trace", None)
        if trace is not None:
            thinned.trace = trace
        return thinned


def maybe_controller(batch_size: int, watermark: float, shed_max: int,
                     metrics=None, seed: int = 2026
                     ) -> Optional[OverloadController]:
    """The ONE gate for the zero-cost-disabled contract: an unset/zero
    watermark returns None and the exporter's shed path stays a single
    ``is None`` check."""
    if not watermark or watermark <= 0:
        return None
    return OverloadController(batch_size, watermark, shed_max=shed_max,
                              metrics=metrics, seed=seed)
