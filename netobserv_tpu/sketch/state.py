"""Combined sketch state and the jittable ingest step — the framework's
"flagship model".

One `ingest` call folds a fixed-shape columnar flow batch into:
- Count-Min (bytes) + Count-Min (packets) over the 5-tuple (both f32),
- a top-K heavy-hitter table scored by CM byte estimates,
- a global distinct-source HyperLogLog, a per-destination HLL grid, and a
  per-source (dst, port) fan-out HLL grid (port-scan signal),
- RTT and DNS-latency log-histograms,
- EWMA accumulators per victim bucket: DDoS volume, half-open SYN attempts
  (+ the window's SYN-ACK responses for the offered:accepted ratio), and
  kernel-dropped bytes,
- drop-cause and DSCP histograms, QUIC/NAT marker totals,
- per-direction bytes of each unordered endpoint pair (conversation
  asymmetry — one-way/exfil shape).

The flag/drop/marker inputs ride the dense feed's feature lane (words
16..19, flowpack.cc layout); feeds without those columns simply skip the
corresponding signals (trace-time optional). The streaming-chunk design is
the long-context answer for this domain (SURVEY.md §5.7): state is
constant-size in stream length; batches are the "sequence chunks"; time is
windowed by `roll_window`.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from netobserv_tpu.model.columnar import KEY_WORDS, FlowBatch
from netobserv_tpu.model.flow import TcpFlags
from netobserv_tpu.ops import countmin, ewma, hashing, hll, quantile, topk
from netobserv_tpu.sketch import tiered


class SketchConfig(NamedTuple):
    cm_depth: int = 4
    cm_width: int = 1 << 16
    hll_precision: int = 14
    perdst_buckets: int = 4096
    perdst_precision: int = 6
    # per-SOURCE fan-out grid (port-scan detection): distinct (dst, dport)
    # per source bucket
    persrc_buckets: int = 4096
    persrc_precision: int = 6
    topk: int = 1024
    hist_buckets: int = 1024
    ewma_buckets: int = 4096
    ewma_alpha: float = 0.3
    #: None = auto: the fused MXU one-hot kernel on TPU at eligible widths
    #: (measured faster than the XLA scatter there, docs/tpu_sketch.md);
    #: the scatter everywhere else, incl. CPU where the kernel interprets
    use_pallas: bool | None = None
    #: False skips the per-source fan-out grid fold (port-scan signal) —
    #: the bench A/B switch for attributing its ingest cost
    enable_fanout: bool = True
    #: False skips the conversation-asymmetry fold (one-way detection)
    enable_asym: bool = True
    #: tiered counter planes (SKETCH_TIERED, sketch/tiered.py): the
    #: resident form of the CM planes + HLL banks goes narrow (u8 base +
    #: u16/u32 overflow tiers; 6-bit packed HLL registers), decoded to the
    #: canonical wide tables transiently inside the fold/roll executables.
    #: None (the default) keeps today's wide-resident path bit-identical.
    tiered: "tiered.TierSpec | None" = None

    @classmethod
    def from_agent_config(cls, cfg) -> "SketchConfig":
        raw = str(cfg.sketch_use_pallas).strip().lower()
        if raw in ("auto", ""):
            pallas = None
        else:
            # accept every spelling the old bool field accepted, so an
            # explicit opt-out like SKETCH_USE_PALLAS=0/off stays an opt-out
            pallas = raw in ("1", "true", "yes", "on")
        tiers = None
        if getattr(cfg, "sketch_tiered", False):
            tiers = tiered.TierSpec(
                mid_group=cfg.sketch_tier_mid_group,
                top_group=cfg.sketch_tier_top_group,
                bytes_unit=cfg.sketch_tier_bytes_unit)
        return cls(cm_depth=cfg.sketch_cm_depth, cm_width=cfg.sketch_cm_width,
                   hll_precision=cfg.sketch_hll_precision, topk=cfg.sketch_topk,
                   ewma_alpha=cfg.sketch_ewma_alpha,
                   use_pallas=pallas, tiered=tiers)


class SketchState(NamedTuple):
    cm_bytes: countmin.CountMin
    cm_pkts: countmin.CountMin
    # persistent-slot heavy-hitter table (ops/topk.SlotTable): rows keep
    # stable per-key identity across folds AND window rolls, so the roll
    # ships a ready top-K with per-key churn (counts vs prev_counts,
    # first_seen, epoch) — candidate maintenance lives in the batch walk
    heavy: topk.SlotTable
    hll_src: hll.HLL
    hll_per_dst: hll.PerDstHLL
    hll_per_src: hll.PerDstHLL  # fan-out grid: distinct (dst,port) per src
    hist_rtt: quantile.LogHist
    hist_dns: quantile.LogHist
    ddos: ewma.EWMA
    # SYN-flood signal: EWMA of half-open SYN attempts per victim bucket,
    # plus this window's SYN-ACK responses in the SAME buckets (the ratio
    # denominator; a flooded service accepts far fewer than it is offered)
    syn: ewma.EWMA
    synack: jax.Array         # f32[m] — current-window SYN-ACK responses
    # drop-anomaly signal: EWMA of dropped bytes per victim bucket
    drops_ewma: ewma.EWMA
    drop_causes: jax.Array    # f32[N_DROP_CAUSES] — window drop pkts by cause
    dscp_bytes: jax.Array     # f32[N_DSCP] — window bytes by DSCP class
    # conversation-asymmetry signal: bytes per DIRECTION of each unordered
    # endpoint pair (one-way elephants = exfiltration / UDP-flood shape).
    # The bucket hash is direction-invariant (sum of the two endpoint
    # hashes under one seed); "fwd" is the canonical lower-hash endpoint
    conv_fwd: jax.Array       # f32[m]
    conv_rev: jax.Array       # f32[m]
    total_records: jax.Array  # f32[] — window totals
    total_bytes: jax.Array    # f32[]
    total_drop_bytes: jax.Array    # f32[]
    total_drop_packets: jax.Array  # f32[]
    quic_records: jax.Array   # f32[] — window records with QUIC marker
    nat_records: jax.Array    # f32[] — window records with a NAT translation
    # valid slot-table occupants evicted by heavier challengers this window
    # (the churn record's eviction pressure scalar)
    heavy_evictions: jax.Array  # f32[]
    window: jax.Array         # i32[]


class WindowReport(NamedTuple):
    """Snapshot emitted at each window roll (still on device until pulled)."""

    heavy: topk.SlotTable
    distinct_src: jax.Array        # f32[] global cardinality estimate
    per_dst_cardinality: jax.Array  # f32[D]
    per_src_fanout: jax.Array       # f32[S] distinct (dst,port) per src bucket
    rtt_quantiles_us: jax.Array    # f32[5] for q = .5 .9 .95 .99 .999
    dns_quantiles_us: jax.Array    # f32[5]
    ddos_z: jax.Array              # f32[m] z-score per dst bucket
    syn_z: jax.Array               # f32[m] half-open SYN surge z per bucket
    syn_rate: jax.Array            # f32[m] this window's half-open attempts
    synack_rate: jax.Array         # f32[m] this window's SYN-ACK responses
    drop_z: jax.Array              # f32[m] dropped-bytes surge z per bucket
    drop_causes: jax.Array         # f32[N_DROP_CAUSES] drop pkts by cause
    dscp_bytes: jax.Array          # f32[N_DSCP] bytes by DSCP class
    conv_fwd: jax.Array            # f32[m] bytes toward the canonical dir
    conv_rev: jax.Array            # f32[m] bytes the other way
    total_records: jax.Array
    total_bytes: jax.Array
    total_drop_bytes: jax.Array
    total_drop_packets: jax.Array
    quic_records: jax.Array
    nat_records: jax.Array
    heavy_evictions: jax.Array
    window: jax.Array


QS = np.array([0.5, 0.9, 0.95, 0.99, 0.999], dtype=np.float32)

#: drop-cause histogram size — kernel SKB_DROP_REASON values clamp to the
#: last bucket (the enum tops out well below this; cf. reference
#: pkg/decode drop-cause table)
N_DROP_CAUSES = 128
#: DSCP class histogram size (6-bit code space)
N_DSCP = 64


def init_state(cfg: SketchConfig = SketchConfig()):
    if cfg.tiered is not None:
        # tiered counter planes (SKETCH_TIERED): encode a fresh wide state
        # — from zeros, the encode is exact. Everything downstream
        # branches on the state's TYPE, so this is the ONE entry gate.
        cfg.tiered.check(cfg.cm_width)
        return tiered.encode_state(init_state(cfg._replace(tiered=None)),
                                   cfg.tiered)
    return SketchState(
        # both counter planes are float32: packet counts stay exact below
        # 2^24 per window, and a single dtype lets the Pallas fold serve both
        cm_bytes=countmin.init(cfg.cm_depth, cfg.cm_width, jnp.float32),
        cm_pkts=countmin.init(cfg.cm_depth, cfg.cm_width, jnp.float32),
        heavy=topk.init_slots(cfg.topk, KEY_WORDS),
        hll_src=hll.init(cfg.hll_precision),
        hll_per_dst=hll.init_per_dst(cfg.perdst_buckets, cfg.perdst_precision),
        hll_per_src=hll.init_per_dst(cfg.persrc_buckets,
                                     cfg.persrc_precision),
        hist_rtt=quantile.init(cfg.hist_buckets),
        hist_dns=quantile.init(cfg.hist_buckets),
        ddos=ewma.init(cfg.ewma_buckets),
        syn=ewma.init(cfg.ewma_buckets),
        synack=jnp.zeros((cfg.ewma_buckets,), jnp.float32),
        drops_ewma=ewma.init(cfg.ewma_buckets),
        drop_causes=jnp.zeros((N_DROP_CAUSES,), jnp.float32),
        dscp_bytes=jnp.zeros((N_DSCP,), jnp.float32),
        conv_fwd=jnp.zeros((cfg.ewma_buckets,), jnp.float32),
        conv_rev=jnp.zeros((cfg.ewma_buckets,), jnp.float32),
        total_records=jnp.zeros((), jnp.float32),
        total_bytes=jnp.zeros((), jnp.float32),
        total_drop_bytes=jnp.zeros((), jnp.float32),
        total_drop_packets=jnp.zeros((), jnp.float32),
        quic_records=jnp.zeros((), jnp.float32),
        nat_records=jnp.zeros((), jnp.float32),
        heavy_evictions=jnp.zeros((), jnp.float32),
        window=jnp.zeros((), jnp.int32),
    )


def batch_to_device(batch: FlowBatch) -> dict[str, np.ndarray]:
    """Convert a host FlowBatch into the dtype-stable array dict the jitted
    ingest expects (bytes to float32 — u64 is unavailable without x64; sketch
    counters are float anyway)."""
    return {
        "keys": batch.keys.astype(np.uint32),
        "bytes": batch.bytes.astype(np.float32),
        "packets": batch.packets.astype(np.int32),
        "rtt_us": batch.rtt_us.astype(np.int32),
        "dns_latency_us": batch.dns_latency_us.astype(np.int32),
        "valid": batch.valid.astype(np.bool_),
        "sampling": batch.sampling.astype(np.int32),
        "tcp_flags": batch.tcp_flags.astype(np.int32),
        "dscp": batch.dscp.astype(np.int32),
        "drop_bytes": batch.drop_bytes.astype(np.int32),
        "drop_packets": batch.drop_packets.astype(np.int32),
    }


DENSE_WORDS = 20  # row width; must equal flowpack.DENSE_WORDS (layout twin)


def dense_to_arrays(dense: jax.Array) -> dict[str, jax.Array]:
    """Device-side unpack of the flowpack dense feed — one host->device
    transfer per batch instead of many (the transfer link, not compute,
    bounds the host path on tunneled/PCIe chips). Accepts the batch either
    as (B, 20) rows or FLAT (B*20,) — flat is how the staging ring ships it:
    a 1-D transfer avoids the device tiling pad a 20-wide minor dimension
    suffers (measured 1.5-8x transfer inflation on the axon chip), and the
    reshape here fuses into the ingest executable. Row layout is pinned in
    flowpack.cc fp_pack_dense."""
    if dense.ndim == 1:
        dense = dense.reshape(-1, DENSE_WORDS)
    return {
        "keys": dense[:, :KEY_WORDS],
        "bytes": jax.lax.bitcast_convert_type(dense[:, 10], jnp.float32),
        "packets": dense[:, 11].astype(jnp.int32),
        "rtt_us": dense[:, 12].astype(jnp.int32),
        "dns_latency_us": dense[:, 13].astype(jnp.int32),
        "valid": dense[:, 14] != 0,
        "sampling": dense[:, 15].astype(jnp.int32),
        "tcp_flags": (dense[:, 16] & jnp.uint32(0xFFFF)).astype(jnp.int32),
        "dscp": ((dense[:, 16] >> 16) & jnp.uint32(0xFF)).astype(jnp.int32),
        "markers": (dense[:, 16] >> 24).astype(jnp.int32),
        "drop_bytes": (dense[:, 17] & jnp.uint32(0xFFFF)).astype(jnp.int32),
        "drop_packets": (dense[:, 17] >> 16).astype(jnp.int32),
        "drop_cause": (dense[:, 18] & jnp.uint32(0xFFFF)).astype(jnp.int32),
    }


def arrays_to_dense(arrays: dict[str, np.ndarray]) -> np.ndarray:
    """Host-side inverse of dense_to_arrays: pack an array dict into the
    flat flowpack dense feed — the one Python twin of the row layout pinned
    in flowpack.cc fp_pack_dense (tests and the dryrun build synthetic
    batches through here so a layout change has a single site). The feature
    columns (tcp_flags/dscp/markers/drop_*) are optional — absent keys pack
    as zero, matching a datapath with those trackers disabled."""
    n = len(arrays["valid"])
    zeros = np.zeros(n, np.uint32)

    def col(name):
        return np.asarray(arrays.get(name, zeros), np.uint32)

    dense = np.zeros((n, DENSE_WORDS), np.uint32)
    dense[:, :KEY_WORDS] = arrays["keys"]
    dense[:, 10] = np.asarray(arrays["bytes"], np.float32).view(np.uint32)
    dense[:, 11] = arrays["packets"]
    dense[:, 12] = arrays["rtt_us"]
    dense[:, 13] = arrays["dns_latency_us"]
    dense[:, 14] = np.asarray(arrays["valid"], np.uint32)
    dense[:, 15] = col("sampling")
    dense[:, 16] = ((col("tcp_flags") & 0xFFFF) | (col("dscp") << 16)
                    | (col("markers") << 24))
    # saturate the 16-bit drop lanes like flowpack.cc fill_feature_words
    # (the C side's inputs are u16 by dtype; this twin takes arbitrary
    # ints and must not bleed bits into the adjacent lane)
    dense[:, 17] = (np.minimum(col("drop_bytes"), 0xFFFF)
                    | (np.minimum(col("drop_packets"), 0xFFFF) << 16))
    dense[:, 18] = np.minimum(col("drop_cause"), 0xFFFF)
    return dense.reshape(-1)


class _TierHook:
    """Trace-time mailbox of one tier-interior fold: carries the resident
    TieredState into the body's branch points (so the CM walk folds the
    tier arrays directly and the fused signal walk folds the packed
    global-src bank) and collects the kernels' tier outputs for
    :func:`tiered.interior_encode`. Plain-Python mutation is safe here —
    tracing is linear and the hook never crosses a jit boundary."""

    __slots__ = ("state", "fuse_hll", "out")

    def __init__(self, state, fuse_hll: bool):
        self.state = state
        self.fuse_hll = fuse_hll
        self.out: dict = {}


def _tier_interior_ok(state) -> bool:
    """Static eligibility of the tier-interior Pallas walk (trace-time)."""
    from netobserv_tpu.ops.pallas import countmin_kernel
    width = state.tables.cm_bytes.base.shape[1]
    return countmin_kernel.tiered_eligible(width, state.spec)


def tiered_fold_form(cfg: SketchConfig) -> str | None:
    """Which fold form a tiered pipeline under ``cfg`` engages on THIS
    backend: ``"interior"`` (tier-native Pallas walk), ``"decode"``
    (decode-to-wide wrap), or None when tiers are off. Mirrors the
    trace-time gate in :func:`ingest` — accounting/attribution only."""
    if cfg.tiered is None:
        return None
    up = cfg.use_pallas
    if up is None:
        up = jax.default_backend() == "tpu" and cfg.cm_width >= 16384
    if up:
        from netobserv_tpu.ops.pallas import countmin_kernel
        if countmin_kernel.tiered_eligible(cfg.cm_width, cfg.tiered):
            return "interior"
    return "decode"


def ingest(state: SketchState, arrays: dict[str, jax.Array],
           sketch_axis: str | None = None, sketch_shards: int = 1,
           use_pallas: bool | None = None,
           enable_fanout: bool = True,
           enable_asym: bool = True,
           tier_interior: bool | None = None,
           _tier: "_TierHook | None" = None) -> SketchState:
    """Fold one batch into all sketches. Pure; jit with donate_argnums=0.

    When `sketch_axis` is set (inside shard_map over a 2D mesh), the Count-Min
    arrays are width-sharded across that axis: updates mask out-of-shard
    columns, queries psum partial gathers (model-parallel sketches).

    Width-sharded (2D mesh) steady state performs NO collectives at all: the
    Count-Min is sharded by KEY OWNERSHIP (`countmin.owner_shard`), so each
    sketch shard folds and point-queries its own keys entirely locally
    (`query_sharded_local`) and keeps a top-K table of just its keys. The
    one psum-backed exact query (`query_sharded`) runs only inside the
    window-roll merge, which gathers per-shard tables and re-scores against
    the globally merged sketch (`parallel.merge.merge_states`).
    """
    if isinstance(state, tiered.TieredState):
        # tiered counter planes: decode the resident tiers to the canonical
        # wide tables TRANSIENTLY (inside this same executable), run the
        # exact same fold below — both equivalence-pinned forms (scatter
        # chain and Pallas walk) unchanged — then fold the per-counter
        # delta back through the saturation-promotion path. Static branch:
        # resolved at trace time, the wide path is untouched when disabled.
        if sketch_axis is not None:
            raise NotImplementedError(
                "SKETCH_TIERED has no owner-sharded form yet — tiered "
                "counter planes are single-device (config.validate blocks "
                "SKETCH_MESH_SHAPE with SKETCH_TIERED)")
        spec = state.spec
        up = use_pallas
        if up is None:  # the same auto rule as the wide path, tier widths
            up = (jax.default_backend() == "tpu"
                  and state.tables.cm_bytes.base.shape[1] >= 16384)
        if up and tier_interior is not False and _tier_interior_ok(state):
            # TIER-INTERIOR fold: the Pallas walks read/promote the narrow
            # tier arrays directly in VMEM — no wide CM temporary in HBM.
            # The decode-wrapped path below stays verbatim as the scatter
            # twin / equivalence oracle (tests/test_tiered.py pins
            # interior vs decode-wrapped-scatter bit-exact).
            from netobserv_tpu.ops.pallas import signal_kernel
            r = state.rest
            probe = signal_kernel.SignalPlanes(
                ddos_rate=r.ddos.rate, syn_rate=r.syn.rate,
                drops_rate=r.drops_ewma.rate, synack=r.synack,
                conv_fwd=r.conv_fwd, conv_rev=r.conv_rev,
                dscp_bytes=r.dscp_bytes, drop_causes=r.drop_causes)
            m_hll = state.tables.hll_src.shape[0] // 3 * 4
            fuse = (signal_kernel.eligible(probe)
                    and signal_kernel.hll_fusible(m_hll))
            hook = _TierHook(state, fuse)
            work = tiered.widen_interior(state, fuse)
            new_work = ingest(work, arrays, use_pallas=True,
                              enable_fanout=enable_fanout,
                              enable_asym=enable_asym, _tier=hook)
            return tiered.interior_encode(
                state, hook.out["cm_bytes"], hook.out["cm_pkts"],
                hook.out.get("hll_src"), new_work)
        cmb_wide = tiered.decode_plane(state.tables.cm_bytes, spec,
                                       spec.bytes_unit)
        cmp_wide = tiered.decode_plane(state.tables.cm_pkts, spec, 1)
        new_wide = ingest(tiered.widen(state, cmb_wide, cmp_wide), arrays,
                          use_pallas=use_pallas,
                          enable_fanout=enable_fanout,
                          enable_asym=enable_asym)
        return tiered.fold_encode(state, cmb_wide, cmp_wide, new_wide)
    if use_pallas is None:
        # auto: the fused kernels (Count-Min fold + HLL) win on TPU at and
        # above the measured ~16K-width crossover (docs/tpu_sketch.md);
        # below it — and everywhere off-TPU — the scatter is faster
        use_pallas = (jax.default_backend() == "tpu"
                      and state.cm_bytes.width >= 16384)
    words = arrays["keys"]
    valid = arrays["valid"]
    bytes_f = arrays["bytes"]
    pkts = arrays["packets"]
    samp = arrays.get("sampling")
    if samp is not None:
        # de-bias sampled traffic: a 1-in-N sampled flow record stands for N
        # flows' worth of volume (reference scales at the collector via the
        # exported Sampling field; sketches must fold the scaled estimate or
        # heavy-hitter/volume numbers undercount). 0 = unsampled. The
        # overload controller (sketch/overload.py) leans on exactly this
        # lane: host-side shedding multiplies its 1-in-N factor into each
        # surviving row's sampling, so kernel sampling and overload shed
        # compose multiplicatively and both de-bias HERE — any change to
        # this factor changes the shed-unbiasedness contract pinned by
        # tests/test_overload.py.
        factor = jnp.maximum(samp, 1)
        bytes_f = bytes_f * factor.astype(jnp.float32)
        pkts = pkts * factor

    # ONE sweep computes every hash family (flow h1/h2, src bucket, dst
    # bucket, dst-port fan-out, src-sym): the murmur k-mix per key word is
    # shared across families instead of five independent base_hashes passes
    mhash = hashing.base_hashes_multi(words)
    h1, h2 = mhash.h1, mhash.h2
    src_h1, src_h2 = mhash.src_h1, mhash.src_h2
    dst_h1 = mhash.dst_h1

    if sketch_axis is None:
        # tier-interior first: the CM fields here are zero-size
        # placeholders (whose width trivially tiles) — the walk reads and
        # promotes the resident tier arrays directly
        if _tier is not None:
            from netobserv_tpu.ops.pallas import countmin_kernel
            t = _tier.state.tables
            new_cmb, new_cmp, est = countmin_kernel.update_two_tiered(
                t.cm_bytes, t.cm_pkts, h1, h2, bytes_f,
                pkts.astype(jnp.float32), valid, _tier.state.spec)
            _tier.out["cm_bytes"] = new_cmb
            _tier.out["cm_pkts"] = new_cmp
            cm_b, cm_p = state.cm_bytes, state.cm_pkts  # stay placeholders
            # the kernel already gathered the post-fold bytes estimate
            # from its transient wide view — exactly countmin.query of the
            # decode-wrapped form's cm_b
            heavy, evicted = topk.slot_update(
                state.heavy, cm_b, words, h1, h2, valid,
                query_fn=lambda a, b: est,
                window=state.window,
                use_pallas=state.heavy.k % 128 == 0)
        else:
            # the Pallas kernel needs the width to tile; silently use the
            # XLA scatter otherwise (static check, resolved at trace time)
            if use_pallas and state.cm_bytes.width % 512 == 0:
                from netobserv_tpu.ops.pallas import countmin_kernel
                # fused: both planes share hash indices + one-hot build
                cm_b, cm_p = countmin_kernel.update_two(
                    state.cm_bytes, state.cm_pkts, h1, h2, bytes_f,
                    pkts.astype(jnp.float32), valid)
            else:
                cm_b, cm_p = countmin.update_two(
                    state.cm_bytes, state.cm_pkts, h1, h2, bytes_f, pkts,
                    valid)
            # persistent-slot maintenance in the batch walk: the fused
            # Pallas reduction twin engages with the other kernels
            # (lane-aligned K); the scatter form everywhere else —
            # bit-exact either way (tests/test_pallas_topk.py pins it)
            heavy, evicted = topk.slot_update(
                state.heavy, cm_b, words, h1, h2, valid,
                window=state.window,
                use_pallas=use_pallas and state.heavy.k % 128 == 0)
    else:
        cm_b = countmin.update_sharded(state.cm_bytes, h1, h2, bytes_f, valid,
                                       sketch_axis, sketch_shards)
        cm_p = countmin.update_sharded(state.cm_pkts, h1, h2, pkts, valid,
                                       sketch_axis, sketch_shards)
        # collective-free scoring: this shard fully owns its keys' counters,
        # so its table tracks exactly the keys it owns (the merge gathers
        # tables across the sketch axis and re-scores globally)
        heavy, evicted = topk.slot_update(
            state.heavy, cm_b, words, h1, h2, valid,
            query_fn=lambda a, b: countmin.query_sharded_local(
                cm_b, a, b, sketch_axis, sketch_shards),
            window=state.window)
    if _tier is not None and _tier.fuse_hll:
        # the global-src bank stays 6-bit packed; the fused signal walk
        # below folds it and stashes the new packed bank in the hook
        hll_src = state.hll_src  # zero-size placeholder
    elif (use_pallas and sketch_axis is None
            and state.hll_src.regs.shape[0] % 512 == 0):
        from netobserv_tpu.ops.pallas import hll_kernel
        hll_src = hll_kernel.update(state.hll_src, src_h1, src_h2, valid)
    else:
        hll_src = hll.update(state.hll_src, src_h1, src_h2, valid)
    per_dst = hll.update_per_dst(state.hll_per_dst, dst_h1, src_h1, src_h2, valid)
    flags = arrays.get("tcp_flags")
    if enable_fanout:
        # port-scan signal: distinct (dst addr, dst port) fan-out per SOURCE
        # bucket — a scanner touches many; a normal client few. The (dst,
        # port) hashes come from the shared multi-hash sweep above (seed:
        # hashing.DSTPORT_FANOUT_SEED). Only INITIATOR-side flows count:
        # a flow that sent SYN+ACK together (the TcpFlags.SYN_ACK
        # composite) is a RESPONDER — without the gate a server answering
        # one NAT'd client churning through hundreds of source ports
        # sweeps hundreds of distinct (addr, port) pairs and lights the
        # grid (the nat_churn scenario). Initiators count whether the
        # handshake completed or not (SYN with or without a later ACK),
        # so both lone-SYN and full-connect scans fire; flows with no
        # SYN-side evidence at all (non-TCP rows, mid-capture sessions:
        # flags without SYN) keep the pre-gate behavior only when they
        # are not responders.
        fanout_valid = valid
        if flags is not None:
            f32 = flags.astype(jnp.int32)
            fanout_valid = valid & ((f32 & TcpFlags.SYN_ACK) == 0)
        per_src = hll.update_per_dst(state.hll_per_src, src_h1, mhash.dp_h1,
                                     mhash.dp_h2, fanout_valid)
    else:
        per_src = state.hll_per_src
    rtt = arrays["rtt_us"]
    dns = arrays["dns_latency_us"]
    gamma = quantile.gamma_for(state.hist_rtt.n_buckets)
    hist_rtt = quantile.update(state.hist_rtt, rtt, valid & (rtt > 0), gamma)
    hist_dns = quantile.update(state.hist_dns, dns, valid & (dns > 0), gamma)
    # --- signal planes (trace-time optional feature columns: a feed
    # without a column — e.g. the legacy six-array dict — simply skips the
    # corresponding signal; the fused kernel receives a zero value row
    # instead, which is bit-identical to skipping) ---
    # conversation asymmetry hashes BOTH endpoints under one seed so the
    # pair bucket is direction-invariant (A->B and B->A land together);
    # the lower endpoint hash defines the canonical "fwd" direction.
    # src_sym hashes the src words under the dst seed — also exactly the
    # victim-bucket hash the SYN-ACK side needs.
    src_sym = mhash.src_sym
    mass = factor.astype(jnp.float32) if samp is not None else 1.0
    if flags is not None:  # read above, at the fan-out gate
        # SYN-flood: half-open attempts (SYN seen, never ACKed — a spoofed
        # flood leaves one such record per probe) bucket by victim = dst;
        # SYN-ACK response flows bucket by victim = src (the responder),
        # using the SAME hash seed so both land in one bucket per victim.
        # Flag bits ride the dense feed from the datapath's OR-accumulated
        # tcp_flags (reference exports them per flow, proto/flow.proto:30).
        f = flags.astype(jnp.int32)
        half_open = valid & ((f & TcpFlags.SYN) != 0) & \
            ((f & TcpFlags.ACK) == 0)
        is_synack = valid & ((f & TcpFlags.SYN_ACK) != 0)
    dscp = arrays.get("dscp")
    db = arrays.get("drop_bytes")
    cause = arrays.get("drop_cause") if db is not None else None
    tdb, tdp = state.total_drop_bytes, state.total_drop_packets
    if db is not None:
        dbf = db.astype(jnp.float32) * mass
        dpf = arrays["drop_packets"].astype(jnp.float32) * mass
        tdb = tdb + jnp.sum(jnp.where(valid, dbf, 0.0))
        tdp = tdp + jnp.sum(jnp.where(valid, dpf, 0.0))
    if enable_asym:
        pair_idx = ((src_sym + dst_h1)
                    & jnp.uint32(state.conv_fwd.shape[0] - 1)).astype(jnp.int32)
        is_fwd = src_sym < dst_h1
        # self-pairs (src == dst: hairpin NAT, loopback capture) have no
        # meaningful direction — both ways would land "fwd" and fire a
        # false one-way alert every window; exclude them from the signal
        conv_ok = valid & (src_sym != dst_h1)

    use_signal_kernel = use_pallas and sketch_axis is None
    if use_signal_kernel:
        from netobserv_tpu.ops.pallas import signal_kernel
        planes = signal_kernel.SignalPlanes(
            ddos_rate=state.ddos.rate, syn_rate=state.syn.rate,
            drops_rate=state.drops_ewma.rate, synack=state.synack,
            conv_fwd=state.conv_fwd, conv_rev=state.conv_rev,
            dscp_bytes=state.dscp_bytes, drop_causes=state.drop_causes)
        use_signal_kernel = signal_kernel.eligible(planes)
    if use_signal_kernel:
        # fused signal-plane fold: all eight scatter targets update in ONE
        # Pallas batch walk (ops/pallas/signal_kernel.py); absent feature
        # columns contribute zero-mass rows — bit-identical to skipping
        m_sig = state.conv_fwd.shape[0]
        zeros_b = jnp.zeros_like(bytes_f)
        izeros_b = jnp.zeros(bytes_f.shape, jnp.int32)
        dst_idx = (dst_h1 & jnp.uint32(m_sig - 1)).astype(jnp.int32)
        src_idx = (src_sym & jnp.uint32(m_sig - 1)).astype(jnp.int32)
        v_ddos = jnp.where(valid, bytes_f, 0.0)
        if flags is not None:
            v_syn = jnp.where(half_open, mass, 0.0)
            v_synack = jnp.where(is_synack, mass, 0.0)
        else:
            v_syn = v_synack = zeros_b
        if db is not None:
            v_drops = jnp.where(valid, dbf, 0.0)
        else:
            v_drops = zeros_b
        if cause is not None:
            cause_idx = jnp.minimum(cause.astype(jnp.int32),
                                    N_DROP_CAUSES - 1)
            v_cause = jnp.where(valid & (dpf > 0), dpf, 0.0)
        else:
            cause_idx, v_cause = izeros_b, zeros_b
        if enable_asym:
            v_fwd = jnp.where(conv_ok & is_fwd, bytes_f, 0.0)
            v_rev = jnp.where(conv_ok & ~is_fwd, bytes_f, 0.0)
        else:
            pair_idx, v_fwd, v_rev = izeros_b, zeros_b, zeros_b
        if dscp is not None:
            dscp_idx = dscp.astype(jnp.int32) & (N_DSCP - 1)
            v_dscp = jnp.where(valid, bytes_f, 0.0)
        else:
            dscp_idx, v_dscp = izeros_b, zeros_b
        sig_idx = jnp.stack([dst_idx, src_idx, pair_idx, dscp_idx,
                             cause_idx])
        sig_vals = jnp.stack([v_ddos, v_syn, v_drops, v_synack, v_fwd,
                              v_rev, v_dscp, v_cause])
        if _tier is not None and _tier.fuse_hll:
            # tiered megakernel: the same signal fold plus the packed
            # global-src HLL lane in one walk (idx/rank mirror
            # hll_kernel.update exactly — max fold, bit-exact)
            packed = _tier.state.tables.hll_src
            m_hll = packed.shape[0] // 3 * 4
            hll_idx = (src_h1 & jnp.uint32(m_hll - 1)).astype(jnp.int32)
            hll_rank = jnp.where(valid, hll._rank(src_h2), 0)
            out, new_packed = signal_kernel.update_tiered(
                planes, packed, sig_idx, sig_vals, hll_idx, hll_rank)
            _tier.out["hll_src"] = new_packed
        else:
            out = signal_kernel.update(planes, sig_idx, sig_vals)
        ddos = state.ddos._replace(rate=out.ddos_rate)
        syn_state = state.syn._replace(rate=out.syn_rate)
        drops_state = state.drops_ewma._replace(rate=out.drops_rate)
        synack_arr = out.synack
        conv_fwd, conv_rev = out.conv_fwd, out.conv_rev
        dscp_bytes, drop_causes = out.dscp_bytes, out.drop_causes
    else:
        # un-fused scatter chain (CPU / owner-sharded / ineligible shapes)
        # — the fused kernel above is equivalence-pinned against exactly
        # this path (tests/test_pallas_signal.py)
        ddos = ewma.accumulate(state.ddos, dst_h1, bytes_f, valid)
        if enable_asym:
            conv_fwd = state.conv_fwd.at[pair_idx].add(
                jnp.where(conv_ok & is_fwd, bytes_f, 0.0), mode="drop")
            conv_rev = state.conv_rev.at[pair_idx].add(
                jnp.where(conv_ok & ~is_fwd, bytes_f, 0.0), mode="drop")
        else:
            conv_fwd, conv_rev = state.conv_fwd, state.conv_rev
        syn_state, synack_arr = state.syn, state.synack
        if flags is not None:
            syn_state = ewma.accumulate(state.syn, dst_h1,
                                        jnp.where(half_open, mass, 0.0),
                                        valid)
            sa_idx = (src_sym & jnp.uint32(state.synack.shape[0] - 1)
                      ).astype(jnp.int32)
            synack_arr = state.synack.at[sa_idx].add(
                jnp.where(is_synack, mass, 0.0), mode="drop")
        dscp_bytes = state.dscp_bytes
        if dscp is not None:
            dscp_bytes = dscp_bytes.at[
                dscp.astype(jnp.int32) & (N_DSCP - 1)].add(
                jnp.where(valid, bytes_f, 0.0), mode="drop")
        drops_state, drop_causes = state.drops_ewma, state.drop_causes
        if db is not None:
            drops_state = ewma.accumulate(state.drops_ewma, dst_h1, dbf,
                                          valid)
        if cause is not None:
            ci = jnp.minimum(cause.astype(jnp.int32), N_DROP_CAUSES - 1)
            drop_causes = drop_causes.at[ci].add(
                jnp.where(valid & (dpf > 0), dpf, 0.0), mode="drop")
    mk = arrays.get("markers")
    quic_rec, nat_rec = state.quic_records, state.nat_records
    if mk is not None:
        mki = mk.astype(jnp.int32)
        quic_rec = quic_rec + jnp.sum(
            (valid & ((mki & 1) != 0)).astype(jnp.float32))
        nat_rec = nat_rec + jnp.sum(
            (valid & ((mki & 2) != 0)).astype(jnp.float32))

    return SketchState(
        cm_bytes=cm_b, cm_pkts=cm_p, heavy=heavy, hll_src=hll_src,
        hll_per_dst=per_dst, hll_per_src=per_src, hist_rtt=hist_rtt,
        hist_dns=hist_dns, ddos=ddos,
        syn=syn_state, synack=synack_arr, drops_ewma=drops_state,
        drop_causes=drop_causes, dscp_bytes=dscp_bytes,
        conv_fwd=conv_fwd, conv_rev=conv_rev,
        total_records=state.total_records + jnp.sum(valid.astype(jnp.float32)),
        total_bytes=state.total_bytes + jnp.sum(
            jnp.where(valid, bytes_f, 0.0)),
        total_drop_bytes=tdb, total_drop_packets=tdp,
        quic_records=quic_rec, nat_records=nat_rec,
        heavy_evictions=state.heavy_evictions + evicted,
        window=state.window,
    )


def make_ingest_fn(donate: bool = True,
                   use_pallas: bool | None = None,
                   enable_fanout: bool = True,
                   enable_asym: bool = True,
                   tier_interior: bool | None = None):
    """Jitted ingest; donates the state buffers so updates are in-place on HBM."""
    fn = lambda s, a: ingest(s, a, use_pallas=use_pallas,  # noqa: E731
                             enable_fanout=enable_fanout,
                             enable_asym=enable_asym,
                             tier_interior=tier_interior)
    return jax.jit(fn, donate_argnums=(0,) if donate else ())


COMPACT_WORDS = 10  # must equal flowpack.COMPACT_WORDS (layout twin)
_V4_PREFIX_WORD2 = 0xFFFF0000  # bytes 8..11 of a v4-in-v6 mapped address


def compact_to_arrays(flat: jax.Array, batch_size: int,
                      spill_cap: int) -> dict[str, jax.Array]:
    """Device-side unpack of the flowpack COMPACT feed (flat
    `[batch_size*10 v4 rows | spill_cap*20 dense rows]`, layout pinned in
    flowpack.cc fp_pack_compact). Reconstructs full 10-word v4-mapped keys
    from the 4-word compact form and concatenates the spill lane, yielding
    one (batch_size + spill_cap)-row array dict for the ordinary ingest —
    the row widening happens in HBM where bandwidth is ~free; the transfer
    link only ever saw ~half of the dense feed's bytes. Drop columns are
    zero on the compact lane by construction: drop-carrying rows always
    ride the spill lane (fp_pack_compact routes them there)."""
    c = flat[:batch_size * COMPACT_WORDS].reshape(batch_size, COMPACT_WORDS)
    spill = dense_to_arrays(
        flat[batch_size * COMPACT_WORDS:].reshape(spill_cap, DENSE_WORDS))
    zeros = jnp.zeros((batch_size,), jnp.uint32)
    prefix = jnp.full((batch_size,), _V4_PREFIX_WORD2, jnp.uint32)
    keys = jnp.stack(
        [zeros, zeros, prefix, c[:, 0],
         zeros, zeros, prefix, c[:, 1],
         c[:, 2], c[:, 3] & jnp.uint32(0x00FFFFFF)], axis=1)
    izeros = zeros.astype(jnp.int32)
    comp = {
        "keys": keys,
        "bytes": jax.lax.bitcast_convert_type(c[:, 4], jnp.float32),
        "packets": c[:, 5].astype(jnp.int32),
        "rtt_us": c[:, 6].astype(jnp.int32),
        "dns_latency_us": c[:, 7].astype(jnp.int32),
        "valid": (c[:, 3] & jnp.uint32(0x80000000)) != 0,
        "sampling": c[:, 8].astype(jnp.int32),
        "tcp_flags": (c[:, 9] & jnp.uint32(0xFFFF)).astype(jnp.int32),
        "dscp": ((c[:, 9] >> 16) & jnp.uint32(0xFF)).astype(jnp.int32),
        "markers": (c[:, 9] >> 24).astype(jnp.int32),
        "drop_bytes": izeros,
        "drop_packets": izeros,
        "drop_cause": izeros,
    }
    return {k: jnp.concatenate([comp[k], spill[k]], axis=0) for k in comp}


def make_ingest_compact_fn(batch_size: int, spill_cap: int,
                           donate: bool = True,
                           use_pallas: bool | None = None,
                           with_token: bool = False,
                           enable_fanout: bool = True,
                           enable_asym: bool = True):
    """Jitted `(state, flat compact feed) -> state` (see compact_to_arrays /
    flowpack.pack_compact). `with_token` as in make_ingest_dense_fn."""
    def fn(s, flat):
        arrays = compact_to_arrays(flat, batch_size, spill_cap)
        s = ingest(s, arrays, use_pallas=use_pallas,
                   enable_fanout=enable_fanout, enable_asym=enable_asym)
        return (s, flat[:1]) if with_token else s
    return jax.jit(fn, donate_argnums=(0,) if donate else ())


RESIDENT_HDR = 4   # layout twins of flowpack.cc fp_pack_resident
HOT_WORDS = 3
NK_WORDS = 11


def init_key_table(slot_cap: int) -> jax.Array:
    """Device twin of the host KeyDict: (slot_cap, 10) u32 key words per
    slot, updated from the new-key lane and gathered by hot-row slot id.
    Auxiliary state — NOT part of SketchState (window rolls and checkpoints
    leave it alone; a fresh process simply starts empty on both sides)."""
    return jnp.zeros((slot_cap, KEY_WORDS), jnp.uint32)


def resident_to_arrays(flat: jax.Array, key_table: jax.Array,
                       batch_size: int, caps) -> tuple[dict, jax.Array]:
    """Device-side unpack of the flowpack RESIDENT feed (layout pinned in
    flowpack.cc fp_pack_resident; host packer flowpack.pack_resident).
    Scatters the new-key lane into the key table FIRST — a slot referenced
    by this batch's hot lane may have been defined by this same batch —
    then gathers full 10-word keys by slot id, decodes the range-coded
    rtt/dns codes, scatters the sparse dns/drop lanes onto their rows, and
    concatenates the full-width spill lane. Returns (arrays, new_key_table)
    for the ordinary ingest: all the row widening happens in HBM, the
    transfer link only ever saw ~15 bytes/record (byte budget in
    docs/tpu_sketch.md)."""
    return _resident_region_arrays(flat, key_table, batch_size, caps)


def _region_nk(flat: jax.Array, batch_size: int, caps,
               slot_cap: int) -> tuple[jax.Array, jax.Array]:
    """A region's new-key lane as (slot indices, key words) — undefined
    rows index slot_cap so a mode=\"drop\" scatter discards them."""
    nk_off = (RESIDENT_HDR + batch_size * HOT_WORDS + caps.dns
              + caps.drop * 2)
    nk = flat[nk_off:nk_off + caps.nk * NK_WORDS].reshape(caps.nk, NK_WORDS)
    nk_def = (nk[:, 0] >> 31) != 0
    nk_slot = jnp.where(nk_def, nk[:, 0] & jnp.uint32(0xFFFFF),
                        jnp.uint32(slot_cap)).astype(jnp.int32)
    return nk_slot, nk[:, 1:]


def _resident_region_arrays(flat: jax.Array, key_tables: jax.Array,
                            batch_size: int, caps,
                            lane: int | None = None,
                            nk_applied: bool = False) -> tuple[dict,
                                                               jax.Array]:
    """One resident region against its key table. `lane=None`: key_tables
    is a single (slot_cap, KW) table; otherwise it is the SHARED
    (L, slot_cap, KW) per-lane array and this region uses row `lane`.
    `nk_applied=True` skips the new-key scatter — the caller already
    applied every region's new-key lane in one combined scatter
    (`resident_lane_arrays`), which XLA updates in place under donation
    (a per-region scatter/gather CHAIN was measured to copy the full
    shared table once per region on the ladder path)."""
    hot_off = RESIDENT_HDR
    dns_off = hot_off + batch_size * HOT_WORDS
    drop_off = dns_off + caps.dns
    nk_off = drop_off + caps.drop * 2
    spill_off = nk_off + caps.nk * NK_WORDS
    hdr = flat[:RESIDENT_HDR]
    hot = flat[hot_off:dns_off].reshape(batch_size, HOT_WORDS)
    dnsl = flat[dns_off:drop_off]
    dropl = flat[drop_off:nk_off].reshape(caps.drop, 2)
    nk = flat[nk_off:spill_off].reshape(caps.nk, NK_WORDS)
    spill = dense_to_arrays(flat[spill_off:].reshape(caps.spill, DENSE_WORDS))

    slot_cap = key_tables.shape[-2]
    nk_def = (nk[:, 0] >> 31) != 0
    # undefined rows index out of range -> mode="drop" discards the write
    nk_slot = jnp.where(nk_def, nk[:, 0] & jnp.uint32(0xFFFFF),
                        jnp.uint32(slot_cap)).astype(jnp.int32)
    w0 = hot[:, 0]
    valid = (w0 >> 31) != 0
    slots = (w0 & jnp.uint32(0xFFFFF)).astype(jnp.int32)
    if lane is None:
        if not nk_applied:
            key_tables = key_tables.at[nk_slot].set(nk[:, 1:], mode="drop")
        keys = key_tables[slots]
    else:
        if not nk_applied:
            key_tables = key_tables.at[lane, nk_slot].set(nk[:, 1:],
                                                          mode="drop")
        keys = key_tables[lane, slots]
    rtt = (((w0 >> 20) & jnp.uint32(0xFF))
           << (2 * ((w0 >> 28) & jnp.uint32(0x7)))).astype(jnp.int32)
    w2 = hot[:, 2]
    # sparse dns lane: unused entries are all-zero -> add 0 to row 0
    d_idx = (dnsl >> 16).astype(jnp.int32)
    d_val = ((dnsl & jnp.uint32(0xFFF))
             << ((dnsl >> 12) & jnp.uint32(0xF))).astype(jnp.int32)
    dns_arr = jnp.zeros((batch_size,), jnp.int32).at[d_idx].add(
        d_val, mode="drop")
    # sparse drop lane: bytes/packets scatter-add; cause scatter-max (a
    # value, not a count — zero rows are no-ops under max as well)
    r_idx = (dropl[:, 0] >> 16).astype(jnp.int32)
    zeros_b = jnp.zeros((batch_size,), jnp.int32)
    drop_bytes = zeros_b.at[r_idx].add(
        (dropl[:, 1] & jnp.uint32(0xFFFF)).astype(jnp.int32), mode="drop")
    drop_pkts = zeros_b.at[r_idx].add(
        (dropl[:, 1] >> 16).astype(jnp.int32), mode="drop")
    drop_cause = zeros_b.at[r_idx].max(
        (dropl[:, 0] & jnp.uint32(0xFFFF)).astype(jnp.int32), mode="drop")
    comp = {
        "keys": keys,
        "bytes": jax.lax.bitcast_convert_type(hot[:, 1], jnp.float32),
        "packets": (w2 & jnp.uint32(0x7FF)).astype(jnp.int32),
        "rtt_us": rtt,
        "dns_latency_us": dns_arr,
        "valid": valid,
        "sampling": jnp.broadcast_to(hdr[0].astype(jnp.int32), (batch_size,)),
        "tcp_flags": ((w2 >> 11) & jnp.uint32(0x7FF)).astype(jnp.int32),
        "dscp": ((w2 >> 22) & jnp.uint32(0x3F)).astype(jnp.int32),
        "markers": (w2 >> 28).astype(jnp.int32),
        "drop_bytes": drop_bytes,
        "drop_packets": drop_pkts,
        "drop_cause": drop_cause,
    }
    arrays = {k: jnp.concatenate([comp[k], spill[k]], axis=0) for k in comp}
    return arrays, key_tables


def init_key_tables(n_lanes: int, slot_cap: int) -> jax.Array:
    """Per-LANE device key tables for the lane-sharded resident feed on a
    single device: (n_lanes, slot_cap, KEY_WORDS) u32 — one independent
    table per host-side packer lane (`sketch.staging` lane-sharded ring),
    the single-device twin of `parallel.merge.init_resident_tables`."""
    return jnp.zeros((n_lanes, slot_cap, KEY_WORDS), jnp.uint32)


def _resident_region_words(batch_size: int, caps) -> int:
    """Flat word count of one resident region — the layout twin of
    `flowpack.resident_buf_len` (state.py keeps its own constants so the
    device unpack has no host-package import)."""
    return (RESIDENT_HDR + batch_size * HOT_WORDS + caps.dns + caps.drop * 2
            + caps.nk * NK_WORDS + caps.spill * DENSE_WORDS)


def resident_lane_arrays(flat: jax.Array, key_tables: jax.Array,
                         batch_per_lane: int, caps,
                         n_lanes: int) -> tuple[dict, jax.Array]:
    """Unpack `n_lanes` concatenated resident regions against per-lane key
    tables into ONE array dict for the ordinary ingest. The three-place wire
    contract (flowpack.cc fp_pack_resident <-> flowpack.pack_resident <->
    resident_to_arrays) is unchanged PER REGION — this only loops it and
    concatenates the resulting fixed-shape columns, so the jitted caller
    still never retraces. Returns (arrays, new_key_tables).

    `key_tables` may carry MORE rows than `n_lanes` (the superbatch fold
    ladder: every ladder entry shares ONE per-region table array sized for
    the largest superbatch; a smaller entry scatters only into its leading
    regions' rows). EVERY region's new-key lane applies as one combined
    scatter on the shared donated array before any hot-row gather — XLA
    keeps that single scatter in place, where a per-region scatter/gather
    chain was measured to copy the full table array once per region; the
    within-region "new keys land before hot rows reference them" ordering
    is preserved because all scatters precede all gathers and lanes are
    row-disjoint."""
    words = _resident_region_words(batch_per_lane, caps)
    regions = [flat[i * words:(i + 1) * words] for i in range(n_lanes)]
    slot_cap = key_tables.shape[-2]
    nk_parts = [_region_nk(r, batch_per_lane, caps, slot_cap)
                for r in regions]
    lane_ids = jnp.concatenate([
        jnp.full((caps.nk,), i, jnp.int32) for i in range(n_lanes)])
    key_tables = key_tables.at[
        lane_ids, jnp.concatenate([s for s, _ in nk_parts])].set(
        jnp.concatenate([w for _, w in nk_parts]), mode="drop")
    lanes = []
    for i, r in enumerate(regions):
        arrays, key_tables = _resident_region_arrays(
            r, key_tables, batch_per_lane, caps, lane=i, nk_applied=True)
        lanes.append(arrays)
    if n_lanes == 1:
        return lanes[0], key_tables
    out = {k: jnp.concatenate([a[k] for a in lanes], axis=0)
           for k in lanes[0]}
    return out, key_tables


def make_ingest_resident_lanes_fn(batch_per_lane: int, caps, n_lanes: int,
                                  donate: bool = True,
                                  use_pallas: bool | None = None,
                                  enable_fanout: bool = True,
                                  enable_asym: bool = True):
    """Jitted `(state, key_tables, flat) -> (state, key_tables, token)` for
    the LANE-SHARDED resident feed on one device: `flat` concatenates
    `n_lanes` independent resident regions, each packed by its own host
    KeyDict (`sketch.staging.ShardedResidentStagingRing` with one shard and
    L lanes — the native pack releases the GIL, so lanes pack in true
    parallel), and `key_tables` is `init_key_tables(n_lanes, slot_cap)`.
    Always returns the slot-reuse token (the ring requires it)."""
    def fn(s, tables, flat):
        arrays, tables = resident_lane_arrays(flat, tables, batch_per_lane,
                                              caps, n_lanes)
        s = ingest(s, arrays, use_pallas=use_pallas,
                   enable_fanout=enable_fanout, enable_asym=enable_asym)
        return s, tables, flat[:1]
    return jax.jit(fn, donate_argnums=(0, 1) if donate else ())


def make_ingest_resident_fn(batch_size: int, caps,
                            donate: bool = True,
                            use_pallas: bool | None = None,
                            with_token: bool = False,
                            enable_fanout: bool = True,
                            enable_asym: bool = True):
    """Jitted `(state, key_table, flat resident feed) -> (state, key_table
    [, token])` — the lowest-bytes-per-record host feed (see
    resident_to_arrays / flowpack.pack_resident). The key table is threaded
    alongside the sketch state (both donated) so table updates are in-place
    HBM scatters."""
    def fn(s, table, flat):
        arrays, table = resident_to_arrays(flat, table, batch_size, caps)
        s = ingest(s, arrays, use_pallas=use_pallas,
                   enable_fanout=enable_fanout, enable_asym=enable_asym)
        return (s, table, flat[:1]) if with_token else (s, table)
    return jax.jit(fn, donate_argnums=(0, 1) if donate else ())


def make_ingest_dense_fn(donate: bool = True,
                         use_pallas: bool | None = None,
                         with_token: bool = False,
                         enable_fanout: bool = True,
                         enable_asym: bool = True):
    """Jitted `(state, dense (B,20)u32) -> state` — the single-transfer host
    feed path (see dense_to_arrays / flowpack.pack_dense).

    `with_token=True` returns `(state, token)` where token is a tiny slice of
    the dense input: it becomes ready only once the whole ingest executable
    has finished reading the (possibly host-aliased) input buffer — the
    slot-reuse guard for `sketch.staging.DenseStagingRing`."""
    if with_token:
        def fn(s, d):
            return ingest(s, dense_to_arrays(d), use_pallas=use_pallas,
                          enable_fanout=enable_fanout,
                          enable_asym=enable_asym), d.reshape(-1)[:1]
    else:
        fn = lambda s, d: ingest(s, dense_to_arrays(d),  # noqa: E731
                                 use_pallas=use_pallas,
                                 enable_fanout=enable_fanout,
                                 enable_asym=enable_asym)
    return jax.jit(fn, donate_argnums=(0,) if donate else ())


def decay_state(state: SketchState, factor: float) -> SketchState:
    """Sliding-window flavor: scale the linear sketches by `factor` instead of
    zeroing them (Count-Min and histograms are linear, so decay is exact for
    them; HLL registers cannot decay and are reset). Slot-table counts are CM
    estimates, so they decay by the same factor to stay consistent with the
    window totals; `slot_roll` additionally snapshots this window's final
    counts into `prev_counts` (the churn baseline) while identity, first_seen
    and epoch persist."""
    if isinstance(state, tiered.TieredState):
        # decay the REST in the wide domain; the CM tiers scale at the
        # representation level (decay_plane) — a decode->re-encode here
        # would re-sum shared-cell attribution and compound the aliasing
        # every decay (counts would GROW under decay; pinned)
        wide_decayed = decay_state(tiered.decode_state(state), factor)
        return tiered.decay_encode(state, wide_decayed, factor)
    return state._replace(
        heavy=topk.slot_roll(state.heavy, factor),
        cm_bytes=countmin.CountMin(state.cm_bytes.counts * factor),
        cm_pkts=countmin.CountMin(
            (state.cm_pkts.counts.astype(jnp.float32) * factor
             ).astype(state.cm_pkts.counts.dtype)),
        hll_src=hll.HLL(jnp.zeros_like(state.hll_src.regs)),
        hll_per_dst=hll.PerDstHLL(jnp.zeros_like(state.hll_per_dst.regs)),
        hll_per_src=hll.PerDstHLL(jnp.zeros_like(state.hll_per_src.regs)),
        hist_rtt=quantile.LogHist(state.hist_rtt.counts * factor),
        hist_dns=quantile.LogHist(state.hist_dns.counts * factor),
        # window accumulators paired with an EWMA rate (synack) reset with
        # it; pure per-window histograms decay like the latency hists
        synack=jnp.zeros_like(state.synack),
        drop_causes=state.drop_causes * factor,
        dscp_bytes=state.dscp_bytes * factor,
        conv_fwd=state.conv_fwd * factor,
        conv_rev=state.conv_rev * factor,
        total_records=state.total_records * factor,
        total_bytes=state.total_bytes * factor,
        total_drop_bytes=state.total_drop_bytes * factor,
        total_drop_packets=state.total_drop_packets * factor,
        quic_records=state.quic_records * factor,
        nat_records=state.nat_records * factor,
        # eviction EVENTS are per-window in every mode (decaying an event
        # count would re-report prior windows' fractional evictions
        # forever, and the publish-time counter inc assumes a window delta)
        heavy_evictions=jnp.zeros_like(state.heavy_evictions),
    )


def roll_window(state: SketchState, cfg: SketchConfig,
                reset_sketches: bool = True,
                decay_factor: float | None = None
                ) -> tuple[SketchState, WindowReport]:
    """Close the current window: emit a report, roll EWMA baselines, and
    reset (or decay) the windowed sketch state while keeping the baselines."""
    if isinstance(state, tiered.TieredState):
        # the decode-to-wide step folded into the existing roll executable:
        # the report and (via state_tables) the delta wire / query snapshot
        # see only canonical wide tables — no wire v4, no checkpoint bump.
        # The FRESH state re-tiers per roll mode WITHOUT a decode->encode
        # round trip (which would re-sum shared-overflow attribution and
        # compound it every window): reset encodes fresh zeros (exact),
        # decay scales the tier arrays elementwise, keep leaves them
        # verbatim.
        new_wide, report = roll_window(tiered.decode_state(state), cfg,
                                       reset_sketches, decay_factor)
        if decay_factor is not None:
            new_state = tiered.decay_encode(state, new_wide, decay_factor)
        elif reset_sketches:
            new_state = tiered.encode_state(new_wide, state.spec)
        else:
            # keep mode leaves the CM planes and HLL banks untouched —
            # the resident tier arrays ARE that, bit for bit
            new_state = tiered.TieredState(
                state.tables, tiered._strip(new_wide), state.spec)
        return new_state, report
    ddos_state, z = ewma.roll(state.ddos, cfg.ewma_alpha)
    syn_state, syn_z = ewma.roll(state.syn, cfg.ewma_alpha)
    drops_state, drop_z = ewma.roll(state.drops_ewma, cfg.ewma_alpha)
    gamma = quantile.gamma_for(state.hist_rtt.n_buckets)
    report = WindowReport(
        heavy=state.heavy,
        distinct_src=hll.estimate(state.hll_src.regs),
        per_dst_cardinality=hll.estimate(state.hll_per_dst.regs),
        per_src_fanout=hll.estimate(state.hll_per_src.regs),
        rtt_quantiles_us=quantile.quantile(state.hist_rtt, jnp.asarray(QS), gamma),
        dns_quantiles_us=quantile.quantile(state.hist_dns, jnp.asarray(QS), gamma),
        ddos_z=z,
        syn_z=syn_z,
        syn_rate=state.syn.rate,
        synack_rate=state.synack,
        drop_z=drop_z,
        drop_causes=state.drop_causes,
        dscp_bytes=state.dscp_bytes,
        conv_fwd=state.conv_fwd,
        conv_rev=state.conv_rev,
        total_records=state.total_records,
        total_bytes=state.total_bytes,
        total_drop_bytes=state.total_drop_bytes,
        total_drop_packets=state.total_drop_packets,
        quic_records=state.quic_records,
        nat_records=state.nat_records,
        heavy_evictions=state.heavy_evictions,
        window=state.window,
    )
    if decay_factor is not None:
        new_state = decay_state(state, decay_factor)._replace(
            ddos=ddos_state, syn=syn_state, drops_ewma=drops_state,
            window=state.window + 1)
    elif reset_sketches:
        fresh = init_state(SketchConfig(
            cm_depth=state.cm_bytes.depth, cm_width=state.cm_bytes.width,
            hll_precision=state.hll_src.precision,
            perdst_buckets=state.hll_per_dst.regs.shape[0],
            perdst_precision=int(state.hll_per_dst.regs.shape[1]).bit_length() - 1,
            persrc_buckets=state.hll_per_src.regs.shape[0],
            persrc_precision=int(state.hll_per_src.regs.shape[1]).bit_length() - 1,
            topk=state.heavy.k, hist_buckets=state.hist_rtt.n_buckets,
            ewma_buckets=state.ddos.rate.shape[0], ewma_alpha=cfg.ewma_alpha))
        # the slot table PERSISTS across the roll (identity, first_seen,
        # epoch); only its windowed counts roll: prev_counts <- counts,
        # counts <- 0 — next window's estimates rebuild from the fresh CM
        # while incumbents defend with last window's mass
        new_state = fresh._replace(ddos=ddos_state, syn=syn_state,
                                   drops_ewma=drops_state,
                                   heavy=topk.slot_roll(state.heavy, 0.0),
                                   window=state.window + 1)
    else:
        # synack pairs with the syn EWMA's per-window rate (which roll just
        # zeroed) — it must reset with it even when sketches are kept, or
        # the flood ratio divides a window numerator by a cumulative
        # denominator and detection decays every window
        new_state = state._replace(ddos=ddos_state, syn=syn_state,
                                   drops_ewma=drops_state,
                                   synack=jnp.zeros_like(state.synack),
                                   # cumulative mode: counts keep growing
                                   # with the kept CM; churn = counts -
                                   # prev_counts per window. Eviction
                                   # EVENTS stay per-window like synack
                                   heavy=topk.slot_roll(state.heavy, 1.0),
                                   heavy_evictions=jnp.zeros_like(
                                       state.heavy_evictions),
                                   window=state.window + 1)
    return new_state, report


def state_tables(state: SketchState) -> dict[str, jax.Array]:
    """The MERGEABLE table snapshot of a (pre-roll) state — the device twin
    of the federation delta-frame layout (`federation.delta.TABLE_SPEC`; the
    encoder itself is jax-free). Every entry merges exactly: CM planes and
    histograms add, HLL registers max, top-K candidates concat + re-score,
    signal-plane window rates add. EWMA baselines (mean/var) are absent by
    design — the aggregator keeps its own cluster-level baselines."""
    if isinstance(state, tiered.TieredState):
        # the delta wire and checkpoints keep seeing wide tables (tiers are
        # a steady-state representation only)
        return state_tables(tiered.decode_state(state))
    return {
        "cm_bytes": state.cm_bytes.counts,
        "cm_pkts": state.cm_pkts.counts,
        "heavy_words": state.heavy.words,
        "heavy_h1": state.heavy.h1,
        "heavy_h2": state.heavy.h2,
        "heavy_counts": state.heavy.counts,
        "heavy_valid": state.heavy.valid,
        # persistent-slot churn metadata (delta wire v3): prev_counts merge
        # by SUM (per-shard partials of one key add), first_seen MIN,
        # epoch MAX — federation.delta.TABLE_SPEC carries all three
        "heavy_prev_counts": state.heavy.prev_counts,
        "heavy_first_seen": state.heavy.first_seen,
        "heavy_epoch": state.heavy.epoch,
        "hll_src": state.hll_src.regs,
        "hll_per_dst": state.hll_per_dst.regs,
        "hll_per_src": state.hll_per_src.regs,
        "hist_rtt": state.hist_rtt.counts,
        "hist_dns": state.hist_dns.counts,
        "ddos_rate": state.ddos.rate,
        "syn_rate": state.syn.rate,
        "synack": state.synack,
        "drops_rate": state.drops_ewma.rate,
        "drop_causes": state.drop_causes,
        "dscp_bytes": state.dscp_bytes,
        "conv_fwd": state.conv_fwd,
        "conv_rev": state.conv_rev,
        # federation.delta.SCALAR_FIELDS order
        "scalars": jnp.stack([
            state.total_records, state.total_bytes,
            state.total_drop_bytes, state.total_drop_packets,
            state.quic_records, state.nat_records,
            state.heavy_evictions]),
    }


def make_roll_fn(cfg: SketchConfig, reset_sketches: bool = True,
                 decay_factor: float | None = None,
                 with_tables: bool = False):
    """Jitted window roll. `with_tables=True` additionally returns the
    PRE-roll mergeable table snapshot (`state_tables`) for the federation
    delta export — one extra output of the same executable, so a due window
    still dispatches exactly one device program."""
    if with_tables:
        def fn(s):
            new_state, report = roll_window(s, cfg, reset_sketches,
                                            decay_factor)
            return new_state, report, state_tables(s)
        return jax.jit(fn)
    return jax.jit(lambda s: roll_window(s, cfg, reset_sketches, decay_factor))
