"""Host->device staging ring for the dense flow feed.

A small ring of preallocated host buffers lets eviction batch i+1 be packed
(`flowpack.pack_dense`, single C++ pass) while batch i's host->device
transfer and ingest are still in flight — the host-path pipelining that
closes the seam the reference names as its own hot spot
(`pkg/model/record_bench_test.go:10-14`).

Slot-reuse safety: a slot is repacked only after the *ingest* that consumed
it has finished, guarded by a token output of the jitted ingest (a tiny
slice of the dense input; it becomes ready only when the whole executable
has run). Blocking on the `device_put` result instead is NOT sufficient: on
backends that zero-copy aligned host arrays (the CPU backend), the put
result is "ready" immediately while the async-dispatched ingest may still be
reading the aliased host memory.

Depth: 2 slots stall the pipeline on tunneled links; 4 reach ~82% of the
pack+put ceiling (measured on the axon chip, see PARITY.md).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

import numpy as np

from netobserv_tpu.datapath import flowpack
from netobserv_tpu.model import binfmt
from netobserv_tpu.utils import faultinject, tracing


class StagingWedged(RuntimeError):
    """A fold exceeded the ring's slot-wait budget: the device (or its
    transfer link) is wedged. Raised only when `slot_wait_budget_s` is set
    (the overload controller arms it); the exporter catches it like any
    ingest failure — the unfolded remainder drops, counted, and the
    eviction feed keeps its cadence instead of inheriting the wedge.

    `state` carries the LAST VALID sketch state at the moment the wait
    tripped. This is load-bearing: a multi-chunk fold may have already
    dispatched earlier chunks, and every ingest jit DONATES its input
    state — the caller's pre-fold reference is a deleted buffer by then.
    The catcher must adopt `state` (identical to what it passed in when
    nothing had dispatched yet), or every later fold reads freed memory."""

    state = None


def default_spill_cap(batch_size: int) -> int:
    """Production spill-lane sizing for the compact feed: 1/8 of the batch
    (v6-heavy batches beyond it fall back to the dense feed). Bench and the
    exporter share this so the measured configuration is the shipped one."""
    return max(batch_size // 8, 64)


def pick_lanes(per_unit: int, want: int) -> int:
    """Largest lane count <= `want` that divides `per_unit` evenly (lane
    regions need equal fixed shapes for the retrace-free jitted unpack)."""
    lanes = max(1, min(want, per_unit))
    while per_unit % lanes:
        lanes -= 1
    return lanes


class PendingEventBuffer:
    """Preallocated rolling accumulator for queued evictions — the
    zero-concat fold path. The exporter used to `np.concatenate` every
    queued eviction's events AND five feature lanes per fold (materializing
    zero arrays for absent lanes); this copies each incoming row exactly
    once into a fixed buffer and hands the fold zero-copy prefix views.

    `superbatch_max > 1` sizes the buffer for that many batches and
    coalesces rows that ARRIVE together: a large eviction (or several
    queued ones delivered back-to-back) folds as ONE k-batch superbatch
    the ladder ring dispatches as a single fixed-shape call instead of k
    per-batch dispatches (`ShardedResidentStagingRing` ladder). Small
    evictions keep the old cadence — fold as soon as a full batch is
    buffered — so the exporter-seam latency of a light stream is
    unchanged; coalescing only ever batches work that was already queued
    in one `append` (deferring folds to a fill deadline instead was
    measured to CONCENTRATE slot waits into multi-second export stalls on
    a device slower than the feed — tests/test_roll_nonblocking.py).

    Feature-lane semantics match the old `_concat_feature`: a lane is
    passed to the fold iff ANY eviction in the current batch carried it,
    with zeroed rows standing in for evictions that lacked it (`_live`
    tracks per-lane liveness so untouched lanes cost nothing).

    DIRECT-TO-LANE fast path: when the buffer is empty and an arriving
    eviction's feature lanes are row-aligned with its events (the columnar
    eviction plane always builds them that way — `decode_eviction`), its
    batch-aligned PREFIX folds straight from zero-copy VIEWS of the
    eviction's own arrays — the resident pack lanes read the drain-decode
    output directly, skipping this buffer's copy entirely; only the
    sub-batch tail is copied in. Fold semantics are identical (the gate
    guarantees the zero-pad contract is moot for aligned lanes), pinned by
    tests/test_staging_direct.py. `direct_rows` counts the bypassing rows
    (`sketch_direct_fold_rows_total`)."""

    LANES = (("extra", binfmt.EXTRA_REC_DTYPE),
             ("dns", binfmt.DNS_REC_DTYPE),
             ("drops", binfmt.DROPS_REC_DTYPE),
             ("xlat", binfmt.XLAT_REC_DTYPE),
             ("quic", binfmt.QUIC_REC_DTYPE))

    def __init__(self, batch_size: int, superbatch_max: int = 1,
                 metrics=None):
        self.batch_size = batch_size
        self.capacity = batch_size * max(1, superbatch_max)
        self.n = 0
        self.events = np.zeros(self.capacity, binfmt.FLOW_EVENT_DTYPE)
        self._lanes = {name: np.zeros(self.capacity, dt)
                       for name, dt in self.LANES}
        self._live = {name: False for name, _ in self.LANES}
        self._metrics = metrics
        #: rows folded directly from eviction views (no buffer copy)
        self.direct_rows = 0

    def __len__(self) -> int:
        return self.n

    def _lanes_aligned(self, evicted, n: int) -> bool:
        """True when every present feature lane covers all `n` event rows —
        the gate for folding views of the eviction's own arrays (a short
        lane needs the buffer's zero-pad; fall back to the copy path)."""
        for name, _dt in self.LANES:
            col = getattr(evicted, name, None)
            if col is not None and len(col) and len(col) != n:
                return False
        return True

    def append(self, evicted, fold: Callable) -> None:
        """Copy `evicted` (an EvictedFlows) into the buffer, then fire
        `fold(events, feats)` with views into it for every full batch
        buffered — as one coalesced batch-aligned prefix (the ladder ring
        dispatches it as a single superbatch), keeping any sub-batch tail
        buffered for the next eviction. The fold must consume its views
        before returning (both ring pack paths copy synchronously).

        An eviction meeting the direct-to-lane gate (empty buffer,
        batch-aligned prefix, aligned lanes) folds that prefix zero-copy
        from its own arrays — in capacity-sized chunks, so a fold is
        never LARGER than the copy path could have produced (the dense/
        compact rings do not chunk internally; only the resident ladder
        ring does) — and the sub-batch tail takes the copy path below."""
        ev = evicted.events
        off = 0
        if self.n == 0 and len(ev) >= self.batch_size \
                and self._lanes_aligned(evicted, len(ev)):
            while len(ev) - off >= self.batch_size:
                take = min(len(ev) - off, self.capacity)
                take -= take % self.batch_size
                feats = {}
                for name, _dt in self.LANES:
                    col = getattr(evicted, name, None)
                    feats[name] = (col[off:off + take]
                                   if col is not None and len(col) else None)
                try:
                    fold(ev[off:off + take], feats)
                except BaseException:
                    # a raising fold drops ITS chunk (counted upstream)
                    # like _fold_prefix — the rest still buffers, and the
                    # dropped rows never count as routed-direct
                    self._copy_in(evicted, off + take, fold)
                    raise
                off += take
                self.direct_rows += take
                if self._metrics is not None:
                    self._metrics.sketch_direct_fold_rows_total.inc(take)
            if off == len(ev):
                return
        self._copy_in(evicted, off, fold)

    def _copy_in(self, evicted, off: int, fold: Callable) -> None:
        """The copy path: buffer `evicted`'s rows from `off` on, folding
        full batches as they fill."""
        ev = evicted.events
        while off < len(ev):
            take = min(len(ev) - off, self.capacity - self.n)
            lo, hi = self.n, self.n + take
            self.events[lo:hi] = ev[off:off + take]
            for name, _ in self.LANES:
                col = getattr(evicted, name, None)
                lane = self._lanes[name]
                if col is not None and len(col):
                    if not self._live[name]:
                        lane[:lo] = 0  # earlier evictions lacked this lane
                        self._live[name] = True
                    c = col[off:off + take]
                    lane[lo:lo + len(c)] = c
                    lane[lo + len(c):hi] = 0  # short lane: zero-pad its tail
                elif self._live[name]:
                    lane[lo:hi] = 0
            self.n += take
            off += take
            if self.n == self.capacity:
                self.flush_to(fold)
        full = self.n - self.n % self.batch_size
        if full:
            self._fold_prefix(fold, full)

    def flush_to(self, fold: Callable) -> None:
        """Fold whatever is buffered (a partial batch pads downstream) and
        reset; no-op when empty."""
        if not self.n:
            return
        n = self.n
        feats = {name: (self._lanes[name][:n] if self._live[name] else None)
                 for name, _ in self.LANES}
        # reset BEFORE folding: a fold that raises must not leave the rows
        # queued for a re-fold (the exporter counts the batch as dropped)
        self.n = 0
        for name, _ in self.LANES:
            self._live[name] = False
        fold(self.events[:n], feats)

    def _fold_prefix(self, fold: Callable, rows: int) -> None:
        """Fold the batch-aligned `rows` prefix and slide the sub-batch
        tail to the front. The fold consumes its views synchronously, so
        the tail move happens after it returns; a RAISING fold still drops
        the prefix (counted upstream) and keeps the tail."""
        n = self.n
        feats = {name: (self._lanes[name][:rows] if self._live[name]
                        else None) for name, _ in self.LANES}
        try:
            fold(self.events[:rows], feats)
        finally:
            tail = n - rows
            if tail:
                self.events[:tail] = self.events[rows:n]
                for name, _ in self.LANES:
                    if self._live[name]:
                        self._lanes[name][:tail] = self._lanes[name][rows:n]
            else:
                for name, _ in self.LANES:
                    self._live[name] = False
            self.n = tail


class _SlotRing:
    """Shared slot/token protocol of every staging ring — ONE definition of
    the slot-reuse guard described in the module docstring (the token must
    be a slice of the jitted ingest's input; blocking on the put result is
    not sufficient on zero-copy backends)."""

    #: recent slot-wait samples kept for the p95 the overload controller
    #: reads (fixed window: one float store per fold, no allocation)
    WAIT_WINDOW = 64

    def _init_slots(self, bufs: list, metrics) -> None:
        self._bufs = bufs
        self._tokens: list = [None] * len(bufs)
        self._slot = 0
        self._metrics = metrics
        self.stalls = 0
        #: optional bound on one fold's slot wait (seconds); None = wait
        #: forever (the historical behavior). The tpu-sketch exporter sets
        #: it when overload shedding is enabled so a wedged device drops
        #: batches instead of wedging the eviction feed (StagingWedged).
        self.slot_wait_budget_s: Optional[float] = None
        self._waits = np.zeros(self.WAIT_WINDOW, np.float64)
        self._wait_i = 0
        self._wait_n = 0

    def _record_wait(self, seconds: float) -> None:
        self._waits[self._wait_i] = seconds
        self._wait_i = (self._wait_i + 1) % self.WAIT_WINDOW
        if self._wait_n < self.WAIT_WINDOW:
            self._wait_n += 1

    def slot_wait_p95(self) -> float:
        """p95 of the last WAIT_WINDOW folds' slot waits (0.0 until any
        fold has run) — the device-backpressure half of the overload
        controller's pressure score."""
        if not self._wait_n:
            return 0.0
        return float(np.percentile(self._waits[:self._wait_n], 95))

    def _fold_trace(self, trace):
        """Resolve a fold's trace context: the caller's (batch trace riding
        the eviction, or the exporter's NULL), else sample one here — a
        directly-driven ring (bench.py --host-only) still exercises the
        span layer. Returns (trace, owned): the ring finishes only traces
        it created."""
        if trace is not None:
            return trace, False
        return tracing.start_trace("fold"), True

    def _wait_slot(self, trace=tracing.NULL_TRACE) -> int:
        """Return the next slot index, blocking until its previous consumer
        (the ingest that read the slot's buffer) has finished."""
        import jax

        # chaos seam: a hang here models a wedged device/transfer stalling
        # the staging feed — the thread folding (the exporter stage) stops
        # beating and the supervisor's hang detection takes over
        faultinject.fire("sketch.staging_wait")
        slot = self._slot
        tok = self._tokens[slot]
        wait_s = 0.0
        if tok is not None:
            if not tok.is_ready():
                self.stalls += 1
                if self._metrics is not None:
                    self._metrics.sketch_staging_stalls_total.inc()
                t0 = time.perf_counter()
                with trace.stage("staging_wait"):
                    budget = self.slot_wait_budget_s
                    if budget is None:
                        jax.block_until_ready(tok)
                    else:
                        # bounded wait: poll readiness up to the budget; a
                        # still-busy slot past it means the device wedged —
                        # raise instead of inheriting the wedge (the token
                        # stays in place; a later fold re-waits on it)
                        deadline = t0 + budget
                        while not tok.is_ready():
                            if time.perf_counter() >= deadline:
                                self._record_wait(time.perf_counter() - t0)
                                raise StagingWedged(
                                    f"staging slot busy past the "
                                    f"{budget:.1f}s slot-wait budget "
                                    "(device/transfer wedged)")
                            time.sleep(0.002)
                        jax.block_until_ready(tok)
                wait_s = time.perf_counter() - t0
                if self._metrics is not None:
                    self._metrics.sketch_slot_wait_seconds.observe(wait_s)
            else:
                jax.block_until_ready(tok)
        self._record_wait(wait_s)
        return slot

    def _advance(self, slot: int, token) -> None:
        self._tokens[slot] = token
        self._slot = (slot + 1) % len(self._bufs)

    def drain(self) -> None:
        """Block until every in-flight batch has been fully ingested (host
        buffers are then free; used before checkpoint/window close)."""
        import jax

        for tok in self._tokens:
            if tok is not None:
                jax.block_until_ready(tok)


class DenseStagingRing(_SlotRing):
    """Reusable host buffers + in-flight tokens for the dense ingest path.

    `ingest` must be a token-returning jitted fn — built with
    `sketch.state.make_ingest_dense_fn(with_token=True)` or
    `parallel.merge.make_sharded_ingest_fn(dense=True, with_token=True)` —
    i.e. `(state, dense) -> (state, token)`. `put` places a packed host
    buffer on device(s); defaults to `jax.device_put` (single device).

    Compact mode (`spill_cap` set, single-device only): slots hold the flat
    v4-compact feed (`flowpack.pack_compact`, ~40% of the dense bytes —
    the transfer link is the host path's bottleneck) and `ingest` must be a
    `make_ingest_compact_fn(with_token=True)` jit. Batches whose non-v4
    flows overflow the spill lane fall back to the dense feed through
    `ingest_fallback` (a `make_ingest_dense_fn(with_token=True)` jit) —
    same math, bigger transfer, synchronously drained (rare path).
    """

    def __init__(self, batch_size: int, ingest: Callable,
                 put: Optional[Callable] = None, n_slots: int = 4,
                 spill_cap: Optional[int] = None,
                 ingest_fallback: Optional[Callable] = None,
                 metrics=None, pack_threads: int = 1):
        import jax

        self.batch_size = batch_size
        #: >1 shards each dense pack across this many native packer threads
        #: (flowpack.pack_dense_sharded) — matters on hosts where the pack,
        #: not the transfer link, bounds the feed
        self.pack_threads = pack_threads
        self.spill_cap = spill_cap
        self._ingest = ingest
        self._ingest_fallback = ingest_fallback
        self._put = put or jax.device_put
        if spill_cap is not None:
            shape: tuple = (flowpack.compact_buf_len(batch_size, spill_cap),)
            if ingest_fallback is None:
                raise ValueError("compact mode needs ingest_fallback")
        else:
            shape = (batch_size, flowpack.DENSE_WORDS)
        self._init_slots([np.empty(shape, np.uint32)
                          for _ in range(n_slots)], metrics)
        self._dense_buf: Optional[np.ndarray] = None  # lazy fallback buffer
        self.dense_fallbacks = 0  # spill-overflow batches shipped full-width

    def fold(self, state, events, extra=None, dns=None, drops=None,
             xlat=None, quic=None, trace=None):
        """Pack `events` into the next free slot, ship it, ingest it; returns
        the new sketch state (async — not blocked on)."""
        trace, owned = self._fold_trace(trace)
        try:
            try:
                slot = self._wait_slot(trace)
            except StagingWedged as exc:
                exc.state = state  # nothing dispatched: caller's own state
                raise
            feats = dict(extra=extra, dns=dns, drops=drops, xlat=xlat,
                         quic=quic)
            if self.spill_cap is not None:
                with trace.stage("pack"):
                    buf = flowpack.pack_compact(
                        events, batch_size=self.batch_size,
                        spill_cap=self.spill_cap,
                        out=self._bufs[slot], **feats)
                if buf is None:
                    return self._fold_dense_fallback(state, events, feats)
                with trace.stage("ingest_dispatch"):
                    state, token = self._ingest(state, self._put(buf))
                self._advance(slot, token)
                return state
            with trace.stage("pack"):
                buf = flowpack.pack_dense_sharded(
                    events, batch_size=self.batch_size,
                    threads=self.pack_threads, out=self._bufs[slot], **feats)
            # ship FLAT: a (B*20,) transfer dodges device-layout padding of
            # the 20-wide minor dim (the ingest jit reshapes back, fused,
            # free)
            with trace.stage("ingest_dispatch"):
                state, token = self._ingest(state, self._put(buf.reshape(-1)))
            self._advance(slot, token)
            return state
        finally:
            if owned:
                trace.finish()

    def _fold_dense_fallback(self, state, events, feats):
        """Non-v4 (or spill-overflow) flows exceeded the spill lane: ship
        this batch full-width. Synchronous (the shared dense buffer has no
        slot ring), and rare — only v6-dominant traffic or a drop storm
        takes it repeatedly, at dense-path speed; the counter makes that
        degradation observable (sketch_dense_fallback_total)."""
        import jax

        self.dense_fallbacks += 1
        if self._metrics is not None:
            self._metrics.sketch_dense_fallback_total.inc()
        if self._dense_buf is None:
            self._dense_buf = np.empty(
                (self.batch_size, flowpack.DENSE_WORDS), np.uint32)
        buf = flowpack.pack_dense_sharded(
            events, batch_size=self.batch_size, threads=self.pack_threads,
            out=self._dense_buf, **feats)
        state, tok = self._ingest_fallback(state, self._put(buf.reshape(-1)))
        jax.block_until_ready(tok)
        return state


class ShardedResidentStagingRing(_SlotRing):
    """Resident feed split into independent pack REGIONS — `n_shards` data
    shards x `lanes` lanes per shard. The batch splits into
    `n_shards * lanes` contiguous row blocks, each packed by its OWN
    KeyDict into its own resident buffer region; the concatenated flat
    buffer ships with one put whose contiguous data-axis split lands
    exactly on per-shard region-group boundaries.

    Two deployments share this ring:

    - mesh (`n_shards` > 1): device twin
      `parallel.merge.make_sharded_ingest_resident_fn` +
      `init_resident_tables` (independent key tables per (shard, lane) —
      lookups stay local, the steady-state no-collectives invariant holds);
      `put` is `parallel.merge.shard_dense` bound to the mesh.
    - single device (`n_shards` == 1, `lanes` > 1): device twin
      `sketch.state.make_ingest_resident_lanes_fn` + `init_key_tables`;
      `put` is a plain `device_put`. This is how SKETCH_PACK_THREADS
      engages the resident feed — the per-lane packs run on the pool in
      true parallel (native pack releases the GIL), raising the host-pack
      ceiling that a single `pack_resident` pass tops out at.

    Multi-process note: every process must fold the SAME global batches
    (the existing `shard_batch`/`shard_dense` assumption) — dictionary
    evolution is deterministic in row order, so all processes assign
    identical slots.

    Superbatch LADDER (`ladder=(1, 2, 4)`): when a fold receives k queued
    batches' worth of rows (the exporter's `PendingEventBuffer` coalesces
    evictions up to `superbatch_max` batches), the whole superbatch packs
    into `n_shards * k * lanes` regions and ships as ONE put + ONE jitted
    ingest dispatch of the k-entry instead of k per-batch dispatches —
    amortizing the per-dispatch python/jit/transfer overhead. Every ladder
    entry is its own fixed-shape jitted fn (no retraces); they all share
    ONE key-table array sized for the largest entry (a smaller entry
    updates only its leading regions' tables, `state.resident_lane_arrays`)
    and per-(shard, ladder-position, lane) dictionaries, so a region's
    dictionary <-> device-table pairing is stable across ladder sizes.

    `ingest`: `{k: (dist_state, key_tables, flat) -> (dist_state,
    key_tables, token)}` for every ladder entry (a bare callable means
    `{1: fn}`). `key_tables` must carry `superbatch_max * lanes` rows per
    shard. `pack_threads > 1` packs the regions concurrently."""

    def __init__(self, batch_size: int, n_shards: int, ingest,
                 key_tables, put: Callable,
                 caps=None, slot_cap: int = 1 << 18, n_slots: int = 4,
                 metrics=None, pack_threads: int = 1, lanes: int = 1,
                 ladder: tuple = (1,), lazy_ladder: bool = False):
        self.ladder = tuple(sorted({int(k) for k in ladder}))
        if not self.ladder or self.ladder[0] != 1:
            raise ValueError("superbatch ladder must include 1")
        self.superbatch_max = self.ladder[-1]
        # lazy_ladder: entries > 1 become SELECTABLE only once mark_warm
        # says their jit is compiled (the exporter's construction warm) —
        # a cold ladder entry must never compile inside a live fold, which
        # would stall export_evicted for seconds (test_roll_nonblocking).
        # Eager (default) trusts the caller to warm by folding (bench,
        # offline tools, tests).
        self._available = {1} if lazy_ladder else set(self.ladder)
        n_regions = n_shards * lanes
        if batch_size % n_regions:
            raise ValueError(
                "batch_size must divide evenly over shards x lanes")
        self.batch_size = batch_size
        self.n_shards = n_shards
        self.lanes = lanes
        #: regions of ONE 1x batch (a k-superbatch packs k*n_regions)
        self.n_regions = n_regions
        self.batch_per_region = batch_size // n_regions
        self.caps = caps or flowpack.default_resident_caps(
            self.batch_per_region)
        self.slot_cap = slot_cap
        self.pack_threads = pack_threads
        self.kdicts = [flowpack.KeyDict(slot_cap)
                       for _ in range(n_regions * self.superbatch_max)]
        self.key_tables = key_tables
        self._ingests = ingest if not callable(ingest) else {1: ingest}
        missing = set(self.ladder) - set(self._ingests)
        if missing:
            raise ValueError(f"no ingest fn for ladder entries {missing}")
        self._put = put
        self.continuations = 0
        self.dict_resets = 0
        self.spill_rows = 0
        #: dispatch counts by superbatch size (mirrors
        #: sketch_superbatch_folds_total{k})
        self.superbatch_folds: dict[int, int] = {}
        self._region_words = flowpack.resident_buf_len(self.batch_per_region,
                                                       self.caps)
        self._init_slots(
            [np.empty(self.superbatch_max * n_regions * self._region_words,
                      np.uint32) for _ in range(n_slots)], metrics)

    @property
    def _ingest(self):
        """The 1x ladder entry (back-compat: retrace introspection in tests
        predates the ladder)."""
        return self._ingests[1]

    def mark_warm(self, *ks: int) -> None:
        """Make ladder entries selectable (call after compiling them — the
        exporter's `warm_superbatch_ladder`)."""
        self._available.update(int(k) for k in ks)

    def fold(self, state, events, extra=None, dns=None, drops=None,
             xlat=None, quic=None, trace=None):
        """Pack `events` (split over the regions, possibly in several
        chunks) into free ring slots, ship and ingest each; returns the new
        dist state (async — not blocked on). Row counts beyond one batch
        dispatch as the largest fitting superbatch ladder entries."""
        n = len(events)
        if n == 0:
            return state
        trace, owned = self._fold_trace(trace)
        try:
            feats = dict(extra=extra, dns=dns, drops=drops, xlat=xlat,
                         quic=quic)
            start = 0
            while start < n:
                remaining = n - start
                k = max((x for x in self.ladder
                         if x in self._available
                         and x * self.batch_size <= remaining), default=1)
                take = min(remaining, k * self.batch_size)
                chunk_feats = {
                    name: (v[start:start + take]
                           if v is not None and len(v) else None)
                    for name, v in feats.items()}
                state = self._fold_chunk(state, events[start:start + take],
                                         chunk_feats, k, trace)
                start += take
            return state
        finally:
            if owned:
                trace.finish()

    def _fold_chunk(self, state, events, feats, k: int, trace):
        """Pack and dispatch ONE k-superbatch chunk (<= k * batch_size rows)
        through the k ladder entry."""
        n = len(events)
        nr = self.n_shards * k * self.lanes
        kl = k * self.lanes
        kmax_l = self.superbatch_max * self.lanes
        ship_words = nr * self._region_words
        bounds = [n * i // nr for i in range(nr + 1)]
        shard_ev = [events[bounds[i]:bounds[i + 1]] for i in range(nr)]
        shard_feats = [
            {name: (v[bounds[i]:bounds[i + 1]] if v is not None and len(v)
                    else None) for name, v in feats.items()}
            for i in range(nr)]
        starts = [0] * nr
        first = True
        while any(starts[i] < len(shard_ev[i]) for i in range(nr)):
            try:
                slot = self._wait_slot(trace)
            except StagingWedged as exc:
                # earlier chunks may have dispatched (donating the caller's
                # state buffers); hand the last valid state to the catcher
                exc.state = state
                raise
            buf = self._bufs[slot]

            def pack_shard(i):
                # touches only region-local state (its dict, its buffer
                # region, starts[i]); returns the diagnostic counters so
                # threaded packs don't race on shared attributes
                region = buf[i * self._region_words:
                             (i + 1) * self._region_words]
                if starts[i] >= len(shard_ev[i]):
                    # exhausted region in a continuation chunk: mask it
                    # empty (validity words only — 1/3 of a full memset),
                    # and don't roll its dictionary epoch for rows it
                    # isn't packing
                    flowpack.zero_resident_region(
                        region, self.batch_per_region, self.caps)
                    return 0, 0
                # region i of a k-chunk is (shard, ladder-position j) —
                # dict j of that shard, whatever k the chunk uses, so the
                # dictionary always matches device table row j
                kd = self.kdicts[(i // kl) * kmax_l + (i % kl)]
                resets = 0
                if kd.count() >= self.slot_cap:
                    kd.reset()  # per-region epoch roll (ResidentStagingRing)
                    resets = 1
                _, consumed = flowpack.pack_resident(
                    shard_ev[i], batch_size=self.batch_per_region,
                    kdict=kd, caps=self.caps, start=starts[i],
                    out=region, **shard_feats[i])
                if consumed == 0 and starts[i] < len(shard_ev[i]):
                    raise RuntimeError("resident pack made no progress")
                starts[i] += consumed
                return int(region[2]), resets

            with trace.stage("resident_pack"):
                if self.pack_threads > 1 and nr > 1:
                    # per-region dictionaries are independent; the native
                    # pack releases the GIL, so regions pack in true parallel
                    outs = [f.result() for f in flowpack._pack_submit(
                        min(self.pack_threads, nr),
                        [lambda i=i: pack_shard(i) for i in range(nr)])]
                else:
                    outs = [pack_shard(i) for i in range(nr)]
            chunk_spills = sum(o[0] for o in outs)
            chunk_resets = sum(o[1] for o in outs)
            self.spill_rows += chunk_spills
            self.dict_resets += chunk_resets
            self.superbatch_folds[k] = self.superbatch_folds.get(k, 0) + 1
            if self._metrics is not None:
                if chunk_spills:
                    self._metrics.sketch_resident_spill_rows_total.inc(
                        chunk_spills)
                if chunk_resets:
                    self._metrics.sketch_resident_dict_epochs_total.inc(
                        chunk_resets)
                if not first:
                    self._metrics.sketch_resident_continuations_total.inc()
                self._metrics.sketch_superbatch_folds_total.labels(
                    str(k)).inc()
            if not first:
                self.continuations += 1
            first = False
            with trace.stage("ingest_dispatch"):
                state, self.key_tables, token = self._ingests[k](
                    state, self.key_tables, self._put(buf[:ship_words]))
            self._advance(slot, token)
        return state

    def fold_packed(self, state, packed, trace=None):
        """Ship PRE-PACKED resident regions (the fused native pipeline's
        arena — loader's fp_drain_to_resident ran the pack stage at drain
        time with this ring's own dictionaries). SCHEDULING ONLY: the arena
        is bit-exact what _fold_chunk would have packed for the same rows
        (tests/test_native_pipeline.py), so this path only replaces the
        per-region python pack loop with one memcpy per segment; counters
        and metrics advance exactly as _fold_chunk would have. The caller
        (exporter) holds the ResidentPackSurface lock and has already
        checked the pack epoch."""
        trace, owned = self._fold_trace(trace)
        try:
            rw = self._region_words
            for ch in packed.chunks:
                nr = self.n_shards * ch.k * self.lanes
                seg_words = nr * rw
                for s in range(ch.n_segs):
                    try:
                        slot = self._wait_slot(trace)
                    except StagingWedged as exc:
                        # chunks already dispatched donated the caller's
                        # state buffers (the _fold_chunk rule) — hand the
                        # last valid state over; the surface invalidates
                        # (pre-packed slot definitions are dropping)
                        exc.state = state
                        raise
                    buf = self._bufs[slot]
                    off = ch.arena_off + s * seg_words
                    with trace.stage("resident_pack"):
                        np.copyto(buf[:seg_words],
                                  packed.arena[off:off + seg_words])
                    self.superbatch_folds[ch.k] = (
                        self.superbatch_folds.get(ch.k, 0) + 1)
                    if s:
                        self.continuations += 1
                    if self._metrics is not None:
                        if s:
                            (self._metrics
                             .sketch_resident_continuations_total.inc())
                        self._metrics.sketch_superbatch_folds_total.labels(
                            str(ch.k)).inc()
                    with trace.stage("ingest_dispatch"):
                        state, self.key_tables, token = self._ingests[ch.k](
                            state, self.key_tables,
                            self._put(buf[:seg_words]))
                    self._advance(slot, token)
                # per-chunk counters the native pack already aggregated
                self.spill_rows += ch.spills
                self.dict_resets += ch.resets
                if self._metrics is not None:
                    if ch.spills:
                        self._metrics.sketch_resident_spill_rows_total.inc(
                            ch.spills)
                    if ch.resets:
                        self._metrics.sketch_resident_dict_epochs_total.inc(
                            ch.resets)
            return state
        finally:
            if owned:
                trace.finish()


class ResidentPackSurface:
    """Coordination point between the drain-side fused pack
    (loader.NativeEvictPipeline / fp_drain_to_resident) and the ring that
    owns the dictionaries the pack mutates.

    The load-bearing invariant is SHIP ORDER = DICT-MUTATION ORDER: a
    shipped resident buffer must contain (or follow) every slot definition
    its hot rows reference. Fused packs mutate the dictionaries at DRAIN
    time but ship at FOLD time; a raw fold (python pack) mutates at ship
    time. So whenever a raw fold would run while fused-packed arenas are
    still outstanding (packed, not yet shipped), those arenas' slot
    definitions would ship AFTER rows referencing them — `invalidate()`
    resolves it by bumping the epoch (outstanding arenas are discarded at
    their fold; their raw rows refold) and resetting every ring dictionary
    (the safe epoch-roll: each live slot is redefined through the new-key
    lane before any hot row references it). With no outstanding arena a
    raw fold needs no invalidation — mixed steady state stays cheap.

    Lock order: the exporter lock may be held when taking `lock`; `lock`
    holders never take the exporter lock (the drain thread holds `lock`
    across the whole fused native call)."""

    def __init__(self, ring: "ShardedResidentStagingRing"):
        self.ring = ring
        self.lock = threading.Lock()
        self.epoch = 0
        #: fused-packed arenas produced but not yet shipped or discarded
        self.outstanding = 0

    def pack_spec(self) -> dict:
        """The ring's current pack geometry for NativePipe.drain(pack=...).
        Call under `lock` (the available-ladder set and the dictionary
        handles must not move between spec and pack)."""
        ring = self.ring
        ks = sorted(k for k in ring.ladder if k in ring._available)
        kmax_l = ring.superbatch_max * ring.lanes
        ladder = []
        for k in ks:
            kl = k * ring.lanes
            nr = ring.n_shards * k * ring.lanes
            ladder.append((k, [
                ring.kdicts[(i // kl) * kmax_l + (i % kl)]._live_handle()
                for i in range(nr)]))
        return {"batch_size": ring.batch_size,
                "batch_per_region": ring.batch_per_region,
                "slot_cap": ring.slot_cap, "caps": ring.caps,
                "ladder": ladder}

    def invalidate_for_raw_fold(self) -> None:
        """Call BEFORE any raw (non-packed) fold while this surface is
        bound. No-op when no fused arena is outstanding."""
        with self.lock:
            if self.outstanding:
                self._invalidate_locked()

    def invalidate(self) -> None:
        with self.lock:
            self._invalidate_locked()

    def note_external_reset(self) -> None:
        """The caller already reset the ring dictionaries itself (the
        ingest-error epoch roll) — record the epoch move so outstanding
        fused arenas (packed against the pre-reset dictionaries) discard
        at their fold instead of shipping stale slot references."""
        with self.lock:
            self.epoch += 1
            self.outstanding = 0

    def _invalidate_locked(self) -> None:
        self.epoch += 1
        self.outstanding = 0
        ring = self.ring
        for kd in ring.kdicts:
            kd.reset()
        ring.dict_resets += len(ring.kdicts)
        if ring._metrics is not None:
            ring._metrics.sketch_resident_dict_epochs_total.inc(
                len(ring.kdicts))


class ResidentStagingRing(_SlotRing):
    """Staging ring for the RESIDENT feed — the lowest-bytes-per-record host
    path (~15B/record vs the compact feed's 40B; byte budget in
    docs/tpu_sketch.md). The host keeps a key->slot dictionary
    (`flowpack.KeyDict`, native); the device keeps the matching key table
    (`sketch.state.init_key_table`) threaded through the jitted ingest.

    `ingest` must be `make_ingest_resident_fn(with_token=True)`:
    `(state, table, flat) -> (state, table, token)`. The packer packs until
    a lane fills and reports how many rows it consumed; the ring ships that
    (always self-consistent) prefix and continues from the stop point in
    the next slot — so the dictionary and the device table learn
    monotonically even under cold-start key floods, with no dense fallback
    and no rollback. A full dictionary starts a fresh epoch (reset) at the
    next fold: stale device-table rows are harmless because every live
    slot is redefined through the new-key lane before any hot row
    references it."""

    def __init__(self, batch_size: int, ingest: Callable,
                 caps=None, slot_cap: int = 1 << 18,
                 put: Optional[Callable] = None, n_slots: int = 4,
                 metrics=None):
        import jax

        from netobserv_tpu.sketch import state as sk

        self.batch_size = batch_size
        self.caps = caps or flowpack.default_resident_caps(batch_size)
        self.slot_cap = slot_cap
        self.kdict = flowpack.KeyDict(slot_cap)
        self.key_table = jax.device_put(sk.init_key_table(slot_cap))
        self._ingest = ingest
        self._put = put or jax.device_put
        self.continuations = 0  # extra chunks beyond one per fold()
        self.dict_resets = 0    # full-dictionary epochs
        self.spill_rows = 0     # rows that rode the full-width spill lane
        total = flowpack.resident_buf_len(batch_size, self.caps)
        self._init_slots([np.empty(total, np.uint32)
                          for _ in range(n_slots)], metrics)

    def fold(self, state, events, extra=None, dns=None, drops=None,
             xlat=None, quic=None, trace=None):
        """Pack `events` (possibly in several chunks) into free ring slots,
        ship and ingest each; returns the new sketch state (async — not
        blocked on)."""
        feats = dict(extra=extra, dns=dns, drops=drops, xlat=xlat, quic=quic)
        n = len(events)
        if n == 0:
            return state
        trace, owned = self._fold_trace(trace)
        try:
            start = 0
            first = True
            while start < n:
                if self.kdict.count() >= self.slot_cap:
                    # epoch roll: the device table needs no reset — every
                    # live slot is redefined before any hot row references it
                    self.kdict.reset()
                    self.dict_resets += 1
                    if self._metrics is not None:
                        self._metrics.sketch_resident_dict_epochs_total.inc()
                try:
                    slot = self._wait_slot(trace)
                except StagingWedged as exc:
                    # earlier chunks may have dispatched (donating the
                    # caller's state buffers); hand over the valid state
                    exc.state = state
                    raise
                with trace.stage("resident_pack"):
                    buf, consumed = flowpack.pack_resident(
                        events, batch_size=self.batch_size, kdict=self.kdict,
                        caps=self.caps, start=start, out=self._bufs[slot],
                        **feats)
                if consumed == 0 and n:
                    raise RuntimeError("resident pack made no progress")
                self.spill_rows += int(buf[2])
                if self._metrics is not None:
                    if buf[2]:
                        self._metrics.sketch_resident_spill_rows_total.inc(
                            int(buf[2]))
                    if not first:
                        self._metrics \
                            .sketch_resident_continuations_total.inc()
                if not first:
                    self.continuations += 1
                first = False
                start += consumed
                with trace.stage("ingest_dispatch"):
                    state, self.key_table, token = self._ingest(
                        state, self.key_table, self._put(buf))
                self._advance(slot, token)
            return state
        finally:
            if owned:
                trace.finish()
