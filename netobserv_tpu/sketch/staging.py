"""Host->device staging ring for the dense flow feed.

A small ring of preallocated host buffers lets eviction batch i+1 be packed
(`flowpack.pack_dense`, single C++ pass) while batch i's host->device
transfer and ingest are still in flight — the host-path pipelining that
closes the seam the reference names as its own hot spot
(`pkg/model/record_bench_test.go:10-14`).

Slot-reuse safety: a slot is repacked only after the *ingest* that consumed
it has finished, guarded by a token output of the jitted ingest (a tiny
slice of the dense input; it becomes ready only when the whole executable
has run). Blocking on the `device_put` result instead is NOT sufficient: on
backends that zero-copy aligned host arrays (the CPU backend), the put
result is "ready" immediately while the async-dispatched ingest may still be
reading the aliased host memory.

Depth: 2 slots stall the pipeline on tunneled links; 4 reach ~82% of the
pack+put ceiling (measured on the axon chip, see PARITY.md).
"""
from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from netobserv_tpu.datapath import flowpack


def default_spill_cap(batch_size: int) -> int:
    """Production spill-lane sizing for the compact feed: 1/8 of the batch
    (v6-heavy batches beyond it fall back to the dense feed). Bench and the
    exporter share this so the measured configuration is the shipped one."""
    return max(batch_size // 8, 64)


class DenseStagingRing:
    """Reusable host buffers + in-flight tokens for the dense ingest path.

    `ingest` must be a token-returning jitted fn — built with
    `sketch.state.make_ingest_dense_fn(with_token=True)` or
    `parallel.merge.make_sharded_ingest_fn(dense=True, with_token=True)` —
    i.e. `(state, dense) -> (state, token)`. `put` places a packed host
    buffer on device(s); defaults to `jax.device_put` (single device).

    Compact mode (`spill_cap` set, single-device only): slots hold the flat
    v4-compact feed (`flowpack.pack_compact`, ~40% of the dense bytes —
    the transfer link is the host path's bottleneck) and `ingest` must be a
    `make_ingest_compact_fn(with_token=True)` jit. Batches whose non-v4
    flows overflow the spill lane fall back to the dense feed through
    `ingest_fallback` (a `make_ingest_dense_fn(with_token=True)` jit) —
    same math, bigger transfer, synchronously drained (rare path).
    """

    def __init__(self, batch_size: int, ingest: Callable,
                 put: Optional[Callable] = None, n_slots: int = 4,
                 spill_cap: Optional[int] = None,
                 ingest_fallback: Optional[Callable] = None,
                 metrics=None, pack_threads: int = 1):
        import jax

        self.batch_size = batch_size
        self._metrics = metrics
        #: >1 shards each dense pack across this many native packer threads
        #: (flowpack.pack_dense_sharded) — matters on hosts where the pack,
        #: not the transfer link, bounds the feed
        self.pack_threads = pack_threads
        #: folds that found their slot's previous ingest still running —
        #: the device (or transfer link) is slower than the eviction feed.
        #: Mirrored into metrics.sketch_staging_stalls_total when wired.
        self.stalls = 0
        self.spill_cap = spill_cap
        self._ingest = ingest
        self._ingest_fallback = ingest_fallback
        self._put = put or jax.device_put
        if spill_cap is not None:
            shape: tuple = (flowpack.compact_buf_len(batch_size, spill_cap),)
            if ingest_fallback is None:
                raise ValueError("compact mode needs ingest_fallback")
        else:
            shape = (batch_size, flowpack.DENSE_WORDS)
        self._bufs = [np.empty(shape, np.uint32) for _ in range(n_slots)]
        self._dense_buf: Optional[np.ndarray] = None  # lazy fallback buffer
        self._tokens: list = [None] * n_slots
        self._slot = 0

    def fold(self, state, events, extra=None, dns=None, drops=None,
             xlat=None, quic=None):
        """Pack `events` into the next free slot, ship it, ingest it; returns
        the new sketch state (async — not blocked on)."""
        import jax

        slot = self._slot
        tok = self._tokens[slot]
        if tok is not None:
            if not tok.is_ready():
                self.stalls += 1
                if self._metrics is not None:
                    self._metrics.sketch_staging_stalls_total.inc()
            jax.block_until_ready(tok)  # slot's last consumer has finished
        feats = dict(extra=extra, dns=dns, drops=drops, xlat=xlat, quic=quic)
        if self.spill_cap is not None:
            buf = flowpack.pack_compact(
                events, batch_size=self.batch_size, spill_cap=self.spill_cap,
                out=self._bufs[slot], **feats)
            if buf is None:
                return self._fold_dense_fallback(state, events, feats)
            state, self._tokens[slot] = self._ingest(state, self._put(buf))
            self._slot = (slot + 1) % len(self._bufs)
            return state
        buf = flowpack.pack_dense_sharded(
            events, batch_size=self.batch_size, threads=self.pack_threads,
            out=self._bufs[slot], **feats)
        # ship FLAT: a (B*20,) transfer dodges device-layout padding of the
        # 20-wide minor dim (the ingest jit reshapes back, fused, free)
        state, self._tokens[slot] = self._ingest(
            state, self._put(buf.reshape(-1)))
        self._slot = (slot + 1) % len(self._bufs)
        return state

    def _fold_dense_fallback(self, state, events, feats):
        """Non-v4 (or spill-overflow) flows exceeded the spill lane: ship
        this batch full-width. Synchronous (the shared dense buffer has no
        slot ring), and rare — only v6-dominant traffic or a drop storm
        takes it repeatedly, at dense-path speed."""
        import jax

        if self._dense_buf is None:
            self._dense_buf = np.empty(
                (self.batch_size, flowpack.DENSE_WORDS), np.uint32)
        buf = flowpack.pack_dense_sharded(
            events, batch_size=self.batch_size, threads=self.pack_threads,
            out=self._dense_buf, **feats)
        state, tok = self._ingest_fallback(state, self._put(buf.reshape(-1)))
        jax.block_until_ready(tok)
        return state

    def drain(self) -> None:
        """Block until every in-flight batch has been fully ingested (host
        buffers are then free; used before checkpoint/window close)."""
        import jax

        for tok in self._tokens:
            if tok is not None:
                jax.block_until_ready(tok)


class ResidentStagingRing:
    """Staging ring for the RESIDENT feed — the lowest-bytes-per-record host
    path (~15B/record vs the compact feed's 40B; byte budget in
    docs/tpu_sketch.md). The host keeps a key->slot dictionary
    (`flowpack.KeyDict`, native); the device keeps the matching key table
    (`sketch.state.init_key_table`) threaded through the jitted ingest.

    `ingest` must be `make_ingest_resident_fn(with_token=True)`:
    `(state, table, flat) -> (state, table, token)`. The packer packs until
    a lane fills and reports how many rows it consumed; the ring ships that
    (always self-consistent) prefix and continues from the stop point in
    the next slot — so the dictionary and the device table learn
    monotonically even under cold-start key floods, with no dense fallback
    and no rollback. A full dictionary starts a fresh epoch (reset) at the
    next fold: stale device-table rows are harmless because every live
    slot is redefined through the new-key lane before any hot row
    references it."""

    def __init__(self, batch_size: int, ingest: Callable,
                 caps=None, slot_cap: int = 1 << 18,
                 put: Optional[Callable] = None, n_slots: int = 4,
                 metrics=None):
        import jax

        from netobserv_tpu.sketch import state as sk

        self.batch_size = batch_size
        self.caps = caps or flowpack.default_resident_caps(batch_size)
        self.slot_cap = slot_cap
        self.kdict = flowpack.KeyDict(slot_cap)
        self.key_table = jax.device_put(sk.init_key_table(slot_cap))
        self._ingest = ingest
        self._put = put or jax.device_put
        self._metrics = metrics
        self.stalls = 0
        self.continuations = 0  # extra chunks beyond one per fold()
        self.dict_resets = 0    # full-dictionary epochs
        self.spill_rows = 0     # rows that rode the full-width spill lane
        total = flowpack.resident_buf_len(batch_size, self.caps)
        self._bufs = [np.empty(total, np.uint32) for _ in range(n_slots)]
        self._tokens: list = [None] * n_slots
        self._slot = 0

    def fold(self, state, events, extra=None, dns=None, drops=None,
             xlat=None, quic=None):
        """Pack `events` (possibly in several chunks) into free ring slots,
        ship and ingest each; returns the new sketch state (async — not
        blocked on)."""
        import jax

        feats = dict(extra=extra, dns=dns, drops=drops, xlat=xlat, quic=quic)
        n = len(events)
        if n == 0:
            return state
        start = 0
        first = True
        while start < n:
            if self.kdict.count() >= self.slot_cap:
                # epoch roll: the device table needs no reset — every live
                # slot is redefined before any hot row references it
                self.kdict.reset()
                self.dict_resets += 1
            slot = self._slot
            tok = self._tokens[slot]
            if tok is not None:
                if not tok.is_ready():
                    self.stalls += 1
                    if self._metrics is not None:
                        self._metrics.sketch_staging_stalls_total.inc()
                jax.block_until_ready(tok)
            buf, consumed = flowpack.pack_resident(
                events, batch_size=self.batch_size, kdict=self.kdict,
                caps=self.caps, start=start, out=self._bufs[slot], **feats)
            if consumed == 0 and n:
                raise RuntimeError("resident pack made no progress")
            self.spill_rows += int(buf[2])
            if not first:
                self.continuations += 1
            first = False
            start += consumed
            state, self.key_table, self._tokens[slot] = self._ingest(
                state, self.key_table, self._put(buf))
            self._slot = (slot + 1) % len(self._bufs)
        return state

    def drain(self) -> None:
        """Block until every in-flight batch has been fully ingested (host
        buffers are then free; used before checkpoint/window close)."""
        import jax

        for tok in self._tokens:
            if tok is not None:
                jax.block_until_ready(tok)
