"""Host->device staging ring for the dense flow feed.

A small ring of preallocated host buffers lets eviction batch i+1 be packed
(`flowpack.pack_dense`, single C++ pass) while batch i's host->device
transfer and ingest are still in flight — the host-path pipelining that
closes the seam the reference names as its own hot spot
(`pkg/model/record_bench_test.go:10-14`).

Slot-reuse safety: a slot is repacked only after the *ingest* that consumed
it has finished, guarded by a token output of the jitted ingest (a tiny
slice of the dense input; it becomes ready only when the whole executable
has run). Blocking on the `device_put` result instead is NOT sufficient: on
backends that zero-copy aligned host arrays (the CPU backend), the put
result is "ready" immediately while the async-dispatched ingest may still be
reading the aliased host memory.

Depth: 2 slots stall the pipeline on tunneled links; 4 reach ~82% of the
pack+put ceiling (measured on the axon chip, see PARITY.md).
"""
from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from netobserv_tpu.datapath import flowpack
from netobserv_tpu.utils import faultinject


def default_spill_cap(batch_size: int) -> int:
    """Production spill-lane sizing for the compact feed: 1/8 of the batch
    (v6-heavy batches beyond it fall back to the dense feed). Bench and the
    exporter share this so the measured configuration is the shipped one."""
    return max(batch_size // 8, 64)


class _SlotRing:
    """Shared slot/token protocol of every staging ring — ONE definition of
    the slot-reuse guard described in the module docstring (the token must
    be a slice of the jitted ingest's input; blocking on the put result is
    not sufficient on zero-copy backends)."""

    def _init_slots(self, bufs: list, metrics) -> None:
        self._bufs = bufs
        self._tokens: list = [None] * len(bufs)
        self._slot = 0
        self._metrics = metrics
        self.stalls = 0

    def _wait_slot(self) -> int:
        """Return the next slot index, blocking until its previous consumer
        (the ingest that read the slot's buffer) has finished."""
        import jax

        # chaos seam: a hang here models a wedged device/transfer stalling
        # the staging feed — the thread folding (the exporter stage) stops
        # beating and the supervisor's hang detection takes over
        faultinject.fire("sketch.staging_wait")
        slot = self._slot
        tok = self._tokens[slot]
        if tok is not None:
            if not tok.is_ready():
                self.stalls += 1
                if self._metrics is not None:
                    self._metrics.sketch_staging_stalls_total.inc()
            jax.block_until_ready(tok)
        return slot

    def _advance(self, slot: int, token) -> None:
        self._tokens[slot] = token
        self._slot = (slot + 1) % len(self._bufs)

    def drain(self) -> None:
        """Block until every in-flight batch has been fully ingested (host
        buffers are then free; used before checkpoint/window close)."""
        import jax

        for tok in self._tokens:
            if tok is not None:
                jax.block_until_ready(tok)


class DenseStagingRing(_SlotRing):
    """Reusable host buffers + in-flight tokens for the dense ingest path.

    `ingest` must be a token-returning jitted fn — built with
    `sketch.state.make_ingest_dense_fn(with_token=True)` or
    `parallel.merge.make_sharded_ingest_fn(dense=True, with_token=True)` —
    i.e. `(state, dense) -> (state, token)`. `put` places a packed host
    buffer on device(s); defaults to `jax.device_put` (single device).

    Compact mode (`spill_cap` set, single-device only): slots hold the flat
    v4-compact feed (`flowpack.pack_compact`, ~40% of the dense bytes —
    the transfer link is the host path's bottleneck) and `ingest` must be a
    `make_ingest_compact_fn(with_token=True)` jit. Batches whose non-v4
    flows overflow the spill lane fall back to the dense feed through
    `ingest_fallback` (a `make_ingest_dense_fn(with_token=True)` jit) —
    same math, bigger transfer, synchronously drained (rare path).
    """

    def __init__(self, batch_size: int, ingest: Callable,
                 put: Optional[Callable] = None, n_slots: int = 4,
                 spill_cap: Optional[int] = None,
                 ingest_fallback: Optional[Callable] = None,
                 metrics=None, pack_threads: int = 1):
        import jax

        self.batch_size = batch_size
        #: >1 shards each dense pack across this many native packer threads
        #: (flowpack.pack_dense_sharded) — matters on hosts where the pack,
        #: not the transfer link, bounds the feed
        self.pack_threads = pack_threads
        self.spill_cap = spill_cap
        self._ingest = ingest
        self._ingest_fallback = ingest_fallback
        self._put = put or jax.device_put
        if spill_cap is not None:
            shape: tuple = (flowpack.compact_buf_len(batch_size, spill_cap),)
            if ingest_fallback is None:
                raise ValueError("compact mode needs ingest_fallback")
        else:
            shape = (batch_size, flowpack.DENSE_WORDS)
        self._init_slots([np.empty(shape, np.uint32)
                          for _ in range(n_slots)], metrics)
        self._dense_buf: Optional[np.ndarray] = None  # lazy fallback buffer

    def fold(self, state, events, extra=None, dns=None, drops=None,
             xlat=None, quic=None):
        """Pack `events` into the next free slot, ship it, ingest it; returns
        the new sketch state (async — not blocked on)."""
        slot = self._wait_slot()
        feats = dict(extra=extra, dns=dns, drops=drops, xlat=xlat, quic=quic)
        if self.spill_cap is not None:
            buf = flowpack.pack_compact(
                events, batch_size=self.batch_size, spill_cap=self.spill_cap,
                out=self._bufs[slot], **feats)
            if buf is None:
                return self._fold_dense_fallback(state, events, feats)
            state, token = self._ingest(state, self._put(buf))
            self._advance(slot, token)
            return state
        buf = flowpack.pack_dense_sharded(
            events, batch_size=self.batch_size, threads=self.pack_threads,
            out=self._bufs[slot], **feats)
        # ship FLAT: a (B*20,) transfer dodges device-layout padding of the
        # 20-wide minor dim (the ingest jit reshapes back, fused, free)
        state, token = self._ingest(state, self._put(buf.reshape(-1)))
        self._advance(slot, token)
        return state

    def _fold_dense_fallback(self, state, events, feats):
        """Non-v4 (or spill-overflow) flows exceeded the spill lane: ship
        this batch full-width. Synchronous (the shared dense buffer has no
        slot ring), and rare — only v6-dominant traffic or a drop storm
        takes it repeatedly, at dense-path speed."""
        import jax

        if self._dense_buf is None:
            self._dense_buf = np.empty(
                (self.batch_size, flowpack.DENSE_WORDS), np.uint32)
        buf = flowpack.pack_dense_sharded(
            events, batch_size=self.batch_size, threads=self.pack_threads,
            out=self._dense_buf, **feats)
        state, tok = self._ingest_fallback(state, self._put(buf.reshape(-1)))
        jax.block_until_ready(tok)
        return state


class ShardedResidentStagingRing(_SlotRing):
    """Resident feed over a DATA-sharded mesh: the global batch splits into
    `n_shards` contiguous row blocks, each packed by its OWN KeyDict into
    its own per-shard resident buffer region; the concatenated flat buffer
    ships with one sharded put whose contiguous split lands exactly on the
    region boundaries. Device-side twin:
    `parallel.merge.make_sharded_ingest_resident_fn` +
    `init_resident_tables` (one independent key table per data shard —
    lookups stay local, the steady-state no-collectives invariant holds).

    Multi-process note: every process must fold the SAME global batches
    (the existing `shard_batch`/`shard_dense` assumption) — dictionary
    evolution is deterministic in row order, so all processes assign
    identical slots.

    `ingest`: `(dist_state, key_tables, flat) -> (dist_state, key_tables,
    token)`. `put` places the flat host buffer (defaults to a plain
    device_put; pass `parallel.merge.shard_dense` bound to the mesh).
    `pack_threads > 1` packs the shard regions concurrently (the per-shard
    KeyDicts are independent; ctypes releases the GIL)."""

    def __init__(self, batch_size: int, n_shards: int, ingest: Callable,
                 key_tables, put: Callable,
                 caps=None, slot_cap: int = 1 << 18, n_slots: int = 4,
                 metrics=None, pack_threads: int = 1):
        if batch_size % n_shards:
            raise ValueError("batch_size must divide evenly over the shards")
        self.batch_size = batch_size
        self.n_shards = n_shards
        self.batch_per_shard = batch_size // n_shards
        self.caps = caps or flowpack.default_resident_caps(
            self.batch_per_shard)
        self.slot_cap = slot_cap
        self.pack_threads = pack_threads
        self.kdicts = [flowpack.KeyDict(slot_cap) for _ in range(n_shards)]
        self.key_tables = key_tables
        self._ingest = ingest
        self._put = put
        self.continuations = 0
        self.dict_resets = 0
        self.spill_rows = 0
        self._shard_words = flowpack.resident_buf_len(self.batch_per_shard,
                                                      self.caps)
        self._init_slots([np.empty(n_shards * self._shard_words, np.uint32)
                          for _ in range(n_slots)], metrics)

    def fold(self, state, events, extra=None, dns=None, drops=None,
             xlat=None, quic=None):
        """Pack `events` (split over the shards, possibly in several
        chunks) into free ring slots, ship and ingest each; returns the new
        dist state (async — not blocked on)."""
        n = len(events)
        if n == 0:
            return state
        feats = dict(extra=extra, dns=dns, drops=drops, xlat=xlat, quic=quic)
        bounds = [n * i // self.n_shards for i in range(self.n_shards + 1)]
        shard_ev = [events[bounds[i]:bounds[i + 1]]
                    for i in range(self.n_shards)]
        shard_feats = [
            {k: (v[bounds[i]:bounds[i + 1]] if v is not None and len(v)
                 else None) for k, v in feats.items()}
            for i in range(self.n_shards)]
        starts = [0] * self.n_shards
        first = True
        while any(starts[i] < len(shard_ev[i])
                  for i in range(self.n_shards)):
            slot = self._wait_slot()
            buf = self._bufs[slot]

            def pack_shard(i):
                # touches only shard-local state (its dict, its buffer
                # region, starts[i]); returns the diagnostic counters so
                # threaded packs don't race on shared attributes
                if starts[i] >= len(shard_ev[i]):
                    # exhausted shard in a continuation chunk: ship a
                    # zeroed region, and don't roll its dictionary epoch
                    # for rows it isn't packing
                    region = buf[i * self._shard_words:
                                 (i + 1) * self._shard_words]
                    region[:] = 0
                    return 0, 0
                kd = self.kdicts[i]
                resets = 0
                if kd.count() >= self.slot_cap:
                    kd.reset()  # per-shard epoch roll (ResidentStagingRing)
                    resets = 1
                region = buf[i * self._shard_words:
                             (i + 1) * self._shard_words]
                _, consumed = flowpack.pack_resident(
                    shard_ev[i], batch_size=self.batch_per_shard,
                    kdict=kd, caps=self.caps, start=starts[i],
                    out=region, **shard_feats[i])
                if consumed == 0 and starts[i] < len(shard_ev[i]):
                    raise RuntimeError("resident pack made no progress")
                starts[i] += consumed
                return int(region[2]), resets

            if self.pack_threads > 1 and self.n_shards > 1:
                # per-shard dictionaries are independent; the native pack
                # releases the GIL, so shards pack in true parallel
                outs = [f.result() for f in flowpack._pack_submit(
                    min(self.pack_threads, self.n_shards),
                    [lambda i=i: pack_shard(i)
                     for i in range(self.n_shards)])]
            else:
                outs = [pack_shard(i) for i in range(self.n_shards)]
            chunk_spills = sum(o[0] for o in outs)
            chunk_resets = sum(o[1] for o in outs)
            self.spill_rows += chunk_spills
            self.dict_resets += chunk_resets
            if self._metrics is not None:
                if chunk_spills:
                    self._metrics.sketch_resident_spill_rows_total.inc(
                        chunk_spills)
                if chunk_resets:
                    self._metrics.sketch_resident_dict_epochs_total.inc(
                        chunk_resets)
                if not first:
                    self._metrics.sketch_resident_continuations_total.inc()
            if not first:
                self.continuations += 1
            first = False
            state, self.key_tables, token = self._ingest(
                state, self.key_tables, self._put(buf))
            self._advance(slot, token)
        return state


class ResidentStagingRing(_SlotRing):
    """Staging ring for the RESIDENT feed — the lowest-bytes-per-record host
    path (~15B/record vs the compact feed's 40B; byte budget in
    docs/tpu_sketch.md). The host keeps a key->slot dictionary
    (`flowpack.KeyDict`, native); the device keeps the matching key table
    (`sketch.state.init_key_table`) threaded through the jitted ingest.

    `ingest` must be `make_ingest_resident_fn(with_token=True)`:
    `(state, table, flat) -> (state, table, token)`. The packer packs until
    a lane fills and reports how many rows it consumed; the ring ships that
    (always self-consistent) prefix and continues from the stop point in
    the next slot — so the dictionary and the device table learn
    monotonically even under cold-start key floods, with no dense fallback
    and no rollback. A full dictionary starts a fresh epoch (reset) at the
    next fold: stale device-table rows are harmless because every live
    slot is redefined through the new-key lane before any hot row
    references it."""

    def __init__(self, batch_size: int, ingest: Callable,
                 caps=None, slot_cap: int = 1 << 18,
                 put: Optional[Callable] = None, n_slots: int = 4,
                 metrics=None):
        import jax

        from netobserv_tpu.sketch import state as sk

        self.batch_size = batch_size
        self.caps = caps or flowpack.default_resident_caps(batch_size)
        self.slot_cap = slot_cap
        self.kdict = flowpack.KeyDict(slot_cap)
        self.key_table = jax.device_put(sk.init_key_table(slot_cap))
        self._ingest = ingest
        self._put = put or jax.device_put
        self.continuations = 0  # extra chunks beyond one per fold()
        self.dict_resets = 0    # full-dictionary epochs
        self.spill_rows = 0     # rows that rode the full-width spill lane
        total = flowpack.resident_buf_len(batch_size, self.caps)
        self._init_slots([np.empty(total, np.uint32)
                          for _ in range(n_slots)], metrics)

    def fold(self, state, events, extra=None, dns=None, drops=None,
             xlat=None, quic=None):
        """Pack `events` (possibly in several chunks) into free ring slots,
        ship and ingest each; returns the new sketch state (async — not
        blocked on)."""
        feats = dict(extra=extra, dns=dns, drops=drops, xlat=xlat, quic=quic)
        n = len(events)
        if n == 0:
            return state
        start = 0
        first = True
        while start < n:
            if self.kdict.count() >= self.slot_cap:
                # epoch roll: the device table needs no reset — every live
                # slot is redefined before any hot row references it
                self.kdict.reset()
                self.dict_resets += 1
                if self._metrics is not None:
                    self._metrics.sketch_resident_dict_epochs_total.inc()
            slot = self._wait_slot()
            buf, consumed = flowpack.pack_resident(
                events, batch_size=self.batch_size, kdict=self.kdict,
                caps=self.caps, start=start, out=self._bufs[slot], **feats)
            if consumed == 0 and n:
                raise RuntimeError("resident pack made no progress")
            self.spill_rows += int(buf[2])
            if self._metrics is not None:
                if buf[2]:
                    self._metrics.sketch_resident_spill_rows_total.inc(
                        int(buf[2]))
                if not first:
                    self._metrics.sketch_resident_continuations_total.inc()
            if not first:
                self.continuations += 1
            first = False
            start += consumed
            state, self.key_table, token = self._ingest(
                state, self.key_table, self._put(buf))
            self._advance(slot, token)
        return state
