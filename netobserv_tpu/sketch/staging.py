"""Host->device staging ring for the dense flow feed.

A small ring of preallocated host buffers lets eviction batch i+1 be packed
(`flowpack.pack_dense`, single C++ pass) while batch i's host->device
transfer and ingest are still in flight — the host-path pipelining that
closes the seam the reference names as its own hot spot
(`pkg/model/record_bench_test.go:10-14`).

Slot-reuse safety: a slot is repacked only after the *ingest* that consumed
it has finished, guarded by a token output of the jitted ingest (a tiny
slice of the dense input; it becomes ready only when the whole executable
has run). Blocking on the `device_put` result instead is NOT sufficient: on
backends that zero-copy aligned host arrays (the CPU backend), the put
result is "ready" immediately while the async-dispatched ingest may still be
reading the aliased host memory.

Depth: 2 slots stall the pipeline on tunneled links; 4 reach ~82% of the
pack+put ceiling (measured on the axon chip, see PARITY.md).
"""
from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from netobserv_tpu.datapath import flowpack


class DenseStagingRing:
    """Reusable host buffers + in-flight tokens for the dense ingest path.

    `ingest` must be a token-returning jitted fn — built with
    `sketch.state.make_ingest_dense_fn(with_token=True)` or
    `parallel.merge.make_sharded_ingest_fn(dense=True, with_token=True)` —
    i.e. `(state, dense) -> (state, token)`. `put` places a packed host
    buffer on device(s); defaults to `jax.device_put` (single device).
    """

    def __init__(self, batch_size: int, ingest: Callable,
                 put: Optional[Callable] = None, n_slots: int = 4):
        import jax

        self.batch_size = batch_size
        self._ingest = ingest
        self._put = put or jax.device_put
        self._bufs = [np.empty((batch_size, flowpack.DENSE_WORDS), np.uint32)
                      for _ in range(n_slots)]
        self._tokens: list = [None] * n_slots
        self._slot = 0

    def fold(self, state, events, extra=None, dns=None):
        """Pack `events` into the next free slot, ship it, ingest it; returns
        the new sketch state (async — not blocked on)."""
        import jax

        slot = self._slot
        tok = self._tokens[slot]
        if tok is not None:
            jax.block_until_ready(tok)  # slot's last consumer has finished
        buf = flowpack.pack_dense(events, batch_size=self.batch_size,
                                  extra=extra, dns=dns, out=self._bufs[slot])
        state, self._tokens[slot] = self._ingest(state, self._put(buf))
        self._slot = (slot + 1) % len(self._bufs)
        return state

    def drain(self) -> None:
        """Block until every in-flight batch has been fully ingested (host
        buffers are then free; used before checkpoint/window close)."""
        import jax

        for tok in self._tokens:
            if tok is not None:
                jax.block_until_ready(tok)
