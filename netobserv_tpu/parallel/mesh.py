"""Mesh construction helpers.

Axes:
- `data`   — batch dimension sharding; each device folds its shard of the flow
             stream into a local sketch replica (per-CPU-map analog).
- `sketch` — optional width sharding of the big linear sketches (Count-Min
             columns), for sketch sizes beyond one chip's comfortable HBM slice.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
SKETCH_AXIS = "sketch"


@dataclass(frozen=True)
class MeshSpec:
    data: int
    sketch: int = 1

    @classmethod
    def parse(cls, text: str, n_devices: int) -> "MeshSpec":
        """Parse "4", "4x2", or "" (all devices on data axis)."""
        if not text:
            return cls(data=n_devices)
        parts = [int(p) for p in text.lower().split("x")]
        if len(parts) == 1:
            return cls(data=parts[0])
        if len(parts) == 2:
            return cls(data=parts[0], sketch=parts[1])
        raise ValueError(f"bad mesh shape {text!r} (want D or DxS)")


def make_mesh(spec: Optional[MeshSpec] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    spec = spec or MeshSpec(data=len(devices))
    n = spec.data * spec.sketch
    if n > len(devices):
        raise ValueError(
            f"mesh {spec} needs {n} devices, have {len(devices)}")
    grid = np.asarray(devices[:n]).reshape(spec.data, spec.sketch)
    return Mesh(grid, (DATA_AXIS, SKETCH_AXIS))
