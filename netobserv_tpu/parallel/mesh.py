"""Mesh construction helpers.

Axes:
- `data`   — batch dimension sharding; each device folds its shard of the flow
             stream into a local sketch replica (per-CPU-map analog).
- `sketch` — optional width sharding of the big linear sketches (Count-Min
             columns), for sketch sizes beyond one chip's comfortable HBM slice.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
SKETCH_AXIS = "sketch"


@dataclass(frozen=True)
class MeshSpec:
    data: int
    sketch: int = 1

    @classmethod
    def parse(cls, text: str, n_devices: int) -> "MeshSpec":
        """Parse "4", "4x2", or "" (all devices on data axis)."""
        if not text:
            return cls(data=n_devices)
        parts = [int(p) for p in text.lower().split("x")]
        if len(parts) == 1:
            return cls(data=parts[0])
        if len(parts) == 2:
            return cls(data=parts[0], sketch=parts[1])
        raise ValueError(f"bad mesh shape {text!r} (want D or DxS)")


def shard_map_compat(f, mesh: Mesh, in_specs, out_specs, check: bool = False):
    """`jax.shard_map` across the jax versions this project meets: newer
    releases expose it at the top level with `check_vma`; 0.4.x only has
    `jax.experimental.shard_map.shard_map` with the same knob spelled
    `check_rep`. One definition so every shard_map call site stays
    version-agnostic."""
    fn = getattr(jax, "shard_map", None)
    kwargs = {"check_vma": check}
    if fn is None:
        from jax.experimental.shard_map import shard_map as fn
        kwargs = {"check_rep": check}
    try:
        return fn(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **kwargs)
    except TypeError:
        # the transition releases spell the knob the other way around
        other = ({"check_rep": check} if "check_vma" in kwargs
                 else {"check_vma": check})
        return fn(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **other)


def make_mesh(spec: Optional[MeshSpec] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    spec = spec or MeshSpec(data=len(devices))
    n = spec.data * spec.sketch
    if n > len(devices):
        raise ValueError(
            f"mesh {spec} needs {n} devices, have {len(devices)}")
    grid = np.asarray(devices[:n]).reshape(spec.data, spec.sketch)
    return Mesh(grid, (DATA_AXIS, SKETCH_AXIS))
