"""Multi-host initialization for the sketch analytics tier.

The reference scales across hosts with one independent agent per node and a
collector assembling results (SURVEY.md §2.3 item 3). The sketch tier instead
runs ONE SPMD program across all hosts' chips: `jax.distributed` wires the
processes (DCN), the mesh spans every device, and the same shard_map
ingest/merge code runs unchanged — collectives ride ICI within a slice and
DCN between hosts.

Environment (standard JAX multi-process contract):
    SKETCH_COORDINATOR   host:port of process 0 (JAX coordinator)
    SKETCH_NUM_PROCESSES total process count
    SKETCH_PROCESS_ID    this process's index
On TPU pods these usually come from the scheduler and jax.distributed
auto-detects; the env vars are the manual override.
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger("netobserv_tpu.parallel.distributed")


def maybe_initialize_distributed() -> bool:
    """Initialize jax.distributed when configured; returns True if multi-host.

    Safe to call unconditionally: no-op without configuration.
    """
    import jax

    coord = os.environ.get("SKETCH_COORDINATOR", "")
    nproc = os.environ.get("SKETCH_NUM_PROCESSES", "")
    pid = os.environ.get("SKETCH_PROCESS_ID", "")
    if coord and not nproc:
        raise ValueError(
            "SKETCH_COORDINATOR is set but SKETCH_NUM_PROCESSES is not — "
            "multi-host init needs both (plus SKETCH_PROCESS_ID per worker)")
    if coord and nproc:
        if not pid:
            raise ValueError(
                "SKETCH_PROCESS_ID must be set per worker (0..N-1) when "
                "SKETCH_COORDINATOR/SKETCH_NUM_PROCESSES are configured")
        jax.distributed.initialize(
            coordinator_address=coord, num_processes=int(nproc),
            process_id=int(pid))
        log.info("jax.distributed initialized: process %s/%s via %s",
                 pid, nproc, coord)
        return True
    # TPU pod auto-detection path
    if os.environ.get("TPU_WORKER_HOSTNAMES", "").count(",") >= 1:
        try:
            jax.distributed.initialize()
            log.info("jax.distributed auto-initialized (%d processes)",
                     jax.process_count())
            return jax.process_count() > 1
        except Exception as exc:  # pragma: no cover - env dependent
            log.warning("jax.distributed auto-init failed: %s", exc)
    return False
