"""Multi-host initialization for the sketch analytics tier.

The reference scales across hosts with one independent agent per node and a
collector assembling results (SURVEY.md §2.3 item 3). The sketch tier instead
runs ONE SPMD program across all hosts' chips: `jax.distributed` wires the
processes (DCN), the mesh spans every device, and the same shard_map
ingest/merge code runs unchanged — collectives ride ICI within a slice and
DCN between hosts.

Environment (standard JAX multi-process contract):
    SKETCH_COORDINATOR   host:port of process 0 (JAX coordinator)
    SKETCH_NUM_PROCESSES total process count
    SKETCH_PROCESS_ID    this process's index
On TPU pods these usually come from the scheduler and jax.distributed
auto-detects; the env vars are the manual override.

The federation aggregator tier spans pods with the same machinery under its
own prefix (FEDERATION_COORDINATOR / FEDERATION_NUM_PROCESSES /
FEDERATION_PROCESS_ID) so a cross-pod aggregator deployment does not
collide with per-host agents' SKETCH_* settings on shared nodes: agents
read ONLY the SKETCH_* vars (default `prefixes`); the aggregator passes
`prefixes=("FEDERATION_", "SKETCH_")` and all three variables resolve from
the ONE prefix whose COORDINATOR is set (never mixed across prefixes).
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger("netobserv_tpu.parallel.distributed")


def maybe_initialize_distributed(
        prefixes: tuple[str, ...] = ("SKETCH_",)) -> bool:
    """Initialize jax.distributed when configured; returns True if multi-host.

    Safe to call unconditionally: no-op without configuration. `prefixes`
    scopes which env-var families this PROCESS may join: per-host agents
    keep the default (SKETCH_* only — an aggregator's FEDERATION_* vars on
    a shared node must never pull an agent into the aggregator's mesh);
    the aggregator tier passes ("FEDERATION_", "SKETCH_"). The first
    prefix with COORDINATOR set wins, and nproc/pid come from that SAME
    prefix only.
    """
    import jax

    prefix = next((p for p in prefixes
                   if os.environ.get(p + "COORDINATOR", "")), prefixes[-1])
    coord_key = prefix + "COORDINATOR"
    coord = os.environ.get(coord_key, "")
    nproc = os.environ.get(prefix + "NUM_PROCESSES", "")
    pid = os.environ.get(prefix + "PROCESS_ID", "")
    if coord and not nproc:
        raise ValueError(
            f"{coord_key} is set but {prefix}NUM_PROCESSES is not — "
            f"multi-host init needs both (plus {prefix}PROCESS_ID per "
            "worker)")
    if coord and nproc:
        if not pid:
            raise ValueError(
                f"{prefix}PROCESS_ID must be set per worker (0..N-1) when "
                f"{coord_key}/{prefix}NUM_PROCESSES are configured")
        jax.distributed.initialize(
            coordinator_address=coord, num_processes=int(nproc),
            process_id=int(pid))
        log.info("jax.distributed initialized: process %s/%s via %s",
                 pid, nproc, coord)
        return True
    # TPU pod auto-detection path
    if os.environ.get("TPU_WORKER_HOSTNAMES", "").count(",") >= 1:
        try:
            jax.distributed.initialize()
            log.info("jax.distributed auto-initialized (%d processes)",
                     jax.process_count())
            return jax.process_count() > 1
        except Exception as exc:  # pragma: no cover - env dependent
            log.warning("jax.distributed auto-init failed: %s", exc)
    return False
