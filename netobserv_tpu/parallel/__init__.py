"""Multi-chip SPMD for sketch state (the distributed communication backend).

The reference scales out with per-CPU kernel maps merged in userspace, one agent
per node, and gRPC/Kafka to a collector tier (SURVEY.md §2.3). Here the same
roles map onto the TPU stack:

- per-CPU partial maps      -> per-device partial sketches (batch sharded on the
                               `data` mesh axis, folded locally, no collectives)
- userspace eviction merge  -> ICI collectives at window roll: psum (Count-Min,
                               histograms, EWMA rates), max (HLL registers),
                               all_gather + re-select (top-K)
- DaemonSet-per-node        -> one process per TPU host, same SPMD program,
                               DCN handled by jax.distributed
- memory scale-out          -> optional `sketch` mesh axis sharding the Count-Min
                               width across devices (model-parallel sketches)
"""

from netobserv_tpu.parallel.mesh import make_mesh, MeshSpec  # noqa: F401
from netobserv_tpu.parallel.merge import (  # noqa: F401
    make_sharded_ingest_fn, merge_states, make_merge_fn,
)
