"""Sharded sketch ingest + ICI window merge (shard_map over the device mesh).

Layout of the distributed state (`DistState` = SketchState pytree with a leading
`data`-axis dimension on every array):

- every leaf:               [n_data, ...]  sharded P("data") — per-device partials
- Count-Min counts:         [n_data, depth, width] sharded P("data", None, "sketch")
                            — width additionally split across the `sketch` axis
- EWMA mean/var:            identical across the data axis (baselines are global;
                            only `rate` is a true partial)

Steady state does **zero collectives**: each device folds its batch shard into
its partial (the per-CPU-map analog, SURVEY.md §2.3 item 1). All communication
happens at window roll: psum for linear sketches, max for HLL registers,
all_gather + re-select for the top-K table — the ICI merge the north star asks
for (BASELINE.json config 3).
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from netobserv_tpu.ops import countmin, ewma, hll, quantile, topk
from netobserv_tpu.parallel.mesh import (
    DATA_AXIS, SKETCH_AXIS, shard_map_compat,
)
from netobserv_tpu.sketch import state as sk
from netobserv_tpu.utils import retrace

# ---------------------------------------------------------------------------
# sharding specs
# ---------------------------------------------------------------------------


def _state_specs(state: sk.SketchState) -> sk.SketchState:
    """PartitionSpec tree for the distributed state (leading data axis added;
    Count-Min width additionally split over the sketch axis; the top-K table
    carries a SECOND leading sketch-axis dim — owner-sharded scoring makes
    each sketch shard's table a distinct key set, not a replica)."""
    d = P(DATA_AXIS)
    h = P(DATA_AXIS, SKETCH_AXIS)
    return sk.SketchState(
        cm_bytes=countmin.CountMin(counts=P(DATA_AXIS, None, SKETCH_AXIS)),
        cm_pkts=countmin.CountMin(counts=P(DATA_AXIS, None, SKETCH_AXIS)),
        heavy=topk.SlotTable(words=h, h1=h, h2=h, counts=h, prev_counts=h,
                             first_seen=h, epoch=h, valid=h),
        hll_src=hll.HLL(regs=d),
        hll_per_dst=hll.PerDstHLL(regs=d),
        hll_per_src=hll.PerDstHLL(regs=d),
        hist_rtt=quantile.LogHist(counts=d),
        hist_dns=quantile.LogHist(counts=d),
        ddos=ewma.EWMA(mean=d, var=d, rate=d, windows=d),
        syn=ewma.EWMA(mean=d, var=d, rate=d, windows=d),
        synack=d,
        drops_ewma=ewma.EWMA(mean=d, var=d, rate=d, windows=d),
        drop_causes=d, dscp_bytes=d,
        conv_fwd=d, conv_rev=d,
        total_records=d, total_bytes=d,
        total_drop_bytes=d, total_drop_packets=d,
        quic_records=d, nat_records=d, heavy_evictions=d, window=d,
    )


def _drop_lead(pstate: sk.SketchState) -> sk.SketchState:
    """Local (inside-shard_map) view: drop the data-axis dim everywhere and
    the extra sketch-axis dim on the top-K table."""
    s = jax.tree.map(lambda x: x[0], pstate)
    return s._replace(heavy=jax.tree.map(lambda x: x[0], s.heavy))


def _add_lead(s: sk.SketchState) -> sk.SketchState:
    """Inverse of _drop_lead."""
    out = jax.tree.map(lambda x: x[None], s)
    return out._replace(heavy=jax.tree.map(lambda x: x[None], out.heavy))


def _put_global(arr: np.ndarray, mesh: Mesh, spec: P) -> jax.Array:
    """device_put a host-global array with the given sharding. On a
    multi-process mesh each addressable shard is placed explicitly: every
    process holds the SAME global array (the existing shard_batch/
    shard_dense contract), and some jax releases route the one-put form
    through a cross-host equality collective that CPU backends cannot
    execute (the 2-process gloo dryrun would die in device_put)."""
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    shards = [
        jax.device_put(arr[idx], d)
        for d, idx in sharding.addressable_devices_indices_map(
            arr.shape).items()
    ]
    return jax.make_array_from_single_device_arrays(
        arr.shape, sharding, shards)


def put_replicated(mesh: Mesh, arr: np.ndarray) -> jax.Array:
    """device_put a host array fully replicated over the mesh (multi-process
    safe — same explicit per-shard placement as `_put_global`). The
    federation fold's delta tables ride this."""
    return _put_global(np.asarray(arr), mesh, P())


def init_dist_state(cfg: sk.SketchConfig, mesh: Mesh) -> sk.SketchState:
    """Per-device partial sketch state, zeros, laid out across the mesh."""
    ndata = mesh.shape[DATA_AXIS]
    nsk = mesh.shape[SKETCH_AXIS]
    template = sk.init_state(cfg)
    specs = _state_specs(template)

    def place(leaf, spec):
        # top-K leaves (spec P(data, sketch)) carry a SECOND lead dim: one
        # distinct owner-sharded table per (data, sketch) device
        lead = (ndata, nsk) if (len(spec) >= 2 and spec[1] == SKETCH_AXIS) \
            else (ndata,)
        arr = np.zeros(lead + leaf.shape, dtype=leaf.dtype)
        return _put_global(arr, mesh, spec)

    return jax.tree.map(place, template, specs)


def shard_batch(mesh: Mesh, arrays: dict[str, np.ndarray]) -> dict[str, jax.Array]:
    """Place a global columnar batch (leading dim divisible by n_data) onto the
    mesh, split along the data axis and replicated along the sketch axis."""
    out = {}
    for k, v in arrays.items():
        out[k] = _put_global(np.asarray(v), mesh, P(DATA_AXIS))
    return out


# ---------------------------------------------------------------------------
# sharded ingest (no collectives)
# ---------------------------------------------------------------------------


def make_sharded_ingest_fn(mesh: Mesh, cfg: sk.SketchConfig,
                           donate: bool = True,
                           dense: bool = False,
                           with_token: bool = False) -> Callable:
    """Jitted `(dist_state, batch) -> dist_state` over the mesh.

    `dense=False`: batch is the six-array dict. `dense=True`: batch is one
    (B, 16) u32 flowpack dense array (row-sharded over the data axis, ONE
    transfer per batch); each shard unpacks its rows locally — the unpack is
    elementwise, so sharding it adds no collectives.

    `with_token=True` (dense only) returns `(dist_state, token)`, the
    slot-reuse guard for `sketch.staging.DenseStagingRing` (see
    `sketch.state.make_ingest_dense_fn`)."""
    if with_token and not dense:
        raise ValueError("with_token requires dense=True")
    nsk = mesh.shape[SKETCH_AXIS]
    template = sk.init_state(cfg)
    specs = _state_specs(template)

    def local_step(pstate: sk.SketchState, batch):
        s = _drop_lead(pstate)
        arrays = sk.dense_to_arrays(batch) if dense else batch
        s = sk.ingest(s, arrays,
                      sketch_axis=SKETCH_AXIS if nsk > 1 else None,
                      sketch_shards=nsk,
                      # owner-sharded sketches keep the masked-scatter path;
                      # the Pallas fold applies to whole-width replicas
                      use_pallas=(cfg.use_pallas if nsk == 1 else False),
                      enable_fanout=cfg.enable_fanout,
                      enable_asym=cfg.enable_asym)
        out = _add_lead(s)
        if with_token:
            return out, (batch[:1] if batch.ndim == 1 else batch[:1, 0])
        return out

    # one spec as a pytree PREFIX covers the whole batch: every column is
    # row-sharded over the data axis, whatever feature columns it carries
    batch_specs = P(DATA_AXIS)
    shmapped = shard_map_compat(
        local_step, mesh=mesh,
        in_specs=(specs, batch_specs),
        out_specs=(specs, P(DATA_AXIS)) if with_token else specs,
        check=False,
    )
    # retrace watchdog (utils/retrace.py): the wrapper delegates .lower /
    # ._cache_size, so the HLO no-collectives checks still introspect it
    return retrace.watch(
        jax.jit(shmapped, donate_argnums=(0,) if donate else ()),
        "sharded_ingest_dense" if dense else "sharded_ingest")


def init_resident_tables(mesh: Mesh, slot_cap: int,
                         lanes: int = 1) -> jax.Array:
    """Per-DATA-shard device key tables for the sharded resident feed:
    (n_data, lanes, slot_cap, KEY_WORDS) u32, sharded P(data) — each data
    shard owns `lanes` independent tables, one per host-side packer lane
    (lanes > 1 lets the host pack a shard's rows across several threads;
    `sketch.staging.ShardedResidentStagingRing`), and the sketch-axis
    replicas stay consistent because every sketch column of a data row
    applies the same new-key lanes. Lookups are pure local gathers, so the
    steady-state no-collectives invariant is untouched."""
    ndata = mesh.shape[DATA_AXIS]
    arr = np.zeros((ndata, lanes, slot_cap, sk.KEY_WORDS), np.uint32)
    return _put_global(arr, mesh, P(DATA_AXIS))


def make_sharded_ingest_resident_fn(mesh: Mesh, cfg: sk.SketchConfig,
                                    batch_per_lane: int, caps,
                                    donate: bool = True,
                                    lanes: int = 1,
                                    watch_name: str =
                                    "sharded_ingest_resident") -> Callable:
    """Jitted `(dist_state, key_tables, flat) -> (dist_state, key_tables,
    token)` — the RESIDENT feed over the mesh (~15B/record instead of the
    dense feed's 80). `flat` concatenates `lanes` resident regions per data
    shard (`flowpack.resident_buf_len(batch_per_lane, caps)` words each,
    packed by that region's own KeyDict —
    `sketch.staging.ShardedResidentStagingRing`); the contiguous split over
    the data axis lands exactly on per-shard region-group boundaries. Each
    shard scatters its new-key lanes into ITS table slices and gathers
    hot-row keys locally — no collectives.

    `key_tables` may carry MORE than `lanes` rows per shard (the superbatch
    fold ladder shares one table array across ladder entries —
    `sketch.state.resident_lane_arrays`); `watch_name` distinguishes ladder
    entries in the retrace watchdog accounting."""
    nsk = mesh.shape[SKETCH_AXIS]
    template = sk.init_state(cfg)
    specs = _state_specs(template)

    def local_step(pstate: sk.SketchState, table, flat):
        s = _drop_lead(pstate)
        arrays, tbl = sk.resident_lane_arrays(flat, table[0], batch_per_lane,
                                              caps, lanes)
        s = sk.ingest(s, arrays,
                      sketch_axis=SKETCH_AXIS if nsk > 1 else None,
                      sketch_shards=nsk,
                      use_pallas=(cfg.use_pallas if nsk == 1 else False),
                      enable_fanout=cfg.enable_fanout,
                      enable_asym=cfg.enable_asym)
        return _add_lead(s), tbl[None], flat[:1]

    shmapped = shard_map_compat(
        local_step, mesh=mesh,
        in_specs=(specs, P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(specs, P(DATA_AXIS), P(DATA_AXIS)),
        check=False,
    )
    return retrace.watch(
        jax.jit(shmapped, donate_argnums=(0, 1) if donate else ()),
        watch_name)


def shard_dense(mesh: Mesh, dense: np.ndarray) -> jax.Array:
    """Place a flowpack dense batch onto the mesh, rows split over the data
    axis, replicated over the sketch axis. Accepts (B, 20) rows or the flat
    (B*20,) form the staging ring ships (a contiguous flat split lands on
    row boundaries because B divides evenly over the data axis)."""
    return _put_global(np.asarray(dense), mesh, P(DATA_AXIS))


def shard_dense_per_device(mesh: Mesh, flat: np.ndarray) -> jax.Array:
    """shard_dense via EXPLICIT per-device placement: slice the flat host
    buffer along the data axis and issue one single-device `device_put` per
    LOCAL device, then assemble the global array. Semantically identical to
    `shard_dense`; the difference is the transfer shape — N independent
    host->device DMAs this host can run in parallel, instead of one sharded
    put whose slicing strategy is the runtime's.

    Multi-process meshes: each process places only the slices of ITS OWN
    devices (`make_array_from_single_device_arrays` takes addressable
    shards only), so `flat` must hold this host's rows at their GLOBAL
    positions — in practice every host packs the full batch layout and
    transfers just its slices (the per-host feed shape the multi-chip
    budget calls for, docs/tpu_sketch.md); `__graft_entry__` measures both
    strategies and the dryrun reports the split."""
    assert flat.ndim == 1
    ndata = mesh.shape[DATA_AXIS]
    per = len(flat) // ndata
    assert per * ndata == len(flat)
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    shards = []
    me = jax.process_index()
    # Mesh.devices is an (data, sketch) ndarray; P(DATA_AXIS) replicates
    # each data-slice across the sketch columns
    for i in range(ndata):
        row = None
        for dev in np.asarray(mesh.devices)[i]:
            if dev.process_index != me:
                continue  # another host feeds that device
            if row is None:
                row = flat[i * per:(i + 1) * per]
            shards.append(jax.device_put(row, dev))
    return jax.make_array_from_single_device_arrays(
        flat.shape, sharding, shards)


# ---------------------------------------------------------------------------
# window roll: merge partials over ICI, emit a replicated report, reset
# ---------------------------------------------------------------------------


def merge_states(s: sk.SketchState, nsk: int) -> sk.SketchState:
    """Merge per-device partials into a replicated view (call inside shard_map;
    arrays here are local slices without the data-axis dim)."""
    cm_b = countmin.CountMin(jax.lax.psum(s.cm_bytes.counts, DATA_AXIS))
    cm_p = countmin.CountMin(jax.lax.psum(s.cm_pkts.counts, DATA_AXIS))

    def gather(x):
        # owner-sharded tables hold DISJOINT key sets per sketch shard, so
        # the candidate pool must be gathered over BOTH mesh axes
        x = jax.lax.all_gather(x, DATA_AXIS, axis=0, tiled=True)
        if nsk > 1:
            x = jax.lax.all_gather(x, SKETCH_AXIS, axis=0, tiled=True)
        return x

    stacked = jax.tree.map(gather, s.heavy)
    if nsk > 1:
        qfn = lambda a, b: countmin.query_sharded(  # noqa: E731
            cm_b, a, b, SKETCH_AXIS, nsk)
    else:
        qfn = None
    # roll-time reconciliation of the persistent slot tables: duplicate
    # identities across shards collapse with segmented metadata merges
    # (prev_counts sum, first_seen min, epoch max) and counts re-score
    # against the globally merged CM — the one place cross-shard top-K
    # work happens (steady state stays collective-free)
    heavy = topk.merge_slot_tables(stacked, cm_b, s.heavy.k, query_fn=qfn)
    return sk.SketchState(
        cm_bytes=cm_b, cm_pkts=cm_p, heavy=heavy,
        hll_src=hll.HLL(jax.lax.pmax(s.hll_src.regs, DATA_AXIS)),
        hll_per_dst=hll.PerDstHLL(jax.lax.pmax(s.hll_per_dst.regs, DATA_AXIS)),
        hll_per_src=hll.PerDstHLL(jax.lax.pmax(s.hll_per_src.regs, DATA_AXIS)),
        hist_rtt=quantile.LogHist(jax.lax.psum(s.hist_rtt.counts, DATA_AXIS)),
        hist_dns=quantile.LogHist(jax.lax.psum(s.hist_dns.counts, DATA_AXIS)),
        ddos=ewma.EWMA(mean=s.ddos.mean, var=s.ddos.var,
                       rate=jax.lax.psum(s.ddos.rate, DATA_AXIS),
                       windows=s.ddos.windows),
        # the EWMA baselines (mean/var) are replicated and rolled identically
        # on every device; only the window rates are true partials
        syn=ewma.EWMA(mean=s.syn.mean, var=s.syn.var,
                      rate=jax.lax.psum(s.syn.rate, DATA_AXIS),
                      windows=s.syn.windows),
        synack=jax.lax.psum(s.synack, DATA_AXIS),
        drops_ewma=ewma.EWMA(mean=s.drops_ewma.mean, var=s.drops_ewma.var,
                             rate=jax.lax.psum(s.drops_ewma.rate, DATA_AXIS),
                             windows=s.drops_ewma.windows),
        drop_causes=jax.lax.psum(s.drop_causes, DATA_AXIS),
        dscp_bytes=jax.lax.psum(s.dscp_bytes, DATA_AXIS),
        conv_fwd=jax.lax.psum(s.conv_fwd, DATA_AXIS),
        conv_rev=jax.lax.psum(s.conv_rev, DATA_AXIS),
        total_records=jax.lax.psum(s.total_records, DATA_AXIS),
        total_bytes=jax.lax.psum(s.total_bytes, DATA_AXIS),
        total_drop_bytes=jax.lax.psum(s.total_drop_bytes, DATA_AXIS),
        total_drop_packets=jax.lax.psum(s.total_drop_packets, DATA_AXIS),
        quic_records=jax.lax.psum(s.quic_records, DATA_AXIS),
        nat_records=jax.lax.psum(s.nat_records, DATA_AXIS),
        heavy_evictions=jax.lax.psum(s.heavy_evictions, DATA_AXIS),
        window=s.window,
    )


def make_fold_delta_fn(mesh: Mesh, cfg: sk.SketchConfig,
                       donate: bool = True) -> Callable:
    """Jitted `(dist_state, tables, owner) -> dist_state` — the FEDERATION
    aggregator's mesh fold: merge ONE agent's delta-frame tables
    (`federation.delta.TABLE_SPEC` device arrays, replicated over the mesh)
    into the data shard that OWNS that agent (`owner`: i32[1], a stable
    hash of the agent id — deltas from one agent always land in one
    shard's partial, the per-CPU-map analog one level up). Steady state
    adds no collectives: every shard computes the masked merge locally;
    all cross-shard reconciliation stays at window roll
    (`make_merge_fn`'s two-axis gather), exactly like the flow ingest.

    The federation mesh shards AGENT ownership over the data axis only:
    a width-sharded (sketch axis > 1) mesh cannot accept deltas, because
    an owner-sharded CM shard is an INDEPENDENT width-w/nsk sketch (keys
    re-hash into the local width) — a whole-width delta table has no
    decomposition into it. Width sharding stays an agent-side feature;
    use an Nx1 federation mesh."""
    from netobserv_tpu.federation import statemerge

    nsk = mesh.shape[SKETCH_AXIS]
    if nsk > 1:
        raise ValueError(
            "federation fold requires a data-axis-only mesh (Nx1): "
            "owner-sharded CM shards re-hash keys into their local width, "
            f"so a whole-width delta table cannot merge into a {nsk}-way "
            "width-sharded aggregate")
    template = sk.init_state(cfg)
    specs = _state_specs(template)

    def local_fold(pstate: sk.SketchState, t: dict, owner: jax.Array):
        s = _drop_lead(pstate)
        mine = jax.lax.axis_index(DATA_AXIS) == owner[0]
        merged = statemerge.merge_tables(s, t)
        new = jax.tree.map(lambda a, b: jnp.where(mine, a, b), merged, s)
        return _add_lead(new)

    shmapped = shard_map_compat(
        local_fold, mesh=mesh,
        # tables + owner are replicated to every device; the fold masks
        in_specs=(specs, P(), P()),
        out_specs=specs, check=False,
    )
    return retrace.watch(
        jax.jit(shmapped, donate_argnums=(0,) if donate else ()),
        "federation_fold_delta")


def make_merge_fn(mesh: Mesh, cfg: sk.SketchConfig,
                  reset_sketches: bool = True,
                  decay_factor: float | None = None,
                  with_tables: bool = False) -> Callable:
    """Jitted `(dist_state) -> (dist_state, WindowReport)`.

    The report is fully replicated (every device computes the cluster-wide
    merge); the returned state is reset for the next window with EWMA baselines
    rolled on the merged rates.

    `with_tables=True` additionally returns the REPLICATED merged table
    snapshot (`sketch.state.state_tables` of the merged pre-roll state) —
    the federation aggregator's query-surface source on mesh deployments.
    Data-axis-only meshes (like the federation fold itself: on a
    width-sharded mesh the per-shard CM planes are independent local-width
    sketches with no replicated whole-width form).
    """
    nsk = mesh.shape[SKETCH_AXIS]
    if with_tables and nsk > 1:
        raise ValueError("with_tables requires a data-axis-only mesh (Nx1) "
                         "— width-sharded CM planes have no replicated "
                         "whole-width snapshot")
    template = sk.init_state(cfg)
    specs = _state_specs(template)

    report_specs = sk.WindowReport(
        heavy=topk.SlotTable(words=P(), h1=P(), h2=P(), counts=P(),
                             prev_counts=P(), first_seen=P(), epoch=P(),
                             valid=P()),
        distinct_src=P(), per_dst_cardinality=P(), per_src_fanout=P(),
        rtt_quantiles_us=P(),
        dns_quantiles_us=P(), ddos_z=P(), syn_z=P(), syn_rate=P(),
        synack_rate=P(), drop_z=P(), drop_causes=P(), dscp_bytes=P(),
        conv_fwd=P(), conv_rev=P(),
        total_records=P(), total_bytes=P(),
        total_drop_bytes=P(), total_drop_packets=P(),
        quic_records=P(), nat_records=P(), heavy_evictions=P(),
        window=P(),
    )

    def local_roll(pstate: sk.SketchState):
        s = _drop_lead(pstate)
        merged = merge_states(s, nsk)
        tables = None
        if with_tables:
            tables = sk.state_tables(merged)
        ddos_state, z = ewma.roll(merged.ddos, cfg.ewma_alpha)
        syn_state, syn_z = ewma.roll(merged.syn, cfg.ewma_alpha)
        drops_state, drop_z = ewma.roll(merged.drops_ewma, cfg.ewma_alpha)
        gamma = quantile.gamma_for(merged.hist_rtt.n_buckets)
        report = sk.WindowReport(
            heavy=merged.heavy,
            distinct_src=hll.estimate(merged.hll_src.regs),
            per_dst_cardinality=hll.estimate(merged.hll_per_dst.regs),
            per_src_fanout=hll.estimate(merged.hll_per_src.regs),
            rtt_quantiles_us=quantile.quantile(merged.hist_rtt,
                                               jnp.asarray(sk.QS), gamma),
            dns_quantiles_us=quantile.quantile(merged.hist_dns,
                                               jnp.asarray(sk.QS), gamma),
            ddos_z=z,
            syn_z=syn_z,
            syn_rate=merged.syn.rate,
            synack_rate=merged.synack,
            drop_z=drop_z,
            drop_causes=merged.drop_causes,
            dscp_bytes=merged.dscp_bytes,
            conv_fwd=merged.conv_fwd,
            conv_rev=merged.conv_rev,
            total_records=merged.total_records,
            total_bytes=merged.total_bytes,
            total_drop_bytes=merged.total_drop_bytes,
            total_drop_packets=merged.total_drop_packets,
            quic_records=merged.quic_records,
            nat_records=merged.nat_records,
            heavy_evictions=merged.heavy_evictions,
            window=merged.window,
        )
        ewma_rolled = dict(
            ddos=ddos_state._replace(rate=jnp.zeros_like(s.ddos.rate)),
            syn=syn_state._replace(rate=jnp.zeros_like(s.syn.rate)),
            drops_ewma=drops_state._replace(
                rate=jnp.zeros_like(s.drops_ewma.rate)),
        )
        if decay_factor is not None:
            # decay the local PARTIAL (linearity makes per-shard decay exact)
            new = sk.decay_state(s, decay_factor)._replace(
                window=s.window + 1, **ewma_rolled,
            )
        elif reset_sketches:
            fresh = jax.tree.map(jnp.zeros_like, s)
            # each device's slot table PERSISTS through the roll (identity,
            # first_seen, epoch stay local — no collectives): prev_counts
            # take this window's final per-device estimates, counts reset
            new = fresh._replace(
                heavy=topk.slot_roll(s.heavy, 0.0),
                window=s.window + 1, **ewma_rolled,
            )
        else:
            # synack resets with its paired EWMA rate (see state.roll_window)
            new = s._replace(ddos=ddos_state, syn=syn_state,
                             drops_ewma=drops_state,
                             synack=jnp.zeros_like(s.synack),
                             heavy=topk.slot_roll(s.heavy, 1.0),
                             heavy_evictions=jnp.zeros_like(
                                 s.heavy_evictions),
                             window=s.window + 1)
        if with_tables:
            return _add_lead(new), report, tables
        return _add_lead(new), report

    if with_tables:
        table_specs = {name: P() for name in
                       sk.state_tables(sk.init_state(cfg))}
        out_specs = (specs, report_specs, table_specs)
    else:
        out_specs = (specs, report_specs)
    shmapped = shard_map_compat(
        local_roll, mesh=mesh, in_specs=(specs,),
        out_specs=out_specs, check=False,
    )
    return retrace.watch(jax.jit(shmapped, donate_argnums=(0,)),
                         "sharded_merge")
