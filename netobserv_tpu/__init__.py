"""netobserv_tpu — a TPU-native network-flow observability framework.

Capability parity target: the NetObserv eBPF Agent (see SURVEY.md). Two planes:

- **Capture plane** (host-native): an eBPF C datapath (``netobserv_tpu/datapath/bpf``)
  aggregates packets into kernel flow maps; a loader/evictor brings flow records into
  userspace (reference seam: ``pkg/tracer/tracer.go:52-76``).
- **Analytics plane** (TPU-idiomatic, the new part): evicted records are packed into
  fixed-shape columnar batches and folded into streaming sketches (Count-Min,
  HyperLogLog, top-K heavy hitters, latency quantiles, EWMA anomaly scores) as
  JAX/Pallas programs, sharded over a `jax.sharding.Mesh` and merged with ICI
  collectives (reference seam replaced: ``pkg/flow`` + ``pkg/exporter``).

Nothing in this package imports jax at module import time except the `ops`, `sketch`
and `parallel` subpackages, so the thin host agent can run on machines without
accelerators.
"""

__version__ = "0.1.0"
