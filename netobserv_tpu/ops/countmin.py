"""Count-Min sketch: point-queryable frequency table in O(d*w) memory.

The TPU replacement for exact per-key hashmap aggregation (reference:
`pkg/flow/account.go` Accounter). Counters are a dense [depth, width] array;
updates are masked scatter-adds over a batch, queries are gather+min. Merging two
sketches (across chips over ICI) is elementwise `+` / `psum` — that linearity is
why this sketch family suits SPMD (SURVEY.md §2.3 item 1).

Error bound (Cormode & Muthukrishnan): with w = 2^k, depth d, a point query
overestimates by at most eps*N with probability 1-delta, eps = e/w, delta = e^-d.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from netobserv_tpu.ops import hashing


class CountMin(NamedTuple):
    """Sketch state: counts[depth, width]. dtype float32 for byte volumes
    (exact below 2^24, ~1e-7 relative above — fine for heavy-hitter ranking),
    int32 for packet counts."""

    counts: jax.Array

    @property
    def depth(self) -> int:
        return self.counts.shape[0]

    @property
    def width(self) -> int:
        return self.counts.shape[1]


def init(depth: int = 4, width: int = 1 << 16, dtype=jnp.float32) -> CountMin:
    assert width & (width - 1) == 0, "width must be a power of two"
    return CountMin(counts=jnp.zeros((depth, width), dtype=dtype))


def update(cm: CountMin, h1: jax.Array, h2: jax.Array, values: jax.Array,
           valid: jax.Array) -> CountMin:
    """Fold one batch into the sketch.

    h1/h2: uint32[B] base hashes; values: [B]; valid: bool[B].
    Duplicate keys within a batch accumulate correctly (scatter-add semantics).
    """
    d, w = cm.counts.shape
    idx = hashing.row_indices(h1, h2, d, w)  # uint32[d, B]
    vals = jnp.where(valid, values, 0).astype(cm.counts.dtype)
    vals = jnp.broadcast_to(vals[None, :], idx.shape)
    rows = jnp.broadcast_to(jnp.arange(d, dtype=jnp.int32)[:, None], idx.shape)
    new = cm.counts.at[rows, idx.astype(jnp.int32)].add(
        vals, mode="drop", unique_indices=False)
    return CountMin(counts=new)


def update_two(cm_a: CountMin, cm_b: CountMin, h1: jax.Array, h2: jax.Array,
               vals_a: jax.Array, vals_b: jax.Array,
               valid: jax.Array) -> tuple[CountMin, CountMin]:
    """Fold one batch into two same-shape sketches with ONE scatter.

    The two counter planes (bytes, packets) share hash indices, so stacking
    them on a trailing axis halves the scatter count on the hot path.

    Both sketches must use inexact (float) counters: the fold accumulates in
    float32, which would silently round large int32 counters."""
    d, w = cm_a.counts.shape
    assert cm_b.counts.shape == (d, w)
    assert (jnp.issubdtype(cm_a.counts.dtype, jnp.inexact)
            and jnp.issubdtype(cm_b.counts.dtype, jnp.inexact)), \
        "update_two requires float sketches (use countmin.update for int)"
    idx = hashing.row_indices(h1, h2, d, w).astype(jnp.int32)  # [d, B]
    stacked = jnp.stack(
        [cm_a.counts.astype(jnp.float32), cm_b.counts.astype(jnp.float32)],
        axis=-1)  # [d, w, 2]
    vals = jnp.stack([
        jnp.where(valid, vals_a, 0).astype(jnp.float32),
        jnp.where(valid, vals_b, 0).astype(jnp.float32)], axis=-1)  # [B, 2]
    vals = jnp.broadcast_to(vals[None], (d,) + vals.shape)  # [d, B, 2]
    rows = jnp.broadcast_to(jnp.arange(d, dtype=jnp.int32)[:, None],
                            idx.shape)
    new = stacked.at[rows, idx].add(vals, mode="drop", unique_indices=False)
    return (CountMin(counts=new[..., 0].astype(cm_a.counts.dtype)),
            CountMin(counts=new[..., 1].astype(cm_b.counts.dtype)))


def query(cm: CountMin, h1: jax.Array, h2: jax.Array) -> jax.Array:
    """Point-query estimated counts for keys given their base hashes."""
    d, w = cm.counts.shape
    idx = hashing.row_indices(h1, h2, d, w)  # [d, B]
    rows = jnp.broadcast_to(jnp.arange(d, dtype=jnp.int32)[:, None], idx.shape)
    ests = cm.counts[rows, idx.astype(jnp.int32)]  # [d, B]
    return jnp.min(ests, axis=0)


def merge(a: CountMin, b: CountMin) -> CountMin:
    """Linear merge — the ICI collective for this sketch is psum."""
    return CountMin(counts=a.counts + b.counts)


# ---------------------------------------------------------------------------
# Width-sharded variants: the [d, W] counter array is split across the
# `sketch` mesh axis by KEY OWNERSHIP (model-parallel sketches — SURVEY.md
# §2.3 mapping). An independent hash assigns every key to one shard; the
# owner folds the key's ENTIRE depth into its local [d, W/nsk] subtable.
# Owner-locality is the point: a shard can point-query its own keys with NO
# collective — which is what lets the steady-state ingest (top-K candidate
# scoring, sketch/state.py) run collective-free on 2D meshes. The psum query
# exists only for the window-roll merge. Per-key error matches an unsharded
# width-W sketch: each shard holds ~1/nsk of the keys in 1/nsk of the
# columns, so counter load (keys per column) is unchanged.
# ---------------------------------------------------------------------------

def owner_shard(h1: jax.Array, h2: jax.Array, n_shards: int) -> jax.Array:
    """Which sketch shard owns each key — an independent hash of the 64-bit
    key identity (decorrelated from the column hashes)."""
    return (hashing.fmix32(h1 ^ (h2 * jnp.uint32(0x9E3779B1)))
            % jnp.uint32(n_shards)).astype(jnp.int32)


def update_sharded(cm_local: CountMin, h1: jax.Array, h2: jax.Array,
                   values: jax.Array, valid: jax.Array,
                   axis_name: str, n_shards: int) -> CountMin:
    """Fold a batch into an owner-sharded sketch (call inside shard_map):
    each shard accumulates only the keys it owns, at full depth."""
    shard = jax.lax.axis_index(axis_name).astype(jnp.int32)
    mine = valid & (owner_shard(h1, h2, n_shards) == shard)
    return update(cm_local, h1, h2, values, mine)


def query_sharded_local(cm_local: CountMin, h1: jax.Array, h2: jax.Array,
                        axis_name: str, n_shards: int) -> jax.Array:
    """Collective-free point query: complete estimates for keys THIS shard
    owns, -1 (dead) for everyone else's. The steady-state scoring primitive."""
    shard = jax.lax.axis_index(axis_name).astype(jnp.int32)
    mine = owner_shard(h1, h2, n_shards) == shard
    return jnp.where(mine, query(cm_local, h1, h2), -1.0)


def query_sharded(cm_local: CountMin, h1: jax.Array, h2: jax.Array,
                  axis_name: str, n_shards: int) -> jax.Array:
    """Exact point query against an owner-sharded sketch (one psum; used at
    window roll, never on the per-batch path)."""
    shard = jax.lax.axis_index(axis_name).astype(jnp.int32)
    mine = owner_shard(h1, h2, n_shards) == shard
    part = jnp.where(mine, query(cm_local, h1, h2), 0.0)
    return jax.lax.psum(part, axis_name)  # exactly one shard owns each key


def total(cm: CountMin) -> jax.Array:
    """Total inserted mass (any single row sums to N)."""
    return jnp.sum(cm.counts[0])
