"""Count-Min sketch: point-queryable frequency table in O(d*w) memory.

The TPU replacement for exact per-key hashmap aggregation (reference:
`pkg/flow/account.go` Accounter). Counters are a dense [depth, width] array;
updates are masked scatter-adds over a batch, queries are gather+min. Merging two
sketches (across chips over ICI) is elementwise `+` / `psum` — that linearity is
why this sketch family suits SPMD (SURVEY.md §2.3 item 1).

Error bound (Cormode & Muthukrishnan): with w = 2^k, depth d, a point query
overestimates by at most eps*N with probability 1-delta, eps = e/w, delta = e^-d.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import custom_batching

from netobserv_tpu.ops import hashing


class CountMin(NamedTuple):
    """Sketch state: counts[depth, width]. dtype float32 for byte volumes
    (exact below 2^24, ~1e-7 relative above — fine for heavy-hitter ranking),
    int32 for packet counts."""

    counts: jax.Array

    @property
    def depth(self) -> int:
        return self.counts.shape[0]

    @property
    def width(self) -> int:
        return self.counts.shape[1]


def init(depth: int = 4, width: int = 1 << 16, dtype=jnp.float32) -> CountMin:
    assert width & (width - 1) == 0, "width must be a power of two"
    return CountMin(counts=jnp.zeros((depth, width), dtype=dtype))


def update(cm: CountMin, h1: jax.Array, h2: jax.Array, values: jax.Array,
           valid: jax.Array) -> CountMin:
    """Fold one batch into the sketch.

    h1/h2: uint32[B] base hashes; values: [B]; valid: bool[B].
    Duplicate keys within a batch accumulate correctly (scatter-add semantics).
    """
    d, w = cm.counts.shape
    idx = hashing.row_indices(h1, h2, d, w)  # uint32[d, B]
    vals = jnp.where(valid, values, 0).astype(cm.counts.dtype)
    vals = jnp.broadcast_to(vals[None, :], idx.shape)
    rows = jnp.broadcast_to(jnp.arange(d, dtype=jnp.int32)[:, None], idx.shape)
    new = cm.counts.at[rows, idx.astype(jnp.int32)].add(
        vals, mode="drop", unique_indices=False)
    return CountMin(counts=new)


@custom_batching.custom_vmap
def _scatter_add_two(counts_a: jax.Array, counts_b: jax.Array,
                     idx: jax.Array, va: jax.Array,
                     vb: jax.Array) -> tuple[jax.Array, jax.Array]:
    """The scatter core of `update_two`: counts [d, w] f32, idx [d, B] i32,
    va/vb [B] f32 (already masked). Unbatched, this is exactly the historic
    one-scatter interleaved form. Under vmap (the tenant-stacked fold,
    sketch/tenancy.py) the custom rule below replaces XLA's batched-scatter
    lowering — which serializes pathologically on CPU — with a flat
    (T*d, w) scatter per plane at the same per-update cost as the unbatched
    form; bit-exact either way (same adds per cell in the same batch order;
    tests/test_tenancy.py pins it per tenant)."""
    d, w = counts_a.shape
    stacked = jnp.stack([counts_a, counts_b], axis=-1)  # [d, w, 2]
    vals = jnp.stack([va, vb], axis=-1)  # [B, 2]
    vals = jnp.broadcast_to(vals[None], (d,) + vals.shape)  # [d, B, 2]
    rows = jnp.broadcast_to(jnp.arange(d, dtype=jnp.int32)[:, None],
                            idx.shape)
    new = stacked.at[rows, idx].add(vals, mode="drop", unique_indices=False)
    return new[..., 0], new[..., 1]


@_scatter_add_two.def_vmap
def _scatter_add_two_batched(axis_size, in_batched, counts_a, counts_b,
                             idx, va, vb):
    t = axis_size

    def bcast(x, batched):
        return x if batched else jnp.broadcast_to(x[None], (t,) + x.shape)

    counts_a = bcast(counts_a, in_batched[0])
    counts_b = bcast(counts_b, in_batched[1])
    idx = bcast(idx, in_batched[2])
    va = bcast(va, in_batched[3])
    vb = bcast(vb, in_batched[4])
    d, w = counts_a.shape[1:]
    b = va.shape[-1]
    # flatten the tenant axis into the row axis: tenant t's depth-r row is
    # flat row t*d + r, so one plain 2-coordinate scatter covers all t*d*b
    # updates (reshape is a bitcast; the scatter stays in place under
    # donation). Two per-plane scatters rather than one interleaved — the
    # [t, d, w, 2] interleave would materialize a full copy of both planes.
    rows = jnp.broadcast_to(jnp.arange(t * d, dtype=jnp.int32)[:, None],
                            (t * d, b))
    fidx = idx.reshape(t * d, b)

    def one(counts, v):
        vv = jnp.broadcast_to(v[:, None, :], (t, d, b)).reshape(t * d, b)
        return counts.reshape(t * d, w).at[rows, fidx].add(
            vv, mode="drop", unique_indices=False).reshape(t, d, w)

    return (one(counts_a, va), one(counts_b, vb)), (True, True)


def update_two(cm_a: CountMin, cm_b: CountMin, h1: jax.Array, h2: jax.Array,
               vals_a: jax.Array, vals_b: jax.Array,
               valid: jax.Array) -> tuple[CountMin, CountMin]:
    """Fold one batch into two same-shape sketches with ONE scatter.

    The two counter planes (bytes, packets) share hash indices, so stacking
    them on a trailing axis halves the scatter count on the hot path.

    Both sketches must use inexact (float) counters: the fold accumulates in
    float32, which would silently round large int32 counters."""
    d, w = cm_a.counts.shape
    assert cm_b.counts.shape == (d, w)
    assert (jnp.issubdtype(cm_a.counts.dtype, jnp.inexact)
            and jnp.issubdtype(cm_b.counts.dtype, jnp.inexact)), \
        "update_two requires float sketches (use countmin.update for int)"
    idx = hashing.row_indices(h1, h2, d, w).astype(jnp.int32)  # [d, B]
    va = jnp.where(valid, vals_a, 0).astype(jnp.float32)
    vb = jnp.where(valid, vals_b, 0).astype(jnp.float32)
    new_a, new_b = _scatter_add_two(cm_a.counts.astype(jnp.float32),
                                    cm_b.counts.astype(jnp.float32), idx,
                                    va, vb)
    return (CountMin(counts=new_a.astype(cm_a.counts.dtype)),
            CountMin(counts=new_b.astype(cm_b.counts.dtype)))


def query(cm: CountMin, h1: jax.Array, h2: jax.Array) -> jax.Array:
    """Point-query estimated counts for keys given their base hashes."""
    d, w = cm.counts.shape
    idx = hashing.row_indices(h1, h2, d, w)  # [d, B]
    rows = jnp.broadcast_to(jnp.arange(d, dtype=jnp.int32)[:, None], idx.shape)
    ests = cm.counts[rows, idx.astype(jnp.int32)]  # [d, B]
    return jnp.min(ests, axis=0)


def merge(a: CountMin, b: CountMin) -> CountMin:
    """Linear merge — the ICI collective for this sketch is psum."""
    return CountMin(counts=a.counts + b.counts)


# ---------------------------------------------------------------------------
# Width-sharded variants: the [d, W] counter array is split across the
# `sketch` mesh axis by KEY OWNERSHIP (model-parallel sketches — SURVEY.md
# §2.3 mapping). An independent hash assigns every key to one shard; the
# owner folds the key's ENTIRE depth into its local [d, W/nsk] subtable.
# Owner-locality is the point: a shard can point-query its own keys with NO
# collective — which is what lets the steady-state ingest (top-K candidate
# scoring, sketch/state.py) run collective-free on 2D meshes. The psum query
# exists only for the window-roll merge. Per-key error matches an unsharded
# width-W sketch: each shard holds ~1/nsk of the keys in 1/nsk of the
# columns, so counter load (keys per column) is unchanged.
# ---------------------------------------------------------------------------

def owner_shard(h1: jax.Array, h2: jax.Array, n_shards: int) -> jax.Array:
    """Which sketch shard owns each key — an independent hash of the 64-bit
    key identity (decorrelated from the column hashes)."""
    return (hashing.fmix32(h1 ^ (h2 * jnp.uint32(0x9E3779B1)))
            % jnp.uint32(n_shards)).astype(jnp.int32)


def update_sharded(cm_local: CountMin, h1: jax.Array, h2: jax.Array,
                   values: jax.Array, valid: jax.Array,
                   axis_name: str, n_shards: int) -> CountMin:
    """Fold a batch into an owner-sharded sketch (call inside shard_map):
    each shard accumulates only the keys it owns, at full depth."""
    shard = jax.lax.axis_index(axis_name).astype(jnp.int32)
    mine = valid & (owner_shard(h1, h2, n_shards) == shard)
    return update(cm_local, h1, h2, values, mine)


def query_sharded_local(cm_local: CountMin, h1: jax.Array, h2: jax.Array,
                        axis_name: str, n_shards: int) -> jax.Array:
    """Collective-free point query: complete estimates for keys THIS shard
    owns, -1 (dead) for everyone else's. The steady-state scoring primitive."""
    shard = jax.lax.axis_index(axis_name).astype(jnp.int32)
    mine = owner_shard(h1, h2, n_shards) == shard
    return jnp.where(mine, query(cm_local, h1, h2), -1.0)


def query_sharded(cm_local: CountMin, h1: jax.Array, h2: jax.Array,
                  axis_name: str, n_shards: int) -> jax.Array:
    """Exact point query against an owner-sharded sketch (one psum; used at
    window roll, never on the per-batch path)."""
    shard = jax.lax.axis_index(axis_name).astype(jnp.int32)
    mine = owner_shard(h1, h2, n_shards) == shard
    part = jnp.where(mine, query(cm_local, h1, h2), 0.0)
    return jax.lax.psum(part, axis_name)  # exactly one shard owns each key


def total(cm: CountMin) -> jax.Array:
    """Total inserted mass (any single row sums to N)."""
    return jnp.sum(cm.counts[0])
