"""Log-bucketed latency histograms with quantile queries (DDSketch-flavored).

BASELINE.json config 4: "RTT-histogram + DNS-latency quantile sketch". Buckets are
log-gamma spaced, so any quantile estimate has bounded *relative* error
(gamma = 1.02 -> ~1%); updates are masked scatter-adds; merge is `+`/psum, same
collective as Count-Min.
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp

DEFAULT_GAMMA = 1.02
DEFAULT_BUCKETS = 1024
DEFAULT_MAX_VALUE = 10_000_000  # 10 s in microseconds


def gamma_for(n_buckets: int, max_value: float = DEFAULT_MAX_VALUE) -> float:
    """Gamma such that `max_value` still lands below the clip bucket.

    With fewer buckets the spacing coarsens (worse relative error) instead of
    silently saturating the range."""
    return float(math.exp(math.log(max_value) / max(n_buckets - 2, 1)))


class LogHist(NamedTuple):
    counts: jax.Array  # float32[n_buckets]; bucket 0 holds zero-valued
    # samples (float so sliding-window decay is exact; counts stay integral
    # in reset mode)

    @property
    def n_buckets(self) -> int:
        return self.counts.shape[0]


def init(n_buckets: int = DEFAULT_BUCKETS) -> LogHist:
    return LogHist(counts=jnp.zeros((n_buckets,), dtype=jnp.float32))


def bucket_of(values: jax.Array, n_buckets: int,
              gamma: float = DEFAULT_GAMMA) -> jax.Array:
    """Bucket index for non-negative integer samples (e.g. microseconds)."""
    v = values.astype(jnp.float32)
    b = jnp.ceil(jnp.log(jnp.maximum(v, 1.0)) / math.log(gamma)).astype(jnp.int32)
    b = jnp.clip(b + 1, 1, n_buckets - 1)  # shift: bucket 0 reserved for v == 0
    return jnp.where(values == 0, 0, b)


def update(h: LogHist, values: jax.Array, valid: jax.Array,
           gamma: float = DEFAULT_GAMMA) -> LogHist:
    idx = bucket_of(values, h.n_buckets, gamma)
    inc = valid.astype(h.counts.dtype)
    return LogHist(counts=h.counts.at[idx].add(inc, mode="drop"))


def bucket_value(bucket: jax.Array, gamma: float = DEFAULT_GAMMA) -> jax.Array:
    """Representative value of a bucket (midpoint estimator: 2*g^b/(g+1))."""
    b = bucket.astype(jnp.float32) - 1.0  # undo the zero-reservation shift
    val = 2.0 * jnp.power(gamma, b) / (gamma + 1.0)
    return jnp.where(bucket == 0, 0.0, val)


def quantile(h: LogHist, qs: jax.Array, gamma: float = DEFAULT_GAMMA) -> jax.Array:
    """Estimate quantiles qs in [0,1]. Returns float32[len(qs)] sample values."""
    c = jnp.cumsum(h.counts.astype(jnp.float32))
    n = c[-1]
    targets = jnp.ceil(qs * jnp.maximum(n, 1.0))
    targets = jnp.maximum(targets, 1.0)
    buckets = jnp.searchsorted(c, targets - 0.5, side="left")
    vals = bucket_value(buckets, gamma)
    return jnp.where(n > 0, vals, 0.0)  # empty histogram -> 0, not max bucket


def merge(a: LogHist, b: LogHist) -> LogHist:
    return LogHist(counts=a.counts + b.counts)
