"""Fixed-K heavy-hitter tables maintained entirely on device.

Two generations live here:

- **SlotTable** (the production plane since ISSUE 13): a SpaceSaving-style
  d-way set-associative slot table whose rows keep STABLE identity across
  batch folds and across window rolls. Candidate maintenance happens in the
  per-batch update path (`slot_update`, with a fused Pallas reduction twin in
  `ops/pallas/topk_kernel.py`), so a window roll ships a READY top-K with
  per-slot churn metadata (`counts`, `prev_counts`, `first_seen`, `epoch`) —
  no host post-pass. Counts are Count-Min point estimates, so the CM error
  bound (count <= true + e/w * N with prob 1-e^-d) carries over verbatim.

- **TopK** (the legacy concat+re-score path): after the CM fold every batch
  key is a candidate; candidates and the current table are re-scored by CM
  point query, deduplicated, and the top K survive via `lax.top_k`. Slot
  identity is NOT stable across folds (rows reshuffle on every update), so
  there is nothing to diff across windows. Kept as the pinned baseline for
  `bench.py --topk-only` and as the exact-sort `_select`/`merge_stacked`
  oracle the slot-table merge is graded against.

Everything is fixed-shape — no heaps, no dynamic growth — so it jits and
shards cleanly (reference analog being replaced: the Go map in
`pkg/flow/account.go`). Key identity is the (h1, h2) 64-bit pair; the full
40-byte key words ride along through gathers so results can be rendered
exactly. A cross-key (h1, h2) collision is ~2^-64 per pair — negligible at
flow scale.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from netobserv_tpu.ops import countmin, hashing


class TopK(NamedTuple):
    words: jax.Array   # uint32[K, W] — packed key material
    h1: jax.Array      # uint32[K]
    h2: jax.Array      # uint32[K]
    counts: jax.Array  # float32[K] — CM-estimated totals, -1 for empty slots
    valid: jax.Array   # bool[K]

    @property
    def k(self) -> int:
        return self.words.shape[0]


def init(k: int = 1024, key_words: int = 10) -> TopK:
    return TopK(
        words=jnp.zeros((k, key_words), dtype=jnp.uint32),
        h1=jnp.zeros((k,), dtype=jnp.uint32),
        h2=jnp.zeros((k,), dtype=jnp.uint32),
        counts=jnp.full((k,), -1.0, dtype=jnp.float32),
        valid=jnp.zeros((k,), dtype=jnp.bool_),
    )


def _select(words, h1, h2, est, k: int) -> TopK:
    """Dedup by (h1, h2) identity and keep the top-k by est (invalid est = -1)."""
    n = h1.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    s_h1, s_h2, s_idx = jax.lax.sort((h1, h2, idx), num_keys=2)
    s_est = est[s_idx]
    first = jnp.concatenate([
        jnp.ones((1,), dtype=jnp.bool_),
        (s_h1[1:] != s_h1[:-1]) | (s_h2[1:] != s_h2[:-1]),
    ])
    s_est = jnp.where(first, s_est, -1.0)
    top_est, top_pos = jax.lax.top_k(s_est, k)
    orig = s_idx[top_pos]
    sel_valid = top_est > 0
    return TopK(
        words=jnp.where(sel_valid[:, None], words[orig], 0),
        h1=jnp.where(sel_valid, s_h1[top_pos], 0),
        h2=jnp.where(sel_valid, s_h2[top_pos], 0),
        counts=jnp.where(sel_valid, top_est, -1.0),
        valid=sel_valid,
    )


_SLOT_BITS = 19  # dedup slot space (2^19 ~ 0.2% residual collision vs K=1024)


def update(table: TopK, cm: countmin.CountMin, words: jax.Array, h1: jax.Array,
           h2: jax.Array, valid: jax.Array, query_fn=None,
           salt: jax.Array | int = 0) -> TopK:
    """Fold one batch (whose mass is already in `cm`) into the table.

    `query_fn(h1, h2) -> est` overrides the plain CM point query (used for
    width-sharded sketches, where the query needs a psum over the sketch axis).

    Dedup strategy: a full lexicographic sort over table+batch is exact but
    dominates ingest cost (~5ms/batch measured). Instead, duplicates are
    collapsed with a scatter-min "slot owner" table over 2^19 slots: every
    live row hashes its full 64-bit key identity (h1 AND h2) plus `salt`
    into a slot, the lowest row index owns it, and only owners are eligible
    for `lax.top_k` selection. Two *distinct* keys sharing a slot suppress
    the higher-indexed one for the CURRENT WINDOW (table rows always outrank
    batch rows); passing the window counter as `salt` reshuffles slots at
    every roll so a colliding pair is re-separated next window. Residual
    loss: ~(K+B)/2^19 ≈ 3% chance a given new key collides with anything in
    one window, ~0.2% with a table key — and never the same pair twice.
    (A naive candidate cut by estimate does NOT work: under skew the top
    rows are duplicates of a few mega-keys and recall collapses — measured.)
    The exact sort-based `_select` remains in use for window merges.
    """
    if query_fn is None:
        query_fn = lambda a, b: countmin.query(cm, a, b)  # noqa: E731
    batch_est = jnp.where(valid, query_fn(h1, h2), -1.0)
    table_est = jnp.where(table.valid,
                          query_fn(table.h1, table.h2), -1.0)
    all_words = jnp.concatenate([table.words, words], axis=0)
    all_h1 = jnp.concatenate([table.h1, h1])
    all_h2 = jnp.concatenate([table.h2, h2])
    all_est = jnp.concatenate([table_est, batch_est])

    n = all_h1.shape[0]
    n_slots = 1 << _SLOT_BITS
    # slot identity covers the full 64-bit key hash (h1 AND h2) plus the salt
    slot = (hashing.fmix32(all_h1 ^ ((all_h2 << 16) | (all_h2 >> 16))
                           ^ jnp.uint32(salt))
            & jnp.uint32(n_slots - 1)).astype(jnp.int32)
    rows = jnp.arange(n, dtype=jnp.int32)
    live = all_est > 0
    owner = jnp.full((n_slots,), n, dtype=jnp.int32)
    # dead rows must not own slots (a stale table slot could otherwise
    # suppress a live key)
    owner = owner.at[jnp.where(live, slot, n_slots - 1)].min(
        jnp.where(live, rows, n), mode="drop")
    is_owner = owner[slot] == rows
    sel_est = jnp.where(is_owner & live, all_est, -1.0)
    top_est, pos = jax.lax.top_k(sel_est, table.k)
    sel_valid = top_est > 0
    return TopK(
        words=jnp.where(sel_valid[:, None], all_words[pos], 0),
        h1=jnp.where(sel_valid, all_h1[pos], 0),
        h2=jnp.where(sel_valid, all_h2[pos], 0),
        counts=jnp.where(sel_valid, top_est, -1.0),
        valid=sel_valid,
    )


def merge_stacked(stacked: TopK, cm_merged: countmin.CountMin, k: int,
                  query_fn=None) -> TopK:
    """Merge per-device tables stacked along axis 0 into one size-k table.

    stacked arrays have shape [n_dev * K, ...]. Counts are re-queried against
    the merged CM so the selection reflects cluster-wide mass (SURVEY.md §5.8:
    "allgather + re-select top-K over ICI")."""
    if query_fn is None:
        query_fn = lambda a, b: countmin.query(cm_merged, a, b)  # noqa: E731
    est = jnp.where(stacked.valid, query_fn(stacked.h1, stacked.h2), -1.0)
    return _select(stacked.words, stacked.h1, stacked.h2, est, k)


# ---------------------------------------------------------------------------
# Persistent-slot heavy-hitter table (the device-resident top-K plane)
# ---------------------------------------------------------------------------

#: d-way set associativity: each key identity hashes to SLOT_WAYS candidate
#: slots (odd stride over a power-of-two K makes them distinct); a new key
#: challenges the weakest of its candidates. 8 ways measured the tail-set
#: F1 of the full table at 0.93+ on the accuracy sweep (4 ways: ~0.87 —
#: recall@100 is 1.0 either way; the extra gathers are noise next to the
#: CM fold) — more choices mean a marginal key almost always finds either
#: an empty slot or the globally-weak occupant it deserves to beat
SLOT_WAYS = 8
#: seed of the slot-placement hash family — deliberately NOT salted by the
#: window counter: a key's candidate slots must be stable across rolls, or
#: the table loses exactly the cross-window identity it exists to keep
_SLOT_SEED = 0x705C
#: "no winner" sentinel for the insertion-row reduction (both the scatter
#: and the Pallas form use it, so the reductions compare bit-exact)
NO_WINNER = 0x7FFFFFFF

#: insertion rounds per batch: one slot admits ONE winner per round, so a
#: new key that loses a same-batch conflict (two new keys targeting the
#: same weakest slot) re-challenges against the UPDATED table in the next
#: round — its min-defense candidate is recomputed, so it usually lands
#: in a still-empty slot. Two rounds make single-appearance insertion
#: near-complete (a sustained stream's keys also re-challenge at their
#: next appearance); the rounds share the same prepare/reduce/compose,
#: so the two-form invariant holds per round
SLOT_ROUNDS = 2


class SlotTable(NamedTuple):
    """Heavy-hitter table with persistent per-slot identity.

    A slot, once owned by a key, keeps that key (and its `first_seen`
    window) until a heavier key evicts it — so diffing `counts` against
    `prev_counts` across a roll is a per-KEY churn record, and `epoch`
    (bumped at every insertion) marks occupancy changes even when the same
    identity re-enters. Invalid slots carry zeros everywhere."""

    words: jax.Array        # uint32[K, W] — packed key material
    h1: jax.Array           # uint32[K]
    h2: jax.Array           # uint32[K]
    counts: jax.Array       # float32[K] — current-window CM estimate
    prev_counts: jax.Array  # float32[K] — previous window's final estimate
    first_seen: jax.Array   # int32[K] — window id at insertion
    epoch: jax.Array        # int32[K] — insertion generation counter
    valid: jax.Array        # bool[K]

    @property
    def k(self) -> int:
        return self.words.shape[0]


def init_slots(k: int = 1024, key_words: int = 10) -> SlotTable:
    assert k & (k - 1) == 0, "slot table size must be a power of two"
    return SlotTable(
        words=jnp.zeros((k, key_words), dtype=jnp.uint32),
        h1=jnp.zeros((k,), dtype=jnp.uint32),
        h2=jnp.zeros((k,), dtype=jnp.uint32),
        counts=jnp.zeros((k,), dtype=jnp.float32),
        prev_counts=jnp.zeros((k,), dtype=jnp.float32),
        first_seen=jnp.zeros((k,), dtype=jnp.int32),
        epoch=jnp.zeros((k,), dtype=jnp.int32),
        valid=jnp.zeros((k,), dtype=jnp.bool_),
    )


def slot_candidates(h1: jax.Array, h2: jax.Array, k: int) -> jax.Array:
    """The SLOT_WAYS candidate slots of each key identity: int32[B, WAYS].

    Kirsch–Mitzenmacher over a slot-family remix of (h1, h2); the stride is
    forced odd so the WAYS candidates are distinct mod the power-of-two K."""
    s1 = hashing.fmix32(h1 ^ jnp.uint32(_SLOT_SEED))
    s2 = hashing.fmix32(h2 ^ jnp.uint32(_SLOT_SEED * 2 + 1)) | jnp.uint32(1)
    ways = jnp.arange(SLOT_WAYS, dtype=jnp.uint32)
    return ((s1[:, None] + ways[None, :] * s2[:, None])
            & jnp.uint32(k - 1)).astype(jnp.int32)


def slot_prepare(table: SlotTable, h1: jax.Array, h2: jax.Array,
                 est: jax.Array) -> tuple[jax.Array, jax.Array]:
    """The SHARED per-row preamble of both slot-maintenance forms.

    Against the PRE-batch table, classify every batch row:

    - `mslot` int32[B]: the slot this row's key already occupies (its count
      refreshes to the new CM estimate), or K for rows with no slot;
    - `target` int32[B]: the weakest candidate slot this row CHALLENGES
      (defense = occupant's `max(counts, prev_counts)`: a persistent
      heavy defends with last window's mass right after a roll zeroes
      `counts`, while in decay/keep modes — where `counts` already folds
      history — the max avoids double-counting the same mass twice into
      the defense; invalid slots defend with -1 and fill first), or K
      when the row matched, is dead (est <= 0), or its estimate does not
      beat the defense.

    Everything downstream — the scatter reduction and the Pallas kernel —
    consumes only (mslot, target, est), which is what makes the two forms
    bit-exact by construction."""
    k = table.k
    live = est > 0.0
    cands = slot_candidates(h1, h2, k)                       # [B, WAYS]
    occ_h1 = table.h1[cands]
    occ_h2 = table.h2[cands]
    occ_valid = table.valid[cands]
    match_way = occ_valid & (occ_h1 == h1[:, None]) & (occ_h2 == h2[:, None])
    matched = live & jnp.any(match_way, axis=1)
    # at most one way can match (a key occupies at most one slot); argmax
    # picks the first True way
    mslot = jnp.take_along_axis(
        cands, jnp.argmax(match_way, axis=1)[:, None], axis=1)[:, 0]
    mslot = jnp.where(matched, mslot, k)
    defense = jnp.where(occ_valid,
                        jnp.maximum(table.counts[cands],
                                    table.prev_counts[cands]), -1.0)
    tj = jnp.argmin(defense, axis=1)                         # ties -> low way
    target = jnp.take_along_axis(cands, tj[:, None], axis=1)[:, 0]
    tdef = jnp.take_along_axis(defense, tj[:, None], axis=1)[:, 0]
    challenger = live & ~matched & (est > tdef)
    target = jnp.where(challenger, target, k)
    return mslot, target


def _slot_reduce_scatter(mslot: jax.Array, target: jax.Array, est: jax.Array,
                         k: int) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Un-fused scatter form of the three per-slot reductions (the Pallas
    kernel's equivalence twin — tests/test_pallas_topk.py pins bit-exact):

    - match_max[K]: max estimate among rows whose key occupies the slot;
    - chall_max[K]: max estimate among the slot's challengers;
    - win_row[K]:   LOWEST batch row index achieving chall_max (the
                    deterministic insertion winner; NO_WINNER when none).

    f32 max is order-independent and the winner tie-break is an integer
    min, so the two forms cannot drift."""
    n = est.shape[0]
    rows = jnp.arange(n, dtype=jnp.int32)
    match_max = jnp.full((k,), -1.0, jnp.float32).at[mslot].max(
        est, mode="drop")
    chall_max = jnp.full((k,), -1.0, jnp.float32).at[target].max(
        est, mode="drop")
    tclip = jnp.minimum(target, k - 1)
    # est > -1 keeps the contract total on degenerate inputs: a slot whose
    # only "challengers" are dead rows (never produced by slot_prepare,
    # but the reductions are pinned on arbitrary rows) elects NO winner in
    # both forms (the kernel gates on its chunk max > -1 the same way)
    winner = (target < k) & (est == chall_max[tclip]) & (est > -1.0)
    win_row = jnp.full((k,), NO_WINNER, jnp.int32).at[
        jnp.where(winner, target, k)].min(rows, mode="drop")
    return match_max, chall_max, win_row


def slot_compose(table: SlotTable, match_max: jax.Array, chall_max: jax.Array,
                 win_row: jax.Array, words: jax.Array, h1: jax.Array,
                 h2: jax.Array, window: jax.Array
                 ) -> tuple[SlotTable, jax.Array]:
    """The SHARED tail of both slot-maintenance forms: apply the per-slot
    reductions to the table. Matched slots refresh `counts` (CM estimates
    are monotone within a window, so max == refresh); slots with a winning
    challenger are OVERWRITTEN — identity, `counts` = winner estimate,
    `prev_counts` = 0, `first_seen` = current window, `epoch` + 1 —
    UNLESS the slot's occupant also appeared in this batch and its
    refreshed estimate meets the challenge (challengers were admitted
    against the PRE-batch defense, which right after a roll can be last
    window's mass while the incumbent's live estimate is already higher;
    without this gate a lighter challenger could evict a heavier matched
    incumbent, destroying its churn identity for a key that immediately
    re-inserts as falsely "new"). Returns (new table, number of VALID
    occupants evicted this batch)."""
    has_winner = chall_max > 0.0
    b = h1.shape[0]
    wr = jnp.minimum(win_row, b - 1)  # clamped; masked by has_winner
    counts = jnp.maximum(table.counts, match_max)
    # match_max is -1 for slots with no matched row, so unmatched slots
    # keep the pre-batch admission verdict unchanged
    sel = has_winner & (chall_max > match_max)
    counts = jnp.where(sel, chall_max, counts)
    evicted = jnp.sum((sel & table.valid).astype(jnp.float32))
    return SlotTable(
        words=jnp.where(sel[:, None], words[wr], table.words),
        h1=jnp.where(sel, h1[wr], table.h1),
        h2=jnp.where(sel, h2[wr], table.h2),
        counts=counts,
        prev_counts=jnp.where(sel, 0.0, table.prev_counts),
        first_seen=jnp.where(sel, jnp.broadcast_to(
            jnp.asarray(window, jnp.int32), table.first_seen.shape),
            table.first_seen),
        epoch=table.epoch + sel.astype(jnp.int32),
        valid=table.valid | sel,
    ), evicted


def slot_update(table: SlotTable, cm: countmin.CountMin, words: jax.Array,
                h1: jax.Array, h2: jax.Array, valid: jax.Array,
                query_fn=None, window: jax.Array | int = 0,
                use_pallas: bool = False) -> tuple[SlotTable, jax.Array]:
    """Fold one batch (whose mass is already in `cm`) into the slot table.

    `query_fn(h1, h2) -> est` overrides the plain CM point query
    (owner-sharded sketches). `use_pallas` routes the per-slot reductions
    through the fused batch-walk kernel (`ops/pallas/topk_kernel.py`) —
    bit-exact against the scatter form by the two-form invariant; the
    preamble and compose are literally shared code.

    Returns (new table, f32 count of valid occupants evicted)."""
    if query_fn is None:
        query_fn = lambda a, b: countmin.query(cm, a, b)  # noqa: E731
    est = jnp.where(valid, query_fn(h1, h2), -1.0)
    evicted = jnp.zeros((), jnp.float32)
    for _ in range(SLOT_ROUNDS):
        mslot, target = slot_prepare(table, h1, h2, est)
        if use_pallas:
            from netobserv_tpu.ops.pallas import topk_kernel
            match_max, chall_max, win_row = topk_kernel.reduce(
                mslot, target, est, table.k)
        else:
            match_max, chall_max, win_row = _slot_reduce_scatter(
                mslot, target, est, table.k)
        table, ev = slot_compose(table, match_max, chall_max, win_row,
                                 words, h1, h2, window)
        evicted = evicted + ev
    return table, evicted


def slot_roll(table: SlotTable, carry: float = 0.0) -> SlotTable:
    """Roll the table across a window boundary WITHOUT touching identity:
    `prev_counts` <- this window's final `counts`, `counts` <- counts *
    `carry` (0.0 = reset mode, 1.0 = cumulative/keep mode, a decay factor
    for sliding windows). Words, hashes, `first_seen`, `epoch` and `valid`
    all persist — the tentpole property the churn record rides on."""
    return table._replace(prev_counts=table.counts,
                          counts=table.counts * jnp.float32(carry))


def merge_slot_tables(stacked: SlotTable, cm_merged: countmin.CountMin,
                      k: int, query_fn=None) -> SlotTable:
    """Roll-time reconciliation: merge slot tables stacked along axis 0
    (per-device partials, or aggregate + delta at the federation tier) into
    one size-k table. Counts re-score against the MERGED CM; duplicate
    identities collapse with segmented metadata merges (`prev_counts` SUM —
    per-shard partials of the same key add; `first_seen` MIN; `epoch` MAX).
    Runs only inside window-roll/merge executables, never per batch."""
    if query_fn is None:
        query_fn = lambda a, b: countmin.query(cm_merged, a, b)  # noqa: E731
    est = jnp.where(stacked.valid, query_fn(stacked.h1, stacked.h2), -1.0)
    n = stacked.h1.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    s_h1, s_h2, s_idx = jax.lax.sort((stacked.h1, stacked.h2, idx),
                                     num_keys=2)
    s_est = est[s_idx]
    s_valid = stacked.valid[s_idx]
    first = jnp.concatenate([
        jnp.ones((1,), dtype=jnp.bool_),
        (s_h1[1:] != s_h1[:-1]) | (s_h2[1:] != s_h2[:-1]),
    ])
    seg = jnp.cumsum(first.astype(jnp.int32)) - 1
    prev_sum = jax.ops.segment_sum(
        jnp.where(s_valid, stacked.prev_counts[s_idx], 0.0), seg,
        num_segments=n)
    fs_min = jax.ops.segment_min(
        jnp.where(s_valid, stacked.first_seen[s_idx], jnp.int32(NO_WINNER)),
        seg, num_segments=n)
    ep_max = jax.ops.segment_max(
        jnp.where(s_valid, stacked.epoch[s_idx], 0), seg, num_segments=n)
    s_est = jnp.where(first & s_valid, s_est, -1.0)
    top_est, top_pos = jax.lax.top_k(s_est, k)
    orig = s_idx[top_pos]
    sid = seg[top_pos]
    sel = top_est > 0
    return SlotTable(
        words=jnp.where(sel[:, None], stacked.words[orig], 0),
        h1=jnp.where(sel, s_h1[top_pos], 0),
        h2=jnp.where(sel, s_h2[top_pos], 0),
        counts=jnp.where(sel, top_est, 0.0),
        prev_counts=jnp.where(sel, prev_sum[sid], 0.0),
        first_seen=jnp.where(sel, jnp.minimum(fs_min[sid],
                                              jnp.int32(0x7FFFFFFE)), 0),
        epoch=jnp.where(sel, ep_max[sid], 0),
        valid=sel,
    )
