"""Fixed-K heavy-hitter table maintained entirely on device.

The CM+candidate-set approach (cf. SpaceSaving / "CM + heap" from the sketch
literature, PAPERS.md top-K): after the Count-Min fold, every batch key is a
candidate; candidates and the current table are re-scored by CM point query,
deduplicated with a lexicographic `lax.sort` on their (h1, h2) identity, and the
top K survive via `lax.top_k`. Everything is fixed-shape — no heaps, no dynamic
growth — so it jits and shards cleanly (reference analog being replaced: the Go
map in `pkg/flow/account.go`).

Key identity here is the (h1, h2) 64-bit pair; the full 40-byte key words ride
along through gathers so results can be rendered exactly. A cross-key (h1, h2)
collision is ~2^-64 per pair — negligible at flow scale.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from netobserv_tpu.ops import countmin, hashing


class TopK(NamedTuple):
    words: jax.Array   # uint32[K, W] — packed key material
    h1: jax.Array      # uint32[K]
    h2: jax.Array      # uint32[K]
    counts: jax.Array  # float32[K] — CM-estimated totals, -1 for empty slots
    valid: jax.Array   # bool[K]

    @property
    def k(self) -> int:
        return self.words.shape[0]


def init(k: int = 1024, key_words: int = 10) -> TopK:
    return TopK(
        words=jnp.zeros((k, key_words), dtype=jnp.uint32),
        h1=jnp.zeros((k,), dtype=jnp.uint32),
        h2=jnp.zeros((k,), dtype=jnp.uint32),
        counts=jnp.full((k,), -1.0, dtype=jnp.float32),
        valid=jnp.zeros((k,), dtype=jnp.bool_),
    )


def _select(words, h1, h2, est, k: int) -> TopK:
    """Dedup by (h1, h2) identity and keep the top-k by est (invalid est = -1)."""
    n = h1.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    s_h1, s_h2, s_idx = jax.lax.sort((h1, h2, idx), num_keys=2)
    s_est = est[s_idx]
    first = jnp.concatenate([
        jnp.ones((1,), dtype=jnp.bool_),
        (s_h1[1:] != s_h1[:-1]) | (s_h2[1:] != s_h2[:-1]),
    ])
    s_est = jnp.where(first, s_est, -1.0)
    top_est, top_pos = jax.lax.top_k(s_est, k)
    orig = s_idx[top_pos]
    sel_valid = top_est > 0
    return TopK(
        words=jnp.where(sel_valid[:, None], words[orig], 0),
        h1=jnp.where(sel_valid, s_h1[top_pos], 0),
        h2=jnp.where(sel_valid, s_h2[top_pos], 0),
        counts=jnp.where(sel_valid, top_est, -1.0),
        valid=sel_valid,
    )


_SLOT_BITS = 19  # dedup slot space (2^19 ~ 0.2% residual collision vs K=1024)


def update(table: TopK, cm: countmin.CountMin, words: jax.Array, h1: jax.Array,
           h2: jax.Array, valid: jax.Array, query_fn=None,
           salt: jax.Array | int = 0) -> TopK:
    """Fold one batch (whose mass is already in `cm`) into the table.

    `query_fn(h1, h2) -> est` overrides the plain CM point query (used for
    width-sharded sketches, where the query needs a psum over the sketch axis).

    Dedup strategy: a full lexicographic sort over table+batch is exact but
    dominates ingest cost (~5ms/batch measured). Instead, duplicates are
    collapsed with a scatter-min "slot owner" table over 2^19 slots: every
    live row hashes its full 64-bit key identity (h1 AND h2) plus `salt`
    into a slot, the lowest row index owns it, and only owners are eligible
    for `lax.top_k` selection. Two *distinct* keys sharing a slot suppress
    the higher-indexed one for the CURRENT WINDOW (table rows always outrank
    batch rows); passing the window counter as `salt` reshuffles slots at
    every roll so a colliding pair is re-separated next window. Residual
    loss: ~(K+B)/2^19 ≈ 3% chance a given new key collides with anything in
    one window, ~0.2% with a table key — and never the same pair twice.
    (A naive candidate cut by estimate does NOT work: under skew the top
    rows are duplicates of a few mega-keys and recall collapses — measured.)
    The exact sort-based `_select` remains in use for window merges.
    """
    if query_fn is None:
        query_fn = lambda a, b: countmin.query(cm, a, b)  # noqa: E731
    batch_est = jnp.where(valid, query_fn(h1, h2), -1.0)
    table_est = jnp.where(table.valid,
                          query_fn(table.h1, table.h2), -1.0)
    all_words = jnp.concatenate([table.words, words], axis=0)
    all_h1 = jnp.concatenate([table.h1, h1])
    all_h2 = jnp.concatenate([table.h2, h2])
    all_est = jnp.concatenate([table_est, batch_est])

    n = all_h1.shape[0]
    n_slots = 1 << _SLOT_BITS
    # slot identity covers the full 64-bit key hash (h1 AND h2) plus the salt
    slot = (hashing.fmix32(all_h1 ^ ((all_h2 << 16) | (all_h2 >> 16))
                           ^ jnp.uint32(salt))
            & jnp.uint32(n_slots - 1)).astype(jnp.int32)
    rows = jnp.arange(n, dtype=jnp.int32)
    live = all_est > 0
    owner = jnp.full((n_slots,), n, dtype=jnp.int32)
    # dead rows must not own slots (a stale table slot could otherwise
    # suppress a live key)
    owner = owner.at[jnp.where(live, slot, n_slots - 1)].min(
        jnp.where(live, rows, n), mode="drop")
    is_owner = owner[slot] == rows
    sel_est = jnp.where(is_owner & live, all_est, -1.0)
    top_est, pos = jax.lax.top_k(sel_est, table.k)
    sel_valid = top_est > 0
    return TopK(
        words=jnp.where(sel_valid[:, None], all_words[pos], 0),
        h1=jnp.where(sel_valid, all_h1[pos], 0),
        h2=jnp.where(sel_valid, all_h2[pos], 0),
        counts=jnp.where(sel_valid, top_est, -1.0),
        valid=sel_valid,
    )


def merge_stacked(stacked: TopK, cm_merged: countmin.CountMin, k: int,
                  query_fn=None) -> TopK:
    """Merge per-device tables stacked along axis 0 into one size-k table.

    stacked arrays have shape [n_dev * K, ...]. Counts are re-queried against
    the merged CM so the selection reflects cluster-wide mass (SURVEY.md §5.8:
    "allgather + re-select top-K over ICI")."""
    if query_fn is None:
        query_fn = lambda a, b: countmin.query(cm_merged, a, b)  # noqa: E731
    est = jnp.where(stacked.valid, query_fn(stacked.h1, stacked.h2), -1.0)
    return _select(stacked.words, stacked.h1, stacked.h2, est, k)
