"""Streaming EWMA anomaly / DDoS scoring over hashed destination buckets.

BASELINE.json config 5: "Streaming EWMA anomaly/DDoS score over merged sketches".
Per destination-hash bucket we accumulate the current window's byte/packet rate,
then at each window roll compute a z-score against an exponentially weighted
mean/variance and decay the baselines. Buckets whose z-score exceeds a threshold
are DDoS suspects; the top-K table maps hot buckets back to concrete keys.

State is three float32[m] arrays; the cross-chip merge for `rate` is psum (rates
are additive), baselines are replicated and updated identically on every chip.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class EWMA(NamedTuple):
    mean: jax.Array     # f32[m] — EW mean of per-window rates
    var: jax.Array      # f32[m] — EW variance
    rate: jax.Array     # f32[m] — current-window accumulator
    windows: jax.Array  # i32[] — number of completed windows


def init(buckets: int = 4096) -> EWMA:
    assert buckets & (buckets - 1) == 0
    return EWMA(
        mean=jnp.zeros((buckets,), jnp.float32),
        var=jnp.zeros((buckets,), jnp.float32),
        rate=jnp.zeros((buckets,), jnp.float32),
        windows=jnp.zeros((), jnp.int32),
    )


def accumulate(s: EWMA, dst_h: jax.Array, values: jax.Array,
               valid: jax.Array) -> EWMA:
    """Add one batch's mass into the current window, bucketed by dst hash."""
    m = s.rate.shape[0]
    idx = (dst_h & jnp.uint32(m - 1)).astype(jnp.int32)
    vals = jnp.where(valid, values, 0).astype(jnp.float32)
    return s._replace(rate=s.rate.at[idx].add(vals, mode="drop"))


def roll(s: EWMA, alpha: float = 0.3) -> tuple[EWMA, jax.Array]:
    """Close the window: return (new_state, z_scores[m]) and reset rates.

    Warmup: the first two windows only seed the baseline (scores stay zero).
    The variance floor is proportional to the mean so a bucket with a tiny but
    noisy baseline doesn't alarm on ordinary jitter.
    """
    first = s.windows == 0
    warming = s.windows < 2
    diff = s.rate - s.mean
    floor = (0.05 * s.mean) ** 2 + 1.0
    z = diff / jnp.sqrt(s.var + floor)
    z = jnp.where(warming, 0.0, z)
    new_mean = jnp.where(first, s.rate, (1 - alpha) * s.mean + alpha * s.rate)
    new_var = jnp.where(first, jnp.zeros_like(s.var),
                        (1 - alpha) * (s.var + alpha * diff * diff))
    return EWMA(mean=new_mean, var=new_var,
                rate=jnp.zeros_like(s.rate),
                windows=s.windows + 1), z


def suspects(z: jax.Array, threshold: float = 6.0) -> jax.Array:
    """Boolean mask of anomalous buckets."""
    return z > threshold
