"""HLL register fold as a Pallas kernel: scatter-max -> tiled one-hot max.

Same reformulation as the Count-Min kernel, with max-reduce on the VPU instead
of an MXU contraction: for each 128-lane register tile, every batch chunk
contributes `where(idx == lane, rank, 0)` and the tile takes the running
elementwise max. Cost is B*m lane compares per batch (~2.7e8 at B=16k,
m=16384), trivially within VPU headroom — versus a serialized XLA scatter.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from netobserv_tpu.ops.hll import HLL

TILE_M = 512
CHUNK_B = 2048


def _fold_kernel(regs_ref, idx_ref, rank_ref, out_ref, *, n_chunks: int):
    j = pl.program_id(0)
    lanes = j * TILE_M + jax.lax.broadcasted_iota(jnp.int32, (1, TILE_M), 1)

    def chunk_body(i, acc):
        sl = pl.dslice(i * CHUNK_B, CHUNK_B)
        idx = idx_ref[sl].reshape(CHUNK_B, 1)
        rank = rank_ref[sl].reshape(CHUNK_B, 1)
        contrib = jnp.max(jnp.where(idx == lanes, rank, 0), axis=0)
        return jnp.maximum(acc, contrib)

    acc = regs_ref[0]
    acc = jax.lax.fori_loop(0, n_chunks, chunk_body, acc)
    out_ref[0] = acc


def update(hll: HLL, h1: jax.Array, h2: jax.Array, valid: jax.Array,
           interpret: bool | None = None) -> HLL:
    """Drop-in replacement for hll.update."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    m = hll.regs.shape[0]
    assert m % TILE_M == 0, f"m={m} must be a multiple of {TILE_M}"
    b = h1.shape[0]
    pad = (-b) % CHUNK_B
    if pad:
        h1 = jnp.pad(h1, (0, pad))
        h2 = jnp.pad(h2, (0, pad))
        valid = jnp.pad(valid, (0, pad))
    idx = (h1 & jnp.uint32(m - 1)).astype(jnp.int32)
    rank = jnp.where(valid, jax.lax.clz(h2.astype(jnp.int32)) + 1, 0)
    n_chunks = idx.shape[0] // CHUNK_B

    kernel = functools.partial(_fold_kernel, n_chunks=n_chunks)
    new_regs = pl.pallas_call(
        kernel,
        grid=(m // TILE_M,),
        in_specs=[
            pl.BlockSpec((1, TILE_M), lambda j: (0, j)),
            pl.BlockSpec((idx.shape[0],), lambda j: (0,)),
            pl.BlockSpec((idx.shape[0],), lambda j: (0,)),
        ],
        out_specs=pl.BlockSpec((1, TILE_M), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((1, m), jnp.int32),
        input_output_aliases={0: 0},
        interpret=interpret,
    )(hll.regs.reshape(1, m), idx, rank)
    return HLL(regs=new_regs.reshape(m))
