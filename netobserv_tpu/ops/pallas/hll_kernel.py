"""HLL register fold as a Pallas kernel: scatter-max -> tiled one-hot max.

Same reformulation as the Count-Min kernel, with max-reduce on the VPU instead
of an MXU contraction: for each 128-lane register tile, every batch chunk
contributes `where(idx == lane, rank, 0)` and the tile takes the running
elementwise max. Cost is B*m lane compares per batch (~2.7e8 at B=16k,
m=16384), trivially within VPU headroom — versus a serialized XLA scatter.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from netobserv_tpu.ops.hll import HLL, PerDstHLL, _rank

TILE_M = 512
CHUNK_B = 2048


def _fold_kernel(regs_ref, idx_ref, rank_ref, out_ref, *, n_chunks: int):
    j = pl.program_id(0)
    lanes = j * TILE_M + jax.lax.broadcasted_iota(jnp.int32, (1, TILE_M), 1)

    def chunk_body(i, acc):
        sl = pl.dslice(i * CHUNK_B, CHUNK_B)
        idx = idx_ref[sl].reshape(CHUNK_B, 1)
        rank = rank_ref[sl].reshape(CHUNK_B, 1)
        contrib = jnp.max(jnp.where(idx == lanes, rank, 0), axis=0)
        return jnp.maximum(acc, contrib)

    acc = regs_ref[0]
    acc = jax.lax.fori_loop(0, n_chunks, chunk_body, acc)
    out_ref[0] = acc


def _fold_flat(regs_flat: jax.Array, idx: jax.Array, rank: jax.Array,
               interpret: bool) -> jax.Array:
    """Shared one-hot max fold over a FLAT register array of any
    TILE_M-aligned size (the global HLL and, via bucket*m + reg flat
    indexing, the per-dst/per-src grids)."""
    m = regs_flat.shape[0]
    assert m % TILE_M == 0, f"m={m} must be a multiple of {TILE_M}"
    pad = (-idx.shape[0]) % CHUNK_B
    if pad:
        idx = jnp.pad(idx, (0, pad))
        rank = jnp.pad(rank, (0, pad))
    n_chunks = idx.shape[0] // CHUNK_B

    kernel = functools.partial(_fold_kernel, n_chunks=n_chunks)
    new_regs = pl.pallas_call(
        kernel,
        grid=(m // TILE_M,),
        in_specs=[
            pl.BlockSpec((1, TILE_M), lambda j: (0, j)),
            pl.BlockSpec((idx.shape[0],), lambda j: (0,)),
            pl.BlockSpec((idx.shape[0],), lambda j: (0,)),
        ],
        out_specs=pl.BlockSpec((1, TILE_M), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((1, m), jnp.int32),
        input_output_aliases={0: 0},
        interpret=interpret,
    )(regs_flat.reshape(1, m), idx, rank)
    return new_regs.reshape(m)


def update(hll: HLL, h1: jax.Array, h2: jax.Array, valid: jax.Array,
           interpret: bool | None = None) -> HLL:
    """Drop-in replacement for hll.update."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    m = hll.regs.shape[0]
    idx = (h1 & jnp.uint32(m - 1)).astype(jnp.int32)
    rank = jnp.where(valid, _rank(h2), 0)
    return HLL(regs=_fold_flat(hll.regs, idx, rank, interpret))


def update_per_dst(s, dst_h: jax.Array, src_h1: jax.Array,
                   src_h2: jax.Array, valid: jax.Array,
                   interpret: bool | None = None):
    """Drop-in replacement for hll.update_per_dst: the (bucket, register)
    grid folds as ONE flat register array of D*m lanes (cell index =
    bucket*m + reg). NOTE the roofline before wiring this in: the one-hot
    fold pays D*m lane-compares per RECORD (e.g. 4096x64 = 262K — 16x the
    global HLL's), while the XLA scatter pays O(1) touches per record
    regardless of grid size; benchmarks/ingest_stage_profile.py carries the
    A/B (docs/tpu_sketch.md records the verdict)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    dbuckets, m = s.regs.shape
    di = (dst_h & jnp.uint32(dbuckets - 1)).astype(jnp.int32)
    ri = (src_h1 & jnp.uint32(m - 1)).astype(jnp.int32)
    idx = di * m + ri
    rank = jnp.where(valid, _rank(src_h2), 0)
    flat = _fold_flat(s.regs.reshape(dbuckets * m), idx, rank, interpret)
    return PerDstHLL(regs=flat.reshape(dbuckets, m))
