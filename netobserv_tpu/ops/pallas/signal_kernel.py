"""Fused signal-plane fold as ONE Pallas kernel: eight scatter chains -> one
batch walk.

The un-fused ingest pays a separate serialized XLA scatter/EWMA pass over the
batch for every small signal table (DDoS/SYN/drop EWMA rates, SYN-ACK
responses, conversation fwd/rev, DSCP bytes, drop causes). All of those
tables together are a few tens of KB — they fit VMEM simultaneously — so the
kernel walks the batch ONCE and updates them together, the TPU analog of the
single-pass sketch accelerators (arxiv 2504.16896, 2005.13332).

Formulation: the eight scatter-adds group into five INDEX FAMILIES (victim =
dst bucket, src bucket, conversation pair, DSCP code, drop cause). Per batch
chunk each family builds its one-hot membership matrix once and contracts it
with ALL of its value rows on the MXU:

  - dst family  -> ddos bytes, SYN half-open mass, dropped bytes   (3 rows)
  - src family  -> SYN-ACK responses                               (1 row)
  - pair family -> conversation fwd / rev bytes                    (2 rows)
  - dscp / cause -> one row each over a shared 256-lane aux table

so a record costs ~3m + 512 lane compares (m = EWMA bucket count, 12.8K at
the m=4096 default) plus MXU MACs, replacing eight dependent scatter passes.
The per-dst / per-src HLL GRIDS are deliberately NOT here: their one-hot
fold pays D*2^p (262K) compares per record versus a single scatter touch —
the measured verdict in docs/tpu_sketch.md ("Per-stage ingest attribution").

Same contract as the sibling kernels: `interpret` defaults to True off-TPU
(testable on the CPU mesh), counters donated via input_output_aliases, and
bit-exact equivalence with the scatter chain is pinned by
tests/test_pallas_signal.py (integer-valued f32 masses make the float sums
order-independent).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from netobserv_tpu.ops.pallas import tier_tiles

CHUNK_B = 1024
#: packed-HLL register-triple tile width of the tiered variant's grid
TILE_R = 512
#: shared width of the small-table aux plane (row 0 = DSCP, row 1 = drop
#: causes); both tables must fit (sketch.state N_DSCP=64, N_DROP_CAUSES=128)
AUX_W = 256

#: value-plane row order (main table rows 0..5 match vals rows 0..5)
#: [ddos, syn, drops | synack | conv_fwd, conv_rev] + aux [dscp, cause]
N_MAIN = 6
N_VALS = 8
#: index families: [dst, src, pair, dscp, cause]
N_IDX = 5


class SignalPlanes(NamedTuple):
    """The signal tables the fused kernel updates, as plain arrays."""

    ddos_rate: jax.Array   # f32[m]
    syn_rate: jax.Array    # f32[m]
    drops_rate: jax.Array  # f32[m]
    synack: jax.Array      # f32[m]
    conv_fwd: jax.Array    # f32[m]
    conv_rev: jax.Array    # f32[m]
    dscp_bytes: jax.Array  # f32[n_dscp]  (n_dscp <= AUX_W)
    drop_causes: jax.Array  # f32[n_causes] (n_causes <= AUX_W)


def _signal_fold_body(main_ref, aux_ref, idx_ref, vals_ref, main_out,
                      aux_out, *, n_chunks: int, m: int):
    """The five-family one-hot fold shared by :func:`_fold_kernel` and the
    tiered variant (one body — the two kernels cannot drift)."""
    lanes_m = jax.lax.broadcasted_iota(jnp.int32, (1, m), 1)
    lanes_a = jax.lax.broadcasted_iota(jnp.int32, (1, AUX_W), 1)

    def chunk_body(i, acc):
        acc_main, acc_aux = acc
        sl = pl.dslice(i * CHUNK_B, CHUNK_B)
        vals = vals_ref[:, sl]                                  # [8, C]

        def onehot(fam, lanes):
            idx = idx_ref[fam, sl].reshape(CHUNK_B, 1)
            return (idx == lanes).astype(jnp.float32)           # [C, W]

        # one one-hot build per index family, shared by its value rows
        c_dst = jnp.dot(vals[0:3], onehot(0, lanes_m),
                        preferred_element_type=jnp.float32)     # [3, m]
        c_src = jnp.dot(vals[3:4], onehot(1, lanes_m),
                        preferred_element_type=jnp.float32)     # [1, m]
        c_pair = jnp.dot(vals[4:6], onehot(2, lanes_m),
                         preferred_element_type=jnp.float32)    # [2, m]
        c_dscp = jnp.dot(vals[6:7], onehot(3, lanes_a),
                         preferred_element_type=jnp.float32)    # [1, AUX_W]
        c_cause = jnp.dot(vals[7:8], onehot(4, lanes_a),
                          preferred_element_type=jnp.float32)   # [1, AUX_W]
        new_main = acc_main + jnp.concatenate([c_dst, c_src, c_pair], axis=0)
        new_aux = acc_aux + jnp.concatenate([c_dscp, c_cause], axis=0)
        return new_main, new_aux

    acc = jax.lax.fori_loop(0, n_chunks, chunk_body,
                            (main_ref[...], aux_ref[...]))
    main_out[...] = acc[0]
    aux_out[...] = acc[1]


def _fold_kernel(main_ref, aux_ref, idx_ref, vals_ref, main_out, aux_out, *,
                 n_chunks: int, m: int):
    _signal_fold_body(main_ref, aux_ref, idx_ref, vals_ref, main_out,
                      aux_out, n_chunks=n_chunks, m=m)


def _fold_tiered_kernel(main_ref, aux_ref, pk3_ref, idx_ref, vals_ref,
                        hidx_ref, hrank_ref, main_out, aux_out, pk3_out, *,
                        n_chunks: int, m: int, tile_r: int):
    """Tiered megakernel: the signal fold plus the packed global-src HLL
    lane in one walk. The grid tiles the packed register triples; the
    signal tables ride constant-index blocks (revisited across grid steps,
    so their fold runs once, on the first step). The HLL registers stay
    6-bit packed in HBM — unpack/max/pack all happen on the VMEM tile."""
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _signal():
        _signal_fold_body(main_ref, aux_ref, idx_ref, vals_ref, main_out,
                          aux_out, n_chunks=n_chunks, m=m)

    # registers 4t + r for the packed triples t of this tile
    rows = tuple(tier_tiles.unpack_reg_rows(pk3_ref[...]))
    t_lanes = j * tile_r + jax.lax.broadcasted_iota(
        jnp.int32, (1, tile_r), 1)

    def hll_body(i, carry):
        sl = pl.dslice(i * CHUNK_B, CHUNK_B)
        hidx = hidx_ref[sl].reshape(CHUNK_B, 1)
        hrank = hrank_ref[sl].reshape(CHUNK_B, 1)
        new = []
        for r in range(4):  # static unroll over the 4 regs per triple
            hit = ((hidx >> 2) == t_lanes) & ((hidx & 3) == r)
            contrib = jnp.max(jnp.where(hit, hrank, 0), axis=0,
                              keepdims=True)
            new.append(jnp.maximum(carry[r], contrib))
        return tuple(new)

    rows = jax.lax.fori_loop(0, n_chunks, hll_body, rows)
    pk3_out[...] = tier_tiles.pack_reg_rows(list(rows))


def eligible(planes: SignalPlanes) -> bool:
    """Static shape gate: the six m-wide planes must share one power-of-two,
    lane-aligned width and the aux tables must fit the shared aux plane."""
    m = planes.ddos_rate.shape[0]
    return (all(p.shape == (m,) for p in
                (planes.syn_rate, planes.drops_rate, planes.synack,
                 planes.conv_fwd, planes.conv_rev))
            and m % 128 == 0
            and planes.dscp_bytes.shape[0] <= AUX_W
            and planes.drop_causes.shape[0] <= AUX_W)


def update(planes: SignalPlanes, idx: jax.Array, vals: jax.Array,
           interpret: bool | None = None) -> SignalPlanes:
    """Fold one batch into every signal plane in one pass.

    idx:  i32[5, B] — [dst_bucket, src_bucket, pair_bucket, dscp, cause],
          each already masked into its table's range.
    vals: f32[8, B] — [ddos, syn, drops, synack, conv_fwd, conv_rev, dscp,
          cause] masses, already validity/signal-masked (0 = no-op).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    assert eligible(planes), "signal planes ineligible for the fused kernel"
    m = planes.ddos_rate.shape[0]
    b = idx.shape[1]
    assert vals.shape == (N_VALS, b) and idx.shape == (N_IDX, b)
    pad = (-b) % CHUNK_B
    if pad:  # zero mass adds nothing — the padded tail is a no-op
        idx = jnp.pad(idx, ((0, 0), (0, pad)))
        vals = jnp.pad(vals, ((0, 0), (0, pad)))
    n_chunks = idx.shape[1] // CHUNK_B

    main = jnp.stack([planes.ddos_rate, planes.syn_rate, planes.drops_rate,
                      planes.synack, planes.conv_fwd, planes.conv_rev])
    n_dscp = planes.dscp_bytes.shape[0]
    n_causes = planes.drop_causes.shape[0]
    aux = jnp.zeros((2, AUX_W), jnp.float32)
    aux = aux.at[0, :n_dscp].set(planes.dscp_bytes)
    aux = aux.at[1, :n_causes].set(planes.drop_causes)

    kernel = functools.partial(_fold_kernel, n_chunks=n_chunks, m=m)
    new_main, new_aux = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((N_MAIN, m), jnp.float32),
                   jax.ShapeDtypeStruct((2, AUX_W), jnp.float32)),
        input_output_aliases={0: 0, 1: 1},
        interpret=interpret,
    )(main, aux, idx.astype(jnp.int32), vals.astype(jnp.float32))
    return SignalPlanes(
        ddos_rate=new_main[0], syn_rate=new_main[1], drops_rate=new_main[2],
        synack=new_main[3], conv_fwd=new_main[4], conv_rev=new_main[5],
        dscp_bytes=new_aux[0, :n_dscp], drop_causes=new_aux[1, :n_causes])


def hll_fusible(m: int) -> bool:
    """Static gate for folding the packed global-src HLL bank into the
    tiered megakernel: the register-triple axis must tile evenly."""
    n3 = m // 4
    return m % 4 == 0 and n3 > 0 and (n3 <= TILE_R or n3 % TILE_R == 0)


def update_tiered(planes: SignalPlanes, packed: jax.Array, idx: jax.Array,
                  vals: jax.Array, hll_idx: jax.Array, hll_rank: jax.Array,
                  interpret: bool | None = None
                  ) -> tuple[SignalPlanes, jax.Array]:
    """Tiered twin of :func:`update`: the same signal fold PLUS the
    6-bit-packed global-src HLL bank folded in the same walk, without ever
    unpacking it to wide i32 registers in HBM.

    packed:   u8[m//4*3] — tiered.pack_hll layout.
    hll_idx:  i32[B] — register index per record (``h1 & (m-1)``).
    hll_rank: i32[B] — rank per record, 0 for invalid (max no-op).
    Returns (new planes, new packed bank) — the max fold is
    order-independent, so the lane is bit-exact vs unpack->update->pack.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    assert eligible(planes), "signal planes ineligible for the fused kernel"
    n = packed.shape[0]
    n3 = n // 3
    m_hll = n3 * 4
    assert n % 3 == 0 and hll_fusible(m_hll), \
        f"packed HLL bank of {n} bytes ineligible for the tiered kernel"
    m = planes.ddos_rate.shape[0]
    b = idx.shape[1]
    assert vals.shape == (N_VALS, b) and idx.shape == (N_IDX, b)
    assert hll_idx.shape == (b,) and hll_rank.shape == (b,)
    pad = (-b) % CHUNK_B
    if pad:  # zero mass / rank-0 tails are no-ops under add / max
        idx = jnp.pad(idx, ((0, 0), (0, pad)))
        vals = jnp.pad(vals, ((0, 0), (0, pad)))
        hll_idx = jnp.pad(hll_idx, (0, pad))
        hll_rank = jnp.pad(hll_rank, (0, pad))
    n_chunks = idx.shape[1] // CHUNK_B
    tile_r = min(TILE_R, n3)

    main = jnp.stack([planes.ddos_rate, planes.syn_rate, planes.drops_rate,
                      planes.synack, planes.conv_fwd, planes.conv_rev])
    n_dscp = planes.dscp_bytes.shape[0]
    n_causes = planes.drop_causes.shape[0]
    aux = jnp.zeros((2, AUX_W), jnp.float32)
    aux = aux.at[0, :n_dscp].set(planes.dscp_bytes)
    aux = aux.at[1, :n_causes].set(planes.drop_causes)
    # kernel-facing byte-row layout: byte j of triple t at [j, t] (the
    # reshape/transpose runs in XLA on the small u8 array, not in-kernel)
    pk3 = packed.reshape(n3, 3).T

    kernel = functools.partial(_fold_tiered_kernel, n_chunks=n_chunks, m=m,
                               tile_r=tile_r)
    new_main, new_aux, new_pk3 = pl.pallas_call(
        kernel,
        grid=(n3 // tile_r,),
        in_specs=[
            pl.BlockSpec((N_MAIN, m), lambda j: (0, 0)),
            pl.BlockSpec((2, AUX_W), lambda j: (0, 0)),
            pl.BlockSpec((3, tile_r), lambda j: (0, j)),
            pl.BlockSpec((N_IDX, idx.shape[1]), lambda j: (0, 0)),
            pl.BlockSpec((N_VALS, idx.shape[1]), lambda j: (0, 0)),
            pl.BlockSpec((idx.shape[1],), lambda j: (0,)),
            pl.BlockSpec((idx.shape[1],), lambda j: (0,)),
        ],
        out_specs=(
            pl.BlockSpec((N_MAIN, m), lambda j: (0, 0)),
            pl.BlockSpec((2, AUX_W), lambda j: (0, 0)),
            pl.BlockSpec((3, tile_r), lambda j: (0, j)),
        ),
        out_shape=(jax.ShapeDtypeStruct((N_MAIN, m), jnp.float32),
                   jax.ShapeDtypeStruct((2, AUX_W), jnp.float32),
                   jax.ShapeDtypeStruct((3, n3), jnp.uint8)),
        input_output_aliases={0: 0, 1: 1, 2: 2},
        interpret=interpret,
    )(main, aux, pk3, idx.astype(jnp.int32), vals.astype(jnp.float32),
      hll_idx.astype(jnp.int32), hll_rank.astype(jnp.int32))
    return (SignalPlanes(
        ddos_rate=new_main[0], syn_rate=new_main[1], drops_rate=new_main[2],
        synack=new_main[3], conv_fwd=new_main[4], conv_rev=new_main[5],
        dscp_bytes=new_aux[0, :n_dscp], drop_causes=new_aux[1, :n_causes]),
        new_pk3.T.reshape(n))
