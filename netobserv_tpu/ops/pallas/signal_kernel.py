"""Fused signal-plane fold as ONE Pallas kernel: eight scatter chains -> one
batch walk.

The un-fused ingest pays a separate serialized XLA scatter/EWMA pass over the
batch for every small signal table (DDoS/SYN/drop EWMA rates, SYN-ACK
responses, conversation fwd/rev, DSCP bytes, drop causes). All of those
tables together are a few tens of KB — they fit VMEM simultaneously — so the
kernel walks the batch ONCE and updates them together, the TPU analog of the
single-pass sketch accelerators (arxiv 2504.16896, 2005.13332).

Formulation: the eight scatter-adds group into five INDEX FAMILIES (victim =
dst bucket, src bucket, conversation pair, DSCP code, drop cause). Per batch
chunk each family builds its one-hot membership matrix once and contracts it
with ALL of its value rows on the MXU:

  - dst family  -> ddos bytes, SYN half-open mass, dropped bytes   (3 rows)
  - src family  -> SYN-ACK responses                               (1 row)
  - pair family -> conversation fwd / rev bytes                    (2 rows)
  - dscp / cause -> one row each over a shared 256-lane aux table

so a record costs ~3m + 512 lane compares (m = EWMA bucket count, 12.8K at
the m=4096 default) plus MXU MACs, replacing eight dependent scatter passes.
The per-dst / per-src HLL GRIDS are deliberately NOT here: their one-hot
fold pays D*2^p (262K) compares per record versus a single scatter touch —
the measured verdict in docs/tpu_sketch.md ("Per-stage ingest attribution").

Same contract as the sibling kernels: `interpret` defaults to True off-TPU
(testable on the CPU mesh), counters donated via input_output_aliases, and
bit-exact equivalence with the scatter chain is pinned by
tests/test_pallas_signal.py (integer-valued f32 masses make the float sums
order-independent).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

CHUNK_B = 1024
#: shared width of the small-table aux plane (row 0 = DSCP, row 1 = drop
#: causes); both tables must fit (sketch.state N_DSCP=64, N_DROP_CAUSES=128)
AUX_W = 256

#: value-plane row order (main table rows 0..5 match vals rows 0..5)
#: [ddos, syn, drops | synack | conv_fwd, conv_rev] + aux [dscp, cause]
N_MAIN = 6
N_VALS = 8
#: index families: [dst, src, pair, dscp, cause]
N_IDX = 5


class SignalPlanes(NamedTuple):
    """The signal tables the fused kernel updates, as plain arrays."""

    ddos_rate: jax.Array   # f32[m]
    syn_rate: jax.Array    # f32[m]
    drops_rate: jax.Array  # f32[m]
    synack: jax.Array      # f32[m]
    conv_fwd: jax.Array    # f32[m]
    conv_rev: jax.Array    # f32[m]
    dscp_bytes: jax.Array  # f32[n_dscp]  (n_dscp <= AUX_W)
    drop_causes: jax.Array  # f32[n_causes] (n_causes <= AUX_W)


def _fold_kernel(main_ref, aux_ref, idx_ref, vals_ref, main_out, aux_out, *,
                 n_chunks: int, m: int):
    lanes_m = jax.lax.broadcasted_iota(jnp.int32, (1, m), 1)
    lanes_a = jax.lax.broadcasted_iota(jnp.int32, (1, AUX_W), 1)

    def chunk_body(i, acc):
        acc_main, acc_aux = acc
        sl = pl.dslice(i * CHUNK_B, CHUNK_B)
        vals = vals_ref[:, sl]                                  # [8, C]

        def onehot(fam, lanes):
            idx = idx_ref[fam, sl].reshape(CHUNK_B, 1)
            return (idx == lanes).astype(jnp.float32)           # [C, W]

        # one one-hot build per index family, shared by its value rows
        c_dst = jnp.dot(vals[0:3], onehot(0, lanes_m),
                        preferred_element_type=jnp.float32)     # [3, m]
        c_src = jnp.dot(vals[3:4], onehot(1, lanes_m),
                        preferred_element_type=jnp.float32)     # [1, m]
        c_pair = jnp.dot(vals[4:6], onehot(2, lanes_m),
                         preferred_element_type=jnp.float32)    # [2, m]
        c_dscp = jnp.dot(vals[6:7], onehot(3, lanes_a),
                         preferred_element_type=jnp.float32)    # [1, AUX_W]
        c_cause = jnp.dot(vals[7:8], onehot(4, lanes_a),
                          preferred_element_type=jnp.float32)   # [1, AUX_W]
        new_main = acc_main + jnp.concatenate([c_dst, c_src, c_pair], axis=0)
        new_aux = acc_aux + jnp.concatenate([c_dscp, c_cause], axis=0)
        return new_main, new_aux

    acc = jax.lax.fori_loop(0, n_chunks, chunk_body,
                            (main_ref[...], aux_ref[...]))
    main_out[...] = acc[0]
    aux_out[...] = acc[1]


def eligible(planes: SignalPlanes) -> bool:
    """Static shape gate: the six m-wide planes must share one power-of-two,
    lane-aligned width and the aux tables must fit the shared aux plane."""
    m = planes.ddos_rate.shape[0]
    return (all(p.shape == (m,) for p in
                (planes.syn_rate, planes.drops_rate, planes.synack,
                 planes.conv_fwd, planes.conv_rev))
            and m % 128 == 0
            and planes.dscp_bytes.shape[0] <= AUX_W
            and planes.drop_causes.shape[0] <= AUX_W)


def update(planes: SignalPlanes, idx: jax.Array, vals: jax.Array,
           interpret: bool | None = None) -> SignalPlanes:
    """Fold one batch into every signal plane in one pass.

    idx:  i32[5, B] — [dst_bucket, src_bucket, pair_bucket, dscp, cause],
          each already masked into its table's range.
    vals: f32[8, B] — [ddos, syn, drops, synack, conv_fwd, conv_rev, dscp,
          cause] masses, already validity/signal-masked (0 = no-op).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    assert eligible(planes), "signal planes ineligible for the fused kernel"
    m = planes.ddos_rate.shape[0]
    b = idx.shape[1]
    assert vals.shape == (N_VALS, b) and idx.shape == (N_IDX, b)
    pad = (-b) % CHUNK_B
    if pad:  # zero mass adds nothing — the padded tail is a no-op
        idx = jnp.pad(idx, ((0, 0), (0, pad)))
        vals = jnp.pad(vals, ((0, 0), (0, pad)))
    n_chunks = idx.shape[1] // CHUNK_B

    main = jnp.stack([planes.ddos_rate, planes.syn_rate, planes.drops_rate,
                      planes.synack, planes.conv_fwd, planes.conv_rev])
    n_dscp = planes.dscp_bytes.shape[0]
    n_causes = planes.drop_causes.shape[0]
    aux = jnp.zeros((2, AUX_W), jnp.float32)
    aux = aux.at[0, :n_dscp].set(planes.dscp_bytes)
    aux = aux.at[1, :n_causes].set(planes.drop_causes)

    kernel = functools.partial(_fold_kernel, n_chunks=n_chunks, m=m)
    new_main, new_aux = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((N_MAIN, m), jnp.float32),
                   jax.ShapeDtypeStruct((2, AUX_W), jnp.float32)),
        input_output_aliases={0: 0, 1: 1},
        interpret=interpret,
    )(main, aux, idx.astype(jnp.int32), vals.astype(jnp.float32))
    return SignalPlanes(
        ddos_rate=new_main[0], syn_rate=new_main[1], drops_rate=new_main[2],
        synack=new_main[3], conv_fwd=new_main[4], conv_rev=new_main[5],
        dscp_bytes=new_aux[0, :n_dscp], drop_causes=new_aux[1, :n_causes])
