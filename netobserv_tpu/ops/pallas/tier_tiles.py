"""Shared tier-tile lanes for the tier-interior Pallas walks.

The tiered counter planes (sketch/tiered.py) keep the resident Count-Min
tables as a u8 base plane plus direct-mapped u16 MID / u32 TOP overflow
groups, and the HLL banks as 6-bit-packed u8 bytes. The decode-wrapped fold
streams a full-width f32 temporary through HBM; the tier-interior kernels
(`countmin_kernel.update_two_tiered`, `signal_kernel.update_tiered`) instead
load the NARROW tier tiles into VMEM, decode/fold/promote in registers, and
store narrow tiles back — the wide array never exists in HBM.

This module owns the tile load/promote/store lanes so the two kernels
cannot drift from each other:

- :func:`decode_tile` / :func:`promote_tile` — the in-VMEM twins of
  ``tiered.decode_plane`` / ``tiered.plane_add`` (op-for-op: the same
  ``ceil``-to-unit overestimate-only rounding, the same saturation
  cascade, the same u32 integer sat-add at the TOP tier). Group
  expand/sum ride iota-built one-hot matrices on the MXU instead of
  reshapes (Mosaic-friendly; expand is an exact gather, group sums are
  exact for the integer-valued-f32 < 2^24 regime every equivalence pin
  in this repo already relies on).
- :func:`unpack_reg_rows` / :func:`pack_reg_rows` — the in-VMEM twins of
  ``tiered.unpack_hll`` / ``tiered.pack_hll`` over the kernel-facing
  ``[3, m//4]`` byte-row layout (byte j of packed triple t lives at
  ``[j, t]``; register ``4t + r`` is row ``r`` of the 6-bit expansion).
  Lossless both ways — ranks are <= 33.

The tier constants are duplicated here by value (ops must not import the
sketch package — layering); tests/test_tiered.py pins them against
sketch/tiered.py so the two definitions cannot drift either.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

#: value twins of sketch/tiered.py BASE_MAX / MID_MAX / TOP_MAX —
#: equality pinned by tests/test_tiered.py (one-truth guard)
BASE_MAX = 255
MID_MAX = 65535
TOP_MAX = 1 << 30


# --------------------------------------------------------------------------
# iota-built group matrices (expand = exact one-hot gather; group-sum =
# one-hot MXU contraction)
# --------------------------------------------------------------------------

def expand_matrix(n: int, g: int) -> jax.Array:
    """f32 ``[n//g, n]`` with ``E[t, k] = 1.0`` iff ``k // g == t`` —
    ``x[d, n//g] @ E`` broadcasts each group cell over its g columns
    (exactly ``tiered._expand``; one 1.0 per column, so the contraction
    is an exact gather)."""
    rows = jax.lax.broadcasted_iota(jnp.int32, (n // g, n), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (n // g, n), 1)
    return (cols // g == rows).astype(jnp.float32)


def groupsum_matrix(n: int, g: int) -> jax.Array:
    """f32 ``[n, n//g]`` with ``G[k, t] = 1.0`` iff ``k // g == t`` —
    ``y[d, n] @ G`` sums each g-column group (``tiered._group_sum``)."""
    rows = jax.lax.broadcasted_iota(jnp.int32, (n, n // g), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (n, n // g), 1)
    return (rows // g == cols).astype(jnp.float32)


# --------------------------------------------------------------------------
# Count-Min tier tiles (decode / promote), one [d, TILE] slab per plane
# --------------------------------------------------------------------------

def decode_tile(base_i: jax.Array, mid_i: jax.Array, top_u: jax.Array,
                em: jax.Array, et: jax.Array, unit: int) -> jax.Array:
    """Wide f32 view of one tier tile — ``tiered.decode_plane`` op-for-op
    (same casts, same masked adds, so the f32 rounding on a large TOP cell
    is bit-identical to the decode-wrapped form's).

    base_i/mid_i: i32 tiles (cast from u8/u16 by the caller — compares
    happen in 32-bit lanes); top_u: the resident u32 tile. ``em`` expands
    mid cells over their columns (``expand_matrix(T, mid_group)``), ``et``
    expands top cells over their mid cells."""
    mid_f = mid_i.astype(jnp.float32)
    top_per_mid = jnp.dot(top_u.astype(jnp.float32), et,
                          preferred_element_type=jnp.float32)
    mid_tot = mid_f + jnp.where(mid_i == MID_MAX, top_per_mid, 0.0)
    per_col = jnp.dot(mid_tot, em, preferred_element_type=jnp.float32)
    units = base_i.astype(jnp.float32) + jnp.where(
        base_i == BASE_MAX, per_col, 0.0)
    return units * unit if unit > 1 else units


def promote_tile(base_i: jax.Array, mid_i: jax.Array, top_u: jax.Array,
                 dec: jax.Array, new: jax.Array, gm: jax.Array,
                 gt: jax.Array, unit: int
                 ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Saturation promotion inside the walk — ``tiered.plane_add`` with
    ``delta = new - dec`` (the exact per-counter fold delta, untouched
    counters contribute 0), as masked in-place tier writes. Every rounding
    step goes UP (ceil to the unit, top-tier u32 integer sat-add) —
    overestimate-only, the one error direction tiers allow.

    ``gm``/``gt`` are the column->mid / mid->top group-sum matrices.
    Returns the new (u8 base, u16 mid, u32 top) tiles."""
    du = jnp.ceil(jnp.maximum(new - dec, 0.0) / unit)
    s = base_i.astype(jnp.float32) + du
    new_base = jnp.minimum(s, float(BASE_MAX))
    s2 = mid_i.astype(jnp.float32) + jnp.dot(
        s - new_base, gm, preferred_element_type=jnp.float32)
    new_mid = jnp.minimum(s2, float(MID_MAX))
    spill = jnp.dot(s2 - new_mid, gt, preferred_element_type=jnp.float32)
    # clamp BEFORE the u32 cast, then sat-add against the remaining room —
    # tiered._spill verbatim (f32 at the top would round small spills away
    # past 2^24 units: an undercount)
    inc = jnp.minimum(spill, float(TOP_MAX)).astype(jnp.uint32)
    room = jnp.uint32(TOP_MAX) - top_u
    new_top = top_u + jnp.minimum(inc, room)
    return (new_base.astype(jnp.uint8), new_mid.astype(jnp.uint16), new_top)


# --------------------------------------------------------------------------
# packed-HLL tiles (6-bit registers, 4 per 3 bytes, byte-row layout)
# --------------------------------------------------------------------------

def unpack_reg_rows(pk3: jax.Array) -> list[jax.Array]:
    """u8 ``[3, T]`` byte-row tile -> four i32 ``[1, T]`` register rows
    (row r holds register ``4t + r`` of packed triple t) — the in-VMEM
    twin of ``tiered.unpack_hll`` over the transposed layout the wrapper
    ships (elementwise bit ops only; no reshape inside the kernel)."""
    b = pk3.astype(jnp.int32)
    v = b[0:1, :] | (b[1:2, :] << 8) | (b[2:3, :] << 16)
    return [(v >> (6 * r)) & 63 for r in range(4)]


def pack_reg_rows(rows: list[jax.Array]) -> jax.Array:
    """Inverse of :func:`unpack_reg_rows`: four i32 ``[1, T]`` register
    rows -> u8 ``[3, T]`` byte rows. Lossless (ranks <= 33 fit 6 bits)."""
    v = rows[0] | (rows[1] << 6) | (rows[2] << 12) | (rows[3] << 18)
    return jnp.concatenate(
        [v & 0xFF, (v >> 8) & 0xFF, (v >> 16) & 0xFF],
        axis=0).astype(jnp.uint8)
