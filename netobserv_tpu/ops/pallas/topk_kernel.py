"""Persistent-slot top-K maintenance as ONE Pallas batch walk.

The un-fused form of the slot plane (`ops.topk._slot_reduce_scatter`) pays
three serialized XLA scatter passes over the batch — match refresh, challenge
max, winner-row min. The whole slot table is K lanes (K=1024 default — a few
KB), so the kernel keeps all three per-slot accumulators in VMEM and walks
the batch ONCE, the same single-pass formulation as the sibling megakernels
(`countmin_kernel.py`, `signal_kernel.py`; cf. the streaming top-K
accelerator line, PAPERS.md arxiv 2505.*/2005.13332: candidate tracking in
the update path, not a post-pass).

Contract (the two-form invariant): this kernel consumes exactly the
`(mslot, target, est)` row classification `ops.topk.slot_prepare` produces
and returns exactly the three reductions `ops.topk.slot_compose` consumes —
bit-exact against the scatter twin (f32 max is order-independent; the
winner tie-break is an integer min), pinned by tests/test_pallas_topk.py.
`interpret` defaults to True off-TPU so the CPU mesh can execute it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from netobserv_tpu.ops.topk import NO_WINNER

#: batch chunk per VMEM walk step — [CHUNK_B, K] intermediates at the
#: default K=1024 are 1 MiB, comfortably inside VMEM next to the three
#: K-lane accumulators
CHUNK_B = 256


def _reduce_kernel(mslot_ref, target_ref, est_ref, match_out, chall_out,
                   row_out, *, n_chunks: int, k: int):
    lanes = jax.lax.broadcasted_iota(jnp.int32, (1, k), 1)

    def chunk_body(i, acc):
        m_max, c_max, c_row = acc
        sl = pl.dslice(i * CHUNK_B, CHUNK_B)
        est = est_ref[0, sl].reshape(CHUNK_B, 1)
        # --- match refresh: max est among rows occupying each slot ---
        m_mask = mslot_ref[0, sl].reshape(CHUNK_B, 1) == lanes   # [C, K]
        m_est = jnp.where(m_mask, est, -1.0)
        m_max = jnp.maximum(m_max, jnp.max(m_est, axis=0, keepdims=True))
        # --- challenge: max est among each slot's challengers, and the
        # LOWEST row index achieving that max (the deterministic winner);
        # the (max, min-row-at-max) pair combines associatively across
        # chunks, so one walk matches the scatter form bit-exact ---
        t_mask = target_ref[0, sl].reshape(CHUNK_B, 1) == lanes  # [C, K]
        t_est = jnp.where(t_mask, est, -1.0)
        k_max = jnp.max(t_est, axis=0, keepdims=True)            # [1, K]
        rows = (i * CHUNK_B
                + jax.lax.broadcasted_iota(jnp.int32, (CHUNK_B, 1), 0))
        at_max = t_mask & (t_est == k_max) & (k_max > -1.0)
        k_row = jnp.min(jnp.where(at_max, rows, NO_WINNER), axis=0,
                        keepdims=True)
        better = k_max > c_max
        tied = k_max == c_max
        c_row = jnp.where(better, k_row,
                          jnp.where(tied, jnp.minimum(c_row, k_row), c_row))
        c_max = jnp.maximum(c_max, k_max)
        return m_max, c_max, c_row

    init = (jnp.full((1, k), -1.0, jnp.float32),
            jnp.full((1, k), -1.0, jnp.float32),
            jnp.full((1, k), NO_WINNER, jnp.int32))
    m_max, c_max, c_row = jax.lax.fori_loop(0, n_chunks, chunk_body, init)
    match_out[...] = m_max
    chall_out[...] = c_max
    row_out[...] = c_row


def eligible(k: int) -> bool:
    """Static shape gate: the slot count must be lane-aligned (the three
    accumulators live as [1, K] VMEM rows)."""
    return k % 128 == 0


def reduce(mslot: jax.Array, target: jax.Array, est: jax.Array, k: int,
           interpret: bool | None = None
           ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """The three per-slot reductions of one batch in one walk.

    mslot/target: int32[B] slot ids (k = inactive row, per slot_prepare);
    est: f32[B] CM estimates (-1 dead). Returns (match_max[K] f32,
    chall_max[K] f32, win_row[K] i32 — NO_WINNER where no challenger)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    assert eligible(k), f"slot count {k} is not lane-aligned"
    b = mslot.shape[0]
    pad = (-b) % CHUNK_B
    if pad:
        # padded rows target slot k (inactive) with dead estimates — the
        # lane compares never match them, exactly like the scatter drop
        mslot = jnp.pad(mslot, (0, pad), constant_values=k)
        target = jnp.pad(target, (0, pad), constant_values=k)
        est = jnp.pad(est, (0, pad), constant_values=-1.0)
    n_chunks = mslot.shape[0] // CHUNK_B

    kernel = functools.partial(_reduce_kernel, n_chunks=n_chunks, k=k)
    match_max, chall_max, win_row = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((1, k), jnp.float32),
                   jax.ShapeDtypeStruct((1, k), jnp.float32),
                   jax.ShapeDtypeStruct((1, k), jnp.int32)),
        interpret=interpret,
    )(mslot.astype(jnp.int32).reshape(1, -1),
      target.astype(jnp.int32).reshape(1, -1),
      est.astype(jnp.float32).reshape(1, -1))
    return match_max[0], chall_max[0], win_row[0]
