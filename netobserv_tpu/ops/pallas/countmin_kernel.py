"""Count-Min fold as a Pallas kernel: scatter-add -> tiled one-hot matmul.

For each width tile of TILE_W counters, the kernel walks the batch in chunks,
builds the one-hot membership matrix (chunk x TILE_W) in VMEM, and contracts
it with the value vector on the MXU — so the per-batch cost is a dense
d * B * W multiply-accumulate instead of B random HBM touches. FLOPs at the
default config (d=4, B=8192, W=65536): ~4.3 GFLOP/batch, well under a chip's
headroom at the target ingest rate.

The counters are donated (input_output_aliases) so the fold is in-place in
HBM. Falls back transparently: callers use `countmin.update` unless
SKETCH_USE_PALLAS is set.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from netobserv_tpu.ops import hashing
from netobserv_tpu.ops.countmin import CountMin
from netobserv_tpu.ops.pallas import tier_tiles

TILE_W = 512
CHUNK_B = 1024


def _fold_kernel(counts_ref, idx_ref, vals_ref, out_ref, *, depth: int,
                 n_chunks: int):
    j = pl.program_id(0)
    base = j * TILE_W
    lanes = base + jax.lax.broadcasted_iota(jnp.int32, (1, TILE_W), 1)

    def chunk_body(i, acc):
        sl = pl.dslice(i * CHUNK_B, CHUNK_B)
        vals = vals_ref[sl].reshape(1, CHUNK_B)
        new_rows = []
        for r in range(depth):  # static unroll over sketch depth
            idx = idx_ref[r, sl].reshape(CHUNK_B, 1)
            onehot = (idx == lanes).astype(jnp.float32)  # [CHUNK_B, TILE_W]
            contrib = jnp.dot(vals, onehot,
                              preferred_element_type=jnp.float32)
            new_rows.append(acc[r] + contrib[0])
        return jnp.stack(new_rows)

    acc = counts_ref[...]
    acc = jax.lax.fori_loop(0, n_chunks, chunk_body, acc)
    out_ref[...] = acc


def _fold2_kernel(counts_ref, idx_ref, vals_ref, out_ref, *, depth: int,
                  n_chunks: int):
    """Fused dual-plane fold: the one-hot membership matrix is built ONCE
    per (depth row, chunk) and contracted with BOTH value planes stacked as
    a (2, CHUNK_B) LHS — halving the dominant VPU compare cost vs two
    single-plane passes and doubling MXU row utilization."""
    j = pl.program_id(0)
    base = j * TILE_W
    lanes = base + jax.lax.broadcasted_iota(jnp.int32, (1, TILE_W), 1)

    def chunk_body(i, acc):
        sl = pl.dslice(i * CHUNK_B, CHUNK_B)
        vals = vals_ref[:, sl]                       # [2, CHUNK_B]
        new_rows = []
        for r in range(depth):  # static unroll over sketch depth
            idx = idx_ref[r, sl].reshape(CHUNK_B, 1)
            onehot = (idx == lanes).astype(jnp.float32)  # [CHUNK_B, TILE_W]
            contrib = jnp.dot(vals, onehot,
                              preferred_element_type=jnp.float32)  # [2, W]
            new_rows.append(acc[:, r] + contrib)
        return jnp.stack(new_rows, axis=1)           # [2, d, TILE_W]

    acc = counts_ref[...]
    acc = jax.lax.fori_loop(0, n_chunks, chunk_body, acc)
    out_ref[...] = acc


def update_two(cm_a: CountMin, cm_b: CountMin, h1: jax.Array, h2: jax.Array,
               vals_a: jax.Array, vals_b: jax.Array, valid: jax.Array,
               interpret: bool | None = None) -> tuple[CountMin, CountMin]:
    """Fused drop-in for countmin.update_two: both planes (bytes, packets)
    fold in ONE kernel sharing hash indices and one-hot construction."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    d, w = cm_a.counts.shape
    assert cm_b.counts.shape == (d, w)
    assert w % TILE_W == 0, f"width {w} must be a multiple of {TILE_W}"
    b = h1.shape[0]
    pad = (-b) % CHUNK_B
    if pad:
        h1 = jnp.pad(h1, (0, pad))
        h2 = jnp.pad(h2, (0, pad), constant_values=1)
        vals_a = jnp.pad(vals_a, (0, pad))
        vals_b = jnp.pad(vals_b, (0, pad))
        valid = jnp.pad(valid, (0, pad))
    idx = hashing.row_indices(h1, h2, d, w).astype(jnp.int32)  # [d, B]
    vals = jnp.stack([
        jnp.where(valid, vals_a, 0).astype(jnp.float32),
        jnp.where(valid, vals_b, 0).astype(jnp.float32)])      # [2, B]
    stacked = jnp.stack([cm_a.counts.astype(jnp.float32),
                         cm_b.counts.astype(jnp.float32)])     # [2, d, w]
    n_chunks = idx.shape[1] // CHUNK_B

    kernel = functools.partial(_fold2_kernel, depth=d, n_chunks=n_chunks)
    new_counts = pl.pallas_call(
        kernel,
        grid=(w // TILE_W,),
        in_specs=[
            pl.BlockSpec((2, d, TILE_W), lambda j: (0, 0, j)),
            pl.BlockSpec((d, idx.shape[1]), lambda j: (0, 0)),
            pl.BlockSpec((2, idx.shape[1]), lambda j: (0, 0)),
        ],
        out_specs=pl.BlockSpec((2, d, TILE_W), lambda j: (0, 0, j)),
        out_shape=jax.ShapeDtypeStruct((2, d, w), jnp.float32),
        input_output_aliases={0: 0},
        interpret=interpret,
    )(stacked, idx, vals)
    return (CountMin(counts=new_counts[0].astype(cm_a.counts.dtype)),
            CountMin(counts=new_counts[1].astype(cm_b.counts.dtype)))


def _tier2_kernel(base_ref, mid_ref, top_ref, idx_ref, vals_ref,
                  base_out, mid_out, top_out, q_out, *, depth: int,
                  n_chunks: int, mid_group: int, top_group: int,
                  units: tuple[int, int]):
    """Tier-interior dual-plane fold: decode the narrow tier tiles to a
    wide f32 view IN VMEM, run the exact `_fold2_kernel` chunk walk on it,
    then promote the per-fold delta back into the tiers — the wide array
    never exists in HBM. A second walk gathers the post-fold bytes-plane
    estimate per record (q_out accumulates across width tiles; each index
    hits exactly one tile, so the sum is an exact gather) so the heavy-
    hitter plane can query without a wide temporary either."""
    j = pl.program_id(0)
    base = j * TILE_W
    lanes = base + jax.lax.broadcasted_iota(jnp.int32, (1, TILE_W), 1)
    tm = TILE_W // mid_group
    em = tier_tiles.expand_matrix(TILE_W, mid_group)
    et = tier_tiles.expand_matrix(tm, top_group // mid_group)
    gm = tier_tiles.groupsum_matrix(TILE_W, mid_group)
    gt = tier_tiles.groupsum_matrix(tm, top_group // mid_group)

    base_i = base_ref[...].astype(jnp.int32)   # [2, d, T]
    mid_i = mid_ref[...].astype(jnp.int32)     # [2, d, T//mg]
    top_u = top_ref[...]                       # [2, d, T//tg] u32
    dec = jnp.stack([
        tier_tiles.decode_tile(base_i[p], mid_i[p], top_u[p], em, et,
                               units[p])
        for p in range(2)])                    # [2, d, T] f32 wide view

    def chunk_body(i, acc):  # _fold2_kernel's walk, acc seeded from dec
        sl = pl.dslice(i * CHUNK_B, CHUNK_B)
        vals = vals_ref[:, sl]                       # [2, CHUNK_B]
        new_rows = []
        for r in range(depth):  # static unroll over sketch depth
            idx = idx_ref[r, sl].reshape(CHUNK_B, 1)
            onehot = (idx == lanes).astype(jnp.float32)  # [CHUNK_B, TILE_W]
            contrib = jnp.dot(vals, onehot,
                              preferred_element_type=jnp.float32)  # [2, W]
            new_rows.append(acc[:, r] + contrib)
        return jnp.stack(new_rows, axis=1)           # [2, d, TILE_W]

    new = jax.lax.fori_loop(0, n_chunks, chunk_body, dec)
    for p in range(2):
        nb, nm, nt = tier_tiles.promote_tile(
            base_i[p], mid_i[p], top_u[p], dec[p], new[p], gm, gt, units[p])
        base_out[p] = nb
        mid_out[p] = nm
        top_out[p] = nt

    # bytes-plane query on the post-fold wide view (pre-promotion — the
    # same values countmin.query reads in the decode-wrapped form)
    @pl.when(j == 0)
    def _zero():
        q_out[...] = jnp.zeros_like(q_out[...])

    wide0 = new[0]

    def q_body(i, carry):
        sl = pl.dslice(i * CHUNK_B, CHUNK_B)
        for r in range(depth):
            idx = idx_ref[r, sl].reshape(CHUNK_B, 1)
            qc = jnp.sum(jnp.where(idx == lanes, wide0[r:r + 1, :], 0.0),
                         axis=1)
            q_out[r, sl] = q_out[r, sl] + qc
        return carry

    jax.lax.fori_loop(0, n_chunks, q_body, 0)


def tiered_eligible(width: int, spec) -> bool:
    """Static gate for the tier-interior walk: whole tiles, whole top
    groups per tile (so promotion never crosses a tile boundary)."""
    return width % TILE_W == 0 and TILE_W % spec.top_group == 0


def update_two_tiered(plane_a, plane_b, h1: jax.Array, h2: jax.Array,
                      vals_a: jax.Array, vals_b: jax.Array, valid: jax.Array,
                      spec, interpret: bool | None = None):
    """Tier-native twin of :func:`update_two`: folds BOTH Count-Min planes
    straight into their (u8 base, u16 mid, u32 top) tier arrays and returns
    ``(new_plane_a, new_plane_b, est)`` where ``est[b]`` is
    ``countmin.query`` of the post-fold bytes plane's transient wide view
    (what the slot table queries). Semantics are ``tiered.fold_encode`` of
    the wide fold — pinned bit-exact by tests/test_tiered.py."""
    from netobserv_tpu.sketch.tiered import TieredPlane
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    d, w = plane_a.base.shape
    assert plane_b.base.shape == (d, w)
    assert tiered_eligible(w, spec), \
        f"width {w} / top_group {spec.top_group} ineligible for tier tiles"
    mg, tg = spec.mid_group, spec.top_group
    b = h1.shape[0]
    pad = (-b) % CHUNK_B
    if pad:
        h1 = jnp.pad(h1, (0, pad))
        h2 = jnp.pad(h2, (0, pad), constant_values=1)
        vals_a = jnp.pad(vals_a, (0, pad))
        vals_b = jnp.pad(vals_b, (0, pad))
        valid = jnp.pad(valid, (0, pad))
    idx = hashing.row_indices(h1, h2, d, w).astype(jnp.int32)  # [d, B]
    vals = jnp.stack([
        jnp.where(valid, vals_a, 0).astype(jnp.float32),
        jnp.where(valid, vals_b, 0).astype(jnp.float32)])      # [2, B]
    base_s = jnp.stack([plane_a.base, plane_b.base])   # [2, d, w] u8
    mid_s = jnp.stack([plane_a.mid, plane_b.mid])      # [2, d, w//mg] u16
    top_s = jnp.stack([plane_a.top, plane_b.top])      # [2, d, w//tg] u32
    n_chunks = idx.shape[1] // CHUNK_B

    kernel = functools.partial(
        _tier2_kernel, depth=d, n_chunks=n_chunks, mid_group=mg,
        top_group=tg, units=(spec.bytes_unit, 1))
    nb, nm, nt, q = pl.pallas_call(
        kernel,
        grid=(w // TILE_W,),
        in_specs=[
            pl.BlockSpec((2, d, TILE_W), lambda j: (0, 0, j)),
            pl.BlockSpec((2, d, TILE_W // mg), lambda j: (0, 0, j)),
            pl.BlockSpec((2, d, TILE_W // tg), lambda j: (0, 0, j)),
            pl.BlockSpec((d, idx.shape[1]), lambda j: (0, 0)),
            pl.BlockSpec((2, idx.shape[1]), lambda j: (0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((2, d, TILE_W), lambda j: (0, 0, j)),
            pl.BlockSpec((2, d, TILE_W // mg), lambda j: (0, 0, j)),
            pl.BlockSpec((2, d, TILE_W // tg), lambda j: (0, 0, j)),
            pl.BlockSpec((d, idx.shape[1]), lambda j: (0, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((2, d, w), jnp.uint8),
            jax.ShapeDtypeStruct((2, d, w // mg), jnp.uint16),
            jax.ShapeDtypeStruct((2, d, w // tg), jnp.uint32),
            jax.ShapeDtypeStruct((d, idx.shape[1]), jnp.float32),
        ),
        input_output_aliases={0: 0, 1: 1, 2: 2},
        interpret=interpret,
    )(base_s, mid_s, top_s, idx, vals)
    est = jnp.min(q[:, :b], axis=0)
    return (TieredPlane(base=nb[0], mid=nm[0], top=nt[0]),
            TieredPlane(base=nb[1], mid=nm[1], top=nt[1]), est)


def update(cm: CountMin, h1: jax.Array, h2: jax.Array, values: jax.Array,
           valid: jax.Array, interpret: bool | None = None) -> CountMin:
    """Drop-in replacement for countmin.update (float32 sketches).

    `interpret` defaults to True off-TPU so the kernel is testable on the
    CPU mesh; on TPU it compiles through Mosaic."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    d, w = cm.counts.shape
    assert w % TILE_W == 0, f"width {w} must be a multiple of {TILE_W}"
    b = h1.shape[0]
    pad = (-b) % CHUNK_B
    if pad:
        h1 = jnp.pad(h1, (0, pad))
        h2 = jnp.pad(h2, (0, pad), constant_values=1)
        values = jnp.pad(values, (0, pad))
        valid = jnp.pad(valid, (0, pad))
    idx = hashing.row_indices(h1, h2, d, w).astype(jnp.int32)  # [d, B]
    vals = jnp.where(valid, values, 0).astype(jnp.float32)
    n_chunks = idx.shape[1] // CHUNK_B

    kernel = functools.partial(_fold_kernel, depth=d, n_chunks=n_chunks)
    new_counts = pl.pallas_call(
        kernel,
        grid=(w // TILE_W,),
        in_specs=[
            pl.BlockSpec((d, TILE_W), lambda j: (0, j)),   # counts tile
            pl.BlockSpec((d, idx.shape[1]), lambda j: (0, 0)),  # all indices
            pl.BlockSpec((idx.shape[1],), lambda j: (0,)),      # all values
        ],
        out_specs=pl.BlockSpec((d, TILE_W), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((d, w), jnp.float32),
        input_output_aliases={0: 0},
        interpret=interpret,
    )(cm.counts.astype(jnp.float32), idx, vals)
    return CountMin(counts=new_counts)
