"""Pallas TPU kernels for the sketch hot path.

XLA's scatter lowering serializes random-index updates; these kernels
reformulate them as tiled one-hot contractions that ride the MXU
(`countmin_kernel`), the classic TPU trick for histogram/scatter workloads.
Selected at runtime via SKETCH_USE_PALLAS=1 (default: XLA scatter, which wins
on CPU and small widths).
"""
