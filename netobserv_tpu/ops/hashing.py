"""Vectorized flow-key hashing in uint32 lanes.

Murmur3-style mixing (multiply/rotate/xor) over the packed KEY_WORDS uint32 words
of each flow key, fully unrolled (word count is static), batched over the leading
axis. Double hashing (Kirsch–Mitzenmacher) derives the d Count-Min row indices
from two base hashes, so each batch is hashed exactly twice regardless of depth.

Replaces the reference's per-record Go map hashing + FNV (implicit in Go's
runtime map, `pkg/flow/account.go:204-246`) with VPU-friendly lane math.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# numpy scalars, NOT jnp: module-level jnp constants would initialize the XLA
# backend at import time, which breaks jax.distributed.initialize() for any
# process that imports this package before multi-host bootstrap
_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)
_M5 = np.uint32(5)
_N1 = np.uint32(0xE6546B64)
_F1 = np.uint32(0x85EBCA6B)
_F2 = np.uint32(0xC2B2AE35)


def _rotl32(x: jax.Array, r: int) -> jax.Array:
    return (x << r) | (x >> (32 - r))


def fmix32(h: jax.Array) -> jax.Array:
    """Murmur3 finalizer: full avalanche on a uint32 lane."""
    h = h ^ (h >> 16)
    h = h * _F1
    h = h ^ (h >> 13)
    h = h * _F2
    h = h ^ (h >> 16)
    return h


def hash_words(words: jax.Array, seed: int | jax.Array) -> jax.Array:
    """Hash packed key words -> uint32.

    words: uint32[..., W] (W static, typically KEY_WORDS=10)
    seed:  scalar (python int or uint32 array)
    returns uint32[...]
    """
    words = words.astype(jnp.uint32)
    w = words.shape[-1]
    h = jnp.broadcast_to(jnp.asarray(seed, dtype=jnp.uint32), words.shape[:-1])
    for i in range(w):  # static unroll
        k = words[..., i] * _C1
        k = _rotl32(k, 15) * _C2
        h = h ^ k
        h = _rotl32(h, 13) * _M5 + _N1
    h = h ^ jnp.uint32(w * 4)
    return fmix32(h)


#: seed of the VICTIM/destination bucket family — the per-dst HLL grid,
#: every EWMA victim bucket (ddos/syn/drops), the conversation pair hash,
#: and the exporter's host-side victim naming all key off it; one
#: definition so the device and host sides cannot drift
DST_BUCKET_SEED = 0x0D57
#: seed of the source-hash family (global/per-src HLL, fan-out grid)
SRC_BUCKET_SEED = 0x0517


def base_hashes(words: jax.Array, seed: int = 0) -> tuple[jax.Array, jax.Array]:
    """Two independent base hashes (h2 forced odd so strides generate Z_{2^k})."""
    h1 = hash_words(words, jnp.uint32(0x9747B28C) ^ jnp.uint32(seed))
    h2 = hash_words(words, jnp.uint32(0x5BD1E995) ^ jnp.uint32(seed))
    return h1, h2 | jnp.uint32(1)


def hash_words_np(words: np.ndarray, seed: int = 0) -> np.ndarray:
    """Pure-numpy twin of `hash_words` under `base_hashes`' h1 seeding —
    for HOST-side bucket lookups (e.g. mapping report suspect buckets back
    to heavy-hitter keys) without dispatching a device op (a wedged
    accelerator link must never stall report rendering). Equivalence-tested
    against the jax path."""
    w = np.ascontiguousarray(words, dtype=np.uint32)
    nwords = w.shape[-1]
    with np.errstate(over="ignore"):
        h = np.full(w.shape[:-1], np.uint32(0x9747B28C) ^ np.uint32(seed),
                    np.uint32)
        for i in range(nwords):
            k = w[..., i] * _C1
            k = ((k << np.uint32(15)) | (k >> np.uint32(17))) * _C2
            h = h ^ k
            h = ((h << np.uint32(13)) | (h >> np.uint32(19))) * _M5 + _N1
        h = h ^ np.uint32(nwords * 4)
        h = h ^ (h >> np.uint32(16))
        h = h * _F1
        h = h ^ (h >> np.uint32(13))
        h = h * _F2
        h = h ^ (h >> np.uint32(16))
    return h


def row_indices(h1: jax.Array, h2: jax.Array, depth: int, width: int) -> jax.Array:
    """Kirsch–Mitzenmacher: index for row i is (h1 + i*h2) mod width.

    width must be a power of two. Returns uint32[depth, ...].
    """
    assert width & (width - 1) == 0, "width must be a power of two"
    rows = jnp.arange(depth, dtype=jnp.uint32).reshape((depth,) + (1,) * h1.ndim)
    return (h1[None] + rows * h2[None]) & jnp.uint32(width - 1)
