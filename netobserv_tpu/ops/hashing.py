"""Vectorized flow-key hashing in uint32 lanes.

Murmur3-style mixing (multiply/rotate/xor) over the packed KEY_WORDS uint32 words
of each flow key, fully unrolled (word count is static), batched over the leading
axis. Double hashing (Kirsch–Mitzenmacher) derives the d Count-Min row indices
from two base hashes, so each batch is hashed exactly twice regardless of depth.

Replaces the reference's per-record Go map hashing + FNV (implicit in Go's
runtime map, `pkg/flow/account.go:204-246`) with VPU-friendly lane math.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

try:  # pragma: no cover - exercised by the jax-less qemu CI tier
    import jax
    import jax.numpy as jnp
except ImportError:  # big-endian s390x: only the numpy twins are usable
    jax = None
    jnp = None

# numpy scalars, NOT jnp: module-level jnp constants would initialize the XLA
# backend at import time, which breaks jax.distributed.initialize() for any
# process that imports this package before multi-host bootstrap
_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)
_M5 = np.uint32(5)
_N1 = np.uint32(0xE6546B64)
_F1 = np.uint32(0x85EBCA6B)
_F2 = np.uint32(0xC2B2AE35)


def _rotl32(x: jax.Array, r: int) -> jax.Array:
    return (x << r) | (x >> (32 - r))


def fmix32(h: jax.Array) -> jax.Array:
    """Murmur3 finalizer: full avalanche on a uint32 lane."""
    h = h ^ (h >> 16)
    h = h * _F1
    h = h ^ (h >> 13)
    h = h * _F2
    h = h ^ (h >> 16)
    return h


def hash_words(words: jax.Array, seed: int | jax.Array) -> jax.Array:
    """Hash packed key words -> uint32.

    words: uint32[..., W] (W static, typically KEY_WORDS=10)
    seed:  scalar (python int or uint32 array)
    returns uint32[...]
    """
    words = words.astype(jnp.uint32)
    w = words.shape[-1]
    h = jnp.broadcast_to(jnp.asarray(seed, dtype=jnp.uint32), words.shape[:-1])
    for i in range(w):  # static unroll
        k = words[..., i] * _C1
        k = _rotl32(k, 15) * _C2
        h = h ^ k
        h = _rotl32(h, 13) * _M5 + _N1
    h = h ^ jnp.uint32(w * 4)
    return fmix32(h)


#: seed of the VICTIM/destination bucket family — the per-dst HLL grid,
#: every EWMA victim bucket (ddos/syn/drops), the conversation pair hash,
#: and the exporter's host-side victim naming all key off it; one
#: definition so the device and host sides cannot drift
DST_BUCKET_SEED = 0x0D57
#: seed of the source-hash family (global/per-src HLL, fan-out grid)
SRC_BUCKET_SEED = 0x0517
#: seed of the (dst addr, dst port) fan-out family — the port-scan signal's
#: per-src HLL grid keys off it (was inlined in sketch/state.py)
DSTPORT_FANOUT_SEED = 0x5CA7
#: seed of the tenant-owner family (multi-tenant sketch planes): the host
#: router assigns every evicted flow to a tenant by this hash of the FULL
#: flow key, so a flow's tenant is stable across windows and agents. Both
#: sides (device `tenant_of`, host `tenant_of_np`) derive from this one
#: constant — never inline it
TENANT_SEED = 0x7E4A

#: base_hashes' two seed constants (h1 / h2 family); every derived family
#: xors its bucket seed into these
_H1_SEED = 0x9747B28C
_H2_SEED = 0x5BD1E995


def base_hashes(words: jax.Array, seed: int = 0) -> tuple[jax.Array, jax.Array]:
    """Two independent base hashes (h2 forced odd so strides generate Z_{2^k})."""
    h1 = hash_words(words, jnp.uint32(_H1_SEED) ^ jnp.uint32(seed))
    h2 = hash_words(words, jnp.uint32(_H2_SEED) ^ jnp.uint32(seed))
    return h1, h2 | jnp.uint32(1)


class MultiHashes(NamedTuple):
    """Every hash family the sketch ingest consumes, from ONE sweep over the
    key words (`base_hashes_multi`). Values are bit-identical to the separate
    `base_hashes` calls they replace — pinned by tests/test_hashing_multi.py."""

    h1: jax.Array       #: flow family h1 (all KEY_WORDS, seed 0)
    h2: jax.Array       #: flow family h2 (odd)
    src_h1: jax.Array   #: SRC_BUCKET_SEED over the src words (0:4)
    src_h2: jax.Array   #: … h2 (odd)
    dst_h1: jax.Array   #: DST_BUCKET_SEED over the dst words (4:8)
    dp_h1: jax.Array    #: DSTPORT_FANOUT_SEED over dst words + dst port
    dp_h2: jax.Array    #: … h2 (odd)
    src_sym: jax.Array  #: DST_BUCKET_SEED over the SRC words (victim-bucket
    #: hash of the source endpoint: conv pair + SYN-ACK bucketing)


#: word-index sets absorbed by each family (KEY_WORDS layout:
#: src ip words 0..3, dst ip words 4..7, ports word 8, proto word 9;
#: index 10 is the synthesized dst-port column)
_FLOW_IDXS = tuple(range(10))
_SRC_IDXS = (0, 1, 2, 3)
_DST_IDXS = (4, 5, 6, 7)
_DP_IDXS = (4, 5, 6, 7, 10)


def base_hashes_multi(words: jax.Array) -> MultiHashes:
    """All five hash families in ONE pass over the key words.

    The murmur3 per-word k-mix (multiply/rotate/multiply) is seed-independent,
    so it is computed once per word and shared by every family; only the
    cheap h-side accumulation runs per family — and the unused h2 halves of
    the dst-bucket and src-sym families are skipped entirely. Replaces five
    separate `base_hashes` sweeps in `sketch.state.ingest` (bit-identical;
    the numpy host twin `hash_words_np` and the seed constants above remain
    the single source of truth)."""
    words = words.astype(jnp.uint32)
    assert words.shape[-1] == 10, "base_hashes_multi expects KEY_WORDS=10"
    shape = words.shape[:-1]

    def k_mix(w):
        k = w * _C1
        return _rotl32(k, 15) * _C2

    ks = [k_mix(words[..., i]) for i in range(10)]
    # the dst-port column the fan-out family hashes (low half of word 8)
    ks.append(k_mix(words[..., 8] & jnp.uint32(0xFFFF)))

    def run(seed: int, idxs: tuple[int, ...]) -> jax.Array:
        h = jnp.broadcast_to(jnp.uint32(seed), shape)
        for i in idxs:
            h = _rotl32(h ^ ks[i], 13) * _M5 + _N1
        return fmix32(h ^ jnp.uint32(len(idxs) * 4))

    return MultiHashes(
        h1=run(_H1_SEED, _FLOW_IDXS),
        h2=run(_H2_SEED, _FLOW_IDXS) | jnp.uint32(1),
        src_h1=run(_H1_SEED ^ SRC_BUCKET_SEED, _SRC_IDXS),
        src_h2=run(_H2_SEED ^ SRC_BUCKET_SEED, _SRC_IDXS) | jnp.uint32(1),
        dst_h1=run(_H1_SEED ^ DST_BUCKET_SEED, _DST_IDXS),
        dp_h1=run(_H1_SEED ^ DSTPORT_FANOUT_SEED, _DP_IDXS),
        dp_h2=run(_H2_SEED ^ DSTPORT_FANOUT_SEED, _DP_IDXS) | jnp.uint32(1),
        src_sym=run(_H1_SEED ^ DST_BUCKET_SEED, _SRC_IDXS),
    )


def base_hashes_multi_np(words: np.ndarray) -> dict[str, np.ndarray]:
    """Pure-numpy twin of `base_hashes_multi` (same field names) — runs on
    jax-less hosts, including the big-endian qemu CI tier, where it pins the
    fused sweep against golden vectors so an endianness regression in the
    shared k-mix cannot drift silently (the multi-hash output feeds the
    host-side numpy twins via the shared seed constants)."""
    w = np.ascontiguousarray(words, dtype=np.uint32)
    assert w.shape[-1] == 10
    with np.errstate(over="ignore"):
        def k_mix(col):
            k = col * _C1
            return ((k << np.uint32(15)) | (k >> np.uint32(17))) * _C2

        ks = [k_mix(w[..., i]) for i in range(10)]
        ks.append(k_mix(w[..., 8] & np.uint32(0xFFFF)))

        def run(seed: int, idxs: tuple[int, ...]) -> np.ndarray:
            h = np.full(w.shape[:-1], np.uint32(seed), np.uint32)
            for i in idxs:
                h = h ^ ks[i]
                h = ((h << np.uint32(13)) | (h >> np.uint32(19))) * _M5 + _N1
            h = h ^ np.uint32(len(idxs) * 4)
            h = h ^ (h >> np.uint32(16))
            h = h * _F1
            h = h ^ (h >> np.uint32(13))
            h = h * _F2
            return h ^ (h >> np.uint32(16))

        return {
            "h1": run(_H1_SEED, _FLOW_IDXS),
            "h2": run(_H2_SEED, _FLOW_IDXS) | np.uint32(1),
            "src_h1": run(_H1_SEED ^ SRC_BUCKET_SEED, _SRC_IDXS),
            "src_h2": run(_H2_SEED ^ SRC_BUCKET_SEED, _SRC_IDXS)
            | np.uint32(1),
            "dst_h1": run(_H1_SEED ^ DST_BUCKET_SEED, _DST_IDXS),
            "dp_h1": run(_H1_SEED ^ DSTPORT_FANOUT_SEED, _DP_IDXS),
            "dp_h2": run(_H2_SEED ^ DSTPORT_FANOUT_SEED, _DP_IDXS)
            | np.uint32(1),
            "src_sym": run(_H1_SEED ^ DST_BUCKET_SEED, _SRC_IDXS),
        }


def hash_words_np(words: np.ndarray, seed: int = 0) -> np.ndarray:
    """Pure-numpy twin of `hash_words` under `base_hashes`' h1 seeding —
    for HOST-side bucket lookups (e.g. mapping report suspect buckets back
    to heavy-hitter keys) without dispatching a device op (a wedged
    accelerator link must never stall report rendering). Equivalence-tested
    against the jax path."""
    w = np.ascontiguousarray(words, dtype=np.uint32)
    nwords = w.shape[-1]
    with np.errstate(over="ignore"):
        h = np.full(w.shape[:-1], np.uint32(0x9747B28C) ^ np.uint32(seed),
                    np.uint32)
        for i in range(nwords):
            k = w[..., i] * _C1
            k = ((k << np.uint32(15)) | (k >> np.uint32(17))) * _C2
            h = h ^ k
            h = ((h << np.uint32(13)) | (h >> np.uint32(19))) * _M5 + _N1
        h = h ^ np.uint32(nwords * 4)
        h = h ^ (h >> np.uint32(16))
        h = h * _F1
        h = h ^ (h >> np.uint32(13))
        h = h * _F2
        h = h ^ (h >> np.uint32(16))
    return h


def tenant_of(words: jax.Array, n_tenants: int) -> jax.Array:
    """Tenant owner of each flow key: int32[...] in [0, n_tenants).

    Hashes the FULL key words under TENANT_SEED (h1 family), mod the tenant
    count — decorrelated from every sketch family, so tenant routing never
    biases bucket occupancy. `n_tenants` need not be a power of two."""
    h = hash_words(words, jnp.uint32(_H1_SEED) ^ jnp.uint32(TENANT_SEED))
    return (h % jnp.uint32(n_tenants)).astype(jnp.int32)


def tenant_of_np(words: np.ndarray, n_tenants: int) -> np.ndarray:
    """Pure-numpy twin of `tenant_of` — the HOST router (sketch/tenancy.py)
    assigns evicted rows with this; equivalence + golden vectors pinned by
    tests/test_tenancy.py (goldens run on the big-endian qemu tier)."""
    h = hash_words_np(words, TENANT_SEED)
    return (h % np.uint32(n_tenants)).astype(np.int32)


def row_indices(h1: jax.Array, h2: jax.Array, depth: int, width: int) -> jax.Array:
    """Kirsch–Mitzenmacher: index for row i is (h1 + i*h2) mod width.

    width must be a power of two. Returns uint32[depth, ...].
    """
    assert width & (width - 1) == 0, "width must be a power of two"
    rows = jnp.arange(depth, dtype=jnp.uint32).reshape((depth,) + (1,) * h1.ndim)
    return (h1[None] + rows * h2[None]) & jnp.uint32(width - 1)
