"""HyperLogLog cardinality sketches, flat and per-destination-bucket.

BASELINE.json config 3: "HyperLogLog distinct-src-IP-per-dst cardinality sketch,
ICI-merged across 4 chips". Registers are int32 (TPU-friendly; int8 would save
memory but costs sublane packing); merge is elementwise max, i.e. `pmax` over ICI.

Register index comes from h1's low p bits, the rank from the leading zeros of an
independent h2 (`lax.clz`) — no byte-wise processing anywhere.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class HLL(NamedTuple):
    regs: jax.Array  # int32[m] — m = 2^precision

    @property
    def precision(self) -> int:
        return int(self.regs.shape[-1]).bit_length() - 1


class PerDstHLL(NamedTuple):
    """D independent small HLLs, one per destination hash bucket."""

    regs: jax.Array  # int32[D, m]


def init(precision: int = 14) -> HLL:
    return HLL(regs=jnp.zeros((1 << precision,), dtype=jnp.int32))


def init_per_dst(dst_buckets: int = 4096, precision: int = 6) -> PerDstHLL:
    assert dst_buckets & (dst_buckets - 1) == 0
    return PerDstHLL(regs=jnp.zeros((dst_buckets, 1 << precision), dtype=jnp.int32))


def _rank(h2: jax.Array) -> jax.Array:
    """Leading-zero rank in [1, 33] of an independent uniform 32-bit hash."""
    return jax.lax.clz(h2.astype(jnp.int32)) + 1


def update(hll: HLL, h1: jax.Array, h2: jax.Array, valid: jax.Array) -> HLL:
    m = hll.regs.shape[0]
    idx = (h1 & jnp.uint32(m - 1)).astype(jnp.int32)
    rank = jnp.where(valid, _rank(h2), 0)
    return HLL(regs=hll.regs.at[idx].max(rank, mode="drop"))


def update_per_dst(s: PerDstHLL, dst_h: jax.Array, src_h1: jax.Array,
                   src_h2: jax.Array, valid: jax.Array) -> PerDstHLL:
    """Fold (dst, src) pairs: register (dst_bucket, src_reg) <- max rank."""
    dbuckets, m = s.regs.shape
    di = (dst_h & jnp.uint32(dbuckets - 1)).astype(jnp.int32)
    ri = (src_h1 & jnp.uint32(m - 1)).astype(jnp.int32)
    rank = jnp.where(valid, _rank(src_h2), 0)
    return PerDstHLL(regs=s.regs.at[di, ri].max(rank, mode="drop"))


def _alpha(m: int) -> float:
    if m <= 16:
        return 0.673
    if m <= 32:
        return 0.697
    if m <= 64:
        return 0.709
    return 0.7213 / (1.0 + 1.079 / m)


def estimate(regs: jax.Array) -> jax.Array:
    """Cardinality estimate with small/large-range corrections (Flajolet et al.).

    regs: int32[..., m]; returns float32[...] — works for flat and per-dst.
    """
    m = regs.shape[-1]
    harm = jnp.sum(jnp.exp2(-regs.astype(jnp.float32)), axis=-1)
    raw = _alpha(m) * m * m / harm
    zeros = jnp.sum((regs == 0).astype(jnp.float32), axis=-1)
    # linear counting below the 2.5m threshold when empty registers remain
    lin = m * jnp.log(jnp.where(zeros > 0, m / jnp.maximum(zeros, 1e-9), 1.0))
    est = jnp.where((raw <= 2.5 * m) & (zeros > 0), lin, raw)
    # large-range correction for 32-bit hashes
    two32 = jnp.float32(2.0**32)
    est = jnp.where(est > two32 / 30.0,
                    -two32 * jnp.log1p(-est / two32), est)
    return est


def merge_regs(a: jax.Array, b: jax.Array) -> jax.Array:
    """Merge = elementwise max — the ICI collective for HLL is pmax."""
    return jnp.maximum(a, b)
