"""TPU sketch operators (JAX).

Everything here follows three TPU rules (SURVEY.md §7.3, pallas_guide.md):
- **No dynamic shapes.** Batches are fixed-size with validity masks; tables are
  fixed-K; histograms fixed-width.
- **Integer lane math.** Flow keys are uint32 word vectors; hashing is murmur-style
  multiply/rotate/xor in 32-bit lanes — never byte loops.
- **Functional state.** Every sketch is a pytree updated by pure folds, so the whole
  ingest step jits, donates, and shards with `shard_map`.
"""

import importlib.util

from netobserv_tpu.ops import hashing  # noqa: F401  — jax-OPTIONAL: its
# numpy twins (hash_words_np, base_hashes_multi_np) must import on
# jax-less hosts, incl. the big-endian qemu CI tier (ci.yml)

if importlib.util.find_spec("jax") is not None:
    # gate on jax's PRESENCE, not a blanket except ImportError — a genuine
    # import failure inside an op module must still surface
    from netobserv_tpu.ops import (  # noqa: F401
        countmin, ewma, hll, quantile, topk,
    )
