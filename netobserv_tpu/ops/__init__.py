"""TPU sketch operators (JAX).

Everything here follows three TPU rules (SURVEY.md §7.3, pallas_guide.md):
- **No dynamic shapes.** Batches are fixed-size with validity masks; tables are
  fixed-K; histograms fixed-width.
- **Integer lane math.** Flow keys are uint32 word vectors; hashing is murmur-style
  multiply/rotate/xor in 32-bit lanes — never byte loops.
- **Functional state.** Every sketch is a pytree updated by pure folds, so the whole
  ingest step jits, donates, and shards with `shard_map`.
"""

from netobserv_tpu.ops import hashing, countmin, hll, topk, quantile, ewma  # noqa: F401
